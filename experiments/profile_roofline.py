"""Profile the 1M-member SWIM round and publish the roofline accounting.

Answers VERDICT round-2 item 3 ("zero performance characterization"): what
the headline ms/round is made of, measured three independent ways on the
real chip:

  1. **Step trace** — ``jax.profiler`` around the timed scan; the chrome
     trace is parsed here (no TensorBoard needed) into per-kernel
     ms/round, attributed to model source lines.
  2. **Analytic traffic model** — every [N,K]/[2N,K] array the shift-mode
     tick reads or writes per round, enumerated from the model's shapes
     (the same accounting style as a hand roofline; see
     ``traffic_model``).  Dividing by measured time gives achieved GB/s
     against the chip's HBM peak.
  3. **XLA cost analysis** — ``compiled.cost_analysis()`` bytes/flops,
     reported with the caveat that slice-heavy programs overcount (XLA
     attributes the full input buffer to each dynamic-slice, so the
     doubled-buffer delivery pattern inflates "bytes accessed" ~4x over
     real HBM traffic; the scan body is counted once, not n_rounds times).

Writes ``artifacts/roofline.json``.  Run on TPU: ``python
experiments/profile_roofline.py`` (~1 min).

Reference seam: this is the perf-characterization analog of the netty
fast-path the reference relies on (transport/TransportImpl.java:257-269);
the reference ships no benchmarks of its own (SURVEY.md §6).
"""

import collections
import glob
import gzip
import json
import os
import re
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.utils import get_logger
from scalecube_cluster_tpu.utils import runlog
from scalecube_cluster_tpu.utils.runlog import enable_compilation_cache

N = int(os.environ.get("SCALECUBE_PROFILE_N", 1_000_000))
K = int(os.environ.get("SCALECUBE_PROFILE_K", 16))
ROUNDS = int(os.environ.get("SCALECUBE_PROFILE_ROUNDS", 200))
# v5e: 819 GB/s HBM per chip (public spec). Override for other chips.
HBM_PEAK_GBPS = float(os.environ.get("SCALECUBE_HBM_PEAK_GBPS", 819.0))

log = get_logger("roofline")
enable_compilation_cache(log)


def traffic_model(n, k, fanout, ping_every):
    """Per-round HBM bytes of the shift-mode focal tick, by array.

    Enumerates materialized reads+writes at the fusion boundaries the
    compiled program actually has (verified against the step trace): the
    scan carry, the doubled payload/mask buffers, per-channel delivered
    slices, and the PRNG draws.  Elementwise temporaries that fuse into
    their consumers are not counted (that is the point of fusion).
    """
    i32, i8 = 4, 1
    rows = {
        # carry read + write per round
        "carry status [N,K] i8 r+w": 2 * n * k * i8,
        "carry inc/spread/deadline [N,K] i32 r+w": 3 * 2 * n * k * i32,
        "carry self_inc [N] i32 r+w": 2 * n * i32,
        # send-side doubled buffers (concat write + source read)
        "h_keys [2N,K] i32 w + src r": 2 * n * k * i32 + n * k * i32,
        "h_tx packed masks [2N,K] i8 w + src r": 2 * n * k * i8 + n * k * i8,
        "h_hot_any [2N] i8 w": 2 * n * i8,
        # per-channel delivered slices: fanout gossip + sync + refute
        "gossip delivers keys+mask": fanout * (n * k * i32 + n * k * i8),
        "sync deliver keys+mask": n * k * i32 + n * k * i8,
        "refute deliver keys+mask": n * k * i32 + n * k * i8,
        # inbox accumulation (written once, read by merge)
        "inbox [N,K] i32 w+r": 2 * n * k * i32,
        "inbox_alive [N,K] i8 w+r": 2 * n * k * i8,
        # PRNG: drop_u [N,F+1] f32; FD chain draws [N,1+R] f32 (probe +
        # R proxies, product form) amortized over ping_every
        "drop uniforms [N,F+1] f32": n * (fanout + 1) * 4,
        "fd chain uniforms [N,4] f32 (every round)": n * 4 * 4,
        # metrics: masks fused into ~2 passes over new_status + status
        "metrics passes [N,K] i8 x2": 2 * n * k * i8,
        # replicated world vector slices (alive/part/ids doubled reads)
        "world vector slices [N] x ~8": 8 * n * i32,
    }
    return rows


def main():
    os.makedirs("artifacts", exist_ok=True)
    params = swim.SwimParams.from_config(
        ClusterConfig.default(), n_members=N, n_subjects=K,
        loss_probability=0.02, per_subject_metrics=True, delivery="shift",
    )
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=50)
    key = jax.random.key(0)
    state = swim.initial_state(params, world)
    fn = jax.jit(
        lambda kk, w, s: swim.run(kk, params, w, ROUNDS, state=s,
                                  start_round=0)
    )
    # One explicit compile, reused for execution, HLO text, and cost
    # analysis (a second lower().compile() would redo the ~45 s compile).
    compiled = fn.lower(key, world, state).compile()

    def force(s):
        return runlog.completion_barrier(s.status)

    t0 = time.perf_counter()
    s2, _ = fn(key, world, state)
    force(s2)
    compile_s = time.perf_counter() - t0
    log.info("compile+first run: %.1fs", compile_s)

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        s2, _ = fn(key, world, state)
        force(s2)
        best = min(best, time.perf_counter() - t0)
    ms_round = best / ROUNDS * 1e3
    log.info("steady state: %.3f ms/round (%.3e member-rounds/s)",
             ms_round, N / ms_round * 1e3)

    # ---- step trace ------------------------------------------------------
    trace_dir = tempfile.mkdtemp(prefix="swim_trace_")
    with jax.profiler.trace(trace_dir):
        s2, _ = fn(key, world, state)
        force(s2)
    tracefiles = glob.glob(
        os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz")
    )
    kernels, device_total_ms = [], None
    if tracefiles:
        d = json.load(gzip.open(tracefiles[-1]))
        device_pids = {
            e["pid"] for e in d["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
            and "TPU" in str(e.get("args", {}).get("name", ""))
        }
        durs = collections.defaultdict(float)
        cnt = collections.Counter()
        for e in d["traceEvents"]:
            if e.get("ph") == "X" and e.get("pid") in device_pids:
                durs[e["name"]] += e.get("dur", 0)
                cnt[e["name"]] += 1
        whiles = {k: v for k, v in durs.items() if k.startswith("while")}
        if whiles:
            device_total_ms = max(whiles.values()) / 1e3
        hlo = compiled.as_text()
        for name, us in sorted(durs.items(), key=lambda kv: -kv[1])[:14]:
            if name.startswith(("while", "jit_")):
                continue
            m = re.search(
                rf"%{re.escape(name)} = [^\n]*?source_line=(\d+)", hlo
            )
            kernels.append({
                "kernel": name,
                "ms_per_round": round(us / 1e3 / ROUNDS, 4),
                "calls": cnt[name],
                "swim_py_line": int(m.group(1)) if m else None,
            })

    # ---- analytic traffic + cost analysis --------------------------------
    rows = traffic_model(N, K, params.fanout, params.ping_every)
    total_bytes = sum(rows.values())
    achieved_gbps = total_bytes / (ms_round / 1e3) / 1e9
    # Wall at a 200-round window carries ~0.4-0.6 ms/round of tunnel
    # dispatch jitter; the device while-loop time is the honest
    # denominator for kernel-level utilization.
    dev_gbps = (total_bytes / (device_total_ms / ROUNDS / 1e3) / 1e9
                if device_total_ms else None)
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca

    result = {
        "config": {"n_members": N, "n_subjects": K, "rounds": ROUNDS,
                   "delivery": "shift", "loss": 0.02,
                   "platform": jax.default_backend()},
        "measured": {
            "ms_per_round": round(ms_round, 3),
            "member_rounds_per_sec": round(N / ms_round * 1e3, 1),
            "device_while_loop_ms_per_round": (
                round(device_total_ms / ROUNDS, 3) if device_total_ms
                else None),
            "compile_seconds": round(compile_s, 1),
        },
        "roofline": {
            "modeled_bytes_per_round": total_bytes,
            "modeled_traffic_breakdown": {
                k: v for k, v in
                sorted(rows.items(), key=lambda kv: -kv[1])
            },
            "achieved_gbps_vs_model": round(achieved_gbps, 1),
            "achieved_gbps_vs_model_device_time": (
                round(dev_gbps, 1) if dev_gbps else None),
            "hbm_peak_gbps": HBM_PEAK_GBPS,
            "hbm_utilization_pct": round(
                100 * achieved_gbps / HBM_PEAK_GBPS, 1),
            "hbm_utilization_pct_device_time": (
                round(100 * dev_gbps / HBM_PEAK_GBPS, 1) if dev_gbps
                else None),
        },
        "xla_cost_analysis": {
            "bytes_accessed_scan_body": ca.get("bytes accessed"),
            "flops_scan_body": ca.get("flops"),
            "transcendentals_scan_body": ca.get("transcendentals"),
            "caveat": "slice ops are charged their full input buffer, so "
                      "this overcounts real HBM traffic ~4x for the "
                      "doubled-buffer delivery pattern; loop body counted "
                      "once",
        },
        "top_kernels_per_round": kernels,
    }
    out = "artifacts/roofline.json"
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result["measured"] | {
        "hbm_utilization_pct": result["roofline"]["hbm_utilization_pct"]},
        indent=1))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
