"""Lifeguard adaptivity drill: false-positive rate A/B under degradation.

Drives ``bench.py --lifeguard`` (the one entry point the measurement
flows through, so the experiment and the driver bench cannot drift):
the seeded ``chaos.asymmetric_degradation`` composite — a Brownout
(loss + mean delay) on the inbound link ranges of a degraded minority
(an eighth of the ids, ``chaos.asymmetric_degraded_range``) plus a
FlappingLink — run
twice per scenario seed on the same key,

  - control: ``lhm_max=0`` (the health plane compiled out),
  - plane:   ``lhm_max=8`` (LHA probe scaling, LHA suspicion, buddy
    refutation — models/lifeguard.py),

and compared on the ``false_positive_observer_rate`` SLO
(false_suspicion_onsets / live_observer_rounds from the PR-5 registry)
plus crash-detection latency P99 for the degraded rack itself crashing
permanently mid-hold (bench.py explains why healthy crash targets
would corrupt the A/B).  Writes ``artifacts/lifeguard_fp.json`` (override
``--artifact``) and runs the ``telemetry regress`` gate in-bench — the
committed artifact is the pinned robustness claim: the plane at least
HALVES the false-positive observer rate at equal (within +1 round P99)
crash-detection latency, and regress exits 1 if that ever rots.

CPU-safe (the workload is a small-N full-view A/B, not a throughput
measurement).

Usage:
    python experiments/lifeguard_fp.py              # committed shape
    python experiments/lifeguard_fp.py --smoke      # tier-1-safe pass
    python experiments/lifeguard_fp.py --n 48 --scenarios 5 --seed 23
"""

import argparse
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe fast pass (one scenario)")
    parser.add_argument("--n", type=int, default=None,
                        help="member count (default 48; 24 under "
                             "--smoke)")
    parser.add_argument("--lhm-max", type=int, default=None,
                        help="Local Health Multiplier ceiling "
                             "(default 8)")
    parser.add_argument("--scenarios", type=int, default=None,
                        help="scenario seeds per arm (default 3; 1 "
                             "under --smoke)")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--artifact", default=None,
                        help="artifact path (default "
                             "artifacts/lifeguard_fp.json)")
    args = parser.parse_args()

    env = dict(os.environ)
    for flag, var in ((args.n, "SCALECUBE_LIFEGUARD_N"),
                      (args.lhm_max, "SCALECUBE_LIFEGUARD_LHM_MAX"),
                      (args.scenarios, "SCALECUBE_LIFEGUARD_SCENARIOS"),
                      (args.seed, "SCALECUBE_LIFEGUARD_SEED"),
                      (args.artifact, "SCALECUBE_LIFEGUARD_ARTIFACT")):
        if flag is not None:
            env[var] = str(flag)

    cmd = [sys.executable, str(REPO / "bench.py"), "--lifeguard"]
    if args.smoke:
        cmd.append("--smoke")
    return subprocess.run(cmd, env=env, cwd=str(REPO)).returncode


if __name__ == "__main__":
    sys.exit(main())
