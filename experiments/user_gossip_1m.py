"""User-gossip infection curves at 1M, co-running with crash detection.

The round-4 verdict's item 5, measured: four user gossips spread()
from different origins at staggered rounds while the full SWIM tick
detects a crash — one gossip machinery carrying user payloads AND
membership records (SwimParams.n_user_gossips; GossipProtocolImpl.java:
124-128's spread() through the same component that piggybacks
membership).  Expected law: fanout-3 infection grows ~(1+fanout)x per
round, so full dissemination at 1M in ~log4(1M) ~= 10-12 rounds.

Writes ``artifacts/user_gossip_1m.json``; pinned by
tests/test_results_claims.py.  Run: ``python
experiments/user_gossip_1m.py`` (TPU, ~1 min).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N = 1_000_000
G = 4
ROUNDS = 120
SPREADS = [(0, 17, 0), (1, 250_017, 5), (2, 500_017, 10), (3, 750_017, 15)]
CRASH_NODE, CRASH_AT = 3, 10


def main():
    import jax
    import numpy as np

    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.utils import runlog

    runlog.enable_compilation_cache()
    params = swim.SwimParams.from_config(
        ClusterConfig.default_local(), n_members=N, n_subjects=16,
        delivery="shift", n_user_gossips=G,
        suspicion_rounds=6, ping_every=2, sync_every=4,
    )
    world = swim.SwimWorld.healthy(params).with_crash(CRASH_NODE,
                                                      at_round=CRASH_AT)
    for g, origin, at in SPREADS:
        world = world.with_spread(g, origin=origin, at_round=at)

    t0 = time.perf_counter()
    state, m = swim.run(jax.random.key(0), params, world, ROUNDS)
    runlog.completion_barrier(state.status)
    wall = time.perf_counter() - t0

    curves = np.asarray(m["user_gossip_infected"])          # [rounds, G]
    dead = np.asarray(m["dead"])[:, CRASH_NODE]
    gossips = []
    for g, origin, at in SPREADS:
        full = np.flatnonzero(curves[:, g] >= N - 1)
        gossips.append({
            "gossip": g, "origin": origin, "spread_at_round": at,
            "full_dissemination_round": int(full[0]) if full.size else None,
            "dissemination_rounds": (int(full[0]) - at) if full.size
            else None,
            "final_infected": int(curves[-1, g]),
        })
    detected = np.flatnonzero(dead >= N - 1)
    out = {
        "n_members": N,
        "n_user_gossips": G,
        "rounds": ROUNDS,
        "delivery": "shift",
        "log4_n": round(float(np.log(N) / np.log(4)), 2),
        "gossips": gossips,
        "crash": {
            "node": CRASH_NODE, "at_round": CRASH_AT,
            "dead_known_by_all_round": (int(detected[0]) if detected.size
                                        else None),
        },
        "wall_s": round(wall, 1),
        "curve_heads": {str(g): curves[:20, g].tolist() for g in range(G)},
    }
    path = os.path.join(REPO, "artifacts", "user_gossip_1m.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: out[k] for k in ("gossips", "crash", "wall_s")},
                     indent=1))
    print(f"wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
