"""Vmapped chaos mega-campaign sweep: thousands of seeded scenarios per
compile bucket, with optional weakened-build coverage + minimization.

The experiment driver over the fuzz engine (chaos/campaign.
build_buckets + run_campaign_vmapped): generates ``--seeds-per-tier``
scenarios PER severity tier (chaos.generate_fuzz_campaign), buckets
them by compiled shape signature, fuzzes each bucket with ONE device
program, and prints the verdict summary plus the bucket histogram (the
no-silent-caps accounting).  ``--weakened`` additionally reruns the
completeness-promising slice on the deliberately-weakened build
(chaos.weakened_knobs — suspicion timers stretched past the horizon; a
dynamic-knobs change, so the rerun reuses the healthy compiled
programs) and reports the planted violations the fuzzer found;
``--minimize`` shrinks the first weakened violation to its guilty op
(chaos.campaign.minimize) and prints the one-line repro.

The regress-gated speed/quality artifact comes from ``bench.py --fuzz``
(artifacts/fuzz_campaign.json); this driver writes a side artifact
(default ``artifacts/fuzz_sweep.json`` — outside the regress glob) for
ad-hoc sweeps at arbitrary scale.

Usage:
    python experiments/fuzz_campaign.py                  # 334/tier, n=32
    python experiments/fuzz_campaign.py --seeds-per-tier 40 --n 24
    python experiments/fuzz_campaign.py --weakened --minimize
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=100,
                   help="campaign base seed (scenario i uses seed+i)")
    p.add_argument("--seeds-per-tier", type=int, default=334,
                   help="scenarios per severity tier (334 -> 1002 total)")
    p.add_argument("--n", type=int, default=32, help="members per scenario")
    p.add_argument("--delivery", choices=["scatter", "shift"],
                   default="shift")
    p.add_argument("--capacity", type=int, default=256,
                   help="violation evidence lanes per scenario")
    p.add_argument("--weakened", action="store_true",
                   help="also rerun the completeness-promising slice on "
                        "the weakened build (planted-violation coverage)")
    p.add_argument("--minimize", action="store_true",
                   help="shrink the first weakened violation to its "
                        "guilty op and print the one-line repro "
                        "(implies --weakened)")
    p.add_argument("--out", default=os.path.join("artifacts",
                                                 "fuzz_sweep.json"))
    args = p.parse_args()
    if args.minimize:
        args.weakened = True

    from scalecube_cluster_tpu import chaos
    from scalecube_cluster_tpu.chaos import campaign as cc
    from scalecube_cluster_tpu.telemetry import sink as tsink
    from scalecube_cluster_tpu.utils import runlog

    log = runlog.get_logger("fuzz")
    scens = chaos.generate_fuzz_campaign(args.seed, args.seeds_per_tier,
                                         n=args.n)
    t0 = time.time()
    buckets = cc.build_buckets(scens, seed=args.seed,
                               delivery=args.delivery, log=log)
    log.info("%d scenarios -> %d compile buckets (sizes %s)",
             len(scens), len(buckets),
             sorted((b.size for b in buckets), reverse=True))
    with tsink.TelemetrySink.from_env(
            default_dir=os.path.join("artifacts", "telemetry"),
            prefix="fuzz-sweep") as sink:
        result = cc.run_campaign_vmapped(
            scens, seed=args.seed, delivery=args.delivery,
            capacity=args.capacity, sink=sink, log=log, buckets=buckets)
    elapsed = time.time() - t0
    summary = result.summary()
    log.info("mega-campaign: %d/%d green in %.1fs (%.2f scenarios/sec "
             "incl. compiles) -> %s", summary["green_scenarios"],
             summary["scenarios"], elapsed, len(scens) / elapsed,
             result.manifest_path)
    for line in summary["failing_repros"][:10]:
        log.info("RED %s", line)

    artifact = {
        "metric": "fuzz_sweep",
        "seed": args.seed,
        "seeds_per_tier": args.seeds_per_tier,
        "n_members": args.n,
        "delivery": args.delivery,
        "elapsed_sec": round(elapsed, 1),
        "buckets": result.buckets,
        "manifest": result.manifest_path,
        **summary,
    }

    if args.weakened:
        t0 = time.time()
        cov, weak_counts, first_red = cc.run_weakened_slice(
            buckets, capacity=args.capacity)
        weak_total = int(weak_counts.sum())
        healthy = sum(result.verdicts[i].verdict["total_violations"]
                      for i in cov)
        log.info("weakened coverage: %d planted violations over %d "
                 "scenarios (healthy arm: %d) in %.1fs",
                 weak_total, len(cov), healthy, time.time() - t0)
        artifact["coverage"] = {"scenarios": len(cov),
                                "weakened_violations": weak_total,
                                "healthy_violations": healthy}
        if args.minimize and first_red is not None:
            # The candidates must replay on the SAME weakened build the
            # violation was found on, or nothing reproduces and nothing
            # shrinks — minimize's run= hook (+ repro_args, so the
            # emitted line carries the weakening too).
            def weak_run(s):
                return cc.run_scenario(
                    s, seed=args.seed + first_red,
                    delivery=args.delivery,
                    knobs=lambda p: cc.weakened_knobs(s, p))

            minimized = cc.minimize(
                weak_run(scens[first_red]), run=weak_run, log=log,
                repro_args="knobs=lambda p: "
                           "chaos.weakened_knobs(None, p)")
            log.info("minimized (%d op(s) dropped): %s",
                     minimized.dropped_ops, minimized.repro())
            artifact["minimized_repro"] = minimized.repro()

    tmp = args.out + ".tmp"
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    os.replace(tmp, args.out)
    print(json.dumps(artifact))
    return 0 if result.green else 1


if __name__ == "__main__":
    sys.exit(main())
