"""Fusion-splitting the merge: a measured NEGATIVE result.

The round-4 verdict's second roofline variant: split the select-heavy
merge fusion (the one kernel below HBM bandwidth — ~1.03 ms/round at 1M,
artifacts/roofline.json) with ``jax.lax.optimization_barrier`` at the
delivery->merge and merge->timers boundaries.  Measured on the real
tick at 1M x 16 (shift, steady state = 3rd+ execution of the loaded
program; the 1st runs ~3x slow on axon):

    baseline            2.88 ms/round
    barrier@inbox       4.02 ms/round   (+40%)
    barrier@merge_out   3.48 ms/round   (+21%)
    both                4.14 ms/round   (+44%)

Every split is strictly worse: the monolithic fusion's win is exactly
that the merge intermediates (winner status/inc, accept masks) never
hit HBM; a barrier forces them to materialize.  The residual-gap
conclusion stands as a pinned negative alongside the pallas route
(experiments/merge_kernel_bench.py — full-kernel compositions crash the
remote-compile helper; experiments/mosaic_probe.py — the individual
capabilities all work).

Run: ``python experiments/merge_split_bench.py [none|inbox|merge_out|both]``.
"""

import sys
import time

sys.path.insert(0, "/root/repo")
variant = sys.argv[1] if len(sys.argv) > 1 else "none"

import jax

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.ops import delivery
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.utils import runlog

runlog.enable_compilation_cache()

if variant == "inbox":
    orig = swim._merge_and_timers

    def patched(state, status, inc, inbox, inbox_alive, *a, **k):
        inbox, inbox_alive = jax.lax.optimization_barrier(
            (inbox, inbox_alive))
        return orig(state, status, inc, inbox, inbox_alive, *a, **k)

    swim._merge_and_timers = patched
elif variant == "merge_out":
    orig_merge = delivery.merge_inbox

    def patched(*args, **kw):
        return jax.lax.optimization_barrier(orig_merge(*args, **kw))

    delivery.merge_inbox = patched
elif variant == "both":
    orig = swim._merge_and_timers

    def patched(state, status, inc, inbox, inbox_alive, *a, **k):
        inbox, inbox_alive = jax.lax.optimization_barrier(
            (inbox, inbox_alive))
        return orig(state, status, inc, inbox, inbox_alive, *a, **k)

    swim._merge_and_timers = patched
    orig_merge = delivery.merge_inbox

    def patched2(*args, **kw):
        return jax.lax.optimization_barrier(orig_merge(*args, **kw))

    delivery.merge_inbox = patched2

params = swim.SwimParams.from_config(
    ClusterConfig.default(), n_members=1_000_000, n_subjects=16,
    loss_probability=0.02, delivery="shift")
world = swim.SwimWorld.healthy(params).with_crash(3, at_round=50)
key = jax.random.key(0)
s = swim.initial_state(params, world)
times = []
for i in range(4):
    t0 = time.perf_counter()
    s, _ = swim.run(key, params, world, 500, state=s, start_round=500 * i)
    runlog.completion_barrier(s.status)
    times.append((time.perf_counter() - t0) / 500 * 1e3)
print(f"[{variant}] steady {min(times[2:]):.3f} ms/round (calls: "
      f"{[round(t, 2) for t in times]})")
