"""Single-chip full-view capacity ceiling, wide vs compact carry layout.

Full-view mode is the reference's per-node O(cluster) table
(MembershipProtocolImpl.java:82) as [N, N] state.  The wide layout
(13 B/cell carry + int32 wire) measured 16,384 fits / 20,480
RESOURCE_EXHAUSTED in round 3; ``SwimParams.compact_carry`` (6 B/cell
carry + int16 wire — the capacity trade round 3 measured slower at 1M
*focal* and rejected *for speed*, re-purposed here *for capacity*)
should roughly double the reachable N^2.

Each (layout, N) attempt runs in a SUBPROCESS so a RESOURCE_EXHAUSTED
cannot poison the runtime for later attempts, probing a ladder of N per
layout and timing ms/round where it fits.  Writes
``artifacts/fullview_ceiling.json``.

Run: ``python experiments/fullview_ceiling.py`` (TPU, ~10 min).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from experiments.ladder_util import bracket, salvage_run  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUNDS = 60          # timed window per fitting attempt (plus 1 warmup run)
# Finer rungs near the boundary: the wide layout's ceiling moved up in
# round 4 (the metrics restructure removed seven [N, N]-sized pred masks
# from live range), so both layouts are probed from 16k upward.
LADDERS = {
    "wide": [16_384, 20_480, 22_528, 24_576, 26_624],
    # 28,160 brackets the compact boundary at 512-row granularity
    # (27,648 fits / 28,160 fails — round-4 measurement).
    "compact": [16_384, 20_480, 22_528, 24_576, 26_624, 27_648, 28_160,
                28_672, 30_720, 32_768, 36_864],
    # compact + roll-based payload delivery (no persistent doubled
    # [2N, N] buffers — value-identical, slower, but the doubled copies
    # bind the ceiling; SwimParams.shift_roll_payloads).
    "compact_roll": [26_624, 28_160, 28_672, 30_720, 32_768, 36_864],
    # compact + K-tiled round body (SwimParams.k_block): per-channel
    # payload/inbox/merge temps shrink from [N, N] to [N, Kb], leaving
    # peak HBM ~= one donated carry — the round-5 answer to the round-4
    # boundary (which reproduced as a clean RESOURCE_EXHAUSTED: 11.8G of
    # HLO temps at 28,160, six 1.48G per-channel payload buffers;
    # experiments/ceiling_probe.py).  The remaining frontier is NOT HBM:
    # above 36,864 the axon remote-compile helper dies (exit 1, no
    # diagnostics) for every probed block width (round-5 bracketing:
    # 36,864@kb=1024 fits; 36,864@2048, 37,376@512, 37,888@{256,512,
    # 1024}, 38,912@{512,1024}, 40,960@{512,1024,2048} all exit-1) — an
    # infrastructure boundary below the ~6 B/cell carry bound (~50k).
    "compact_blocked": [32_768, 34_816, 36_864, 37_888, 38_912, 40_960],
}
BLOCKED_KB = 1_024   # divides every rung above; 2048 trips the helper
                     # crash earlier (36,864@2048 fails, @1024 fits)

# The (N, k_block) bracketing matrix for the helper-crash frontier —
# recorded into the artifact so the RESULTS.md bracket claims are
# checkable data, not prose.  ``python experiments/fullview_ceiling.py
# bracket`` re-probes just this matrix into the existing artifact.
BRACKETING = [
    (36_864, 1_024),   # fits — the ceiling
    (36_864, 2_048),
    (37_376, 512),
    (37_888, 256), (37_888, 512), (37_888, 1_024),
    (38_912, 512), (38_912, 1_024),
    (40_960, 512), (40_960, 1_024), (40_960, 2_048),
]
# Keep probing past the first failure so the boundary gets bracketed
# (compile-stage failures at rung r don't imply failure at every r' > r a
# priori); stop only once this many consecutive rungs fail.
CONSECUTIVE_FAILURES_TO_STOP = 2

_CHILD = r"""
import json, sys, time
sys.path.insert(0, %(repo)r)
import jax, jax.numpy as jnp, numpy as np
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.utils.runlog import enable_compilation_cache

enable_compilation_cache()
n, compact, roll, rounds = %(n)d, %(compact)r, %(roll)r, %(rounds)d
k_block = %(k_block)d
try:
    params = swim.SwimParams.from_config(
        ClusterConfig.default_local(), n_members=n, delivery="shift",
        compact_carry=compact, shift_roll_payloads=roll,
        suspicion_rounds=6, ping_every=2,
        sync_every=4, per_subject_metrics=False, k_block=k_block,
    )
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=2)
    key = jax.random.key(0)

    # Donate the carry: the caller never reuses the previous window's
    # state, so XLA may alias it into the scan instead of holding input
    # + output copies live — a full carry's worth of HBM at [N, N].
    step = jax.jit(
        lambda k, w, s, r0: swim.run(
            k, params, w, rounds, state=s, start_round=r0),
        donate_argnums=(2,))

    from scalecube_cluster_tpu.utils import runlog

    def force(s):
        return runlog.completion_barrier(s.status)

    state = swim.initial_state(params, world)
    t0 = time.perf_counter()
    state, _ = step(key, world, state, 0)
    force(state)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    state, m = step(key, world, state, rounds)
    force(state)
    elapsed = time.perf_counter() - t0
    # The crash at round 2 must be noticed (suspicion window 6 rounds).
    dead = int(np.asarray(m["dead"]).sum())
    print(json.dumps({
        "fits": True,
        "ms_per_round": round(elapsed / rounds * 1e3, 2),
        "record_updates_per_sec": round(n * n * rounds / elapsed, 1),
        "compile_plus_first_window_s": round(compile_s, 1),
        "crash_noticed": dead > 0,
    }))
except Exception as e:  # noqa: BLE001 — OOM classification by message
    oom = "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)
    print(json.dumps({"fits": False, "oom": oom,
                      "error": f"{type(e).__name__}: {str(e)[:300]}"}))
"""


def attempt(n, layout, k_block=None):
    if k_block is None:
        k_block = BLOCKED_KB if layout.endswith("_blocked") else 0
    code = _CHILD % {"repo": REPO, "n": n,
                     "compact": layout.startswith("compact"),
                     "roll": layout.endswith("_roll"),
                     "k_block": k_block,
                     "rounds": ROUNDS}
    # Subprocess + timeout-salvage machinery shared with
    # experiments/focal_ceiling.py (experiments/ladder_util.py).
    return salvage_run(code, cwd=REPO,
                       fallback={"fits": False, "oom": False})


def run_bracketing():
    """Probe the (N, k_block) frontier matrix; returns artifact rows."""
    rows = []
    for n, kb in BRACKETING:
        r = attempt(n, "compact_blocked", k_block=kb)
        rows.append({"n_members": n, "k_block": kb, "fits": r["fits"]})
        print(f"[bracket] N={n} kb={kb}: fits={r['fits']}", file=sys.stderr)
    return rows


def bracket_only():
    """Update just the kb_bracketing section of the existing artifact."""
    path = os.path.join(REPO, "artifacts", "fullview_ceiling.json")
    with open(path) as f:
        out = json.load(f)
    out["kb_bracketing"] = run_bracketing()
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"updated kb_bracketing in {path}", file=sys.stderr)


def main():
    results = {}
    for layout, ladder in LADDERS.items():
        rows = []
        consecutive_failures = 0
        for n in ladder:
            t0 = time.perf_counter()
            r = attempt(n, layout)
            r.update(n_members=n,
                     attempt_wall_s=round(time.perf_counter() - t0, 1))
            rows.append(r)
            print(f"[{layout}] N={n}: {json.dumps(r)}", file=sys.stderr)
            consecutive_failures = 0 if r["fits"] else consecutive_failures + 1
            if consecutive_failures >= CONSECUTIVE_FAILURES_TO_STOP:
                break
        # The capacity boundary: smallest non-fitting rung ABOVE every
        # fitting rung (ladder_util.bracket; bracketing may probe past a
        # transient failure that a later rung contradicts, so "first
        # failure in probe order" is not the boundary).
        max_fits, first_fail = bracket(rows)
        results[layout] = {
            "bytes_per_cell_carry": 13 if layout == "wide" else 6,
            "attempts": rows,
            "max_fits": max_fits or 0,     # artifact schema: 0, not None
            "first_oom": first_fail,
        }

    ratio = (results["compact"]["max_fits"]
             / max(results["wide"]["max_fits"], 1))
    ratio_b = (results["compact_blocked"]["max_fits"]
               / max(results["wide"]["max_fits"], 1))
    out = {
        "mode": "full-view [N, N], shift delivery, single real TPU chip",
        "rounds_timed": ROUNDS,
        "blocked_k_block": BLOCKED_KB,
        "kb_bracketing": run_bracketing(),
        "layouts": results,
        "compact_over_wide_members": round(ratio, 3),
        "compact_over_wide_cells": round(ratio ** 2, 2),
        "blocked_over_wide_members": round(ratio_b, 3),
        "blocked_over_wide_cells": round(ratio_b ** 2, 2),
    }
    os.makedirs(os.path.join(REPO, "artifacts"), exist_ok=True)
    path = os.path.join(REPO, "artifacts", "fullview_ceiling.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    print(f"wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "bracket":
        bracket_only()
    else:
        main()
