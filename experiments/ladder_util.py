"""Shared machinery for subprocess capacity-ladder experiments.

Used by experiments/fullview_ceiling.py and experiments/focal_ceiling.py:
each (layout, N) attempt runs in a child process so a RESOURCE_EXHAUSTED
(or a compile-helper crash) cannot poison the parent for later rungs,
and a hung child is salvaged rather than losing the ladder.
"""

import json
import subprocess
import sys


def salvage_run(code, cwd, timeout=1200, fallback=None):
    """Run ``python -c code``; return its last JSON line as a dict.

    A hung child is a non-fitting rung, not a lost ladder: on timeout,
    salvage any result the child already printed (a completed
    measurement followed by a teardown hang is a fit), else return
    ``fallback`` annotated with the timeout.  A child that produced no
    JSON at all returns ``fallback`` with rc/stderr context.
    """
    fallback = dict(fallback or {"fits": False, "oom": False})
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout, cwd=cwd)
    except subprocess.TimeoutExpired as e:
        stdout = e.stdout or b""
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", "replace")
        for line in reversed(stdout.splitlines()):
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    break  # killed mid-write: treat as the timeout it is
        return {**fallback, "error": f"timeout ({timeout}s)"}
    for line in reversed(out.stdout.splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                break  # child died mid-print: fall through to context
    return {**fallback,
            "error": f"no parseable output; rc={out.returncode}; "
                     f"stderr tail: {out.stderr[-300:]}"}


def bracket(rows):
    """(max_fits, first_fail_above_max_fits) from ladder rows.

    ``first_fail`` is the smallest failing N above the largest fitting
    one (bracketing may probe past a transient failure), or the smallest
    failing N when nothing fits.
    """
    fits = [r["n_members"] for r in rows if r["fits"]]
    fails = [r["n_members"] for r in rows if not r["fits"]]
    max_fits = max(fits) if fits else None
    first_fail = min([n for n in fails if max_fits is None or n > max_fits],
                     default=None)
    return max_fits, first_fail
