"""Composed plane-runner drill: the full instrumented stack in ONE scan.

Drives ``bench.py --compose`` (the one entry point the measurement
flows through, so the experiment and the driver bench cannot drift):
the full instrumented stack — membership event trace ⊕ in-jit invariant
monitor ⊕ health-metrics registry — run through the composed plane
runner's single scan and single compiled program
(``models/compose.run_composed``), A/B'd against the pre-compose
alias-by-alias route (``run_traced`` + ``run_metered`` +
``run_monitored`` sequentially: three programs, three passes over the
rounds, each re-deriving the per-round live masks / status-change gates
/ wide carry decodes the composed body computes once), with a bare
``swim.run`` anchor arm, all three on one rotated-order interleaved
best-of window and a bit-identity PARITY probe run before any timing.

A separate compile-cost arm counts programs compiled (jit cache misses)
and compile wall seconds across the entry-point × layout matrix:
head-style full instrumentation pays THREE programs per layout, the
composed stack ONE — the strictly-reduced compile count the regress
gate pins alongside ``compose_speedup_ratio >= 1.0`` and the composed
overhead staying within the band of head-style's.

Writes ``artifacts/compose_perf.json`` (override
``SCALECUBE_COMPOSE_ARTIFACT``) and runs the ``telemetry regress`` gate
in-bench — the committed artifact is the pinned compose claim, and
regress exits 1 if it ever rots.  CPU-safe (ratios are same-host
interleaved; absolute rates are provenance).

Usage:
    python experiments/compose_perf.py              # committed shape
    python experiments/compose_perf.py --smoke      # tier-1-safe pass
    python experiments/compose_perf.py --n 2048 --rounds 120
"""

import argparse
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

# The committed artifact's CPU-feasible shape (bench defaults target an
# accelerator: N=1M, 1000-round windows).
DEFAULT_N = 4096
DEFAULT_ROUNDS = 240


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe fast pass (small N, few "
                             "rounds, 2-layout compile arm)")
    parser.add_argument("--n", type=int, default=None,
                        help=f"member count (default {DEFAULT_N}; the "
                             f"bench smoke preset under --smoke)")
    parser.add_argument("--rounds", type=int, default=None,
                        help=f"timed window rounds (default "
                             f"{DEFAULT_ROUNDS})")
    parser.add_argument("--artifact", default=None,
                        help="artifact path (default "
                             "artifacts/compose_perf.json; smoke runs "
                             "default to compose_perf_smoke.json)")
    args = parser.parse_args()

    env = dict(os.environ)
    if not args.smoke:
        env.setdefault("SCALECUBE_BENCH_N", str(args.n or DEFAULT_N))
        env.setdefault("SCALECUBE_BENCH_ROUNDS",
                       str(args.rounds or DEFAULT_ROUNDS))
    else:
        if args.n is not None:
            env["SCALECUBE_BENCH_N"] = str(args.n)
        if args.rounds is not None:
            env["SCALECUBE_BENCH_ROUNDS"] = str(args.rounds)
    if args.artifact:
        env["SCALECUBE_COMPOSE_ARTIFACT"] = args.artifact

    cmd = [sys.executable, str(REPO / "bench.py"), "--compose"]
    if args.smoke:
        cmd.append("--smoke")
    proc = subprocess.run(cmd, cwd=str(REPO), env=env)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
