"""Mosaic capability probes for the merge-kernel plan (run on TPU).

Round 3 established this stack's Mosaic rejects int8 vector compares
("Target does not support this comparison") and i32->i8 truncating
stores ("Unsupported target bitwidth for truncation"), killing the int8
merge-kernel idea (RESULTS.md round-3 log).  The round-4 verdict asks
for the INT16 variant to be measured: this probe answers, per
capability, whether a pallas kernel can

  p16_load:   load int16, upcast, compute in int32
  p16_store:  truncate int32 -> int16 on store
  p16_cmp:    compare int16 vectors directly
  p8_load:    load int8 + upcast (reads are fine even if compares are not)
  merge_core: the actual merge inner loop (i8/i16/i32 planes in, int32
              compute, i16/i32 out) at a realistic [rows, 128] block

Prints one JSON line per probe.  Run: ``python
experiments/mosaic_probe.py``.
"""

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def try_probe(name, fn):
    try:
        out = fn()
        print(json.dumps({"probe": name, "ok": True,
                          "out": float(jnp.asarray(out).sum())}))
    except Exception as e:  # noqa: BLE001 — capability probe by design
        msg = str(e).split("\n")[0][:200]
        print(json.dumps({"probe": name, "ok": False, "error": msg}))


ROWS, COLS = 512, 128


def k_load16(x_ref, o_ref):
    o_ref[...] = x_ref[...].astype(jnp.int32) + 1


def k_store16(x_ref, o_ref):
    o_ref[...] = (x_ref[...] + 1).astype(jnp.int16)


def k_cmp16(x_ref, y_ref, o_ref):
    o_ref[...] = jnp.where(x_ref[...] > y_ref[...],
                           jnp.int16(1), jnp.int16(0)).astype(jnp.int32)


def k_load8(x_ref, o_ref):
    o_ref[...] = x_ref[...].astype(jnp.int32) * 2


def k_merge_core(status_ref, inc_ref, inbox_ref, alive_ref,
                 status_out, inc_out):
    """The merge inner loop shape: int8-as-i32 status plane + i32 inc +
    i32 inbox keys + bool-as-i8 gate; i16 status out, i32 inc out."""
    status = status_ref[...].astype(jnp.int32)
    inc = inc_ref[...]
    key = inbox_ref[...]
    gate = alive_ref[...].astype(jnp.int32)
    win_inc = jnp.where(key < 0, 0, (key >> 1) & ((1 << 29) - 1))
    win_dead = (key >> 30) & 1
    win_status = jnp.where(win_dead == 1, 2,
                           jnp.where((key & 1) == 1, 1, 0))
    win_status = jnp.where(key < 0, 3, win_status)
    accepts = (win_inc > inc) | ((win_inc == inc) & (win_status > status))
    accepts = accepts & (gate > 0)
    status_out[...] = jnp.where(accepts, win_status, status).astype(jnp.int16)
    inc_out[...] = jnp.where(accepts, win_inc, inc)


def main():
    key = jax.random.key(0)
    x16 = jax.random.randint(key, (ROWS, COLS), 0, 100, dtype=jnp.int16)
    y16 = jax.random.randint(key, (ROWS, COLS), 0, 100, dtype=jnp.int16)
    x8 = jax.random.randint(key, (ROWS, COLS), 0, 100, dtype=jnp.int8)
    xi = jax.random.randint(key, (ROWS, COLS), -1, 1 << 20, dtype=jnp.int32)

    try_probe("p16_load", lambda: pl.pallas_call(
        k_load16, out_shape=jax.ShapeDtypeStruct((ROWS, COLS), jnp.int32)
    )(x16))
    try_probe("p16_store", lambda: pl.pallas_call(
        k_store16, out_shape=jax.ShapeDtypeStruct((ROWS, COLS), jnp.int16)
    )(jnp.abs(xi) % 1000))
    try_probe("p16_cmp", lambda: pl.pallas_call(
        k_cmp16, out_shape=jax.ShapeDtypeStruct((ROWS, COLS), jnp.int32)
    )(x16, y16))
    try_probe("p8_load", lambda: pl.pallas_call(
        k_load8, out_shape=jax.ShapeDtypeStruct((ROWS, COLS), jnp.int32)
    )(x8))
    try_probe("merge_core", lambda: pl.pallas_call(
        k_merge_core,
        out_shape=(jax.ShapeDtypeStruct((ROWS, COLS), jnp.int16),
                   jax.ShapeDtypeStruct((ROWS, COLS), jnp.int32)),
    )(x8, jnp.abs(xi), xi, x8))


if __name__ == "__main__":
    main()
