"""The BASELINE north-star run: 1M SWIM members × 10k rounds on one chip.

Executes the full target workload ("simulate 1M SWIM members for 10k
gossip rounds", BASELINE.json) with a realistic fault schedule — 2% loss,
a hard crash, a graceful leave, and a crash-with-revival — checkpointing
the carry every 2500 rounds (utils/checkpoint.py), then a BASELINE
config-5 parameter sweep (fanout × ping-interval × suspicion-mult) at the
same 1M scale.  Writes ``artifacts/northstar_1m_10k.json`` with event
timelines, throughput, and the sweep curves.

Run: ``python experiments/northstar.py`` (TPU; ~2 min total).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.telemetry import sink as telemetry_sink
from scalecube_cluster_tpu.utils import checkpoint, get_logger
from scalecube_cluster_tpu.utils.runlog import enable_compilation_cache

N = 1_000_000
K = 16
ROUNDS = 10_000
# Scan round fusion (SwimParams.rounds_per_step): bit-identical to the
# unfused scan, amortizes per-step scan dispatch/carry fix-ups on
# device; 1 on the CPU fallback, where unrolling measured slower
# (bench.resolve_rounds_per_step has the numbers).
ROUNDS_PER_STEP = 1 if jax.default_backend() == "cpu" else 4
CRASH_NODE, CRASH_AT = 3, 500
LEAVE_NODE, LEAVE_AT = 5, 2_000
REVIVE_NODE, REVIVE_DOWN, REVIVE_UP = 7, 4_000, 7_000

log = get_logger("northstar")
enable_compilation_cache(log)


def first(cond, default=-1):
    idx = np.flatnonzero(cond)
    return int(idx[0]) if idx.size else default


def event_timeline(metrics, slot, t0):
    alive_view = np.asarray(metrics["alive"])[:, slot]
    suspects = np.asarray(metrics["suspect"])[:, slot]
    deads = np.asarray(metrics["dead"])[:, slot]
    return {
        "suspect_onset": first((suspects > 0) & (np.arange(len(suspects)) >= t0)),
        "dead_declared": first((deads > 0) & (np.arange(len(deads)) >= t0)),
        "fully_disseminated": first(
            (alive_view == 0) & (suspects == 0) & (deads > 0)
            & (np.arange(len(deads)) >= t0)
        ),
    }


def main():
    params = swim.SwimParams.from_config(
        ClusterConfig.default(), n_members=N, n_subjects=K,
        loss_probability=0.02, delivery="shift",
        rounds_per_step=ROUNDS_PER_STEP,
    )
    world = (
        swim.SwimWorld.healthy(params)
        .with_crash(CRASH_NODE, at_round=CRASH_AT)
        .with_leave(LEAVE_NODE, at_round=LEAVE_AT)
        .with_crash(REVIVE_NODE, at_round=REVIVE_DOWN, until_round=REVIVE_UP)
    )
    key = jax.random.key(0)

    ckpt = "artifacts/northstar_ckpt.npz"
    os.makedirs("artifacts", exist_ok=True)
    for f in os.listdir("artifacts"):
        if f.startswith("northstar_ckpt"):
            os.unlink(os.path.join("artifacts", f))

    t0 = time.perf_counter()
    # Passing the initial state explicitly keeps every chunk on ONE
    # compiled trace (state=None would trace chunk 1 without a state
    # argument and chunk 2 with one — two ~45s compiles instead of one).
    final, chunks = checkpoint.run_checkpointed(
        swim.run, key, params, world, ROUNDS, ckpt, chunk=2_500,
        state=swim.initial_state(params, world),
        meta={"n": N, "rounds": ROUNDS}, log=log,
    )
    jax.block_until_ready(final.status)
    elapsed = time.perf_counter() - t0
    metrics = {
        name: np.concatenate([np.asarray(c[name]) for c in chunks])
        for name in chunks[0]
    }
    log.info("10k rounds in %.1fs (%.2e member-rounds/s incl. compile + io)",
             elapsed, N * ROUNDS / elapsed)

    # Telemetry manifest: run id + config digest + device info, one
    # counter row per checkpoint chunk, and the crash-dissemination
    # curve (telemetry/sink.py; dir from SCALECUBE_TPU_TELEMETRY_DIR,
    # default artifacts/telemetry).
    sink = telemetry_sink.TelemetrySink.from_env(
        default_dir="artifacts/telemetry", prefix="northstar"
    )
    if sink is not None:
        sink.write_manifest(
            params=params,
            workload={"n_members": N, "rounds": ROUNDS, "chunk": 2_500,
                      "loss": 0.02, "delivery": "shift"},
        )
        for i, c in enumerate(chunks):
            sink.write_counters(c, round_offset=i * 2_500,
                                label=f"chunk_{i}")
        sink.write_curve(
            "fraction_informed",
            telemetry_sink.fraction_informed_curve(
                np.asarray(metrics["dead"])[:, CRASH_NODE], N - 1
            ),
            subject=CRASH_NODE, fault_round=CRASH_AT,
        )
        telemetry_sink.maybe_export_tensorboard(
            sink.run_id,
            scalars={
                "northstar/dead_views": metrics["dead"],
                "northstar/false_positives": metrics["false_positives"],
                "northstar/messages_gossip": metrics["messages_gossip"],
            },
            log=log,
        )

    suspicion = params.suspicion_rounds
    result = {
        "workload": f"{N} members x {ROUNDS} rounds, 2% loss, shift delivery",
        "wall_seconds": round(elapsed, 1),
        "member_rounds_per_sec_incl_overheads": round(N * ROUNDS / elapsed, 1),
        "suspicion_rounds": suspicion,
        "events": {
            f"crash@{CRASH_AT}": event_timeline(metrics, CRASH_NODE,
                                                CRASH_AT),
            f"leave@{LEAVE_AT}": event_timeline(metrics, LEAVE_NODE,
                                                LEAVE_AT),
            f"crash@{REVIVE_DOWN}_revive@{REVIVE_UP}": event_timeline(
                metrics, REVIVE_NODE, REVIVE_DOWN
            ),
        },
        # Live observers of the revived node at the end: everyone except
        # itself, the permanently crashed node, and the leaver.
        "revived_reaccepted": bool(
            np.asarray(metrics["alive"])[-1, REVIVE_NODE] == N - 3
        ),
        "revival_disseminated_round": first(
            (np.asarray(metrics["alive"])[:, REVIVE_NODE] == N - 3)
            & (np.arange(ROUNDS) >= REVIVE_UP)
        ),
        "total_refutations": int(np.asarray(metrics["refutations"]).sum()),
        "false_positive_observer_rounds": int(
            np.asarray(metrics["false_positives"]).sum()
        ),
        # The FP split (see swim_tick metrics docs): genuine FD false-alarm
        # onset events vs stale-DEAD-tombstone observer-rounds (dominated
        # by the post-revival window until re-dissemination).
        "false_suspicion_onsets": int(
            np.asarray(metrics["false_suspicion_onsets"]).sum()
        ),
        "false_suspect_observer_rounds": int(
            np.asarray(metrics["false_suspect_rounds"]).sum()
        ),
        "stale_view_observer_rounds": int(
            np.asarray(metrics["stale_view_rounds"]).sum()
        ),
    }

    # Close the manifest BEFORE the sweep: the headline run's records are
    # durable even if a sweep point dies (the riskiest section at 1M).
    if sink is not None:
        sink.write_summary(
            wall_seconds=result["wall_seconds"],
            events=result["events"],
            total_refutations=result["total_refutations"],
            false_positive_observer_rounds=result[
                "false_positive_observer_rounds"],
        )
        sink.close()
        log.info("telemetry manifest at %s", sink.path)

    # ---- BASELINE config 5: the 1M parameter sweep -----------------------
    # One compiled program (knobs are traced), looped over the grid points
    # sequentially; 2k rounds per point keeps the whole sweep ~2 min.
    grid = []
    for fanout in (2, 3):
        for ping_every in (2, 5):
            for suspicion_mult in (3, 5):
                grid.append((fanout, ping_every, suspicion_mult))
    sweep_params = swim.SwimParams.from_config(
        ClusterConfig.default(), n_members=N, n_subjects=K,
        loss_probability=0.02, delivery="shift", fanout=3,
        rounds_per_step=ROUNDS_PER_STEP,
    )
    sweep_world = swim.SwimWorld.healthy(sweep_params).with_crash(
        0, at_round=0
    )
    sweep_rows = []
    base_cfg = ClusterConfig.default()
    for i, (fanout, ping_every, sus_mult) in enumerate(grid):
        # Derive the suspicion timeout exactly the way every other run
        # does: sweep ping_every by scaling ping_interval on the config,
        # then let to_sim quantize (ClusterMath.suspicionTimeout ties the
        # timeout to the swept ping interval, ClusterMath.java:123-125).
        cfg_i = base_cfg.replace(
            ping_interval=base_cfg.gossip_interval * ping_every,
            ping_timeout=base_cfg.gossip_interval * ping_every // 2,
            suspicion_mult=sus_mult,
        )
        sim_i = cfg_i.to_sim(N)
        kn = swim.Knobs(
            loss_probability=jax.numpy.float32(0.02),
            suspicion_rounds=jax.numpy.int32(sim_i.suspicion_rounds),
            ping_every=jax.numpy.int32(sim_i.ping_every),
            sync_every=jax.numpy.int32(sweep_params.sync_every),
            fanout=jax.numpy.int32(fanout),
        )
        _, m = swim.run(jax.random.fold_in(key, i), sweep_params,
                        sweep_world, 2_000, knobs=kn)
        deads = np.asarray(m["dead"])[:, 0]
        alive_view = np.asarray(m["alive"])[:, 0]
        suspects = np.asarray(m["suspect"])[:, 0]
        sweep_rows.append({
            "fanout": fanout, "ping_every": ping_every,
            "suspicion_mult": sus_mult,
            "detection_round": first(deads > 0),
            "dissemination_round": first(
                (alive_view == 0) & (suspects == 0) & (deads > 0)
            ),
            "fp_observer_rounds": int(
                np.asarray(m["false_positives"]).sum()
            ),
            "false_suspicion_onsets": int(
                np.asarray(m["false_suspicion_onsets"]).sum()
            ),
            "false_suspect_observer_rounds": int(
                np.asarray(m["false_suspect_rounds"]).sum()
            ),
            "stale_view_observer_rounds": int(
                np.asarray(m["stale_view_rounds"]).sum()
            ),
        })
        log.info("sweep point %d/%d done", i + 1, len(grid))
    result["sweep_1m"] = sweep_rows

    out = "artifacts/northstar_1m_10k.json"
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({k: v for k, v in result.items() if k != "sweep_1m"},
                     indent=1))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
