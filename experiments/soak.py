"""Production soak: one long-lived service under streaming chaos.

Drives ``bench.py --soak`` (the one entry point the drift invariants
flow through, so the experiment and the driver bench cannot drift):
the never-repeating seeded chaos stream (``soak.schedule`` — every
segment boundary straddled by an in-flight fault) run through the
resilient supervisor's ``composed`` shape with the full plane stack
(trace ⊕ metrics ⊕ monitor ⊕ sync ⊕ lifeguard ⊕ open-world) and the
live alarm engine armed, all rows streaming to ONE exactly-once JSONL
journal.  Per-segment drift invariants: compile cache flat after
segment 1, host RSS bounded, zero monitor violations.  Then the
drill: a seeded mid-soak SIGKILL in a child process, relaunch over
the rotated checkpoints — the merged journal's content rows must be
BYTE-IDENTICAL to an uninterrupted reference run and the final state
digest must match bit-for-bit.

Writes ``artifacts/soak_report.json`` (override
``SCALECUBE_SOAK_ARTIFACT``) plus the soak journal next to it, and
runs the ``telemetry regress`` gate in-bench — the committed artifact
is the pinned robustness claim, and regress exits 1 if it ever rots.
The journal replays live (segment boundaries + cumulative rounds)::

    python -m scalecube_cluster_tpu.telemetry watch \
        artifacts/soak_journal.jsonl

CPU-safe (the stream is seeded; ``SCALECUBE_SOAK_ROUNDS=100000``
scales the lifetime — also reachable as the ``@slow`` arm of
``tests/test_soak.py``).

Usage:
    python experiments/soak.py                  # committed shape
    python experiments/soak.py --smoke          # tier-1-safe pass
    python experiments/soak.py --rounds 100000  # the long arm
"""

import argparse
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe fast pass (the bench smoke "
                             "geometry: n=16, 2 x 128-round segments)")
    parser.add_argument("--n", type=int, default=None,
                        help="member count (bench default: 32 full / "
                             "16 smoke)")
    parser.add_argument("--seed", type=int, default=None,
                        help="stream seed (default 7; the stream is "
                             "pure in (seed, segment, n, severity))")
    parser.add_argument("--severity", default=None,
                        choices=("mild", "moderate", "severe"),
                        help="chaos severity tier (default moderate)")
    parser.add_argument("--rounds", type=int, default=None,
                        help="total lifetime in rounds (rounded up to "
                             "whole segments; default 8 x 256 full / "
                             "2 x 128 smoke)")
    parser.add_argument("--artifact", default=None,
                        help="artifact path (default "
                             "artifacts/soak_report.json; smoke runs "
                             "default to soak_report_smoke.json)")
    args = parser.parse_args()

    env = dict(os.environ)
    for flag, var in ((args.n, "SCALECUBE_SOAK_N"),
                      (args.seed, "SCALECUBE_SOAK_SEED"),
                      (args.severity, "SCALECUBE_SOAK_SEVERITY"),
                      (args.rounds, "SCALECUBE_SOAK_ROUNDS"),
                      (args.artifact, "SCALECUBE_SOAK_ARTIFACT")):
        if flag is not None:
            env[var] = str(flag)

    cmd = [sys.executable, str(REPO / "bench.py"), "--soak"]
    if args.smoke:
        cmd.append("--smoke")
    proc = subprocess.run(cmd, cwd=str(REPO), env=env)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
