"""BASELINE config 5 as ONE compiled program: a 27-cell grid at 1M.

Round 4 satisfied config 5 by looping the grid sequentially
(experiments/northstar.py) because vmapped shift-mode delivery degraded
to gathers above ~16k members.  Round 5's shared-shift batching
(sweep.sweep_run docstring: the channel shifts come from one unbatched
key, so the payload dynamic-slices stay batch-invariant under vmap)
makes the original promise real: one ``jax.vmap`` over one compiled
scan sweeps fanout × ping-interval × suspicion-mult at 1,000,000
members — and runs FASTER than the sequential loop (the batch amortizes
the per-round [N]-vector work and dispatch).

Writes ``artifacts/sweep_1m.json`` with the per-cell crash curves, the
analytic anchors, and the measured vmap-vs-sequential wall comparison;
pinned by tests/test_results_claims.py.

Run: ``python experiments/sweep_1m.py`` (TPU, ~5 min).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_MEMBERS = 1_000_000
N_SUBJECTS = 16
N_ROUNDS = 600
GRID = dict(fanout=[2, 3, 4], ping_every=[2, 5, 10],
            suspicion_rounds=[20, 40, 60])


def main():
    import jax
    import numpy as np

    from scalecube_cluster_tpu import sweep, swim_math
    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.utils.runlog import enable_compilation_cache

    enable_compilation_cache()
    config = ClusterConfig.default()
    params = swim.SwimParams.from_config(
        config, n_members=N_MEMBERS, n_subjects=N_SUBJECTS,
        delivery="shift", fanout=max(GRID["fanout"]),
    )
    world = swim.SwimWorld.healthy(params).with_crash(0, at_round=0)
    knobs = sweep.knob_grid(params, **GRID)
    n_cells = int(knobs.fanout.shape[0])
    key = jax.random.key(0)

    # One compiled program over the whole grid: warm, then time.
    t0 = time.perf_counter()
    metrics = sweep.sweep_run(key, params, world, N_ROUNDS, knobs)
    jax.block_until_ready(metrics["dead"])
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    metrics = sweep.sweep_run(jax.random.key(1), params, world, N_ROUNDS,
                              knobs)
    float(np.asarray(metrics["dead"]).sum())   # scalar-fetch barrier
    vmap_s = time.perf_counter() - t0
    print(f"[sweep] {n_cells} cells x {N_ROUNDS} rounds @ {N_MEMBERS}: "
          f"{vmap_s:.1f}s (compile+first {compile_s:.1f}s)",
          file=sys.stderr)

    # The sequential baseline: same grid, one compiled single-cell
    # program looped on the host.
    def one(key, kn):
        _, m = swim.run(key, params, world, N_ROUNDS, knobs=kn)
        return m

    one_j = jax.jit(one)
    kn0 = jax.tree.map(lambda x: x[0], knobs)
    m1 = one_j(jax.random.key(2), kn0)
    jax.block_until_ready(m1["dead"])
    t0 = time.perf_counter()
    for b in range(n_cells):
        knb = jax.tree.map(lambda x: x[b], knobs)
        m1 = one_j(jax.random.fold_in(jax.random.key(1), b), knb)
    float(np.asarray(m1["dead"]).sum())
    seq_s = time.perf_counter() - t0
    print(f"[seq] {seq_s:.1f}s; vmap/seq = {vmap_s / seq_s:.2f}",
          file=sys.stderr)

    curves = sweep.crash_curves(metrics, subject_slot=0, n_rounds=N_ROUNDS,
                                n_members=N_MEMBERS)
    out = {
        "n_members": N_MEMBERS,
        "n_subjects": N_SUBJECTS,
        "n_rounds": N_ROUNDS,
        "n_cells": n_cells,
        "grid": {name: np.asarray(getattr(knobs, name)).tolist()
                 for name in ("fanout", "ping_every", "suspicion_rounds",
                              "loss_probability", "sync_every")},
        "curves": {k: v.tolist() for k, v in curves.items()},
        "one_program": True,
        "wall": {
            "vmap_s": round(vmap_s, 2),
            "sequential_s": round(seq_s, 2),
            "vmap_over_sequential": round(vmap_s / seq_s, 3),
            "compile_plus_first_s": round(compile_s, 1),
        },
        "analytic": {
            "periods_to_spread": swim_math.gossip_periods_to_spread(
                config.gossip_repeat_mult, N_MEMBERS
            ),
        },
    }
    path = os.path.join(REPO, "artifacts", "sweep_1m.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: out[k] for k in ("n_cells", "wall")}, indent=1))
    print(f"wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
