"""Open-world membership drill: JOIN admission A/B under a churn storm.

Drives ``bench.py --churn`` (the one entry point the measurement flows
through, so the experiment and the driver bench cannot drift): the
seeded ``chaos.churn_growth_scenario`` NET-POSITIVE arrival storm —
permanent crash waves whose slots are recycled by mid-run JOINs landing
mid-suspicion of the previous occupants (who die at incarnation >= 1
via a pre-death scare), plus a pre-dead arrivals pool so the cluster
GROWS — run twice per scenario seed on the same key,

  - plane:   ``open_world=True`` with the identity-epoch guard
    (``SwimState.epoch`` lane + (slot, epoch, incarnation) wire keys;
    cross-epoch records drop at the merge gate, new identities admit
    only through their own ALIVE announcement),
  - control: ``epoch_guard=False`` — NAIVE slot reuse on the
    reference's epoch-blind wire,

and judged by the in-jit invariant monitor: the guard must hold ZERO
``NO_RESURRECTION`` / ``JOIN_COMPLETENESS`` violations with
``join_propagation_p99`` (rounds from each join to every observer's
JOINED admission, from the traced event stream) inside the scenario's
dissemination bound, while the naive arm must DEMONSTRATE the
resurrection failure (violations > 0 — the dead identity's
ALIVE@inc>=1 records living in tables, convicted attribution-free by
incarnation forensics).  Writes ``artifacts/churn_growth.json``
(override ``--artifact``) and runs the ``telemetry regress`` gate
in-bench — the committed artifact is the pinned open-world claim, and
regress exits 1 if it ever rots.

CPU-safe (the workload is a small-N full-view A/B, not a throughput
measurement).

Usage:
    python experiments/churn_growth.py              # committed shape
    python experiments/churn_growth.py --smoke      # tier-1-safe pass
    python experiments/churn_growth.py --n 48 --scenarios 5 --seed 23
"""

import argparse
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe fast pass (one scenario)")
    parser.add_argument("--n", type=int, default=None,
                        help="member count (default 48; 24 under "
                             "--smoke)")
    parser.add_argument("--scenarios", type=int, default=None,
                        help="scenario seeds per arm (default 3; 1 "
                             "under --smoke)")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--suppress", type=int, default=None,
                        help="dead_suppress_rounds on both arms "
                             "(default 0 — the reference reopen "
                             "behavior; the guard must admit joins "
                             "over suppressed tombstones either way)")
    parser.add_argument("--artifact", default=None,
                        help="artifact path (default "
                             "artifacts/churn_growth.json)")
    args = parser.parse_args()

    env = dict(os.environ)
    for flag, var in ((args.n, "SCALECUBE_CHURN_N"),
                      (args.scenarios, "SCALECUBE_CHURN_SCENARIOS"),
                      (args.seed, "SCALECUBE_CHURN_SEED"),
                      (args.suppress, "SCALECUBE_CHURN_SUPPRESS"),
                      (args.artifact, "SCALECUBE_CHURN_ARTIFACT")):
        if flag is not None:
            env[var] = str(flag)

    cmd = [sys.executable, str(REPO / "bench.py"), "--churn"]
    if args.smoke:
        cmd.append("--smoke")
    return subprocess.run(cmd, env=env, cwd=str(REPO)).returncode


if __name__ == "__main__":
    sys.exit(main())
