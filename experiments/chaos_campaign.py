"""Seeded chaos-campaign sweep: generated fault scenarios through the
in-jit invariant monitor, with JSONL verdict manifests + an artifact.

Drives ``chaos.generate_campaign`` (severity-tiered scenarios — churn
storms, flapping links, rolling partitions, crash bursts, brownouts)
through ``chaos.run_campaign``: every scenario runs on-device under
the invariant monitor (chaos/monitor.py) and any failure prints its
one-line repro.  Optionally cross-validates the oracle-expressible
scenarios (crash/leave schedules) against the event-driven oracle at
small N.

Writes ``artifacts/chaos_campaign.json`` (atomic) plus one JSONL
manifest per invocation under ``SCALECUBE_TPU_TELEMETRY_DIR`` (default
``artifacts/telemetry``).

Usage:
    python experiments/chaos_campaign.py                 # 21 scenarios, n=32
    python experiments/chaos_campaign.py --scenarios 45 --n 64
    python experiments/chaos_campaign.py --severity severe --seed 7
    python experiments/chaos_campaign.py --cross-validate --n 16
    python experiments/chaos_campaign.py --repro-seed 103 --severity mild
                                          # re-run ONE failing scenario
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=100,
                   help="campaign base seed (scenario i uses seed+i)")
    p.add_argument("--scenarios", type=int, default=21,
                   help="number of generated scenarios")
    p.add_argument("--n", type=int, default=32, help="members per scenario")
    p.add_argument("--severity", choices=["mild", "moderate", "severe"],
                   default=None,
                   help="restrict to one severity tier (default: cycle "
                        "mild/moderate/severe)")
    p.add_argument("--delivery", choices=["scatter", "shift"],
                   default="shift")
    p.add_argument("--cross-validate", action="store_true",
                   help="also replay oracle-expressible scenarios on the "
                        "event-driven oracle and diff event key sets "
                        "(small n recommended)")
    p.add_argument("--repro-seed", type=int, default=None,
                   help="run exactly ONE scenario, "
                        "generate_scenario(seed=REPRO_SEED, n, severity), "
                        "with run seed REPRO_SEED (the campaign's seed "
                        "alignment); requires --severity")
    p.add_argument("--out", default=os.path.join("artifacts",
                                                 "chaos_campaign.json"))
    args = p.parse_args()

    from scalecube_cluster_tpu import chaos
    from scalecube_cluster_tpu.telemetry import sink as tsink
    from scalecube_cluster_tpu.utils import runlog

    log = runlog.get_logger("chaos")
    severities = ([args.severity] if args.severity
                  else list(chaos.SEVERITIES))

    if args.repro_seed is not None:
        if args.severity is None:
            p.error("--repro-seed needs --severity: the scenario is a "
                    "pure function of (seed, n, severity), and a repro "
                    "with the wrong tier is a different scenario")
        scens = [chaos.generate_scenario(
            seed=args.repro_seed, n=args.n, severity=args.severity)]
        run_seed = args.repro_seed      # campaign alignment: run == scenario
    else:
        scens = chaos.generate_campaign(
            seed=args.seed, n_scenarios=args.scenarios, n=args.n,
            severities=severities)
        run_seed = args.seed

    t0 = time.time()
    with tsink.TelemetrySink.from_env(
            default_dir=os.path.join("artifacts", "telemetry"),
            prefix="chaos") as sink:
        result = chaos.run_campaign(
            scens, seed=run_seed, delivery=args.delivery, sink=sink,
            log=log, cross_validate_small_n=args.cross_validate)
    elapsed = time.time() - t0

    summary = result.summary()
    xv = [v.cross_validation for v in result.verdicts
          if v.cross_validation is not None]
    artifact = {
        "metric": "chaos_campaign",
        "seed": run_seed,
        "n_members": args.n,
        "delivery": args.delivery,
        "severities": severities,
        "elapsed_sec": round(elapsed, 1),
        "manifest": result.manifest_path,
        "cross_validated": len(xv),
        # null when nothing was cross-validated — a check that never
        # ran must not read as a check that passed.
        "cross_validation_agree": (all(d["agree"] for d in xv)
                                   if xv else None),
        **summary,
    }
    for v in result.verdicts:
        tag = "green" if v.green else "RED"
        log.info("%-44s %s  %s", v.scenario.name, tag,
                 "" if v.green else v.repro())
    log.info("campaign: %d/%d green in %.1fs -> %s",
             summary["green_scenarios"], summary["scenarios"], elapsed,
             result.manifest_path)

    tmp = args.out + ".tmp"
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    os.replace(tmp, args.out)
    print(json.dumps(artifact))
    return 0 if result.green else 1


if __name__ == "__main__":
    sys.exit(main())
