"""Live SLO alarm drill: streaming breach detection, measured.

Drives ``bench.py --alarms`` (the one entry point the detection-lag
measurement flows through, so the experiment and the driver bench
cannot drift): the seeded ``chaos.alarm_drill_scenario`` square loss
pulse run TWICE on the same world through live journaling
``stream_metered_run(..., alarm_specs=...)`` — the HEALTHY arm
(campaign-default Knobs) must ride the pulse out with zero
``alarm_transition`` rows, the BREACH arm (``chaos.alarm_breach_knobs``
probe-every-round weakening; dynamic Knobs, so the rerun reuses the
healthy arm's compiled program — zero extra compiles) must reach
FIRING within one metrics window of the pulse onset
(``alarm_detection_lag_windows <= 1``, the headline) and RESOLVE after
the heal.

Writes ``artifacts/alarm_drill.json`` (override
``SCALECUBE_ALARM_ARTIFACT``) plus both arms' journals next to it, and
runs the ``telemetry regress`` gate in-bench — the committed artifact
is the pinned detection claim, and regress exits 1 if it ever rots.
The journals replay live::

    python -m scalecube_cluster_tpu.telemetry watch \
        artifacts/alarm_drill_breach.jsonl --json

CPU-safe (the drill is seeded and threshold-calibrated per geometry —
telemetry.alarms.DEFAULT_FP_THRESHOLD / bench.SMOKE_ALARM_THRESHOLD).

Usage:
    python experiments/alarm_drill.py               # committed shape
    python experiments/alarm_drill.py --smoke       # tier-1-safe pass
    python experiments/alarm_drill.py --n 48 --seed 7
"""

import argparse
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe fast pass (the bench smoke "
                             "geometry: n=24, 16-round windows)")
    parser.add_argument("--n", type=int, default=None,
                        help="member count (bench default: 48 full / "
                             "24 smoke)")
    parser.add_argument("--seed", type=int, default=None,
                        help="scenario seed (default 7; NOTE the smoke "
                             "threshold is calibrated for seed 7 — a "
                             "different seed needs "
                             "SCALECUBE_ALARM_THRESHOLD recalibrated)")
    parser.add_argument("--threshold", type=float, default=None,
                        help="override the calibrated breach threshold")
    parser.add_argument("--artifact", default=None,
                        help="artifact path (default "
                             "artifacts/alarm_drill.json; smoke runs "
                             "default to alarm_drill_smoke.json)")
    args = parser.parse_args()

    env = dict(os.environ)
    for flag, var in ((args.n, "SCALECUBE_ALARM_N"),
                      (args.seed, "SCALECUBE_ALARM_SEED"),
                      (args.threshold, "SCALECUBE_ALARM_THRESHOLD"),
                      (args.artifact, "SCALECUBE_ALARM_ARTIFACT")):
        if flag is not None:
            env[var] = str(flag)

    cmd = [sys.executable, str(REPO / "bench.py"), "--alarms"]
    if args.smoke:
        cmd.append("--smoke")
    proc = subprocess.run(cmd, cwd=str(REPO), env=env)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
