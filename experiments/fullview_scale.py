"""Full-view (exact reference semantics) past one chip: sharded rows.

Full-view mode is the reference's per-node O(cluster) membership table
(MembershipProtocolImpl.java:82) — [N, N] state, 13 bytes/cell across the
carry.  One v5e chip fits N = 16,384 (measured 45 ms/round; N = 20,480
is RESOURCE_EXHAUSTED — the mode is HBM-capacity-bound, not
compute-bound).  Beyond that the row-sharded mesh path
(parallel/mesh.shard_run + ShiftEngine block rotations) carries
13*N^2/D bytes per device, so every doubling of the mesh doubles the
reachable N^2.

This experiment demonstrates exact-semantics correctness PAST the
single-chip ceiling on the virtual 8-device CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8): a full
crash -> suspicion -> DEAD -> dissemination -> revival -> re-acceptance
cycle at N = 32,768 rows (2x the single-chip ceiling; env
SCALECUBE_FULLVIEW_N to push further — 65,536 fits this host's RAM).
Timing on the virtual mesh is NOT a performance number (all 8 virtual
devices share this host's core); the multi-chip perf projection is
parallel/traffic.py's job.  Writes ``artifacts/fullview_scale.json``.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8
     JAX_PLATFORMS=cpu python experiments/fullview_scale.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# This experiment is DEFINED on the virtual CPU mesh (one real chip is
# attached at most); force the platform — the environment may carry
# JAX_PLATFORMS=axon, under which make_mesh(8) would silently become a
# 1-device TPU mesh and OOM at [N, N] state.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    )

import jax

# The axon image pins jax_platforms at import time, so the env var alone
# is not enough (same workaround as tests/conftest.py).
jax.config.update("jax_platforms", "cpu")

import numpy as np

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.parallel import mesh as mesh_lib
from scalecube_cluster_tpu.utils import get_logger
from scalecube_cluster_tpu.utils.runlog import enable_compilation_cache

N = int(os.environ.get("SCALECUBE_FULLVIEW_N", 32_768))
# 1 = the capacity-oriented compact carry layout (6 B/cell + int16 wire,
# SwimParams.compact_carry) — halves per-device state on the mesh.
COMPACT = os.environ.get("SCALECUBE_FULLVIEW_COMPACT", "") == "1"
BYTES_PER_CELL = 6 if COMPACT else 13
# Measured N=32k timeline: suspected 2, DEAD 8, disseminated 16; the
# revived node's first sync push lands on the next sync_every boundary
# and the re-accept gossips out in ~log4(N)+sweep rounds, so heal lands
# ~12 rounds after revival.
CRASH_NODE, CRASH_AT, REVIVE_AT = 3, 2, 22
ROUNDS = int(os.environ.get("SCALECUBE_FULLVIEW_ROUNDS", 52))

log = get_logger("fullview_scale")
enable_compilation_cache(log)


def first(cond, default=-1):
    idx = np.flatnonzero(cond)
    return int(idx[0]) if idx.size else default


def main():
    mesh = mesh_lib.make_mesh(8)
    config = ClusterConfig.default_local()
    # Short protocol windows so the full cycle fits in a ~minute-scale
    # run at [N, N] state (the LOCAL preset's 480-round suspicion window
    # would demand thousands of rounds; the schedule is the same
    # machinery, just faster).
    params = swim.SwimParams.from_config(
        config, n_members=N, delivery="shift",  # full view: n_subjects=None
        suspicion_rounds=6, ping_every=2, sync_every=4,
        compact_carry=COMPACT,
    )
    world = swim.SwimWorld.healthy(params).with_crash(
        CRASH_NODE, at_round=CRASH_AT, until_round=REVIVE_AT
    )
    log.info("N=%d full-view rows over %d devices (%s layout: %.1f GB "
             "state, %.2f GB/device)", N, mesh.devices.size,
             "compact" if COMPACT else "wide",
             BYTES_PER_CELL * N * N / 1e9,
             BYTES_PER_CELL * N * N / mesh.devices.size / 1e9)

    t0 = time.perf_counter()
    state, metrics = mesh_lib.shard_run(
        jax.random.key(0), params, world, ROUNDS, mesh
    )
    jax.block_until_ready(state.status)
    wall = time.perf_counter() - t0
    log.info("%d rounds in %.1fs (%.1f s/round incl. compile, virtual "
             "mesh — not a perf number)", ROUNDS, wall, wall / ROUNDS)

    suspects = np.asarray(metrics["suspect"])[:, CRASH_NODE]
    deads = np.asarray(metrics["dead"])[:, CRASH_NODE]
    alive_view = np.asarray(metrics["alive"])[:, CRASH_NODE]
    n_obs = N - 1  # everyone but the crashed node itself

    timeline = {
        "suspected": first(suspects > 0),
        "declared_dead": first(deads > 0),
        "death_disseminated": first(deads == n_obs),
        "healed": first(
            (alive_view == n_obs) & (np.arange(ROUNDS) >= REVIVE_AT)
        ),
    }
    log.info("timeline: %s", timeline)
    fp = int(np.asarray(metrics["false_suspicion_onsets"]).sum())

    result = {
        "n_members": N,
        "mode": "full-view (exact reference semantics, [N, N] state)",
        "carry_layout": "compact" if COMPACT else "wide",
        "bytes_per_cell": BYTES_PER_CELL,
        "devices": int(mesh.devices.size),
        "state_gb": round(BYTES_PER_CELL * N * N / 1e9, 2),
        "state_gb_per_device": round(
            BYTES_PER_CELL * N * N / mesh.devices.size / 1e9, 2),
        "rounds": ROUNDS,
        "wall_seconds_virtual_mesh": round(wall, 1),
        "timeline": timeline,
        "false_suspicion_onsets": fp,
        # Measured separately (per layout) by experiments/fullview_ceiling.py.
        "single_chip_ceiling": "see artifacts/fullview_ceiling.json",
        "note": "virtual 8-device CPU mesh shares one host core; timing "
                "is a correctness artifact, not perf — see "
                "parallel/traffic.py for the multi-chip projection",
    }
    # Artifact first (a ~1.5h compute run must not evaporate on a failed
    # expectation), assertions second.
    os.makedirs("artifacts", exist_ok=True)
    # Non-default configurations get their own artifact name (derived
    # from N + layout) so the canonical 32k wide demo — cited by
    # RESULTS.md and pinned by tests/test_results_claims.py — is never
    # silently overwritten by a differently-configured run.
    default_out = (
        "artifacts/fullview_scale.json"
        if (N, COMPACT) == (32_768, False)
        else f"artifacts/fullview_scale_{N // 1024}k_"
             f"{'compact' if COMPACT else 'wide'}.json"
    )
    out = os.environ.get("SCALECUBE_FULLVIEW_OUT", default_out)
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result, indent=1))
    print(f"wrote {out}")

    # Correctness assertions: the full exact-semantics cycle.
    assert CRASH_AT <= timeline["suspected"] < timeline["declared_dead"], timeline
    assert timeline["declared_dead"] == timeline["suspected"] + \
        params.suspicion_rounds, timeline
    assert timeline["declared_dead"] <= timeline["death_disseminated"] \
        < REVIVE_AT, timeline
    assert timeline["healed"] >= REVIVE_AT, timeline
    # Final state: every live observer holds ALIVE for the revived node.
    assert int(alive_view[-1]) == n_obs, int(alive_view[-1])
    assert fp == 0, f"lossless run produced {fp} false-suspicion onsets"
    print("correctness assertions passed")


if __name__ == "__main__":
    main()
