"""Staged config-rollout drill: the metadata KV plane under fire.

Drives ``bench.py --rollout`` (the one entry point the rollout
measurement flows through, so the experiment and the driver bench
cannot drift): a staged ``ConfigPush`` wave schedule rolled through a
live cluster while a partition splits and heals mid-rollout, then

  - the gated arm polls ``models/metadata.divergence_probe`` at every
    stage boundary and advances only while each stage converges inside
    its deadline — otherwise it rebuilds the tail as a rollback push
    (``StagedRollout.rollback_ops``); the committed claim is that NO
    rollback fires and the final table is globally agreed;
  - the monitored chaos-campaign arm (``chaos.run_monitored``) must
    come back green with zero invariant violations;
  - the gossip-only control (``sync_interval=0``) demonstrably stays
    divergent at the horizon: without the SYNC full-table exchange a
    push landing inside the split never heals.

Writes ``artifacts/config_rollout.json`` (override ``--artifact``) and
runs the ``telemetry regress`` gate in-bench — the committed artifact
is the pinned robustness claim: versioned config propagates, staged
rollouts converge within ``metadata_convergence_p99`` of the deadline,
and without the anti-entropy leg they provably do not.

CPU-safe; the committed shape is N=48, three stages of four owners.

Usage:
    python experiments/config_rollout.py            # committed shape
    python experiments/config_rollout.py --smoke    # tier-1-safe pass
    python experiments/config_rollout.py --n 256 --stages 4
    python experiments/config_rollout.py --sync-interval 16 --seed 7
"""

import argparse
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe fast pass (small N)")
    parser.add_argument("--n", type=int, default=None,
                        help="member count (default 48)")
    parser.add_argument("--stages", type=int, default=None,
                        help="rollout stage count (default 3)")
    parser.add_argument("--stage-size", type=int, default=None,
                        help="owners flipped per stage (default 4)")
    parser.add_argument("--sync-interval", type=int, default=None,
                        help="anti-entropy exchange cadence in rounds "
                             "(default 8)")
    parser.add_argument("--probe-step", type=int, default=None,
                        help="divergence-probe cadence in rounds "
                             "(default 2)")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--artifact", default=None,
                        help="artifact path (default "
                             "artifacts/config_rollout.json)")
    args = parser.parse_args()

    env = dict(os.environ)
    for flag, var in ((args.n, "SCALECUBE_ROLLOUT_N"),
                      (args.stages, "SCALECUBE_ROLLOUT_STAGES"),
                      (args.stage_size, "SCALECUBE_ROLLOUT_STAGE_SIZE"),
                      (args.sync_interval,
                       "SCALECUBE_ROLLOUT_SYNC_INTERVAL"),
                      (args.probe_step, "SCALECUBE_ROLLOUT_PROBE_STEP"),
                      (args.seed, "SCALECUBE_ROLLOUT_SEED"),
                      (args.artifact, "SCALECUBE_ROLLOUT_ARTIFACT")):
        if flag is not None:
            env[var] = str(flag)

    cmd = [sys.executable, str(REPO / "bench.py"), "--rollout"]
    if args.smoke:
        cmd.append("--smoke")
    return subprocess.run(cmd, env=env, cwd=str(REPO)).returncode


if __name__ == "__main__":
    sys.exit(main())
