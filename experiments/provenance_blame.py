"""Provenance blame drill: per-belief channel attribution, measured.

Drives ``bench.py --blame`` (the one entry point the blame measurement
flows through, so the experiment and the driver bench cannot drift):
the seeded ``chaos.blame_drill_scenario`` — ONE asymmetric faulty link
(victim→observer acks drop at loss=1.0, every other link pristine) —
run through the composed stack with the provenance plane armed.  Four
claims measured and regress-gated ABSOLUTELY:

  - BLAME: the host-side blame engine, fed only the recorded
    (observer, subject, transition, channel, round) attributions, must
    name the planted link's observer as ``origin_observer`` with a
    first-hand ``fd_direct`` sighting — even though almost every other
    member heard the false suspicion second-hand via gossip;
  - ATTRIBUTION: every recorded transition carries exactly one channel
    (fractions sum to 1.0), zero provenance-buffer and trace drops;
  - OFF-SWITCH: the same composed run with ``provenance=False`` is
    bit-identical in protocol states AND stacked metrics;
  - OVERHEAD: ``provenance_overhead_ratio`` (interleaved best-of,
    armed vs bare composed stack) <= query.PROVENANCE_OVERHEAD_LIMIT.

Writes ``artifacts/provenance_blame.json`` (override
``SCALECUBE_BLAME_ARTIFACT``) plus the journal with the new
``provenance`` record kind next to it.  Any recorded belief replays
from the journal alone::

    python -m scalecube_cluster_tpu.telemetry explain \
        artifacts/provenance_blame_journal.jsonl \
        --observer 11 --subject 3

CPU-safe (the drill is seeded; the overhead arm is an interleaved
best-of, resilient to host-load jitter).

Usage:
    python experiments/provenance_blame.py            # committed shape
    python experiments/provenance_blame.py --smoke    # tier-1-safe pass
    python experiments/provenance_blame.py --n 48 --seed 7
"""

import argparse
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe fast pass (the bench smoke "
                             "geometry: n=16, 128-round horizon)")
    parser.add_argument("--n", type=int, default=None,
                        help="member count (bench default: 48 full / "
                             "16 smoke)")
    parser.add_argument("--seed", type=int, default=None,
                        help="scenario + run seed (default 7)")
    parser.add_argument("--victim", type=int, default=None,
                        help="the falsely-suspected member (default 3)")
    parser.add_argument("--observer", type=int, default=None,
                        help="the member behind the faulty link "
                             "(default 11)")
    parser.add_argument("--reps", type=int, default=None,
                        help="overhead-arm interleaved windows "
                             "(default 40)")
    parser.add_argument("--artifact", default=None,
                        help="artifact path (default "
                             "artifacts/provenance_blame.json; smoke "
                             "runs default to "
                             "provenance_blame_smoke.json)")
    args = parser.parse_args()

    env = dict(os.environ)
    for flag, var in ((args.n, "SCALECUBE_BLAME_N"),
                      (args.seed, "SCALECUBE_BLAME_SEED"),
                      (args.victim, "SCALECUBE_BLAME_VICTIM"),
                      (args.observer, "SCALECUBE_BLAME_OBSERVER"),
                      (args.reps, "SCALECUBE_BLAME_REPS"),
                      (args.artifact, "SCALECUBE_BLAME_ARTIFACT")):
        if flag is not None:
            env[var] = str(flag)

    cmd = [sys.executable, str(REPO / "bench.py"), "--blame"]
    if args.smoke:
        cmd.append("--smoke")
    proc = subprocess.run(cmd, cwd=str(REPO), env=env)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
