"""Bisect the full-view compile-stage ceiling: which program piece fails?

Round 4 established that compact full-view [N, N] fits at 27,648 and
fails at 28,160 with an opaque remote-compile failure
(``tpu_compile_helper subprocess exit code 1`` — not a clean
RESOURCE_EXHAUSTED; artifacts/fullview_ceiling.json).  This probe runs
one piece of the program per subprocess at a chosen N to localize the
failing stage:

  piece=scan60   the round-4 shape: 60-round scan (known-fail at 28160)
  piece=scan1    a single-round scan (is the scan the problem?)
  piece=tick     the tick body jitted without any scan
  piece=deliver  just the shift-delivery channels (prep + 5 rotations)
  piece=merge    just the merge + timers tail on a fake inbox
  piece=alloc    just allocating the carry + one elementwise pass

Run: ``python experiments/ceiling_probe.py N piece`` in a child, or
``python experiments/ceiling_probe.py sweep N`` to try all pieces.
Findings land in RESULTS.md; this script is the reproducer.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PIECES = ["alloc", "deliver", "merge", "tick", "scan1", "scan60"]


def child(n: int, piece: str) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.ops import shift as shift_ops
    from scalecube_cluster_tpu.utils.runlog import (
        completion_barrier, enable_compilation_cache,
    )

    enable_compilation_cache()
    params = swim.SwimParams.from_config(
        ClusterConfig.default_local(), n_members=n, delivery="shift",
        compact_carry=True, suspicion_rounds=6, ping_every=2,
        sync_every=4, per_subject_metrics=False,
    )
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=2)
    key = jax.random.key(0)
    state = swim.initial_state(params, world)

    t0 = time.perf_counter()
    if piece == "alloc":
        @jax.jit
        def f(s):
            return jnp.sum((s.status == 1).astype(jnp.int32))
        out = float(f(state))
    elif piece == "deliver":
        # The five channel rotations on the doubled payload buffer — the
        # largest single intermediate ([2N, N] int16).
        @jax.jit
        def f(s, k):
            eng = shift_ops.ShiftEngine(n)
            keys16 = s.inc  # int16 [N, N] stand-in payload
            h = eng.prep(keys16)
            shifts = jax.random.randint(k, (5,), 1, n, dtype=jnp.int32)
            acc = jnp.zeros_like(keys16)
            for c in range(5):
                acc = jnp.maximum(acc, eng.deliver(h, shifts[c]))
            return jnp.sum(acc.astype(jnp.int32))
        out = float(f(state, key))
    elif piece == "merge":
        from scalecube_cluster_tpu.ops import delivery
        @jax.jit
        def f(s, k):
            inbox = jnp.where(
                jax.random.bernoulli(k, 0.1, s.status.shape),
                jnp.int16(2), jnp.int16(-1))
            st, inc, ch = delivery.merge_inbox(
                s.status, s.inc.astype(jnp.int32), inbox,
                inbox >= 0, compact=True)
            return jnp.sum(ch.astype(jnp.int32))
        out = float(f(state, key))
    elif piece == "tick":
        @jax.jit
        def f(s, k):
            s2, m = swim.swim_tick(s, jnp.int32(0), k, params, world)
            return s2
        out = completion_barrier(f(state, key).status)
    elif piece in ("scan1", "scan60"):
        rounds = 1 if piece == "scan1" else 60
        step = jax.jit(
            lambda k, w, s: swim.run(k, params, w, rounds, state=s),
            static_argnums=(), donate_argnums=(2,))
        s2, m = step(key, world, state)
        out = completion_barrier(s2.status)
    else:
        raise SystemExit(f"unknown piece {piece}")
    print(json.dumps({"ok": True, "piece": piece, "n": n,
                      "wall_s": round(time.perf_counter() - t0, 1),
                      "out": out}))


def probe(n: int, piece: str) -> dict:
    code = (f"import sys; sys.path.insert(0, {REPO!r}); "
            f"from experiments.ceiling_probe import child; "
            f"child({n}, {piece!r})")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=1200,
                             cwd=REPO)
    except subprocess.TimeoutExpired:
        return {"ok": False, "piece": piece, "n": n, "error": "timeout"}
    for line in reversed(out.stdout.splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                break
    tail = (out.stderr or "")[-500:]
    return {"ok": False, "piece": piece, "n": n,
            "rc": out.returncode, "stderr_tail": tail}


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "sweep":
        n = int(sys.argv[2])
        pieces = sys.argv[3:] or PIECES
        for piece in pieces:
            r = probe(n, piece)
            print(f"[{piece}@{n}] {json.dumps(r)[:400]}", file=sys.stderr)
    else:
        child(int(sys.argv[1]), sys.argv[2])


if __name__ == "__main__":
    main()
