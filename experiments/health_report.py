"""Health report under chaos: a seeded fault scenario with the
always-on metrics registry riding the monitored run.

The end-to-end drill of the numeric health plane (telemetry/metrics.py
+ telemetry/query.py): one generated chaos scenario
(chaos/scenarios.py, reproducible from its seed line) runs through
``chaos.monitor.run_monitored_metered`` in flush windows; every window
lands as a ``metrics_window`` JSONL record, the invariant verdict as a
``chaos_scenario`` record, and the script then folds the manifest BACK
through the query layer — the same ``report`` path the CLI serves — to
render the per-window SLO table and write ``artifacts/
health_report.json``.  What this proves: health numbers survive the
full device → registry → JSONL → query round trip under real faults,
not just on a healthy run.

Env overrides: SCALECUBE_HEALTH_SEED (default 7), SCALECUBE_HEALTH_N
(default 32), SCALECUBE_HEALTH_SEVERITY (default "moderate"),
SCALECUBE_HEALTH_WINDOW (default horizon/4).

Usage:  JAX_PLATFORMS=cpu python experiments/health_report.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import numpy as np  # noqa: F401 — keeps the experiment import shape

    from scalecube_cluster_tpu import chaos
    from scalecube_cluster_tpu.chaos import campaign as ccampaign
    from scalecube_cluster_tpu.chaos import monitor as cmonitor
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.telemetry import metrics as tmetrics
    from scalecube_cluster_tpu.telemetry import query as tquery
    from scalecube_cluster_tpu.telemetry import sink as tsink

    seed = int(os.environ.get("SCALECUBE_HEALTH_SEED", 7))
    n = int(os.environ.get("SCALECUBE_HEALTH_N", 32))
    severity = os.environ.get("SCALECUBE_HEALTH_SEVERITY", "moderate")

    scenario = chaos.generate_scenario(seed=seed, n=n, severity=severity)
    params = ccampaign.campaign_params(scenario)
    world, mon_spec = scenario.build(params)
    spec = tmetrics.MetricsSpec.default()
    window = int(os.environ.get("SCALECUBE_HEALTH_WINDOW",
                                max(1, scenario.horizon // 4)))
    print(f"[health] scenario {scenario.name} (repro: {scenario.repro()})"
          f"\n[health] horizon {scenario.horizon} rounds, "
          f"window {window}, n={n}", file=sys.stderr)

    out_dir = (os.environ.get(tsink.TELEMETRY_DIR_ENV)
               or os.path.join("artifacts", "telemetry"))
    sink = tsink.TelemetrySink(out_dir, prefix="health")
    sink.write_manifest(params=params, workload={
        "mode": "health_report",
        "scenario": scenario.name,
        "repro": scenario.repro(),
        "severity": severity,
        "horizon": scenario.horizon,
    })

    t0 = time.time()
    state = swim.initial_state(params, world)
    monitor = None
    ms = tmetrics.MetricsState.init(spec)
    r = 0
    while r < scenario.horizon:
        step = min(window, scenario.horizon - r)
        state, monitor, ms, _ = cmonitor.run_monitored_metered(
            jax.random.key(seed), params, world, mon_spec, step,
            state=state, start_round=r, monitor=monitor,
            metrics_spec=spec, metrics_state=ms,
        )
        row = {"round_start": r, "round_end": r + step,
               **tmetrics.to_json(jax.device_get(ms), spec)}
        sink.write_metrics_window(row)
        ms = tmetrics.reset_window(ms)
        r += step
    verdict = cmonitor.verdict(monitor)
    sink.write_record("chaos_scenario", {
        "name": scenario.name, "repro": scenario.repro(),
        "green": verdict["green"], "verdict": verdict,
    })
    sink.write_summary(green=verdict["green"],
                       total_violations=verdict["total_violations"])
    sink.close()
    elapsed = time.time() - t0

    # Fold the manifest back through the query layer (the CLI's path).
    report = tquery.load_report(sink.path)
    slos = tquery.compute_slos(report)

    wrows = [{
        "window": f"[{w['round_start']}, {w['round_end']})",
        "fp_onsets": w["counters"]["false_suspicion_onsets"],
        "suspicions": w["counters"]["suspicions_started"],
        "fired": w["counters"]["suspicions_fired"],
        "violations": w["counters"]["chaos_violations"],
        "suspect_q": w["gauges"]["suspect_entries"],
        "occupancy": w["gauges"]["gossip_piggyback_occupancy"],
    } for w in report.windows]
    print(f"\n# per-window health ({scenario.name}, seed {seed})")
    print(tquery.format_table(
        wrows, ["window", "fp_onsets", "suspicions", "fired",
                "violations", "suspect_q", "occupancy"]))
    print("\n# SLOs")
    print(tquery.format_table(
        [{"metric": k, "value": v} for k, v in slos.items()],
        ["metric", "value"]))
    print(f"\n[health] verdict: "
          f"{'green' if verdict['green'] else 'RED'} in {elapsed:.1f}s",
          file=sys.stderr)

    payload = {
        "experiment": "health_report",
        "scenario": scenario.name,
        "repro": scenario.repro(),
        "seed": seed,
        "n_members": n,
        "severity": severity,
        "horizon": scenario.horizon,
        "window_rounds": window,
        "green": verdict["green"],
        "violations_by_code": {k: v["violations"]
                               for k, v in verdict["codes"].items()},
        "windows": report.windows,
        "slos": slos,
        "counters": report.counters,
        "gauges": report.gauges,
        "manifest": sink.path,
        "elapsed_sec": round(elapsed, 2),
    }
    out = os.environ.get("SCALECUBE_HEALTH_ARTIFACT",
                         os.path.join("artifacts", "health_report.json"))
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(json.dumps({"experiment": "health_report", "green":
                      verdict["green"], "artifact": out,
                      "slos": {k: v for k, v in slos.items()
                               if v is not None}}), flush=True)


if __name__ == "__main__":
    main()
