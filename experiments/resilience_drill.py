"""Kill-injection resilience drill: SIGKILL + relaunch over rotated,
checksummed checkpoints, for every run shape, with an artifact.

Drives ``resilience.harness.run_drill``: for each run shape (plain /
traced / monitored) a subprocess runs the resilient supervisor
(``resilience/supervisor.py`` — checkpointed segments into the
generation-rotated checksummed store, resumable JSONL journal), is
SIGKILLed at seeded random (round, write-stage) points, and is
relaunched to completion.  The drill then asserts the two headline
guarantees — resumed final state bit-identical to an uninterrupted run
(full-payload content digest), merged journal covering every round
exactly once with the event stream matching — plus the
corrupted-latest-generation fallback (bit-flip the newest checkpoint,
load recovers from the previous intact generation).

CPU by design: this is a correctness harness, and the guarantees are
backend-independent.

Writes ``artifacts/resilience_drill.json`` (atomic).

Usage:
    python experiments/resilience_drill.py                # full matrix
    python experiments/resilience_drill.py --kills 5 --rounds 192
    python experiments/resilience_drill.py --shapes traced --kills 1
    python experiments/resilience_drill.py --seed 77      # new kill draw
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--shapes", default="plain,traced,monitored",
                   help="comma list of run shapes to drill")
    p.add_argument("--n", type=int, default=32, help="members per run")
    p.add_argument("--rounds", type=int, default=96,
                   help="protocol rounds per run")
    p.add_argument("--segment", type=int, default=16,
                   help="rounds per checkpointed segment")
    p.add_argument("--kills", type=int, default=3,
                   help="SIGKILLs injected per shape before the final "
                        "relaunch")
    p.add_argument("--seed", type=int, default=1234,
                   help="kill-schedule seed (rounds + write-stages)")
    p.add_argument("--keep", type=int, default=3,
                   help="checkpoint generations retained")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="per-child-launch timeout (seconds)")
    p.add_argument("--out", default=os.path.join("artifacts",
                                                 "resilience_drill.json"))
    args = p.parse_args()

    from scalecube_cluster_tpu.resilience import harness as rh
    from scalecube_cluster_tpu.utils import runlog

    log = runlog.get_logger("resilience")
    shapes = tuple(s for s in args.shapes.split(",") if s)
    overrides = {
        "n_members": args.n,
        "n_rounds": args.rounds,
        "segment_rounds": args.segment,
        "keep_generations": args.keep,
    }

    t0 = time.time()
    with tempfile.TemporaryDirectory(prefix="resilience-drill-") as wd:
        report = rh.run_drill(
            shapes, wd, kill_seed=args.seed, n_kills=args.kills,
            timeout=args.timeout, cfg_overrides=overrides,
            extra_env={"JAX_PLATFORMS": "cpu"},
        )
    elapsed = time.time() - t0

    for shape, v in report["shapes"].items():
        tag = "green" if v["ok"] else "RED"
        log.info("%-10s %s  kills=%s launches=%d segments=%s",
                 shape, tag, v.get("kills"),
                 len(v.get("launches", ())), v.get("journal_segments"))
        if not v["ok"]:
            log.info("  detail: %s", json.dumps(v))
    log.info("corruption fallback: %s (loaded gen %s after: %s)",
             "green" if report["corruption"]["ok"] else "RED",
             report["corruption"].get("loaded_generation"),
             report["corruption"].get("fallbacks"))
    log.info("drill: green=%s in %.1fs", report["green"], elapsed)

    artifact = {
        "metric": "resilience_drill",
        "seed": args.seed,
        "shapes": list(shapes),
        "n_members": args.n,
        "rounds": args.rounds,
        "segment_rounds": args.segment,
        "kills_per_shape": args.kills,
        "keep_generations": args.keep,
        "elapsed_sec": round(elapsed, 1),
        "green": report["green"],
        "verdicts": {
            s: {k: v[k] for k in ("ok", "bit_identical",
                                  "journal_complete", "events_match",
                                  "journal_segments", "events", "kills")
                if k in v}
            for s, v in report["shapes"].items()
        },
        "corruption": {
            k: report["corruption"][k]
            for k in ("ok", "generations", "loaded_generation",
                      "fallbacks")
            if k in report["corruption"]
        },
    }
    tmp = args.out + ".tmp"
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    os.replace(tmp, args.out)
    print(json.dumps(artifact))
    return 0 if report["green"] else 1


if __name__ == "__main__":
    sys.exit(main())
