"""SYNC anti-entropy partition-heal drill: the plane vs gossip-only.

Drives ``bench.py --sync`` (the one entry point the heal measurement
flows through, so the experiment and the driver bench cannot drift):
a quiesced RollingPartition split, healed, then

  - the monitored chaos-campaign-scale arm (``chaos.run_monitored``
    with the POST_HEAL_DIVERGENCE agreement window armed) must come
    back green while the gossip-only control's tables stay divergent;
  - the focal-shift scale arm (the 1M bench shape) is probed every few
    rounds after the heal for the first divergence-free membership
    table: ``sync_rounds_to_converge``.

Writes ``artifacts/sync_heal.json`` (override ``--artifact``) and runs
the ``telemetry regress`` gate in-bench — the committed artifact is the
pinned robustness claim: partitions HEAL, with a measured convergence
bound, and without the plane they provably do not.

CPU-safe; the design-target scale arm is N=1M on an accelerator
(``--n 1000000``), default here is the CPU-feasible 65536.

Usage:
    python experiments/sync_heal.py                 # committed shape
    python experiments/sync_heal.py --smoke         # tier-1-safe pass
    python experiments/sync_heal.py --n 1000000     # accelerator scale
    python experiments/sync_heal.py --sync-interval 64 --seed 11
"""

import argparse
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe fast pass (small N)")
    parser.add_argument("--n", type=int, default=None,
                        help="scale-arm member count "
                             "(default 65536; 1000000 on an accelerator)")
    parser.add_argument("--subjects", type=int, default=None,
                        help="focal subject count (default 16)")
    parser.add_argument("--sync-interval", type=int, default=None,
                        help="anti-entropy exchange cadence in rounds "
                             "(default 32)")
    parser.add_argument("--monitor-n", type=int, default=None,
                        help="monitored-arm member count (default 32)")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--artifact", default=None,
                        help="artifact path (default "
                             "artifacts/sync_heal.json)")
    args = parser.parse_args()

    env = dict(os.environ)
    if not args.smoke and args.n is None:
        env.setdefault("SCALECUBE_SYNC_N", "65536")
    for flag, var in ((args.n, "SCALECUBE_SYNC_N"),
                      (args.subjects, "SCALECUBE_SYNC_SUBJECTS"),
                      (args.sync_interval, "SCALECUBE_SYNC_INTERVAL"),
                      (args.monitor_n, "SCALECUBE_SYNC_MONITOR_N"),
                      (args.seed, "SCALECUBE_SYNC_SEED"),
                      (args.artifact, "SCALECUBE_SYNC_ARTIFACT")):
        if flag is not None:
            env[var] = str(flag)

    cmd = [sys.executable, str(REPO / "bench.py"), "--sync"]
    if args.smoke:
        cmd.append("--smoke")
    return subprocess.run(cmd, env=env, cwd=str(REPO)).returncode


if __name__ == "__main__":
    sys.exit(main())
