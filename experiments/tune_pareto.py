"""Protocol autotuner: sweep the knob grid in one compile per shape
bucket, ship the Pareto frontier and the tuned-default profiles.

Drives ``bench.py --tune`` (the one entry point the tune measurement
flows through, so the experiment and the driver bench cannot drift):
the config grid (probe cadence, timeouts, suspicion, SYNC cadence,
Lifeguard ceilings, dead-suppression) runs over the seeded scenario
batch through ``tune/search.sweep`` — knob data is TRACED operands on
the batched composed scan, so the whole grid compiles once per
scenario shape bucket and never per config (the witness lands in the
artifact: ``tune_compiles == tune_shape_buckets``, warm recompiles 0).
The gated ``batch_speedup_ratio`` compares that one-compile dynamic
sweep against the static counterfactual — every config baked into
``SwimParams`` and recompiled — measured on real cold configs.  Each
shipped profile must be monitor-green, STRICTLY better than the
reference default on its target objective, Pareto-non-dominated, and
fuzz-oracle green on a held-out seed.

Writes ``artifacts/tune_pareto.json`` (override
``SCALECUBE_TUNE_ARTIFACT``) and runs the ``telemetry regress`` gate
in-bench — the committed artifact is the pinned frontier claim, and
regress exits 1 if it ever rots.  Apply a shipped profile::

    params = SwimParams.tuned("fast-detect", n_members=4096)

CPU-safe (the committed artifact's scale); on an accelerator raise
``--scenarios``/``--n`` for a denser frontier.

Usage:
    python experiments/tune_pareto.py               # committed shape
    python experiments/tune_pareto.py --smoke       # tier-1-safe pass
    python experiments/tune_pareto.py --n 32 --scenarios 12
"""

import argparse
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe fast pass (core grid, n=16, "
                             "6 scenarios, 1 fuzz seed/tier)")
    parser.add_argument("--n", type=int, default=None,
                        help="member count (bench default: 32 full / "
                             "16 smoke)")
    parser.add_argument("--scenarios", type=int, default=None,
                        help="scenario-batch size (default 12 full / "
                             "6 smoke)")
    parser.add_argument("--seed", type=int, default=None,
                        help="scenario seed (default 500)")
    parser.add_argument("--held-out-seed", type=int, default=None,
                        help="fuzz-oracle validation seed (default "
                             "7001; must differ from --seed)")
    parser.add_argument("--artifact", default=None,
                        help="artifact path (default "
                             "artifacts/tune_pareto.json; smoke runs "
                             "default to tune_pareto_smoke.json)")
    args = parser.parse_args()

    env = dict(os.environ)
    for flag, var in ((args.n, "SCALECUBE_TUNE_N"),
                      (args.scenarios, "SCALECUBE_TUNE_SCENARIOS"),
                      (args.seed, "SCALECUBE_TUNE_SEED"),
                      (args.held_out_seed, "SCALECUBE_TUNE_HELDOUT_SEED"),
                      (args.artifact, "SCALECUBE_TUNE_ARTIFACT")):
        if flag is not None:
            env[var] = str(flag)

    cmd = [sys.executable, str(REPO / "bench.py"), "--tune"]
    if args.smoke:
        cmd.append("--smoke")
    proc = subprocess.run(cmd, cwd=str(REPO), env=env)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
