"""Pallas merge kernel vs the XLA merge fusion, isolated at 1M x 16.

The round-4 roofline pinned the residual gap to ONE fusion: the merge
(merge_inbox + refutation + timers + freeze) runs ~1.03 ms/round at 1M
focal — ~350-500 GB/s on its ~0.5 GB of plane traffic vs the 819 GB/s
HBM peak.  Mosaic rejects int8 compares and i32->i8 stores (round-3
negative), but experiments/mosaic_probe.py shows int8/int16 LOADS,
int32 compute, and i32->i16 stores all work — so the int16-status-plane
variant the round-4 verdict asked for is buildable.

This benchmark isolates the comparison: the same merge math over
[1M, 16] planes, (a) as XLA ops (what the tick's fusion does), (b) as a
pallas kernel (int8 status in, int16 status out, i32 compute).  Both
run inside a 100-iteration lax.scan that feeds outputs back to inputs,
so the measurement is steady-state HBM streaming, immune to the axon
memoization trap.  Prints one JSON line; informs whether the kernel is
worth integrating.

Run: ``python experiments/merge_kernel_bench.py`` (TPU, ~2 min).
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

N, K = 1_000_000, 16
ITERS = 100
SUSPECT, DEAD, ABSENT, ALIVE = 1, 2, 3, 0
INT32_MAX = jnp.iinfo(jnp.int32).max


def merge_math(status, inc, spread, deadline, self_inc, inbox, inbox_alive,
               alive_here, round_idx, is_self):
    """The merge + refutation + timers + freeze math, dtype-generic
    (mirrors models/swim._merge_and_timers at G=0, no rings)."""
    status = status.astype(jnp.int32)
    inc = inc
    key = inbox
    win_inc = jnp.where(key < 0, 0, (key >> 1) & ((1 << 29) - 1))
    win_dead = (key >> 30) & 1
    win_status = jnp.where(win_dead == 1, DEAD,
                           jnp.where((key & 1) == 1, SUSPECT, ALIVE))
    win_status = jnp.where(key < 0, ABSENT, win_status)
    gate_status = jnp.where(status == DEAD, ABSENT, status)
    # is_overrides lattice in packed-key order (records.merge_key is
    # monotone): higher inc wins; equal inc -> SUSPECT beats ALIVE.
    accepts = (
        (win_inc > inc) | ((win_inc == inc) & (win_status == SUSPECT)
                           & (gate_status == ALIVE))
    ) & (win_status != ABSENT)
    absent = gate_status == ABSENT
    accepts = jnp.where(absent,
                        (inbox_alive > 0) & (win_status != ABSENT), accepts)

    new_status = jnp.where(accepts, win_status, status)
    new_inc = jnp.where(accepts, win_inc, inc)
    changed = accepts & ((new_status != status) | (new_inc != inc))

    self_ov = is_self & (win_inc > self_inc[:, None])
    refuted = jnp.any(self_ov, axis=1)
    bumped = jnp.max(jnp.where(self_ov, win_inc, 0), axis=1) + 1
    new_self = jnp.where(refuted & alive_here, jnp.maximum(self_inc, bumped),
                         self_inc)
    new_status = jnp.where(is_self, ALIVE, new_status)
    new_inc = jnp.where(is_self, new_self[:, None], new_inc)

    no_timer = deadline == INT32_MAX
    start = changed & (new_status == SUSPECT) & no_timer
    cancel = changed & (new_status != SUSPECT)
    dl = jnp.where(start, round_idx + 30,
                   jnp.where(cancel, INT32_MAX, deadline))
    fired = (new_status == SUSPECT) & (round_idx >= dl)
    new_status = jnp.where(fired, DEAD, new_status)
    dl = jnp.where(fired, INT32_MAX, dl)
    changed = changed | fired

    frozen = ~alive_here[:, None]
    new_status = jnp.where(frozen, status, new_status)
    new_inc = jnp.where(frozen, inc, new_inc)
    dl = jnp.where(frozen, deadline, dl)
    new_spread = jnp.where(changed & ~frozen, round_idx + 25, spread)
    return new_status, new_inc, new_spread, dl, new_self


def xla_step(carry, r, is_self, alive_here):
    status, inc, spread, dl, self_inc, inbox, ia = carry
    ns, ni, nsp, ndl, nself = merge_math(
        status, inc, spread, dl, self_inc, inbox, ia, alive_here, r, is_self)
    # Feed outputs back; inbox evolves cheaply so iterations differ.
    return (ns.astype(status.dtype), ni, nsp, ndl, nself,
            inbox ^ (r + 1), ia), None


def kernel(status_ref, inc_ref, spread_ref, dl_ref, self_ref, inbox_ref,
           ia_ref, alive_ref, iota_ref, r_ref,
           status_out, inc_out, spread_out, dl_out, self_out):
    """Arithmetic-select style throughout: this stack's Mosaic helper
    crashes (exit 1, no diagnostics) on the straightforward nested-where
    form of this very computation — each stage compiles alone, the
    composition doesn't — while 0/1-mask arithmetic for the multi-way
    selects compiles.  Correctness is pinned against the XLA reference
    below."""
    r = r_ref[0, 0]
    status = status_ref[...].astype(jnp.int32)
    inc = inc_ref[...]
    spread = spread_ref[...]
    deadline = dl_ref[...]
    self_inc = self_ref[...]                       # [Nb, 1]
    key = inbox_ref[...]
    ia = ia_ref[...].astype(jnp.int32)
    alive_i = alive_ref[...].astype(jnp.int32)     # [Nb, 1] 0/1
    self_m = iota_ref[...].astype(jnp.int32)       # [Nb, K] 0/1

    neg = (key < 0).astype(jnp.int32)
    win_inc = (1 - neg) * ((key >> 1) & ((1 << 29) - 1))
    wd = (key >> 30) & 1
    win_status = wd * DEAD + (1 - wd) * (key & 1)
    win_status = neg * ABSENT + (1 - neg) * win_status
    gate_status = status + (status == DEAD).astype(jnp.int32)  # DEAD->ABSENT
    absent_m = (gate_status == ABSENT).astype(jnp.int32)
    present_ok = (
        (win_inc > inc) | ((win_inc == inc) & (win_status == SUSPECT)
                           & (gate_status == ALIVE))
    ) & (win_status != ABSENT)
    absent_ok = (ia > 0) & (win_status != ABSENT)
    acc = (absent_m * absent_ok.astype(jnp.int32)
           + (1 - absent_m) * present_ok.astype(jnp.int32))
    new_status = acc * win_status + (1 - acc) * status
    new_inc = acc * win_inc + (1 - acc) * inc
    changed = acc * ((new_status != status)
                     | (new_inc != inc)).astype(jnp.int32)

    self_ov = self_m * (win_inc > self_inc).astype(jnp.int32)
    refuted = jnp.max(self_ov, axis=1, keepdims=True)
    bumped = jnp.max(self_ov * win_inc, axis=1, keepdims=True) + 1
    ref_m = refuted * alive_i
    new_self = ref_m * jnp.maximum(self_inc, bumped) + (1 - ref_m) * self_inc
    new_status = (1 - self_m) * new_status + self_m * ALIVE
    new_inc = (1 - self_m) * new_inc + self_m * new_self

    no_timer = (deadline == INT32_MAX).astype(jnp.int32)
    is_susp = (new_status == SUSPECT).astype(jnp.int32)
    start = changed * is_susp * no_timer
    cancel = changed * (1 - is_susp)
    keep = (1 - start) * (1 - cancel)
    dl = start * (r + 30) + cancel * INT32_MAX + keep * deadline
    fired = is_susp * (r >= dl).astype(jnp.int32)
    new_status = fired * DEAD + (1 - fired) * new_status
    dl = fired * INT32_MAX + (1 - fired) * dl
    changed = jnp.maximum(changed, fired)

    new_status = alive_i * new_status + (1 - alive_i) * status
    new_inc = alive_i * new_inc + (1 - alive_i) * inc
    dl = alive_i * dl + (1 - alive_i) * deadline
    ch = changed * alive_i
    new_spread = ch * (r + 25) + (1 - ch) * spread

    status_out[...] = new_status.astype(jnp.int16)
    inc_out[...] = new_inc
    spread_out[...] = new_spread
    dl_out[...] = dl
    self_out[...] = alive_i * new_self + (1 - alive_i) * self_inc


def pallas_step(nb, carry, r, is_self8, alive8):
    status, inc, spread, dl, self_inc, inbox, ia = carry
    grid = N // nb
    row = lambda: pl.BlockSpec((nb, 1), lambda i: (i, 0))
    plane = lambda: pl.BlockSpec((nb, K), lambda i: (i, 0))
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[plane(), plane(), plane(), plane(), row(), plane(),
                  plane(), row(), plane(),
                  pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=[plane(), plane(), plane(), plane(), row()],
        out_shape=[
            jax.ShapeDtypeStruct((N, K), jnp.int16),
            jax.ShapeDtypeStruct((N, K), jnp.int32),
            jax.ShapeDtypeStruct((N, K), jnp.int32),
            jax.ShapeDtypeStruct((N, K), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
        ],
    )(status, inc, spread, dl, self_inc, inbox, ia, alive8, is_self8,
      jnp.full((1, 1), r, jnp.int32))
    ns, ni, nsp, ndl, nself = outs
    return (ns, ni, nsp, ndl, nself, inbox ^ (r + 1), ia), None


def bench(step, carry, label):
    @jax.jit
    def loop(carry):
        return jax.lax.scan(step, carry, jnp.arange(ITERS, dtype=jnp.int32))

    out, _ = loop(carry)
    float(jnp.sum(out[1].astype(jnp.int64)))       # completion barrier
    t0 = time.perf_counter()
    out, _ = loop(carry)
    float(jnp.sum(out[1].astype(jnp.int64)))
    ms = (time.perf_counter() - t0) / ITERS * 1e3
    print(f"[{label}] {ms:.3f} ms/iter", file=sys.stderr)
    return ms, out


def main():
    key = jax.random.key(0)
    ks = jax.random.split(key, 4)
    inbox = jax.random.randint(ks[0], (N, K), -1, 1 << 20, dtype=jnp.int32)
    ia = (jax.random.uniform(ks[1], (N, K)) < 0.5).astype(jnp.int8)
    inc0 = jax.random.randint(ks[2], (N, K), 0, 1 << 10, dtype=jnp.int32)
    spread0 = jnp.zeros((N, K), jnp.int32)
    dl0 = jnp.full((N, K), INT32_MAX, jnp.int32)
    self0 = jnp.zeros((N,), jnp.int32)
    alive = jnp.ones((N,), jnp.bool_)
    is_self = (jnp.arange(K)[None, :] == (jnp.arange(N) % K)[:, None])

    # XLA reference (status int8 like the tick's carry).
    status8 = jnp.zeros((N, K), jnp.int8)
    ms_xla, out_x = bench(
        functools.partial(xla_step, is_self=is_self, alive_here=alive),
        (status8, inc0, spread0, dl0, self0, inbox, ia), "xla-fusion")

    # Pallas (status int16 plane; row vectors as [N,1]; is_self as int8).
    status16 = jnp.zeros((N, K), jnp.int16)
    is_self8 = is_self.astype(jnp.int8)
    alive8 = alive.astype(jnp.int8)[:, None]
    self0c = self0[:, None]
    results = {"xla_ms": round(ms_xla, 3), "pallas": {}}
    for nb in (8192, 32768):
        try:
            step = functools.partial(pallas_step, nb, is_self8=is_self8,
                                     alive8=alive8)
            ms_p, out_p = bench(
                step, (status16, inc0, spread0, dl0, self0c, inbox, ia),
                f"pallas nb={nb}")
            # Value check vs XLA (status compared as int32).
            same = bool(jnp.array_equal(out_x[0].astype(jnp.int32),
                                        out_p[0].astype(jnp.int32))
                        and jnp.array_equal(out_x[1], out_p[1]))
            results["pallas"][str(nb)] = {"ms": round(ms_p, 3),
                                          "matches_xla": same}
        except Exception as e:  # noqa: BLE001 — capability bench
            results["pallas"][str(nb)] = {
                "error": str(e).split("\n")[0][:200]}
    print(json.dumps(results))


if __name__ == "__main__":
    main()
