"""Single-chip FOCAL-mode (K=16) member-count ceiling, by carry layout.

Focal mode is the bench/headline configuration: each node tracks K=16
subjects, so capacity scales with N rather than N² — this is where the
33.5M-member dissemination rung lives (artifacts/dissemination_scale.json).
This experiment brackets the focal ceiling the way
experiments/fullview_ceiling.py brackets the full-view one, with each
(layout, N) attempt in a subprocess so a RESOURCE_EXHAUSTED cannot
poison later attempts (experiments/ladder_util.py):

  - wide layout: the standard 13 B/cell carry + int32 wire;
  - compact: 6 B/cell + int16 wire (trace-identical,
    tests/test_compact_carry.py) — the layout the 33.5M rung uses;
  - compact_roll: compact + ``shift_roll_payloads`` (no persistent
    doubled payload buffers) — probes whether dropping the doubled
    buffers moves the boundary, as it could not for full view.

The artifact records the measured bracket per layout plus an
``anatomy_probe``: one deliberate over-ceiling attempt (67M compact,
retried a few times) that preserves the raw failure text, because the
failure MODE at a given over-ceiling rung is nondeterministic — the
same rung reports a clean RESOURCE_EXHAUSTED with an allocation dump
on one run and an axon compile-helper exit-1 on the next (the helper
itself dying on the too-big program).  The BRACKET (max_fits /
first_fail N) is stable across regenerations; consumers should pin
those, not the oom/helper_crash flags.  When the clean dump surfaces
it shows the [N, 16] per-channel payload/metric temps — the full-view
boundary's anatomy at K=16, where ``k_block`` has nothing to tile; and
roll payloads fail at every probed rung >= 46.1M, so dropping the
doubled buffers cannot be tested past the compact ceiling.

Writes ``artifacts/focal_ceiling.json``; pinned by
tests/test_results_claims.py.  Run: ``python
experiments/focal_ceiling.py`` (TPU, ~30 min), or ``... anatomy`` to
refresh only the anatomy probe in the existing artifact.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from experiments.ladder_util import bracket, salvage_run  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUNDS = 50
K = 16

# Layouts suffixed ``_ps`` run with per-subject metrics on — the
# bench/dissemination configuration.  Measured: the metric mode does
# NOT move the boundary (wide_ps fits 33.5M like wide; the bench's
# 33.5M wide OOM comes from its TWO-program pipeline — throughput
# window plus the separate dissemination program — holding buffers
# concurrently, which is why the dissemination rung runs compact).
LADDERS = {
    "wide": [16_777_216, 25_165_824, 33_554_432, 41_943_040],
    "wide_ps": [25_165_824, 33_554_432],
    "compact": [33_554_432, 41_943_040, 46_137_344, 50_331_648],
    "compact_ps": [33_554_432, 41_943_040],
    "compact_roll": [46_137_344, 50_331_648, 67_108_864],
}
CONSECUTIVE_FAILURES_TO_STOP = 2
ANATOMY_N = 67_108_864          # deliberate over-ceiling probe (compact)
ANATOMY_RETRIES = 3             # until a clean RESOURCE_EXHAUSTED dump

_CHILD = r"""
import json, sys, time
sys.path.insert(0, %(repo)r)
import jax
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.utils.runlog import enable_compilation_cache

enable_compilation_cache()
n, compact, roll, rounds = %(n)d, %(compact)r, %(roll)r, %(rounds)d
per_subject = %(per_subject)r
try:
    params = swim.SwimParams.from_config(
        ClusterConfig.default_lan(), n_members=n, n_subjects=%(k)d,
        delivery="shift", compact_carry=compact,
        shift_roll_payloads=roll, loss_probability=0.02,
        per_subject_metrics=per_subject,
    )
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=5)
    step = jax.jit(
        lambda k_, w, s, r0: swim.run(k_, params, w, rounds, state=s,
                                      start_round=r0),
        donate_argnums=(2,))
    key = jax.random.key(0)

    from scalecube_cluster_tpu.utils import runlog

    def force(s):
        # Scalar-fetch completion barrier: on the tunnelled axon link,
        # block_until_ready returns before device completion and the
        # window timing lies (utils/runlog.completion_barrier docstring).
        return runlog.completion_barrier(s.status)

    state = swim.initial_state(params, world)
    t0 = time.perf_counter()
    state, _ = step(key, world, state, 0)
    force(state)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    state, m = step(key, world, state, rounds)
    force(state)
    elapsed = time.perf_counter() - t0
    print(json.dumps({
        "fits": True,
        "ms_per_round": round(elapsed / rounds * 1e3, 2),
        "member_rounds_per_sec": round(n * rounds / elapsed, 1),
        "compile_plus_first_window_s": round(compile_s, 1),
    }))
except Exception as e:  # noqa: BLE001 — boundary classification by message
    msg = str(e)
    # Case-insensitive substring, not the exact phrase "compile_helper
    # subprocess exit code": the helper's message wording has already
    # drifted across toolchain builds, and a missed match silently
    # reclassified helper deaths as generic (non-oom) failures.  The
    # raw message is ALWAYS recorded alongside the flags, so even a
    # misclassification stays diagnosable from the artifact.
    helper = "compile_helper" in msg.lower()
    oom = not helper and ("RESOURCE_EXHAUSTED" in msg
                          or "Ran out of memory" in msg)
    print(json.dumps({"fits": False, "oom": oom, "helper_crash": helper,
                      "error": f"{type(e).__name__}: {msg[:%(err_chars)d]}"}))
"""

_FALLBACK = {"fits": False, "oom": False, "helper_crash": False}


def attempt(n, layout, err_chars=300):
    code = _CHILD % {"repo": REPO, "n": n, "k": K,
                     "compact": layout.startswith("compact"),
                     "roll": "_roll" in layout,
                     "per_subject": layout.endswith("_ps"),
                     "rounds": ROUNDS,
                     "err_chars": err_chars}
    return salvage_run(code, cwd=REPO, fallback=dict(_FALLBACK))


def run_anatomy_probe():
    """One over-ceiling attempt preserving the raw failure text.

    Retries until the failure surfaces as a clean RESOURCE_EXHAUSTED
    (whose text carries the allocation dump's "Used X of Y hbm" line)
    or retries run out — the helper-crash mode carries no diagnostics.
    """
    last = None
    for i in range(ANATOMY_RETRIES):
        r = attempt(ANATOMY_N, "compact", err_chars=4000)
        r.update(n_members=ANATOMY_N, layout="compact", try_idx=i)
        print(f"[focal:anatomy] try {i}: fits={r['fits']} "
              f"oom={r.get('oom')} helper={r.get('helper_crash')}",
              file=sys.stderr, flush=True)
        last = r
        if r.get("oom"):
            break
    return last


def main(anatomy_only=False):
    path = os.path.join(REPO, "artifacts", "focal_ceiling.json")
    if anatomy_only:
        with open(path) as f:
            out = json.load(f)
        out["anatomy_probe"] = run_anatomy_probe()
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        print(f"updated anatomy_probe in {path}", file=sys.stderr)
        return

    results = {}
    for layout, ladder in LADDERS.items():
        rows, consecutive_failures = [], 0
        for n in ladder:
            t0 = time.perf_counter()
            r = attempt(n, layout)
            r.update(n_members=n,
                     attempt_wall_s=round(time.perf_counter() - t0, 1))
            rows.append(r)
            print(f"[focal:{layout}] N={n}: fits={r['fits']} "
                  f"{r.get('ms_per_round', r.get('error', ''))}",
                  file=sys.stderr, flush=True)
            consecutive_failures = 0 if r["fits"] else \
                consecutive_failures + 1
            if consecutive_failures >= CONSECUTIVE_FAILURES_TO_STOP:
                break
        max_fits, first_fail = bracket(rows)
        results[layout] = {
            "rows": rows,
            "max_fits": max_fits,
            "first_fail_above_max_fits": first_fail,
        }
    out = {
        "mode": f"focal shift, K={K}, {ROUNDS}-round windows, "
                "crash at round 5",
        "layouts": results,
        "anatomy_probe": run_anatomy_probe(),
    }
    os.makedirs(os.path.join(REPO, "artifacts"), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    print(f"wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    main(anatomy_only=len(sys.argv) > 1 and sys.argv[1] == "anatomy")
