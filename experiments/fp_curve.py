"""The first-false-positive curve, measured vs the closed-form probe model.

BASELINE.md's north star asks for the SWIM paper's first-false-positive
curve "within 5%"; the reference's own methodology is measure-then-compare
-against-ClusterMath (GossipProtocolTest.java:178-205).  ClusterMath has
no FD formula, but the tick's probe collapse (models/swim._chain_ok) IS a
closed form — swim_math.fd_false_suspect_probability — so the curve can
be validated quantitatively: measured false-suspicion ONSET counts on the
FD-only configuration (models/fd.py; BASELINE config 3's shape: 10k
members under symmetric loss) across a loss x ping_req_members grid,
against swim_math.fd_expected_false_onsets.

Each cell runs enough fd rounds that the expected event count E >= 5000,
putting the 2-sigma Poisson noise of the measurement itself at <= 2.9% —
small enough that a 5% relative band tests the model, not the seed.

Run: ``python experiments/fp_curve.py`` (TPU, ~10 min).  Writes
``artifacts/fp_curve.json``; tests/test_results_claims.py pins the RESULTS
prose to it, and tests/test_scaling_curves.py asserts the same law at CPU
scale on every CI run.
"""

import dataclasses
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from scalecube_cluster_tpu import swim_math
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import fd as fdmodel
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.utils import get_logger
from scalecube_cluster_tpu.utils.runlog import enable_compilation_cache

N = 10_000
LOSSES = [0.02, 0.05, 0.10, 0.25]
PING_REQS = [0, 1, 3]
CHUNK = 1_000           # fixed scan length -> one compile per ping_req
TARGET_E = 5_000.0      # expected events per cell (2-sigma <= 2.9%)

log = get_logger("fp_curve")
enable_compilation_cache(log)


def run_cell(params, world, knobs, n_chunks, key):
    state = swim.initial_state(params, world)
    onsets = 0
    for c in range(n_chunks):
        state, m = swim.run(key, params, world, CHUNK, state=state,
                            start_round=c * CHUNK, knobs=knobs)
        onsets += int(np.asarray(m["false_suspicion_onsets"]).sum())
    return onsets


def main():
    cells = []
    t_all = time.perf_counter()
    for pr in PING_REQS:
        params = swim.SwimParams.from_config(
            ClusterConfig.default(), n_members=N, ping_req_members=pr,
            delivery="shift", per_subject_metrics=False,
        )
        world = swim.SwimWorld.healthy(params)
        for loss in LOSSES:
            p_fs = swim_math.fd_false_suspect_probability(loss, pr, N)
            n_chunks = max(1, math.ceil(TARGET_E / (N * p_fs) / CHUNK))
            rounds = n_chunks * CHUNK
            knobs = dataclasses.replace(
                fdmodel.fd_only_knobs(params),
                loss_probability=jnp.float32(loss),
                ping_every=jnp.int32(1),
                suspicion_rounds=jnp.int32(1_000_000),
            )
            t0 = time.perf_counter()
            measured = run_cell(params, world, knobs, n_chunks,
                                jax.random.key(hash((pr, loss)) % 2**31))
            expected = swim_math.fd_expected_false_onsets(loss, pr, N, rounds)
            rel_err = measured / expected - 1.0
            two_sigma = 2.0 / math.sqrt(expected)
            cells.append({
                "loss": loss,
                "ping_req_members": pr,
                "fd_rounds": rounds,
                "measured_onsets": measured,
                "expected_onsets": round(expected, 1),
                "p_false_suspect_per_probe": p_fs,
                "rel_err": round(rel_err, 4),
                "poisson_two_sigma": round(two_sigma, 4),
                "within_5pct": bool(abs(rel_err) <= 0.05),
                "wall_seconds": round(time.perf_counter() - t0, 1),
            })
            log.info("loss=%.2f pr=%d F=%d: measured %d vs expected %.0f "
                     "(rel err %+.2f%%, 2sigma %.2f%%)",
                     loss, pr, rounds, measured, expected, 100 * rel_err,
                     100 * two_sigma)

    worst = max(abs(c["rel_err"]) for c in cells)
    result = {
        "n_members": N,
        "mode": "FD-only (models/fd.py), warm full view, every round an "
                "fd round, suspicion horizon > run",
        "grid": "loss x ping_req_members",
        "model": "swim_math.fd_false_suspect_probability / "
                 "fd_expected_false_onsets",
        "cells": cells,
        "worst_abs_rel_err": round(worst, 4),
        "all_within_5pct": all(c["within_5pct"] for c in cells),
        "wall_seconds_total": round(time.perf_counter() - t_all, 1),
    }
    os.makedirs("artifacts", exist_ok=True)
    with open("artifacts/fp_curve.json", "w") as f:
        json.dump(result, f, indent=1)
    log.info("worst |rel err| %.2f%%; all within 5%%: %s",
             100 * worst, result["all_within_5pct"])


if __name__ == "__main__":
    main()
