"""Pin the O(log n) dissemination law to 33.5M members on one chip.

BASELINE.md's north star reproduces SWIM's O(log n) dissemination; round
4 fitted it to N=16,384 and stated the 16,777,216-member headroom run in
prose only.  This experiment makes both an artifact:

  - leave-dissemination rounds (one graceful leave, rounds until every
    live observer dropped the leaver — pure infection spread, no
    suspicion wait; bench.py's dissemination_at_scale) measured at
    N = 16k .. 33.5M (the 33,554,432 rung uses the compact carry —
    trace-identical, tests/test_compact_carry.py — because the wide
    focal carry RESOURCE_EXHAUSTs at that N);
  - a linear fit rounds = a + b*log2(N): fanout-3 gossip grows the
    infected set ~(1+fanout)x per round, so b ~= 1/log2(4) = 0.5;
  - throughput pins at 16.7M (wide) and 33.5M (compact)
    (member-rounds/sec over a 100-round window, fresh subprocesses).

Writes ``artifacts/dissemination_scale.json``; pinned by
tests/test_results_claims.py.  Run: ``python
experiments/dissemination_scale.py`` (TPU, ~10 min).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LADDER = [16_384, 65_536, 262_144, 1_048_576, 4_194_304, 16_777_216,
          33_554_432]
# Above this N the wide focal carry RESOURCE_EXHAUSTs; the rung runs on
# the trace-identical compact layout instead.
COMPACT_ABOVE = 16_777_216
N_SUBJECTS = 16
THROUGHPUT_PINS = [(16_777_216, False), (33_554_432, True)]
THROUGHPUT_ROUNDS = 100


def throughput_pin(n, compact):
    """The documented bench command at N, in a FRESH subprocess.

    Fresh for two reasons: an in-process pin after the ladder measured
    ~20% low (residue from prior compiled programs skews the window),
    and the 33.5M rung needs the whole chip — it RESOURCE_EXHAUSTs if
    the parent still holds the ladder's buffers.  main() therefore runs
    the pins BEFORE the parent touches the device.
    """
    import subprocess
    env = dict(os.environ,
               SCALECUBE_BENCH_N=str(n),
               SCALECUBE_BENCH_ROUNDS=str(THROUGHPUT_ROUNDS),
               SCALECUBE_BENCH_SKIP_CANARY="1",
               **({"SCALECUBE_BENCH_COMPACT": "1"} if compact else {}))
    rate, crash_noticed, tput_error = None, None, None
    try:
        bench = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=1200, env=env,
            cwd=REPO,
        )
        lines = bench.stdout.strip().splitlines()
        if bench.returncode != 0 or not lines:
            tput_error = (f"bench rc={bench.returncode}; stderr tail: "
                          f"{(bench.stderr or '')[-300:]}")
        else:
            bench_json = json.loads(lines[-1])
            rate = bench_json["value"]
            # bench returns dissemination_rounds=-1 (no error key)
            # when the leave was never noticed — require a positive
            # count.
            crash_noticed = (
                "error" not in bench_json
                and bench_json.get("dissemination_rounds", -1) > 0
            )
            tput_error = bench_json.get("error")
    except Exception as e:  # noqa: BLE001 — record, keep the artifact
        tput_error = f"{type(e).__name__}: {e}"
    print(f"[tput] {rate and f'{rate:.3e}'} member-rounds/s @ {n} "
          f"compact={compact} (error={tput_error})", file=sys.stderr)
    return {
        "n_members": n,
        "rounds_timed": THROUGHPUT_ROUNDS,
        "compact_carry": compact,
        "member_rounds_per_sec": rate and round(rate, 1),
        "crash_noticed": crash_noticed,
        **({"error": tput_error} if tput_error else {}),
    }


def main():
    # Pins first: the parent must not have touched the chip yet (see
    # throughput_pin docstring).
    pins = [throughput_pin(n, compact) for n, compact in THROUGHPUT_PINS]

    import jax
    import numpy as np

    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.utils import runlog

    runlog.enable_compilation_cache()

    # Round fusion: bit-identical scan outputs, less per-step dispatch
    # (SwimParams.rounds_per_step) — 4 on device, 1 on the CPU fallback
    # where unrolling measured slower (bench.resolve_rounds_per_step).
    rounds_per_step = 1 if jax.default_backend() == "cpu" else 4

    def dissemination_rounds(n, seed=1):
        params = swim.SwimParams.from_config(
            ClusterConfig.default(), n_members=n, n_subjects=N_SUBJECTS,
            delivery="shift", compact_carry=n > COMPACT_ABOVE,
            rounds_per_step=rounds_per_step,
        )
        world = swim.SwimWorld.healthy(params).with_leave(3, at_round=10)
        _, m = swim.run(jax.random.key(seed), params, world, 60)
        alive_view = np.asarray(m["alive"])[:, 3]
        gone = np.flatnonzero(alive_view == 0)
        return int(gone[0]) - 10 if gone.size else -1

    rows = []
    for n in LADDER:
        t0 = time.perf_counter()
        # Median of 3 seeds: the quantity is integer-round-valued and
        # seed spread is ±1 round.
        vals = [dissemination_rounds(n, seed) for seed in (1, 2, 3)]
        rows.append({
            "n_members": n,
            "dissemination_rounds": sorted(vals)[1],
            "seed_values": vals,
            "compact_carry": n > COMPACT_ABOVE,
            "wall_s": round(time.perf_counter() - t0, 1),
        })
        print(f"[diss] N={n}: {rows[-1]}", file=sys.stderr, flush=True)

    x = np.log2([r["n_members"] for r in rows])
    y = np.asarray([r["dissemination_rounds"] for r in rows], dtype=float)
    b, a = np.polyfit(x, y, 1)
    resid = y - (a + b * x)

    out = {
        "mode": "focal shift, K=16, graceful-leave dissemination",
        "rows": rows,
        "fit": {
            "model": "rounds = a + b*log2(N)",
            "a": round(float(a), 3),
            "b": round(float(b), 4),
            "b_ideal_log4": 0.5,
            "max_abs_residual_rounds": round(float(np.abs(resid).max()), 3),
        },
        "throughput_16m": pins[0],
        "throughput_33m": pins[1],
    }
    os.makedirs(os.path.join(REPO, "artifacts"), exist_ok=True)
    path = os.path.join(REPO, "artifacts", "dissemination_scale.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    print(f"wrote {path}", file=sys.stderr)

    # Telemetry manifest: the ladder as counter rows + the fit summary
    # (telemetry/sink.py; dir from SCALECUBE_TPU_TELEMETRY_DIR, default
    # artifacts/telemetry).
    from scalecube_cluster_tpu.telemetry import sink as telemetry_sink

    sink = telemetry_sink.TelemetrySink.from_env(
        default_dir=os.path.join(REPO, "artifacts", "telemetry"),
        prefix="dissemination-scale",
    )
    if sink is not None:
        sink.write_manifest(
            params={"mode": out["mode"], "ladder": LADDER,
                    "n_subjects": N_SUBJECTS},
        )
        sink.write_curve(
            "dissemination_rounds_vs_log2n",
            [r["dissemination_rounds"] for r in rows],
            ladder=[r["n_members"] for r in rows],
            seed_values=[r["seed_values"] for r in rows],
        )
        sink.write_summary(fit=out["fit"],
                           throughput_16m=pins[0], throughput_33m=pins[1])
        sink.close()
        print(f"telemetry manifest at {sink.path}", file=sys.stderr)


if __name__ == "__main__":
    main()
