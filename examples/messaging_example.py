"""Point-to-point messaging between cluster members.

Mirror of the reference's MessagingExample
(examples/src/main/java/io/scalecube/examples/MessagingExample.java:15-48):
Alice and Bob join one cluster, listen to their inboxes, and exchange
greetings — fire-and-forget ``send`` plus a correlated request/response.

Run: ``python examples/messaging_example.py``
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scalecube_cluster_tpu.oracle import Cluster, Message, Simulator


def main():
    sim = Simulator(seed=7)
    alice = Cluster.join(sim, alias="alice")
    bob = Cluster.join(sim, seeds=[alice.address], alias="bob")
    sim.run_for(2_000)

    inbox = []

    # Alice prints every incoming message and answers greetings.
    def on_alice_message(msg: Message):
        inbox.append(("alice", msg.data))
        if msg.correlation_id is not None:
            alice.send(
                msg.sender,
                Message(qualifier="greeting/ack", data="hi Bob!",
                        correlation_id=msg.correlation_id),
            )

    alice.listen(on_alice_message)
    bob.listen(lambda msg: inbox.append(("bob", msg.data)))

    # Fire-and-forget: Bob -> Alice.
    bob.send(alice.address, Message(qualifier="greeting", data="hello Alice!"))

    # Request/response: Bob asks, Alice's reply resolves the future.
    reply = bob.request_response(
        alice.address,
        Message(qualifier="greeting", data="are you there?",
                correlation_id="rr-1"),
    )
    sim.run_for(1_000)

    print("inbox:", inbox)
    print("reply:", reply.value.data)
    assert ("alice", "hello Alice!") in inbox
    assert reply.value.data == "hi Bob!"


if __name__ == "__main__":
    main()
