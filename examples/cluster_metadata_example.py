"""Metadata: join with KV metadata, discover it, and watch updates.

Mirror of the reference's ClusterMetadataExample
(examples/src/main/java/io/scalecube/examples/ClusterMetadataExample.java:21-57):
Joe joins with metadata, Carol discovers it; Joe then updates a property
and the change propagates via the incarnation-bump gossip + remote fetch
(metadata itself is pulled, not gossiped — MetadataStoreImpl.java:149-186).

Run: ``python examples/cluster_metadata_example.py``
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scalecube_cluster_tpu.oracle import Cluster, Simulator


def main():
    sim = Simulator(seed=31)
    carol = Cluster.join(sim, alias="carol")
    joe = Cluster.join(
        sim, seeds=[carol.address],
        metadata={"name": "Joe", "role": "worker"}, alias="joe",
    )
    sim.run_for(3_000)

    print("carol's view of joe:", carol.metadata(joe.member()))
    assert carol.metadata(joe.member()) == {"name": "Joe", "role": "worker"}

    # Joe updates one property; the incarnation bump gossips and Carol
    # re-fetches the metadata from Joe directly.
    joe.update_metadata_property("role", "coordinator")
    sim.run_for(5_000)

    print("after update:        ", carol.metadata(joe.member()))
    assert carol.metadata(joe.member())["role"] == "coordinator"


if __name__ == "__main__":
    main()
