"""Membership events: ADDED on join, REMOVED on graceful leave and crash.

Mirror of the reference's MembershipEventsExample
(examples/src/main/java/io/scalecube/examples/MembershipEventsExample.java:21-53):
Alice watches the cluster; Bob joins (ADDED), later leaves gracefully
(REMOVED via his self-announced DEAD record, no suspicion delay), and
Carol crashes hard (REMOVED only after suspicion timeout).

Run: ``python examples/membership_events_example.py``
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scalecube_cluster_tpu.oracle import Cluster, Simulator


def main():
    sim = Simulator(seed=23)
    alice = Cluster.join(sim, alias="alice")

    events = []
    alice.listen_membership(
        lambda e: events.append((sim.now, e.type.name, e.member.id))
    )

    bob = Cluster.join(sim, seeds=[alice.address], alias="bob")
    carol = Cluster.join(sim, seeds=[alice.address], alias="carol")
    sim.run_for(3_000)

    bob.shutdown()          # graceful leave: DEAD@inc+1 gossip, fast REMOVED
    t_leave = sim.now
    sim.run_for(3_000)
    leave_events = [e for e in events if e[1] == "REMOVED"]

    carol.transport.stop()  # hard crash: suspicion timeout must elapse
    t_crash = sim.now
    sim.run_for(30_000)

    for t, kind, who in events:
        print(f"t={t:>8.0f}ms  {kind:<7} {who}")

    assert [e[2] for e in events if e[1] == "ADDED"] == ["bob", "carol"]
    removed = [e for e in events if e[1] == "REMOVED"]
    assert [e[2] for e in removed] == ["bob", "carol"]
    # Graceful leave disseminates fast; the crash pays the suspicion timeout.
    leave_latency = leave_events[0][0] - t_leave
    crash_latency = removed[1][0] - t_crash
    print(f"leave latency {leave_latency:.0f}ms vs crash latency "
          f"{crash_latency:.0f}ms")
    assert leave_latency < crash_latency


if __name__ == "__main__":
    main()
