"""Metadata at TPU scale: a 1M-member update propagating by incarnation.

The reference's ClusterMetadataExample (examples/src/main/java/io/
scalecube/examples/ClusterMetadataExample.java:21-57) at the north-star
scale: metadata content lives host-side keyed by (id, incarnation)
(utils/metadata.py — the reference's pull-on-bump protocol,
MetadataStoreImpl.java:106-186), while the tick disseminates the bump
through the normal membership machinery among 1,000,000 members.

Run: ``python examples/metadata_at_scale.py`` (TPU or CPU, ~1 min).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.utils import metadata as md


def main():
    n = 1_000_000 if jax.default_backend() != "cpu" else 65_536
    params = swim.SwimParams.from_config(
        ClusterConfig.default(), n_members=n, n_subjects=16,
        delivery="shift",
    )
    world = swim.SwimWorld.healthy(params)
    store = md.TickMetadataStore()
    for s in np.asarray(world.subject_ids):
        store.put(int(s), 0, {"endpoint": f"tcp://node-{int(s)}:4801",
                              "version": 0})

    key = jax.random.key(0)
    t0 = time.perf_counter()
    state, _ = swim.run(key, params, world, 50)

    # The owner updates its metadata: incarnation bump + re-announce.
    subject = 3
    state = store.update(
        state, params, world, subject,
        {"endpoint": f"tcp://node-{subject}:4801", "version": 1},
        current_round=50,
    )
    new_inc = int(np.asarray(state.self_inc)[subject])

    # Chunked resume (the checkpoint seam): watch the bump's dissemination
    # curve — the fraction of observers whose table reached the new
    # incarnation is exactly the fraction whose next fetch returns v1.
    slot = int(np.asarray(world.slot_of_node)[subject])
    curve = []
    r = 50
    for chunk in (2, 2, 4, 8, 16):
        state, _ = swim.run(key, params, world, chunk, state=state,
                            start_round=r)
        r += chunk
        frac = float(np.asarray(
            (state.inc[:, slot] >= new_inc).mean(), dtype=np.float64))
        curve.append((r, round(frac, 4)))
    wall = time.perf_counter() - t0

    print(f"N={n}: update at round 50 (incarnation {new_inc})")
    for rounds, frac in curve:
        print(f"  round {rounds}: {frac:.2%} of members see the bump")
    v_new = store.view(state, params, world, n - 1, subject, round_idx=r)
    print(f"observer {n - 1} fetches: {v_new}")
    assert v_new["version"] == 1
    assert curve[-1][1] == 1.0, curve
    # An observer that saw only incarnation 0 would still fetch v0.
    assert store.resolve(subject, 0) == {
        "endpoint": f"tcp://node-{subject}:4801", "version": 0}
    print(f"OK ({wall:.1f}s)")


if __name__ == "__main__":
    main()
