"""Joining clusters: seeds, metadata at join, and sync-group isolation.

Mirror of the reference's ClusterJoinExamples
(examples/src/main/java/io/scalecube/examples/ClusterJoinExamples.java:21-76):
Alice starts alone, Bob joins via her address, Carol joins with metadata,
and Dan — configured with a different sync group — stays invisible to the
others even though he contacts the same seed.

Run: ``python examples/cluster_join_example.py``
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.oracle import Cluster, Simulator


def main():
    sim = Simulator(seed=42)

    # Start cluster node Alice as a seed node.
    alice = Cluster.join(sim, alias="alice")

    # Join cluster node Bob to the cluster via Alice's address.
    bob = Cluster.join(sim, seeds=[alice.address], alias="bob")

    # Join cluster node Carol with some metadata.
    carol = Cluster.join(
        sim, seeds=[alice.address],
        metadata={"name": "Carol"}, alias="carol",
    )

    # Dan is configured with a different sync group: same seed address, but
    # his SYNC messages are filtered out, so the clusters stay isolated
    # (MembershipProtocolImpl.java:431-437).
    other_group = ClusterConfig.default_local().replace(sync_group="group-B")
    dan = Cluster.join(sim, seeds=[alice.address], config=other_group,
                       alias="dan")

    sim.run_for(5_000)  # let SYNC + gossip converge (virtual ms)

    print("alice sees :", sorted(str(m) for m in alice.other_members()))
    print("bob sees   :", sorted(str(m) for m in bob.other_members()))
    print("carol meta :", bob.metadata(carol.member()))
    print("dan sees   :", sorted(str(m) for m in dan.other_members()))
    assert len(alice.other_members()) == 2      # bob + carol, not dan
    assert dan.other_members() == []            # isolated by sync group


if __name__ == "__main__":
    main()
