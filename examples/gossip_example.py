"""Infection-style gossip: one spread reaches every member exactly once.

Mirror of the reference's GossipExample
(examples/src/main/java/io/scalecube/examples/GossipExample.java:15-37):
a handful of members join, everyone listens for gossips, Alice spreads one
message, and the spread future resolves once the gossip has been
retransmitted for its full spread period and swept.

Run: ``python examples/gossip_example.py``
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scalecube_cluster_tpu.oracle import Cluster, Message, Simulator


def main():
    sim = Simulator(seed=11)
    alice = Cluster.join(sim, alias="alice")
    members = [alice] + [
        Cluster.join(sim, seeds=[alice.address], alias=name)
        for name in ("bob", "carol", "dan", "eve")
    ]
    sim.run_for(3_000)

    received = []
    for m in members:
        m.listen_gossips(
            lambda msg, who=m: received.append((who.member().id, msg.data))
        )

    done = alice.spread_gossip(
        Message(qualifier="news", data="Joe Joe Joe has arrived!")
    )
    sim.run_for(10_000)  # > gossip sweep timeout

    print("received:", sorted(received))
    print("spread future done:", done.done)
    # Everyone but the spreader hears it exactly once (delivery dedups by
    # gossip id, GossipProtocolImpl.java:176-180).
    assert sorted(w for w, _ in received) == ["bob", "carol", "dan", "eve"]
    assert done.done


if __name__ == "__main__":
    main()
