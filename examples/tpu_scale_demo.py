"""TPU-scale demo: one million SWIM members, crash detection end to end.

This is the scenario the reference cannot run (its largest exercised
cluster is 50 members, SURVEY.md §6): 1M members in focal mode on one TPU
chip, shift-delivery fast path, with a mid-run crash — printing the
detection/dissemination timeline and the measured throughput.

Run: ``python examples/tpu_scale_demo.py`` (TPU; falls back to CPU with a
smaller N if no accelerator is available).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.utils import runlog


def main():
    on_accel = jax.default_backend() != "cpu"
    n = 1_000_000 if on_accel else 16_384
    rounds = 1_500
    crash_round = 100

    params = swim.SwimParams.from_config(
        ClusterConfig.default(),
        n_members=n,
        n_subjects=16,
        loss_probability=0.02,
        delivery="shift",
    )
    world = swim.SwimWorld.healthy(params).with_crash(0, at_round=crash_round)
    print(f"{n:,} members on {jax.default_backend()}, "
          f"suspicion timeout = {params.suspicion_rounds} rounds")

    t0 = time.perf_counter()
    _, metrics = swim.run(jax.random.key(0), params, world, rounds)
    # Scalar-fetch barrier: block_until_ready can return before execution
    # finishes on the axon TPU platform (utils/runlog.completion_barrier).
    runlog.completion_barrier(metrics["alive"])
    elapsed = time.perf_counter() - t0

    suspects = np.asarray(metrics["suspect"])[:, 0]
    deads = np.asarray(metrics["dead"])[:, 0]
    alive_view = np.asarray(metrics["alive"])[:, 0]

    def first(cond, default=-1):
        idx = np.flatnonzero(cond)
        return int(idx[0]) if idx.size else default

    onset = first(suspects > 0)
    declared = first(deads > 0)
    gone = first((alive_view == 0) & (suspects == 0) & (deads > 0))
    print(f"crash at round {crash_round}")
    print(f"  first SUSPECT verdict : round {onset}")
    print(f"  first DEAD declaration: round {declared} "
          f"(timeout {params.suspicion_rounds} rounds after suspicion)")
    print(f"  death known cluster-wide: round {gone}")
    print(f"{rounds} rounds (incl. compile) in {elapsed:.1f}s -> "
          f"{n * rounds / elapsed:.2e} member-rounds/sec")


if __name__ == "__main__":
    main()
