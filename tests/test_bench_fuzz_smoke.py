"""bench.py --fuzz --smoke: the vmapped mega-campaign JSON contract.

The smoke-pin pattern of tests/test_bench_sync_smoke.py: the bench is
the one entry point the fuzz measurement flows through, so this tier-1
test runs the real script in a subprocess (CPU) and pins the published
contract — one JSON line with the interleaved sequential-vs-vmapped
throughput fields, the bucket accounting (sizes sum to the scenario
count — no silent drops), the weakened-build coverage arm (planted
violations FOUND, healthy arm clean), an artifacts/fuzz_campaign.json-
style artifact the query layer loads as a real payload, and the regress
gate walking the dedicated fuzz checks.  The full thousand-seed
campaign runs under @slow.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.chaos, pytest.mark.fuzz]

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_fuzz_bench(tmp_path, extra_args=(), extra_env=None, timeout=540):
    artifact = tmp_path / "fuzz_campaign_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_FUZZ_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--fuzz", *extra_args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    return json.loads(lines[0]), artifact


def test_bench_fuzz_smoke_contract(tmp_path):
    result, artifact = _run_fuzz_bench(tmp_path, extra_args=("--smoke",))

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == "fuzz_campaign"
    # value stays None BY DESIGN (scenarios/sec is host-dependent and
    # the coverage gates are absolute); the payload says so.
    assert result["value"] is None
    assert "value_note" in result

    # The scenario batch + bucket accounting: sizes sum to the scenario
    # count — bucketing never silently drops a scenario.
    assert result["scenarios"] == 3 * result["seeds_per_tier"]
    assert sum(b["scenarios"] for b in result["buckets"]) \
        == result["scenarios"]

    # Speed: both arms measured, ratio recorded (a smoke mini batch is
    # mostly singleton buckets, so the >= 1 floor gates full rounds
    # only — telemetry/query.py).
    assert result["scenario_throughput"] > 0
    assert result["scenario_throughput_sequential"] > 0
    assert result["member_rounds_per_sec"] > 0
    assert result["vmap_speedup_ratio"] > 0

    # Quality: the healthy mega-campaign is green; the weakened
    # coverage arm FOUND its planted violations while the healthy arm
    # found none on the same slice.
    assert result["green"] is True
    assert result["green_scenarios"] == result["scenarios"]
    assert all(v == 0 for v in result["violations_by_code"].values())
    cov = result["coverage"]
    assert cov["scenarios"] > 0
    assert cov["weakened_violations"] > 0
    assert cov["weakened_by_code"].get("COMPLETENESS", 0) > 0
    assert cov["healthy_violations"] == 0
    assert "weakened_knobs" in cov["first_repro"]

    # Manifest: bucket rows + per-scenario verdict rows round-trip.
    from scalecube_cluster_tpu.telemetry import sink as tsink

    path = result["manifest"]
    assert os.path.dirname(path) == str(tmp_path)
    kinds = {r["kind"] for r in tsink.read_records(path)}
    assert {"manifest", "chaos_bucket", "chaos_scenario",
            "chaos_verdict"} <= kinds
    rows = tsink.read_records(path, kind="chaos_scenario")
    assert len(rows) == result["scenarios"]
    assert all(r["green"] for r in rows)

    # The artifact loads as a REAL (non-stub) payload and the regress
    # gate ran green with the dedicated fuzz checks.
    from scalecube_cluster_tpu.telemetry import query as tquery

    art = json.loads(artifact.read_text())
    assert art["metric"] == result["metric"]
    payload, skip_note = tquery.load_bench_payload(str(artifact))
    assert skip_note is None
    assert payload["coverage"]["weakened_violations"] > 0

    assert result["regress"]["ok"] is True
    ok, checks = tquery.regress([str(artifact)])
    assert ok
    names = {r["check"] for r in checks}
    assert {"slo/fuzz_campaign_green", "slo/fuzz_coverage_finds_planted",
            "slo/fuzz_coverage_healthy_clean"} <= names
    # The speedup floor reports the smoke round as provenance, not a
    # verdict (singleton buckets can't amortize dispatch).
    speedup_rows = [r for r in checks
                    if r["check"] == "slo/fuzz_vmap_speedup"]
    assert speedup_rows and speedup_rows[0]["ok"] is None


def test_regress_gates_fuzz_quality_absolutely(tmp_path):
    """A fuzz artifact whose coverage arm missed the plant (or whose
    healthy arm tripped, or whose vmapped batch lost to the sequential
    loop on a full round) must fail the gate."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    def art(path, **kw):
        doc = {
            "metric": "fuzz_campaign", "value": None, "smoke": False,
            "green": True, "vmap_speedup_ratio": 2.0,
            "scenario_throughput": 50.0,
            "coverage": {"scenarios": 4, "weakened_violations": 100,
                         "healthy_violations": 0},
        }
        doc.update(kw)
        path.write_text(json.dumps(doc))
        return str(path)

    ok, _ = tquery.regress([art(tmp_path / "fuzz_ok.json")])
    assert ok

    ok, rows = tquery.regress([art(
        tmp_path / "fuzz_missed.json",
        coverage={"scenarios": 4, "weakened_violations": 0,
                  "healthy_violations": 0})])
    assert not ok
    assert any(r["check"] == "slo/fuzz_coverage_finds_planted"
               and r["ok"] is False for r in rows)

    ok, rows = tquery.regress([art(
        tmp_path / "fuzz_dirty.json",
        coverage={"scenarios": 4, "weakened_violations": 100,
                  "healthy_violations": 3})])
    assert not ok
    assert any(r["check"] == "slo/fuzz_coverage_healthy_clean"
               and r["ok"] is False for r in rows)

    ok, rows = tquery.regress([art(tmp_path / "fuzz_red.json",
                                   green=False)])
    assert not ok
    assert any(r["check"] == "slo/fuzz_campaign_green"
               and r["ok"] is False for r in rows)

    ok, rows = tquery.regress([art(tmp_path / "fuzz_slow.json",
                                   vmap_speedup_ratio=0.8)])
    assert not ok
    assert any(r["check"] == "slo/fuzz_vmap_speedup"
               and r["ok"] is False for r in rows)


def test_regress_bands_fuzz_throughput_series(tmp_path):
    """The non-smoke scenario_throughput series is smaller-is-worse
    within the noise band; smoke rounds stay out of the series."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    def art(path, rate, smoke=False):
        path.write_text(json.dumps({
            "metric": "fuzz_campaign", "value": None, "smoke": smoke,
            "green": True, "vmap_speedup_ratio": 2.0,
            "scenario_throughput": rate,
            "coverage": {"scenarios": 4, "weakened_violations": 10,
                         "healthy_violations": 0},
        }))
        return str(path)

    a = art(tmp_path / "fuzz_r01.json", 100.0)
    ok, _ = tquery.regress([a, art(tmp_path / "fuzz_r02.json", 95.0)])
    assert ok                                  # within the band
    ok, rows = tquery.regress([a, art(tmp_path / "fuzz_r03.json", 50.0)])
    assert not ok
    assert any(r["check"] == "slo/fuzz_scenario_throughput"
               and r["ok"] is False for r in rows)
    # A smoke round's host-dependent rate never enters the series.
    ok, _ = tquery.regress([a, art(tmp_path / "fuzz_smoke.json", 1.0,
                                   smoke=True)])
    assert ok


@pytest.mark.slow
def test_bench_fuzz_full_campaign(tmp_path):
    """The full (non-smoke) mega-campaign path.  The design-target
    scale is thousands of seeds per tier on an accelerator; under the
    CPU-forced test environment the same non-smoke code path runs at a
    CPU-feasible seed count (env override drops on real hardware) —
    real buckets, interleaved timing with the >= 1 speedup floor, the
    weakened coverage arm, the regress gate."""
    result, artifact = _run_fuzz_bench(
        tmp_path,
        extra_env={
            "SCALECUBE_FUZZ_N": os.environ.get("SCALECUBE_FUZZ_N", "16"),
            "SCALECUBE_FUZZ_SEEDS_PER_TIER": os.environ.get(
                "SCALECUBE_FUZZ_SEEDS_PER_TIER", "12"),
        },
        timeout=3000,
    )
    assert "error" not in result, result
    assert result["smoke"] is False
    assert result["scenarios"] == 3 * result["seeds_per_tier"]
    assert result["green"] is True
    assert result["vmap_speedup_ratio"] >= 1.0
    assert result["coverage"]["weakened_violations"] > 0
    assert result["coverage"]["healthy_violations"] == 0
    assert result["regress"]["ok"] is True, result["regress"]
