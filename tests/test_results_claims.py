"""RESULTS.md is pinned to its artifacts.

Round 2 and round 3 each shipped prose describing a *previous* generation
of a regenerated artifact (the sweep-cell FP attribution, the leave@2000
event row, the roofline GB/s).  This suite makes that failure mode a red
test: every number RESULTS.md states about a regenerated artifact is
extracted from the prose by regex and compared against the artifact
itself.  Editing one without the other fails here.

Conventions the prose must keep for the regexes to bite:
  - large counts keep their thousands separators (``26,607,890``);
  - rounded values round half-away-from-zero at the stated precision.
"""

import json
import math
import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    with open(os.path.join(REPO, "artifacts", name)) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def results_text():
    with open(os.path.join(REPO, "RESULTS.md")) as f:
        return f.read()


@pytest.fixture(scope="module")
def northstar():
    return _load("northstar_1m_10k.json")


@pytest.fixture(scope="module")
def roofline():
    return _load("roofline.json")


@pytest.fixture(scope="module")
def fullview():
    return _load("fullview_scale.json")


@pytest.fixture(scope="module")
def bench_r03():
    with open(os.path.join(REPO, "BENCH_r03.json")) as f:
        return json.load(f)["parsed"]


@pytest.fixture(scope="module")
def fp_curve():
    return _load("fp_curve.json")


@pytest.fixture(scope="module")
def ceiling():
    return _load("fullview_ceiling.json")


def claim(text, pattern):
    """The unique match of ``pattern`` in RESULTS.md, numbers de-comma'd.

    Returns a tuple of captured groups as floats (int-valued floats for
    counts).  Zero or multiple matches fail the calling test: each claim
    regex must pin exactly one sentence.
    """
    matches = re.findall(pattern, text)
    assert len(matches) == 1, (
        f"claim pattern {pattern!r} matched {len(matches)} times in "
        f"RESULTS.md — it must pin exactly one statement"
    )
    groups = matches[0] if isinstance(matches[0], tuple) else (matches[0],)
    return tuple(float(g.replace(",", "")) for g in groups)


def rounded(value, digits=0):
    """Round half away from zero on the BINARY value (2.698 -> 2.70).

    Note the usual FP caveat: a decimal .5 boundary stored inexactly
    (e.g. 2.695 == 2.69499...) rounds by its binary value, i.e. down.
    Artifact values come from measurements, so exact decimal halfway
    points are measure-zero; if one ever bites, restate the prose digit
    rather than complicating this helper.
    """
    scale = 10 ** digits
    return math.floor(abs(value) * scale + 0.5) / scale * (1 if value >= 0 else -1)


# ---------------------------------------------------------------------------
# Headline bench (BENCH_r03.json — driver-recorded round-3 measurement)
# ---------------------------------------------------------------------------


def test_headline_rate_matches_bench_artifact(results_text, bench_r03):
    (rate,) = claim(results_text,
                    r"\*\*(3\.\d+)e8 member-rounds/sec/chip at N = 1,000,000\*\*")
    assert rate == rounded(bench_r03["value"] / 1e8, 2)
    (vsb,) = claim(results_text, r'"vs_baseline": (\d+),')
    assert vsb == rounded(bench_r03["vs_baseline"])
    (ms,) = claim(results_text, r"(\d\.\d+) ms per full\s+SWIM round")
    assert ms == rounded(
        bench_r03["n_members"] / bench_r03["value"] * 1e3, 2
    )
    (diss,) = claim(results_text, r"`dissemination_rounds: (\d+)` — a graceful")
    assert diss == bench_r03["dissemination_rounds"]


# ---------------------------------------------------------------------------
# Roofline (artifacts/roofline.json)
# ---------------------------------------------------------------------------


def test_roofline_measured_rates(results_text, roofline):
    window, device = claim(
        results_text,
        r"ms/round wall at a (\d+)-round window, (\d\.\d\d) ms on-device",
    )
    assert window == roofline["config"]["rounds"]
    (wall,) = claim(results_text, r"\*\*(\d\.\d\d) ms/round wall at a")
    assert wall == rounded(roofline["measured"]["ms_per_round"], 2)
    assert device == rounded(
        roofline["measured"]["device_while_loop_ms_per_round"], 2
    )


def test_roofline_traffic_and_utilization(results_text, roofline):
    (gb,) = claim(results_text, r"\*\*Modeled HBM traffic (\d\.\d\d) GB/round\*\*")
    assert gb == rounded(roofline["roofline"]["modeled_bytes_per_round"] / 1e9, 2)
    dev_gbps, dev_pct = claim(
        results_text,
        r"\*\*(\d+) GB/s ≈ (\d+)% of the v5e's 819 GB/s\s+HBM peak "
        r"on device time\*\*",
    )
    assert dev_gbps == rounded(
        roofline["roofline"]["achieved_gbps_vs_model_device_time"])
    assert dev_pct == rounded(
        roofline["roofline"]["hbm_utilization_pct_device_time"])
    wall_gbps, wall_pct = claim(
        results_text, r"\((\d+) GB/s ≈ (\d+)% against the"
    )
    assert wall_gbps == rounded(roofline["roofline"]["achieved_gbps_vs_model"])
    assert wall_pct == rounded(roofline["roofline"]["hbm_utilization_pct"])


def test_roofline_top_kernels(results_text, roofline):
    top = roofline["top_kernels_per_round"]
    (merge_ms,) = claim(
        results_text, r"one multi-output fusion\) at (\d\.\d\d) ms/round"
    )
    assert merge_ms == rounded(top[0]["ms_per_round"], 2)
    (metrics_ms,) = claim(
        results_text, r"the metrics\s+reductions \((\d\.\d\d) ms\)"
    )
    assert metrics_ms == rounded(top[1]["ms_per_round"], 2)


# ---------------------------------------------------------------------------
# North-star run (artifacts/northstar_1m_10k.json)
# ---------------------------------------------------------------------------


def test_northstar_wall_and_suspicion(results_text, northstar):
    (wall,) = claim(results_text, r"wall = (\d+) s\b")
    assert wall == rounded(northstar["wall_seconds"])
    assert northstar["suspicion_rounds"] == 500  # the "500-round" claims below


def test_northstar_event_table(results_text, northstar):
    ev = northstar["events"]
    crash = claim(
        results_text,
        r"\| hard crash @500 \| round (\d+) \| round (\d+) \(= exactly the "
        r"(\d+)-round suspicion timeout\) \| round (\d+) \|",
    )
    e = ev["crash@500"]
    assert crash == (e["suspect_onset"], e["dead_declared"],
                     northstar["suspicion_rounds"], e["fully_disseminated"])

    leave = claim(
        results_text,
        r"\| graceful leave @2000 \| round (\d+)† \| round (\d+) "
        r"\(self-announced DEAD@inc\+1\) \| round (\d+) \|",
    )
    e = ev["leave@2000"]
    assert leave == (e["suspect_onset"], e["dead_declared"],
                     e["fully_disseminated"])

    revive = claim(
        results_text,
        r"\| crash @4000, revive @7000 \| round (\d+) \| round (\d+) \| "
        r"round (\d+); \*\*re-accepted everywhere by (\d+)\*\* \|",
    )
    e = ev["crash@4000_revive@7000"]
    assert revive == (e["suspect_onset"], e["dead_declared"],
                      e["fully_disseminated"],
                      northstar["revival_disseminated_round"])
    assert northstar["revived_reaccepted"] is True


def test_northstar_false_positive_split(results_text, northstar):
    (onsets,) = claim(results_text,
                      r"records \*\*(\d+) false-suspicion onsets\*\*")
    assert onsets == northstar["false_suspicion_onsets"]
    stale, observers = claim(
        results_text,
        r"(?s)\*\*([\d,]+) stale-view observer-rounds\*\*.*?"
        r"([\d,]+) observers",
    )
    assert stale == northstar["stale_view_observer_rounds"]
    assert stale == northstar["false_positive_observer_rounds"]
    assert northstar["false_suspect_observer_rounds"] == 0
    # The stated per-observer average window: stale / live observers.
    avg = northstar["stale_view_observer_rounds"] / observers
    (stated_avg,) = claim(results_text, r"(\d+\.\d+) rounds on average")
    assert stated_avg == rounded(avg, 2)


def test_northstar_sweep_cells(results_text, northstar):
    cells = northstar["sweep_1m"]
    assert len(cells) == 8
    clean = [c for c in cells if c["fp_observer_rounds"] == 0
             and c["false_suspicion_onsets"] == 0
             and c["stale_view_observer_rounds"] == 0]
    dirty = [c for c in cells if c not in clean]

    (n_clean_word,) = re.findall(
        r"(\w+)\s+cells record zero false positives of any kind", results_text
    ) or ("",)
    words = {"Six": 6, "Seven": 7, "Eight": 8}
    assert words.get(n_clean_word) == len(clean), (n_clean_word, len(clean))

    # Exactly one dirty cell, and the prose names it with its counts.
    assert len(dirty) == 1
    cell = dirty[0]
    fanout, ping_every, mult = claim(
        results_text,
        r"One cell —\s+\(fanout=(\d+), ping_every=(\d+), mult=(\d+)\)",
    )
    assert (fanout, ping_every, mult) == (
        cell["fanout"], cell["ping_every"], cell["suspicion_mult"]
    )
    episode_words = re.findall(
        r"\*\*(\w+) false-suspicion episodes that disseminated\s+"
        r"cluster-wide\*\*", results_text
    )
    assert len(episode_words) == 1
    # Episode count is not in the artifact directly; each episode is one
    # false SUSPECT record gossiped to ~all 1M observers, so onsets/1M
    # rounds to the episode count.
    n_episodes = {"one": 1, "two": 2, "three": 3, "four": 4}[episode_words[0]]
    assert n_episodes == rounded(cell["false_suspicion_onsets"] / 1e6)
    (onsets,) = claim(results_text, r"([\d,]+) onset observer-events")
    assert onsets == cell["false_suspicion_onsets"]
    (fp_rounds,) = claim(results_text, r"([\d,]+) FP observer-rounds and")
    assert fp_rounds == cell["fp_observer_rounds"]
    assert cell["stale_view_observer_rounds"] == 0
    # Average hold window stated as ~13 rounds.
    (hold,) = claim(results_text, r"held ~(\d+) rounds on average")
    assert hold == rounded(cell["fp_observer_rounds"]
                           / cell["false_suspicion_onsets"])

    # "detection tracks suspicion_mult*ceil(log2 n)*ping_every exactly in
    # all 8 cells" — enforce the formula itself.
    for c in cells:
        assert c["detection_round"] == (
            c["suspicion_mult"] * 20 * c["ping_every"]
        ), c


# ---------------------------------------------------------------------------
# First-false-positive curve (artifacts/fp_curve.json)
# ---------------------------------------------------------------------------


def test_fp_curve_claims(results_text, fp_curve):
    cells = fp_curve["cells"]
    assert len(cells) == 12
    assert fp_curve["all_within_5pct"] is True
    n_cells, worst = claim(
        results_text,
        r"\*\*all (\d+) cells match the closed form within 5%; "
        r"worst \|rel err\|\s+(\d\.\d+)%\*\*",
    )
    assert n_cells == len(cells)
    assert worst == rounded(100 * fp_curve["worst_abs_rel_err"], 2)
    (n_half_pct,) = claim(results_text, r"(\d+) of 12 within 0\.5%")
    assert n_half_pct == sum(abs(c["rel_err"]) <= 0.005 for c in cells)
    # The quoted example cell: loss=2%, 3 proxies.
    cell = next(c for c in cells
                if c["loss"] == 0.02 and c["ping_req_members"] == 3)
    p_probe, rounds_k, meas, exp = claim(
        results_text,
        r"(?s)P = (\d\.\d+e-\d+) per probe.*?(\d+),000 fd rounds × 10k "
        r"probes\s+measured ([\d,]+) onsets vs ([\d,]+) expected",
    )
    assert p_probe == float(f"{cell['p_false_suspect_per_probe']:.2e}")
    assert rounds_k * 1000 == cell["fd_rounds"]
    assert meas == cell["measured_onsets"]
    assert exp == rounded(cell["expected_onsets"])


# ---------------------------------------------------------------------------
# Full-view scale (artifacts/fullview_scale.json)
# ---------------------------------------------------------------------------


def test_fullview_ceiling_row(results_text, fullview):
    # The round-3 BUILD's ceiling is a historical fact (that build fit
    # 16,384 and OOMed at 20,480; the current build's ceiling lives in
    # fullview_ceiling.json).  The committed 32k artifact records it in
    # its legacy single_chip_ceiling dict; a REGENERATED artifact
    # carries a pointer string instead, in which case these constants
    # remain the historical source of truth for the round-3 table rows.
    hist = {"fits": 16_384, "oom": 20_480, "ms_per_round_at_16384_tpu": 45}
    legacy = fullview.get("single_chip_ceiling")
    if isinstance(legacy, dict):
        assert legacy == hist
    fits, ms = claim(
        results_text,
        r"\| ([\d,]+) \| 1 × v5e \| (\d+) \| \*\*6\.0e9\*\* \| "
        r"round-3 single-chip ceiling \|",
    )
    assert fits == hist["fits"]
    assert ms == hist["ms_per_round_at_16384_tpu"]
    (oom,) = claim(results_text, r"\| ([\d,]+) \| 1 × v5e \| — \| — \| "
                                 r"round-3 build: RESOURCE_EXHAUSTED")
    assert oom == hist["oom"]


def test_fullview_ceiling_table(results_text, ceiling):
    def at(layout, n):
        return next(a for a in ceiling["layouts"][layout]["attempts"]
                    if a["n_members"] == n)

    for layout, cells in (("wide", 13), ("compact", 6)):
        lay = ceiling["layouts"][layout]
        fits, fail, ms_max, ms_16k = claim(
            results_text,
            rf"\| {layout} \({cells} B/cell\) \| \*\*([\d,]+)\*\* \| "
            rf"([\d,]+) \| (\d+\.\d) \| (\d+\.\d) \|",
        )
        assert fits == lay["max_fits"]
        assert fail == lay["first_oom"]
        assert ms_max == rounded(at(layout, lay["max_fits"])["ms_per_round"], 1)
        assert ms_16k == rounded(at(layout, 16_384)["ms_per_round"], 1)
        for a in lay["attempts"]:
            if a["fits"]:
                assert a["crash_noticed"], a

    # The round-5 blocked row.
    blk = ceiling["layouts"]["compact_blocked"]
    kb = ceiling["blocked_k_block"]
    fits, fail, ms_max = claim(
        results_text,
        rf"\| compact \+ `k_block={kb}` \| \*\*([\d,]+)\*\* \| "
        rf"([\d,]+) \| (\d+\.\d) \| — \|",
    )
    assert fits == blk["max_fits"]
    assert fail == blk["first_oom"]
    assert ms_max == rounded(at("compact_blocked", blk["max_fits"])
                             ["ms_per_round"], 1)
    for a in blk["attempts"]:
        if a["fits"]:
            assert a["crash_noticed"], a
    # "2.25x the round-4 wide cells" in the index table.
    (cells_x,) = claim(
        results_text,
        r"\*\*27,648 → 36,864\*\* \((\d\.\d\d)× the round-4 wide cells\)",
    )
    assert cells_x == rounded(
        (blk["max_fits"] / ceiling["layouts"]["wide"]["max_fits"]) ** 2, 2)
    assert blk["max_fits"] == 36_864
    assert ceiling["layouts"]["compact"]["max_fits"] == 27_648
    (ratio,) = claim(results_text, r"is\s+\*\*(\d+)×\*\* the largest cluster")
    assert ratio == rounded(blk["max_fits"] / 50)

    # The helper-crash frontier bracket: the prose's probe list must be
    # exactly the artifact's kb_bracketing matrix.
    matrix = {(r["n_members"], r["k_block"]): r["fits"]
              for r in ceiling["kb_bracketing"]}
    assert matrix[(36_864, 1_024)] is True
    expect_fail = [(36_864, 2_048), (37_376, 512), (37_888, 256),
                   (37_888, 512), (37_888, 1_024), (38_912, 512),
                   (38_912, 1_024), (40_960, 512), (40_960, 1_024),
                   (40_960, 2_048)]
    for pair in expect_fail:
        assert matrix[pair] is False, pair
    claim(results_text,
          r"36,864@kb=1024 fits while\s*\n36,864@2048, 37,376@512, "
          r"37,888@\{256,512,1024\}, 38,912@\{512,1024\} and\s*\n"
          r"40,960@\{512,1024,2048\} all exit-(1)")


# ---------------------------------------------------------------------------
# Round-5 artifacts: 1M sweep, user gossip, dissemination law
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def sweep_1m():
    return _load("sweep_1m.json")


@pytest.fixture(scope="module")
def user_gossip_1m():
    return _load("user_gossip_1m.json")


@pytest.fixture(scope="module")
def dissemination_scale():
    return _load("dissemination_scale.json")


@pytest.fixture(scope="module")
def focal_ceiling():
    return _load("focal_ceiling.json")


def test_sweep_1m_claims(results_text, sweep_1m):
    assert sweep_1m["one_program"] is True
    assert sweep_1m["n_members"] == 1_000_000
    cells, rounds_, vmap_s, seq_s = claim(
        results_text,
        r"\*\*(\d+) cells × (\d+) rounds at 1M members in\s+"
        r"(\d+\.\d) s — 2\.9× faster than the sequential loop\*\* "
        r"\((\d+\.\d) s;",
    )
    assert cells == sweep_1m["n_cells"] >= 27
    assert rounds_ == sweep_1m["n_rounds"]
    assert vmap_s == rounded(sweep_1m["wall"]["vmap_s"], 1)
    assert seq_s == rounded(sweep_1m["wall"]["sequential_s"], 1)
    (ratio,) = claim(results_text, r"ratio (0\.\d+)\), *\n?in one program")
    assert ratio == sweep_1m["wall"]["vmap_over_sequential"] <= 2.0
    det_lo, det_hi = claim(results_text,
                           r"\((\d+)\.\.(\d+) rounds across the grid\)")
    det = sweep_1m["curves"]["detection_rounds"]
    assert (det_lo, det_hi) == (min(det), max(det))
    dis_lo, dis_hi = claim(results_text,
                           r"dissemination spans (\d+)\.\.(\d+) rounds")
    dis = sweep_1m["curves"]["dissemination_rounds"]
    assert (dis_lo, dis_hi) == (min(dis), max(dis))
    assert max(dis) <= 2 * sweep_1m["analytic"]["periods_to_spread"]


def test_user_gossip_1m_claims(results_text, user_gossip_1m):
    gossips = user_gossip_1m["gossips"]
    assert len(gossips) == user_gossip_1m["n_user_gossips"] == 4
    (diss,) = claim(
        results_text,
        r"each reaches all 999,999 live members in\s+exactly (\d+) rounds",
    )
    n = user_gossip_1m["n_members"]
    for g in gossips:
        assert g["dissemination_rounds"] == diss
        # >= n-1, not == n-1: the crashed node counts as infected if a
        # gossip reached it before its crash round (seed-dependent), so
        # pinning the exact value would make regeneration flaky.
        assert n - 1 <= g["final_infected"] <= n
    (crash_round,) = claim(
        results_text, r"the crash is known cluster-wide by round (\d+),")
    assert crash_round == user_gossip_1m["crash"]["dead_known_by_all_round"]


def test_dissemination_scale_claims(results_text, dissemination_scale):
    rows = {r["n_members"]: r["dissemination_rounds"]
            for r in dissemination_scale["rows"]}
    r16k, r65k, r262k, r1m, r4m, r16m, r33m = claim(
        results_text,
        r"takes (\d+) rounds at 16k, (\d+) at 65k, (\d+) at 262k, "
        r"(\d+) at 1M, (\d+) at 4\.2M,\s+(\d+) at 16\.7M, and (\d+) "
        r"at 33\.5M",
    )
    assert (r16k, r65k, r262k, r1m, r4m, r16m, r33m) == (
        rows[16_384], rows[65_536], rows[262_144], rows[1_048_576],
        rows[4_194_304], rows[16_777_216], rows[33_554_432],
    )
    fit = dissemination_scale["fit"]
    (b,) = claim(results_text, r"with b = (0\.\d\d) \(ideal fanout-3")
    assert b == rounded(fit["b"], 2)
    (resid,) = claim(results_text, r"max residual (0\.\d\d) rounds")
    assert resid == rounded(fit["max_abs_residual_rounds"], 2)
    tput = dissemination_scale["throughput_16m"]
    (rate,) = claim(
        results_text,
        r"\*\*16,777,216 members on the same\s+single chip sustain "
        r"(\d\.\d+)e8 member-rounds/sec\*\*",
    )
    assert rate == rounded(tput["member_rounds_per_sec"] / 1e8, 2)
    assert tput["crash_noticed"] is True
    tput33 = dissemination_scale["throughput_33m"]
    (rate33,) = claim(
        results_text,
        r"\*\*33,554,432 members — 32×\s+the north-star count — sustain "
        r"(\d\.\d+)e8 member-rounds/sec\*\*",
    )
    assert rate33 == rounded(tput33["member_rounds_per_sec"] / 1e8, 2)
    assert tput33["crash_noticed"] is True
    assert tput33["compact_carry"] is True
    # The 33.5M ladder rung runs on the trace-identical compact layout
    # (the wide carry RESOURCE_EXHAUSTs there) — recorded per row.
    by_n = {r["n_members"]: r for r in dissemination_scale["rows"]}
    assert by_n[33_554_432]["compact_carry"] is True
    assert by_n[16_777_216]["compact_carry"] is False


def test_focal_ceiling_claims(results_text, focal_ceiling):
    lay = focal_ceiling["layouts"]
    w_fit, w_fail = claim(
        results_text,
        r"the wide layout fits ([\d,]+)\s+members and fails at ([\d,]+)",
    )
    assert (w_fit, w_fail) == (lay["wide"]["max_fits"],
                               lay["wide"]["first_fail_above_max_fits"])
    c_fit, c_fail = claim(
        results_text,
        r"the compact layout fits ([\d,]+)\s+and fails at ([\d,]+)",
    )
    assert (c_fit, c_fail) == (lay["compact"]["max_fits"],
                               lay["compact"]["first_fail_above_max_fits"])
    (rate_m,) = claim(
        results_text,
        r"focal ceiling is 41\.9M members on one\s+chip\*\* "
        r"\((\d+\.\d)M member-rounds/s at the ceiling rung\)",
    )
    ceiling_row = next(r for r in lay["compact"]["rows"]
                       if r["n_members"] == lay["compact"]["max_fits"])
    assert rate_m == rounded(ceiling_row["member_rounds_per_sec"] / 1e6, 1)
    # The metric mode must not move the bracket (stated negative).
    assert lay["wide_ps"]["max_fits"] == lay["wide"]["max_fits"]
    assert lay["compact_ps"]["max_fits"] == lay["compact"]["max_fits"]
    # Roll payloads fail at every probed rung (stated negative).
    assert lay["compact_roll"]["max_fits"] is None
    # The over-ceiling anatomy probe is recorded with its raw failure
    # text (the stated mode nondeterminism: pin bracket, not flags).
    probe = focal_ceiling["anatomy_probe"]
    assert probe["n_members"] == 67_108_864 and not probe["fits"]
    assert probe.get("oom") or probe.get("helper_crash")
    assert probe["error"]


def test_stated_suite_size_matches_collection(results_text):
    """Round 2 said "218 tests" when 245 existed; round 3 repeated it.
    Collection is ~1.5 s, so just count."""
    import subprocess
    import sys

    (stated,) = claim(results_text, r"(\d+) tests, all green")
    # Collection alone is ~2 s, but on this 1-core host a concurrently
    # running suite can starve the child — keep the timeout generous.
    out = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "--collect-only", "-q",
         "-p", "no:cacheprovider"],
        cwd=REPO, capture_output=True, text=True, timeout=900,
    )
    m = re.search(r"(\d+) tests collected", out.stdout)
    assert m, out.stdout[-2000:]
    assert stated == float(m.group(1)), (
        f"RESULTS.md states {int(stated)} tests; collection finds "
        f"{m.group(1)} — update the prose"
    )


def test_fullview_36k_compact_demo(results_text):
    d = _load("fullview_scale_36k_compact.json")
    assert d["carry_layout"] == "compact" and d["bytes_per_cell"] == 6
    n_rows, suspected, dead, n_obs, diss, healed = claim(
        results_text,
        r"(?s)\*\*([\d,]+) rows, compact layout, 8-device mesh\*\*.*?"
        r"crash@2 →\s+suspected@(\d+) →\s+DEAD@(\d+) →\s+disseminated"
        r"\s+to all ([\d,]+) observers@(\d+) →\s+revived@22 →"
        r"\s+re-accepted\s+everywhere@(\d+)",
    )
    assert n_rows == d["n_members"]
    tl = d["timeline"]
    assert (suspected, dead, diss, healed) == (
        tl["suspected"], tl["declared_dead"], tl["death_disseminated"],
        tl["healed"],
    )
    assert n_obs == d["n_members"] - 1
    assert d["false_suspicion_onsets"] == 0
    (gb_dev,) = claim(results_text, r"(\d\.\d\d) GB state/device\.")
    assert gb_dev == rounded(d["state_gb_per_device"], 2)
    wall_new, wall_old = claim(
        results_text, r"was ([\d,]+) s vs the 32k wide demo's ([\d,]+) s"
    )
    old = _load("fullview_scale.json")
    assert wall_new == rounded(d["wall_seconds_virtual_mesh"])
    assert wall_old == rounded(old["wall_seconds_virtual_mesh"])
    # The stated ratios: cells vs the 32k demo and vs the compact
    # single-chip ceiling; wall and cell percent changes.
    cells_32k, cells_ceiling = claim(
        results_text,
        r"(\d\.\d\d)× the cells of the round-3 32k demo and (\d\.\d\d)× "
        r"the cells of the\s+compact single-chip ceiling",
    )
    assert cells_32k == rounded((d["n_members"] / old["n_members"]) ** 2, 2)
    ceiling = _load("fullview_ceiling.json")["layouts"]["compact"]["max_fits"]
    assert cells_ceiling == rounded((d["n_members"] / ceiling) ** 2, 2)
    wall_pct, cells_pct = claim(
        results_text, r"(\d+)%\s+less despite (\d+)% more cells"
    )
    assert wall_pct == rounded(100 * (1 - d["wall_seconds_virtual_mesh"]
                                      / old["wall_seconds_virtual_mesh"]))
    assert cells_pct == rounded(
        100 * ((d["n_members"] / old["n_members"]) ** 2 - 1))


def test_fullview_sharded_demo_row(results_text, fullview):
    tl = fullview["timeline"]
    suspected, dead, n_obs, diss, healed = claim(
        results_text,
        r"crash@2 → suspected@(\d+) → DEAD@(\d+) → disseminated to all "
        r"([\d,]+) observers@(\d+) → revived@22 → re-accepted "
        r"everywhere@(\d+)",
    )
    assert (suspected, dead, diss, healed) == (
        tl["suspected"], tl["declared_dead"], tl["death_disseminated"],
        tl["healed"],
    )
    assert n_obs == fullview["n_members"] - 1
    assert fullview["false_suspicion_onsets"] == 0
    # "|"-terminated: the 32k table row (the 36k demo paragraph states
    # its own figure, checked by test_fullview_36k_compact_demo).
    (gb,) = claim(results_text, r"(\d\.\d\d) GB state/device \|")
    assert gb == rounded(fullview["state_gb_per_device"], 2)
