"""bench.py --rollout --smoke: the staged config-rollout JSON contract.

Like tests/test_bench_sync_smoke.py for the heal plane: the bench is
the one entry point the rollout measurement flows through, so this
test runs the real script in a subprocess (CPU) and pins the published
contract — one JSON line with the rollout fields (every push converged
inside its deadline with no rollback, the monitored chaos arm green,
the gossip-only control permanently divergent), an
artifacts/config_rollout.json-style artifact the query layer loads as
a real payload, the regress gate walking it with the absolute rollout
checks, and the ``metadata_convergence_p99`` SLO surfaced from the
JSONL manifest.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.metadata

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_rollout_bench(tmp_path, extra_env=None, timeout=900):
    artifact = tmp_path / "config_rollout_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_ROLLOUT_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--rollout", "--smoke"],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    return json.loads(lines[0]), artifact


def test_bench_rollout_smoke_contract(tmp_path):
    result, artifact = _run_rollout_bench(tmp_path)

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == "config_rollout_convergence"
    # value stays None BY DESIGN (smaller-is-better must not enter the
    # generic throughput walk); the payload says so.
    assert result["value"] is None
    assert "value_note" in result

    # The headline acceptance: every push converged inside its
    # deadline (no rollback triggered), the final table is globally
    # agreed, the monitored chaos-campaign arm is green, and the
    # gossip-only control demonstrably stays divergent.
    assert result["rollout_converged"] is True
    assert result["rolled_back"] is False
    assert 0 <= result["metadata_convergence_p99"] <= \
        result["convergence_deadline_rounds"]
    assert result["final_divergent_cells"] == 0
    assert result["monitored_green"] is True
    assert result["monitor_violations"] == 0
    assert result["control_converged"] is False
    assert result["control_divergent_cells"] > 0

    # Workload provenance: the staged schedule really is staged, under
    # a real split, with the plane armed.
    assert result["delivery"] == "shift"
    assert result["sync_interval"] > 0
    assert result["metadata_keys"] >= 1
    assert result["n_stages"] >= 2
    assert len(result["owners"]) == result["n_stages"] * \
        result["stage_size"]
    assert len(result["stage_rounds"]) == result["n_stages"]
    assert len(result["stage_converge_rounds"]) == len(result["owners"])
    assert result["split_rounds"] > 0
    assert result["horizon_rounds"] >= max(result["stage_rounds"])

    # The artifact round-trips and loads as a REAL (non-stub) payload.
    art = json.loads(artifact.read_text())
    assert art["metric"] == result["metric"]
    assert art["metadata_convergence_p99"] == \
        result["metadata_convergence_p99"]

    from scalecube_cluster_tpu.telemetry import query as tquery

    payload, skip_note = tquery.load_bench_payload(str(artifact))
    assert skip_note is None
    assert payload["rollout_converged"] is True

    # The in-bench regress gate ran and the dedicated absolute checks
    # are present and green for the fresh artifact.
    assert result["regress"]["ok"] is True
    assert result["regress"]["artifacts"] >= 1
    ok, rows = tquery.regress([str(artifact)])
    assert ok
    names = {r["check"] for r in rows}
    assert {"slo/rollout_converged", "slo/rollout_not_rolled_back",
            "slo/rollout_control_diverges",
            "slo/metadata_convergence_p99_within_bound",
            "slo/rollout_monitor_violations"} <= names

    # The SLO surface: the manifest's summary row folds into
    # metadata_convergence_p99.
    report = tquery.load_report(result["manifest"])
    slos = tquery.compute_slos(report)
    assert slos["metadata_convergence_p99"] == (
        result["metadata_convergence_p99"])


@pytest.mark.slow
def test_bench_rollout_full(tmp_path):
    """The full (non-smoke) three-stage rollout.  The design-target
    scale is accelerator-sized; under the CPU-forced test environment
    the env override keeps the FULL (non-smoke) path honest at a
    feasible N."""
    artifact = tmp_path / "config_rollout_full.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_ROLLOUT_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",
        SCALECUBE_ROLLOUT_N=os.environ.get("SCALECUBE_ROLLOUT_N", "32"),
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--rollout"],
        capture_output=True, text=True, timeout=3000, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "error" not in result, result
    assert result["smoke"] is False
    assert result["rollout_converged"] is True
    assert result["rolled_back"] is False
    assert result["monitored_green"] is True
    assert result["control_converged"] is False
    assert result["n_stages"] == 3
