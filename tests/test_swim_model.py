"""Tests for the full SWIM TPU model (models/swim.py).

Ports the scenario coverage of the reference's
MembershipProtocolTest/FailureDetectorTest (SURVEY.md §4) to the dense
tick: healthy steady state, crash -> SUSPECT -> suspicion-timeout -> DEAD
dissemination, network partition + heal via SYNC, crashed-node restart
(tombstone re-acceptance + self-refutation), and determinism.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim


def fast_config():
    """The reference's sped-up test config (MembershipProtocolTest.java:545-554
    uses sync=500ms ping=200ms); here gossip=100ms ping=200ms sync=1s."""
    return ClusterConfig.default().replace(
        gossip_interval=100,
        ping_interval=200,
        ping_timeout=100,
        sync_interval=1_000,
        suspicion_mult=3,
    )


def make(n, k=None, loss=0.0, **overrides):
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=k, loss_probability=loss,
        **overrides,
    )
    world = swim.SwimWorld.healthy(params)
    return params, world


def counts_at(metrics, round_idx, name):
    return np.asarray(metrics[name])[round_idx]


class TestHealthySteadyState:
    def test_no_false_positives_lossless(self):
        """With no faults and no loss, nobody is ever suspected."""
        params, world = make(16)
        _, metrics = swim.run(jax.random.key(0), params, world, 100)
        assert np.asarray(metrics["false_positives"]).sum() == 0
        # Everyone keeps full ALIVE view of all other members.
        alive_counts = np.asarray(metrics["alive"])[-1]
        assert np.all(alive_counts == params.n_members - 1)

    def test_low_false_positive_rate_under_loss(self):
        """Modest loss with ping-req backup keeps false suspicions rare
        (FailureDetectorTest's asymmetric-loss rescue scenario, :117-147)."""
        params, world = make(32, loss=0.05)
        n_rounds = 200
        _, metrics = swim.run(jax.random.key(1), params, world, n_rounds)
        fp = np.asarray(metrics["false_positives"])
        # Suspicions may flicker but DEAD declarations of live members
        # should not occur at 5% loss with 3 proxies.
        dead = np.asarray(metrics["dead"])
        assert dead.sum() == 0, "live member wrongly declared dead"
        assert fp.sum() < 0.01 * n_rounds * 32 * 31


class TestCrashDetection:
    def test_crash_suspect_then_dead(self):
        """A crashed member is suspected by FD probes, declared DEAD after
        the suspicion timeout, and the death disseminates to everyone
        (MembershipProtocolTest suspicion->removal, :312-366)."""
        n = 16
        params, world = make(n)
        crash_round = 10
        world = world.with_crash(0, at_round=crash_round)
        horizon = crash_round + params.ping_every * n + params.suspicion_rounds \
            + 4 * params.periods_to_spread
        _, metrics = swim.run(jax.random.key(2), params, world, horizon)

        suspects = np.asarray(metrics["suspect"])[:, 0]
        deads = np.asarray(metrics["dead"])[:, 0]
        assert suspects.max() > 0, "crashed node never suspected"
        # Eventually every live observer has processed the death (DEAD
        # tombstone or, post-sweep, a removed entry — both non-ALIVE).
        alive_view = np.asarray(metrics["alive"])[:, 0]
        assert alive_view[-1] == 0, "some observer still sees the crashed node ALIVE"
        assert deads.max() > 0, "death never declared"

    def test_detection_respects_suspicion_timeout(self):
        """DEAD cannot be declared before suspicion_rounds after first
        suspicion (ClusterMath.suspicionTimeout, ClusterMath.java:123-125)."""
        n = 8
        params, world = make(n)
        world = world.with_crash(3, at_round=0)
        _, metrics = swim.run(jax.random.key(3), params, world, 200)
        suspects = np.asarray(metrics["suspect"])[:, 3]
        deads = np.asarray(metrics["dead"])[:, 3]
        first_suspect = int(np.argmax(suspects > 0))
        assert suspects.max() > 0
        assert deads.max() > 0, "death never declared within horizon"
        first_dead = int(np.argmax(deads > 0))
        assert first_dead >= first_suspect + params.suspicion_rounds


class TestPartition:
    def test_partition_and_heal(self):
        """Symmetric split: each side declares the other side dead; after
        heal, ALIVE records (re-accepted through the tombstone gate) plus
        refutation restore the full view (MembershipProtocolTest partition
        + recovery, :82-310)."""
        n = 12
        params, world = make(n)
        # Rounds [0, 40): no partition; [40, 40+phase): split 0-5 / 6-11.
        phase_len = 150
        sched = jnp.stack([
            jnp.zeros((n,), dtype=jnp.int8),
            jnp.array([0] * 6 + [1] * 6, dtype=jnp.int8),
            jnp.zeros((n,), dtype=jnp.int8),
        ])
        world = world.with_partition_schedule(sched, phase_len)
        horizon = 3 * phase_len
        final, metrics = swim.run(jax.random.key(4), params, world, horizon)

        # During the split, cross-side members get suspected/declared dead.
        mid = 2 * phase_len - 1
        fp_mid = counts_at(metrics, mid, "false_positives")
        assert fp_mid.sum() > 0, "partition never caused suspicions"

        # After healing, everyone sees everyone ALIVE again.
        status = np.asarray(final.status)
        diag = np.eye(n, dtype=bool)
        assert np.all(status[~diag] == records.ALIVE), (
            "view did not heal after partition"
        )

    def test_refutation_bumps_incarnation(self):
        """Suspected-but-alive members refute with an incarnation bump
        (MembershipProtocolImpl.java:488-509)."""
        n = 12
        params, world = make(n, loss=0.30)
        final, metrics = swim.run(jax.random.key(5), params, world, 300)
        # At 30% loss some suspicion must have happened, hence refutations.
        assert np.asarray(metrics["refutations"]).sum() > 0
        assert np.asarray(final.self_inc).max() > 0


class TestRestart:
    def test_restart_after_death_is_reaccepted(self):
        """A node crashed long enough to be declared dead, then revived,
        is re-accepted (no tombstone forever — SURVEY.md §5.3, exercised by
        MembershipProtocolTest.testRestartFailedMembers:368-430)."""
        n = 10
        params, world = make(n)
        down_from = 5
        down_until = down_from + params.ping_every * n + params.suspicion_rounds \
            + 3 * params.periods_to_spread
        world = world.with_crash(2, at_round=down_from, until_round=down_until)
        horizon = down_until + 400
        final, metrics = swim.run(jax.random.key(6), params, world, horizon)

        alive_view = np.asarray(metrics["alive"])[:, 2]
        assert alive_view[down_until - 1] < n - 1, "death never observed"
        status = np.asarray(final.status)[:, 2]
        observers = np.arange(n) != 2
        assert np.all(status[observers] == records.ALIVE), (
            "revived node not re-accepted everywhere"
        )
        # No refutation is expected here: the death fully disseminated and
        # the records were deleted everywhere before revival, so (like the
        # reference, whose SYNC carries no deleted records) the node never
        # hears of its own death — re-acceptance is via its SYNC pushes
        # through the no-tombstone gate (MembershipRecord.java:67-69).


class TestFocalMode:
    def test_focal_matches_full_view_statistically(self):
        """Focal mode (K<N) detects a crashed focal subject on the same
        timescale as full-view mode.

        Band justified by the measured seed spread (8 seeds, printed on
        failure): full-view first-full-death rounds {5..10} (median 7),
        focal {4..7} (median 5) — the medians sit within 3 rounds and no
        seed pair differs by more than 6.  Round 2's tolerance was
        [r/3, 2r], loose enough to hide a 1.8x fidelity drift; this is
        the measured envelope plus one round of slack."""
        n = 64
        rs_full, rs_focal = [], []

        def first_full_death(metrics):
            gone = np.asarray(metrics["alive"])[:, 0] == 0
            return int(np.argmax(gone)) if gone.any() else -1

        for seed in range(8):
            params_full, world_full = make(n)
            world_full = world_full.with_crash(0, at_round=0)
            _, m_full = swim.run(jax.random.key(seed), params_full,
                                 world_full, 250)
            params_focal, world_focal = make(n, k=4, ping_known_only=False)
            world_focal = world_focal.with_crash(0, at_round=0)
            _, m_focal = swim.run(jax.random.key(seed), params_focal,
                                  world_focal, 250)
            rs_full.append(first_full_death(m_full))
            rs_focal.append(first_full_death(m_focal))

        spread = list(zip(rs_full, rs_focal))
        assert all(r > 0 for r in rs_full + rs_focal), spread
        assert abs(np.median(rs_full) - np.median(rs_focal)) <= 3, spread
        assert max(abs(a - b) for a, b in spread) <= 7, spread

    def test_detection_K_invariant(self):
        """Detection/dissemination of a crash is invariant in the tracked-
        subject count K — the measured envelope at N=4096 is EXACT
        (detection round 78, dissemination 85, for every K in
        {8, 64, 512, 4096=full} and every seed tried), so the band here is
        +-2 rounds.  This is the measured K-invariance curve behind the 1M
        focal-mode headline (K=16 <<< N).  2 seeds per K: the observed
        spread is zero and the K=4096 full-view compiles dominate the
        test's runtime (the 6-seed exploratory run is recorded in
        RESULTS.md)."""
        n = 4096
        meds = {}
        for k in (8, 64, 512, n):
            det, dis = [], []
            for seed in range(2):
                params = swim.SwimParams.from_config(
                    fast_config(), n_members=n,
                    n_subjects=(None if k == n else k), delivery="shift",
                )
                world = swim.SwimWorld.healthy(params).with_crash(
                    0, at_round=0
                )
                _, m = swim.run(jax.random.key(seed), params, world, 160)
                deads = np.asarray(m["dead"])[:, 0]
                alive_view = np.asarray(m["alive"])[:, 0]
                suspects = np.asarray(m["suspect"])[:, 0]
                det.append(int(np.flatnonzero(deads > 0)[0]))
                full = np.flatnonzero(
                    (alive_view == 0) & (suspects == 0) & (deads > 0)
                )
                assert full.size, f"K={k} seed={seed}: never disseminated"
                dis.append(int(full[0]))
            meds[k] = (float(np.median(det)), float(np.median(dis)))
        base = meds[n]  # full view = exact reference semantics
        for k, (d, s) in meds.items():
            assert abs(d - base[0]) <= 2, meds
            assert abs(s - base[1]) <= 2, meds

    def test_focal_no_false_positives_lossless(self):
        params, world = make(256, k=8, ping_known_only=False)
        _, metrics = swim.run(jax.random.key(8), params, world, 120)
        assert np.asarray(metrics["false_positives"]).sum() == 0


class TestFalsePositiveSplit:
    """The FP aggregate splits into onset EVENTS vs stale-view ROUNDS
    (swim_tick metrics docs) — two phenomena with different semantics:
    genuine FD false alarms vs lingering DEAD tombstones about a revived
    member (the reference's delete-then-re-add window,
    MembershipProtocolImpl.java:512-516)."""

    @pytest.mark.parametrize("delivery", ["scatter", "shift"])
    def test_revival_stale_view_not_counted_as_suspicion(self, delivery):
        n = 10
        params, world = make(n, delivery=delivery)
        down_from = 5
        down_until = down_from + params.ping_every * n \
            + params.suspicion_rounds + 3 * params.periods_to_spread
        world = world.with_crash(2, at_round=down_from,
                                 until_round=down_until)
        _, m = swim.run(jax.random.key(20), params, world, down_until + 200)

        stale = np.asarray(m["stale_view_rounds"]).sum()
        onsets = np.asarray(m["false_suspicion_onsets"]).sum()
        suspect_live = np.asarray(m["false_suspect_rounds"]).sum()
        fp = np.asarray(m["false_positives"]).sum()
        # Lossless: the only FP phenomenon is the post-revival stale-DEAD
        # window, so it accounts for the whole aggregate and no
        # false-suspicion onset ever fires.
        assert stale > 0, "revival produced no stale-view window"
        assert onsets == 0
        assert fp == stale + suspect_live  # exact status partition
        assert fp == stale

    def test_loss_false_suspicions_are_onsets_not_stale(self):
        # Suspicion timeout pushed out of the horizon: suspicions never
        # mature to DEAD, so every FP round is a SUSPECT round.
        params, world = make(32, loss=0.3, suspicion_rounds=10_000)
        _, m = swim.run(jax.random.key(21), params, world, 150)
        onsets = np.asarray(m["false_suspicion_onsets"]).sum()
        suspect_live = np.asarray(m["false_suspect_rounds"]).sum()
        stale = np.asarray(m["stale_view_rounds"]).sum()
        fp = np.asarray(m["false_positives"]).sum()
        assert onsets > 0, "30% loss produced no false suspicions"
        assert stale == 0
        assert fp == suspect_live  # every FP round holds SUSPECT here
        # Each onset event holds SUSPECT for >= 1 observer-round.
        assert fp >= onsets

    @pytest.mark.parametrize("delivery", ["scatter", "shift"])
    def test_quick_revival_suspect_rounds_partition(self, delivery):
        """A member that revives before its suspicion matures to DEAD
        leaves observers holding SUSPECT about a live subject: those rounds
        are false_suspect_rounds (not onsets — the transition happened
        while the subject was down; not stale — never DEAD), and the
        aggregate still partitions exactly."""
        n = 10
        params, world = make(n, delivery=delivery)
        # Down long enough to get suspected, revived well before the
        # suspicion_rounds timeout matures the SUSPECT to DEAD.
        down_from = 5
        down_until = down_from + params.ping_every * n + 2
        assert down_until - down_from < params.suspicion_rounds
        world = world.with_crash(2, at_round=down_from,
                                 until_round=down_until)
        _, m = swim.run(jax.random.key(22), params, world, down_until + 120)
        onsets = np.asarray(m["false_suspicion_onsets"]).sum()
        suspect_live = np.asarray(m["false_suspect_rounds"]).sum()
        stale = np.asarray(m["stale_view_rounds"]).sum()
        fp = np.asarray(m["false_positives"]).sum()
        assert fp == suspect_live + stale  # exact status partition
        if fp > 0:  # suspicion arose before revival in this seed
            assert suspect_live > 0
            assert onsets == 0


class TestDeterminism:
    def test_same_key_same_trace(self):
        params, world = make(16, loss=0.2)
        world = world.with_crash(1, at_round=5)
        _, m1 = swim.run(jax.random.key(9), params, world, 80)
        _, m2 = swim.run(jax.random.key(9), params, world, 80)
        for k in m1:
            np.testing.assert_array_equal(np.asarray(m1[k]), np.asarray(m2[k]))

    def test_checkpoint_resume_matches(self):
        """Splitting the scan at a checkpoint reproduces the unbroken run
        bit-exactly (the §5.4 checkpoint/resume contract)."""
        params, world = make(12, loss=0.1)
        key = jax.random.key(10)
        final_a, m_a = swim.run(key, params, world, 60)
        mid, m1 = swim.run(key, params, world, 30)
        final_b, m2 = swim.run(key, params, world, 30, state=mid, start_round=30)
        np.testing.assert_array_equal(
            np.asarray(final_a.status), np.asarray(final_b.status)
        )
        np.testing.assert_array_equal(
            np.asarray(m_a["alive"]),
            np.concatenate([np.asarray(m1["alive"]), np.asarray(m2["alive"])]),
        )


class TestAggregateMetricsPath:
    @pytest.mark.parametrize("delivery", ["scatter", "shift"])
    def test_aggregate_equals_summed_per_subject(self, delivery):
        """per_subject_metrics=False (the 1M-bench observability path) must
        equal the per-subject traces summed over subjects."""
        n = 24
        params_ps = swim.SwimParams.from_config(
            fast_config(), n_members=n, loss_probability=0.1,
            delivery=delivery, per_subject_metrics=True,
        )
        params_agg = dataclasses.replace(params_ps, per_subject_metrics=False)
        world = swim.SwimWorld.healthy(params_ps).with_crash(1, at_round=5)
        key = jax.random.key(11)
        _, m_ps = swim.run(key, params_ps, world, 80)
        _, m_agg = swim.run(key, params_agg, world, 80)
        for name in ("alive", "suspect", "dead", "absent", "false_positives",
                     "false_suspicion_onsets", "false_suspect_rounds",
                     "stale_view_rounds"):
            np.testing.assert_array_equal(
                np.asarray(m_ps[name]).sum(axis=1), np.asarray(m_agg[name])
            )
        for name in ("messages_gossip", "messages_ping",
                     "messages_ping_sent", "messages_ping_req_sent",
                     "refutations"):
            np.testing.assert_array_equal(
                np.asarray(m_ps[name]), np.asarray(m_agg[name])
            )


class TestHonestMessageCounters:
    """``messages_ping_sent`` counts real wire probes (the reference's
    per-period probe logs, FailureDetectorImpl.java:148,156-164);
    ``messages_ping`` counts only tracked-subject verdicts — in focal mode
    they differ by ~N/K and both must be reported (round-3 verdict:
    the 1M bench read "3 pings/round" for a cluster issuing ~1M)."""

    @pytest.mark.parametrize("delivery", ["scatter", "shift"])
    def test_focal_mode_probes_sent_is_all_live_members(self, delivery):
        n, k = 64, 8
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, n_subjects=k, delivery=delivery,
        )
        assert not params.ping_known_only
        world = swim.SwimWorld.healthy(params)
        _, m = swim.run(jax.random.key(3), params, world, 20)
        sent = np.asarray(m["messages_ping_sent"])
        tracked = np.asarray(m["messages_ping"])
        fd_rounds = np.arange(20) % params.ping_every == 0
        # Every live member issues exactly one PING per fd round.
        np.testing.assert_array_equal(sent[fd_rounds], n)
        np.testing.assert_array_equal(sent[~fd_rounds], 0)
        # Tracked-subject verdicts are a strict subset in focal mode.
        assert np.all(tracked <= sent)
        assert tracked.sum() < sent.sum()
        # Lossless: no direct ping fails, so no ping-req fan-out.
        assert np.asarray(m["messages_ping_req_sent"]).sum() == 0

    @pytest.mark.parametrize("delivery", ["scatter", "shift"])
    def test_ping_req_fanout_counted_under_loss(self, delivery):
        n = 48
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, loss_probability=0.3,
            delivery=delivery,
        )
        world = swim.SwimWorld.healthy(params)
        _, m = swim.run(jax.random.key(4), params, world, 30)
        pr = np.asarray(m["messages_ping_req_sent"])
        assert pr.sum() > 0
        # Each launch fans out to exactly ping_req_members proxies.
        assert np.all(pr % params.ping_req_members == 0)
        # Full view: every probe lands on a tracked subject, so the two
        # families coincide.
        np.testing.assert_array_equal(
            np.asarray(m["messages_ping_sent"]), np.asarray(m["messages_ping"])
        )


def test_shift_delivery_requires_ping_known_only_matching_full_view():
    """Directly-constructed shift params with mismatched flags must fail
    loudly: shift mode has no known-only probe path at K < N, so a focal
    SwimParams keeping the dataclass default ping_known_only=True would
    silently count wire probes differently across delivery modes."""
    with pytest.raises(ValueError, match="ping_known_only"):
        swim.SwimParams.from_config(
            fast_config(), n_members=64, n_subjects=8, delivery="shift",
            ping_known_only=True,
        )
    # from_config derives the flag; both delivery modes accept the result.
    p = swim.SwimParams.from_config(
        fast_config(), n_members=64, n_subjects=8, delivery="shift"
    )
    assert not p.ping_known_only
