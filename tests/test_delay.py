"""Delayed gossip/SYNC delivery (the ring in models/swim.py).

The reference's NetworkEmulator delays every message by an exponential
draw (NetworkLinkSettings.java:64-74) and its gossip experiment matrix
sweeps mean delay to half a gossip period (GossipProtocolTest.java:50-66).
With ``max_delay_rounds > 0`` the tick quantizes those delays to round
offsets: late messages land in future rounds instead of vanishing.
"""

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config


def make(n, delivery, mean_delay_ms=0.0, max_delay_rounds=0, **overrides):
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery=delivery,
        mean_delay_ms=mean_delay_ms, max_delay_rounds=max_delay_rounds,
        **overrides,
    )
    world = swim.SwimWorld.healthy(params)
    return params, world


def dissemination_round(params, world, seed, horizon=300):
    """First round every live observer has dropped crashed node 0."""
    world = world.with_crash(0, at_round=0)
    _, m = swim.run(jax.random.key(seed), params, world, horizon)
    alive_view = np.asarray(m["alive"])[:, 0]
    suspects = np.asarray(m["suspect"])[:, 0]
    deads = np.asarray(m["dead"])[:, 0]
    done = (alive_view == 0) & (suspects == 0) & (deads > 0)
    idx = np.flatnonzero(done)
    return int(idx[0]) if idx.size else horizon


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
class TestDelayRing:
    def test_zero_delay_ring_is_identity(self, delivery):
        """max_delay_rounds>0 with mean delay 0 must reproduce the D=0
        path: every message bins to offset 0."""
        pa0, w0 = make(16, delivery)
        pa1, w1 = make(16, delivery, max_delay_rounds=2)
        key = jax.random.key(0)
        _, m0 = swim.run(key, pa0, w0, 60)
        _, m1 = swim.run(key, pa1, w1, 60)
        # Same protocol outcomes (message RNG streams differ slightly, so
        # compare the deterministic lossless steady state).
        np.testing.assert_array_equal(np.asarray(m0["alive"]),
                                      np.asarray(m1["alive"]))
        assert np.asarray(m1["false_positives"]).sum() == 0

    def test_heavy_delay_slows_but_does_not_stop_dissemination(self, delivery):
        """Mean delay of one full round: ~37% of messages arrive late, but
        nothing is lost — the death still fully disseminates, later."""
        n = 24
        fast = [dissemination_round(*make(n, delivery), seed=s)
                for s in range(3)]
        slow = [dissemination_round(
                    *make(n, delivery,
                          mean_delay_ms=float(fast_config().gossip_interval),
                          max_delay_rounds=3),
                    seed=s)
                for s in range(3)]
        assert all(r < 300 for r in slow), "dissemination never completed"
        assert np.median(slow) >= np.median(fast)

    def test_delayed_messages_survive_rounds(self, delivery):
        """With ALL messages delayed >= 1 round (huge mean, ring depth 4),
        dissemination still completes — proof the ring really carries
        messages across rounds instead of dropping them."""
        n = 16
        params, world = make(n, delivery, mean_delay_ms=2_000.0,
                             max_delay_rounds=4)
        r = dissemination_round(params, world, seed=1, horizon=600)
        assert r < 600

    def test_determinism_with_ring(self, delivery):
        params, world = make(12, delivery, mean_delay_ms=150.0,
                             max_delay_rounds=2, loss_probability=0.1)
        world = world.with_crash(2, at_round=5)
        _, m1 = swim.run(jax.random.key(4), params, world, 80)
        _, m2 = swim.run(jax.random.key(4), params, world, 80)
        for name in m1:
            np.testing.assert_array_equal(np.asarray(m1[name]),
                                          np.asarray(m2[name]))

    def test_checkpoint_resume_with_ring(self, delivery):
        """The ring is part of the carry: a split run matches an unbroken
        one bit-exactly even with messages in flight at the split."""
        params, world = make(12, delivery, mean_delay_ms=150.0,
                             max_delay_rounds=2, loss_probability=0.05)
        world = world.with_crash(3, at_round=10)
        key = jax.random.key(5)
        final_a, _ = swim.run(key, params, world, 61)
        mid, _ = swim.run(key, params, world, 31)
        final_b, _ = swim.run(key, params, world, 30, state=mid,
                              start_round=31)
        np.testing.assert_array_equal(np.asarray(final_a.status),
                                      np.asarray(final_b.status))
        np.testing.assert_array_equal(np.asarray(final_a.inbox_ring),
                                      np.asarray(final_b.inbox_ring))


def test_per_link_delay_rule_is_not_loss():
    """A per-link delay rule (node 0's uplink is slow) with FD budgets
    generous enough to absorb it: messages arrive late via the ring but
    nothing is lost, so no false suspicion ever forms.  (With tight
    budgets the same delay correctly DOES cause suspicion — the FD treats
    a blown timeout as failure, FailureDetectorImpl.java:152.)"""
    n = 12
    cfg = fast_config().replace(ping_timeout=4_000, ping_interval=8_000)
    params = swim.SwimParams.from_config(
        cfg, n_members=n, delivery="scatter", max_delay_rounds=3,
    )
    world = swim.SwimWorld.healthy(params).with_link_fault(
        src=0, dst=(0, n), loss=0.0, delay_ms=300.0
    )
    _, m = swim.run(jax.random.key(6), params, world, 200)
    assert np.asarray(m["false_positives"]).sum() == 0


def test_gossip_model_delay_matrix():
    """The gossip-only model supports the reference's {loss, delay} matrix
    (GossipProtocolTest.java:50-66): delay slows dissemination without
    preventing it."""
    from scalecube_cluster_tpu.models import gossip as gmodel

    cfg = fast_config()
    key = jax.random.key(3)
    n = 128
    p0 = gmodel.GossipSimParams.from_config(cfg, n_members=n)
    p1 = gmodel.GossipSimParams.from_config(
        cfg, n_members=n,
        mean_delay_ms=float(cfg.gossip_interval),
        max_delay_rounds=3,
    )
    _, m0 = gmodel.run(key, p0, 120)
    _, m1 = gmodel.run(key, p1, 120)
    r0 = int(np.asarray(gmodel.dissemination_rounds(m0, n))[0])
    r1 = int(np.asarray(gmodel.dissemination_rounds(m1, n))[0])
    assert r0 > 0 and r1 > 0, "dissemination incomplete"
    assert r1 >= r0
