"""bench.py --alarms --smoke: the live SLO alarm drill JSON contract.

Like tests/test_bench_lifeguard_smoke.py for the health plane: the
bench is the one entry point the detection-lag measurement flows
through, so this tier-1 test runs the real script in a subprocess
(CPU) and pins the published contract — one JSON line with the drill
fields (breach arm fires within one window of onset and resolves after
the heal, healthy arm stays silent through the same pulse, zero extra
compiles witnessed per-arm), an artifacts/alarm_drill.json-style
artifact the query layer loads as a real payload, and the regress gate
walking it with the absolute alarm checks.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.alarm

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_alarm_bench(tmp_path, flags=("--alarms", "--smoke"),
                     extra_env=None, timeout=540):
    artifact = tmp_path / "alarm_drill_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_ALARM_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), *flags],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    return json.loads(lines[0]), artifact


def test_bench_alarms_smoke_contract(tmp_path):
    result, artifact = _run_alarm_bench(tmp_path)

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == "alarm_detection_lag_windows"
    # value stays None BY DESIGN (detection lag is smaller-is-better
    # and must not enter the generic throughput walk); the payload
    # says so.
    assert result["value"] is None
    assert "value_note" in result

    # The headline acceptance: the planted breach reaches FIRING
    # within one metrics window of the pulse onset, resolves after the
    # heal, and the healthy arm rides the same pulse out silently.
    assert result["breach_fired"] >= 1
    assert result["alarm_detection_lag_windows"] <= 1.0
    assert result["breach_resolved"] is True
    assert result["healthy_transitions"] == 0
    # The calibration evidence: real margin on both sides of the
    # threshold (alarms.DEFAULT_FP_THRESHOLD / SMOKE_ALARM_THRESHOLD
    # docstrings).
    assert result["healthy_peak_rate"] < result["threshold"]
    assert result["breach_first_fire_rate"] > result["threshold"]
    assert result["margin_healthy"] > 0
    assert result["margin_breach"] > 0

    # Workload provenance + both arms' journals, live-tailable.
    assert result["delivery"] == "scatter"
    assert "alarm_drill_scenario" in result["repro"]
    assert set(result["arms"]) == {"healthy", "breach"}
    for arm, row in result["arms"].items():
        assert os.path.exists(row["journal"]), arm
        assert row["seconds"] > 0            # zero-extra-compiles witness
        assert len(row["window_rates"]) == (result["horizon"]
                                            // result["window_rounds"])
    breach_fires = [t for t in result["arms"]["breach"]["transitions"]
                    if t["to"] == "firing"]
    assert breach_fires and breach_fires[0]["round_end"] == (
        result["onset_round"] + result["window_rounds"])
    assert result["arms"]["healthy"]["transitions"] == []

    # The artifact round-trips and loads as a REAL (non-stub) payload.
    art = json.loads(artifact.read_text())
    assert art["metric"] == result["metric"]
    assert (art["alarm_detection_lag_windows"]
            == result["alarm_detection_lag_windows"])

    from scalecube_cluster_tpu.telemetry import query as tquery

    payload, skip_note = tquery.load_bench_payload(str(artifact))
    assert skip_note is None
    assert payload["breach_fired"] == result["breach_fired"]

    # The in-bench regress gate ran and the dedicated absolute checks
    # are present and green for the fresh artifact.
    assert result["regress"]["ok"] is True
    assert result["regress"]["artifacts"] >= 1
    ok, rows = tquery.regress([str(artifact)])
    assert ok
    names = {r["check"] for r in rows}
    assert {"slo/alarm_breach_fired", "slo/alarm_detection_lag",
            "slo/alarm_resolved_after_heal",
            "slo/alarm_healthy_quiet"} <= names


def test_alarms_flag_is_exclusive(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--alarms", "--sync"],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode != 0
    assert "--alarms" in proc.stderr


def test_regress_fails_on_rotted_alarm_drill(tmp_path):
    """An artifact recording a missed/late detection, a stuck alarm or
    a noisy healthy arm must fail the gate — the committed claim
    cannot silently rot."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    bad = tmp_path / "alarm_drill_bad.json"
    bad.write_text(json.dumps({
        "metric": "alarm_detection_lag_windows", "value": None,
        "alarm_detection_lag_windows": 3.0, "breach_fired": 0,
        "breach_resolved": False, "healthy_transitions": 2,
    }))
    ok, rows = tquery.regress([str(bad)])
    assert not ok
    failed = {r["check"] for r in rows if r.get("ok") is False}
    assert {"slo/alarm_breach_fired", "slo/alarm_detection_lag",
            "slo/alarm_resolved_after_heal",
            "slo/alarm_healthy_quiet"} <= failed


def test_regress_never_fired_lag_is_a_failure(tmp_path):
    """``breach_fired = 0`` leaves the lag null — that must read as a
    FAILED detection gate, not a vacuous pass."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    bad = tmp_path / "alarm_drill_nofire.json"
    bad.write_text(json.dumps({
        "metric": "alarm_detection_lag_windows", "value": None,
        "alarm_detection_lag_windows": None, "breach_fired": 0,
        "breach_resolved": True, "healthy_transitions": 0,
    }))
    ok, rows = tquery.regress([str(bad)])
    assert not ok
    failed = {r["check"] for r in rows if r.get("ok") is False}
    assert "slo/alarm_detection_lag" in failed


def test_regress_smoke_artifacts_are_provenance_next_to_full(tmp_path):
    """A smoke alarm drill sitting next to a full one is a provenance
    row; the full round carries the gates."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    def art(path, smoke, fired):
        path.write_text(json.dumps({
            "metric": "alarm_detection_lag_windows", "value": None,
            "smoke": smoke, "alarm_detection_lag_windows":
            1.0 if fired else None, "breach_fired": int(fired),
            "breach_resolved": fired, "healthy_transitions": 0,
        }))
        return str(path)

    full = art(tmp_path / "alarm_drill.json", False, True)
    smoke = art(tmp_path / "alarm_drill_smoke.json", True, False)
    ok, rows = tquery.regress([full, smoke])
    assert ok                              # the bad smoke round skips
    notes = [r for r in rows if r.get("ok") is None
             and r["check"] == "slo/alarm_drill"]
    assert notes and "smoke" in notes[0]["note"]


@pytest.mark.slow
def test_bench_alarms_full_drill(tmp_path):
    """The full (non-smoke) drill: the committed-artifact geometry
    (n=48, pulse_loss=0.6, DEFAULT_FP_THRESHOLD) through the real
    bench, the aggregate gates green."""
    artifact = tmp_path / "alarm_drill_full.json"
    result, _ = _run_alarm_bench(
        tmp_path, flags=("--alarms",),
        extra_env={"SCALECUBE_ALARM_ARTIFACT": str(artifact)},
        timeout=3000)
    assert "error" not in result, result
    assert result["smoke"] is False
    assert result["breach_fired"] >= 1
    assert result["alarm_detection_lag_windows"] <= 1.0
    assert result["breach_resolved"] is True
    assert result["healthy_transitions"] == 0
    assert result["regress"]["ok"] is True
