"""The runnable examples must actually run (reference ships 5 mains,
examples/src/main/java/io/scalecube/examples/*.java — SURVEY.md §2.1 row 13).

Each example asserts its own invariants; this suite just executes them.
The TPU-scale demo is excluded (it sizes itself for an accelerator).
"""

import importlib.util
import pathlib

import pytest

EXAMPLES = [
    "cluster_join_example",
    "messaging_example",
    "gossip_example",
    "membership_events_example",
    "cluster_metadata_example",
    # Sizes itself down on CPU (the suite backend); the 1M variant runs
    # on the accelerator.
    "metadata_at_scale",
]

EXAMPLES_DIR = pathlib.Path(__file__).resolve().parent.parent / "examples"


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name):
    spec = importlib.util.spec_from_file_location(
        name, EXAMPLES_DIR / f"{name}.py"
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    module.main()
