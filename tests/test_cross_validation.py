"""Oracle ↔ TPU-tick cross-validation (SURVEY.md §7 step 4 exit criterion).

Runs the SAME scenario on both layers — the event-driven oracle (the
behavioral stand-in for the reference's in-JVM harness,
MembershipProtocolTest.java:312-366, FailureDetectorTest.java:117-147) and
the dense TPU tick — with the oracle configured at exactly the tick's time
quantization (gossip interval = 1 round), and compares protocol timescales
across seeds:

  - SUSPECT onset (crash -> first live observer marks SUSPECT),
  - DEAD declaration (suspicion timeout fires),
  - full dissemination (every live observer has dropped the victim),
  - false-suspicion behavior under symmetric link loss.

Medians across seeds must agree within the stated tolerance; the suite
fails if either layer drifts.  Both delivery modes of the tick are pinned.

The suspicion timeout is deterministic and identical by construction
(suspicion_mult * ceil(log2(n+1)) * ping_interval, ClusterMath.java:123-125),
so the compared quantities differ only by probe-discovery and dissemination
dynamics — the parts the dense lift actually approximates.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.oracle import Cluster, Simulator
from scalecube_cluster_tpu.records import MemberStatus

N = 24
ROUND_MS = 100  # gossip interval = the tick's base round

# One config, both layers: tick quantization maps ping_every=2,
# sync_every=10, suspicion_rounds = 3 * ceil(log2(25)) * 200/100 = 30.
CFG = ClusterConfig.default_local().replace(
    gossip_interval=ROUND_MS,
    ping_interval=200,
    ping_timeout=100,
    sync_interval=1_000,
    suspicion_mult=3,
)

# Per layer; medians compared.  32 seeds on the small-N comparisons:
# cheap (one compile per config, ~ms per extra seed) and tight enough
# that the tolerance bands below could be set from the PRINTED seed
# spread rather than guessed — a 1.5x systematic fidelity drift now
# fails where round 2's 2-3x bands would have hidden it.
N_SEEDS = 32
HORIZON_ROUNDS = 250


def _round(t_ms: float) -> float:
    return t_ms / ROUND_MS


# --------------------------------------------------------------------------
# Oracle side
# --------------------------------------------------------------------------


def build_oracle_cluster(seed: int, n: int, cfg=CFG, warmup_ms: int = 4_000):
    """n joined-and-warmed-up oracle clusters (seed member first)."""
    sim = Simulator(seed=seed)
    clusters = [Cluster.join(sim, config=cfg, alias="m0")]
    for i in range(1, n):
        clusters.append(
            Cluster.join(sim, seeds=[clusters[0].address], config=cfg,
                         alias=f"m{i}")
        )
    sim.run_for(warmup_ms)
    assert all(len(c.members()) == n for c in clusters), "warmup incomplete"
    return sim, clusters


def oracle_crash_timescales(seed: int, loss_percent: int = 0):
    """(suspect_onset, dead_first, gone_all) in rounds after the crash."""
    sim, clusters = build_oracle_cluster(seed, N)
    victim = clusters[3]
    observers = [c for c in clusters if c is not victim]

    if loss_percent:
        for c in clusters:
            c.network_emulator.set_default_link_settings(loss_percent, 0)

    t_crash = sim.now
    victim.transport.stop()
    vid = victim.member().id

    suspect_onset = dead_first = gone_all = None
    step_ms = ROUND_MS
    for _ in range(HORIZON_ROUNDS):
        sim.run_for(step_ms)
        if suspect_onset is None:
            for c in observers:
                recs = {r.member.id: r.status
                        for r in c.membership.membership_records()}
                if recs.get(vid) == MemberStatus.SUSPECT:
                    suspect_onset = sim.now - t_crash
                    break
        if dead_first is None:
            if any(vid not in {m.id for m in c.members()} for c in observers):
                dead_first = sim.now - t_crash
        if all(vid not in {m.id for m in c.members()} for c in observers):
            gone_all = sim.now - t_crash
            break
    return tuple(
        _round(x) if x is not None else float("inf")
        for x in (suspect_onset, dead_first, gone_all)
    )


def oracle_false_suspicion(seed: int, loss_percent: int):
    """First false-suspicion round under symmetric loss (inf if none)."""
    sim, clusters = build_oracle_cluster(seed, N)
    for c in clusters:
        c.network_emulator.set_default_link_settings(loss_percent, 0)
    t0 = sim.now
    for _ in range(120):
        sim.run_for(ROUND_MS)
        for c in clusters:
            if any(r.status == MemberStatus.SUSPECT
                   for r in c.membership.membership_records()):
                return _round(sim.now - t0)
    return float("inf")


# --------------------------------------------------------------------------
# Tick side
# --------------------------------------------------------------------------


def tick_crash_timescales(seed: int, delivery: str, loss: float = 0.0):
    params = swim.SwimParams.from_config(
        CFG, n_members=N, loss_probability=loss, delivery=delivery,
    )
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=0)
    _, m = swim.run(jax.random.key(seed), params, world, HORIZON_ROUNDS)
    suspects = np.asarray(m["suspect"])[:, 3]
    deads = np.asarray(m["dead"])[:, 3]
    alive_view = np.asarray(m["alive"])[:, 3]

    def first(cond):
        idx = np.flatnonzero(cond)
        return float(idx[0]) if idx.size else float("inf")

    # "Gone" = the death (not mere suspicion) reached every live observer:
    # no observer holds ALIVE *or* SUSPECT anymore — the analog of the
    # oracle's members()-no-longer-contains check (REMOVED emitted).
    return (
        first(suspects > 0),
        first(deads > 0),
        first((alive_view == 0) & (suspects == 0) & (deads > 0)),
    )


def tick_false_suspicion(seed: int, delivery: str, loss: float):
    params = swim.SwimParams.from_config(
        CFG, n_members=N, loss_probability=loss, delivery=delivery,
    )
    world = swim.SwimWorld.healthy(params)
    _, m = swim.run(jax.random.key(seed), params, world, 120)
    fp = np.asarray(m["false_positives"]).sum(axis=1)
    idx = np.flatnonzero(fp > 0)
    return float(idx[0]) if idx.size else float("inf")


# --------------------------------------------------------------------------
# The comparisons
# --------------------------------------------------------------------------


def medians(values):
    return float(np.median([v for v in values]))


@pytest.fixture(scope="module")
def oracle_crash_stats():
    runs = [oracle_crash_timescales(s) for s in range(N_SEEDS)]
    return tuple(medians(col) for col in zip(*runs))


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_crash_timescales_match_oracle(oracle_crash_stats, delivery):
    o_onset, o_dead, o_gone = oracle_crash_stats
    runs = [tick_crash_timescales(s, delivery) for s in range(N_SEEDS)]
    t_onset, t_dead, t_gone = (medians(col) for col in zip(*runs))

    # Every stage must complete on both layers.
    assert np.isfinite([o_onset, o_dead, o_gone]).all()
    assert np.isfinite([t_onset, t_dead, t_gone]).all()

    # Tolerances set from the measured 32-seed spread (printed in the
    # assertion message on failure), not guessed:
    #   oracle  onset med 3 (3..11), dead med 33 (33..41), gone med 35
    #   tick    onset med 0 (0..6),  dead med 30 (30..36), gone med 33
    # Onset: the tick resolves probe -> verdict within the probe round
    # (the phased collapse, SURVEY.md §7) while the oracle spends the
    # full ping interval, a deterministic offset < one ping cycle; the
    # medians must agree ADDITIVELY within one ping cycle + 2
    # quantization edges (round 2 allowed 2x multiplicative on top —
    # loose enough to hide a 2x drift; this band's headroom is ~2
    # rounds).
    slack = 2 * (CFG.ping_interval // ROUND_MS) + 2
    assert abs(t_onset - o_onset) <= slack, (delivery, t_onset, o_onset, runs)

    # DEAD declaration: onset offset + the (identical, deterministic)
    # suspicion timeout; within 15% + 3 rounds (measured diff: 3).
    assert abs(t_dead - o_dead) <= 0.15 * o_dead + 3, \
        (delivery, t_dead, o_dead, runs)

    # Full dissemination of the death: within 15% + 3 (measured diff: 2).
    assert abs(t_gone - o_gone) <= 0.15 * o_gone + 3, \
        (delivery, t_gone, o_gone, runs)


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_false_suspicion_under_loss_matches_oracle(delivery):
    """At 25% symmetric loss both layers must produce false suspicions on
    the same timescale; at 0% neither may produce any."""
    o_runs = [oracle_false_suspicion(s, 25) for s in range(N_SEEDS)]
    t_runs = [tick_false_suspicion(s, delivery, 0.25) for s in range(N_SEEDS)]
    o_first, t_first = medians(o_runs), medians(t_runs)
    assert np.isfinite(o_first), "oracle produced no false suspicion at 25%"
    assert np.isfinite(t_first), "tick produced no false suspicion at 25%"
    # Measured 32-seed spread: oracle med 2 (2..4), tick med 0 (0..0) —
    # both layers false-suspect within the first probe cycle at 25% loss;
    # the offset is the same within-round-verdict quantization as the
    # crash-onset comparison.  Additive band: one ping cycle + 2.
    slack = 2 * (CFG.ping_interval // ROUND_MS) + 2
    assert abs(t_first - o_first) <= slack, (t_first, o_first, o_runs, t_runs)

    # Control: lossless runs stay clean on both layers.
    assert oracle_false_suspicion(0, 0) == float("inf")
    assert tick_false_suspicion(0, delivery, 0.0) == float("inf")


# --------------------------------------------------------------------------
# Gossip dissemination curve shape: oracle component vs dense model
# --------------------------------------------------------------------------


def oracle_gossip_curve(seed: int, n: int, horizon_rounds: int):
    """Fraction of members infected per round for one spread_gossip."""
    from scalecube_cluster_tpu.oracle import Message

    sim = Simulator(seed=seed)
    clusters = [Cluster.join(sim, config=CFG, alias="m0")]
    for i in range(1, n):
        clusters.append(
            Cluster.join(sim, seeds=[clusters[0].address], config=CFG,
                         alias=f"m{i}")
        )
    sim.run_for(4_000)
    got = set()
    for c in clusters[1:]:
        c.listen_gossips(lambda m, c=c: got.add(c.member().id))
    clusters[0].spread_gossip(Message(qualifier="x", data="payload"))
    curve = []
    for _ in range(horizon_rounds):
        sim.run_for(ROUND_MS)
        curve.append((len(got) + 1) / n)   # +1: the origin itself
    return np.asarray(curve)


def tick_gossip_curve(seed: int, n: int, horizon_rounds: int):
    from scalecube_cluster_tpu.models import gossip as gmodel

    p = gmodel.GossipSimParams.from_config(CFG, n_members=n, n_gossips=1)
    _, m = gmodel.run(jax.random.key(seed), p, horizon_rounds)
    return np.asarray(m["infected_count"])[:, 0] / n


def quartile_rounds(curve, q):
    idx = np.flatnonzero(curve >= q)
    return float(idx[0]) if idx.size else float(len(curve))


def test_gossip_dissemination_curve_shape_matches_oracle():
    """The infection S-curve's quartile crossings (25/50/75/100%) agree
    between the oracle's real gossip component and the dense gossip model
    across seeds — the curve-level form of GossipProtocolTest's
    measured-vs-ClusterMath comparison (:178-205)."""
    n, horizon = 48, 40
    seeds = range(4)
    o = np.asarray([[quartile_rounds(oracle_gossip_curve(s, n, horizon), q)
                     for q in (0.25, 0.5, 0.75, 1.0)] for s in seeds])
    t = np.asarray([[quartile_rounds(tick_gossip_curve(s, n, horizon), q)
                     for q in (0.25, 0.5, 0.75, 1.0)] for s in seeds])
    o_med = np.median(o, axis=0)
    t_med = np.median(t, axis=0)
    assert np.all(o_med < horizon) and np.all(t_med < horizon), (o_med, t_med)
    # Each quartile crossing within 50% + 2 rounds (small-n epidemic
    # curves are steep, so a 1-2 round shift is a large relative error).
    for q, om, tm in zip((25, 50, 75, 100), o_med, t_med):
        assert abs(om - tm) <= 0.5 * om + 2, (q, om, tm)


# ==========================================================================
# Signature fault scenarios — the reference's defining tests, compared
# ACROSS layers (round-3 fidelity matrix).  Each scenario runs the same
# fault on the event-driven oracle and on both tick delivery modes.
# ==========================================================================

N_SEEDS_SIG = 16


# ---- (a) Asymmetric single-link fault + ping-req rescue ------------------
# The reference's signature FD test (FailureDetectorTest.java:117-147):
# one bad direct link, healthy proxies => the ping-req 3-hop rescue keeps
# the pair trusted.  With proxies disabled the same fault must produce
# suspicion on the same timescale on both layers.

FD_N = 8
FD_HORIZON = 80


def oracle_asymmetric_onset(seed: int, proxies: int, horizon: int = FD_HORIZON):
    """First round any observer suspects member 1 with the 0<->1 link dead
    (inf if never)."""
    cfg = CFG.replace(ping_req_members=proxies)
    sim, clusters = build_oracle_cluster(seed, FD_N, cfg)
    a, b = clusters[0], clusters[1]
    a.network_emulator.block(b.address)
    b.network_emulator.block(a.address)
    bid = b.member().id
    t0 = sim.now
    for _ in range(horizon):
        sim.run_for(ROUND_MS)
        for c in clusters:
            if c is b:
                continue
            recs = {r.member.id: r.status
                    for r in c.membership.membership_records()}
            if recs.get(bid) == MemberStatus.SUSPECT:
                return _round(sim.now - t0)
    return float("inf")


def tick_asymmetric_onset(seed: int, delivery: str, proxies: int,
                          horizon: int = FD_HORIZON):
    params = swim.SwimParams.from_config(
        CFG, n_members=FD_N, delivery=delivery, ping_req_members=proxies,
    )
    world = (swim.SwimWorld.healthy(params)
             .with_block(0, 1).with_block(1, 0))
    _, m = swim.run(jax.random.key(seed), params, world, horizon)
    # Watch subject 1 only (the oracle measurement watches member b);
    # the symmetric b-suspects-a onsets are a separate subject column.
    onsets = np.asarray(m["false_suspicion_onsets"])[:, 1]
    idx = np.flatnonzero(onsets > 0)
    return float(idx[0]) if idx.size else float("inf")


@pytest.fixture(scope="module")
def oracle_asymmetric_stats():
    rescued = [oracle_asymmetric_onset(s, proxies=3, horizon=60)
               for s in range(6)]
    onsets = [oracle_asymmetric_onset(s, proxies=0)
              for s in range(N_SEEDS_SIG)]
    return rescued, onsets


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_asymmetric_link_pingreq_rescue_matches_oracle(
        oracle_asymmetric_stats, delivery):
    """With 3 proxies the faulted pair stays trusted on BOTH layers; with 0
    proxies both layers suspect, and onset medians agree within 1.5x."""
    o_rescued, o_runs = oracle_asymmetric_stats
    t_rescued = [tick_asymmetric_onset(s, delivery, proxies=3, horizon=60)
                 for s in range(6)]
    assert all(v == float("inf") for v in o_rescued), o_rescued
    assert all(v == float("inf") for v in t_rescued), t_rescued

    t_runs = [tick_asymmetric_onset(s, delivery, proxies=0)
              for s in range(N_SEEDS_SIG)]
    o_med, t_med = medians(o_runs), medians(t_runs)
    assert np.isfinite(o_med), o_runs
    assert np.isfinite(t_med), t_runs
    # Onset = first probe of the dead link: round-robin (oracle) vs uniform
    # draw (tick) over n-1 targets; medians within 1.5x + one ping cycle.
    slack = CFG.ping_interval // ROUND_MS + 1
    assert t_med <= 1.5 * o_med + slack, (delivery, t_med, o_med, t_runs)
    assert o_med <= 1.5 * t_med + slack, (delivery, t_med, o_med, o_runs)


# ---- (b) Partition -> declared dead -> heal ------------------------------
# MembershipProtocolTest.java:82-310: a full split long enough for each
# side to declare the other dead, then heal; the cross-layer quantity is
# the HEAL TIME (unblock -> every live node sees all N again).  This is
# also the direct measurement of the tick's SYNC-exchange fidelity (the
# anti-entropy path is what heals a fully-partitioned view).

PART_N = 12
PART_ROUNDS = 120
HEAL_HORIZON = 150


def oracle_partition_heal(seed: int):
    """(split_complete, heal_rounds) for a 6/6 split of 12 members."""
    sim, clusters = build_oracle_cluster(seed, PART_N, CFG)
    side_a, side_b = clusters[:6], clusters[6:]
    for c in side_a:
        c.network_emulator.block([d.address for d in side_b])
    for c in side_b:
        c.network_emulator.block([d.address for d in side_a])
    sim.run_for(PART_ROUNDS * ROUND_MS)
    split_complete = all(len(c.members()) == 6 for c in clusters)
    for c in clusters:
        c.network_emulator.unblock_all()
    t0 = sim.now
    for _ in range(HEAL_HORIZON):
        sim.run_for(ROUND_MS)
        if all(len(c.members()) == PART_N for c in clusters):
            return split_complete, _round(sim.now - t0)
    return split_complete, float("inf")


def tick_partition_heal(seed: int, delivery: str):
    """Same split on the tick.  ``with_seeds(0)`` enables the known-or-seed
    contact gate, matching the oracle's doSync candidate rule (seeds ∪ live
    members) — the heal must flow through the seed exactly as it does on
    the oracle."""
    params = swim.SwimParams.from_config(CFG, n_members=PART_N,
                                         delivery=delivery)
    # Three phases so the rolling schedule cannot wrap back into the split
    # within the horizon (split covers [0, 120), healthy [120, 360)).
    sched = jnp.stack([
        jnp.array([0] * 6 + [1] * 6, dtype=jnp.int8),
        jnp.zeros((PART_N,), dtype=jnp.int8),
        jnp.zeros((PART_N,), dtype=jnp.int8),
    ])
    world = (swim.SwimWorld.healthy(params)
             .with_partition_schedule(sched, PART_ROUNDS)
             .with_seeds(0))
    horizon = PART_ROUNDS + HEAL_HORIZON
    _, m = swim.run(jax.random.key(seed), params, world, horizon)
    alive_view = np.asarray(m["alive"])          # [rounds, N]
    split_complete = bool(np.all(alive_view[PART_ROUNDS - 1] == 5))
    healed = np.all(alive_view == PART_N - 1, axis=1)
    idx = np.flatnonzero(healed & (np.arange(horizon) >= PART_ROUNDS))
    heal = float(idx[0] - PART_ROUNDS) if idx.size else float("inf")
    return split_complete, heal


@pytest.fixture(scope="module")
def oracle_heal_stats():
    runs = [oracle_partition_heal(s) for s in range(N_SEEDS_SIG)]
    assert all(split for split, _ in runs), "oracle split incomplete"
    return [heal for _, heal in runs]


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_partition_heal_time_matches_oracle(oracle_heal_stats, delivery):
    o_med = medians(oracle_heal_stats)
    t_runs = [tick_partition_heal(s, delivery) for s in range(N_SEEDS_SIG)]
    assert all(split for split, _ in t_runs), "tick split incomplete"
    t_heals = [heal for _, heal in t_runs]
    t_med = medians(t_heals)
    assert np.isfinite(o_med), oracle_heal_stats
    assert np.isfinite(t_med), t_heals
    # Heal is sync-interval-quantized on both layers; medians within 1.5x
    # + one sync cycle.  (This is the measurement of the SYNC-exchange
    # fidelity across layers.)
    slack = CFG.sync_interval // ROUND_MS
    assert t_med <= 1.5 * o_med + slack, (delivery, t_med, o_med, t_heals)
    assert o_med <= 1.5 * t_med + slack, (delivery, t_med, o_med,
                                          oracle_heal_stats)


# ---- (c) Mean link delay (GossipProtocolTest.java:50-66) -----------------
# The reference's gossip matrix sweeps mean delay to half the gossip
# period.  Same comparison as the curve-shape test above, but with every
# link delayed exp(round_ms/2) on both layers — the tick's delayed-delivery
# ring (max_delay_rounds) vs the oracle's real exponential delays.

DELAY_MS = ROUND_MS // 2


def oracle_gossip_curve_delayed(seed: int, n: int, horizon_rounds: int):
    """Infection curve with every link at exp(DELAY_MS) mean delay, using
    the reference's stubbed-membership gossip harness
    (GossipProtocolTest.java:254-274) so membership dynamics can't
    interfere with the measurement."""
    from scalecube_cluster_tpu.oracle import (
        GossipProtocol, Member, Message, Transport,
    )
    from scalecube_cluster_tpu.oracle.membership import MembershipEvent

    sim = Simulator(seed=seed)
    transports = [Transport(sim) for _ in range(n)]
    members = [Member(f"m{i}", t.address) for i, t in enumerate(transports)]
    protocols = []
    for i in range(n):
        transports[i].network_emulator.set_default_link_settings(0, DELAY_MS)
        g = GossipProtocol(members[i], transports[i], CFG, sim)
        for j in range(n):
            if j != i:
                g.on_member_event(MembershipEvent.added(members[j], None))
        protocols.append(g)
        g.start()

    got = set()
    for i, g in enumerate(protocols[1:], start=1):
        g.listen(lambda msg, i=i: got.add(i))
    protocols[0].spread(Message(qualifier="x", data="payload"))
    curve = []
    for _ in range(horizon_rounds):
        sim.run_for(ROUND_MS)
        curve.append((len(got) + 1) / n)
    return np.asarray(curve)


def tick_gossip_curve_delayed(seed: int, n: int, horizon_rounds: int):
    from scalecube_cluster_tpu.models import gossip as gmodel

    p = gmodel.GossipSimParams.from_config(
        CFG, n_members=n, n_gossips=1,
        mean_delay_ms=float(DELAY_MS), max_delay_rounds=3,
    )
    _, m = gmodel.run(jax.random.key(seed), p, horizon_rounds)
    return np.asarray(m["infected_count"])[:, 0] / n


def test_gossip_curve_under_mean_delay_matches_oracle():
    """Quartile crossings of the delayed infection curve agree across
    layers within 1.5x — validating the delayed-delivery ring against the
    oracle's true exponential per-message delays."""
    n, horizon = 48, 48
    seeds = range(N_SEEDS_SIG)
    o = np.asarray([[quartile_rounds(oracle_gossip_curve_delayed(s, n, horizon), q)
                     for q in (0.25, 0.5, 0.75, 1.0)] for s in seeds])
    t = np.asarray([[quartile_rounds(tick_gossip_curve_delayed(s, n, horizon), q)
                     for q in (0.25, 0.5, 0.75, 1.0)] for s in seeds])
    o_med = np.median(o, axis=0)
    t_med = np.median(t, axis=0)
    assert np.all(o_med < horizon) and np.all(t_med < horizon), (o_med, t_med)
    for q, om, tm in zip((25, 50, 75, 100), o_med, t_med):
        assert abs(om - tm) <= 0.5 * om + 2, (q, om, tm)
