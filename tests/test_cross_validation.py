"""Oracle ↔ TPU-tick cross-validation (SURVEY.md §7 step 4 exit criterion).

Runs the SAME scenario on both layers — the event-driven oracle (the
behavioral stand-in for the reference's in-JVM harness,
MembershipProtocolTest.java:312-366, FailureDetectorTest.java:117-147) and
the dense TPU tick — with the oracle configured at exactly the tick's time
quantization (gossip interval = 1 round), and compares protocol timescales
across seeds:

  - SUSPECT onset (crash -> first live observer marks SUSPECT),
  - DEAD declaration (suspicion timeout fires),
  - full dissemination (every live observer has dropped the victim),
  - false-suspicion behavior under symmetric link loss.

Medians across seeds must agree within the stated tolerance; the suite
fails if either layer drifts.  Both delivery modes of the tick are pinned.

The suspicion timeout is deterministic and identical by construction
(suspicion_mult * ceil(log2(n+1)) * ping_interval, ClusterMath.java:123-125),
so the compared quantities differ only by probe-discovery and dissemination
dynamics — the parts the dense lift actually approximates.
"""

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.oracle import Cluster, Simulator
from scalecube_cluster_tpu.records import MemberStatus

N = 24
ROUND_MS = 100  # gossip interval = the tick's base round

# One config, both layers: tick quantization maps ping_every=2,
# sync_every=10, suspicion_rounds = 3 * ceil(log2(25)) * 200/100 = 30.
CFG = ClusterConfig.default_local().replace(
    gossip_interval=ROUND_MS,
    ping_interval=200,
    ping_timeout=100,
    sync_interval=1_000,
    suspicion_mult=3,
)

N_SEEDS = 8          # per layer; medians compared
HORIZON_ROUNDS = 250


def _round(t_ms: float) -> float:
    return t_ms / ROUND_MS


# --------------------------------------------------------------------------
# Oracle side
# --------------------------------------------------------------------------


def oracle_crash_timescales(seed: int, loss_percent: int = 0):
    """(suspect_onset, dead_first, gone_all) in rounds after the crash."""
    sim = Simulator(seed=seed)
    clusters = [Cluster.join(sim, config=CFG, alias="m0")]
    for i in range(1, N):
        clusters.append(
            Cluster.join(sim, seeds=[clusters[0].address], config=CFG,
                         alias=f"m{i}")
        )
    sim.run_for(4_000)
    victim = clusters[3]
    observers = [c for c in clusters if c is not victim]
    assert all(len(c.members()) == N for c in clusters), "warmup incomplete"

    if loss_percent:
        for c in clusters:
            c.network_emulator.set_default_link_settings(loss_percent, 0)

    t_crash = sim.now
    victim.transport.stop()
    vid = victim.member().id

    suspect_onset = dead_first = gone_all = None
    step_ms = ROUND_MS
    for _ in range(HORIZON_ROUNDS):
        sim.run_for(step_ms)
        if suspect_onset is None:
            for c in observers:
                recs = {r.member.id: r.status
                        for r in c.membership.membership_records()}
                if recs.get(vid) == MemberStatus.SUSPECT:
                    suspect_onset = sim.now - t_crash
                    break
        if dead_first is None:
            if any(vid not in {m.id for m in c.members()} for c in observers):
                dead_first = sim.now - t_crash
        if all(vid not in {m.id for m in c.members()} for c in observers):
            gone_all = sim.now - t_crash
            break
    return tuple(
        _round(x) if x is not None else float("inf")
        for x in (suspect_onset, dead_first, gone_all)
    )


def oracle_false_suspicion(seed: int, loss_percent: int):
    """First false-suspicion round under symmetric loss (inf if none)."""
    sim = Simulator(seed=seed)
    clusters = [Cluster.join(sim, config=CFG, alias="m0")]
    for i in range(1, N):
        clusters.append(
            Cluster.join(sim, seeds=[clusters[0].address], config=CFG,
                         alias=f"m{i}")
        )
    sim.run_for(4_000)
    for c in clusters:
        c.network_emulator.set_default_link_settings(loss_percent, 0)
    t0 = sim.now
    for _ in range(120):
        sim.run_for(ROUND_MS)
        for c in clusters:
            if any(r.status == MemberStatus.SUSPECT
                   for r in c.membership.membership_records()):
                return _round(sim.now - t0)
    return float("inf")


# --------------------------------------------------------------------------
# Tick side
# --------------------------------------------------------------------------


def tick_crash_timescales(seed: int, delivery: str, loss: float = 0.0):
    params = swim.SwimParams.from_config(
        CFG, n_members=N, loss_probability=loss, delivery=delivery,
    )
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=0)
    _, m = swim.run(jax.random.key(seed), params, world, HORIZON_ROUNDS)
    suspects = np.asarray(m["suspect"])[:, 3]
    deads = np.asarray(m["dead"])[:, 3]
    alive_view = np.asarray(m["alive"])[:, 3]

    def first(cond):
        idx = np.flatnonzero(cond)
        return float(idx[0]) if idx.size else float("inf")

    # "Gone" = the death (not mere suspicion) reached every live observer:
    # no observer holds ALIVE *or* SUSPECT anymore — the analog of the
    # oracle's members()-no-longer-contains check (REMOVED emitted).
    return (
        first(suspects > 0),
        first(deads > 0),
        first((alive_view == 0) & (suspects == 0) & (deads > 0)),
    )


def tick_false_suspicion(seed: int, delivery: str, loss: float):
    params = swim.SwimParams.from_config(
        CFG, n_members=N, loss_probability=loss, delivery=delivery,
    )
    world = swim.SwimWorld.healthy(params)
    _, m = swim.run(jax.random.key(seed), params, world, 120)
    fp = np.asarray(m["false_positives"]).sum(axis=1)
    idx = np.flatnonzero(fp > 0)
    return float(idx[0]) if idx.size else float("inf")


# --------------------------------------------------------------------------
# The comparisons
# --------------------------------------------------------------------------


def medians(values):
    return float(np.median([v for v in values]))


@pytest.fixture(scope="module")
def oracle_crash_stats():
    runs = [oracle_crash_timescales(s) for s in range(N_SEEDS)]
    return tuple(medians(col) for col in zip(*runs))


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_crash_timescales_match_oracle(oracle_crash_stats, delivery):
    o_onset, o_dead, o_gone = oracle_crash_stats
    runs = [tick_crash_timescales(s, delivery) for s in range(N_SEEDS)]
    t_onset, t_dead, t_gone = (medians(col) for col in zip(*runs))

    # Every stage must complete on both layers.
    assert np.isfinite([o_onset, o_dead, o_gone]).all()
    assert np.isfinite([t_onset, t_dead, t_gone]).all()

    # Onset: dominated by probe discovery (~(n-1)/probes-per-round rounds).
    # The tick resolves probe -> verdict within the probe round (the phased
    # collapse, SURVEY.md §7), while the oracle spends the full ping
    # interval before the verdict lands, so allow 2x plus an additive slack
    # of one ping cycle (2 * ping_every rounds) + 2 quantization edges.
    slack = 2 * (CFG.ping_interval // ROUND_MS) + 2
    assert t_onset <= 2 * o_onset + slack, (delivery, t_onset, o_onset)
    assert o_onset <= 2 * t_onset + slack, (delivery, t_onset, o_onset)

    # DEAD declaration: onset + the (identical, deterministic) suspicion
    # timeout; must agree within 25% + 3 rounds.
    assert abs(t_dead - o_dead) <= 0.25 * o_dead + 3, (delivery, t_dead, o_dead)

    # Full dissemination of the death: within 35% + 5 rounds.
    assert abs(t_gone - o_gone) <= 0.35 * o_gone + 5, (delivery, t_gone, o_gone)


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_false_suspicion_under_loss_matches_oracle(delivery):
    """At 25% symmetric loss both layers must produce false suspicions on
    the same timescale; at 0% neither may produce any."""
    o_first = medians([oracle_false_suspicion(s, 25) for s in range(N_SEEDS)])
    t_first = medians(
        [tick_false_suspicion(s, delivery, 0.25) for s in range(N_SEEDS)]
    )
    assert np.isfinite(o_first), "oracle produced no false suspicion at 25%"
    assert np.isfinite(t_first), "tick produced no false suspicion at 25%"
    ratio = (t_first + 1) / (o_first + 1)
    assert 1 / 3 <= ratio <= 3, (t_first, o_first)

    # Control: lossless runs stay clean on both layers.
    assert oracle_false_suspicion(0, 0) == float("inf")
    assert tick_false_suspicion(0, delivery, 0.0) == float("inf")


# --------------------------------------------------------------------------
# Gossip dissemination curve shape: oracle component vs dense model
# --------------------------------------------------------------------------


def oracle_gossip_curve(seed: int, n: int, horizon_rounds: int):
    """Fraction of members infected per round for one spread_gossip."""
    from scalecube_cluster_tpu.oracle import Message

    sim = Simulator(seed=seed)
    clusters = [Cluster.join(sim, config=CFG, alias="m0")]
    for i in range(1, n):
        clusters.append(
            Cluster.join(sim, seeds=[clusters[0].address], config=CFG,
                         alias=f"m{i}")
        )
    sim.run_for(4_000)
    got = set()
    for c in clusters[1:]:
        c.listen_gossips(lambda m, c=c: got.add(c.member().id))
    clusters[0].spread_gossip(Message(qualifier="x", data="payload"))
    curve = []
    for _ in range(horizon_rounds):
        sim.run_for(ROUND_MS)
        curve.append((len(got) + 1) / n)   # +1: the origin itself
    return np.asarray(curve)


def tick_gossip_curve(seed: int, n: int, horizon_rounds: int):
    from scalecube_cluster_tpu.models import gossip as gmodel

    p = gmodel.GossipSimParams.from_config(CFG, n_members=n, n_gossips=1)
    _, m = gmodel.run(jax.random.key(seed), p, horizon_rounds)
    return np.asarray(m["infected_count"])[:, 0] / n


def quartile_rounds(curve, q):
    idx = np.flatnonzero(curve >= q)
    return float(idx[0]) if idx.size else float(len(curve))


def test_gossip_dissemination_curve_shape_matches_oracle():
    """The infection S-curve's quartile crossings (25/50/75/100%) agree
    between the oracle's real gossip component and the dense gossip model
    across seeds — the curve-level form of GossipProtocolTest's
    measured-vs-ClusterMath comparison (:178-205)."""
    n, horizon = 48, 40
    seeds = range(4)
    o = np.asarray([[quartile_rounds(oracle_gossip_curve(s, n, horizon), q)
                     for q in (0.25, 0.5, 0.75, 1.0)] for s in seeds])
    t = np.asarray([[quartile_rounds(tick_gossip_curve(s, n, horizon), q)
                     for q in (0.25, 0.5, 0.75, 1.0)] for s in seeds])
    o_med = np.median(o, axis=0)
    t_med = np.median(t, axis=0)
    assert np.all(o_med < horizon) and np.all(t_med < horizon), (o_med, t_med)
    # Each quartile crossing within 50% + 2 rounds (small-n epidemic
    # curves are steep, so a 1-2 round shift is a large relative error).
    for q, om, tm in zip((25, 50, 75, 100), o_med, t_med):
        assert abs(om - tm) <= 0.5 * om + 2, (q, om, tm)
