"""The cross-run query layer + CLI (telemetry/query.py, __main__.py).

Pins: manifest folding (windows merge, gauges last-write), SLO
computation (FP observer-rate, bucket percentiles, dissemination from
the curve), ``diff`` row semantics, and the ``regress`` gate — which
must PASS on the committed BENCH_r01..r05 trajectory (r01 is a failed
run and must be skipped, not fatal) and FAIL on a synthetic 20%
throughput drop; both through the library API and the
``python -m scalecube_cluster_tpu.telemetry`` entry point.
"""

import json
import os
import pathlib
import shutil
import subprocess
import sys

import pytest

from scalecube_cluster_tpu.telemetry import query
from scalecube_cluster_tpu.telemetry import sink as tsink
from scalecube_cluster_tpu.telemetry.__main__ import main as cli_main

pytestmark = pytest.mark.metrics

REPO = pathlib.Path(__file__).resolve().parent.parent


def write_manifest(path, windows, histograms=(), curve=None, summary=None):
    with tsink.TelemetrySink(path=str(path)) as sink:
        sink.write_manifest(params={"n": 8})
        for w in windows:
            sink.write_metrics_window(w)
        for name, edges, counts in histograms:
            sink.write_histogram(name, edges, counts)
        if curve is not None:
            sink.write_curve(*curve)
        if summary:
            sink.write_summary(**summary)
    return str(path)


def window(start, end, counters=None, gauges=None, hist=None):
    return {
        "round_start": start, "round_end": end,
        "counters": {"false_suspicion_onsets": 0,
                     "live_observer_rounds": (end - start) * 8,
                     **(counters or {})},
        "gauges": {"suspect_entries": 0.0, **(gauges or {})},
        "histograms": {"suspicion_lifetime_rounds": {
            "edges": [0, 4, 16], "counts": hist or [0, 0, 0]}},
    }


# --------------------------------------------------------------------------
# Loading, merging, SLOs
# --------------------------------------------------------------------------


def test_load_report_merges_windows(tmp_path):
    path = write_manifest(
        tmp_path / "a.jsonl",
        [window(0, 32, counters={"false_suspicion_onsets": 3},
                gauges={"suspect_entries": 5.0}, hist=[1, 2, 0]),
         window(32, 64, counters={"false_suspicion_onsets": 1},
                gauges={"suspect_entries": 2.0}, hist=[0, 1, 1])],
        histograms=[("detection_latency_rounds", [0, 2, 4], [0, 3, 1])],
    )
    r = query.load_report(path)
    assert r.rounds_covered == 64
    assert r.counters["false_suspicion_onsets"] == 4
    assert r.counters["live_observer_rounds"] == 64 * 8
    assert r.gauges["suspect_entries"] == 2.0          # last window wins
    assert r.histograms["suspicion_lifetime_rounds"][1] == [1, 3, 1]
    assert r.histograms["detection_latency_rounds"] == ([0, 2, 4],
                                                        [0, 3, 1])
    slos = query.compute_slos(r)
    assert slos["false_positive_observer_rate"] \
        == pytest.approx(4 / (64 * 8))
    assert slos["rounds_covered"] == 64


def test_trace_dropped_total_folds_additively(tmp_path):
    """Every events_footer closes one segment's trace buffer: the
    report folds the per-segment ``dropped`` counts ADDITIVELY into the
    ``trace_dropped_total`` counter lane, and compute_slos surfaces it
    first-class — a truncated event stream can't pass for a complete
    one.  Journals with no event stream at all read as None, not 0."""
    from scalecube_cluster_tpu.telemetry.events import (
        MembershipTraceEvent, TraceEventType)

    path = tmp_path / "a.jsonl"
    ev = MembershipTraceEvent(round=1, observer=0, subject=3,
                              event_type=TraceEventType.SUSPECTED,
                              incarnation=0)
    with tsink.TelemetrySink(path=str(path)) as sink:
        sink.write_manifest(params={"n": 8})
        sink.write_metrics_window(window(0, 32))
        sink.write_events([ev], dropped=3)       # segment 1
        sink.write_metrics_window(window(32, 64))
        sink.write_events([ev], dropped=2)       # segment 2
    r = query.load_report(str(path))
    assert r.counters["trace_dropped_total"] == 5
    assert query.compute_slos(r)["trace_dropped_total"] == 5

    clean = write_manifest(tmp_path / "clean.jsonl", [window(0, 32)])
    slos = query.compute_slos(query.load_report(clean))
    assert slos["trace_dropped_total"] is None


def test_percentile_from_histogram():
    # 10 samples in [0,4), 10 in [4,16): p50 = upper edge of bucket 0.
    assert query.percentile_from_histogram([0, 4, 16], [10, 10], 0.5) \
        == pytest.approx(4.0)
    # All mass in the OPEN last bucket clamps to its lower edge
    # (conservative, never understated).
    assert query.percentile_from_histogram([0, 4, 16], [0, 0, 7], 0.99) \
        == pytest.approx(16.0)
    assert query.percentile_from_histogram([0, 4], [0, 0], 0.5) is None


def test_incompatible_histogram_edges_raise(tmp_path):
    path = write_manifest(
        tmp_path / "a.jsonl",
        [window(0, 8)],
        histograms=[("suspicion_lifetime_rounds", [0, 8, 32], [1, 0, 0])],
    )
    with pytest.raises(ValueError, match="incompatible edges"):
        query.load_report(path)


def test_dissemination_from_curve(tmp_path):
    path = write_manifest(
        tmp_path / "a.jsonl", [window(0, 16)],
        curve=("fraction_informed", [0.0, 0.25, 0.75, 1.0, 1.0]),
    )
    r = query.load_report(path)
    assert query.compute_slos(r)["dissemination_rounds"] == 3


# --------------------------------------------------------------------------
# diff
# --------------------------------------------------------------------------


def test_diff_reports(tmp_path):
    a = query.load_report(write_manifest(
        tmp_path / "a.jsonl",
        [window(0, 32, counters={"false_suspicion_onsets": 4})]))
    b = query.load_report(write_manifest(
        tmp_path / "b.jsonl",
        [window(0, 32, counters={"false_suspicion_onsets": 8})]))
    rows = {r["metric"]: r for r in query.diff_reports(a, b)}
    row = rows["counter/false_suspicion_onsets"]
    assert (row["a"], row["b"], row["delta"]) == (4, 8, 4)
    assert row["rel"] == pytest.approx(1.0)
    slo = rows["slo/false_positive_observer_rate"]
    assert slo["b"] == pytest.approx(2 * slo["a"])


def test_cli_diff(tmp_path, capsys):
    a = write_manifest(tmp_path / "a.jsonl", [window(0, 32)])
    b = write_manifest(tmp_path / "b.jsonl",
                       [window(0, 32, counters={"fd_probes_sent": 5})])
    assert cli_main(["diff", a, b, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    rows = {r["metric"]: r for r in out["rows"]}
    assert rows["counter/fd_probes_sent"]["b"] == 5


def test_cli_report(tmp_path, capsys):
    path = write_manifest(
        tmp_path / "a.jsonl",
        [window(0, 32, counters={"false_suspicion_onsets": 2})])
    assert cli_main(["report", path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["slos"]["false_positive_observer_rate"] \
        == pytest.approx(2 / (32 * 8))
    assert out["counters"]["false_suspicion_onsets"] == 2


# --------------------------------------------------------------------------
# regress: the committed trajectory + the synthetic drop
# --------------------------------------------------------------------------


def committed_bench_paths():
    paths = sorted(str(p) for p in REPO.glob("BENCH_r0*.json"))
    assert len(paths) >= 5, "committed BENCH_r01..r05 series missing"
    return paths


def test_regress_passes_on_committed_trajectory():
    ok, rows = query.regress(committed_bench_paths())
    assert ok, rows
    # r01 is a failed run (rc=1): skipped with a note, never fatal.
    skipped = [r for r in rows if r.get("ok") is None]
    assert any("BENCH_r01" in r["source"] for r in skipped)
    checks = [r for r in rows if r.get("ok") is not None]
    assert any(r["check"].startswith("throughput/") for r in checks)
    assert all(r["ok"] for r in checks)


def synthetic_drop_dir(tmp_path, factor=0.8):
    for p in committed_bench_paths():
        shutil.copy(p, tmp_path)
    with open(tmp_path / "BENCH_r05.json") as f:
        last = json.load(f)
    payload = dict(last["parsed"])
    payload["value"] = round(payload["value"] * factor, 1)
    with open(tmp_path / "BENCH_r06.json", "w") as f:
        json.dump({"n": 6, "cmd": last["cmd"], "rc": 0, "tail": "",
                   "parsed": payload}, f)
    return sorted(str(p) for p in tmp_path.glob("BENCH_*.json"))


def test_regress_fails_on_synthetic_20pct_drop(tmp_path):
    ok, rows = query.regress(synthetic_drop_dir(tmp_path, factor=0.8))
    assert not ok
    bad = [r for r in rows if r.get("ok") is False]
    assert len(bad) == 1
    assert bad[0]["check"].startswith("throughput/")
    assert "BENCH_r06" in bad[0]["source"]


def test_regress_tolerates_drop_inside_noise_band(tmp_path):
    ok, rows = query.regress(synthetic_drop_dir(tmp_path, factor=0.95))
    assert ok, rows


def test_regress_overhead_ratio_gate(tmp_path):
    art = tmp_path / "BENCH_overhead.json"
    with open(art, "w") as f:
        json.dump({"metric": "traced_vs_untraced", "untraced": 100.0,
                   "traced": 80.0, "traced_overhead_ratio": 1.25}, f)
    ok, rows = query.regress([str(art)])
    assert not ok
    (bad,) = [r for r in rows if r.get("ok") is False]
    assert bad["check"] == "slo/traced_overhead_ratio"


def committed_multichip_paths():
    paths = sorted(str(p) for p in REPO.glob("MULTICHIP_r0*.json"))
    assert len(paths) >= 6, "committed MULTICHIP series missing"
    return paths


def test_regress_walks_multichip_trajectory():
    """BENCH + MULTICHIP gate in ONE walk: the legacy r01..r05 stubs
    ({"rc":0,"ok":true}, no throughput fields) skip as provenance, the
    real r06 artifact contributes the per-chip value and the
    pipelined-speedup floor check."""
    ok, rows = query.regress(committed_bench_paths()
                             + committed_multichip_paths())
    assert ok, rows
    stubs = [r for r in rows
             if r.get("ok") is None and r["check"] == "load"]
    assert sum(1 for r in stubs if "MULTICHIP_r0" in r["source"]) >= 5
    assert all("stub" in r["note"] for r in stubs
               if "MULTICHIP_r0" in r["source"])
    checks = [r for r in rows if r.get("ok") is not None]
    (speedup,) = [r for r in checks
                  if r["check"] == "slo/pipelined_speedup_ratio"]
    assert speedup["ok"] and "MULTICHIP_r06" in speedup["source"]


def test_regress_multichip_throughput_drop_fails(tmp_path):
    """A future multichip round regressing per-chip throughput beyond
    the band fails loudly — same trajectory discipline as BENCH."""
    for p in committed_multichip_paths():
        shutil.copy(p, tmp_path)
    with open(tmp_path / "MULTICHIP_r06.json") as f:
        real = json.load(f)
    # Future real rounds are non-smoke (driver bench on the pinned
    # host) — only those form the throughput trajectory.
    real.pop("smoke", None)
    with open(tmp_path / "MULTICHIP_r06.json", "w") as f:
        json.dump(real, f)
    worse = dict(real, value=round(real["value"] * 0.8, 1))
    with open(tmp_path / "MULTICHIP_r07.json", "w") as f:
        json.dump(worse, f)
    ok, rows = query.regress(
        sorted(str(p) for p in tmp_path.glob("MULTICHIP_*.json")))
    assert not ok
    bad = [r for r in rows if r.get("ok") is False]
    assert len(bad) == 1
    assert bad[0]["check"].startswith("throughput/swim_multichip")
    assert "MULTICHIP_r07" in bad[0]["source"]


def test_regress_smoke_rounds_skip_throughput_gate(tmp_path):
    """A smoke round's absolute rate reflects whatever host/load ran
    it — it neither gates nor anchors the throughput trajectory
    (skipped provenance row), while its machine-independent ratio
    checks still run.  This is what keeps bench --multichip --smoke's
    in-bench gate green on a loaded or differently-sized CI box."""
    base = {"metric": "swim_multichip_member_rounds_per_sec_per_chip"}
    with open(tmp_path / "MULTICHIP_r06.json", "w") as f:
        json.dump(dict(base, value=100.0), f)
    with open(tmp_path / "MULTICHIP_r07.json", "w") as f:
        json.dump(dict(base, value=50.0, smoke=True,
                       pipelined_speedup_ratio=1.2), f)
    ok, rows = query.regress(
        sorted(str(p) for p in tmp_path.glob("MULTICHIP_*.json")))
    assert ok, rows   # the 2x throughput drop is a smoke round: skipped
    (skip,) = [r for r in rows if r.get("ok") is None]
    assert "MULTICHIP_r07" in skip["source"] and "smoke" in skip["note"]
    (speedup,) = [r for r in rows
                  if r["check"] == "slo/pipelined_speedup_ratio"]
    assert speedup["ok"] and "MULTICHIP_r07" in speedup["source"]
    # A non-smoke round with the same drop DOES gate.
    with open(tmp_path / "MULTICHIP_r08.json", "w") as f:
        json.dump(dict(base, value=50.0), f)
    ok, rows = query.regress(
        sorted(str(p) for p in tmp_path.glob("MULTICHIP_*.json")))
    assert not ok
    (bad,) = [r for r in rows if r.get("ok") is False]
    assert "MULTICHIP_r08" in bad["source"]


def test_regress_orders_by_basename_not_directory(tmp_path):
    """bench.py gates the artifact it just wrote by (often absolute,
    tmp-dir) path: round order must come from the FILENAME, or the
    fresh round would sort before the committed ones and be compared
    as a prior instead of as the latest."""
    sub = tmp_path / "aaa-sorts-first"
    sub.mkdir()
    base = {"metric": "swim_multichip_member_rounds_per_sec_per_chip"}
    with open(tmp_path / "MULTICHIP_r06.json", "w") as f:
        json.dump(dict(base, value=100.0), f)
    with open(sub / "MULTICHIP_r07.json", "w") as f:
        json.dump(dict(base, value=50.0), f)
    ok, rows = query.regress([str(tmp_path / "MULTICHIP_r06.json"),
                              str(sub / "MULTICHIP_r07.json")])
    assert not ok
    (bad,) = [r for r in rows if r.get("ok") is False]
    assert "MULTICHIP_r07" in bad["source"], rows


def test_regress_pipelined_speedup_floor(tmp_path):
    """pipelined/serial below 1 - band = the pipeline costs throughput
    somewhere — gate it like the overhead ratios, direction flipped."""
    art = tmp_path / "MULTICHIP_slow.json"
    with open(art, "w") as f:
        json.dump({"metric": "swim_multichip_member_rounds_per_sec_per_chip",
                   "value": 100.0, "pipelined_speedup_ratio": 0.85}, f)
    ok, rows = query.regress([str(art)])
    assert not ok
    (bad,) = [r for r in rows if r.get("ok") is False]
    assert bad["check"] == "slo/pipelined_speedup_ratio"
    ok, rows = query.regress([str(art)], band=0.2)  # inside a wider band
    assert ok, rows


def _wire_payload(**overrides):
    payload = {
        "metric": "swim_wire_fused_member_rounds_per_sec_per_chip",
        "value": 95375.8,
        "fused_serial_speedup_ratio": 1.356,
        "fused_pipelined_speedup_ratio": 1.5217,
        "pipelined_serial_parity": {"fused": True, "legacy": True},
        "hlo_full_height_collectives": {"fused": 1, "legacy": 2},
        "wire_collectives_per_round": {"fused": 1, "legacy": 2},
        "wire_bytes_per_slot": {"fused": 4, "legacy": 5},
        "shift_accounting_unchanged": True,
    }
    payload.update(overrides)
    return payload


def test_regress_wire_fused_gates(tmp_path):
    """The --wire artifact's gates: fused >= legacy on BOTH run shapes
    (absolute 1.0 floor), the 4-vs-5 B/slot and 1-vs-2 collective
    models pinned exactly, HLO counts gated when recorded and
    provenance when null."""
    art = tmp_path / "wire_fused.json"
    with open(art, "w") as f:
        json.dump(_wire_payload(), f)
    ok, rows = query.regress([str(art)])
    assert ok, rows
    checks = {r["check"] for r in rows if r.get("ok") is not None}
    assert {"slo/fused_serial_speedup_ratio",
            "slo/fused_pipelined_speedup_ratio",
            "slo/wire_fused_bytes_per_slot",
            "slo/wire_fused_collectives_per_round",
            "slo/wire_hlo_fused_single_collective",
            "slo/wire_shift_accounting_unchanged",
            "slo/wire_pipelined_serial_parity"} <= checks

    # A fused wire that runs SLOWER than the two-buffer HEAD fails the
    # absolute floor — no band: the committed win must not rot.
    with open(art, "w") as f:
        json.dump(_wire_payload(fused_pipelined_speedup_ratio=0.97), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    (bad,) = [r for r in rows if r.get("ok") is False]
    assert bad["check"] == "slo/fused_pipelined_speedup_ratio"

    # A second collective sneaking back into the fused program fails
    # the absolute instruction pin.
    with open(art, "w") as f:
        json.dump(_wire_payload(
            hlo_full_height_collectives={"fused": 2, "legacy": 2}), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/wire_hlo_fused_single_collective"
               for r in rows if r.get("ok") is False)

    # Null HLO counts (unparseable lowering) are provenance, not a
    # failure.
    with open(art, "w") as f:
        json.dump(_wire_payload(hlo_full_height_collectives=None), f)
    ok, rows = query.regress([str(art)])
    assert ok, rows
    assert any(r["check"] == "slo/wire_hlo_fused_single_collective"
               and r.get("ok") is None for r in rows)


def test_regress_wire_smoke_is_provenance_beside_full_round(tmp_path):
    """The sync-heal rule for --wire: a smoke artifact beside a full
    round is provenance; alone it gates itself."""
    full = tmp_path / "wire_fused.json"
    smoke = tmp_path / "wire_fused_smoke.json"
    with open(full, "w") as f:
        json.dump(_wire_payload(), f)
    with open(smoke, "w") as f:
        json.dump(_wire_payload(smoke=True,
                                fused_serial_speedup_ratio=0.8), f)
    # Beside the full round the failing smoke ratio must NOT gate.
    ok, rows = query.regress([str(full), str(smoke)])
    assert ok, rows
    assert any(r["check"] == "slo/wire_fused" and r.get("ok") is None
               for r in rows)
    # Alone, the smoke round gates itself and the bad ratio bites.
    ok, rows = query.regress([str(smoke)])
    assert not ok


def _compose_payload(**overrides):
    payload = {
        "metric": "swim_compose_full_stack_member_rounds_per_sec",
        "value": 702646.3,
        "compose_speedup_ratio": 2.8489,
        "full_stack_overhead_ratio": 0.8365,
        "head_style_overhead_ratio": 2.3831,
        "parity": {"final_status": True, "trace_lanes": True,
                   "trace_count": True, "monitor_code_counts": True,
                   "metrics_counters": True},
        "compile": {"programs_head_style": 6, "programs_composed": 2},
    }
    payload.update(overrides)
    return payload


def test_regress_compose_gates(tmp_path):
    """The --compose artifact's gates: the one-scan full stack at least
    matches the alias-by-alias route (absolute 1.0 floor), the
    composed overhead stays within the band of head-style's, the
    compile count is STRICTLY reduced, and the alias-parity probe was
    green."""
    art = tmp_path / "compose_perf.json"
    with open(art, "w") as f:
        json.dump(_compose_payload(), f)
    ok, rows = query.regress([str(art)])
    assert ok, rows
    checks = {r["check"] for r in rows if r.get("ok") is not None}
    assert {"slo/compose_speedup_ratio",
            "slo/compose_full_stack_overhead",
            "slo/compose_compile_count_reduced",
            "slo/compose_alias_parity"} <= checks

    # A composed stack slower than three sequential alias runs fails
    # the absolute floor — no band: one scan losing to three is rot.
    with open(art, "w") as f:
        json.dump(_compose_payload(compose_speedup_ratio=0.93), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/compose_speedup_ratio"
               for r in rows if r.get("ok") is False)

    # Composed overhead drifting past head-style's (beyond the band)
    # fails — the shared round context must keep paying for itself.
    with open(art, "w") as f:
        json.dump(_compose_payload(full_stack_overhead_ratio=3.1), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/compose_full_stack_overhead"
               for r in rows if r.get("ok") is False)

    # The compile matrix must stay STRICTLY reduced: head-style and
    # composed compiling the same program count means the one-program
    # claim rotted.
    with open(art, "w") as f:
        json.dump(_compose_payload(
            compile={"programs_head_style": 6, "programs_composed": 6}), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/compose_compile_count_reduced"
               for r in rows if r.get("ok") is False)

    # A failed parity lane is a correctness gate, not noise.
    with open(art, "w") as f:
        json.dump(_compose_payload(
            parity={"final_status": True, "trace_lanes": False}), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/compose_alias_parity"
               for r in rows if r.get("ok") is False)

    # The ratio gates apply to smoke rounds too (the
    # metrics_overhead_ratio convention: same-host interleaved ratios
    # are machine-independent) — a smoke round with a bad ratio bites.
    with open(art, "w") as f:
        json.dump(_compose_payload(smoke=True,
                                   compose_speedup_ratio=0.9), f)
    ok, rows = query.regress([str(art)])
    assert not ok


def test_regress_static_analysis_gate(tmp_path):
    """The swimlint artifact gates ABSOLUTELY: findings_total > 0 (an
    unsuppressed static-analysis finding — a plane missing from a run
    shape, a red compile audit) fails regress outright; baselined
    suppressions (suppressed_total) never gate."""
    art = tmp_path / "static_analysis.json"

    def payload(**kw):
        doc = {"schema": "swimlint/1", "metric": "static_analysis",
               "findings_total": 0, "suppressed_total": 12, "ok": True,
               "findings": []}
        doc.update(kw)
        return doc

    with open(art, "w") as f:
        json.dump(payload(), f)
    ok, rows = query.regress([str(art)])
    assert ok, rows
    checks = {r["check"] for r in rows if r.get("ok") is not None}
    assert {"slo/static_analysis_clean",
            "slo/static_analysis_ok"} <= checks

    with open(art, "w") as f:
        json.dump(payload(findings_total=2, ok=False), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    bad = {r["check"] for r in rows if r.get("ok") is False}
    assert "slo/static_analysis_clean" in bad


_TUNE_OBJS = ["false_positive_observer_rate",
              "detection_latency_p99_rounds",
              "removal_latency_p99_rounds",
              "wire_bytes_per_member_round"]
_TUNE_REF = {"false_positive_observer_rate": 0.30,
             "detection_latency_p99_rounds": 30.0,
             "removal_latency_p99_rounds": 44.0,
             "wire_bytes_per_member_round": 120.0}


def _tune_profile(target, **slo_overrides):
    slos = dict(_TUNE_REF)
    slos.update(slo_overrides)
    return {"target": target, "slos": slos, "fuzz_green": True}


def _tune_payload(**overrides):
    payload = {
        "metric": "tune_pareto",
        "value": None,
        "smoke": False,
        "batch_speedup_ratio": 12.5,
        "objectives": list(_TUNE_OBJS),
        "reference_slos": dict(_TUNE_REF),
        "profiles": {
            "fast-detect": _tune_profile(
                "detection_latency_p99_rounds",
                detection_latency_p99_rounds=16.0,
                wire_bytes_per_member_round=190.0),
            "low-traffic": _tune_profile(
                "wire_bytes_per_member_round",
                wire_bytes_per_member_round=70.0,
                detection_latency_p99_rounds=48.0),
        },
    }
    payload.update(overrides)
    return payload


def test_regress_tune_gates(tmp_path):
    """The --tune artifact's gates: the traced-knob grid sweep at least
    matches the static recompile-per-config counterfactual (absolute
    1.0 floor), >= 2 named profiles ship, every profile is
    Pareto-non-dominated by the reference (dominance RECOMPUTED from
    the payload's SLO rows) and fuzz-oracle green on held-out seeds."""
    art = tmp_path / "tune_pareto.json"
    with open(art, "w") as f:
        json.dump(_tune_payload(), f)
    ok, rows = query.regress([str(art)])
    assert ok, rows
    checks = {r["check"] for r in rows if r.get("ok") is not None}
    assert {"slo/tune_batch_speedup", "slo/tune_profiles_shipped",
            "slo/tune_profiles_nondominated",
            "slo/tune_profiles_fuzz_green"} <= checks

    # The dynamic sweep losing to per-config recompilation is the
    # tentpole claim rotting — absolute floor, no noise band.
    with open(art, "w") as f:
        json.dump(_tune_payload(batch_speedup_ratio=0.9), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/tune_batch_speedup"
               for r in rows if r.get("ok") is False)
    # ... and a missing ratio fails the same gate, never passes it.
    with open(art, "w") as f:
        json.dump(_tune_payload(batch_speedup_ratio=None), f)
    ok, rows = query.regress([str(art)])
    assert not ok

    # Fewer than two shipped profiles is not a tuned-defaults release.
    with open(art, "w") as f:
        json.dump(_tune_payload(profiles={
            "fast-detect": _tune_profile(
                "detection_latency_p99_rounds",
                detection_latency_p99_rounds=16.0)}), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/tune_profiles_shipped"
               for r in rows if r.get("ok") is False)

    # A profile the reference Pareto-dominates (worse on one objective,
    # no better anywhere) fails — recomputed here, not trusted from
    # the writer's nondominated_vs_reference flag.
    dominated = dict(_tune_payload()["profiles"])
    dominated["low-traffic"] = _tune_profile(
        "wire_bytes_per_member_round",
        wire_bytes_per_member_round=150.0)
    with open(art, "w") as f:
        json.dump(_tune_payload(profiles=dominated), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/tune_profiles_nondominated"
               for r in rows if r.get("ok") is False)

    # An SLO row missing an objective can't prove non-dominance.
    incomplete = dict(_tune_payload()["profiles"])
    del incomplete["low-traffic"]["slos"]["removal_latency_p99_rounds"]
    with open(art, "w") as f:
        json.dump(_tune_payload(profiles=incomplete), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/tune_profiles_nondominated"
               for r in rows if r.get("ok") is False)

    # The held-out fuzz oracle is a correctness gate: False or missing
    # both fail (only an explicit True passes).
    for fg in (False, None):
        flaky = dict(_tune_payload()["profiles"])
        flaky["fast-detect"] = dict(flaky["fast-detect"], fuzz_green=fg)
        with open(art, "w") as f:
            json.dump(_tune_payload(profiles=flaky), f)
        ok, rows = query.regress([str(art)])
        assert not ok, fg
        assert any(r["check"] == "slo/tune_profiles_fuzz_green"
                   for r in rows if r.get("ok") is False)


def test_regress_tune_smoke_is_provenance_beside_full_round(tmp_path):
    """A smoke tune sweep beside a full round is provenance (ok=None
    note row); alone it gates itself — the sync-heal fallback rule, so
    ``--tune --smoke``'s in-bench check of its own artifact bites."""
    full = tmp_path / "tune_pareto.json"
    smoke = tmp_path / "tune_pareto_smoke.json"
    with open(full, "w") as f:
        json.dump(_tune_payload(), f)
    with open(smoke, "w") as f:
        json.dump(_tune_payload(smoke=True, batch_speedup_ratio=0.7), f)
    # Beside the full round the failing smoke ratio must NOT gate.
    ok, rows = query.regress([str(full), str(smoke)])
    assert ok, rows
    assert any(r["check"] == "slo/tune_pareto" and r.get("ok") is None
               for r in rows)
    gated = [r for r in rows if r["check"] == "slo/tune_batch_speedup"]
    assert gated and all(r["source"] == "tune_pareto.json"
                         for r in gated)
    # Alone, the smoke round gates itself and the bad ratio bites.
    ok, rows = query.regress([str(smoke)])
    assert not ok
    assert any(r["check"] == "slo/tune_batch_speedup"
               for r in rows if r.get("ok") is False)


def test_load_bench_payload_accepts_tune_artifact(tmp_path):
    """A tune artifact is a real measurement payload (ratio-bearing,
    ``value: null`` by design) — never skipped as a stub."""
    art = tmp_path / "tune_pareto.json"
    with open(art, "w") as f:
        json.dump(_tune_payload(), f)
    payload, note = query.load_bench_payload(str(art))
    assert note is None
    assert payload["batch_speedup_ratio"] == 12.5


def _soak_report_payload(**overrides):
    payload = {
        "metric": "soak_rounds_survived", "value": None,
        "rounds_survived": 2048, "segments": 8, "segment_rounds": 256,
        "violations": 0,
        "drift": {"ok": True, "compile_flat": True,
                  "cache_sizes": [1] * 8, "rss_bounded": True,
                  "rss_growth_mb": 4.0, "violations": 0,
                  "monitor_green": True, "segments_sampled": 8},
        "kill_drill": {"ok": True, "journal_match": True,
                       "state_match": True, "content_rows": 16},
        "alarms": {"quiet": True, "transitions": 0},
    }
    payload.update(overrides)
    return payload


def test_regress_soak_gates(tmp_path):
    """The --soak artifact's ABSOLUTE gates: zero violations over the
    whole lifetime, compile cache flat after segment 1, RSS bounded,
    the SIGKILL/relaunch drill exactly-once (byte-identical journal +
    state digest), the live alarm engine quiet."""
    art = tmp_path / "soak_report.json"
    with open(art, "w") as f:
        json.dump(_soak_report_payload(), f)
    ok, rows = query.regress([str(art)])
    assert ok, rows
    checks = {r["check"] for r in rows if r.get("ok") is not None}
    assert {"slo/soak_violations", "slo/soak_compile_flat",
            "slo/soak_rss_bounded", "slo/soak_kill_exactly_once",
            "slo/soak_alarms_quiet"} <= checks

    # One monitor violation anywhere in the soak is a failed release.
    with open(art, "w") as f:
        json.dump(_soak_report_payload(
            violations=1,
            drift=dict(_soak_report_payload()["drift"],
                       violations=1, monitor_green=False,
                       ok=False)), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/soak_violations"
               for r in rows if r.get("ok") is False)

    # A recompile after segment 1 is a drift leak, not noise.
    with open(art, "w") as f:
        json.dump(_soak_report_payload(
            drift=dict(_soak_report_payload()["drift"],
                       compile_flat=False, cache_sizes=[1, 1, 2],
                       ok=False)), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/soak_compile_flat"
               for r in rows if r.get("ok") is False)
    # ... and an empty probe trace can't prove flatness (only an
    # explicit True with at least one sample passes).
    with open(art, "w") as f:
        json.dump(_soak_report_payload(
            drift=dict(_soak_report_payload()["drift"],
                       cache_sizes=[])), f)
    ok, rows = query.regress([str(art)])
    assert not ok

    # Unbounded host RSS fails even with the scan itself green.
    with open(art, "w") as f:
        json.dump(_soak_report_payload(
            drift=dict(_soak_report_payload()["drift"],
                       rss_bounded=False, rss_growth_mb=900.0,
                       ok=False)), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/soak_rss_bounded"
               for r in rows if r.get("ok") is False)

    # The kill drill diverging — journal OR state — fails; so does a
    # report that never ran the drill (missing block is not a pass).
    for drill in ({"ok": False, "journal_match": False,
                   "state_match": True},
                  {"ok": False, "journal_match": True,
                   "state_match": False},
                  None):
        doc = _soak_report_payload()
        if drill is None:
            del doc["kill_drill"]
        else:
            doc["kill_drill"] = drill
        with open(art, "w") as f:
            json.dump(doc, f)
        ok, rows = query.regress([str(art)])
        assert not ok, drill
        assert any(r["check"] == "slo/soak_kill_exactly_once"
                   for r in rows if r.get("ok") is False)

    # An alarm transition during the soak means the SLO engine saw a
    # breach the drift verdict didn't — never quiet-pass it.
    with open(art, "w") as f:
        json.dump(_soak_report_payload(
            alarms={"quiet": False, "transitions": 2}), f)
    ok, rows = query.regress([str(art)])
    assert not ok
    assert any(r["check"] == "slo/soak_alarms_quiet"
               for r in rows if r.get("ok") is False)


def test_load_bench_payload_accepts_soak_artifact(tmp_path):
    """A soak report is a real measurement payload (gate-bearing,
    ``value: null`` by design) — never skipped as a stub."""
    art = tmp_path / "soak_report.json"
    with open(art, "w") as f:
        json.dump(_soak_report_payload(), f)
    payload, note = query.load_bench_payload(str(art))
    assert note is None
    assert payload["rounds_survived"] == 2048


def _rollout_payload(**overrides):
    payload = {
        "metric": "config_rollout_convergence", "value": None,
        "metadata_convergence_p99": 12.0, "rollout_converged": True,
        "rolled_back": False, "convergence_deadline_rounds": 58,
        "final_divergent_cells": 0, "control_divergent_cells": 24,
        "control_converged": False, "monitored_green": True,
        "monitor_violations": 0, "n_members": 48, "metadata_keys": 1,
        "n_stages": 3, "stage_size": 4, "sync_interval": 8,
    }
    payload.update(overrides)
    return payload


def test_regress_rollout_gates(tmp_path):
    """The --rollout artifact's ABSOLUTE gates: converged inside the
    deadline with no rollback, the gossip-only control still
    divergent, zero monitor violations — the committed claim cannot
    silently rot."""
    art = tmp_path / "config_rollout.json"
    with open(art, "w") as f:
        json.dump(_rollout_payload(), f)
    ok, rows = query.regress([str(art)])
    assert ok, rows
    checks = {r["check"] for r in rows if r.get("ok") is not None}
    assert {"slo/rollout_converged", "slo/rollout_not_rolled_back",
            "slo/rollout_control_diverges",
            "slo/metadata_convergence_p99_within_bound",
            "slo/rollout_monitor_violations"} <= checks

    bad_cases = [
        ("slo/rollout_converged",
         dict(rollout_converged=False, final_divergent_cells=3)),
        ("slo/rollout_not_rolled_back", dict(rolled_back=True)),
        ("slo/rollout_control_diverges",
         dict(control_converged=True, control_divergent_cells=0)),
        ("slo/metadata_convergence_p99_within_bound",
         dict(metadata_convergence_p99=200.0)),
        ("slo/rollout_monitor_violations", dict(monitor_violations=2)),
    ]
    for check_name, overrides in bad_cases:
        with open(art, "w") as f:
            json.dump(_rollout_payload(**overrides), f)
        ok, rows = query.regress([str(art)])
        assert not ok, check_name
        assert any(r["check"] == check_name
                   for r in rows if r.get("ok") is False), check_name


def test_regress_bands_rollout_convergence_series(tmp_path):
    """The p99 series gates within the band, floored at one exchange
    interval (phase luck must not make a lucky prior a knife edge)."""
    def art(path, p99):
        path.write_text(json.dumps(_rollout_payload(
            metadata_convergence_p99=p99)))
        return str(path)

    a = art(tmp_path / "config_rollout_r01.json", 1.0)   # lucky phase
    ok, _ = query.regress(
        [a, art(tmp_path / "config_rollout_r02.json", 9.0)])
    assert ok                                            # inside floor
    ok, rows = query.regress(
        [a, art(tmp_path / "config_rollout_r03.json", 120.0)])
    assert not ok
    assert any(r["check"] == "slo/metadata_convergence_p99"
               and r["ok"] is False for r in rows)


def test_regress_rollout_smoke_is_provenance_beside_full_round(
        tmp_path):
    """A smoke rollout next to a full round is provenance only — but a
    walk holding ONLY smoke rounds still gates them (the sync-heal
    fallback rule, so `--rollout --smoke`'s in-bench check bites)."""
    smoke = tmp_path / "config_rollout_smoke.json"
    smoke.write_text(json.dumps(_rollout_payload(
        smoke=True, rollout_converged=False, rolled_back=True,
        monitor_violations=9)))
    full = tmp_path / "config_rollout.json"
    full.write_text(json.dumps(_rollout_payload()))
    ok, rows = query.regress([str(smoke), str(full)])
    assert ok, rows          # the red smoke round is provenance only
    assert any(r["check"] == "slo/config_rollout" and r["ok"] is None
               for r in rows)
    # smoke-only walk: the gates bite the smoke round itself
    ok, rows = query.regress([str(smoke)])
    assert not ok
    assert any(r["check"] == "slo/rollout_converged"
               and r["ok"] is False for r in rows)


def test_load_bench_payload_accepts_rollout_artifact(tmp_path):
    art = tmp_path / "config_rollout.json"
    with open(art, "w") as f:
        json.dump(_rollout_payload(), f)
    payload, note = query.load_bench_payload(str(art))
    assert note is None
    assert payload["rollout_converged"] is True


def test_cli_regress_default_globs_include_rollout(tmp_path, capsys,
                                                   monkeypatch):
    """Bare ``regress`` walks artifacts/config_rollout*.json — the
    committed rollout round passes its absolute gates."""
    monkeypatch.chdir(REPO)
    assert cli_main(["regress", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is True
    ro_rows = [r for r in out["checks"]
               if r.get("source", "").startswith("config_rollout")]
    assert any(r["check"] == "slo/rollout_converged"
               and r.get("ok") is True for r in ro_rows)


def test_cli_regress_default_globs_include_soak(tmp_path, capsys,
                                                monkeypatch):
    """Bare ``regress`` walks artifacts/soak_report*.json — the
    committed soak round passes its absolute gates."""
    monkeypatch.chdir(REPO)
    assert cli_main(["regress", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is True
    soak_rows = [r for r in out["checks"]
                 if r.get("source", "").startswith("soak_report")]
    assert any(r["check"] == "slo/soak_kill_exactly_once"
               and r.get("ok") is True for r in soak_rows)


def test_cli_regress_default_globs_include_static_analysis(
        tmp_path, capsys, monkeypatch):
    """Bare ``regress`` walks artifacts/static_analysis.json — the
    committed swimlint round passes its absolute gate."""
    monkeypatch.chdir(REPO)
    assert cli_main(["regress", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is True
    assert any(r.get("source") == "static_analysis.json"
               and r["check"] == "slo/static_analysis_clean"
               and r.get("ok") is True
               for r in out["checks"])


def test_cli_regress_default_globs_include_multichip(tmp_path, capsys,
                                                     monkeypatch):
    """Bare ``regress`` walks BENCH_*.json AND MULTICHIP_*.json from
    the working directory — the committed repo trajectory passes."""
    monkeypatch.chdir(REPO)
    assert cli_main(["regress", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is True
    sources = {r.get("source") for r in out["checks"]}
    assert any(s and s.startswith("MULTICHIP_") for s in sources)
    assert any(s and s.startswith("BENCH_") for s in sources)


def test_cli_regress_exit_codes(tmp_path, capsys):
    assert cli_main(["regress", str(REPO / "BENCH_r0*.json")]) == 0
    capsys.readouterr()
    synthetic_drop_dir(tmp_path)
    assert cli_main(["regress", str(tmp_path / "BENCH_*.json"),
                     "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is False
    assert cli_main(["regress", str(tmp_path / "no_such_*.json")]) == 2


def test_cli_module_entry_point(tmp_path):
    """python -m scalecube_cluster_tpu.telemetry really resolves (the
    CLI contract the README documents)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "scalecube_cluster_tpu.telemetry",
         "regress", "BENCH_r0*.json", "--json"],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout)
    assert out["ok"] is True

# --------------------------------------------------------------------------
# Degraded inputs: torn journals, overlapping merges, one-sided diffs
# --------------------------------------------------------------------------


def test_report_degrades_on_torn_trailing_window(tmp_path, capsys):
    """A journal whose writer was SIGKILLed mid-``metrics_window`` line
    still reports: the torn trailing record is skipped WITH a warning
    (never silently, never a crash) and the durable prefix carries the
    SLOs — exit code 0, the CI-stable contract."""
    path = write_manifest(
        tmp_path / "torn.jsonl",
        [window(0, 32, counters={"false_suspicion_onsets": 2}),
         window(32, 64, counters={"false_suspicion_onsets": 1})])
    with open(path, "a") as f:      # half a window row, no newline
        f.write('{"kind": "metrics_window", "round_start": 64, '
                '"round_end": 96, "counters": {"false_susp')
    with pytest.warns(UserWarning, match="torn trailing"):
        assert cli_main(["report", path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    # The durable prefix only: the torn window's rounds/counters are
    # not in the fold.
    assert out["slos"]["rounds_covered"] == 64
    assert out["counters"]["false_suspicion_onsets"] == 3
    assert out["slos"]["false_positive_observer_rate"] \
        == pytest.approx(3 / (64 * 8))
    # Interior corruption stays a hard input error (exit 2): a
    # terminated-but-unparseable line cannot come from a torn write.
    bad = tmp_path / "corrupt.jsonl"
    bad.write_text("not json at all\n")
    assert cli_main(["report", str(bad)]) == 2
    capsys.readouterr()


def test_merge_reports_overlapping_out_of_order_windows(tmp_path):
    """Merging runs whose windows overlap and arrive out of round
    order: counters stay plain sums (window totals — the defined
    semantics, double-counted rounds and all), every raw window
    survives for time-resolved rendering, and rounds_covered is the
    max round_end, not the concatenation order's last."""
    a = query.load_report(write_manifest(
        tmp_path / "a.jsonl",
        [window(32, 64, counters={"false_suspicion_onsets": 1},
                gauges={"suspect_entries": 5.0}),
         window(0, 32, counters={"false_suspicion_onsets": 2})]))
    b = query.load_report(write_manifest(
        tmp_path / "b.jsonl",
        [window(16, 48, counters={"false_suspicion_onsets": 4},
                gauges={"suspect_entries": 7.0})]))
    merged = query.merge_reports([a, b])
    assert merged.counters["false_suspicion_onsets"] == 7
    assert merged.counters["live_observer_rounds"] == (64 + 32) * 8
    assert len(merged.windows) == 3
    assert merged.rounds_covered == 64          # max end, order-proof
    assert merged.gauges["suspect_entries"] == 7.0   # last report wins
    slos = query.compute_slos(merged)
    assert slos["false_positive_observer_rate"] \
        == pytest.approx(7 / ((64 + 32) * 8))
    # And the CLI multi-manifest path folds the same way.
    assert cli_main(["report", a.path, b.path, "--json"]) == 0


def test_diff_reports_one_sided_keys(tmp_path):
    """A metric present in only one run must diff as a row with the
    missing side None and delta/rel None — never a KeyError, never a
    fabricated zero."""
    a = query.load_report(write_manifest(
        tmp_path / "a.jsonl",
        [window(0, 32, counters={"fd_probes_sent": 5})]))
    b = query.load_report(write_manifest(
        tmp_path / "b.jsonl",
        [window(0, 32)],
        summary={"sync_rounds_to_converge": 9}))
    rows = {r["metric"]: r for r in query.diff_reports(a, b)}
    one_sided = rows["counter/fd_probes_sent"]
    assert (one_sided["a"], one_sided["b"]) == (5, None)
    assert one_sided["delta"] is None and one_sided["rel"] is None
    slo = rows["slo/sync_rounds_to_converge"]
    assert (slo["a"], slo["b"]) == (None, 9)
    assert slo["delta"] is None and slo["rel"] is None
    # Symmetric direction: b-only keys diff against a None a-side too.
    back = {r["metric"]: r for r in query.diff_reports(b, a)}
    assert (back["counter/fd_probes_sent"]["a"],
            back["counter/fd_probes_sent"]["b"]) == (None, 5)
