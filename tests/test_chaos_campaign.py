"""Campaign runner + the bench --chaos --smoke contract.

Tier-1 keeps a mini campaign (one scenario per severity tier, small N)
plus the ``bench.py --chaos --smoke`` subprocess pin (the --smoke
contract style of tests/test_bench_smoke.py, shrunk further through the
documented env overrides to stay tier-1-safe).  The full >= 20-scenario
acceptance campaign runs under the ``slow`` marker (and in CI-adjacent
sweeps via ``experiments/chaos_campaign.py`` / ``bench.py --chaos``).
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

from scalecube_cluster_tpu.chaos import campaign as cc
from scalecube_cluster_tpu.chaos import monitor as cm
from scalecube_cluster_tpu.chaos import scenarios as cs
from scalecube_cluster_tpu.telemetry import sink as tsink

pytestmark = pytest.mark.chaos

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_mini_campaign_green_with_manifest(tmp_path):
    """One generated scenario per severity tier runs green through the
    monitored scan, and the JSONL manifest round-trips: manifest header,
    one chaos_scenario row per scenario (verdict + repro), closing
    chaos_verdict summary."""
    scens = [cs.generate_scenario(seed=100 + i, n=24, severity=sev)
             for i, sev in enumerate(cs.SEVERITIES)]
    with tsink.TelemetrySink(str(tmp_path), prefix="chaos") as sink:
        result = cc.run_campaign(scens, seed=0, sink=sink)

    assert result.green, result.summary()
    summary = result.summary()
    assert summary["scenarios"] == 3
    assert summary["green_scenarios"] == 3
    assert summary["failing_repros"] == []
    assert set(summary["violations_by_code"]) \
        == {c.name for c in cm.InvariantCode}
    assert all(v == 0 for v in summary["violations_by_code"].values())

    rows = tsink.read_records(result.manifest_path, kind="chaos_scenario")
    assert len(rows) == 3
    for row, scen in zip(rows, scens):
        assert row["name"] == scen.name
        assert row["green"] is True
        assert scen.repro() in row["repro"]     # + run seed & delivery
        assert f"severity={scen.severity!r}" in row["repro"]
        assert row["verdict"]["total_violations"] == 0
        assert row["counters"]["messages_gossip"] > 0
    (verdict_row,) = tsink.read_records(result.manifest_path,
                                        kind="chaos_verdict")
    assert verdict_row["green"] is True
    (manifest,) = tsink.read_records(result.manifest_path, kind="manifest")
    assert manifest["config_digest"]
    assert manifest["workload"]["kind"] == "chaos_campaign"
    assert manifest["workload"]["scenarios"] == 3


def test_red_scenario_reports_instead_of_failing(tmp_path):
    """Graceful degradation end-to-end: a campaign containing a broken
    scenario (completeness promised absurdly early) COMPLETES, writes
    the red verdict row with evidence, and names the repro."""
    good = cs.generate_scenario(seed=100, n=24, severity="mild")
    # Hand-broken: a permanent crash whose completeness deadline is
    # pulled (negative extra_slack) to 2 rounds after the crash —
    # before the protocol can possibly detect + time out the fault.
    broken = cs.Scenario(
        name="broken-deadline", n_members=24, horizon=192,
        ops=(cs.Crash(3, at_round=5),),
        extra_slack=-cs.completeness_bound(
            cc.campaign_params(good), 24) + 2,
    )
    scens = [good, broken]
    with tsink.TelemetrySink(str(tmp_path), prefix="chaos") as sink:
        result = cc.run_campaign(scens, seed=0, sink=sink)
    assert not result.green
    summary = result.summary()
    assert summary["green_scenarios"] >= 1
    assert summary["violations_by_code"]["COMPLETENESS"] > 0
    # The repro line names the scenario AND the run seed (seed 0 + index
    # 1): violations depend on the PRNG stream, so the full line is
    # what reproduces.
    (repro,) = summary["failing_repros"]
    assert broken.repro() in repro and "seed=1" in repro
    red_rows = [r for r in tsink.read_records(result.manifest_path,
                                              kind="chaos_scenario")
                if not r["green"]]
    assert red_rows and red_rows[0]["verdict"]["evidence"]


@pytest.mark.slow
def test_full_campaign_20_scenarios_green(tmp_path):
    """The acceptance-criterion campaign: >= 20 generated scenarios
    across all severity tiers, zero invariant violations."""
    scens = cs.generate_campaign(seed=100, n_scenarios=21, n=32)
    with tsink.TelemetrySink(str(tmp_path), prefix="chaos") as sink:
        result = cc.run_campaign(scens, seed=0, sink=sink)
    assert result.green, result.summary()
    assert result.summary()["scenarios"] == 21


def test_bench_chaos_smoke_emits_result_and_manifest(tmp_path):
    """bench.py --chaos --smoke: one JSON line, green mini campaign,
    parseable chaos manifest — shrunk via the documented env overrides
    so the pin stays tier-1-safe."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_XLA_CACHE_DIR="",
        SCALECUBE_CHAOS_SCENARIOS="3",
        SCALECUBE_CHAOS_N="16",
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--chaos", "--smoke"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    result = json.loads(lines[0])

    assert "error" not in result, result
    assert result["metric"] == "chaos_campaign_green_scenarios"
    assert result["smoke"] is True
    assert result["scenarios"] == 3
    assert result["value"] == 3              # all green
    assert result["green"] is True
    assert result["failing_repros"] == []
    assert all(v == 0 for v in result["violations_by_code"].values())

    path = result["manifest"]
    assert os.path.dirname(path) == str(tmp_path)
    kinds = {r["kind"] for r in tsink.read_records(path)}
    assert {"manifest", "chaos_scenario", "chaos_verdict"} <= kinds
    rows = tsink.read_records(path, kind="chaos_scenario")
    assert len(rows) == 3 and all(r["green"] for r in rows)


def test_bench_rejects_chaos_with_throughput_flags():
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--chaos", "--traced"],
        capture_output=True, text=True, timeout=60, cwd=str(REPO),
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode != 0
    # The one-JSON-line contract holds even for bad argv.
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1
    assert json.loads(lines[0])["value"] is None
