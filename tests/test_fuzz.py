"""Seeded scenario fuzz: random fault worlds hold the core invariants.

The fixed-scenario suites pin known cases; this file drives randomized
(but seeded — every failure reproduces) combinations of crash, revive,
graceful leave, per-link faults, wire loss, and delivery mode through
the invariants that must hold REGARDLESS of scenario:

  1. determinism — same key, same metrics, bit-for-bit;
  2. the false-positive partition identity
     ``false_positives == false_suspect_rounds + stale_view_rounds``;
  3. layout transparency — compact_carry and int16_wire trace-match the
     wide layout on the same scenario (the fixed-scenario contracts of
     tests/test_compact_carry.py / test_wire16.py, under random worlds);
  4. no phantom suspicion — a lossless, fault-free network never
     records a false-suspicion onset;
  5. time-bounded completeness (the SWIM paper property the reference's
     suspicion config encodes) — every permanently crashed node is DEAD
     in every live member's view by crash + detection + suspicion +
     dissemination slack.

The reference's harness cannot fuzz like this: its randomness is
unseeded and its clock is wall time (SURVEY.md §4 "weaknesses worth
fixing"); here a failing seed is a one-line repro.
"""

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config

HORIZON = 160


def build_scenario(seed):
    """(params-kwargs, world-builder, scenario-dict) from one seed."""
    rng = np.random.default_rng(seed)
    n = int(rng.choice([24, 32, 40]))
    delivery = ["scatter", "shift"][seed % 2]
    loss = float(rng.choice([0.0, 0.05, 0.15]))
    scen = {
        "n": n, "delivery": delivery, "loss": loss,
        # A permanent crash early enough that completeness must land
        # inside HORIZON.
        "crash_node": int(rng.integers(0, n)),
        "crash_at": int(rng.integers(0, 12)),
        "revive": bool(rng.integers(0, 2)),
        "leave": bool(rng.integers(0, 2)),
        "link_fault": bool(rng.integers(0, 2)),
    }
    scen["leave_node"] = int((scen["crash_node"] + 1 + rng.integers(0, n - 2))
                             % n)
    # Faulted link between two nodes that are neither crashed nor leaving.
    others = [i for i in range(n)
              if i not in (scen["crash_node"], scen["leave_node"])]
    scen["fault_src"], scen["fault_dst"] = map(
        int, rng.choice(others, size=2, replace=False))
    return scen


def make_world(params, scen):
    world = swim.SwimWorld.healthy(params)
    until = 120 if scen["revive"] else swim.INT32_MAX
    world = world.with_crash(scen["crash_node"], at_round=scen["crash_at"],
                             until_round=until)
    if scen["leave"]:
        world = world.with_leave(scen["leave_node"], at_round=20)
    if scen["link_fault"]:
        world = world.with_link_fault(scen["fault_src"], scen["fault_dst"],
                                      loss=0.8)
    return world


def run(scen, seed, **layout):
    params = swim.SwimParams.from_config(
        fast_config(), n_members=scen["n"], delivery=scen["delivery"],
        loss_probability=scen["loss"], **layout,
    )
    world = make_world(params, scen)
    state, metrics = swim.run(jax.random.key(seed), params, world, HORIZON)
    return params, state, metrics


_WIDE_CACHE = {}


def run_wide_cached(seed):
    """The wide-layout baseline per seed, shared across the layout
    params (the scenario is a pure function of the seed)."""
    if seed not in _WIDE_CACHE:
        scen = build_scenario(seed)
        _WIDE_CACHE[seed] = run(scen, seed)
    return _WIDE_CACHE[seed]


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_invariants(seed):
    scen = build_scenario(seed)
    params, state, m = run(scen, seed)

    # 1. Determinism: bit-identical re-run.
    _, _, m2 = run(scen, seed)
    for name in m:
        np.testing.assert_array_equal(
            np.asarray(m[name]), np.asarray(m2[name]),
            err_msg=f"seed {seed}: nondeterministic metric {name}",
        )

    # 2. The FP partition identity holds per round under any scenario.
    np.testing.assert_array_equal(
        np.asarray(m["false_positives"]),
        np.asarray(m["false_suspect_rounds"])
        + np.asarray(m["stale_view_rounds"]),
        err_msg=f"seed {seed}: FP partition identity broken",
    )

    # 5. Time-bounded completeness for a permanent crash: DEAD in every
    # live observer's view well inside the horizon.
    if not scen["revive"]:
        crash = scen["crash_node"]
        alive_view = np.asarray(m["alive"])[:, crash]
        dead_view = np.asarray(m["dead"])[:, crash]
        assert alive_view[-1] == 0, (
            f"seed {seed}: someone still holds ALIVE about the crashed "
            f"node at the horizon — {scen}"
        )
        assert dead_view[-1] > 0, f"seed {seed}: crash never declared {scen}"


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("layout", ["compact_carry", "int16_wire"])
def test_fuzz_layout_transparency(seed, layout):
    # 3. Narrow layouts trace-match wide under random scenarios.
    scen = build_scenario(seed)
    _, s_w, m_w = run_wide_cached(seed)
    _, s_n, m_n = run(scen, seed, **{layout: True})
    for name in m_w:
        np.testing.assert_array_equal(
            np.asarray(m_w[name]), np.asarray(m_n[name]),
            err_msg=f"seed {seed}: {layout} diverged on metric {name}",
        )
    if layout == "int16_wire":          # carry directly comparable
        np.testing.assert_array_equal(
            np.asarray(s_w.status), np.asarray(s_n.status))
        np.testing.assert_array_equal(
            np.asarray(s_w.inc), np.asarray(s_n.inc))


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_fuzz_no_phantom_suspicion(delivery):
    # 4. Lossless fault-free network: zero false-suspicion onsets over
    # many random healthy worlds (only the PRNG key varies).
    params = swim.SwimParams.from_config(
        fast_config(), n_members=32, delivery=delivery,
    )
    world = swim.SwimWorld.healthy(params)
    for seed in range(4):
        _, m = swim.run(jax.random.key(1000 + seed), params, world, 120)
        assert int(np.asarray(m["false_suspicion_onsets"]).sum()) == 0, (
            f"{delivery} seed {seed}: phantom suspicion without loss"
        )
