"""Per-sender wire counters on the dense tick (SwimParams.link_counters).

The reference's NetworkEmulator keeps totalMessageSentCount /
totalMessageLostCount per node (transport/NetworkEmulator.java:200-222)
and its gossip experiments use them as the measurement substrate
(GossipProtocolTest.java:212-228).  The tick's analog: per-round
``sent_by_node`` / ``lost_by_node`` [N] traces.  Semantics under test:

  - sent counts wire messages the sender issued (ping, ping-req fan-out,
    gossip per active channel, SYNC, refute push);
  - lost counts in-flight network drops only (loss rules, partition
    walls) on the gossip/SYNC/refute channels; a message toward a
    crashed receiver was still sent; FD probe-chain losses are collapsed
    into verdicts (documented deviation, SwimParams docstring);
  - both delivery modes agree on the accounting exactly where it is
    deterministic and statistically where it is random.
"""

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config


def run_counters(n, rounds, delivery, world_fn=None, seed=0, **overrides):
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery=delivery, link_counters=True,
        **overrides,
    )
    world = swim.SwimWorld.healthy(params)
    if world_fn is not None:
        world = world_fn(world)
    _, m = swim.run(jax.random.key(seed), params, world, rounds)
    return params, np.asarray(m["sent_by_node"]), np.asarray(m["lost_by_node"]), m


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
class TestLinkCounters:
    def test_steady_state_schedule(self, delivery):
        """Warm lossless steady state: nothing is hot, so each live node
        sends exactly 1 PING per fd round + 1 SYNC per sync round, and
        nothing is ever lost."""
        rounds = 24
        params, sent, lost, m = run_counters(16, rounds, delivery)
        r = np.arange(rounds)
        expect = ((r % params.ping_every == 0).astype(int)
                  + (r % params.sync_every == 0).astype(int))
        np.testing.assert_array_equal(sent, expect[:, None] * np.ones(16, int))
        assert lost.sum() == 0

    def test_totals_match_aggregate_counters(self, delivery):
        """sum over nodes of sent_by_node == the aggregate ping counters
        plus gossip/SYNC sends (lossless, everyone alive, so gossip sent
        == gossip delivered)."""
        rounds = 40
        params, sent, lost, m = run_counters(
            24, rounds, delivery,
            world_fn=lambda w: w.with_crash(3, at_round=10, until_round=20),
        )
        # Rounds before the crash: state is warm and static — only
        # schedule traffic, which the aggregate families fully explain.
        pings = np.asarray(m["messages_ping_sent"])
        ping_reqs = np.asarray(m["messages_ping_req_sent"])
        r = np.arange(rounds)
        syncs = np.where(r % params.sync_every == 0, 24, 0)
        syncs[10:20] -= (r[10:20] % params.sync_every == 0).astype(int)  # node 3 down
        gossip = np.asarray(m["messages_gossip"])
        pre = slice(0, 10)
        np.testing.assert_array_equal(
            sent[pre].sum(axis=1),
            pings[pre] + ping_reqs[pre] + syncs[pre] + gossip[pre],
        )

    def test_crashed_sender_sends_nothing(self, delivery):
        rounds = 30
        _, sent, lost, _ = run_counters(
            16, rounds, delivery,
            world_fn=lambda w: w.with_crash(5, at_round=8, until_round=20),
        )
        assert sent[8:20, 5].sum() == 0
        assert sent[:8, 5].sum() > 0 and sent[20:, 5].sum() > 0
        assert lost[8:20, 5].sum() == 0

    def test_blocked_sender_loses_gossip_and_sync(self, delivery):
        """A src->all block rule (100% loss): every gossip/SYNC message
        node 0 sends is counted lost; ping sends still count as sent (the
        probe chain's loss shows in verdicts, not lost_by_node)."""
        rounds = 40
        params, sent, lost, m = run_counters(
            16, rounds, delivery, seed=3,
            world_fn=lambda w: w.with_block(0, (0, 16)),
        )
        r = np.arange(rounds)
        sync_rounds = r % params.sync_every == 0
        # Node 0's sync sends all dropped (warm state: no gossip traffic;
        # its own records never change because nothing it sends arrives).
        assert (lost[sync_rounds, 0] >= 1).all()
        # Other nodes lose nothing on their own links...
        assert lost[:, 1:].sum() == 0
        # ...and node 0 never loses more than it sent.
        assert (lost <= sent).all()

    def test_loss_rate_statistical(self, delivery):
        """Under default loss p, lost/sent over the loss-counted channels
        (everything except the closed-form ping families: gossip + SYNC +
        refute pushes) converges to p.  High loss is not a static regime —
        false suspicions generate gossip traffic — so the denominator is
        taken from the counters themselves."""
        rounds = 400
        params, sent, lost, m = run_counters(
            32, rounds, delivery, loss_probability=0.4, seed=7,
        )
        lossy_sends = (sent.sum()
                       - np.asarray(m["messages_ping_sent"]).sum()
                       - np.asarray(m["messages_ping_req_sent"]).sum())
        assert lossy_sends > 500  # the regime actually generated traffic
        rate = lost.sum() / lossy_sends
        assert 0.36 <= rate <= 0.44, (rate, lossy_sends)

    def test_partition_crossings_are_lost(self, delivery):
        """A static half/half partition: cross-partition SYNC messages
        count lost at the sender (the reference injects partitions as
        blocked links, which its emulator counts the same way)."""
        rounds = 200
        n = 32
        params, sent, lost, _m = run_counters(
            n, rounds, delivery, seed=11,
            world_fn=lambda w: w.with_partition_schedule(
                np.r_[np.zeros(16), np.ones(16)].astype(np.int8),
                phase_rounds=10_000,
            ),
        )
        # Uniform targets cross the wall with prob 16/31 ~= 0.516 in both
        # modes (a cyclic shift over a contiguous half-partition has the
        # same expectation); shift mode's shared per-round offsets
        # correlate the crossings within a round, so its sample variance
        # is higher — the band covers both.  Denominator from the
        # counters themselves (suspicion-driven gossip traffic rides the
        # same accounting).
        lossy_sends = (sent.sum()
                       - np.asarray(_m["messages_ping_sent"]).sum()
                       - np.asarray(_m["messages_ping_req_sent"]).sum())
        rate = lost.sum() / lossy_sends
        assert 0.40 <= rate <= 0.65, (rate, lossy_sends)


def test_link_counters_rejected_under_sharding():
    params = swim.SwimParams.from_config(
        fast_config(), n_members=16, link_counters=True,
    )
    world = swim.SwimWorld.healthy(params)
    state = swim.initial_state(params, world)
    with pytest.raises(NotImplementedError, match="single-device"):
        swim.swim_tick(state, 0, jax.random.key(0), params, world,
                       axis_name="i", n_devices=2)
