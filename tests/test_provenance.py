"""The provenance plane (models/provenance.py + SwimParams.provenance).

Four contracts, the PR-20 acceptance pins:

  1. *off = bit-identical*: ``provenance=False`` (the default) compiles
     the per-channel exposure out — states AND metrics are exactly the
     pre-plane program's, across carry layouts, delivery modes, and the
     composed run shapes;
  2. *the cascade names the right channel*: unit-level pins of the
     attribute_channels where-chain (SYNC beats GOSSIP on a key tie,
     first-hand FD beats both, the ping-req launch flag splits
     direct/proxy only when proxies are configured, timer-fired
     removals are FD even when a relay carried the stale key,
     join-rebirth overrides everything) plus integration pins: the
     blame drill's first sighting is ``fd_direct`` at the planted
     observer, the refutation surfaces as ``self_refutation``, an
     open-world admission lands as ``join_rebirth``;
  3. *overflow counts exactly*: the fixed-capacity buffer is a true
     prefix — fast (gather-compact) and exact (scatter) record paths
     append bit-identical rows and ``recorded + dropped`` is invariant;
  4. *sharded twins*: serial == pipelined bit for bit with the plane
     riding composed_shard_scan, and the sharded rows are the
     single-device rows as a multiset.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.chaos import scenarios as cs
from scalecube_cluster_tpu.models import compose, swim
from scalecube_cluster_tpu.models import provenance as mprov
from scalecube_cluster_tpu.ops import delivery
from scalecube_cluster_tpu.telemetry.events import TraceEventType

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.provenance

N = 16
ROUNDS = 36


def make_params(**overrides):
    kw = dict(ping_every=2, ping_req_members=2, sync_interval=8,
              loss_probability=0.05)
    kw.update(overrides)
    return swim.SwimParams.from_config(fast_config(), n_members=N, **kw)


def chaos_world(params):
    """Seeded chaos schedule (the test_compose idiom): crash, leave,
    lossy inter-half link — enough churn that every wire channel
    carries real transitions."""
    n = params.n_members
    return (swim.SwimWorld.healthy(params)
            .with_crash(3, at_round=8)
            .with_leave(5, at_round=14)
            .with_crash(7, at_round=5, until_round=24)
            .with_link_fault((0, n // 2), (n // 2, n), loss=0.3,
                             from_round=4, until_round=20))


def states_equal(a, b):
    for f in dataclasses.fields(swim.SwimState):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f.name)), np.asarray(getattr(b, f.name)),
            err_msg=f"SwimState.{f.name} diverged")


def metrics_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=f"metrics[{k}] diverged")


# --------------------------------------------------------------------------
# 1: the off-switch, and the knob's validation envelope
# --------------------------------------------------------------------------


def test_provenance_defaults_off():
    params = make_params()
    assert params.provenance is False
    explicit = dataclasses.replace(params, provenance=False)
    assert explicit == params          # same static params, same program


def test_provenance_rejects_delay_rings():
    params = make_params(max_delay_rounds=2)
    with pytest.raises(ValueError, match="provenance"):
        dataclasses.replace(params, provenance=True)


@pytest.mark.parametrize("overrides", [
    dict(),                                      # scatter, wide carry
    dict(compact_carry=True),
    dict(delivery="shift"),
    dict(delivery="shift", k_block=8),
    dict(delivery="shift", n_subjects=8),        # focal
], ids=["scatter", "compact", "shift", "k_block", "focal"])
def test_knob_on_is_bit_identical(overrides):
    """Arming the knob without mounting the plane changes NOTHING: the
    per-channel maxima are additive exposure, the combined inbox
    dataflow is textually untouched — states and the metrics tree are
    bit-for-bit the knob-off program's."""
    p_off = make_params(**overrides)
    p_on = dataclasses.replace(p_off, provenance=True)
    world = chaos_world(p_off)
    s_off, m_off = swim.run(jax.random.key(0), p_off, world, ROUNDS)
    s_on, m_on = swim.run(jax.random.key(0), p_on, world, ROUNDS)
    states_equal(s_off, s_on)
    metrics_equal(m_off, m_on)


def test_composed_stack_off_switch():
    """The full composed stack with the plane mounted: protocol state,
    per-round metrics, and the TRACE plane's lanes are bit-identical to
    the plane-less stack — the plane only observes."""
    p_off = make_params()
    p_on = dataclasses.replace(p_off, provenance=True)
    world = chaos_world(p_off)
    key = jax.random.key(7)
    f_off, r_off, m_off = compose.run_composed(
        key, p_off, world, ROUNDS, with_monitor=False)
    f_on, r_on, m_on = compose.run_composed(
        key, p_on, world, ROUNDS, with_monitor=False,
        with_provenance=True, provenance_capacity=4096)
    states_equal(f_off, f_on)
    metrics_equal(m_off, m_on)
    assert set(r_on) == set(r_off) | {"provenance"}
    for a, b in zip(jax.tree.leaves(r_off["trace"]),
                    jax.tree.leaves(r_on["trace"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    pv = r_on["provenance"]
    assert int(pv.count) > 0 and int(pv.dropped) == 0


def test_plane_requires_knob():
    p_off = make_params()
    world = swim.SwimWorld.healthy(p_off)
    plane = mprov.ProvenancePlane()
    with pytest.raises(ValueError, match="provenance=True"):
        plane.init(p_off, world)


# --------------------------------------------------------------------------
# 2a: the attribution cascade, unit level
# --------------------------------------------------------------------------


def _key(params, status, inc):
    return int(delivery.pack_record(
        jnp.int8(status), jnp.int32(inc),
        epoch_bits=params.epoch_bits, fmt=params.wire_format))


def _attribute(params, fd=-1, gossip=-1, sync=-1, code=None,
               ping_req=False, join=False):
    """One-cell cascade probe: [1, 1] arrays around scalar evidence."""
    if code is None:
        code = int(TraceEventType.SUSPECTED) + 1
    prov = dict(
        fd=jnp.full((1, 1), fd, jnp.int32),
        gossip=jnp.full((1, 1), gossip, jnp.int32),
        sync=jnp.full((1, 1), sync, jnp.int32),
        ping_req=jnp.full((1,), ping_req, jnp.bool_),
    )
    codes = jnp.full((1, 1), code, jnp.int8)
    join_now = jnp.full((1, 1), join, jnp.bool_)
    return int(mprov.attribute_channels(params, prov, codes, join_now)[0, 0])


def test_cascade_gossip_alone():
    p = make_params()
    k = _key(p, 1, 3)                   # SUSPECT @ inc 3
    assert _attribute(p, gossip=k) == mprov.CH_GOSSIP


def test_cascade_sync_beats_gossip_on_tie():
    """Both channels delivered the identical key: the exchange is the
    direct conversation, SYNC wins the tie."""
    p = make_params()
    k = _key(p, 1, 3)
    assert _attribute(p, gossip=k, sync=k) == mprov.CH_SYNC
    # A strictly greater gossip key still wins over a stale sync key.
    assert _attribute(p, gossip=_key(p, 1, 4), sync=k) == mprov.CH_GOSSIP


def test_cascade_fd_beats_relays_on_tie():
    """First-hand evidence outranks relays carrying the same record."""
    p = make_params(ping_req_members=0)
    k = _key(p, 1, 3)
    assert _attribute(p, fd=k, gossip=k, sync=k) == mprov.CH_FD_DIRECT


def test_cascade_ping_req_flag_splits_fd():
    p = make_params(ping_req_members=2)
    k = _key(p, 1, 3)
    assert _attribute(p, fd=k, ping_req=False) == mprov.CH_FD_DIRECT
    assert _attribute(p, fd=k, ping_req=True) == mprov.CH_PINGREQ_PROXY
    # Without proxies configured the launch flag means only "a direct
    # probe failed" — the verdict is still first-hand.
    p0 = make_params(ping_req_members=0)
    k0 = _key(p0, 1, 3)
    assert _attribute(p0, fd=k0, ping_req=True) == mprov.CH_FD_DIRECT


def test_cascade_timer_fired_removal_is_fd():
    """A REMOVED transition whose wire winner is not DEAD came from the
    local suspicion timer — FD, even when a relay carried the stale
    SUSPECT key that started it."""
    p = make_params()
    stale = _key(p, 1, 3)               # SUSPECT on the wire
    removed = int(TraceEventType.REMOVED) + 1
    assert _attribute(p, gossip=stale, code=removed) == mprov.CH_FD_DIRECT
    # A DEAD key on the wire explains the removal: the relay keeps it.
    dead = _key(p, 2, 3)
    assert _attribute(p, gossip=dead, code=removed) == mprov.CH_GOSSIP


def test_cascade_join_rebirth_overrides_all():
    p = make_params()
    k = _key(p, 0, 0)
    assert _attribute(p, fd=k, gossip=k, sync=k,
                      join=True) == mprov.CH_JOIN_REBIRTH


def test_cascade_no_wire_evidence_falls_back_to_fd():
    """A transition none of the wire maxima explain is first-hand by
    elimination (e.g. the merge funnel's own in-tick edges)."""
    p = make_params()
    assert _attribute(p) == mprov.CH_FD_DIRECT


# --------------------------------------------------------------------------
# 2b: integration — the drill, the refutation, the admission
# --------------------------------------------------------------------------


def _drill_rows(n=16, victim=3, observer=11, capacity=4096, **overrides):
    scen = cs.blame_drill_scenario(7, n=n, victim=victim,
                                   observer=observer, onset_round=16,
                                   pulse_rounds=64, cool_rounds=48)
    kw = dict(delivery="scatter", ping_known_only=False,
              ping_req_members=0, ping_every=1, sync_interval=8,
              provenance=True)
    kw.update(overrides)
    params = swim.SwimParams.from_config(fast_config(), n_members=n, **kw)
    world, _ = scen.build(params)
    _, results, _ = compose.run_composed(
        jax.random.key(7), params, world, scen.horizon,
        with_monitor=False, with_provenance=True,
        provenance_capacity=capacity)
    pv = results["provenance"]
    assert int(pv.dropped) == 0
    return mprov.decode_attributions(pv)


def _check_drill(rows, victim, observer):
    sightings = [r for r in rows if r["subject"] == victim
                 and r["transition"] == "SUSPECTED"]
    assert sightings, "the planted fault produced no suspicion"
    first = min(sightings, key=lambda r: (r["round"], r["observer"]))
    # The planted asymmetric link: ONLY the observer times the victim
    # out first-hand; everyone else hears the rumor second-hand.
    assert first["observer"] == observer
    assert first["channel"] == "fd_direct"
    assert all(r["channel"] in ("gossip", "sync") for r in sightings
               if r["observer"] != observer)
    refutes = [r for r in rows if r["transition"] == "ALIVE_REFUTED"
               and r["observer"] == victim and r["subject"] == victim]
    assert refutes and all(
        r["channel"] == "self_refutation" for r in refutes)
    assert all(r["channel"] in mprov.CHANNEL_NAMES for r in rows)


def test_blame_drill_first_sighting_is_first_hand():
    rows = _drill_rows()
    _check_drill(rows, victim=3, observer=11)


def test_join_rebirth_attribution():
    """An open-world admission this round is attributed to the
    admission itself, not to the wire channel that carried it; later
    observers learn of the new identity via the wire."""
    params = make_params(open_world=True, ping_req_members=0)
    params = dataclasses.replace(params, provenance=True)
    world = (swim.SwimWorld.healthy(params)
             .with_crash(7, at_round=5)
             .with_join(7, at_round=22))
    _, results, _ = compose.run_composed(
        jax.random.key(3), params, world, 48, with_monitor=False,
        with_provenance=True, provenance_capacity=4096)
    rows = mprov.decode_attributions(results["provenance"])
    at_join = [r for r in rows if r["round"] == 22 and r["subject"] == 7]
    assert at_join
    assert all(r["channel"] == "join_rebirth" for r in at_join)
    later = [r for r in rows if r["round"] > 22 and r["subject"] == 7
             and r["transition"] in ("ADDED", "JOINED")]
    assert later
    assert all(r["channel"] in ("gossip", "sync") for r in later)


@pytest.mark.slow
@pytest.mark.parametrize("overrides", [
    dict(),
    dict(delivery="shift", ping_known_only=True),
    dict(delivery="shift", ping_known_only=True, k_block=8),
], ids=["scatter", "shift", "k_block"])
def test_blame_matrix_across_deliveries(overrides):
    """The drill's blame verdict is delivery-agnostic: every tick body
    (scatter, shift, k_block) exposes the same per-channel evidence."""
    rows = _drill_rows(**overrides)
    _check_drill(rows, victim=3, observer=11)


# --------------------------------------------------------------------------
# 3: the buffer — fast/exact parity, exact overflow accounting
# --------------------------------------------------------------------------


def _burst(seed, n, k, density=0.2):
    rng = np.random.default_rng(seed)
    codes = jnp.asarray(
        np.where(rng.random((n, k)) < density,
                 rng.integers(1, 6, (n, k)), 0), jnp.int8)
    channels = jnp.asarray(rng.integers(0, 6, (n, k)), jnp.int8)
    epochs = jnp.asarray(rng.integers(0, 4, (n, k)), jnp.int32)
    return codes, channels, epochs


def _record(pv, round_idx, burst, n):
    codes, channels, epochs = burst
    return mprov.record_attributions(
        pv, jnp.int32(round_idx), codes, channels, epochs,
        jnp.arange(n, dtype=jnp.int32))


def test_fast_and_exact_paths_bit_identical(monkeypatch):
    """The gather-compact fast path appends byte-for-byte what the
    sparse-scatter exact path appends: same rows, same order, same
    accounting.  COMPACT_WINDOW=0 forces every call down the exact
    path."""
    n, k = 12, 12
    pv_fast = mprov.ProvenanceState.empty(512)
    pv_exact = mprov.ProvenanceState.empty(512)
    for r in range(4):
        burst = _burst(r, n, k)
        pv_fast = _record(pv_fast, r, burst, n)
        with monkeypatch.context() as m:
            m.setattr(mprov, "COMPACT_WINDOW", 0)
            pv_exact = _record(pv_exact, r, burst, n)
    np.testing.assert_array_equal(np.asarray(pv_fast.lanes),
                                  np.asarray(pv_exact.lanes))
    assert int(pv_fast.count) == int(pv_exact.count) > 0
    assert int(pv_fast.dropped) == int(pv_exact.dropped) == 0


def test_big_burst_takes_exact_path():
    """A burst beyond COMPACT_WINDOW records completely (the exact
    path), nothing truncated."""
    n = k = 32                              # 1024 changed > window 256
    codes = jnp.ones((n, k), jnp.int8)
    channels = jnp.zeros((n, k), jnp.int8)
    epochs = jnp.zeros((n, k), jnp.int32)
    assert n * k > mprov.COMPACT_WINDOW
    pv = _record(mprov.ProvenanceState.empty(2048), 5,
                 (codes, channels, epochs), n)
    assert int(pv.count) == n * k and int(pv.dropped) == 0
    lanes = np.asarray(pv.lanes)[: n * k]
    # Flat (observer-major) order, every cell exactly once.
    np.testing.assert_array_equal(lanes[:, 0], np.repeat(np.arange(n), k))
    np.testing.assert_array_equal(lanes[:, 1], np.tile(np.arange(k), n))
    assert (lanes[:, 5] == 5).all()


def test_overflow_is_an_exact_prefix():
    """A small buffer holds the EXACT prefix of the big buffer's stream
    and counts every lost record — recorded + dropped is invariant.
    The second call lands in the buffer's last window, forcing the
    fast->exact crossover."""
    n, k = 8, 8
    small_cap = 12
    big = mprov.ProvenanceState.empty(512)
    small = mprov.ProvenanceState.empty(small_cap)
    total = 0
    for r in range(3):
        burst = _burst(100 + r, n, k, density=0.15)
        total += int(np.asarray(burst[0] > 0).sum())
        big = _record(big, r, burst, n)
        small = _record(small, r, burst, n)
    assert int(big.count) == total and int(big.dropped) == 0
    assert total > small_cap
    assert int(small.count) == small_cap
    assert int(small.count) + int(small.dropped) == total
    np.testing.assert_array_equal(
        np.asarray(small.lanes)[:small_cap],
        np.asarray(big.lanes)[:small_cap])


def test_decode_and_payload_shape():
    p = make_params(ping_req_members=0)
    p = dataclasses.replace(p, provenance=True)
    world = chaos_world(p)
    _, results, _ = compose.run_composed(
        jax.random.key(5), p, world, ROUNDS, with_monitor=False,
        with_provenance=True, provenance_capacity=1024)
    pv = results["provenance"]
    payload = mprov.attributions_payload(pv)
    assert payload["recorded"] == int(pv.count) == len(payload["rows"])
    assert payload["dropped"] == 0 and payload["capacity"] == 1024
    for row in payload["rows"]:
        assert set(row) == {"observer", "subject", "epoch", "transition",
                            "channel", "round"}
        assert row["channel"] in mprov.CHANNEL_NAMES
        assert row["transition"] in TraceEventType.__members__
    # Rows arrive in (round, observer-major cell) order.
    rounds = [r["round"] for r in payload["rows"]]
    assert rounds == sorted(rounds)


# --------------------------------------------------------------------------
# 4: sharded twins
# --------------------------------------------------------------------------


@pytest.mark.multichip
def test_sharded_pipelined_equals_serial_with_plane():
    """The plane rides composed_shard_scan: sharded pipelined == sharded
    serial bit for bit (lanes, count, dropped), and the union of the
    per-device rows is the single-device stream as a multiset."""
    from jax.sharding import PartitionSpec as P

    from scalecube_cluster_tpu.parallel import compat
    from scalecube_cluster_tpu.parallel import mesh as pmesh

    if not compat.HAS_SHARD_MAP:
        pytest.skip(compat.SKIP_REASON)
    n, rounds, cap = 32, 48, 1024
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", ping_every=2,
        ping_req_members=0, sync_interval=8, provenance=True)
    world = (swim.SwimWorld.healthy(params)
             .with_crash(3, at_round=8)
             .with_crash(19, at_round=5, until_round=24)
             .with_link_fault((0, n // 2), (n // 2, n), loss=0.3,
                              from_round=4, until_round=20))
    mesh = pmesh.make_mesh(4)
    axis, n_dev, n_local, state_specs, out_metric_specs = \
        pmesh._shard_prelude(params, mesh)
    world_specs = jax.tree.map(lambda _: P(), world)

    def sharded(use_pipeline):
        def body(key, world, state):
            offset = jax.lax.axis_index(axis) * n_local
            fs, results, metrics = compose.composed_shard_scan(
                key, params, world, state, rounds, 0, offset, axis,
                n_dev, n_local,
                planes=(mprov.ProvenancePlane(capacity=cap),),
                use_pipeline=use_pipeline)
            pv = results["provenance"]
            return fs, (pv.lanes, pv.count[None], pv.dropped[None]), \
                metrics
        run = compat.shard_map(
            body, mesh=mesh,
            in_specs=(P(), world_specs, state_specs),
            out_specs=(state_specs, (P(axis), P(axis), P(axis)),
                       out_metric_specs),
            check_replication=False)
        return run(jax.random.key(6), world,
                   swim.initial_state(params, world))

    s_ser, (lanes_s, count_s, drop_s), m_ser = sharded(False)
    s_pip, (lanes_p, count_p, drop_p), m_pip = sharded(True)
    states_equal(s_ser, s_pip)
    metrics_equal(m_ser, m_pip)
    np.testing.assert_array_equal(np.asarray(lanes_s),
                                  np.asarray(lanes_p))
    np.testing.assert_array_equal(np.asarray(count_s),
                                  np.asarray(count_p))
    np.testing.assert_array_equal(np.asarray(drop_s), np.asarray(drop_p))

    # Each device records only its own observer rows (global ids via
    # the shard offset), the stream is non-trivial, and nothing spilled.
    # (No single-device comparison: the sharded draws are their own
    # seeded stream — sharded-vs-serial identity is the pin above.)
    assert int(np.asarray(drop_s).sum()) == 0
    lanes = np.asarray(lanes_s)
    seen = 0
    for d in range(n_dev):
        cnt = int(np.asarray(count_s)[d])
        seen += cnt
        rows = lanes[d * cap: d * cap + cnt]
        lo, hi = d * n_local, (d + 1) * n_local
        assert ((rows[:, 0] >= lo) & (rows[:, 0] < hi)).all()
        assert ((rows[:, 4] >= 0) & (rows[:, 4] < len(
            mprov.CHANNEL_NAMES))).all()
    assert seen > 0
