"""Composable plane runner (models/compose.py): plane-combination
property suite + alias bit-identity pins.

The contract under test (ISSUE 15 / ROADMAP item 1):

  - any sampled subset of {trace, metrics, monitor, sync, lifeguard,
    open_world} toggled on a seeded chaos world leaves the PROTOCOL
    bit-identical to the bare run with the same params — observer
    planes only observe, in-tick planes are compiled by their knobs
    exactly as before (tier-1 samples ~8 combos; the full 2^6 sweep is
    @slow);
  - the seven entry points are thin aliases: the composed multi-plane
    stack produces byte-for-byte the same trace lanes / monitor counts
    / registry counters as the corresponding single-plane aliases on
    the same inputs, including under round fusion (the generalized
    fused body) and non-divisible fusion tails;
  - the plane registry inventory names real SwimParams knobs and real
    SwimState lanes (no rot against the dataclasses).
"""

import dataclasses

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.chaos import monitor as cmonitor
from scalecube_cluster_tpu.models import compose, swim
from scalecube_cluster_tpu.telemetry import metrics as tmetrics
from scalecube_cluster_tpu.telemetry import trace as ttrace

pytestmark = pytest.mark.compose

N = 16
ROUNDS = 36


def chaos_params(sync=False, lifeguard=False, open_world=False,
                 **overrides):
    kw = dict(
        n_members=N, n_subjects=N, fanout=3, periods_to_spread=3,
        ping_every=2, sync_every=4, suspicion_rounds=6,
        ping_req_members=2, loss_probability=0.05,
        sync_interval=8 if sync else 0,
        lhm_max=3 if lifeguard else 0,
        open_world=open_world,
    )
    kw.update(overrides)
    return swim.SwimParams(**kw)


def chaos_world(params, open_world=False):
    """Seeded chaos schedule: crash, leave, a lossy link rule, and —
    when the open-world plane is armed — a JOIN into the crashed
    slot."""
    world = (swim.SwimWorld.healthy(params)
             .with_crash(3, at_round=8)
             .with_leave(5, at_round=14)
             .with_link_fault((0, N // 2), (N // 2, N), loss=0.3,
                              from_round=4, until_round=20))
    if open_world:
        world = world.with_crash(7, at_round=5).with_join(7, at_round=22)
    else:
        world = world.with_crash(7, at_round=5, until_round=24)
    return world


def states_equal(a, b):
    for f in dataclasses.fields(swim.SwimState):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f.name)), np.asarray(getattr(b, f.name)),
            err_msg=f"SwimState.{f.name} diverged")


def metrics_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=f"metrics[{k}] diverged")


# Sampled tier-1 combos over (trace, metrics, monitor, sync, lifeguard,
# open_world); the full 2^6 sweep runs @slow below.
SAMPLED_COMBOS = [
    (False, False, False, False, False, False),
    (True, True, True, False, False, False),
    (True, False, False, True, False, False),
    (False, True, False, False, True, False),
    (False, False, True, True, True, False),
    (True, True, False, False, False, True),
    (False, False, True, False, True, True),
    (True, True, True, True, True, True),
]


def run_combo(trace, metr, mon, sync, lifeg, ow):
    key = jax.random.key(7)
    params = chaos_params(sync=sync, lifeguard=lifeg, open_world=ow)
    world = chaos_world(params, open_world=ow)
    bare_state, bare_metrics = swim.run(key, params, world, ROUNDS)
    spec = cmonitor.MonitorSpec.passive(params) if mon else None
    final, results, metrics = compose.run_composed(
        key, params, world, ROUNDS, monitor_spec=spec, with_trace=trace,
        with_metrics=metr, with_monitor=mon,
    )
    # observer planes only observe: protocol table + per-round metrics
    # bit-identical to the bare run on the same params
    states_equal(bare_state, final)
    metrics_equal(bare_metrics, metrics)
    assert set(results) == ({"trace"} if trace else set()) \
        | ({"metrics"} if metr else set()) | ({"monitor"} if mon else set())
    if mon:
        # the passive safety invariants hold on every sampled combo
        assert int(np.asarray(results["monitor"].code_counts).sum()) == 0
    return params, world, key, results


@pytest.mark.parametrize("combo", SAMPLED_COMBOS,
                         ids=lambda c: "".join("tmMslo"[i] if f else "-"
                                               for i, f in enumerate(c)))
def test_sampled_plane_combos_agree_with_bare_run(combo):
    run_combo(*combo)


@pytest.mark.slow
@pytest.mark.parametrize("mask", range(64))
def test_full_plane_combo_sweep(mask):
    run_combo(*(bool(mask >> i & 1) for i in range(6)))


def test_full_stack_matches_every_alias():
    """The composed trace/metrics/monitor slices are byte-for-byte the
    single-plane aliases' outputs on the same inputs — the alias
    bit-identity pin."""
    params, world, key, results = run_combo(
        True, True, True, True, True, False)
    _, tel, _ = swim.run_traced(key, params, world, ROUNDS)
    np.testing.assert_array_equal(np.asarray(tel.trace.lanes),
                                  np.asarray(results["trace"].trace.lanes))
    assert int(tel.trace.count) == int(results["trace"].trace.count)
    np.testing.assert_array_equal(
        np.asarray(tel.first_suspect),
        np.asarray(results["trace"].first_suspect))
    _, ms, _ = swim.run_metered(key, params, world, ROUNDS)
    spec = tmetrics.MetricsSpec.default()
    for i, name in enumerate(spec.counters):
        if name == "chaos_violations":
            continue  # rides only the monitored registry
        assert int(ms.counters[i]) == int(results["metrics"].counters[i]), \
            name
    np.testing.assert_array_equal(np.asarray(ms.gauges),
                                  np.asarray(results["metrics"].gauges))
    mspec = cmonitor.MonitorSpec.passive(params)
    _, mon, _ = cmonitor.run_monitored(key, params, world, mspec, ROUNDS)
    np.testing.assert_array_equal(np.asarray(mon.code_counts),
                                  np.asarray(results["monitor"].code_counts))
    np.testing.assert_array_equal(np.asarray(mon.lanes),
                                  np.asarray(results["monitor"].lanes))
    # ... and the monitored-metered registry (incl. chaos_violations)
    _, mon2, ms2, _ = cmonitor.run_monitored_metered(
        key, params, world, mspec, ROUNDS)
    np.testing.assert_array_equal(np.asarray(ms2.counters),
                                  np.asarray(results["metrics"].counters))
    np.testing.assert_array_equal(np.asarray(mon2.code_counts),
                                  np.asarray(results["monitor"].code_counts))


def test_full_stack_under_round_fusion_with_tail():
    """The generalized fused body (trace batching its event record per
    step while monitor/metrics fold per round) is bit-identical to the
    unfused composed stack, including a non-divisible fusion tail —
    and to the aliases at the same K."""
    key = jax.random.key(11)
    spec_args = dict(sync=True, lifeguard=True)
    p1 = chaos_params(**spec_args)
    pk = chaos_params(**spec_args, rounds_per_step=5)  # 36 = 7*5 + 1
    world = chaos_world(p1)
    mspec = cmonitor.MonitorSpec.passive(p1)
    f1, r1, m1 = compose.run_composed(key, p1, world, ROUNDS,
                                      monitor_spec=mspec)
    fk, rk, mk = compose.run_composed(key, pk, world, ROUNDS,
                                      monitor_spec=mspec)
    states_equal(f1, fk)
    metrics_equal(m1, mk)
    np.testing.assert_array_equal(np.asarray(r1["trace"].trace.lanes),
                                  np.asarray(rk["trace"].trace.lanes))
    assert int(r1["trace"].trace.dropped) == int(rk["trace"].trace.dropped)
    np.testing.assert_array_equal(np.asarray(r1["monitor"].code_counts),
                                  np.asarray(rk["monitor"].code_counts))
    np.testing.assert_array_equal(np.asarray(r1["metrics"].counters),
                                  np.asarray(rk["metrics"].counters))
    # alias parity at the same fused K
    _, telk, _ = swim.run_traced(key, pk, world, ROUNDS)
    np.testing.assert_array_equal(np.asarray(telk.trace.lanes),
                                  np.asarray(rk["trace"].trace.lanes))


def test_composed_resume_matches_unbroken():
    """Chunked composed runs resume every plane slice (state +
    telemetry + monitor + metrics) bit-identically to one unbroken
    composed run — the checkpoint-segment shape."""
    key = jax.random.key(23)
    params = chaos_params(sync=True)
    world = chaos_world(params)
    mspec = cmonitor.MonitorSpec.passive(params)
    f_all, r_all, _ = compose.run_composed(key, params, world, ROUNDS,
                                           monitor_spec=mspec)
    half = ROUNDS // 2
    f1, r1, _ = compose.run_composed(key, params, world, half,
                                     monitor_spec=mspec)
    f2, r2, _ = compose.run_composed(
        key, params, world, ROUNDS - half, monitor_spec=mspec, state=f1,
        start_round=half, telemetry=r1["trace"], monitor=r1["monitor"],
        metrics_state=r1["metrics"],
    )
    states_equal(f_all, f2)
    np.testing.assert_array_equal(np.asarray(r_all["trace"].trace.lanes),
                                  np.asarray(r2["trace"].trace.lanes))
    np.testing.assert_array_equal(np.asarray(r_all["monitor"].code_counts),
                                  np.asarray(r2["monitor"].code_counts))
    np.testing.assert_array_equal(np.asarray(r_all["metrics"].counters),
                                  np.asarray(r2["metrics"].counters))


def test_run_composed_monitor_requires_spec():
    params = chaos_params()
    world = chaos_world(params)
    with pytest.raises(ValueError, match="monitor_spec"):
        compose.run_composed(jax.random.key(0), params, world, 4)


def test_plane_registry_names_real_knobs_and_lanes():
    """The plane inventory cannot rot against the dataclasses: every
    declared knob is a SwimParams field, every declared lane a
    SwimState field, names are unique, and the known planes are all
    listed."""
    fields = {f.name for f in dataclasses.fields(swim.SwimParams)}
    lanes = {f.name for f in dataclasses.fields(swim.SwimState)}
    reg = compose.plane_registry()
    names = [p["name"] for p in reg]
    assert len(names) == len(set(names))
    assert {"protocol", "sync", "lifeguard", "delay", "user_gossip",
            "open_world", "trace", "monitor", "metrics"} <= set(names)
    for plane in reg:
        assert plane["kind"] in ("core", "in-tick", "observer")
        assert set(plane["knobs"]) <= fields, plane["name"]
        assert set(plane["lanes"]) <= lanes, plane["name"]


def test_round_ctx_memoizes_shared_derivations():
    """The shared round context traces each derivation once: repeated
    property reads return the SAME traced value object (what makes the
    composed stack pay the live-mask / emptiness / wide-decode
    reductions once per round instead of once per plane)."""
    params = chaos_params()
    world = chaos_world(params)
    state = swim.initial_state(params, world)
    new_state, m = swim.swim_tick(state, 0, jax.random.key(0), params,
                                  world)
    rc = compose.RoundCtx(params, world, swim.Knobs.from_params(params),
                          0, state, new_state, m)
    assert rc.alive_now is rc.alive_now
    assert rc.status_changed is rc.status_changed
    assert rc.any_status_change is rc.any_status_change
    assert rc.prev_wide is rc.prev_wide
    assert rc.prev_deadline_wide is rc.prev_deadline_wide
    # prev_deadline_wide is served FROM the already-paid wide decode
    np.testing.assert_array_equal(
        np.asarray(rc.prev_deadline_wide),
        np.asarray(rc.prev_wide.suspect_deadline))
