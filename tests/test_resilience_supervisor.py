"""resilience/supervisor.py: segmentation, retry classification, and
in-process preemption/resume semantics.

The REAL (subprocess SIGKILL) drills live in tests/test_resilience_kill
.py; this module pins the same guarantees in-process where they are
cheap: segmented == monolithic bit for bit, a simulated preemption at
the nastiest write stages resumes bit-identically with a gap-free
journal, transient errors retry with backoff while deterministic ones
raise immediately.
"""

import dataclasses
import os

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.resilience import harness as rh
from scalecube_cluster_tpu.resilience import store as rstore
from scalecube_cluster_tpu.resilience import supervisor as rsup
from scalecube_cluster_tpu.telemetry import sink as tsink

pytestmark = pytest.mark.resilience


def drill_cfg(tmp_path, shape="plain", sub="run", **overrides):
    base = tmp_path / sub
    os.makedirs(base, exist_ok=True)
    kw = dict(n_members=12, n_rounds=24, segment_rounds=8)
    kw.update(overrides)
    return rh.DrillConfig(shape=shape, base_path=str(base / "ck"), **kw)


def test_segmented_plain_matches_monolithic(tmp_path):
    cfg = drill_cfg(tmp_path)
    key, params, world, _ = rh.build_workload(cfg)
    mono_state, _ = swim.run(key, params, world, cfg.n_rounds)
    res = rh.run_config(cfg)
    for f in dataclasses.fields(swim.SwimState):
        np.testing.assert_array_equal(
            np.asarray(getattr(mono_state, f.name)),
            np.asarray(getattr(res.state, f.name)),
            err_msg=f"segmented vs monolithic diverged on {f.name}",
        )
    v = rh.verify_journal(res.journal_path, cfg.n_rounds)
    assert v["complete"], v["problems"]
    assert v["n_segments"] == 3
    # The journal round-trips through the standard sink readers, with a
    # manifest and a closing summary.
    kinds = [r["kind"] for r in tsink.read_records(res.journal_path)]
    assert kinds[0] == "manifest" and kinds[-1] == "summary"


@pytest.mark.parametrize("stage", ["mid_journal", "post_journal"])
def test_simulated_preemption_resumes_bit_identical(tmp_path, stage):
    """The two nastiest write stages in-process (torn journal line;
    journal ahead of checkpoint -> dedup).  The full stage x shape
    matrix runs under @slow with real SIGKILLs."""
    ref = rh.run_config(drill_cfg(tmp_path, shape="traced", sub="ref"))
    ref_digest = rh.result_digest(ref)
    ref_events = rh.merged_events(ref.journal_path)
    assert ref.events_recorded > 0          # the crash really traced

    cfg = drill_cfg(tmp_path, shape="traced", sub=stage)
    with pytest.raises(rsup.SimulatedPreemption):
        rh.run_config(cfg, kill_plan=rsup.KillPlan(
            round=12, stage=stage, mode="raise"))
    res = rh.run_config(cfg)
    assert res.resumed_from is not None
    assert rh.result_digest(res) == ref_digest
    v = rh.verify_journal(res.journal_path, cfg.n_rounds)
    assert v["complete"], v["problems"]
    assert rh.merged_events(res.journal_path) == ref_events
    if stage == "post_journal":
        # The re-run segment's record was already durable: deduped.
        assert res.segments_deduped == 1


def test_resume_after_corrupt_latest_generation(tmp_path):
    """Preemption + disk corruption stacked: kill mid-run, bit-flip the
    newest surviving generation, and the relaunch still completes
    bit-identically from the generation before it."""
    ref = rh.run_config(drill_cfg(tmp_path, sub="ref2"))
    cfg = drill_cfg(tmp_path, sub="both")
    with pytest.raises(rsup.SimulatedPreemption):
        rh.run_config(cfg, kill_plan=rsup.KillPlan(
            round=17, stage="post_checkpoint", mode="raise"))
    store = rstore.CheckpointStore(cfg.base_path, keep=3)
    gens = store.generations_on_disk()
    assert len(gens) >= 2
    with open(store.gen_path(gens[-1]), "rb+") as f:
        f.seek(os.path.getsize(store.gen_path(gens[-1])) // 2)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))
    res = rh.run_config(cfg)
    assert rh.result_digest(res) == rh.result_digest(ref)
    assert res.resumed_from["fallbacks"]    # the corrupt gen was named
    assert res.resumed_from["generation"] == gens[-2]
    v = rh.verify_journal(res.journal_path, cfg.n_rounds)
    assert v["complete"], v["problems"]


# --------------------------------------------------------------------------
# Retry policy
# --------------------------------------------------------------------------


def test_transient_errors_retry_with_backoff_then_succeed(tmp_path,
                                                          monkeypatch):
    cfg = drill_cfg(tmp_path, sub="retry")
    real = rsup._run_segment
    failures = {"left": 2}

    def flaky(*args, **kwargs):
        if failures["left"] > 0:
            failures["left"] -= 1
            raise RuntimeError("transient device hiccup")
        return real(*args, **kwargs)

    sleeps = []
    monkeypatch.setattr(rsup, "_run_segment", flaky)
    key, params, world, _ = rh.build_workload(cfg)
    store = rstore.CheckpointStore(cfg.base_path)
    res = rsup.run_resilient(
        "plain", key, params, world, cfg.n_rounds, store=store,
        segment_rounds=cfg.segment_rounds,
        retry=rsup.RetryPolicy(max_attempts=4, base_delay_s=0.1,
                               max_delay_s=1.0, jitter=0.5, seed=7),
        sleep=sleeps.append,
    )
    assert res.retries == 2
    assert len(sleeps) == 2
    # Exponential envelope with non-negative jitter: delay k in
    # [base * 2^k, base * 2^k * (1 + jitter)].
    assert 0.1 <= sleeps[0] <= 0.1 * 1.5
    assert 0.2 <= sleeps[1] <= 0.2 * 1.5
    # And the flaky run still produced the right answer.
    mono, _ = swim.run(key, params, world, cfg.n_rounds)
    np.testing.assert_array_equal(np.asarray(mono.status),
                                  np.asarray(res.state.status))


def test_transient_errors_exhaust_attempt_budget(tmp_path, monkeypatch):
    cfg = drill_cfg(tmp_path, sub="exhaust")
    monkeypatch.setattr(
        rsup, "_run_segment",
        lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("permanently flaky")),
    )
    key, params, world, _ = rh.build_workload(cfg)
    sleeps = []
    with pytest.raises(RuntimeError, match="permanently flaky"):
        rsup.run_resilient(
            "plain", key, params, world, cfg.n_rounds,
            store=rstore.CheckpointStore(cfg.base_path),
            segment_rounds=cfg.segment_rounds,
            retry=rsup.RetryPolicy(max_attempts=3, base_delay_s=0.01),
            sleep=sleeps.append,
        )
    assert len(sleeps) == 2                 # attempts - 1 backoffs


def test_deterministic_failures_raise_immediately(tmp_path):
    """Meta mismatch (a DIFFERENT run at the same lineage) is
    non-retryable: no sleeps, immediate ValueError."""
    cfg = drill_cfg(tmp_path, sub="meta")
    rh.run_config(cfg)                      # complete a lineage
    key, params, world, _ = rh.build_workload(cfg)
    sleeps = []
    with pytest.raises(ValueError, match="meta mismatch"):
        rsup.run_resilient(
            "plain", key, params, world, cfg.n_rounds + 8,   # different
            store=rstore.CheckpointStore(cfg.base_path),
            segment_rounds=cfg.segment_rounds, sleep=sleeps.append,
        )
    with pytest.raises(ValueError, match="meta mismatch"):
        rsup.run_resilient(                 # different segment grid
            "plain", key, params, world, cfg.n_rounds,
            store=rstore.CheckpointStore(cfg.base_path),
            segment_rounds=cfg.segment_rounds + 1,
            sleep=sleeps.append,
        )
    with pytest.raises(ValueError, match="meta mismatch"):
        rsup.run_resilient(                 # different fault schedule
            "plain", key, params, world.with_crash(7, at_round=11),
            cfg.n_rounds, store=rstore.CheckpointStore(cfg.base_path),
            segment_rounds=cfg.segment_rounds, sleep=sleeps.append,
        )
    assert sleeps == []


def test_is_retryable_classification():
    assert rsup.is_retryable(RuntimeError("xla runtime"))
    assert rsup.is_retryable(OSError("disk wobble"))
    assert not rsup.is_retryable(ValueError("shape mismatch"))
    assert not rsup.is_retryable(TypeError("bad arg"))
    assert not rsup.is_retryable(KeyError("state/status"))
    assert not rsup.is_retryable(AssertionError("invariant"))
    assert not rsup.is_retryable(KeyboardInterrupt())    # BaseException
    assert not rsup.is_retryable(rsup.SimulatedPreemption())


def test_monitored_shape_requires_spec(tmp_path):
    cfg = drill_cfg(tmp_path, sub="spec")
    key, params, world, _ = rh.build_workload(cfg)
    with pytest.raises(ValueError, match="MonitorSpec"):
        rsup.run_resilient(
            "monitored", key, params, world, 8,
            store=rstore.CheckpointStore(cfg.base_path),
        )
    with pytest.raises(ValueError, match="run shape"):
        rsup.run_resilient(
            "warped", key, params, world, 8,
            store=rstore.CheckpointStore(cfg.base_path),
        )


def test_monitored_resume_carries_violation_counts(tmp_path):
    """The monitor buffer rides the checkpoint: the resumed run's final
    verdict (counts, first rounds, evidence) equals the uninterrupted
    reference's exactly, and the full carry digest matches."""
    ref = rh.run_config(drill_cfg(tmp_path, shape="monitored",
                                  sub="mref"))
    cfg = drill_cfg(tmp_path, shape="monitored", sub="mkill")
    with pytest.raises(rsup.SimulatedPreemption):
        rh.run_config(cfg, kill_plan=rsup.KillPlan(
            round=12, stage="post_checkpoint", mode="raise"))
    res = rh.run_config(cfg)
    assert res.monitor_verdict == ref.monitor_verdict
    assert res.monitor_verdict["green"] is True
    assert rh.result_digest(res) == rh.result_digest(ref)


def test_legacy_single_file_lineage_adopts_and_continues(tmp_path):
    """A pre-rotation utils/checkpoint lineage (plain <base>.npz, no
    checksum, no journal) resumes through run_resilient: the user meta
    is matched, the journal starts at the adoption cursor, the
    continuation is bit-identical to an unbroken run, and the first
    rotated generation appears at the next boundary (MIGRATING.md)."""
    from scalecube_cluster_tpu.utils import checkpoint as ckpt

    cfg = drill_cfg(tmp_path, sub="legacy")
    key, params, world, _ = rh.build_workload(cfg)
    mid, _ = swim.run(key, params, world, 8)
    ckpt.save(cfg.base_path, jax.device_get(mid), next_round=8, key=key,
              meta={"who": "legacy"})

    store = rstore.CheckpointStore(cfg.base_path, keep=3)
    res = rsup.run_resilient(
        "plain", key, params, world, cfg.n_rounds, store=store,
        segment_rounds=cfg.segment_rounds, meta={"who": "legacy"},
    )
    assert res.resumed_from is not None \
        and res.resumed_from.get("legacy") is True
    mono, _ = swim.run(key, params, world, cfg.n_rounds)
    np.testing.assert_array_equal(np.asarray(mono.status),
                                  np.asarray(res.state.status))
    np.testing.assert_array_equal(np.asarray(mono.inc),
                                  np.asarray(res.state.inc))
    # Rotated, checksummed generations now exist; the legacy file stays.
    assert store.generations_on_disk()
    assert os.path.exists(cfg.base_path)
    # The journal's origin is the adoption cursor, and coverage from
    # there is complete.
    (manifest,) = tsink.read_records(res.journal_path, kind="manifest")
    assert manifest["workload"]["legacy_adoption"] is True
    assert manifest["workload"]["journal_origin"] == 8
    segs = tsink.read_records(res.journal_path, kind="segment")
    assert [r["round_start"] for r in segs][0] == 8
    assert segs[-1]["round_end"] == cfg.n_rounds
    # Wrong user meta refuses the adoption (a different run).
    cfg2 = drill_cfg(tmp_path, sub="legacy2")
    ckpt.save(cfg2.base_path, jax.device_get(mid), next_round=8,
              key=key, meta={"who": "legacy"})
    with pytest.raises(ValueError, match="meta mismatch"):
        rsup.run_resilient(
            "plain", key, params, world, cfg.n_rounds,
            store=rstore.CheckpointStore(cfg2.base_path, keep=3),
            segment_rounds=cfg.segment_rounds, meta={"who": "else"},
        )
    # Non-plain shapes cannot adopt a carry whose aux never existed.
    with pytest.raises(ValueError, match="legacy"):
        rsup.run_resilient(
            "traced", key, params, world, cfg.n_rounds,
            store=rstore.CheckpointStore(cfg2.base_path, keep=3),
            segment_rounds=cfg.segment_rounds, meta={"who": "legacy"},
        )


def test_torn_manifest_only_journal_still_gets_manifest(tmp_path):
    """A first launch killed mid-manifest-write leaves a journal whose
    ONLY content is one torn unterminated line.  The relaunch heals it
    to empty at sink reopen and must then classify it FRESH — writing
    the manifest — rather than reading the pre-heal byte count and
    skipping the manifest for the rest of the run's life."""
    cfg = drill_cfg(tmp_path, sub="tornfirst")
    journal = cfg.base_path + ".journal.jsonl"
    with open(journal, "w") as f:
        f.write('{"kind": "manifest", "run_id": "ck.journal", "schem')
    with pytest.warns(UserWarning, match="torn trailing"):
        res = rh.run_config(cfg)
    kinds = [r["kind"] for r in tsink.read_records(res.journal_path)]
    assert kinds[0] == "manifest" and kinds[-1] == "summary"
    v = rh.verify_journal(res.journal_path, cfg.n_rounds)
    assert v["complete"], v["problems"]


def test_out_of_band_journal_loss_refuses_resume(tmp_path):
    """The journal write precedes the checkpoint save, so the journal
    can never legitimately be BEHIND the cursor; a deleted journal next
    to surviving checkpoints must refuse to continue instead of leaving
    a silent interior hole in the telemetry."""
    cfg = drill_cfg(tmp_path, sub="gone")
    with pytest.raises(rsup.SimulatedPreemption):
        rh.run_config(cfg, kill_plan=rsup.KillPlan(
            round=12, stage="post_checkpoint", mode="raise"))
    journal = cfg.base_path + ".journal.jsonl"
    os.unlink(journal)
    with pytest.raises(ValueError, match="lost out-of-band"):
        rh.run_config(cfg)


def test_kill_plan_env_roundtrip():
    plan = rsup.KillPlan(round=17, stage="mid_journal")
    assert rsup.KillPlan.from_env(plan.encode()) == plan
    assert rsup.KillPlan.from_env("") is None
    with pytest.raises(ValueError, match="stage"):
        rsup.KillPlan(round=1, stage="nonsense")
