"""Vmapped chaos mega-campaign: verdict parity, bucketing, seed
stability, the minimizing reducer.

The tentpole contract of the fuzz engine (chaos/monitor.
run_monitored_batch + chaos/campaign.build_buckets/run_campaign_vmapped):
a bucketed, vmapped batch produces EXACTLY the verdicts the sequential
``run_scenario`` loop produces for the same (scenario, run-seed) pairs —
green flags, per-code totals, first-trip rounds AND the recorded
evidence lanes — while bucketing never silently drops a scenario
(singleton buckets run and are counted).  ``generate_scenario``'s
seed-stability pin locks historical (seed, severity) -> op-kind mappings
(the PR-10 trailing-draw contract) so the mega-campaign can grow tiers
without invalidating historical repro lines.  ``campaign.minimize``
shrinks a planted multi-op violation to its single guilty op on the
deliberately-weakened build (``campaign.weakened_knobs``).
"""

import dataclasses

import numpy as np
import pytest

from scalecube_cluster_tpu.chaos import campaign as cc
from scalecube_cluster_tpu.chaos import monitor as cm
from scalecube_cluster_tpu.chaos import scenarios as cs
from scalecube_cluster_tpu.telemetry import sink as tsink

pytestmark = [pytest.mark.chaos, pytest.mark.fuzz]


def test_vmapped_batch_verdict_parity_all_tiers(tmp_path):
    """One generated scenario per severity tier: the vmapped campaign's
    verdict rows — green flag, per-code violation totals, first-trip
    rounds, evidence lanes, counters, repro lines — are identical to
    the sequential runner's for the same (scenario, run seed) pairs."""
    scens = [cs.generate_scenario(seed=100 + i, n=16, severity=sev)
             for i, sev in enumerate(cs.SEVERITIES)]
    seq = cc.run_campaign(scens, seed=0)
    with tsink.TelemetrySink(str(tmp_path), prefix="fuzz") as sink:
        vm = cc.run_campaign_vmapped(scens, seed=0, sink=sink)

    assert len(vm.verdicts) == len(seq.verdicts) == 3
    for a, b in zip(seq.verdicts, vm.verdicts):
        assert a.to_json() == b.to_json()      # verdict + evidence + repro
    assert vm.summary() == seq.summary()

    # The no-silent-caps accounting: every scenario landed in exactly
    # one bucket, and the manifest carries the bucket rows.
    assert vm.buckets is not None
    assert sum(b["scenarios"] for b in vm.buckets) == 3
    bucket_rows = tsink.read_records(vm.manifest_path, kind="chaos_bucket")
    assert len(bucket_rows) == len(vm.buckets)
    assert sum(r["scenarios"] for r in bucket_rows) == 3
    (manifest,) = tsink.read_records(vm.manifest_path, kind="manifest")
    assert manifest["workload"]["kind"] == "chaos_campaign_vmapped"
    assert manifest["workload"]["bucket_sizes"] == [
        b["scenarios"] for b in vm.buckets]
    rows = tsink.read_records(vm.manifest_path, kind="chaos_scenario")
    assert [r["name"] for r in rows] == [s.name for s in scens]


def test_monitor_batch_lane_parity_shared_bucket():
    """Rows of one SHARED bucket (same compiled shape, different seeds)
    reproduce the sequential monitor states bit-for-bit — including the
    raw evidence-lane buffers, not just the verdict digest."""
    import jax

    scens = [
        cs.Scenario(name=f"crash-{v}", n_members=16, horizon=64,
                    ops=(cs.Crash(v, at_round=5),))
        for v in (3, 4, 7)
    ]
    (bucket,) = cc.build_buckets(scens, seed=9)
    assert bucket.size == 3
    mon_b, _ = cc.run_bucket(bucket, capacity=128)
    rows = cm.unstack_monitor(mon_b)
    for j, (i, (world, spec)) in enumerate(zip(bucket.indices,
                                               bucket.members)):
        _, mon, _ = cm.run_monitored(
            jax.random.key(9 + i), bucket.params, world, spec,
            bucket.horizon, capacity=128)
        assert np.array_equal(rows[j].lanes, np.asarray(mon.lanes))
        assert np.array_equal(rows[j].code_counts,
                              np.asarray(mon.code_counts))
        assert np.array_equal(rows[j].code_first_round,
                              np.asarray(mon.code_first_round))
        assert int(rows[j].count) == int(mon.count)
        assert int(rows[j].dropped) == int(mon.dropped)


def test_bucketing_never_drops_singletons_run():
    """Heterogeneous shapes split into buckets; every scenario lands in
    exactly one, singleton buckets RUN (and verdict), none are skipped."""
    scens = [
        cs.Scenario(name="a", n_members=16, horizon=64,
                    ops=(cs.Crash(3, at_round=5),)),
        cs.Scenario(name="b", n_members=16, horizon=64,
                    ops=(cs.Crash(4, at_round=7),)),
        # Different horizon -> different compiled shape -> singleton.
        cs.Scenario(name="c", n_members=16, horizon=128,
                    ops=(cs.Crash(5, at_round=5),)),
    ]
    buckets = cc.build_buckets(scens, seed=0)
    assert sorted(b.size for b in buckets) == [1, 2]
    covered = sorted(i for b in buckets for i in b.indices)
    assert covered == [0, 1, 2]

    result = cc.run_campaign_vmapped(scens, seed=0, buckets=buckets)
    assert all(v is not None for v in result.verdicts)
    assert [v.scenario.name for v in result.verdicts] == ["a", "b", "c"]
    # Horizon 64/128 ends before any completeness deadline and the
    # network is pristine: all green.
    assert result.green


SEED_STABILITY_PIN = {
    # (seed, n, severity) -> scenario name (the op-kind sequence is the
    # name's suffix).  The PR-10 trailing-draw contract: historical
    # seeds keep their historical op lists even as the mega-campaign
    # grows tiers — new severity rungs must TRAIL the existing draws,
    # never reshuffle them.  Regenerating this table means breaking
    # every historical repro line; don't.
    (100, 16, "mild"): "mild-100-leave",
    (100, 16, "moderate"): "moderate-100-churn+flap",
    (100, 16, "severe"): "severe-100-partition+churn+brownout",
    (103, 16, "mild"): "mild-103-crash_revive+config_push",
    (105, 16, "moderate"): "moderate-105-brownout+burst+config_push",
    (100, 24, "mild"): "mild-100-crash",
    (101, 24, "moderate"): "moderate-101-flap+leave+churn_arrivals",
    (105, 24, "severe"): "severe-105-partition+churn+flap"
                         "+churn_arrivals+config_push",
    (100, 32, "mild"): "mild-100-crash",
    (100, 32, "moderate"): "moderate-100-leave+burst+churn_arrivals",
    (100, 32, "severe"): "severe-100-partition+churn+brownout"
                         "+churn_arrivals",
    (103, 32, "moderate"): "moderate-103-leave+churn+churn_arrivals",
    (104, 32, "severe"): "severe-104-partition+churn+flap+config_push",
}


def test_generate_scenario_seed_stability_pin():
    for (seed, n, sev), name in SEED_STABILITY_PIN.items():
        scen = cs.generate_scenario(seed=seed, n=n, severity=sev)
        assert scen.name == name, (seed, n, sev, scen.name)


def test_generate_scenario_exact_op_pin():
    """Two fully-pinned scenarios — fields, not just kinds — so a drawn
    constant can't drift inside an unchanged kind sequence."""
    mild = cs.generate_scenario(seed=100, n=16, severity="mild")
    assert mild.horizon == 192 and mild.loss_probability == 0.0
    assert mild.ops == (cs.Leave(node=3, at_round=5),)

    mod = cs.generate_scenario(seed=100, n=32, severity="moderate")
    assert mod.horizon == 320 and mod.loss_probability == 0.02
    assert mod.ops == (
        cs.Leave(node=18, at_round=5),
        cs.CrashBurst(nodes=(7, 9, 1), at_round=4, until_round=100),
        cs.ChurnStorm(nodes=(29, 19, 23, 28), wave_size=2,
                      start_round=3, wave_every=48, down_rounds=0,
                      join_wave_size=3, join_lag=43, arrivals=(15, 4)),
    )

    # The trailing config rung (metadata plane), fully field-pinned:
    # a historical seed that draws it keeps the exact push forever.
    cfg = cs.generate_scenario(seed=103, n=16, severity="mild")
    assert cfg.name == "mild-103-crash_revive+config_push"
    assert cfg.horizon == 256 and cfg.loss_probability == 0.0
    assert cfg.ops == (
        cs.Crash(node=10, at_round=4, until_round=88),
        cs.ConfigPush(node=3, key=0, value=1, at_round=9),
    )
    assert cfg.has_metadata and cfg.metadata_keys_needed() == 1


def test_generate_fuzz_campaign_is_tiled_generate_campaign():
    fuzz = cs.generate_fuzz_campaign(100, 4, n=16)
    assert len(fuzz) == 4 * len(cs.SEVERITIES)
    assert [s.name for s in fuzz] == [
        s.name for s in cs.generate_campaign(100, 12, n=16)]


def test_minimize_shrinks_planted_violation_to_guilty_op():
    """The minimizing reducer on the weakened build: a 3-op scenario
    whose only real violation source is the permanent crash (suspicion
    timers stretched -> COMPLETENESS trips) shrinks to exactly that op,
    and the emitted repro is one executable line."""
    scen = cs.Scenario(
        name="planted", n_members=16, horizon=256,
        ops=(cs.FlappingLink(src=5, dst=9, from_round=0, n_cycles=3,
                             down_rounds=4, up_rounds=6),
             cs.Crash(3, at_round=8),
             cs.Leave(7, at_round=12)),
        loss_probability=0.02,
    )

    def weak_run(s):
        return cc.run_scenario(
            s, seed=0, knobs=lambda p: cc.weakened_knobs(s, p))

    verdict = weak_run(scen)
    assert not verdict.green
    assert verdict.verdict["codes"]["COMPLETENESS"]["violations"] > 0

    minimized = cc.minimize(
        verdict, run=weak_run,
        repro_args="knobs=lambda p: chaos.weakened_knobs(None, p)")
    assert minimized.scenario.ops == (cs.Crash(3, at_round=8),)
    assert minimized.dropped_ops == 2
    assert minimized.codes == ["COMPLETENESS"]
    assert not minimized.verdict.green
    line = minimized.repro()
    assert line.startswith("chaos.run_scenario(chaos.Scenario(")
    assert "chaos.Crash(node=3, at_round=8" in line and "\n" not in line
    # The line is EXECUTABLE under the documented namespace and replays
    # the minimized violation.
    from scalecube_cluster_tpu import chaos

    replay = eval(line, {"chaos": chaos})  # noqa: S307 — own repro line
    assert not replay.green
    assert replay.verdict["codes"]["COMPLETENESS"]["violations"] > 0


def test_minimize_requires_a_red_verdict():
    green = cc.run_scenario(
        cs.Scenario(name="green", n_members=16, horizon=64,
                    ops=(cs.Crash(3, at_round=5),)))
    assert green.green
    with pytest.raises(ValueError, match="violating verdict"):
        cc.minimize(green)


def test_weakened_rerun_reuses_compiled_batch():
    """The coverage arm's weakened knobs are traced DATA: rerunning a
    bucket weakened must hit the same compiled program (no retrace)."""
    import jax
    import jax.numpy as jnp

    scens = [
        cs.Scenario(name=f"w-{v}", n_members=16, horizon=64,
                    ops=(cs.Crash(v, at_round=5),))
        for v in (3, 4)
    ]
    (bucket,) = cc.build_buckets(scens, seed=0)
    cc.run_bucket(bucket, capacity=128)           # compiles
    kn_w = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[cc.weakened_knobs(s, bucket.params) for s in bucket.scenarios])
    before = cm.run_monitored_batch._cache_size()
    cc.run_bucket(bucket, capacity=128, knobs=kn_w)
    assert cm.run_monitored_batch._cache_size() == before
