"""Shift-delivery mode (ops/shift.py + models/swim._tick_shift).

The fast path must reproduce the protocol behavior of the exact-scatter
mode: same scenarios as tests/test_swim_model.py plus a statistical
equivalence check of detection timescales between the two modes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.ops import shift as shift_ops

from tests.test_swim_model import fast_config


def make(n, k=None, loss=0.0, **overrides):
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=k, loss_probability=loss,
        delivery="shift", **overrides,
    )
    world = swim.SwimWorld.healthy(params)
    return params, world


class TestShiftOps:
    def test_deliver_and_look_are_duals(self):
        x = jnp.arange(10, dtype=jnp.int32)
        d = shift_ops.doubled(x)
        for s in [1, 3, 9]:
            # deliver: receiver j gets sender (j - s) % n
            got = np.asarray(shift_ops.deliver(d, jnp.int32(s), 10))
            want = np.asarray([(j - s) % 10 for j in range(10)])
            np.testing.assert_array_equal(got, want)
            # look: sender i sees target (i + s) % n
            got = np.asarray(shift_ops.look(d, jnp.int32(s), 10))
            want = np.asarray([(i + s) % 10 for i in range(10)])
            np.testing.assert_array_equal(got, want)

    def test_deliver_matrix_rows(self):
        x = jnp.arange(12, dtype=jnp.int32).reshape(6, 2)
        d = shift_ops.doubled(x)
        got = np.asarray(shift_ops.deliver(d, jnp.int32(2), 6))
        np.testing.assert_array_equal(got[2], np.asarray(x[0]))


class TestShiftScenarios:
    def test_no_false_positives_lossless(self):
        params, world = make(16)
        _, metrics = swim.run(jax.random.key(0), params, world, 100)
        assert np.asarray(metrics["false_positives"]).sum() == 0
        alive_counts = np.asarray(metrics["alive"])[-1]
        assert np.all(alive_counts == params.n_members - 1)

    def test_crash_suspect_then_dead_disseminates(self):
        n = 16
        params, world = make(n)
        world = world.with_crash(0, at_round=10)
        horizon = 10 + params.ping_every * n + params.suspicion_rounds \
            + 4 * params.periods_to_spread
        _, metrics = swim.run(jax.random.key(2), params, world, horizon)
        assert np.asarray(metrics["suspect"])[:, 0].max() > 0
        assert np.asarray(metrics["dead"])[:, 0].max() > 0
        assert np.asarray(metrics["alive"])[-1, 0] == 0

    def test_determinism(self):
        params, world = make(16, loss=0.2)
        world = world.with_crash(1, at_round=5)
        _, m1 = swim.run(jax.random.key(9), params, world, 80)
        _, m2 = swim.run(jax.random.key(9), params, world, 80)
        for name in m1:
            np.testing.assert_array_equal(np.asarray(m1[name]), np.asarray(m2[name]))

    def test_restart_after_death_reaccepted(self):
        n = 10
        params, world = make(n)
        down_from = 5
        down_until = down_from + params.ping_every * n + params.suspicion_rounds \
            + 3 * params.periods_to_spread
        world = world.with_crash(2, at_round=down_from, until_round=down_until)
        final, metrics = swim.run(jax.random.key(6), params, world,
                                  down_until + 400)
        assert np.asarray(metrics["alive"])[down_until - 1, 2] < n - 1
        status = np.asarray(final.status)[:, 2]
        observers = np.arange(n) != 2
        assert np.all(status[observers] == records.ALIVE)

    def test_focal_mode_detects_crash(self):
        n = 256
        params, world = make(n, k=8, ping_known_only=False)
        world = world.with_crash(0, at_round=0)
        _, metrics = swim.run(jax.random.key(7), params, world, 400)
        alive_view = np.asarray(metrics["alive"])[:, 0]
        assert alive_view[-1] == 0, "death never fully disseminated"


class TestShiftMatchesScatterStatistically:
    def test_detection_time_same_scale(self):
        """Median full-dissemination round of a crash must be comparable
        between the two delivery modes across seeds."""
        n = 32

        def detect_round(delivery_mode, seed):
            params = swim.SwimParams.from_config(
                fast_config(), n_members=n, loss_probability=0.05,
                delivery=delivery_mode,
            )
            world = swim.SwimWorld.healthy(params).with_crash(0, at_round=0)
            _, m = swim.run(jax.random.key(seed), params, world, 300)
            alive_view = np.asarray(m["alive"])[:, 0]
            gone = alive_view == 0
            return int(np.argmax(gone)) if gone.any() else 300

        seeds = range(6)
        sc = np.median([detect_round("scatter", s) for s in seeds])
        sh = np.median([detect_round("shift", s) for s in seeds])
        assert sc < 300 and sh < 300
        ratio = sh / max(sc, 1)
        assert 0.5 < ratio < 2.0, f"shift/scatter detection ratio {ratio}"


class TestLinkFaults:
    def test_asymmetric_loss_rescued_by_ping_req(self):
        """100% loss a->b: direct pings a->b all fail, but ping-req via
        proxies rescues the verdict, so b is never declared dead and false
        suspicion stays rare (FailureDetectorTest.java:117-147)."""
        n = 8
        params, world = make(n)
        world = world.with_link_fault(src=0, dst=1, loss=1.0)
        _, metrics = swim.run(jax.random.key(11), params, world, 400)
        assert np.asarray(metrics["dead"]).sum() == 0
        # ping-req keeps the cluster healthy: no suspicion survives to the
        # end of the run.
        assert np.asarray(metrics["suspect"])[-1].sum() == 0

    def test_asymmetric_loss_without_ping_req_suspects(self):
        """Same scenario with ping-req disabled: the lost direct pings must
        produce SUSPECT verdicts (the rescue is really the proxies)."""
        n = 8
        params, world = make(n, ping_req_members=0)
        world = world.with_link_fault(src=0, dst=1, loss=1.0)
        _, metrics = swim.run(jax.random.key(12), params, world, 400)
        assert np.asarray(metrics["suspect"]).sum() > 0

    def test_block_unblock_recovers(self):
        """Block all links of one node for a window shorter than the
        suspicion timeout: suspicion arises, then the verdicts flip back
        ALIVE after unblock and refutation cancels the timers
        (NetworkEmulator block/unblock, TransportTest.java:334-355)."""
        n = 12
        params, world = make(n)
        t0, t1 = 20, 20 + params.suspicion_rounds // 2
        world = (
            world.with_block(src=(0, n), dst=3, from_round=t0, until_round=t1)
            .with_block(src=3, dst=(0, n), from_round=t0, until_round=t1)
        )
        _, metrics = swim.run(jax.random.key(13), params, world, 400)
        suspects = np.asarray(metrics["suspect"])[:, 3]
        deads = np.asarray(metrics["dead"])[:, 3]
        assert suspects.max() > 0, "block never caused suspicion"
        assert deads.sum() == 0, "node wrongly declared dead"
        assert suspects[-1] == 0, "suspicion did not clear after unblock"

    def test_scatter_mode_link_faults_too(self):
        """The same per-link rules drive the exact-scatter path."""
        n = 8
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, delivery="scatter",
        )
        world = swim.SwimWorld.healthy(params).with_link_fault(
            src=0, dst=1, loss=1.0
        )
        _, metrics = swim.run(jax.random.key(14), params, world, 400)
        assert np.asarray(metrics["dead"]).sum() == 0
        assert np.asarray(metrics["suspect"])[-1].sum() == 0


class TestGracefulLeave:
    @pytest.mark.parametrize("mode", ["scatter", "shift"])
    def test_leave_disseminates_dead_at_bumped_incarnation(self, mode):
        """A leaving member gossips DEAD@inc+1 in its final round; everyone
        converges to a non-ALIVE view of it without any suspicion phase
        (MembershipProtocolImpl.leaveCluster, :197-206)."""
        n = 12
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, delivery=mode,
        )
        world = swim.SwimWorld.healthy(params).with_leave(5, at_round=30)
        horizon = 30 + 6 * params.periods_to_spread
        final, metrics = swim.run(jax.random.key(15), params, world, horizon)
        alive_view = np.asarray(metrics["alive"])[:, 5]
        assert alive_view[-1] == 0, "leave never fully disseminated"
        # The death notice is the bumped-incarnation DEAD record, not a
        # suspicion timeout: observers that hold the tombstone store inc 1.
        status = np.asarray(final.status)[:, 5]
        inc = np.asarray(final.inc)[:, 5]
        observers = np.arange(n) != 5
        held = observers & (status == records.DEAD)
        assert held.any()
        assert np.all(inc[held] == 1)


class TestColdStartJoin:
    @pytest.mark.parametrize("mode", ["scatter", "shift"])
    def test_growth_from_seeds_to_full_view(self, mode):
        """Cold start: all rows ABSENT except self + seeds; the cluster
        must converge to everyone-sees-everyone ALIVE through the
        ABSENT->ALIVE gate (seed-chain join,
        MembershipProtocolTest.java:432-462)."""
        n = 16
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, delivery=mode,
        )
        world = swim.SwimWorld.healthy(params).with_seeds([0, 1])
        state0 = swim.initial_state(params, world, warm=False)
        assert (np.asarray(state0.status) == records.ABSENT).sum() > 0
        horizon = 12 * params.periods_to_spread
        final, metrics = swim.run(
            jax.random.key(16), params, world, horizon, state=state0
        )
        status = np.asarray(final.status)
        diag = np.eye(n, dtype=bool)
        assert np.all(status[~diag] == records.ALIVE), (
            "cold-start cluster did not converge to full membership"
        )
        # Convergence is monotone growth of the mean known-alive count.
        alive_curve = np.asarray(metrics["alive"]).sum(axis=1)
        assert alive_curve[0] < alive_curve[-1]
        assert alive_curve[-1] == n * (n - 1)
