"""Membership protocol tests, ported from the reference's
MembershipProtocolTest.java (673 LoC) — initial join, partitions with
suspicion->death, recovery, restart, seed chains, incarnation refutation —
on virtual time with seeded randomness (the reference's wall-clock
``awaitSeconds`` sleeps become exact ``sim.run_for`` calls)."""

from scalecube_cluster_tpu.oracle import Cluster, Simulator
from scalecube_cluster_tpu.records import MemberStatus


# Fast test config in the spirit of MembershipProtocolTest.java:545-554
# (sync=500ms, ping=200ms there; we keep local preset ratios).
from tests.oracle_helpers import FAST, ids


def statuses(cluster):
    return {
        r.member.id: r.status for r in cluster.membership.membership_records()
    }


def make_trio(sim, config=FAST):
    alice = Cluster.join(sim, config=config, alias="alice")
    bob = Cluster.join(sim, seeds=[alice.address], config=config, alias="bob")
    carol = Cluster.join(sim, seeds=[alice.address], config=config, alias="carol")
    sim.run_for(2_000)
    return alice, bob, carol


def test_initial_three_way_join():
    """MembershipProtocolTest.testInitialPhaseOk:57-80."""
    sim = Simulator(seed=1)
    alice, bob, carol = make_trio(sim)
    assert ids(alice.other_members()) == ["bob", "carol"]
    assert ids(bob.other_members()) == ["alice", "carol"]
    assert ids(carol.other_members()) == ["alice", "bob"]


def test_full_partition_then_recovery():
    """MembershipProtocolTest.testNetworkPartitionThenRecovery:82-310."""
    sim = Simulator(seed=2)
    alice, bob, carol = make_trio(sim)
    # Full partition of carol.
    for c in (alice, bob):
        c.network_emulator.block(carol.address)
    carol.network_emulator.block(alice.address, bob.address)

    sim.run_for(2_000)
    assert statuses(alice).get("carol") == MemberStatus.SUSPECT

    sim.run_for(15_000)  # > suspicion timeout
    assert ids(alice.other_members()) == ["bob"]
    assert ids(bob.other_members()) == ["alice"]
    assert ids(carol.other_members()) == []

    # Heal: periodic SYNC (to seeds ∪ known members) re-merges the cluster.
    for c in (alice, bob, carol):
        c.network_emulator.unblock_all()
    sim.run_for(20_000)
    assert ids(alice.other_members()) == ["bob", "carol"]
    assert ids(carol.other_members()) == ["alice", "bob"]


def test_suspicion_timeout_declares_dead_and_emits_removed():
    """MembershipProtocolTest suspicion->removal:312-366."""
    sim = Simulator(seed=3)
    alice, bob, carol = make_trio(sim)
    removed = []
    alice.membership.listen(lambda e: removed.append(e) if e.is_removed() else None)
    carol.transport.stop()  # hard crash, no leave gossip
    sim.run_for(20_000)
    assert ids(alice.other_members()) == ["bob"]
    assert [e.member.id for e in removed] == ["carol"]


def test_restart_failed_member_same_port_new_id():
    """MembershipProtocolTest.testRestartFailedMembers:368-430 — a crashed
    member's address can rejoin under a fresh id and be re-accepted."""
    sim = Simulator(seed=4)
    alice, bob, carol = make_trio(sim)
    carol_address = carol.address
    carol.transport.stop()
    sim.run_for(20_000)
    assert ids(alice.other_members()) == ["bob"]

    cfg = FAST.replace(port=carol_address.port)
    carol2 = Cluster.join(sim, seeds=[alice.address], config=cfg, alias="carol2")
    assert carol2.address == carol_address
    sim.run_for(5_000)
    assert ids(alice.other_members()) == ["bob", "carol2"]
    assert ids(carol2.other_members()) == ["alice", "bob"]


def test_seed_chain_join():
    """MembershipProtocolTest.testNodeJoinClusterWithNoInbound-shaped seed
    chains:432-462 — c only knows b, b only knows a; all converge."""
    sim = Simulator(seed=5)
    a = Cluster.join(sim, config=FAST, alias="a")
    b = Cluster.join(sim, seeds=[a.address], config=FAST, alias="b")
    sim.run_for(1_000)
    c = Cluster.join(sim, seeds=[b.address], config=FAST, alias="c")
    sim.run_for(3_000)
    assert ids(a.other_members()) == ["b", "c"]
    assert ids(c.other_members()) == ["a", "b"]


def test_incarnation_refutation_on_false_suspicion():
    """A lossy (not dead) member refutes its suspicion with a bumped
    incarnation and stays in the cluster
    (MembershipProtocolImpl.java:488-509 self-refutation)."""
    sim = Simulator(seed=6)
    alice, bob, carol = make_trio(sim)
    # Carol's outbound links are 85% lossy: acks often lost => suspicion
    # arises; suspicion gossip still reaches carol (inbound is clean) and her
    # refutation eventually squeezes through.
    carol.network_emulator.set_default_link_settings(85, 0)
    saw_suspect = False
    for _ in range(200):
        sim.run_for(500)
        if statuses(alice).get("carol") == MemberStatus.SUSPECT:
            saw_suspect = True
        if saw_suspect and carol.membership.incarnation > 0:
            break
    assert saw_suspect, "expected carol to be suspected at least once"
    assert carol.membership.incarnation > 0, "expected a refutation bump"
    # Heal the links; carol must end ALIVE everywhere (not DEAD).
    carol.network_emulator.unblock_all()
    carol.network_emulator.set_default_link_settings(0, 0)
    sim.run_for(20_000)
    assert statuses(alice).get("carol") == MemberStatus.ALIVE
    assert "carol" in ids(alice.other_members())


def test_sync_group_isolation():
    """Different sync groups never merge (MembershipProtocolImpl.java:431-437;
    ClusterJoinExamples.java:35-42 uses this as cluster isolation)."""
    sim = Simulator(seed=7)
    alice = Cluster.join(sim, config=FAST, alias="alice")
    eve_cfg = FAST.replace(sync_group="other")
    eve = Cluster.join(sim, seeds=[alice.address], config=eve_cfg, alias="eve")
    sim.run_for(10_000)
    assert alice.other_members() == []
    assert eve.other_members() == []


def test_leave_spreads_dead_at_higher_incarnation():
    """MembershipProtocolImpl.leaveCluster:197-206 — graceful leave is
    gossiped as DEAD at inc+1 and removes the member everywhere quickly
    (no suspicion timeout involved)."""
    sim = Simulator(seed=8)
    alice, bob, carol = make_trio(sim)
    removed = []
    alice.membership.listen(lambda e: removed.append(e) if e.is_removed() else None)
    bob.shutdown()
    sim.run_for(3_000)  # well under the suspicion timeout
    assert ids(alice.other_members()) == ["carol"]
    assert [e.member.id for e in removed] == ["bob"]
