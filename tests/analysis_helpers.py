"""Fixture-package builders for the swimlint suites.

``write_tree`` materializes a mini package tree that satisfies the
plane-matrix root contract (all seven entry points + the four tick-body
roots exist), so rule tests can plant ONE deliberate defect and assert
exactly ONE finding fires — and mutate a copy of the REAL package to
prove the matrix catches a deleted threading site
(tests/test_analysis_rules.py).
"""

from __future__ import annotations

import pathlib
import re
import shutil
from typing import Dict

# A structurally-faithful miniature of the real layering: SwimParams
# knobs, a dispatcher (swim_tick) fanning into three sibling tick
# bodies, the pipelined half pair sharing the dispatcher's preamble
# (_round_context), the composed scan drivers (models/compose.py) and
# seven THIN entry points across three modules delegating to them.
MINI_SWIM = '''\
import dataclasses

from scalecube_cluster_tpu.models import compose


@dataclasses.dataclass(frozen=True)
class SwimParams:
    n_members: int
    sync_interval: int = 0
    lhm_max: int = 0
    shadow_knob: int = 0


def _round_context(state, params):
    return state + params.lhm_max


def _tick_scatter(state, params):
    return state + params.sync_interval


def _tick_shift(state, params):
    return state + params.sync_interval


def _tick_shift_blocked(state, params):
    return state + params.sync_interval


def swim_tick_send(state, params):
    ctx = _round_context(state, params)
    return ctx + params.sync_interval


def swim_tick_recv(state, params):
    return state + params.sync_interval


def swim_tick(state, params):
    ctx = _round_context(state, params)
    if params.n_members > 2:
        return _tick_scatter(ctx, params)
    if state:
        return _tick_shift(ctx, params)
    return _tick_shift_blocked(ctx, params)


def run(key, params, world, n_rounds):
    return compose.composed_scan(key, params, world, n_rounds)


def run_traced(key, params, world, n_rounds):
    return compose.composed_scan(key, params, world, n_rounds)


def run_metered(key, params, world, n_rounds):
    return compose.composed_scan(key, params, world, n_rounds)
'''

MINI_COMPOSE = '''\
from scalecube_cluster_tpu.models import swim


def composed_scan(key, params, world, n_rounds, planes=()):
    state = 0
    for _ in range(n_rounds if isinstance(n_rounds, int) else 1):
        state = swim.swim_tick(state, params)
    return state


def composed_shard_scan(key, params, world, n_rounds, planes=()):
    pending = swim.swim_tick_send(0, params)
    state = swim.swim_tick_recv(pending, params)
    return swim.swim_tick(state, params)


def composed_batch_scan(keys, params, worlds, n_rounds, planes=()):
    state = 0
    for _ in range(n_rounds if isinstance(n_rounds, int) else 1):
        state = swim.swim_tick(state, params)
    return state
'''

MINI_MONITOR = '''\
from scalecube_cluster_tpu.models import compose


def run_monitored(key, params, world, n_rounds):
    return compose.composed_scan(key, params, world, n_rounds)


def run_monitored_metered(key, params, world, n_rounds):
    return compose.composed_scan(key, params, world, n_rounds)


def run_monitored_batch(keys, params, worlds, n_rounds):
    return compose.composed_batch_scan(keys, params, worlds, n_rounds)
'''

MINI_MESH = '''\
from scalecube_cluster_tpu.models import compose


def shard_run(key, params, world, n_rounds, mesh):
    return compose.composed_shard_scan(key, params, world, n_rounds)


def shard_run_metered(key, params, world, n_rounds, mesh):
    return compose.composed_shard_scan(key, params, world, n_rounds)
'''

MINI_FILES: Dict[str, str] = {
    "models/swim.py": MINI_SWIM,
    "models/compose.py": MINI_COMPOSE,
    "chaos/monitor.py": MINI_MONITOR,
    "parallel/mesh.py": MINI_MESH,
}


def write_tree(tmp_path, files: Dict[str, str],
               base: bool = True) -> pathlib.Path:
    """Write ``files`` (rel path -> source) under ``tmp_path/pkg``,
    overlaid on the MINI_FILES skeleton when ``base``."""
    root = pathlib.Path(tmp_path) / "pkg"
    merged = dict(MINI_FILES) if base else {}
    merged.update(files)
    for rel, src in merged.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    return root


def copy_real_package(tmp_path) -> pathlib.Path:
    """A mutable copy of the installed package tree."""
    from scalecube_cluster_tpu import models

    src = pathlib.Path(models.__file__).resolve().parents[1]
    dst = pathlib.Path(tmp_path) / "pkg_copy"
    shutil.copytree(src, dst,
                    ignore=shutil.ignore_patterns("__pycache__"))
    return dst


def blank_consults_in_function(path: pathlib.Path, func: str,
                               attr_expr: str, replacement: str) -> int:
    """Textually replace every ``attr_expr`` occurrence INSIDE one
    top-level function's body (from its ``def`` line to the next
    column-0 ``def``/``class``/``@``) — the "delete one real threading
    site" mutation.  Returns the number of sites blanked."""
    src = path.read_text()
    m = re.search(rf"^def {re.escape(func)}\b", src, flags=re.M)
    if m is None:
        raise AssertionError(f"{path}: no top-level def {func}")
    tail = src[m.start():]
    end = re.search(r"^(?:def |class |@)", tail[1:], flags=re.M)
    seg_end = m.start() + 1 + (end.start() if end else len(tail) - 1)
    segment = src[m.start():seg_end]
    count = segment.count(attr_expr)
    if count == 0:
        raise AssertionError(
            f"{path}::{func}: no {attr_expr!r} sites to blank — the "
            f"mutation target moved; pick another knob/function")
    path.write_text(src[:m.start()]
                    + segment.replace(attr_expr, replacement)
                    + src[seg_end:])
    return count
