"""Round fusion (SwimParams.rounds_per_step) is bit-identical to the
classic one-tick-per-step scan.

The fused scan unrolls K ticks per scan step and reshapes the stacked
[steps, K, ...] metric rows back to [rounds, ...]; a n_rounds % K
remainder runs through an unfused tail (models/swim._fused_scan).  The
contract is exact equality — every PRNG draw is a function of
(base_key, round_idx), never of scan position — for:

  - every per-round counter trace,
  - the final carry (all SwimState fields),
  - the FULL event trace of run_traced (lanes, count, overflow drops),

across rounds_per_step in {1, 2, 4}, both delivery modes, and a
crash/revive world (the scenario with the densest event stream: the
revival path exercises SUSPECTED, REMOVED, ADDED and ALIVE_REFUTED).
Also pinned here: the overlapped-offload driver
(telemetry.sink.stream_traced_run) reproduces the monolithic traced
run's event stream, metrics, and latency inputs segment-for-segment.
"""

import dataclasses

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.telemetry import sink as tsink
from scalecube_cluster_tpu.telemetry import trace as ttrace

from tests.test_swim_model import fast_config

N = 16
# 4 does NOT divide 90: the {4} case exercises the fused head + unfused
# remainder-tail concatenation too.
ROUNDS = 90


def make_params(delivery, rounds_per_step):
    return swim.SwimParams.from_config(
        fast_config(), n_members=N, delivery=delivery,
        rounds_per_step=rounds_per_step,
    )


def crash_revive_world(params):
    # Crash long enough to be removed, then revive: the densest event
    # mix (SUSPECTED -> REMOVED -> ADDED, plus refutations on the short
    # second dip).
    return (
        swim.SwimWorld.healthy(params)
        .with_crash(3, at_round=5, until_round=55)
        .with_crash(7, at_round=20, until_round=26)
    )


def state_fields(state):
    return {f.name: np.asarray(getattr(state, f.name))
            for f in dataclasses.fields(state)}


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
@pytest.mark.parametrize("k", [2, 4])
def test_fused_run_bit_identical(delivery, k):
    params_1 = make_params(delivery, 1)
    params_k = make_params(delivery, k)
    world = crash_revive_world(params_1)
    state_1, m_1 = swim.run(jax.random.key(0), params_1, world, ROUNDS)
    state_k, m_k = swim.run(jax.random.key(0), params_k, world, ROUNDS)
    assert set(m_1) == set(m_k)
    for name in m_1:
        np.testing.assert_array_equal(
            np.asarray(m_1[name]), np.asarray(m_k[name]),
            err_msg=f"{delivery}, K={k}: metric {name} diverged",
        )
    for name, v in state_fields(state_1).items():
        np.testing.assert_array_equal(
            v, state_fields(state_k)[name],
            err_msg=f"{delivery}, K={k}: state.{name} diverged",
        )


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
@pytest.mark.parametrize("k", [2, 4])
def test_fused_run_traced_trace_identical(delivery, k):
    """The full event trace — lane buffer contents, count, AND the
    overflow drop count under a deliberately too-small buffer — is
    identical under fusion: trace lanes stay per-round."""
    for capacity in (ttrace.DEFAULT_CAPACITY, 11):
        outs = []
        for rps in (1, k):
            params = make_params(delivery, rps)
            world = crash_revive_world(params)
            _, tel, metrics = swim.run_traced(
                jax.random.key(1), params, world, ROUNDS,
                trace_capacity=capacity,
            )
            outs.append((tel, metrics))
        tel_1, m_1 = outs[0]
        tel_k, m_k = outs[1]
        np.testing.assert_array_equal(
            np.asarray(tel_1.trace.lanes), np.asarray(tel_k.trace.lanes),
            err_msg=f"{delivery}, K={k}, cap={capacity}: lanes diverged",
        )
        assert int(tel_1.trace.count) == int(tel_k.trace.count)
        assert int(tel_1.trace.dropped) == int(tel_k.trace.dropped)
        if capacity == 11:
            assert int(tel_k.trace.dropped) > 0, \
                "scenario must overflow an 11-slot buffer"
        np.testing.assert_array_equal(
            np.asarray(tel_1.first_suspect), np.asarray(tel_k.first_suspect))
        np.testing.assert_array_equal(
            np.asarray(tel_1.first_removed), np.asarray(tel_k.first_removed))
        for name in m_1:
            np.testing.assert_array_equal(
                np.asarray(m_1[name]), np.asarray(m_k[name]))


def test_rounds_per_step_validation():
    with pytest.raises(ValueError, match="rounds_per_step"):
        make_params("shift", 0)


def test_stream_traced_run_matches_monolithic():
    """The segmented overlapped-offload driver reproduces the monolithic
    run_traced exactly: same decoded event stream (order included), same
    metrics, same first-suspect/first-removed matrices — with zero drops
    at default capacity."""
    params = make_params("shift", 4)
    world = crash_revive_world(params)
    key = jax.random.key(2)
    _, tel_mono, m_mono = swim.run_traced(key, params, world, ROUNDS)
    assert int(tel_mono.trace.dropped) == 0

    # 40-round segments: exercises segment remainder (90 = 40 + 40 + 10)
    # AND the fused head + tail inside each segment (40 % 4 == 0 but the
    # trailing 10-round segment has a fused head of 8 + tail of 2).
    _, res = tsink.stream_traced_run(
        key, params, world, ROUNDS, segment_rounds=40,
    )
    assert res.n_segments == 3
    assert res.dropped == 0
    assert res.events == ttrace.decode_events(tel_mono)
    assert res.recorded == int(tel_mono.trace.count)
    np.testing.assert_array_equal(
        np.asarray(tel_mono.first_suspect),
        np.asarray(res.telemetry.first_suspect))
    np.testing.assert_array_equal(
        np.asarray(tel_mono.first_removed),
        np.asarray(res.telemetry.first_removed))
    for name in m_mono:
        np.testing.assert_array_equal(
            np.asarray(m_mono[name]), res.metrics[name],
            err_msg=f"metric {name} diverged across segmentation",
        )


class TestShardedRoundFusion:
    """PR 14 (the plane-matrix's first real finding): shard_run /
    shard_run_metered honor rounds_per_step on the serial sharded path
    (the same _fused_scan — bit-identical for any K, incl. the
    90 % 4 remainder tail), and the pipelined path declares fusion
    unsupported: auto-select falls back serial-fused, ``pipelined=True``
    raises."""

    @staticmethod
    def _mesh():
        from scalecube_cluster_tpu.parallel import compat
        from scalecube_cluster_tpu.parallel import mesh as pmesh

        if not compat.HAS_SHARD_MAP:
            pytest.skip(compat.SKIP_REASON)
        return pmesh.make_mesh(1)

    @staticmethod
    def _params(rounds_per_step):
        return swim.SwimParams.from_config(
            fast_config(), n_members=N, delivery="scatter",
            rounds_per_step=rounds_per_step,
        )

    @staticmethod
    def _assert_same(tag, st_a, m_a, st_b, m_b):
        assert set(m_a) == set(m_b)
        for name in m_a:
            np.testing.assert_array_equal(
                np.asarray(m_a[name]), np.asarray(m_b[name]),
                err_msg=f"{tag}: metric {name} diverged")
        fields_b = state_fields(st_b)
        for name, v in state_fields(st_a).items():
            np.testing.assert_array_equal(
                v, fields_b[name],
                err_msg=f"{tag}: state.{name} diverged")

    def test_sharded_fused_bit_identical_and_pipelined_raises(self):
        from scalecube_cluster_tpu.parallel import mesh as pmesh

        mesh = self._mesh()
        key = jax.random.key(0)
        p1, p4 = self._params(1), self._params(4)
        world = crash_revive_world(p1)
        st1, m1 = pmesh.shard_run(key, p1, world, ROUNDS, mesh,
                                  pipelined=False)
        st4, m4 = pmesh.shard_run(key, p4, world, ROUNDS, mesh,
                                  pipelined=False)
        self._assert_same("sharded serial K=4", st1, m1, st4, m4)
        # auto-select with fusion falls back to the serial fused scan
        # (bit-identical again), instead of silently unfusing
        sta, ma = pmesh.shard_run(key, p4, world, ROUNDS, mesh)
        self._assert_same("auto-select K=4", st1, m1, sta, ma)
        # insisting on the pipeline with fusion is a loud error
        with pytest.raises(NotImplementedError, match="rounds_per_step"):
            pmesh.shard_run(key, p4, world, ROUNDS, mesh, pipelined=True)

    def test_sharded_metered_fused_registry_identical(self):
        from scalecube_cluster_tpu.parallel import mesh as pmesh

        mesh = self._mesh()
        key = jax.random.key(0)
        p1, p4 = self._params(1), self._params(4)
        world = crash_revive_world(p1)
        st1, ms1, m1 = pmesh.shard_run_metered(key, p1, world, ROUNDS,
                                               mesh, pipelined=False)
        st4, ms4, m4 = pmesh.shard_run_metered(key, p4, world, ROUNDS,
                                               mesh, pipelined=False)
        self._assert_same("sharded metered K=4", st1, m1, st4, m4)
        leaves1, tree1 = jax.tree_util.tree_flatten(ms1)
        leaves4, tree4 = jax.tree_util.tree_flatten(ms4)
        assert tree1 == tree4
        for a, b in zip(leaves1, leaves4):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg="metered registry diverged under fusion")
