"""Shared oracle-test fixtures: the sped-up timing preset + id helper.

One definition so the membership and cluster e2e suites always exercise
identical protocol timings (the analog of the reference's shared test
config, MembershipProtocolTest.java:545-554).
"""

from scalecube_cluster_tpu.config import ClusterConfig

FAST = ClusterConfig.default_local().replace(
    sync_interval=2_000, ping_interval=500, ping_timeout=200, gossip_interval=100
)


def ids(members):
    return sorted(m.id for m in members)
