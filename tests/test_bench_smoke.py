"""bench.py --smoke: the fast CPU-safe pass that keeps the telemetry
wiring honest.

The bench is the one entry point every round's measurements flow
through; its telemetry stage (traced crash scenario -> JSONL manifest
with latency histogram buckets) must not silently rot, so this tier-1
test runs the real script in a subprocess and asserts the published
contract: one JSON line on stdout, a parseable manifest with
detection-latency BUCKETS (a distribution, not a mean), and zero event
drops at the default trace capacity.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_bench_smoke_emits_result_and_manifest(tmp_path):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    result = json.loads(lines[0])

    assert "error" not in result, result
    assert "telemetry_error" not in result, result
    assert result["smoke"] is True
    assert result["value"] and result["value"] > 0
    assert result["dissemination_rounds"] > 0

    # Traced-vs-untraced contract (ISSUE 2): both throughputs present
    # and positive, overhead ratio finite and consistent.  The smoke
    # pass runs the traced + overlapped-offload pipeline with
    # rounds_per_step resolved per backend (1 on CPU — unrolling
    # measured slower there; the fused trace path itself is pinned
    # bit-identical by tests/test_round_fusion.py), so these fields are
    # the proof it executed.
    import math

    untraced = result["untraced_member_rounds_per_sec"]
    traced = result["traced_member_rounds_per_sec"]
    ratio = result["traced_overhead_ratio"]
    assert untraced > 0 and traced > 0
    assert math.isfinite(ratio) and ratio > 0
    assert ratio == pytest.approx(untraced / traced, rel=1e-3)
    assert result["rounds_per_step"] >= 1
    # value stays the untraced hot-path headline.
    assert result["value"] == untraced

    # The telemetry contract: manifest path, zero drops, real buckets.
    tele = result["telemetry"]
    assert tele["event_drops"] == 0
    assert tele["events_recorded"] > 0
    hist = tele["detection_latency_hist"]
    assert len(hist["counts"]) == len(hist["edges"]) > 1
    assert sum(hist["counts"]) > 0

    # And the manifest itself round-trips through the sink reader.
    from scalecube_cluster_tpu.telemetry import sink as tsink

    path = tele["manifest"]
    assert os.path.dirname(path) == str(tmp_path)
    kinds = {r["kind"] for r in tsink.read_records(path)}
    assert {"manifest", "counters", "histogram", "curve", "events",
            "summary"} <= kinds
    (manifest,) = tsink.read_records(path, kind="manifest")
    assert manifest["config_digest"]
    assert manifest["workload"]["smoke"] is True
    (summary,) = tsink.read_records(path, kind="summary")
    assert summary["event_drops"] == 0
    events = tsink.read_events(path)
    assert len(events) == tele["events_recorded"]
    # The crash victim's SUSPECTED/REMOVED stream is what filled the
    # histogram: every live observer contributes one detection sample.
    n = manifest["scenario"]["n_members"]
    victim = manifest["scenario"]["crash_node"]
    suspected = {e.observer for e in events
                 if e.event_type.name == "SUSPECTED"
                 and e.subject == victim}
    assert len(suspected) == n - 1
    assert sum(hist["counts"]) == n - 1
