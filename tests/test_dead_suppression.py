"""Dead-member suppression window (SwimParams.dead_suppress_rounds).

The PR-7 known debt: a partition healed MID-SUSPICION releases
freshly-hot tombstones into the healed cluster, and the merge
precedence (a DEAD record overrides any live incarnation, while a
stored tombstone reopens for any ALIVE) sustains an unbounded
DEAD/ALIVE reinfection ping-pong — the subject burns incarnations
forever (documented in models/sync.py).  The suppression window makes
each stored tombstone HOLD (no reopen) for ``dead_suppress_rounds``,
so the death notice's retransmission windows all expire while every
cell is closed, and the eventual reopens meet a cold network.

Contracts:

  1. default 0 = current behavior, bit-identical (param equality plus
     a fault-bearing run equality pin);
  2. the merge gate: a suppressed tombstone rejects ALIVE at ANY
     incarnation (reopening would re-hot the notice) and
     equal-or-lower DEAD keys, but still admits a strictly higher
     DEAD; unsuppressed it reopens per the reference rule;
  3. the headline: a mid-suspicion heal with the window OFF burns
     incarnations without bound; with a window sized past the
     suspicion + spread tail the burn STOPS within the window and
     (with the SYNC plane on) the tables fully re-converge.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.models import sync as sync_plane
from scalecube_cluster_tpu.ops import delivery

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.lifeguard


def _mid_suspicion_heal_world(params, n, split):
    """One split phase SHORTER than the quiesce bound: cross-half
    suspicions are still maturing (or freshly tombstoned and hot) when
    the heal lands."""
    world = swim.SwimWorld.healthy(params)
    part = np.zeros((8, n), np.int8)
    part[0, : n // 2] = 1
    return world.with_partition_schedule(part, split)


def test_default_off_is_bit_identical():
    params = swim.SwimParams.from_config(fast_config(), n_members=16)
    assert params.dead_suppress_rounds == 0
    explicit = dataclasses.replace(params, dead_suppress_rounds=0)
    assert explicit == params
    # A window on a run with real deaths changes nothing once the
    # window is 0-length... and a NONZERO window on a fault-free world
    # also changes nothing (nothing ever dies).
    world = swim.SwimWorld.healthy(params)
    p_w = dataclasses.replace(params, dead_suppress_rounds=24)
    s0, _ = swim.run(jax.random.key(0), params, world, 30)
    s1, _ = swim.run(jax.random.key(0), p_w, world, 30)
    for f in ("status", "inc", "suspect_deadline", "self_inc"):
        assert np.array_equal(np.asarray(getattr(s0, f)),
                              np.asarray(getattr(s1, f))), f


def test_merge_gate_suppression():
    """Unit pin of the merge rule change (ops/delivery.merge_inbox):
    suppressed tombstones reject every ALIVE and equal/lower DEAD;
    a strictly higher DEAD still lands; unsuppressed reopens."""
    entry_status = jnp.full((1, 4), records.DEAD, jnp.int8)
    entry_inc = jnp.full((1, 4), 5, jnp.int32)
    inbox = jnp.stack([
        records.merge_key(jnp.int8(records.ALIVE), jnp.int32(5)),   # equal
        records.merge_key(jnp.int8(records.ALIVE), jnp.int32(9)),   # higher
        records.merge_key(jnp.int8(records.DEAD), jnp.int32(5)),    # same
        records.merge_key(jnp.int8(records.DEAD), jnp.int32(6)),    # higher
    ])[None, :]
    alive_flags = jnp.asarray(
        [[True, True, False, False]], jnp.bool_)

    sup_status, sup_inc, sup_changed = delivery.merge_inbox(
        entry_status, entry_inc, inbox, alive_flags,
        suppress=jnp.ones((1, 4), jnp.bool_))
    assert np.asarray(sup_status).tolist()[0] == [
        records.DEAD, records.DEAD, records.DEAD, records.DEAD]
    assert np.asarray(sup_inc).tolist()[0] == [5, 5, 5, 6]
    assert np.asarray(sup_changed).tolist()[0] == [
        False, False, False, True]

    open_status, open_inc, _ = delivery.merge_inbox(
        entry_status, entry_inc, inbox, alive_flags,
        suppress=jnp.zeros((1, 4), jnp.bool_))
    # Unsuppressed: the reference reopen — ALIVE at any incarnation.
    assert np.asarray(open_status).tolist()[0][:2] == [
        records.ALIVE, records.ALIVE]


def test_suppression_expiry_rides_the_deadline_lane():
    """A tombstone formed by a fired timer carries its suppression
    expiry in ``suspect_deadline`` (and the monitor accepts it)."""
    from scalecube_cluster_tpu.chaos import monitor as cm

    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter",
        dead_suppress_rounds=40)
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=0)
    spec = cm.MonitorSpec.passive(params)
    rounds = 80
    state, mon, _ = cm.run_monitored(
        jax.random.key(1), params, world, spec, rounds)
    assert cm.verdict(mon)["green"], cm.verdict(mon)
    dl = np.asarray(state.suspect_deadline)
    st = np.asarray(state.status)
    held = dl[(st == records.DEAD) & (dl != swim.INT32_MAX)]
    assert held.size > 0                      # expiries were armed
    assert (held <= rounds + 40).all()        # bounded by the window


@pytest.mark.parametrize("supp,terminates", [(0, False), (64, True)])
def test_mid_suspicion_heal_oscillation(supp, terminates):
    """The headline pin: the mid-suspicion heal's incarnation burn is
    unbounded without the window and STOPS within it with the window
    sized past the suspicion + spread tail (suspicion_rounds=30,
    periods_to_spread=15 on the fast config — 64 covers both), after
    which the SYNC plane re-converges the tables."""
    n = 16
    split = 48              # < quiesce_bound: tombstones hot at heal
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter",
        sync_interval=8, dead_suppress_rounds=supp)
    world = _mid_suspicion_heal_world(params, n, split)

    # Settle through heal + window, then observe two later segments.
    state, _ = swim.run(jax.random.key(1), params, world,
                        split + supp + 40)
    r = split + supp + 40
    inc0 = int(np.asarray(state.self_inc).sum())
    state, _ = swim.run(jax.random.key(1), params, world, 60,
                        state=state, start_round=r)
    inc1 = int(np.asarray(state.self_inc).sum())
    state, _ = swim.run(jax.random.key(1), params, world, 60,
                        state=state, start_round=r + 60)
    inc2 = int(np.asarray(state.self_inc).sum())
    div = int(sync_plane.divergence_probe(state, params, world, r + 120))
    if terminates:
        # Burn stopped inside the window and the tables re-converged.
        assert inc1 == inc0 and inc2 == inc1, (inc0, inc1, inc2)
        assert div == 0
    else:
        # The documented unbounded regime: still burning in BOTH later
        # segments, tables still divergent.
        assert inc1 > inc0 and inc2 > inc1, (inc0, inc1, inc2)
        assert div > 0
