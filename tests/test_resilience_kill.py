"""The REAL kill-injection drills: subprocess SIGKILL + relaunch.

Tier-1 keeps one single-kill smoke case (one shape, one seeded SIGKILL,
one relaunch, verified bit-identical with a complete journal) plus the
``bench.py --resilience --smoke`` subprocess pin, shrunk through the
documented env overrides.  The full shapes x kills matrix — every run
shape SIGKILLed at multiple seeded random (round, write-stage) points —
runs under ``slow`` (and in CI-adjacent sweeps via
``experiments/resilience_drill.py`` / ``bench.py --resilience``).
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

from scalecube_cluster_tpu.resilience import harness as rh

pytestmark = pytest.mark.resilience

REPO = pathlib.Path(__file__).resolve().parent.parent

CPU_ENV = {"JAX_PLATFORMS": "cpu", "SCALECUBE_XLA_CACHE_DIR": ""}


def test_single_kill_smoke_traced(tmp_path):
    """One seeded SIGKILL against the traced shape (the richest
    telemetry surface), one relaunch: bit-identical final state,
    gap-free duplicate-free journal, event stream equal to the
    uninterrupted run's."""
    cfg = rh.DrillConfig(
        shape="traced", base_path=str(tmp_path / "drill.ckpt"),
        n_members=12, n_rounds=24, segment_rounds=8,
    )
    report = rh.run_kill_sequence(
        cfg, kill_seed=42, n_kills=1, workdir=str(tmp_path),
        extra_env=CPU_ENV,
    )
    assert report["ok"], report
    assert report["bit_identical"]
    assert report["journal_complete"], report["journal_problems"]
    assert report["events_match"] and report["events"] > 0
    # Exactly one real SIGKILL (-9) then one clean completion.
    assert [launch["returncode"] for launch in report["launches"]] \
        == [-9, 0]


@pytest.mark.slow
def test_full_kill_matrix_all_shapes(tmp_path):
    """The acceptance matrix: every run shape SIGKILLed at 3 seeded
    random (round, write-stage) points and relaunched; plus the
    corrupted-latest-generation fallback drill."""
    report = rh.run_drill(
        ("plain", "traced", "monitored"), str(tmp_path),
        kill_seed=1234, n_kills=3,
        cfg_overrides=dict(n_members=16, n_rounds=48, segment_rounds=12),
        extra_env=CPU_ENV,
    )
    assert report["green"], json.dumps(report, indent=1)
    for shape, verdict in report["shapes"].items():
        assert verdict["bit_identical"], (shape, verdict)
        assert verdict["journal_complete"], (shape, verdict)
        assert verdict["events_match"], (shape, verdict)
        # 3 SIGKILLs + the clean final completion.
        codes = [launch["returncode"] for launch in verdict["launches"]]
        assert codes.count(-9) == 3 and codes[-1] == 0, (shape, codes)
    assert report["corruption"]["ok"], report["corruption"]


def test_bench_resilience_smoke_emits_result(tmp_path):
    """bench.py --resilience --smoke: one JSON line, all shapes green,
    corruption fallback green — shrunk via the documented env overrides
    so the pin stays tier-1-safe."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_XLA_CACHE_DIR="",
        SCALECUBE_RESILIENCE_N="12",
        SCALECUBE_RESILIENCE_ROUNDS="24",
        SCALECUBE_RESILIENCE_SEGMENT="8",
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--resilience",
         "--smoke"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    result = json.loads(lines[0])

    assert "error" not in result, result
    assert result["metric"] == "resilience_drill_green_shapes"
    assert result["smoke"] is True
    assert result["green"] is True
    assert result["value"] == 3              # plain, traced, monitored
    assert sorted(result["shapes_run"]) == ["monitored", "plain",
                                            "traced"]
    for shape, verdict in result["verdicts"].items():
        assert verdict["ok"] and verdict["bit_identical"], (shape,
                                                            verdict)
        assert verdict["journal_complete"] and verdict["events_match"]
        assert len(verdict["kills"]) == 1    # smoke = single kill
    assert result["corruption"]["ok"] is True
    assert result["corruption"]["fallbacks"]  # the reason is recorded


def test_bench_rejects_resilience_with_other_modes():
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--resilience",
         "--chaos"],
        capture_output=True, text=True, timeout=60, cwd=str(REPO),
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode != 0
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1                   # one-JSON-line contract
    assert json.loads(lines[0])["value"] is None
