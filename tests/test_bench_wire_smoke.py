"""bench.py --wire --smoke: the fused-wire A/B JSON contract.

Like tests/test_bench_multichip_smoke.py for the pipelined delivery
gap: the bench is the one entry point the fused-vs-two-buffer
measurement flows through, so this tier-1 test runs the real script in
a subprocess (CPU, virtual 8-device mesh) and pins the published
contract — one JSON line with both wires' serial AND pipelined rates,
finite speedup ratios, the pipelined==serial parity probes, the
compiled-HLO 1-vs-2 full-height collective counts, the traffic model's
4-vs-5 B/slot + wire24 headroom numbers, a wire_fused_smoke.json
artifact (never the committed one), and the regress gate walking it.
"""

import json
import math
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.wire

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_bench_wire_smoke_contract(tmp_path):
    artifact = tmp_path / "wire_fused_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_WIRE_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    # The subprocess must size its own virtual mesh (conftest's 8-device
    # XLA_FLAGS hack applies to THIS process, not children).
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--wire", "--smoke"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    result = json.loads(lines[0])

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == "swim_wire_fused_member_rounds_per_sec_per_chip"
    assert result["n_devices"] >= 2
    assert result["mesh_shape"] == [result["n_devices"]]
    assert result["delivery"] == "scatter"

    # Both wires, both run shapes, measured for real; ratios finite and
    # consistent.  No floor on the smoke ratios here (a loaded CI box
    # can skew one window); the committed artifacts/wire_fused.json
    # records the pinned >= 1.0 measurements and the regress gate
    # holds future committed rounds to the floor.
    for pipe in ("serial", "pipelined"):
        fused = result[f"fused_{pipe}_member_rounds_per_sec_per_chip"]
        legacy = result[f"legacy_{pipe}_member_rounds_per_sec_per_chip"]
        ratio = result[f"fused_{pipe}_speedup_ratio"]
        assert fused > 0 and legacy > 0
        assert math.isfinite(ratio) and ratio > 0
        assert ratio == pytest.approx(fused / legacy, rel=1e-3)
    assert result["value"] == \
        result["fused_pipelined_member_rounds_per_sec_per_chip"]
    assert result["rounds_timed"] > 0

    # Within each wire the pipeline is a pure scheduling change.
    assert result["pipelined_serial_parity"] == {
        "fused": True, "legacy": True}

    # The collective-halving pins: the model's counts, and — whenever
    # the program text was parseable (it is on this runner's lowering)
    # — the compiled HLO's full-height combine count agreeing: ONE
    # instruction per round fused, the pair on the legacy wire.
    assert result["wire_collectives_per_round"] == {
        "fused": 1, "legacy": 2}
    assert result["wire_bytes_per_slot"] == {"fused": 4, "legacy": 5}
    hlo = result["hlo_full_height_collectives"]
    if hlo is not None:
        assert hlo == {"fused": 1, "legacy": 2}

    # wire24: headroom at zero extra wire bytes — same 4 B/slot as the
    # wide fused wire, with the ROADMAP saturation ladder recorded.
    assert result["wire24_bytes_per_slot"] == 4
    assert result["wire_inc_sat"]["wire16"] == 2047
    assert result["wire_inc_sat"]["wire24"] == 32767
    assert result["shift_accounting_unchanged"] is True

    # The artifact round-trips as a real (non-stub) payload and the
    # regress gate's wire checks bite on it.
    art = json.loads(artifact.read_text())
    assert art["metric"] == result["metric"]

    from scalecube_cluster_tpu.telemetry import query as tquery

    payload, skip_note = tquery.load_bench_payload(str(artifact))
    assert skip_note is None
    assert payload["fused_serial_speedup_ratio"] == \
        result["fused_serial_speedup_ratio"]
    assert result["regress"]["ok"] is True
    ok, rows = tquery.regress([str(artifact)])
    wire_checks = {r["check"] for r in rows if r.get("ok") is not None}
    assert "slo/fused_serial_speedup_ratio" in wire_checks
    assert "slo/fused_pipelined_speedup_ratio" in wire_checks
    assert "slo/wire_fused_bytes_per_slot" in wire_checks
    assert "slo/wire_fused_collectives_per_round" in wire_checks
