"""Analytic-model checks against hand-computed values from the reference
(ClusterMath.java; defaults from ClusterConfig.java:26-57)."""

import pytest

from scalecube_cluster_tpu import swim_math


@pytest.mark.parametrize(
    "n,expected",
    [(0, 0), (1, 1), (2, 2), (3, 2), (4, 3), (7, 3), (8, 4), (50, 6), (1000, 10), (10**6, 20)],
)
def test_ceil_log2(n, expected):
    assert swim_math.ceil_log2(n) == expected
    assert int(swim_math.ceil_log2_jnp(n)) == expected


def test_gossip_periods_and_time_lan_defaults():
    # n=50, repeatMult=3, interval=200ms -> 18 periods, 3.6s (SURVEY.md §6).
    assert swim_math.gossip_periods_to_spread(3, 50) == 18
    assert swim_math.gossip_dissemination_time(3, 50, 200) == 3600
    assert swim_math.gossip_periods_to_sweep(3, 50) == 38
    assert swim_math.gossip_timeout_to_sweep(3, 50, 200) == 7600


def test_max_messages():
    # n=50 LAN defaults: 3*3*6 = 54 per node (SURVEY.md §6).
    assert swim_math.max_messages_per_gossip_per_node(3, 3, 50) == 54
    assert swim_math.max_messages_per_gossip_total(3, 3, 50) == 50 * 54


def test_suspicion_timeout():
    # n=1000 LAN defaults: 5*10*1000 = 50s (SURVEY.md §6).
    assert swim_math.suspicion_timeout(5, 1000, 1000) == 50_000


def test_convergence_probability_formula():
    # Direct formula check: n - n^-(F(1-loss)R - 2), normalized.
    n, fanout, repeat, loss = 50, 3, 3, 0.25
    expected = (n - n ** -((1 - loss) * fanout * repeat - 2)) / n
    assert swim_math.gossip_convergence_probability(fanout, repeat, n, loss) == pytest.approx(expected)
    assert swim_math.gossip_convergence_percent(fanout, repeat, n, 25.0) == pytest.approx(expected * 100)
    # Lossless LAN defaults converge with overwhelming probability.
    assert swim_math.gossip_convergence_probability(3, 3, 50, 0.0) > 0.999999


def test_config_presets_and_quantization():
    from scalecube_cluster_tpu.config import ClusterConfig

    lan = ClusterConfig.default()
    assert (lan.ping_interval, lan.ping_timeout, lan.gossip_fanout) == (1000, 500, 3)
    wan = ClusterConfig.default_wan()
    assert (wan.suspicion_mult, wan.sync_interval, wan.gossip_fanout) == (6, 60_000, 4)
    local = ClusterConfig.default_local()
    assert (local.gossip_interval, local.ping_req_members, local.gossip_repeat_mult) == (100, 1, 2)

    with pytest.raises(ValueError):
        ClusterConfig(ping_timeout=1000, ping_interval=1000)

    sim = lan.to_sim(cluster_size=50)
    assert sim.ping_every == 5          # 1000ms / 200ms
    assert sim.sync_every == 150        # 30s / 200ms
    assert sim.periods_to_spread == 18
    assert sim.suspicion_rounds == 150  # 5*6*1000ms / 200ms
