"""bench.py --soak --smoke: the production-soak JSON contract.

Like tests/test_bench_alarms_smoke.py for the alarm drill: the bench
is the one entry point the soak's drift invariants flow through, so
this tier-1 test runs the real script in a subprocess (CPU) and pins
the published contract — one JSON line with the soak fields (zero
monitor violations across the lifetime, the compose compile cache flat
after segment 1, bounded RSS, the seeded mid-soak SIGKILL/relaunch
drill byte-identical to the uninterrupted run, alarms quiet), an
artifacts/soak_report.json-style artifact the query layer loads as a
real payload, and the regress gate walking it with the absolute soak
checks.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.soak

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_soak_bench(tmp_path, flags=("--soak", "--smoke"),
                    extra_env=None, timeout=840):
    artifact = tmp_path / "soak_report_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_SOAK_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), *flags],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    return json.loads(lines[0]), artifact


def test_bench_soak_smoke_contract(tmp_path):
    result, artifact = _run_soak_bench(tmp_path)

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == "soak_rounds_survived"
    # value stays None BY DESIGN (rounds survived is configured, not
    # measured — the absolute invariant gates carry the claim); the
    # payload says so.
    assert result["value"] is None
    assert "value_note" in result
    assert result["platform"] == "cpu(forced)"

    # The headline acceptance: the whole lifetime survived with zero
    # invariant violations and one compiled program.
    assert result["rounds_survived"] == (result["segments"]
                                         * result["segment_rounds"])
    assert result["violations"] == 0
    drift = result["drift"]
    assert drift["ok"], drift
    assert drift["compile_flat"] is True
    assert len(set(drift["cache_sizes"])) == 1
    assert drift["segments_sampled"] == result["segments"]
    assert drift["rss_bounded"] is True
    assert drift["monitor_green"] is True

    # The seeded mid-soak SIGKILL/relaunch drill: byte-identical
    # journal content rows, bit-identical final state digest.
    drill = result["kill_drill"]
    assert drill["ok"], drill
    assert drill["journal_match"] is True
    assert drill["state_match"] is True
    assert drill["content_rows"] == 2 * result["segments"]
    assert ":" in drill["kill"]              # "<round>:<stage>"

    # Live alarms were armed and stayed quiet.
    assert result["alarms"]["quiet"] is True
    assert result["alarms"]["transitions"] == 0
    assert result["alarms"]["specs"]         # armed, not disarmed

    # Workload provenance + the copied journal, live-tailable.
    assert result["scenario"].startswith("soak-")
    assert "run_soak" in result["repro"]
    assert os.path.exists(result["journal"])

    # The artifact round-trips and loads as a REAL (non-stub) payload.
    art = json.loads(artifact.read_text())
    assert art["metric"] == result["metric"]
    assert art["violations"] == 0

    from scalecube_cluster_tpu.telemetry import query as tquery

    payload, skip_note = tquery.load_bench_payload(str(artifact))
    assert skip_note is None
    assert payload["rounds_survived"] == result["rounds_survived"]

    # The in-bench regress gate ran and the dedicated absolute checks
    # are present and green for the fresh artifact.
    assert result["regress"]["ok"] is True
    assert result["regress"]["artifacts"] >= 1
    ok, rows = tquery.regress([str(artifact)])
    assert ok
    names = {r["check"] for r in rows}
    assert {"slo/soak_violations", "slo/soak_compile_flat",
            "slo/soak_rss_bounded", "slo/soak_kill_exactly_once",
            "slo/soak_alarms_quiet"} <= names


def test_soak_flag_is_exclusive(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--soak", "--alarms"],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode != 0
    assert "--soak" in proc.stderr


def _soak_payload(**over):
    base = {
        "metric": "soak_rounds_survived", "value": None,
        "rounds_survived": 2048, "segments": 8, "segment_rounds": 256,
        "violations": 0,
        "drift": {"ok": True, "compile_flat": True,
                  "cache_sizes": [1] * 8, "rss_bounded": True,
                  "rss_growth_mb": 3.0, "violations": 0,
                  "monitor_green": True, "segments_sampled": 8},
        "kill_drill": {"ok": True, "journal_match": True,
                       "state_match": True},
        "alarms": {"quiet": True, "transitions": 0},
    }
    base.update(over)
    return base


def test_regress_fails_on_rotted_soak_report(tmp_path):
    """A soak recording a violation, a recompile, a diverged drill or
    a noisy alarm engine must fail the gate — the committed claim
    cannot silently rot."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    bad = tmp_path / "soak_report_bad.json"
    doc = _soak_payload()
    doc["violations"] = 2
    doc["drift"] = {"ok": False, "compile_flat": False,
                    "cache_sizes": [1, 2, 3], "rss_bounded": False,
                    "rss_growth_mb": 900.0, "violations": 2,
                    "monitor_green": False, "segments_sampled": 3}
    doc["kill_drill"] = {"ok": False, "journal_match": False,
                         "state_match": True}
    doc["alarms"] = {"quiet": False, "transitions": 4}
    bad.write_text(json.dumps(doc))
    ok, rows = tquery.regress([str(bad)])
    assert not ok
    failed = {r["check"] for r in rows if r.get("ok") is False}
    assert {"slo/soak_violations", "slo/soak_compile_flat",
            "slo/soak_rss_bounded", "slo/soak_kill_exactly_once",
            "slo/soak_alarms_quiet"} <= failed


def test_regress_missing_drill_is_a_failure(tmp_path):
    """A report with no kill_drill block must read as a FAILED
    exactly-once gate, not a vacuous pass."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    bad = tmp_path / "soak_report_nodrill.json"
    doc = _soak_payload()
    del doc["kill_drill"]
    bad.write_text(json.dumps(doc))
    ok, rows = tquery.regress([str(bad)])
    assert not ok
    failed = {r["check"] for r in rows if r.get("ok") is False}
    assert "slo/soak_kill_exactly_once" in failed


def test_regress_smoke_soak_is_provenance_next_to_full(tmp_path):
    """A smoke soak sitting next to a full one is a provenance row;
    the full round carries the gates (the sync-heal fallback rule)."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    full = tmp_path / "soak_report.json"
    full.write_text(json.dumps(_soak_payload()))
    smoke = tmp_path / "soak_report_smoke.json"
    bad = _soak_payload(smoke=True)
    bad["violations"] = 7                      # would fail if gated
    smoke.write_text(json.dumps(bad))
    ok, rows = tquery.regress([str(full), str(smoke)])
    assert ok                                  # the bad smoke round skips
    notes = [r for r in rows if r.get("ok") is None
             and r["check"] == "slo/soak"]
    assert notes and "smoke" in notes[0]["note"]


@pytest.mark.slow
def test_bench_soak_full(tmp_path):
    """The full (non-smoke) soak: the committed-artifact geometry
    (n=32, 8 x 256 rounds, moderate) through the real bench, the
    aggregate gates green."""
    artifact = tmp_path / "soak_report_full.json"
    result, _ = _run_soak_bench(
        tmp_path, flags=("--soak",),
        extra_env={"SCALECUBE_SOAK_ARTIFACT": str(artifact)},
        timeout=7200)
    assert "error" not in result, result
    assert result["smoke"] is False
    assert result["violations"] == 0
    assert result["drift"]["ok"]
    assert result["kill_drill"]["ok"]
    assert result["regress"]["ok"] is True
