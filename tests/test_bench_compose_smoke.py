"""bench.py --compose --smoke: the composed full-stack A/B JSON
contract.

Like tests/test_bench_wire_smoke.py for the fused wire: the bench is
the one entry point the composed-vs-alias measurement flows through, so
this tier-1 test runs the real script in a subprocess (CPU) and pins
the published contract — one JSON line with the three interleaved arms'
rates, a finite ``compose_speedup_ratio`` consistent with the times,
the alias-parity probe all green, the compile-count arm strictly
reduced (one program per layout vs three), a compose_perf_smoke.json
artifact (never the committed one), and the regress gate walking it.
"""

import json
import math
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.compose

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_bench_compose_smoke_contract(tmp_path):
    artifact = tmp_path / "compose_perf_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_COMPOSE_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--compose", "--smoke"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    result = json.loads(lines[0])

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == \
        "swim_compose_full_stack_member_rounds_per_sec"

    # All three arms measured for real; the ratio consistent with the
    # rates.  No floor on the smoke ratio HERE (a loaded CI box can
    # skew one window); the committed artifacts/compose_perf.json
    # records the pinned >= 1.0 measurement and the regress gate holds
    # committed rounds to the floor.
    for arm in ("composed", "head_style", "bare"):
        assert result[f"{arm}_member_rounds_per_sec"] > 0
    ratio = result["compose_speedup_ratio"]
    assert math.isfinite(ratio) and ratio > 0
    assert ratio == pytest.approx(
        result["composed_member_rounds_per_sec"]
        / result["head_style_member_rounds_per_sec"], rel=1e-3)
    assert result["value"] == result["composed_member_rounds_per_sec"]
    for key in ("full_stack_overhead_ratio", "head_style_overhead_ratio"):
        assert math.isfinite(result[key]) and result[key] > 0
    assert result["rounds_timed"] > 0

    # The PARITY probe is a hard correctness gate even on smoke: the
    # composed stack produced byte-identical alias outputs.
    assert result["parity"] == {
        "final_status": True, "trace_lanes": True, "trace_count": True,
        "monitor_code_counts": True, "metrics_counters": True,
    }

    # Compile-count arm: head-style full instrumentation pays THREE
    # programs per layout, the composed stack ONE — strictly reduced,
    # per layout and in total.
    comp = result["compile"]
    assert comp["programs_head_style"] == 3 * len(comp["layouts"])
    assert comp["programs_composed"] == len(comp["layouts"])
    for row in comp["layouts"]:
        assert row["programs_head_style"] == 3
        assert row["programs_composed"] == 1

    # The artifact round-trips through the regress loader as a
    # measurement (not a skipped stub), and the in-bench gate ran.
    assert result["artifact"] == str(artifact)
    doc = json.loads(artifact.read_text())
    assert doc["compose_speedup_ratio"] == ratio
    sys.path.insert(0, str(REPO))
    from scalecube_cluster_tpu.telemetry import query as tquery

    payload, note = tquery.load_bench_payload(str(artifact))
    assert note is None and payload is not None
    assert result["regress"]["ok"] is True, result["regress"]
