"""The FUSED single-buffer wire (SwimParams.fused_wire, default True).

The scatter tick's per-round inbox exchange is ONE packed-key buffer:
the ALIVE/transmit flag is not a parallel int8 buffer but the key
word's own spare bits (dead + suspect bits clear —
ops/delivery.is_alive_key), so the merge gate derives it from the
folded winner.  Contract pinned here:

  - on deterministic-network fault schedules (crash/revive, graceful
    leave, permanent crash; loss 0) the fused wire is BIT-IDENTICAL to
    the legacy two-buffer combine across full-view / focal / compact /
    wire24 layouts — same draws, same merge winners, same timers;
  - all run shapes agree under the fused wire, and the sharded
    pipelined path equals the serial combine (single-buffer carry);
  - the ONE documented gate deviation is exactly the corner the
    SwimParams.fused_wire docstring names: an ALIVE and a strictly
    higher non-ALIVE record landing at the same ABSENT-gated cell in
    the same round — the legacy OR-gate opened on the losing ALIVE and
    stored the non-ALIVE winner; the fused gate is the reference's
    per-message null-gate (MembershipRecord.java:67-69) applied to the
    round's one folded message, so the cell stays closed until a round
    whose winner is ALIVE.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.ops import delivery

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.wire


LAYOUTS = {
    "wide": {},
    "focal": {"n_subjects": 8, "ping_known_only": False},
    "wire16": {"compact_carry": True},
    "wire24": {"compact_carry": True, "wire24": True},
}

# Faults start AFTER the bootstrap spread settles: while initial
# ABSENT cells are still opening, a stale hot ALIVE and a strictly
# higher SUSPECT about the same crashed subject can land on one
# ABSENT-gated cell in one round — the documented gate corner
# (test_fused_gate_corner_is_the_reference_null_gate), where the two
# gates transiently differ by design.
SCENARIOS = {
    "crash_revive": lambda w: w.with_crash(3, at_round=12, until_round=45),
    "leave": lambda w: w.with_leave(2, at_round=12),
    "crash_permanent": lambda w: w.with_crash(5, at_round=12),
}


def run_one(fused, layout, scenario, n=24, rounds=70, seed=0, **overrides):
    kw = dict(LAYOUTS[layout])
    kw.update(overrides)
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", fused_wire=fused,
        **kw,
    )
    world = SCENARIOS[scenario](swim.SwimWorld.healthy(params))
    return swim.run(jax.random.key(seed), params, world, rounds)


def assert_pair_identical(pair, msg):
    (s_a, m_a), (s_b, m_b) = pair
    for name in m_a:
        np.testing.assert_array_equal(
            np.asarray(m_a[name]), np.asarray(m_b[name]),
            err_msg=f"{msg}: metric {name} diverged",
        )
    for field in ("status", "inc", "spread_until", "suspect_deadline",
                  "self_inc", "epoch"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_a, field)),
            np.asarray(getattr(s_b, field)),
            err_msg=f"{msg}: state.{field} diverged",
        )


@pytest.mark.parametrize("layout", sorted(LAYOUTS))
@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_fused_identical_to_two_buffer(layout, scenario):
    """Fused vs legacy two-buffer wire, deterministic network: every
    metric row and every carry lane bit-identical."""
    pair = [run_one(fused, layout, scenario) for fused in (True, False)]
    assert_pair_identical(pair, f"{layout}/{scenario}")


def test_fused_identical_with_sync_plane():
    """The anti-entropy plane's extra scatter folds ride the SAME fused
    buffer (zero extra collectives) — identity holds with the plane on
    through a permanent crash."""
    pair = [run_one(fused, "wide", "crash_permanent", sync_interval=16)
            for fused in (True, False)]
    assert_pair_identical(pair, "sync-plane")


def test_fused_delay_ring_converges_to_the_same_table():
    """Each delay bin's combine is likewise single-buffer; under the
    fused wire the flag ring is dead weight (flags rederive from the
    ring's folded keys at open time).

    Delays re-create the documented gate corner on purpose — a DELAYED
    stale ALIVE can co-arrive with a fresher non-ALIVE winner at a
    DEAD-gated cell, so per-round metrics may transiently differ
    (test_fused_gate_corner_is_the_reference_null_gate pins the gate
    semantics) — but both gates admit the same records once any round's
    winner is ALIVE, so the arms RECONVERGE: same final table, and the
    crashed member is DEAD everywhere."""
    pair = [run_one(fused, "wide", "crash_permanent", mean_delay_ms=150.0,
                    max_delay_rounds=2, rounds=110)
            for fused in (True, False)]
    (s_f, m_f), (s_l, m_l) = pair
    for field in ("status", "inc", "self_inc"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_f, field)),
            np.asarray(getattr(s_l, field)),
            err_msg=f"delay-ring: final state.{field} diverged",
        )
    # Both arms actually converged (the identity isn't two equally
    # stuck tables): every OTHER member holds the crashed one DEAD.
    st = np.asarray(s_f.status)
    others = [i for i in range(st.shape[0]) if i != 5]
    assert (st[others, 5] == records.DEAD).all()
    # And the corner is TRANSIENT, not a drift: the last quiet rounds
    # agree on every metric.
    for name in m_f:
        np.testing.assert_array_equal(
            np.asarray(m_f[name])[-20:], np.asarray(m_l[name])[-20:],
            err_msg=f"delay-ring: late-window metric {name} diverged",
        )


def test_fused_identical_open_world_join():
    """A JOIN into a recycled slot crosses the fused wire with its
    epoch field intact: admission, trace and tables match the
    two-buffer path on a deterministic network."""
    out = []
    for fused in (True, False):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=16, delivery="scatter",
            open_world=True, fused_wire=fused,
        )
        world = swim.SwimWorld.healthy(params).with_crash(4, at_round=4)
        world = world.with_join(4, at_round=30)
        out.append(swim.run(jax.random.key(2), params, world, 60))
    assert_pair_identical(out, "open-world join")


def test_run_shapes_agree_under_fused_wire():
    """run / run_traced / run_metered / run_monitored /
    run_monitored_metered — all five run shapes end on the same table
    under the fused wire (the house all-shapes pin)."""
    from scalecube_cluster_tpu.chaos import monitor as chaos_monitor

    params = swim.SwimParams.from_config(
        fast_config(), n_members=16, delivery="scatter",
    )
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=5,
                                                      until_round=30)
    key = jax.random.key(0)
    spec = chaos_monitor.MonitorSpec.passive(params)
    finals = {}
    finals["run"], _ = swim.run(key, params, world, 50)
    finals["traced"], _, _ = swim.run_traced(key, params, world, 50)
    finals["metered"], _, _ = swim.run_metered(key, params, world, 50)
    finals["monitored"], _, _ = chaos_monitor.run_monitored(
        key, params, world, spec, 50)
    finals["monitored_metered"], _, _, _ = chaos_monitor.run_monitored_metered(
        key, params, world, spec, 50)
    base = finals.pop("run")
    for name, st in finals.items():
        for field in ("status", "inc", "self_inc"):
            np.testing.assert_array_equal(
                np.asarray(getattr(base, field)),
                np.asarray(getattr(st, field)),
                err_msg=f"{name}: state.{field} diverged from run",
            )


def test_fused_gate_corner_is_the_reference_null_gate():
    """The ONE documented deviation from the two-buffer gate, at merge
    level: an ABSENT-gated cell receiving both an ALIVE record and a
    strictly HIGHER non-ALIVE winner in one round.

      - two-buffer gate (inbox_any_alive OR-folded over all arrivals):
        the losing ALIVE opens the gate and the non-ALIVE winner is
        stored;
      - fused gate (the winner's own flag, is_alive_key of the folded
        key): the cell stays ABSENT — exactly is_overrides rule 1
        (MembershipRecord.java:67-69) applied to the folded message.

    Both agree whenever the winner itself is ALIVE — which is every
    round of a deterministic-network schedule (the identity tests
    above), where no live SUSPECT contends with a same-incarnation
    ALIVE in flight.
    """
    fmt = delivery.WIDE
    alive_key = delivery.pack_record(records.ALIVE, 3, fmt=fmt)
    suspect_key = delivery.pack_record(records.SUSPECT, 3, fmt=fmt)
    winner = jnp.maximum(alive_key, suspect_key)
    assert int(winner) == int(suspect_key)  # suspect bit wins the tie

    entry = (jnp.int8(records.ABSENT), jnp.int32(0))
    # Legacy two-buffer gate: OR of per-arrival flags == True.
    st2, inc2, ch2 = delivery.merge_inbox(
        *entry, winner, jnp.asarray(True), fmt=fmt)
    assert (int(st2), int(inc2), bool(ch2)) == (records.SUSPECT, 3, True)
    # Fused gate: the winner's own flag.
    fused_gate = delivery.is_alive_key(winner, fmt=fmt)
    assert not bool(fused_gate)
    st1, inc1, ch1 = delivery.merge_inbox(*entry, winner, fused_gate,
                                          fmt=fmt)
    assert (int(st1), bool(ch1)) == (records.ABSENT, False)

    # ALIVE winner: both gates agree (the dominant case).
    st3, inc3, ch3 = delivery.merge_inbox(
        *entry, alive_key, delivery.is_alive_key(alive_key, fmt=fmt),
        fmt=fmt)
    assert (int(st3), int(inc3), bool(ch3)) == (records.ALIVE, 3, True)


@pytest.mark.skipif(
    "not __import__('scalecube_cluster_tpu.parallel.compat', "
    "fromlist=['HAS_SHARD_MAP']).HAS_SHARD_MAP")
@pytest.mark.multichip
def test_sharded_pipelined_equals_serial_single_buffer():
    """The pipelined double-buffer carries ONE contribution buffer under
    the fused wire and stays bit-identical to the serial combine — and
    the legacy two-buffer pipeline still composes (the bench baseline).
    """
    from scalecube_cluster_tpu.parallel import mesh as pmesh

    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    mesh = pmesh.make_mesh(8)
    for fused in (True, False):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=64, fused_wire=fused,
            loss_probability=0.1,
        )
        world = swim.SwimWorld.healthy(params).with_crash(
            5, at_round=4, until_round=40)
        key = jax.random.key(0)
        f_ser, m_ser = pmesh.shard_run(key, params, world, 60, mesh,
                                       pipelined=False)
        f_pip, m_pip = pmesh.shard_run(key, params, world, 60, mesh,
                                       pipelined=True)
        for field in dataclasses.fields(f_ser):
            np.testing.assert_array_equal(
                np.asarray(getattr(f_ser, field.name)),
                np.asarray(getattr(f_pip, field.name)),
                err_msg=f"fused={fused}: state {field.name} diverged",
            )
        for name in m_ser:
            np.testing.assert_array_equal(
                np.asarray(m_ser[name]), np.asarray(m_pip[name]),
                err_msg=f"fused={fused}: metric {name} diverged",
            )
