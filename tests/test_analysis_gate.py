"""The tier-1 swimlint gate: ``analysis check`` runs CLEAN at HEAD with
the full compile-time audits — every SwimParams plane knob accounted
for across all seven run entry points, zero host callbacks in any hot
scan, compact carry lanes unwidened, and no recompile on a second
same-shape call (ISSUE 14 acceptance criteria).
"""

import json
import pathlib

import pytest

from scalecube_cluster_tpu.analysis import engine, rules

pytestmark = pytest.mark.lint

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def result():
    return engine.run_analysis()  # installed tree, compile audit on


def test_check_is_clean_at_head(result):
    assert result.ok, "\n".join(
        f"[{f.rule}] {f.path}:{f.line}: {f.message}"
        for f in result.findings)


def test_compile_audits_green_on_all_seven_entry_points(result):
    assert set(result.compile_report) == set(rules.ENTRY_POINTS)
    for entry, row in result.compile_report.items():
        assert row.get("ok") is True, (entry, row)
        if "skipped" in row:
            # environment-level skip (e.g. no shard_map on legacy JAX)
            # — mirrors the sharded test suites' skip, never a red
            continue
        assert row["host_callbacks"] == [], entry
        carry = row["scan_carry"]
        assert carry["wide_dtypes"] == [], entry
        assert carry["int16_lanes"] >= carry["int16_expected"] > 0, entry
        assert carry["int8_lanes"] >= carry["int8_expected"] > 0, entry
        rec = row["recompile"]
        # compile_audit degrades gracefully on jax builds without the
        # _cache_size API (records a skip, no finding) — the gate must
        # agree with the audit about that being acceptable
        assert rec.get("skipped") or rec.get("second_call_misses") == 0, \
            (entry, rec)


def test_matrix_is_complete_for_every_knob(result):
    """Every knob consulted anywhere in the run cones reaches ALL seven
    run shapes (the acceptance criterion: a complete plane-threading
    matrix)."""
    for field in result.fields:
        row = result.matrix["entries"][field]
        reached = {e for e, sites in row.items() if sites}
        assert reached in (set(), set(rules.ENTRY_POINTS)), (
            f"SwimParams.{field} reaches only {sorted(reached)}")


def test_committed_artifact_is_fresh(result):
    """artifacts/static_analysis.json matches HEAD: clean, same knob
    rows, same suppression set — regenerate with
    ``python -m scalecube_cluster_tpu.analysis check`` after changing
    planes or the baseline."""
    doc = json.loads((REPO / "artifacts" /
                      "static_analysis.json").read_text())
    assert doc["schema"] == engine.SCHEMA
    assert doc["ok"] is True and doc["findings_total"] == 0
    assert doc["fields"] == result.fields
    assert {s["id"] for s in doc["suppressed"]} == \
        {f.id for f in result.suppressed}
    assert set(doc["compile_audit"]) == set(rules.ENTRY_POINTS)
