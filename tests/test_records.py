"""Merge-rule truth table, ported from the reference's MembershipRecordTest
(cluster/src/test/java/io/scalecube/cluster/membership/MembershipRecordTest.java:34-108).

This table pins the SWIM merge semantics; both the scalar (oracle) and the
vectorized (TPU) forms must satisfy it bit-exactly.
"""

import itertools

import numpy as np
import pytest

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.records import ABSENT, ALIVE, DEAD, SUSPECT


def both(new_s, new_i, old_s, old_i):
    """Evaluate scalar and vectorized is_overrides; assert they agree."""
    scalar = records.is_overrides(new_s, new_i, old_s, old_i)
    vec = bool(records.is_overrides_array(new_s, new_i, old_s, old_i))
    assert scalar == vec, (
        f"scalar/vector divergence for new=({new_s},{new_i}) old=({old_s},{old_i}): "
        f"{scalar} vs {vec}"
    )
    return scalar


class TestDeadOverride:
    """MembershipRecordTest.testDeadOverride:47-65."""

    def test_dead_vs_null(self):
        assert not both(DEAD, 1, ABSENT, 0)

    @pytest.mark.parametrize("old_inc", [0, 1, 2])
    def test_dead_vs_alive(self, old_inc):
        assert both(DEAD, 1, ALIVE, old_inc)

    @pytest.mark.parametrize("old_inc", [0, 1, 2])
    def test_dead_vs_suspect(self, old_inc):
        assert both(DEAD, 1, SUSPECT, old_inc)

    @pytest.mark.parametrize("old_inc", [0, 1, 2])
    def test_dead_vs_dead(self, old_inc):
        assert not both(DEAD, 1, DEAD, old_inc)


class TestAliveOverride:
    """MembershipRecordTest.testAliveOverride:67-86."""

    def test_alive_vs_null(self):
        assert both(ALIVE, 1, ABSENT, 0)

    @pytest.mark.parametrize("old_inc,expected", [(0, True), (1, False), (2, False)])
    def test_alive_vs_alive(self, old_inc, expected):
        assert both(ALIVE, 1, ALIVE, old_inc) == expected

    @pytest.mark.parametrize("old_inc,expected", [(0, True), (1, False), (2, False)])
    def test_alive_vs_suspect(self, old_inc, expected):
        assert both(ALIVE, 1, SUSPECT, old_inc) == expected

    @pytest.mark.parametrize("old_inc", [0, 1, 2])
    def test_alive_vs_dead(self, old_inc):
        assert not both(ALIVE, 1, DEAD, old_inc)


class TestSuspectOverride:
    """MembershipRecordTest.testSuspectOverride:88-107."""

    def test_suspect_vs_null(self):
        assert not both(SUSPECT, 1, ABSENT, 0)

    @pytest.mark.parametrize("old_inc,expected", [(0, True), (1, True), (2, False)])
    def test_suspect_vs_alive(self, old_inc, expected):
        assert both(SUSPECT, 1, ALIVE, old_inc) == expected

    @pytest.mark.parametrize("old_inc,expected", [(0, True), (1, False), (2, False)])
    def test_suspect_vs_suspect(self, old_inc, expected):
        assert both(SUSPECT, 1, SUSPECT, old_inc) == expected

    @pytest.mark.parametrize("old_inc", [0, 1, 2])
    def test_suspect_vs_dead(self, old_inc):
        assert not both(SUSPECT, 1, DEAD, old_inc)


def test_equal_record_not_overriding():
    """MembershipRecordTest.testEqualRecordNotOverriding:104-108."""
    for status in (ALIVE, SUSPECT, DEAD):
        assert not both(status, 1, status, 1)


def test_vectorized_matches_scalar_exhaustively():
    """Full cross product: statuses x incarnations 0..3, batched evaluation."""
    statuses = [ALIVE, SUSPECT, DEAD, ABSENT]
    incs = [0, 1, 2, 3]
    cases = list(itertools.product(statuses, incs, statuses, incs))
    new_s = np.array([c[0] for c in cases])
    new_i = np.array([c[1] for c in cases])
    old_s = np.array([c[2] for c in cases])
    old_i = np.array([c[3] for c in cases])
    vec = np.asarray(records.is_overrides_array(new_s, new_i, old_s, old_i))
    scalar = np.array([records.is_overrides(*c[:2], *c[2:]) for c in cases])
    np.testing.assert_array_equal(vec, scalar)


def test_apply_record_dead_removes_entry():
    """Accepted DEAD deletes the table entry (MembershipProtocolImpl.java:512-516)."""
    s, i = records.apply_record(ALIVE, 3, DEAD, 1)
    assert int(s) == ABSENT
    s, i = records.apply_record(SUSPECT, 0, DEAD, 0)
    assert int(s) == ABSENT
    # ...and a later ALIVE at any incarnation is accepted again (rejoin).
    s2, i2 = records.apply_record(s, i, ALIVE, 0)
    assert int(s2) == ALIVE and int(i2) == 0


def test_merge_inbound_is_a_valid_serialization():
    """``merge_inbound`` must equal sequential ``updateMembership`` application
    under SOME arrival order — the reference delivers same-round messages in
    arbitrary order, so any permutation's outcome is a faithful schedule
    (SURVEY.md §7 'incarnation races').  Exhaustive over permutations."""
    import itertools as it

    trials = 300
    kmax = 4
    rng = np.random.RandomState(42)
    # ABSENT-padded record batches: one vectorized merge_inbound call for all
    # trials (per-call dispatch overhead would dominate otherwise).
    statuses = rng.choice([ALIVE, SUSPECT, DEAD, ABSENT], size=(trials, kmax))
    incs = rng.randint(0, 4, size=(trials, kmax))
    ks = rng.randint(1, kmax + 1, size=trials)
    for t in range(trials):
        statuses[t, ks[t] :] = ABSENT  # vary the record count via padding
    entry_s = rng.choice([ALIVE, SUSPECT, ABSENT], size=trials)
    entry_i = rng.randint(0, 4, size=trials)

    got_s, got_i = records.merge_inbound(entry_s, entry_i, statuses, incs, axis=1)
    got_s, got_i = np.asarray(got_s), np.asarray(got_i)

    def apply_scalar(s0, i0, s1, i1):
        if not records.is_overrides(s1, i1, s0, i0):
            return s0, i0
        return (ABSENT, i1) if s1 == DEAD else (s1, i1)

    for t in range(trials):
        live = [j for j in range(kmax) if statuses[t, j] != ABSENT]
        outcomes = set()
        for perm in it.permutations(live):
            seq_s, seq_i = int(entry_s[t]), int(entry_i[t])
            for j in perm:
                seq_s, seq_i = apply_scalar(seq_s, seq_i, int(statuses[t, j]), int(incs[t, j]))
            outcomes.add((seq_s, seq_i))
        assert (int(got_s[t]), int(got_i[t])) in outcomes, (
            f"trial {t}: merge_inbound={(int(got_s[t]), int(got_i[t]))} not among valid "
            f"serializations {outcomes} for entry=({entry_s[t]},{entry_i[t]}) records="
            f"{list(zip(statuses[t].tolist(), incs[t].tolist()))}"
        )


def test_merge_key_ordering():
    """DEAD absorbs; then incarnation; then SUSPECT > ALIVE; ABSENT never wins."""
    key = lambda s, i: int(records.merge_key(s, i))
    assert key(DEAD, 0) > key(SUSPECT, 100)
    assert key(SUSPECT, 2) > key(ALIVE, 1) > key(SUSPECT, 0) > key(ALIVE, 0)
    assert key(SUSPECT, 1) > key(ALIVE, 1)
    assert key(ABSENT, 100) < key(ALIVE, 0)
