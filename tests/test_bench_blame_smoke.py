"""bench.py --blame --smoke: the provenance blame drill JSON contract.

Like tests/test_bench_alarms_smoke.py for the alarm engine: the bench
is the one entry point the blame measurement flows through, so this
tier-1 test runs the real script in a subprocess (CPU) and pins the
published contract — one JSON line with the drill verdicts (blame
names the planted origin first-hand, attribution fractions sum to
1.0 with zero drops, off-switch bit-identity, the explain probe
resolves with the right channel and round), an
artifacts/provenance_blame.json-style artifact the query layer loads
as a real payload, and the regress gate walking it with the absolute
blame checks.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.provenance

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_blame_bench(tmp_path, flags=("--blame", "--smoke"),
                     extra_env=None, timeout=540):
    artifact = tmp_path / "provenance_blame_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_BLAME_ARTIFACT=str(artifact),
        SCALECUBE_BLAME_REPS="3",             # keep the timing arm short
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), *flags],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    return json.loads(lines[0]), artifact


def test_bench_blame_smoke_contract(tmp_path):
    result, artifact = _run_blame_bench(tmp_path)

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == "provenance_blame_drill"
    # value stays None BY DESIGN (attribution correctness is a verdict,
    # not a rate, and must not enter the generic throughput walk).
    assert result["value"] is None
    assert "value_note" in result

    # The headline acceptance: the blame engine, fed only the recorded
    # attributions, names the planted link's observer as the origin
    # with a first-hand fd_direct sighting.
    assert result["blame_origin_correct"] is True
    br = result["blame_report"]
    assert br["origin_observer"] == result["observer"]
    assert br["origin_channel"] == "fd_direct"
    assert br["origin_first_hand"] is True
    assert br["subject"] == result["victim"]

    # Every transition carries exactly one channel; nothing dropped on
    # either the provenance buffer or the trace buffer.
    attr = result["attribution"]
    assert attr["total_fraction"] == pytest.approx(1.0, abs=1e-9)
    assert attr["dropped"] == 0 and attr["recorded"] > 0
    assert result["trace_dropped_total"] == 0
    mix = result["channel_mix"]
    assert set(mix) and all(0.0 <= v <= 1.0 for v in mix.values())
    assert sum(mix.values()) == pytest.approx(1.0, abs=1e-5)

    # The off-switch and the explain probe.
    assert result["off_switch_identical"] is True
    ex = result["explain_check"]
    assert ex["resolved"] is True
    assert ex["channel_correct"] is True and ex["round_correct"] is True
    assert ex["answer"]["channel"] == "fd_direct"

    # Overhead measured (the smoke run reports it; the <= 1.10 gate is
    # enforced on the committed full artifact, where reps=40).
    assert result["provenance_overhead_ratio"] > 0
    assert result["provenance_armed_seconds"] > 0

    # Workload provenance + the journal, explain's fixture.
    assert result["delivery"] == "scatter"
    assert "blame_drill_scenario" in result["repro"]
    assert os.path.exists(result["journal"])

    # The artifact round-trips and loads as a REAL (non-stub) payload.
    art = json.loads(artifact.read_text())
    assert art["metric"] == result["metric"]
    assert art["blame_origin_correct"] is True

    from scalecube_cluster_tpu.telemetry import query as tquery

    payload, skip_note = tquery.load_bench_payload(str(artifact))
    assert skip_note is None
    assert payload["blame_origin_correct"] is True

    # The in-bench regress gate ran; the dedicated absolute checks are
    # present and green for the fresh artifact.
    assert result["regress"]["ok"] is True
    assert result["regress"]["artifacts"] >= 1
    ok, rows = tquery.regress([str(artifact)])
    assert ok
    names = {r["check"] for r in rows}
    assert {"slo/blame_origin_correct",
            "slo/provenance_attribution_total",
            "slo/provenance_dropped", "slo/trace_dropped_total",
            "slo/provenance_off_switch_identical",
            "slo/provenance_overhead_ratio",
            "slo/provenance_explain_resolved"} <= names

    # The journal's explain CLI resolves the seeded query end to end.
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "scalecube_cluster_tpu.telemetry",
         "explain", result["journal"],
         "--observer", str(result["observer"]),
         "--subject", str(result["victim"]),
         "--round", str(br["onset_round"]), "--json"],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=str(REPO))
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout)
    assert out["answer"]["channel"] == "fd_direct"


def test_blame_flag_is_exclusive(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--blame", "--sync"],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode != 0
    assert "--blame" in proc.stderr


def test_regress_fails_on_rotted_blame_artifact(tmp_path):
    """An artifact recording a wrong blame verdict, lossy attribution,
    a broken off-switch or a blown overhead budget must fail the gate —
    the committed claim cannot silently rot."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    bad = tmp_path / "provenance_blame_bad.json"
    bad.write_text(json.dumps({
        "metric": "provenance_blame_drill", "value": None,
        "blame_origin_correct": False,
        "attribution": {"total_fraction": 0.8, "dropped": 3},
        "trace_dropped_total": 2,
        "off_switch_identical": False,
        "provenance_overhead_ratio": 1.5,
        "explain_check": {"resolved": False},
    }))
    ok, rows = tquery.regress([str(bad)])
    assert not ok
    failed = {r["check"] for r in rows if r.get("ok") is False}
    assert {"slo/blame_origin_correct",
            "slo/provenance_attribution_total",
            "slo/provenance_dropped", "slo/trace_dropped_total",
            "slo/provenance_off_switch_identical",
            "slo/provenance_overhead_ratio",
            "slo/provenance_explain_resolved"} <= failed


def test_regress_smoke_blame_is_provenance_next_to_full(tmp_path):
    """A smoke blame drill sitting next to a full one is a provenance
    row; the full round carries the gates (the sync-heal fallback
    rule)."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    def art(path, smoke, correct):
        path.write_text(json.dumps({
            "metric": "provenance_blame_drill", "value": None,
            "smoke": smoke, "blame_origin_correct": correct,
            "attribution": {"total_fraction": 1.0, "dropped": 0},
            "trace_dropped_total": 0, "off_switch_identical": correct,
            "provenance_overhead_ratio": 1.0,
            "explain_check": {"resolved": correct,
                              "channel_correct": correct,
                              "round_correct": correct},
        }))
        return str(path)

    full = art(tmp_path / "provenance_blame.json", False, True)
    smoke = art(tmp_path / "provenance_blame_smoke.json", True, False)
    ok, rows = tquery.regress([full, smoke])
    assert ok                              # the bad smoke round skips
    notes = [r for r in rows if r.get("ok") is None
             and r["check"] == "slo/blame_drill"]
    assert notes and "smoke" in notes[0]["note"]
