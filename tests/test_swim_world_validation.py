"""World/fault-schedule builder validation + composition edges.

Satellites of ISSUE 3: ``LinkFaults.add`` argument validation (a
nonsense rule used to be silently appended and matched nothing — or,
for an out-of-range loss, skewed every Bernoulli draw it joined),
out-of-range node-id guards on the ``SwimWorld`` crash/leave/seed
builders (``jnp .at[].set`` silently drops out-of-bounds updates, so a
typo'd node id produced a healthy world and a vacuously green test),
and pinned behavior for the fault-schedule composition edges:
leave-after-crash clobbering, revive-before-crash empty windows, and
``partition_at`` phase boundaries.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config

INT32_MAX = int(jnp.iinfo(jnp.int32).max)


def make_world(n=16):
    params = swim.SwimParams.from_config(fast_config(), n_members=n)
    return params, swim.SwimWorld.healthy(params)


# --------------------------------------------------------------------------
# LinkFaults.add validation
# --------------------------------------------------------------------------


class TestLinkFaultsValidation:
    @pytest.mark.parametrize("loss", [-0.1, 1.5, 2.0])
    def test_loss_outside_unit_interval_raises(self, loss):
        with pytest.raises(ValueError, match="loss"):
            swim.LinkFaults.none().add(0, 1, loss=loss)

    @pytest.mark.parametrize("src,dst", [
        ((3, 3), 1),          # empty src range
        (0, (5, 2)),          # inverted dst range
        ((4, 1), (7, 7)),     # both
    ])
    def test_empty_or_inverted_range_raises(self, src, dst):
        with pytest.raises(ValueError, match="empty id range"):
            swim.LinkFaults.none().add(src, dst, loss=0.5)

    def test_inverted_round_window_raises(self):
        with pytest.raises(ValueError, match="round window"):
            swim.LinkFaults.none().add(0, 1, loss=0.5,
                                       from_round=10, until_round=10)
        with pytest.raises(ValueError, match="round window"):
            swim.LinkFaults.none().add(0, 1, loss=0.5,
                                       from_round=20, until_round=5)

    def test_negative_delay_raises(self):
        with pytest.raises(ValueError, match="delay_ms"):
            swim.LinkFaults.none().add(0, 1, loss=0.0, delay_ms=-1.0)

    def test_valid_rules_still_append(self):
        f = (swim.LinkFaults.none()
             .add(0, 1, loss=1.0)                        # block
             .add((0, 4), (4, 8), loss=0.3, delay_ms=5.0,
                  from_round=2, until_round=50))
        assert f.n_rules == 2
        assert float(f.loss[0]) == 1.0
        assert int(f.until_round[1]) == 50

    def test_world_builders_propagate_validation(self):
        _, world = make_world()
        with pytest.raises(ValueError, match="empty id range"):
            world.with_link_fault((2, 2), 5, loss=0.5)
        with pytest.raises(ValueError, match="round window"):
            world.with_block(0, 1, from_round=9, until_round=3)


# --------------------------------------------------------------------------
# Node-id guards (with_crash / with_leave / with_seeds)
# --------------------------------------------------------------------------


class TestNodeIdGuards:
    @pytest.mark.parametrize("bad", [-1, 16, 99, [3, 16], [-2, 5]])
    def test_with_crash_out_of_range_raises(self, bad):
        _, world = make_world(16)
        with pytest.raises(ValueError, match="with_crash"):
            world.with_crash(bad, at_round=0)

    def test_with_leave_out_of_range_raises(self):
        _, world = make_world(16)
        with pytest.raises(ValueError, match="with_leave"):
            world.with_leave(16, at_round=5)

    def test_with_seeds_out_of_range_raises(self):
        _, world = make_world(16)
        with pytest.raises(ValueError, match="with_seeds"):
            world.with_seeds([0, 16])

    def test_in_range_ids_accepted(self):
        _, world = make_world(16)
        w = (world.with_crash([0, 15], at_round=3)
                  .with_leave(7, at_round=9)
                  .with_seeds([0, 1]))
        assert int(w.down_from[15]) == 3
        assert int(w.leave_at[7]) == 9
        assert np.array_equal(np.asarray(w.seed_ids), [0, 1])


# --------------------------------------------------------------------------
# Fault-schedule composition edges (pinned behavior)
# --------------------------------------------------------------------------


class TestCompositionEdges:
    def test_leave_after_crash_clobbers_the_crash_window(self):
        """One down schedule per node: with_leave overwrites the crash
        window (down from leave+1, forever) — the later builder wins,
        like the reference's one-transport-per-node lifecycle."""
        _, world = make_world()
        w = (world.with_crash(4, at_round=10, until_round=30)
                  .with_leave(4, at_round=50))
        assert int(w.down_from[4]) == 51
        assert int(w.down_until[4]) == INT32_MAX
        assert int(w.leave_at[4]) == 50
        # The crash window [10, 30) is GONE: node 4 is alive at 20.
        assert bool(w.alive_at(20)[4])
        assert bool(w.alive_at(50)[4])       # leave round: still sends
        assert not bool(w.alive_at(51)[4])

    def test_revive_before_crash_is_an_empty_window(self):
        """until_round <= at_round: the down window is empty — the node
        is never down (alive_at tests down_from <= r < down_until)."""
        _, world = make_world()
        w = world.with_crash(3, at_round=40, until_round=40)
        alive = np.asarray(
            jnp.stack([w.alive_at(r) for r in (0, 39, 40, 41, 100)]))
        assert alive[:, 3].all()
        w2 = world.with_crash(3, at_round=40, until_round=12)
        assert bool(w2.alive_at(40)[3])

    def test_partition_at_phase_boundary_rounds(self):
        """Phase flips exactly at multiples of phase_rounds, and the
        schedule wraps modulo the phase count."""
        _, world = make_world(8)
        sched = np.stack([
            np.array([0] * 4 + [1] * 4, dtype=np.int8),
            np.zeros(8, dtype=np.int8),
        ])
        w = world.with_partition_schedule(sched, phase_rounds=10)
        assert np.asarray(w.partition_at(0)).tolist() == sched[0].tolist()
        assert np.asarray(w.partition_at(9)).tolist() == sched[0].tolist()
        assert np.asarray(w.partition_at(10)).tolist() == [0] * 8
        assert np.asarray(w.partition_at(19)).tolist() == [0] * 8
        # Wrap: round 20 re-enters phase 0 (the rolling schedule).
        assert np.asarray(w.partition_at(20)).tolist() == sched[0].tolist()

    def test_crash_then_recrash_overwrites_window(self):
        """with_crash on an already-crashed node replaces (not merges)
        its window — last write wins on the single down schedule."""
        _, world = make_world()
        w = (world.with_crash(2, at_round=5, until_round=20)
                  .with_crash(2, at_round=40, until_round=60))
        assert bool(w.alive_at(10)[2])       # first window clobbered
        assert not bool(w.alive_at(45)[2])


# --------------------------------------------------------------------------
# with_join validation (open-world JOIN schedule, PR 10)
# --------------------------------------------------------------------------


class TestWithJoinValidation:
    """``with_join`` mirrors the crash/leave guards and enforces the
    recycled-slot precondition: the slot must be scheduled dead
    strictly before the join and still down AT the join round."""

    def test_out_of_range_slot_raises(self):
        _, world = make_world()
        with pytest.raises(ValueError, match="with_join"):
            world.with_crash(2, 5).with_join(99, 10)

    def test_join_into_live_slot_raises(self):
        _, world = make_world()
        with pytest.raises(ValueError, match="LIVE slot"):
            world.with_join(3, at_round=10)

    def test_join_before_death_raises(self):
        _, world = make_world()
        with pytest.raises(ValueError, match="strictly after"):
            world.with_crash(3, at_round=10).with_join(3, at_round=10)
        with pytest.raises(ValueError, match="strictly after"):
            world.with_crash(3, at_round=10).with_join(3, at_round=4)

    def test_join_at_or_before_leave_raises(self):
        _, world = make_world()
        with pytest.raises(ValueError, match="strictly after"):
            world.with_leave(3, at_round=10).with_join(3, at_round=10)

    def test_join_over_scheduled_revival_raises(self):
        """crash -> revive -> join would put two identities in sequence
        with no death between the revival and the join — refuse."""
        _, world = make_world()
        with pytest.raises(ValueError, match="revive the OLD identity"):
            (world.with_crash(3, at_round=5, until_round=20)
                  .with_join(3, at_round=30))

    def test_valid_join_revives_slot_as_new_epoch(self):
        _, world = make_world()
        w = world.with_crash(3, at_round=5).with_join(3, at_round=30)
        assert int(w.join_at[3]) == 30
        # Ground truth: dead during [5, 30), alive (new identity) after.
        assert not bool(w.alive_at(10)[3])
        assert bool(w.alive_at(30)[3])
        assert int(w.epoch_at(29)[3]) == 0
        assert int(w.epoch_at(30)[3]) == 1
        assert bool(w.joining_at(30)[3])
        assert not bool(w.joining_at(31)[3])

    def test_join_after_leave_is_valid(self):
        _, world = make_world()
        w = world.with_leave(3, at_round=10).with_join(3, at_round=30)
        assert int(w.join_at[3]) == 30
        assert bool(w.alive_at(31)[3])

    def test_second_join_without_second_death_raises(self):
        """One join per slot per run: re-joining requires re-killing
        first (the previous join's revival reads as a live occupant)."""
        _, world = make_world()
        w = world.with_crash(3, at_round=5).with_join(3, at_round=20)
        with pytest.raises(ValueError, match="revive the OLD identity"):
            w.with_join(3, at_round=40)
