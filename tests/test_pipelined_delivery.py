"""Pipelined ICI delivery (parallel/mesh._pipelined_rounds): the
double-buffered cross-device inbox combine must be a pure SCHEDULING
change — bit-identical protocol output vs the serial in-round combine
on a fixed mesh, across layouts, run shapes, and fault schedules.

The HLO-placement facts (combine pair carried into the next loop body,
async start/done overlap on TPU lowerings) are pinned in
tests/test_traffic.py; this file pins semantics.
"""

import dataclasses

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.parallel import compat
from scalecube_cluster_tpu.parallel import mesh as pmesh

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.skipif(not compat.HAS_SHARD_MAP,
                                reason=compat.SKIP_REASON)


def make(n, k=None, loss=0.0, **overrides):
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=k, loss_probability=loss,
        **overrides,
    )
    return params, swim.SwimWorld.healthy(params)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return pmesh.make_mesh(8)


def assert_states_equal(a, b, msg=""):
    for field in dataclasses.fields(a):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, field.name)),
            np.asarray(getattr(b, field.name)),
            err_msg=f"{msg}: state field {field.name} diverged",
        )


def assert_runs_identical(params, world, mesh, n_rounds, key_seed=0,
                          start_round=0, msg=""):
    key = jax.random.key(key_seed)
    f_ser, m_ser = pmesh.shard_run(key, params, world, n_rounds, mesh,
                                   start_round=start_round, pipelined=False)
    f_pip, m_pip = pmesh.shard_run(key, params, world, n_rounds, mesh,
                                   start_round=start_round, pipelined=True)
    assert_states_equal(f_ser, f_pip, msg=msg)
    assert set(m_ser) == set(m_pip)
    for name in m_ser:
        np.testing.assert_array_equal(
            np.asarray(m_ser[name]), np.asarray(m_pip[name]),
            err_msg=f"{msg}: metric {name} diverged",
        )


class TestBitIdenticalParity:
    def test_fullview_crash_revive_loss(self, mesh8):
        params, world = make(64, loss=0.15)
        world = world.with_crash(5, at_round=4, until_round=60)
        assert_runs_identical(params, world, mesh8, 100,
                              msg="full-view crash/revive")

    def test_focal_mode(self, mesh8):
        """The 1M-member sharded configuration in miniature: K << N,
        cluster-uniform probing."""
        params, world = make(512, k=8, ping_known_only=False, loss=0.05)
        world = world.with_crash(2, at_round=0)
        assert_runs_identical(params, world, mesh8, 120, key_seed=1,
                              msg="focal")

    @pytest.mark.parametrize("layout", ["int16_wire", "compact_carry"])
    def test_compact_layouts(self, mesh8, layout):
        """The int16 wire (and the re-relativized compact carry) must
        survive the extra round the pending buffers spend in the scan
        carry without dtype promotion or encode drift."""
        params, world = make(64, loss=0.1, **{layout: True})
        world = world.with_crash(5, at_round=4, until_round=60)
        assert_runs_identical(params, world, mesh8, 90, key_seed=2,
                              msg=layout)

    def test_user_gossips_ride_pipeline(self, mesh8):
        """User-gossip infection bits share the carried contribution;
        their delivery round (and so the infection curve) must not
        shift by the deferred combine."""
        params, world = make(64, n_user_gossips=3)
        world = world.with_spread(0, origin=3, at_round=2)
        world = world.with_spread(1, origin=9, at_round=5)
        assert_runs_identical(params, world, mesh8, 60, key_seed=3,
                              msg="user gossip")

    def test_leave_and_partition(self, mesh8):
        params, world = make(64, loss=0.05)
        world = world.with_leave(7, at_round=6)
        world = world.with_partition_schedule(
            [[0] * 32 + [1] * 32, [0] * 64], phase_rounds=10
        )
        assert_runs_identical(params, world, mesh8, 80, key_seed=4,
                              msg="leave+partition")

    def test_single_round_window(self, mesh8):
        """n_rounds=1 runs prologue + epilogue with an empty scan —
        the resume-loop edge (segmented supervisors step one window at
        a time)."""
        params, world = make(32)
        assert_runs_identical(params, world, mesh8, 1, key_seed=5,
                              msg="one round")

    def test_nonzero_start_round_resume(self, mesh8):
        """Windowed execution: running [0, 30) then [30, 60) pipelined
        must equal one serial [0, 60) window (the checkpoint-resume
        contract under the pipeline)."""
        params, world = make(32, loss=0.1)
        world = world.with_crash(3, at_round=10, until_round=45)
        key = jax.random.key(6)
        f_ser, _ = pmesh.shard_run(key, params, world, 60, mesh8,
                                   pipelined=False)
        mid, _ = pmesh.shard_run(key, params, world, 30, mesh8,
                                 pipelined=True)
        f_pip, _ = pmesh.shard_run(key, params, world, 30, mesh8,
                                   state=mid, start_round=30,
                                   pipelined=True)
        assert_states_equal(f_ser, f_pip, msg="resume")


class TestMeteredParity:
    def test_metered_registry_identical(self, mesh8):
        """shard_run_metered through the pipeline: per-round metrics AND
        the psum-combined registry must match the serial twin exactly
        (the observe hook sees the same pre-merge state per round)."""
        from scalecube_cluster_tpu.telemetry import metrics as tmetrics

        params, world = make(64, loss=0.1)
        world = world.with_crash(5, at_round=4, until_round=60)
        spec = tmetrics.MetricsSpec.default()
        key = jax.random.key(7)
        f_ser, ms_ser, m_ser = pmesh.shard_run_metered(
            key, params, world, 90, mesh8, spec=spec, pipelined=False
        )
        f_pip, ms_pip, m_pip = pmesh.shard_run_metered(
            key, params, world, 90, mesh8, spec=spec, pipelined=True
        )
        assert_states_equal(f_ser, f_pip, msg="metered")
        for name in m_ser:
            np.testing.assert_array_equal(
                np.asarray(m_ser[name]), np.asarray(m_pip[name]),
                err_msg=f"metered metric {name}",
            )
        for leaf_s, leaf_p in zip(jax.tree.leaves(ms_ser),
                                  jax.tree.leaves(ms_pip)):
            np.testing.assert_array_equal(
                np.asarray(leaf_s), np.asarray(leaf_p),
                err_msg="metered registry diverged",
            )


class TestResolutionAndGuards:
    def test_auto_resolution_shift_falls_back(self, mesh8):
        """pipelined=None on a shift config silently runs the serial
        path (shift's ppermutes are already per-channel)."""
        params, world = make(64, delivery="shift")
        _, m = pmesh.shard_run(jax.random.key(8), params, world, 20, mesh8)
        assert np.asarray(m["alive"]).shape[0] == 20

    def test_pipelined_true_on_shift_raises(self, mesh8):
        params, world = make(64, delivery="shift")
        with pytest.raises(NotImplementedError, match="pipelined delivery"):
            pmesh.shard_run(jax.random.key(9), params, world, 20, mesh8,
                            pipelined=True)

    def test_pipelined_true_on_delay_rings_raises(self, mesh8):
        params, world = make(64, max_delay_rounds=2)
        with pytest.raises(NotImplementedError, match="delay"):
            pmesh.shard_run(jax.random.key(10), params, world, 20, mesh8,
                            pipelined=True)

    def test_seed_gated_fullview_falls_back(self, mesh8):
        """Configured seeds enable the in-round anti-entropy round trip
        — auto-resolution must fall back to serial, and the run still
        work."""
        params, world = make(64)
        world = world.with_seeds([0, 1])
        _, m = pmesh.shard_run(jax.random.key(11), params, world, 20, mesh8)
        assert np.asarray(m["alive"]).shape[0] == 20
        with pytest.raises(NotImplementedError, match="anti-entropy"):
            pmesh.shard_run(jax.random.key(11), params, world, 20, mesh8,
                            pipelined=True)

    def test_make_mesh_too_few_devices_raises(self):
        n_avail = len(jax.devices())
        with pytest.raises(ValueError, match="requested"):
            pmesh.make_mesh(n_avail + 1)

    def test_make_mesh_all_devices_default(self):
        mesh = pmesh.make_mesh()
        assert mesh.devices.size == len(jax.devices())


@pytest.mark.slow
@pytest.mark.multichip
class TestMeshSweepSlow:
    """The scale ladder over the full virtual mesh: parity at every
    rung (the cheap CI shadow of experiments/multichip_sweep.py, which
    sweeps real meshes past the pinned single-chip ceiling)."""

    @pytest.mark.parametrize("n,k", [(1024, 8), (4096, 8), (8192, 16)])
    def test_ladder_parity(self, mesh8, n, k):
        params, world = make(n, k=k, ping_known_only=False, loss=0.02)
        world = world.with_crash(2, at_round=0)
        assert_runs_identical(params, world, mesh8, 60, key_seed=12,
                              msg=f"ladder {n}x{k}")
