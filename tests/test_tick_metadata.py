"""Host-side metadata on the dense tick (utils/metadata.py).

The reference's metadata protocol: content is never gossiped — the
owner's incarnation bump travels via membership, and observers pull
content keyed by the incarnation they saw (MetadataStoreImpl.java:
106-146, 149-186; MembershipProtocolImpl.java:572-584).  These tests pin
the tick-side analog end to end at small N; the 1M demonstration is
examples/metadata_at_scale.py.
"""

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.utils import metadata as md

from tests.test_swim_model import fast_config


def setup(n=32, delivery="shift", **overrides):
    params = swim.SwimParams.from_config(fast_config(), n_members=n,
                                         delivery=delivery, **overrides)
    world = swim.SwimWorld.healthy(params)
    store = md.TickMetadataStore()
    for i in range(n):
        store.put(i, 0, {"name": f"m{i}", "version": 0})
    return params, world, store


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_update_propagates_and_is_queryable(delivery):
    n = 32
    params, world, store = setup(n, delivery)
    key = jax.random.key(0)
    state, _ = swim.run(key, params, world, 20)

    # Owner 3 updates its metadata between scan chunks.
    state = store.update(state, params, world, 3, {"name": "m3", "version": 1},
                         current_round=20)
    # Before dissemination: another observer still resolves version 0.
    assert store.view(state, params, world, 9, 3, round_idx=20) == {
        "name": "m3", "version": 0}
    # The owner immediately sees its own new version.
    assert store.view(state, params, world, 3, 3, round_idx=20)["version"] == 1

    # Host snapshot BEFORE the run: swim.run donates its state argument
    # (the carry buffers are reused in place), so the device arrays may
    # be gone afterwards — the documented don't-reuse-a-donated-state
    # caveat (README Telemetry > Performance).
    prev = jax.device_get(state)
    state, m = swim.run(key, params, world, 40, state=state, start_round=20)
    # The bump disseminated: every observer now fetches version 1.
    for obs in (0, 9, 17, 31):
        assert store.view(state, params, world, obs, 3,
                          round_idx=60)["version"] == 1, obs
    # The UPDATED-event stream carried the wave (observer, subject=3,
    # 0 -> 1 transitions).
    events = md.updated_events(prev, state, world)
    bumps = [(o, s, a, b) for o, s, a, b in events if s == 3]
    assert len(bumps) == n - 1, len(bumps)
    assert all(a == 0 and b == 1 for _, _, a, b in bumps)


def test_refutation_bump_resolves_to_prior_content():
    """A refutation bumps incarnation WITHOUT a metadata change — the
    fetch must return the existing content at the highest registered
    version <= the seen incarnation (the reference's fetch is content-
    at-owner, unchanged by the refutation)."""
    n = 24
    params, world, store = setup(n)
    # Crash + revive node 5: its revival refutes its death at a bumped
    # incarnation nobody registered metadata for.
    world = world.with_crash(5, at_round=4, until_round=40)
    state, _ = swim.run(jax.random.key(1), params, world, 120)
    snap = swim.node_snapshot(state, params, world, 0)
    assert 5 in snap["alive_members"]
    seen = snap["record_incarnations"][5]
    assert seen >= 1                       # the refutation bump traveled
    assert store.view(state, params, world, 0, 5)["name"] == "m5"


def test_update_requires_tracked_subject():
    params = swim.SwimParams.from_config(fast_config(), n_members=64,
                                         n_subjects=8)
    world = swim.SwimWorld.healthy(params)
    store = md.TickMetadataStore()
    state = swim.initial_state(params, world)
    with pytest.raises(ValueError, match="tracked subject"):
        store.update(state, params, world, 40, {"x": "y"}, current_round=0)


def test_update_compact_carry_layout():
    """The bump + window-reopen writes respect the compact encodings."""
    import dataclasses
    params = dataclasses.replace(
        swim.SwimParams.from_config(fast_config(), n_members=24,
                                    delivery="shift"),
        compact_carry=True,
    )
    world = swim.SwimWorld.healthy(params)
    store = md.TickMetadataStore()
    store.put(3, 0, {"v": 0})
    key = jax.random.key(0)
    state, _ = swim.run(key, params, world, 20)
    state = store.update(state, params, world, 3, {"v": 1}, current_round=20)
    state, _ = swim.run(key, params, world, 40, state=state, start_round=20)
    assert store.view(state, params, world, 11, 3, round_idx=60) == {"v": 1}
