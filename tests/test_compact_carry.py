"""compact_carry (int16 wire + narrow relative carry) is protocol-trace-
identical to the wide layout.

The compact layout exists to raise the full-view [N, N] single-chip
capacity ceiling (SwimParams.compact_carry docstring; measured on TPU in
experiments/fullview_scale.py).  Its contract: below the saturation
points (incarnation 8191, deadline 32766 rounds ahead) every protocol
outcome is bit-identical to the wide layout — same PRNG draws, same
merge winners, same timers — because the encodings are lossless in range
and re-relativized each round.
"""

import dataclasses

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config


def run_pair(n, rounds, world_fn=None, seed=0, **overrides):
    """(wide metrics+state, compact metrics+state) for the same scenario."""
    out = []
    for compact in (False, True):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, compact_carry=compact, **overrides
        )
        world = swim.SwimWorld.healthy(params)
        if world_fn is not None:
            world = world_fn(world)
        state, metrics = swim.run(jax.random.key(seed), params, world, rounds)
        out.append((state, metrics))
    return out


SCENARIOS = {
    "crash_revive": lambda w: w.with_crash(3, at_round=5, until_round=60),
    "leave": lambda w: w.with_leave(2, at_round=10),
    "asym_link": lambda w: w.with_link_fault(1, 4, loss=0.9),
    "partition": lambda w: w.with_partition_schedule(
        np.r_[np.zeros(16), np.ones(16)].astype(np.int8), phase_rounds=40
    ),
}


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_compact_trace_identical(delivery, scenario):
    (s_w, m_w), (s_c, m_c) = run_pair(
        32, 120, SCENARIOS[scenario], delivery=delivery,
        loss_probability=0.1,
    )
    for name in m_w:
        np.testing.assert_array_equal(
            np.asarray(m_w[name]), np.asarray(m_c[name]),
            err_msg=f"{scenario}/{delivery}: metric {name} diverged",
        )
    # Final tables agree (the compact state decoded at the final cursor).
    dec = swim._carry_decode(s_c, 120)
    np.testing.assert_array_equal(np.asarray(s_w.status), np.asarray(dec.status))
    np.testing.assert_array_equal(np.asarray(s_w.inc), np.asarray(dec.inc))
    np.testing.assert_array_equal(
        np.asarray(s_w.self_inc), np.asarray(dec.self_inc)
    )
    # Timers: equal wherever pending; cancelled is INT32_MAX in both.
    np.testing.assert_array_equal(
        np.asarray(s_w.suspect_deadline == swim.INT32_MAX),
        np.asarray(dec.suspect_deadline == swim.INT32_MAX),
    )
    pending = np.asarray(s_w.suspect_deadline) != swim.INT32_MAX
    np.testing.assert_array_equal(
        np.asarray(s_w.suspect_deadline)[pending],
        np.asarray(dec.suspect_deadline)[pending],
    )


def test_compact_state_dtypes_and_size():
    params = swim.SwimParams.from_config(
        fast_config(), n_members=16, compact_carry=True
    )
    state = swim.initial_state(params, swim.SwimWorld.healthy(params))
    assert state.inc.dtype == np.int16
    assert state.spread_until.dtype == np.int8
    assert state.suspect_deadline.dtype == np.int16
    assert state.status.dtype == np.int8
    # 6 B/cell of [N, K] carry vs 13 wide.
    per_cell = sum(a.dtype.itemsize for a in
                   (state.status, state.inc, state.spread_until,
                    state.suspect_deadline))
    assert per_cell == 6


def test_compact_checkpoint_roundtrip(tmp_path):
    from scalecube_cluster_tpu.utils import checkpoint

    params = swim.SwimParams.from_config(
        fast_config(), n_members=24, compact_carry=True, delivery="shift",
    )
    world = swim.SwimWorld.healthy(params).with_crash(1, at_round=3)
    path = str(tmp_path / "ck.npz")
    final_a, chunks = checkpoint.run_checkpointed(
        swim.run, jax.random.key(7), params, world, 60, path, chunk=25,
        state=swim.initial_state(params, world),
    )
    final_b, _ = swim.run(jax.random.key(7), params, world, 60)
    np.testing.assert_array_equal(np.asarray(final_a.status),
                                  np.asarray(final_b.status))
    assert final_a.inc.dtype == np.int16


def test_compact_far_deadline_becomes_no_timer():
    """A traced Knobs.suspicion_rounds beyond the int16 horizon (the
    FD-isolation pattern: push timers past the run) must NOT silently
    fire ~32766 rounds in — it encodes as "no timer", so suspicions
    never mature, and observable behavior matches the wide layout for
    any run shorter than the horizon."""
    import jax.numpy as jnp

    from scalecube_cluster_tpu.models import fd as fdmodel

    rounds, n = 120, 32
    out = {}
    for compact in (False, True):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, loss_probability=0.3,
            delivery="shift", compact_carry=compact,
        )
        knobs = dataclasses.replace(
            fdmodel.fd_only_knobs(params),
            ping_every=jnp.int32(1),
            suspicion_rounds=jnp.int32(1_000_000),
        )
        world = swim.SwimWorld.healthy(params)
        state, metrics = swim.run(jax.random.key(5), params, world, rounds,
                                  knobs=knobs)
        out[compact] = (state, metrics)
    (s_w, m_w), (s_c, m_c) = out[False], out[True]
    # Suspicions happened but never matured, identically in both layouts.
    assert np.asarray(m_w["suspect"]).sum() > 0
    for name in m_w:
        np.testing.assert_array_equal(np.asarray(m_w[name]),
                                      np.asarray(m_c[name]), err_msg=name)
    assert np.asarray(m_w["dead"]).sum() == 0
    # Wide holds far deadlines; compact dropped them to the sentinel.
    assert (np.asarray(s_w.suspect_deadline) < swim.INT32_MAX).any()
    dl_c = np.asarray(s_c.suspect_deadline)
    assert (dl_c == 32767).all()


def test_compact_node_snapshot_matches_wide():
    """The JMX-analog snapshot decodes the compact encodings: absolute
    deadlines, int32 incarnations, sentinel timers excluded."""
    rounds = 60
    (s_w, _), (s_c, _) = run_pair(
        24, rounds, lambda w: w.with_crash(3, at_round=5),
        delivery="shift", loss_probability=0.2, seed=9,
    )
    params_w = swim.SwimParams.from_config(fast_config(), n_members=24)
    params_c = dataclasses.replace(params_w, compact_carry=True)
    world = swim.SwimWorld.healthy(params_w)
    for node in (0, 7):
        snap_w = swim.node_snapshot(s_w, params_w, world, node,
                                    round_idx=rounds)
        snap_c = swim.node_snapshot(s_c, params_c, world, node,
                                    round_idx=rounds)
        assert snap_w == snap_c, (node, snap_w, snap_c)


@pytest.mark.slow
def test_compact_sharded_matches_wide_sharded():
    """The compact layout under shard_map (int16 payload blocks riding
    the ppermute rotations) equals the WIDE layout under the same
    sharding, metric for metric.  (Sharded runs are not bit-identical
    to single-device ones in either layout — per-device PRNG folding —
    so the layout-equivalence comparison is made at equal sharding.)
    @slow: two 250-round 128-member runs on the virtual 8-device mesh —
    the heaviest case in this file by an order of magnitude."""
    import jax as jax_mod

    from scalecube_cluster_tpu.parallel import compat
    if not compat.HAS_SHARD_MAP:
        pytest.skip(compat.SKIP_REASON)

    from scalecube_cluster_tpu.parallel import mesh as pmesh

    assert len(jax_mod.devices()) >= 8
    params = swim.SwimParams.from_config(
        fast_config(), n_members=128, delivery="shift", compact_carry=True,
    )
    world = swim.SwimWorld.healthy(params).with_crash(
        9, at_round=2, until_round=150
    )
    mesh = pmesh.make_mesh(8)
    _, m_shard = pmesh.shard_run(jax.random.key(13), params, world, 250, mesh)
    # Not bit-identical to single-device (per-device PRNG folding), so
    # compare against the WIDE sharded run — layouts must agree exactly
    # under the same sharding.
    params_w = dataclasses.replace(params, compact_carry=False)
    _, m_wide = pmesh.shard_run(jax.random.key(13), params_w, world, 250, mesh)
    for name in m_shard:
        np.testing.assert_array_equal(
            np.asarray(m_shard[name]), np.asarray(m_wide[name]),
            err_msg=f"sharded compact vs wide diverged on {name}",
        )
    # The crash+heal cycle completed.
    alive9 = np.asarray(m_shard["alive"])[:, 9]
    assert np.asarray(m_shard["dead"])[:, 9].max() > 0
    assert alive9[-1] == params.n_members - 1


@pytest.mark.parametrize("compact", [False, True])
def test_roll_payload_delivery_is_bit_identical(compact):
    """shift_roll_payloads (jnp.roll per channel instead of a persistent
    doubled [2N, K] buffer — the capacity variant) must not change a
    single bit of the trace in either carry layout."""
    params = swim.SwimParams.from_config(
        fast_config(), n_members=48, delivery="shift",
        compact_carry=compact, loss_probability=0.15,
    )
    params_roll = dataclasses.replace(params, shift_roll_payloads=True)
    world = (swim.SwimWorld.healthy(params)
             .with_crash(4, at_round=10, until_round=80))
    s_a, m_a = swim.run(jax.random.key(21), params, world, 150)
    s_b, m_b = swim.run(jax.random.key(21), params_roll, world, 150)
    for name in m_a:
        np.testing.assert_array_equal(np.asarray(m_a[name]),
                                      np.asarray(m_b[name]), err_msg=name)
    np.testing.assert_array_equal(np.asarray(s_a.status),
                                  np.asarray(s_b.status))
    np.testing.assert_array_equal(np.asarray(s_a.inc), np.asarray(s_b.inc))


def test_compact_validation():
    base = swim.SwimParams.from_config(fast_config(), n_members=16)
    # The delay ring is supported under compact_carry (int16 wire slots) —
    # see test_compact_delay_ring_trace_identical.
    dataclasses.replace(base, compact_carry=True, max_delay_rounds=2)
    with pytest.raises(ValueError, match="suspicion"):
        dataclasses.replace(base, compact_carry=True,
                            suspicion_rounds=40_000)
    with pytest.raises(ValueError, match="spread"):
        dataclasses.replace(base, compact_carry=True, periods_to_spread=200)


def test_compact_node_snapshot_requires_round_idx():
    """A compact state's relative encodings have no correct default cursor
    — omitting round_idx must raise, not silently decode against 0."""
    params = dataclasses.replace(
        swim.SwimParams.from_config(fast_config(), n_members=16),
        compact_carry=True,
    )
    world = swim.SwimWorld.healthy(params)
    state = swim.initial_state(params, world)
    with pytest.raises(ValueError, match="round_idx"):
        swim.node_snapshot(state, params, world, node_id=0)
    # The wide layout stays optional (its state is absolute).
    params_w = swim.SwimParams.from_config(fast_config(), n_members=16)
    state_w = swim.initial_state(params_w, world)
    swim.node_snapshot(state_w, params_w, world, node_id=0)


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_compact_delay_ring_trace_identical(delivery):
    """The delayed-delivery ring under compact_carry (int16 wire slots)
    is bit-identical to the wide layout's int32 ring — same delay bins,
    same late arrivals, same merges."""
    (s_w, m_w), (s_c, m_c) = run_pair(
        24, 100, lambda w: w.with_crash(3, at_round=5),
        delivery=delivery, loss_probability=0.1,
        mean_delay_ms=100.0, max_delay_rounds=2,
    )
    assert str(s_c.inbox_ring.dtype) == "int16"
    assert str(s_w.inbox_ring.dtype) == "int32"
    for name in m_w:
        np.testing.assert_array_equal(
            np.asarray(m_w[name]), np.asarray(m_c[name]),
            err_msg=f"delay/{delivery}: metric {name} diverged",
        )
