"""bench.py --sync --smoke: the partition-heal convergence JSON contract.

Like tests/test_bench_multichip_smoke.py for the delivery pipeline: the
bench is the one entry point the heal measurement flows through, so this
tier-1 test runs the real script in a subprocess (CPU) and pins the
published contract — one JSON line with the convergence fields (the
plane converged inside the window with POST_HEAL_DIVERGENCE 0, the
gossip-only control still divergent), an artifacts/sync_heal.json-style
artifact the query layer loads as a real payload, the regress gate
walking it with the absolute convergence checks, and the
``sync_rounds_to_converge`` SLO surfaced from the JSONL manifest.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.sync

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_sync_bench(tmp_path, extra_env=None, timeout=540):
    artifact = tmp_path / "sync_heal_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_SYNC_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--sync", "--smoke"],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    return json.loads(lines[0]), artifact


def test_bench_sync_smoke_contract(tmp_path):
    result, artifact = _run_sync_bench(tmp_path)

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == "sync_heal_rounds_to_converge"
    # value stays None BY DESIGN (smaller-is-better must not enter the
    # generic throughput walk); the payload says so.
    assert result["value"] is None
    assert "value_note" in result

    # The headline acceptance: the plane converged inside the bounded
    # window with zero post-heal divergence, the monitored
    # chaos-campaign-scale arm is green, and the gossip-only control
    # demonstrably did NOT converge.
    assert result["converged"] is True
    assert 1 <= result["sync_rounds_to_converge"] <= result["window_rounds"]
    assert result["post_heal_divergence"] == 0
    assert result["monitored_green"] is True
    assert result["monitored_control_divergence"] > 0
    assert result["gossip_only_converged"] is False
    assert result["gossip_only_divergence"] > 0
    assert result["divergence_at_heal"] > 0   # the split really diverged

    # Workload provenance + the traffic comparison figures.
    assert result["delivery"] == "shift"
    assert result["sync_interval"] > 0
    assert result["split_rounds"] > 0 and result["window_rounds"] > 0
    assert result["sync_exchange_bytes_per_member"] > 0
    assert result["piggyback_bytes_per_member_round"] > 0

    # The artifact round-trips and loads as a REAL (non-stub) payload.
    art = json.loads(artifact.read_text())
    assert art["metric"] == result["metric"]
    assert art["sync_rounds_to_converge"] == result["sync_rounds_to_converge"]

    from scalecube_cluster_tpu.telemetry import query as tquery

    payload, skip_note = tquery.load_bench_payload(str(artifact))
    assert skip_note is None
    assert payload["converged"] is True

    # The in-bench regress gate ran and the dedicated absolute checks
    # are present and green for the fresh artifact.
    assert result["regress"]["ok"] is True
    assert result["regress"]["artifacts"] >= 1
    ok, rows = tquery.regress([str(artifact)])
    assert ok
    names = {r["check"] for r in rows}
    assert {"slo/sync_heal_converged", "slo/post_heal_divergence",
            "slo/gossip_only_diverges",
            "slo/sync_converge_within_window"} <= names

    # The SLO surface: the manifest's summary row folds into
    # sync_rounds_to_converge.
    report = tquery.load_report(result["manifest"])
    slos = tquery.compute_slos(report)
    assert slos["sync_rounds_to_converge"] == (
        result["sync_rounds_to_converge"])


def test_regress_fails_on_unconverged_heal(tmp_path):
    """A sync_heal artifact recording a failed heal (or lingering
    divergence) must fail the gate — the committed claim cannot
    silently rot."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    bad = tmp_path / "sync_heal_bad.json"
    bad.write_text(json.dumps({
        "metric": "sync_heal_rounds_to_converge", "value": None,
        "sync_rounds_to_converge": None, "converged": False,
        "post_heal_divergence": 3, "gossip_only_converged": False,
        "window_rounds": 100, "sync_interval": 32,
    }))
    ok, rows = tquery.regress([str(bad)])
    assert not ok
    failed = {r["check"] for r in rows if r.get("ok") is False}
    assert "slo/sync_heal_converged" in failed
    assert "slo/post_heal_divergence" in failed


def test_regress_bands_convergence_series(tmp_path):
    """The convergence-time series gates within the band, floored at
    one exchange interval (phase luck of the heal round must not make
    a lucky prior a knife edge)."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    def art(path, rounds):
        path.write_text(json.dumps({
            "metric": "sync_heal_rounds_to_converge", "value": None,
            "sync_rounds_to_converge": rounds, "converged": True,
            "post_heal_divergence": 0, "gossip_only_converged": False,
            "window_rounds": 200, "sync_interval": 32,
        }))
        return str(path)

    a = art(tmp_path / "sync_heal_r01.json", 1)       # lucky phase
    ok, _ = tquery.regress([a, art(tmp_path / "sync_heal_r02.json", 30)])
    assert ok                                          # inside the floor
    ok, rows = tquery.regress(
        [a, art(tmp_path / "sync_heal_r03.json", 120)])
    assert not ok
    assert any(r["check"] == "slo/sync_rounds_to_converge"
               and r["ok"] is False for r in rows)


@pytest.mark.slow
def test_bench_sync_full_convergence(tmp_path):
    """The full (non-smoke) convergence measurement.  The design-target
    scale is N=1M on an accelerator; under the CPU-forced test
    environment the same workload runs at a CPU-feasible N so the full
    code path (real split quiesce, probe loop, control arm, regress
    gate) is still exercised end to end."""
    artifact = tmp_path / "sync_heal_full.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_SYNC_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",
        # 1M on CPU would run for hours; the env override keeps the
        # FULL (non-smoke) path honest at a feasible scale.  On a real
        # accelerator drop the override for the 1M measurement.
        SCALECUBE_SYNC_N=os.environ.get("SCALECUBE_SYNC_N", "65536"),
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--sync"],
        capture_output=True, text=True, timeout=3000, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "error" not in result, result
    assert result["smoke"] is False
    assert result["converged"] is True
    assert result["post_heal_divergence"] == 0
    assert result["monitored_green"] is True
    assert result["gossip_only_converged"] is False
    assert result["sync_rounds_to_converge"] <= result["window_rounds"]
