"""User-payload gossip co-running with membership in the swim tick.

The reference's gossip component carries arbitrary user gossips AND
membership piggyback through one machinery (GossipProtocolImpl.java:
124-128 spread(), 139-157 doSpreadGossip; membership piggybacks via
spreadMembershipGossip, MembershipProtocolImpl.java:620-635).  The tick
analog: ``SwimParams.n_user_gossips`` + ``SwimWorld.with_spread``.

Contract under test:
  - user-gossip bits ride the SAME channels/loss draws as membership
    records (no new PRNG draws: membership traces are bit-identical to a
    G=0 run);
  - dissemination follows the ClusterMath O(log n) schedule while crash
    detection runs concurrently;
  - crashed origins can't spread; crashed receivers are frozen; delayed
    delivery shares the membership payload's bins.
"""

import dataclasses

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu import swim_math
from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config


def run_gossip(n, rounds, g=1, delivery="shift", world_fn=None, seed=0,
               **overrides):
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery=delivery, n_user_gossips=g,
        **overrides,
    )
    world = swim.SwimWorld.healthy(params)
    if world_fn is not None:
        world = world_fn(world)
    state, m = swim.run(jax.random.key(seed), params, world, rounds)
    return params, state, m


def first_full_round(m, n, g=0):
    curve = np.asarray(m["user_gossip_infected"])[:, g]
    full = np.flatnonzero(curve >= n)
    return int(full[0]) if full.size else None


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
class TestUserGossip:
    def test_membership_trace_unchanged_by_user_gossip(self, delivery):
        """Adding user gossips must not perturb the membership machinery:
        no new PRNG draws, bit-identical protocol traces."""
        n, rounds = 48, 60
        _, _, m_g = run_gossip(
            n, rounds, g=3, delivery=delivery,
            world_fn=lambda w: (w.with_crash(5, at_round=8)
                                .with_spread(0, 1, 0)
                                .with_spread(1, 20, 10)
                                .with_spread(2, 40, 25)),
            loss_probability=0.1,
        )
        params0 = swim.SwimParams.from_config(
            fast_config(), n_members=n, delivery=delivery,
            loss_probability=0.1,
        )
        world0 = swim.SwimWorld.healthy(params0).with_crash(5, at_round=8)
        _, m_0 = swim.run(jax.random.key(0), params0, world0, rounds)
        for name in m_0:
            if name == "messages_gossip":
                continue  # wire count legitimately includes user gossip
            np.testing.assert_array_equal(
                np.asarray(m_0[name]), np.asarray(m_g[name]), err_msg=name
            )

    def test_dissemination_tracks_cluster_math(self, delivery):
        """Lossless dissemination completes within the reference's spread
        schedule: periodsToSpread = repeatMult * ceil(log2(n+1))
        (ClusterMath.java:111-113) — the gossip stops spreading after
        that, so full coverage must happen within it."""
        n = 128
        params, _, m = run_gossip(
            n, 60, delivery=delivery,
            world_fn=lambda w: w.with_spread(0, 7, 0),
        )
        full_at = first_full_round(m, n)
        assert full_at is not None
        assert full_at <= params.periods_to_spread, (
            full_at, params.periods_to_spread)
        # And it takes at least ~log2(n)/log2(1+fanout) rounds (growth
        # is at most (1+fanout)x per round).
        lower = int(np.floor(np.log(n) / np.log(1 + params.fanout)))
        assert full_at >= lower, (full_at, lower)

    def test_spread_windows_close(self, delivery):
        """After dissemination completes, retransmission windows expire
        (sweepGossips analog): wire gossip traffic returns to zero."""
        n = 64
        params, state, m = run_gossip(
            n, 120, delivery=delivery,
            world_fn=lambda w: w.with_spread(0, 3, 0),
        )
        msgs = np.asarray(m["messages_gossip"])
        assert msgs[:3].sum() > 0
        assert msgs[-20:].sum() == 0  # everyone's window closed
        assert np.asarray(state.g_infected).all()

    def test_crashed_origin_does_not_spread(self, delivery):
        n = 32
        _, _, m = run_gossip(
            n, 40, delivery=delivery,
            world_fn=lambda w: (w.with_crash(3, at_round=0)
                                .with_spread(0, 3, 5)),
        )
        assert np.asarray(m["user_gossip_infected"]).sum() == 0

    def test_crashed_receiver_frozen_then_reachable_after_revival(
            self, delivery):
        """A node down during dissemination misses the gossip; after
        revival it can still be infected while senders' windows are open
        (a fresh infection resets the window at each new member)."""
        n = 32
        params, state, m = run_gossip(
            n, 100, delivery=delivery, seed=2,
            world_fn=lambda w: (w.with_crash(9, at_round=0, until_round=8)
                                .with_spread(0, 3, 0)),
        )
        curve = np.asarray(m["user_gossip_infected"])[:, 0]
        infected = np.asarray(state.g_infected)[:, 0]
        assert curve[7] <= n - 1          # node 9 can't have it while down
        assert infected[9]                # but gets it after revival
        assert curve[-1] == n

    def test_co_running_with_crash_detection(self, delivery):
        """The verdict scenario: infection curves AND crash detection in
        one run, both completing."""
        n = 96
        params, _, m = run_gossip(
            n, 80, g=2, delivery=delivery,
            world_fn=lambda w: (w.with_crash(11, at_round=2)
                                .with_spread(0, 0, 0)
                                .with_spread(1, 50, 20)),
        )
        assert first_full_round(m, n - 1, 0) is not None  # crashed node 11 may miss g0
        dead_view = np.asarray(m["dead"])[:, 11]
        assert dead_view[-1] >= n - 2      # everyone declared node 11 dead

    def test_delayed_user_gossip_rides_membership_bins(self, delivery):
        """With mean delay ~ the round length, dissemination still
        completes (late bits land via the g_ring) — and determinism
        holds."""
        n = 48
        params, _, m1 = run_gossip(
            n, 120, delivery=delivery, mean_delay_ms=100.0,
            max_delay_rounds=2,
            world_fn=lambda w: w.with_spread(0, 5, 0),
        )
        _, _, m2 = run_gossip(
            n, 120, delivery=delivery, mean_delay_ms=100.0,
            max_delay_rounds=2,
            world_fn=lambda w: w.with_spread(0, 5, 0),
        )
        assert first_full_round(m1, n) is not None
        np.testing.assert_array_equal(
            np.asarray(m1["user_gossip_infected"]),
            np.asarray(m2["user_gossip_infected"]),
        )


def test_user_gossip_compact_carry_trace_identical():
    """G fields stay int32/bool in both carry layouts; traces match."""
    outs = []
    for compact in (False, True):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=32, delivery="shift",
            n_user_gossips=2, compact_carry=compact, loss_probability=0.1,
        )
        world = (swim.SwimWorld.healthy(params)
                 .with_crash(3, at_round=5)
                 .with_spread(0, 1, 0).with_spread(1, 30, 12))
        _, m = swim.run(jax.random.key(4), params, world, 80)
        outs.append(m)
    for name in outs[0]:
        np.testing.assert_array_equal(
            np.asarray(outs[0][name]), np.asarray(outs[1][name]),
            err_msg=name,
        )


def test_user_gossip_sharded_matches_semantics():
    """8-device sharded run: injection lands on the right shard, curves
    complete, metrics replicate."""
    from scalecube_cluster_tpu.parallel import mesh as pmesh

    n = 64
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="shift", n_user_gossips=2,
    )
    world = (swim.SwimWorld.healthy(params)
             .with_spread(0, 2, 0)      # shard 0 origin
             .with_spread(1, 61, 4))    # last-shard origin
    mesh = pmesh.make_mesh(8)
    _, m = pmesh.shard_run(jax.random.key(0), params, world, 50, mesh)
    curve = np.asarray(m["user_gossip_infected"])
    assert curve[0, 0] >= 1
    assert (curve[-1] == n).all(), curve[-1]


def test_checkpoint_resume_with_user_gossip(tmp_path):
    """Kill-and-resume carries the G state bit-exactly."""
    from scalecube_cluster_tpu.utils import checkpoint as ckpt

    params = swim.SwimParams.from_config(
        fast_config(), n_members=32, delivery="shift", n_user_gossips=1,
    )
    world = swim.SwimWorld.healthy(params).with_spread(0, 3, 2)
    key = jax.random.key(0)
    s_full, m_full = swim.run(key, params, world, 40)

    s_half, _ = swim.run(key, params, world, 20)
    path = str(tmp_path / "g.npz")
    ckpt.save(path, s_half, 20, key=key)
    s_loaded, next_round, key_loaded, _ = ckpt.load(path)
    s_resumed, _ = swim.run(key_loaded, params, world, 20,
                            state=s_loaded, start_round=next_round)
    np.testing.assert_array_equal(np.asarray(s_full.g_infected),
                                  np.asarray(s_resumed.g_infected))
    np.testing.assert_array_equal(np.asarray(s_full.status),
                                  np.asarray(s_resumed.status))


def test_old_checkpoint_without_g_fields_loads(tmp_path):
    """Pre-user-gossip checkpoints load as G=0 layouts."""
    import numpy as onp
    from scalecube_cluster_tpu.utils import checkpoint as ckpt

    params = swim.SwimParams.from_config(fast_config(), n_members=16)
    world = swim.SwimWorld.healthy(params)
    state = swim.initial_state(params, world)
    path = str(tmp_path / "old.npz")
    ckpt.save(path, state, 0)
    # Strip the g fields to simulate an old file.
    with onp.load(path) as z:
        arrays = {k: z[k] for k in z.files if not k.startswith("state/g_")}
    onp.savez(path, **arrays)
    loaded, _, _, _ = ckpt.load(path)
    assert loaded.g_infected.shape == (16, 0)
