"""The live SLO alarm engine (telemetry/alarms.py) + journal follower
(sink.JournalFollower) + their integrations.

Pins: the pending→firing→resolved state machine (debounce, clear-side
hysteresis, pending-cancel, sliding windows, no-signal rules), the
follower's consumed-bytes-are-never-re-read cursor (torn-tail wait,
shrink refusal, interior-corruption refusal), exactly-once transition
resume through ``replay_journal``/``write_transitions`` — unit-level
AND through ``stream_metered_run`` re-runs and a supervisor
``KillPlan(stage="post_journal")`` kill/relaunch (the preemption that
strands a durable segment with its alarm rows missing) — and the
``telemetry watch`` CLI tailing a journal a live subprocess is still
writing without dropping or duplicating a single window.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

from scalecube_cluster_tpu.telemetry import alarms
from scalecube_cluster_tpu.telemetry import sink as tsink

pytestmark = pytest.mark.alarm

REPO = pathlib.Path(__file__).resolve().parent.parent


def row(start, end, onsets, observers=40, kind="metrics_window"):
    c = {"false_suspicion_onsets": onsets}
    if observers is not None:
        c["live_observer_rounds"] = observers
    return {"kind": kind, "round_start": start, "round_end": end,
            "counters": c}


def fp_engine(threshold=0.5, **kw):
    return alarms.AlarmEngine(
        alarms.default_specs(threshold=threshold, **kw))


def transitions_of(records):
    return [(r["alarm"], r["from"], r["to"], r["round_end"])
            for r in records]


# --------------------------------------------------------------------------
# Spec validation
# --------------------------------------------------------------------------


def test_spec_rejects_unknown_comparator():
    with pytest.raises(ValueError, match="comparator"):
        alarms.AlarmSpec(name="x", numerator="a", comparator="!=")


@pytest.mark.parametrize("field", ["window", "for_windows",
                                   "clear_windows"])
def test_spec_rejects_nonpositive_windows(field):
    with pytest.raises(ValueError, match=field):
        alarms.AlarmSpec(name="x", numerator="a", **{field: 0})


def test_engine_rejects_duplicate_names():
    spec = alarms.AlarmSpec(name="dup", numerator="a")
    with pytest.raises(ValueError, match="duplicate"):
        alarms.AlarmEngine([spec, spec])


# --------------------------------------------------------------------------
# State machine
# --------------------------------------------------------------------------


def test_fires_immediately_then_resolves():
    eng = fp_engine(threshold=0.5)
    assert eng.observe(row(0, 4, onsets=0)) == []
    fired = eng.observe(row(4, 8, onsets=40))     # rate 1.0 > 0.5
    assert transitions_of(fired) == [
        ("false_positive_observer_rate", "ok", "firing", 8)]
    assert eng.state_of("false_positive_observer_rate") == alarms.FIRING
    resolved = eng.observe(row(8, 12, onsets=0))
    assert transitions_of(resolved) == [
        ("false_positive_observer_rate", "firing", "resolved", 12)]
    # resolved is a transition, not a resting state: back at ok, the
    # alarm can fire again.
    assert eng.state_of("false_positive_observer_rate") == alarms.OK
    assert eng.observe(row(12, 16, onsets=40))[0]["to"] == alarms.FIRING
    [st] = eng.state_rows()
    assert st["fired"] == 2 and st["resolved"] == 1


def test_for_windows_debounce_goes_pending_first():
    eng = fp_engine(threshold=0.5, for_windows=3)
    t1 = eng.observe(row(0, 4, onsets=40))
    assert transitions_of(t1) == [
        ("false_positive_observer_rate", "ok", "pending", 4)]
    assert eng.observe(row(4, 8, onsets=40)) == []   # still pending
    t3 = eng.observe(row(8, 12, onsets=40))
    assert transitions_of(t3) == [
        ("false_positive_observer_rate", "pending", "firing", 12)]
    assert t3[0]["streak"] == 3


def test_pending_cancels_on_clear_window():
    eng = fp_engine(threshold=0.5, for_windows=3)
    eng.observe(row(0, 4, onsets=40))
    t = eng.observe(row(4, 8, onsets=0))
    assert transitions_of(t) == [
        ("false_positive_observer_rate", "pending", "ok", 8)]
    [st] = eng.state_rows()
    assert st["fired"] == 0
    # And the streak reset: a fresh breach starts the debounce over.
    assert eng.observe(row(8, 12, onsets=40))[0]["to"] == alarms.PENDING


def test_clear_windows_hysteresis_prevents_flapping():
    eng = fp_engine(threshold=0.5, clear_windows=2)
    eng.observe(row(0, 4, onsets=40))
    assert eng.observe(row(4, 8, onsets=0)) == []    # 1 clear: holds
    # A re-breach inside the incident resets the clear streak.
    assert eng.observe(row(8, 12, onsets=40)) == []
    assert eng.observe(row(12, 16, onsets=0)) == []
    t = eng.observe(row(16, 20, onsets=0))
    assert transitions_of(t) == [
        ("false_positive_observer_rate", "firing", "resolved", 20)]


def test_sliding_window_is_ratio_of_sums():
    spec = alarms.AlarmSpec(
        name="fp", numerator="false_suspicion_onsets",
        denominator="live_observer_rounds", threshold=0.5, window=2)
    eng = alarms.AlarmEngine([spec])
    eng.observe(row(0, 4, onsets=0, observers=40))
    eng.observe(row(4, 8, onsets=40, observers=40))
    # (0 + 40) / (40 + 40) = 0.5, not the instantaneous 1.0 — the
    # sliding mean must not breach the strict > 0.5 threshold.
    [st] = eng.state_rows()
    assert st["value"] == pytest.approx(0.5)
    assert st["state"] == alarms.OK


def test_absent_lane_and_zero_denominator_are_not_evaluations():
    eng = fp_engine(threshold=0.5)
    eng.observe(row(0, 4, onsets=40))
    assert eng.state_of("false_positive_observer_rate") == alarms.FIRING
    # Segment rows without the SLO's lanes must not touch the state:
    # absence of signal is not health.
    assert eng.observe({"kind": "metrics_window", "round_start": 4,
                        "round_end": 8, "counters": {}}) == []
    assert eng.observe(row(8, 12, onsets=0, observers=0)) == []
    assert eng.state_of("false_positive_observer_rate") == alarms.FIRING


def test_rounds_denominator_and_segment_kind():
    spec = alarms.AlarmSpec(name="gossip", numerator="messages_gossip",
                            denominator="rounds", threshold=2.0)
    eng = alarms.AlarmEngine([spec], kinds=("segment",))
    rec = {"kind": "segment", "round_start": 0, "round_end": 8,
           "counters": {"messages_gossip": 24}}
    t = eng.observe(rec)
    assert transitions_of(t) == [("gossip", "ok", "firing", 8)]
    assert t[0]["value"] == pytest.approx(3.0)       # 24 / 8 rounds
    # Kinds outside the engine's filter pass through untouched.
    assert eng.observe({"kind": "metrics_window", **rec}) == []
    assert eng.observe({"kind": "histogram"}) == []


# --------------------------------------------------------------------------
# Replay + exactly-once dedup (unit level)
# --------------------------------------------------------------------------


def test_replay_dedup_writes_exactly_the_missing_tail(tmp_path):
    windows = [row(0, 4, onsets=40), row(4, 8, onsets=0),
               row(8, 12, onsets=40)]
    ref = fp_engine(threshold=0.5)
    all_transitions = [t for w in windows for t in ref.observe(w)]
    assert len(all_transitions) == 3          # fire, resolve, fire

    # The dead process journaled every window but only the FIRST two
    # transitions (killed mid-transition-list).
    path = tmp_path / "resume.jsonl"
    with tsink.TelemetrySink(path=str(path)) as sink:
        for w in windows:
            sink.write_metrics_window(
                {k: v for k, v in w.items() if k != "kind"})
        alarms.write_transitions(sink, all_transitions[:2])

    records = tsink.read_records(str(path))
    fresh = fp_engine(threshold=0.5)
    replayed, existing = alarms.replay_journal(fresh, records)
    assert transitions_of(replayed) == transitions_of(all_transitions)
    with tsink.TelemetrySink(path=str(path), append=True) as sink:
        written = alarms.write_transitions(sink, replayed, existing)
    assert transitions_of(written) == transitions_of(all_transitions[2:])
    durable = tsink.read_records(str(path), kind=alarms.TRANSITION_KIND)
    assert transitions_of(durable) == transitions_of(all_transitions)

    # Idempotence: a second replay finds nothing missing.
    eng2 = fp_engine(threshold=0.5)
    replayed2, existing2 = alarms.replay_journal(
        eng2, tsink.read_records(str(path)))
    with tsink.TelemetrySink(path=str(path), append=True) as sink:
        assert alarms.write_transitions(sink, replayed2, existing2) == []


# --------------------------------------------------------------------------
# JournalFollower
# --------------------------------------------------------------------------


def test_follower_consumes_only_terminated_lines(tmp_path):
    path = tmp_path / "live.jsonl"
    line1 = json.dumps({"kind": "metrics_window", "round_start": 0,
                        "round_end": 4}) + "\n"
    frag = '{"kind": "metrics_window", "round_st'
    path.write_text(line1 + frag)
    f = tsink.follow_records(str(path))
    recs = f.poll()
    assert [r["round_end"] for r in recs] == [4]
    assert f.offset == len(line1)             # cursor stops at the newline
    assert f.poll() == []                     # fragment: wait, don't parse
    with open(path, "a") as fh:
        fh.write('art": 4, "round_end": 8}\n')
    assert [r["round_end"] for r in f.poll()] == [8]
    assert f.covered_upto(kind="metrics_window") == 8


def test_follower_never_rereads_consumed_bytes(tmp_path):
    """The satellite pin: a long journal is scanned ONCE.  After a
    poll, the consumed prefix is overwritten in place with garbage —
    if any later poll re-parsed those bytes it would raise; instead
    only the appended tail is returned."""
    path = tmp_path / "prefix.jsonl"
    with tsink.TelemetrySink(path=str(path)) as sink:
        for i in range(50):
            sink.write_metrics_window(
                {"round_start": 4 * i, "round_end": 4 * (i + 1),
                 "counters": {}})
    f = tsink.follow_records(str(path))
    first = f.poll()
    assert len(first) == 50
    consumed = f.offset
    with open(path, "r+b") as fh:             # same length: offsets hold
        fh.write(b"X" * consumed)
    with open(path, "a") as fh:
        fh.write(json.dumps({"kind": "metrics_window",
                             "round_start": 200,
                             "round_end": 204}) + "\n")
    tail = f.poll()
    assert [r["round_end"] for r in tail] == [204]
    assert f.covered_upto(kind="metrics_window") == 204


def test_follower_refuses_shrunk_journal(tmp_path):
    path = tmp_path / "shrink.jsonl"
    path.write_text('{"kind": "segment", "round_end": 8}\n')
    f = tsink.follow_records(str(path))
    assert len(f.poll()) == 1
    os.truncate(path, 3)
    with pytest.raises(ValueError, match="shrank"):
        f.poll()


def test_follower_refuses_interior_corruption(tmp_path):
    path = tmp_path / "corrupt.jsonl"
    path.write_text("not json at all\n")
    f = tsink.follow_records(str(path))
    with pytest.raises(ValueError, match="corrupt"):
        f.poll()


def test_follower_kind_filter_still_tracks_all_cursors(tmp_path):
    path = tmp_path / "filter.jsonl"
    path.write_text(
        '{"kind": "segment", "round_end": 8}\n'
        '{"kind": "metrics_window", "round_end": 4}\n')
    f = tsink.follow_records(str(path), kind="segment")
    assert [r["kind"] for r in f.poll()] == ["segment"]
    # The per-kind cursors rebase from everything scanned, matching
    # the whole-file covered_upto on the same bytes.
    assert f.covered_upto(kind="segment") == 8
    assert f.covered_upto(kind="metrics_window") == 4
    assert tsink.covered_upto(str(path), kind="segment") == 8


def test_follower_missing_file_waits(tmp_path):
    f = tsink.follow_records(str(tmp_path / "notyet.jsonl"))
    assert f.poll() == []


# --------------------------------------------------------------------------
# stream_metered_run integration: live transitions + resumed dedup
# --------------------------------------------------------------------------


def small_workload(n=12, loss=0.0):
    import jax

    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim

    cfg = ClusterConfig.default().replace(
        gossip_interval=100, ping_interval=200, ping_timeout=100,
        sync_interval=1_000, suspicion_mult=3)
    params = swim.SwimParams.from_config(cfg, n_members=n,
                                         loss_probability=loss)
    return jax.random.key(3), params, swim.SwimWorld.healthy(params)


# Fires on the first window of any live run: every member observes.
ACTIVITY_SPEC = alarms.AlarmSpec(
    name="observers_present", numerator="live_observer_rounds",
    denominator="rounds", comparator=">", threshold=0.0)


def test_stream_metered_run_journals_transitions(tmp_path):
    from scalecube_cluster_tpu.telemetry import metrics as tmetrics

    key, params, world = small_workload()
    path = tmp_path / "run.jsonl"
    with tsink.TelemetrySink(path=str(path)) as sink:
        _, rows = tmetrics.stream_metered_run(
            key, params, world, 16, sink=sink, window_rounds=4,
            alarm_specs=[ACTIVITY_SPEC])
    assert len(rows) == 4
    durable = tsink.read_records(str(path), kind=alarms.TRANSITION_KIND)
    assert transitions_of(durable) == [
        ("observers_present", "ok", "firing", 4)]


def test_stream_metered_run_resume_is_exactly_once(tmp_path):
    """A full re-run over the same journal (the supervisor's relaunch
    shape) recomputes every window but writes NOTHING new: windows
    dedup through the cursor, transitions through the replay."""
    from scalecube_cluster_tpu.telemetry import metrics as tmetrics

    key, params, world = small_workload()
    path = tmp_path / "resumed.jsonl"
    with tsink.TelemetrySink(path=str(path)) as sink:
        tmetrics.stream_metered_run(
            key, params, world, 16, sink=sink, window_rounds=4,
            alarm_specs=[ACTIVITY_SPEC])
    before = [json.dumps(r) for r in tsink.read_records(str(path))]
    with tsink.TelemetrySink(path=str(path), append=True) as sink:
        tmetrics.stream_metered_run(
            key, params, world, 16, sink=sink, window_rounds=4,
            alarm_specs=[ACTIVITY_SPEC])
    after = [json.dumps(r) for r in tsink.read_records(str(path))]
    assert after == before


def test_alarm_specs_without_sink_refused():
    from scalecube_cluster_tpu.telemetry import metrics as tmetrics

    key, params, world = small_workload()
    with pytest.raises(ValueError, match="sink"):
        tmetrics.stream_metered_run(key, params, world, 8,
                                    alarm_specs=[ACTIVITY_SPEC])


# --------------------------------------------------------------------------
# Supervisor integration: kill mid-transition, relaunch, exactly once
# --------------------------------------------------------------------------


SUPERVISOR_SPECS = (
    # Fires at the first segment of any live run.
    alarms.AlarmSpec(name="gossip_active", numerator="messages_gossip",
                     denominator="rounds", comparator=">",
                     threshold=0.0),
    # Debounced twin: pending at segment 1, firing at segment 2 — the
    # transition the post_journal kill strands.
    alarms.AlarmSpec(name="gossip_active_debounced",
                     numerator="messages_gossip", denominator="rounds",
                     comparator=">", threshold=0.0, for_windows=2),
)


def run_supervised(tmp_path, sub, kill_plan=None):
    from scalecube_cluster_tpu.resilience import harness as rh
    from scalecube_cluster_tpu.resilience import store as rstore
    from scalecube_cluster_tpu.resilience import supervisor as rsup

    base = tmp_path / sub
    os.makedirs(base, exist_ok=True)
    cfg = rh.DrillConfig(shape="plain", base_path=str(base / "ck"),
                         n_members=12, n_rounds=24, segment_rounds=8)
    key, params, world, _ = rh.build_workload(cfg)
    return rsup.run_resilient(
        "plain", key, params, world, cfg.n_rounds,
        store=rstore.CheckpointStore(cfg.base_path),
        segment_rounds=cfg.segment_rounds,
        alarm_specs=SUPERVISOR_SPECS, kill_plan=kill_plan)


def test_supervisor_kill_relaunch_transitions_exactly_once(tmp_path):
    from scalecube_cluster_tpu.resilience import supervisor as rsup

    ref = run_supervised(tmp_path, "ref")
    ref_rows = tsink.read_records(ref.journal_path,
                                  kind=alarms.TRANSITION_KIND)
    assert transitions_of(ref_rows) == [
        ("gossip_active", "ok", "firing", 8),
        ("gossip_active_debounced", "ok", "pending", 8),
        ("gossip_active_debounced", "pending", "firing", 16),
    ]
    assert ref.alarm_transitions == 3

    # Kill at the nastiest stage: the round-16 segment record is
    # durable, its firing transition is NOT.
    with pytest.raises(rsup.SimulatedPreemption):
        run_supervised(tmp_path, "kill", kill_plan=rsup.KillPlan(
            round=12, stage="post_journal", mode="raise"))
    killed = tsink.read_records(
        str(tmp_path / "kill" / "ck.journal.jsonl"),
        kind=alarms.TRANSITION_KIND)
    assert transitions_of(killed) == transitions_of(ref_rows)[:2]

    res = run_supervised(tmp_path, "kill")
    assert res.resumed_from is not None
    rows = tsink.read_records(res.journal_path,
                              kind=alarms.TRANSITION_KIND)
    # The relaunch replayed the journal, wrote EXACTLY the stranded
    # firing row, and the resumed segments added nothing new: the
    # kill/relaunch journal is row-for-row the uninterrupted one.
    assert transitions_of(rows) == transitions_of(ref_rows)
    assert res.alarm_transitions == 1


# --------------------------------------------------------------------------
# The watch CLI against a live writer subprocess
# --------------------------------------------------------------------------


WRITER = r"""
import sys, time
from scalecube_cluster_tpu.telemetry import sink as tsink

path, n = sys.argv[1], int(sys.argv[2])
with tsink.TelemetrySink(path=path) as s:
    s.write_manifest(params={"n": 8})
    for i in range(n):
        breach = 40 if (n // 3) <= i < (2 * n // 3) else 0
        s.write_metrics_window({
            "round_start": 4 * i, "round_end": 4 * (i + 1),
            "counters": {"false_suspicion_onsets": breach,
                         "live_observer_rounds": 40}})
        time.sleep(0.02)
    s.write_summary(windows=n)
"""


def test_watch_tails_live_subprocess_exactly_once(tmp_path):
    """End-to-end acceptance pin: watch tails a journal ANOTHER process
    is still writing and sees every window exactly once, fires on the
    mid-stream breach plateau, and exits on the summary record."""
    n = 30
    path = tmp_path / "live_run.jsonl"
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    writer = subprocess.Popen(
        [sys.executable, "-c", WRITER, str(path), str(n)], env=env)
    try:
        watch = subprocess.run(
            [sys.executable, "-m", "scalecube_cluster_tpu.telemetry",
             "watch", str(path), "--json", "--interval", "0.05",
             "--threshold", "0.5", "--max-seconds", "60"],
            env=env, capture_output=True, text=True, timeout=120)
    finally:
        writer.wait(timeout=60)
    assert watch.returncode == 0, watch.stderr
    lines = [json.loads(ln) for ln in watch.stdout.splitlines()]
    windows = [ln for ln in lines if ln["kind"] == "window"]
    # Every window exactly once, in order — no drops, no duplicates.
    assert [w["round_end"] for w in windows] == [
        4 * (i + 1) for i in range(n)]
    fired = [t for w in windows for t in w["transitions"]
             if t["to"] == "firing"]
    resolved = [t for w in windows for t in w["transitions"]
                if t["to"] == "resolved"]
    assert len(fired) == 1 and len(resolved) == 1
    assert fired[0]["round_end"] == 4 * (n // 3 + 1)
    summary = lines[-1]
    assert summary["kind"] == "watch_summary"
    assert summary["windows"] == n and summary["run_ended"] is True
    assert summary["engine_transitions"] == 2


def test_watch_counts_unknown_record_kinds(tmp_path):
    """A journal written by a newer schema (e.g. ``provenance`` rows
    landing on an old reader) degrades LOUDLY: watch emits ONE
    unknown_record_kind notice per kind on first sight and counts every
    occurrence into the watch_summary — never a silent skip."""
    path = tmp_path / "newer_schema.jsonl"
    with tsink.TelemetrySink(path=str(path)) as s:
        s.write_manifest(params={"n": 8})
        s.write_metrics_window(row(0, 4, 0))
        s.write_provenance({"rows": [
            {"observer": 1, "subject": 3, "epoch": 0,
             "transition": "SUSPECTED", "channel": "gossip",
             "round": 2}], "recorded": 1, "dropped": 0,
            "capacity": 64})
        s.write_provenance({"rows": [], "recorded": 1, "dropped": 0,
                            "capacity": 64})
        s.write_summary(windows=1)
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    watch = subprocess.run(
        [sys.executable, "-m", "scalecube_cluster_tpu.telemetry",
         "watch", str(path), "--json", "--interval", "0.05",
         "--threshold", "0.5", "--max-seconds", "30"],
        env=env, capture_output=True, text=True, timeout=120)
    assert watch.returncode == 0, watch.stderr
    lines = [json.loads(ln) for ln in watch.stdout.splitlines()]
    notices = [ln for ln in lines if ln["kind"] == "unknown_record_kind"]
    assert len(notices) == 1                 # first sight only
    assert notices[0]["record_kind"] == "provenance"
    summary = lines[-1]
    assert summary["kind"] == "watch_summary"
    assert summary["unknown_kinds"]["provenance"] == 2
    assert summary["windows"] == 1 and summary["run_ended"] is True
