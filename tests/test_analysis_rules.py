"""swimlint rule units: every rule has a triggering and a
non-triggering fixture case, plus the mutation pin — deleting one real
threading site from a copied package makes the plane matrix fire
(ISSUE 14 satellite contract).
"""

import pathlib

import pytest

from scalecube_cluster_tpu.analysis import compile_audit
from scalecube_cluster_tpu.analysis import rules as lint
from scalecube_cluster_tpu.analysis.callgraph import PackageGraph

from tests.analysis_helpers import (
    MINI_SWIM, blank_consults_in_function, copy_real_package, write_tree,
)

pytestmark = pytest.mark.lint


def graph_of(tmp_path, files, base=True):
    return PackageGraph(write_tree(tmp_path, files, base=base))


def ids_of(findings):
    return {f.id for f in findings}


# --------------------------------------------------------------------------
# plane-matrix
# --------------------------------------------------------------------------

# MINI_SWIM grown a metadata_keys knob consulted in scatter, shift and
# the pipelined send half — but NOT in the k_block body (the planted
# gap the triggering fixture asserts on).
_MD_SWIM = MINI_SWIM.replace(
    "    shadow_knob: int = 0",
    "    shadow_knob: int = 0\n    metadata_keys: int = 0",
).replace(
    "def _tick_scatter(state, params):\n"
    "    return state + params.sync_interval",
    "def _tick_scatter(state, params):\n"
    "    return state + params.sync_interval + params.metadata_keys",
).replace(
    "def _tick_shift(state, params):\n"
    "    return state + params.sync_interval",
    "def _tick_shift(state, params):\n"
    "    return state + params.sync_interval + params.metadata_keys",
).replace(
    "def swim_tick_send(state, params):\n"
    "    ctx = _round_context(state, params)\n"
    "    return ctx + params.sync_interval",
    "def swim_tick_send(state, params):\n"
    "    ctx = _round_context(state, params)\n"
    "    return ctx + params.sync_interval + params.metadata_keys",
)


class TestPlaneMatrix:
    def test_uniform_tree_is_clean(self, tmp_path):
        matrix, findings = lint.plane_matrix(graph_of(tmp_path, {}))
        assert findings == []
        # every entry column of a consulted knob is populated
        assert all(matrix["entries"]["sync_interval"][e]
                   for e in lint.ENTRY_POINTS)
        assert all(matrix["bodies"]["sync_interval"][b]
                   for b in lint.TICK_BODIES)
        # ... and the compose column: the knob is reachable from the
        # composed scan drivers the entries delegate to
        assert matrix["compose"]["sync_interval"]["compose"]
        # ... and the batch column: the batched driver runs the same
        # tick, so the knob is sweepable on the batch axis too
        assert matrix["batch"]["sync_interval"]["batch"]
        # dispatch-level-only and never-consulted knobs are all-empty
        # rows in the body matrix — allowed (the entry matrix covers
        # them)
        assert not any(matrix["bodies"]["lhm_max"][b]
                       for b in ("scatter", "shift", "k_block"))

    def test_entry_gap_fires_per_missing_entry(self, tmp_path):
        swim_src = MINI_SWIM.replace(
            "    shadow_knob: int = 0",
            "    shadow_knob: int = 0\n    entry_knob: int = 0",
        ).replace(
            "def run(key, params, world, n_rounds):\n"
            "    return compose.composed_scan(key, params, world, "
            "n_rounds)",
            "def run(key, params, world, n_rounds):\n"
            "    return compose.composed_scan(key, params, world, "
            "n_rounds) + params.entry_knob",
        )
        _, findings = lint.plane_matrix(
            graph_of(tmp_path, {"models/swim.py": swim_src}))
        got = ids_of(findings)
        missing = set(lint.ENTRY_POINTS) - {"run"}
        # a knob consulted in ONE entry body bypasses compose() too —
        # the per-entry gaps, the compose-bypass finding AND the
        # batch-bypass finding all fire (the batched driver cannot
        # reach an entry-body-only consult either)
        assert got == {f"plane-matrix:entry_knob:entry:{e}"
                       for e in missing} | {
                           "plane-matrix:entry_knob:compose",
                           "plane-matrix:entry_knob:batch"}

    def test_body_gap_fires_for_the_unthreaded_body(self, tmp_path):
        swim_src = MINI_SWIM.replace(
            "def _tick_shift_blocked(state, params):\n"
            "    return state + params.sync_interval",
            "def _tick_shift_blocked(state, params):\n"
            "    return state + 0",
        )
        _, findings = lint.plane_matrix(
            graph_of(tmp_path, {"models/swim.py": swim_src}))
        assert ids_of(findings) == {
            "plane-matrix:sync_interval:body:k_block"}

    def test_half_tick_split_loss_fires_pipelined(self, tmp_path):
        swim_src = MINI_SWIM.replace(
            "def swim_tick_send(state, params):\n"
            "    ctx = _round_context(state, params)\n"
            "    return ctx + params.sync_interval",
            "def swim_tick_send(state, params):\n"
            "    ctx = _round_context(state, params)\n"
            "    return ctx",
        ).replace(
            "def swim_tick_recv(state, params):\n"
            "    return state + params.sync_interval",
            "def swim_tick_recv(state, params):\n"
            "    return state",
        )
        _, findings = lint.plane_matrix(
            graph_of(tmp_path, {"models/swim.py": swim_src}))
        assert ids_of(findings) == {
            "plane-matrix:sync_interval:body:pipelined"}

    def test_batch_gap_fires_for_the_batch_driver_only(self, tmp_path):
        # the batched driver loses its tick delegation: every knob the
        # entries still consult becomes unreachable from the batch
        # axis — exactly the per-knob batch cells fire, nothing else
        compose_src = (
            "from scalecube_cluster_tpu.models import swim\n\n\n"
            "def composed_scan(key, params, world, n_rounds, planes=()):\n"
            "    return swim.swim_tick(0, params)\n\n\n"
            "def composed_shard_scan(key, params, world, n_rounds,\n"
            "                        planes=()):\n"
            "    pending = swim.swim_tick_send(0, params)\n"
            "    state = swim.swim_tick_recv(pending, params)\n"
            "    return swim.swim_tick(state, params)\n\n\n"
            "def composed_batch_scan(keys, params, worlds, n_rounds,\n"
            "                        planes=()):\n"
            "    return 0\n"
        )
        _, findings = lint.plane_matrix(
            graph_of(tmp_path, {"models/compose.py": compose_src}))
        got = ids_of(findings)
        assert {"plane-matrix:sync_interval:batch",
                "plane-matrix:n_members:batch",
                "plane-matrix:lhm_max:batch"} <= got
        assert all(":batch" in fid for fid in got)

    def test_uniformly_threaded_metadata_knob_is_clean(self, tmp_path):
        # The metadata KV plane's knob rides the same matrix as every
        # other plane: threaded through all tick bodies, it reaches
        # every entry / compose / batch column with no new rule code.
        src = _MD_SWIM.replace(
            "def _tick_shift_blocked(state, params):\n"
            "    return state + params.sync_interval",
            "def _tick_shift_blocked(state, params):\n"
            "    return state + params.sync_interval"
            " + params.metadata_keys",
        )
        matrix, findings = lint.plane_matrix(
            graph_of(tmp_path, {"models/swim.py": src}))
        assert findings == []
        assert all(matrix["entries"]["metadata_keys"][e]
                   for e in lint.ENTRY_POINTS)
        assert matrix["compose"]["metadata_keys"]["compose"]
        assert matrix["batch"]["metadata_keys"]["batch"]

    def test_metadata_knob_body_gap_fires(self, tmp_path):
        # ... and un-threading it from ONE sibling body fires exactly
        # that cell — the metadata plane cannot silently skip a tick
        # variant.
        _, findings = lint.plane_matrix(
            graph_of(tmp_path, {"models/swim.py": _MD_SWIM}))
        assert ids_of(findings) == {
            "plane-matrix:metadata_keys:body:k_block"}

    def test_missing_entry_root_is_an_input_error(self, tmp_path):
        swim_src = MINI_SWIM.replace(
            "def run_metered(key", "def run_metered_renamed(key")
        with pytest.raises(ValueError, match="run_metered"):
            lint.plane_matrix(
                graph_of(tmp_path, {"models/swim.py": swim_src}))


class TestMutationPin:
    """Deleting one REAL threading site from a copied package tree
    makes the matrix rule fire — the rule reads the actual code, not a
    curated site list."""

    def test_blanked_sites_fire_blanked_only(self, tmp_path):
        pristine = lint.plane_matrix(PackageGraph(
            pathlib.Path(compile_audit.__file__).resolve().parents[1]))
        mutated_root = copy_real_package(tmp_path)
        # body-level: the blocked tick's SYNC fold is its own site
        blank_consults_in_function(
            mutated_root / "models/swim.py", "_tick_shift_blocked",
            "params.sync_interval", "0")
        # entry-level: the single-device composed scan driver's fusion
        # consult feeds all five single-device run shapes (the sharded
        # driver keeps its own consult, so exactly those five cells
        # empty out)
        blank_consults_in_function(
            mutated_root / "models/compose.py", "composed_scan",
            "params.rounds_per_step", "1")
        # batch-level: the batched driver's own fusion consult is the
        # ONLY rounds_per_step site in composed_batch_scan's cone, so
        # blanking it empties exactly the batch cell (the unbatched
        # drivers keep theirs)
        blank_consults_in_function(
            mutated_root / "models/compose.py", "composed_batch_scan",
            "params.rounds_per_step", "1")
        _, findings = lint.plane_matrix(PackageGraph(mutated_root))
        got = ids_of(findings)
        expect = {
            "plane-matrix:sync_interval:body:k_block",
            "plane-matrix:rounds_per_step:entry:run",
            "plane-matrix:rounds_per_step:entry:run_traced",
            "plane-matrix:rounds_per_step:entry:run_metered",
            "plane-matrix:rounds_per_step:entry:run_monitored",
            "plane-matrix:rounds_per_step:entry:run_monitored_metered",
            "plane-matrix:rounds_per_step:batch",
        }
        assert expect <= got
        # the batch mutation fired no OTHER batch cell: every other
        # knob's batch column survives both blanks
        assert {fid for fid in got if fid.endswith(":batch")} == {
            "plane-matrix:rounds_per_step:batch"}
        # and none of these fire at HEAD
        assert not expect & ids_of(pristine[1])


# --------------------------------------------------------------------------
# thin-entry
# --------------------------------------------------------------------------

class TestThinEntries:
    def test_uniform_tree_is_clean(self, tmp_path):
        assert lint.thin_entries(graph_of(tmp_path, {})) == []

    def test_entry_touching_tick_internal_fires(self, tmp_path):
        swim_src = MINI_SWIM.replace(
            "def run(key, params, world, n_rounds):\n"
            "    return compose.composed_scan(key, params, world, "
            "n_rounds)",
            "def run(key, params, world, n_rounds):\n"
            "    compose.composed_scan(key, params, world, n_rounds)\n"
            "    return swim_tick(0, params)",
        )
        findings = lint.thin_entries(
            graph_of(tmp_path, {"models/swim.py": swim_src}))
        assert ids_of(findings) == {"thin-entry:run:swim_tick"}

    def test_entry_not_delegating_to_compose_fires(self, tmp_path):
        # an entry re-growing its own scan body (no compose delegation,
        # direct _fused_scan-style internals) fires BOTH shapes
        swim_src = MINI_SWIM.replace(
            "def run_metered(key, params, world, n_rounds):\n"
            "    return compose.composed_scan(key, params, world, "
            "n_rounds)",
            "def run_metered(key, params, world, n_rounds):\n"
            "    return swim_tick(0, params)",
        )
        findings = lint.thin_entries(
            graph_of(tmp_path, {"models/swim.py": swim_src}))
        assert ids_of(findings) == {
            "thin-entry:run_metered:swim_tick",
            "thin-entry:run_metered:no-compose-delegation",
        }

    def test_batch_entry_touching_tick_internal_fires(self, tmp_path):
        # the batch entry is held to the same thin-alias bar: private
        # scan plumbing next to the composed delegation fires
        monitor_src = (
            "from scalecube_cluster_tpu.models import compose\n"
            "from scalecube_cluster_tpu.models import swim\n\n\n"
            "def run_monitored(key, params, world, n_rounds):\n"
            "    return compose.composed_scan(key, params, world, "
            "n_rounds)\n\n\n"
            "def run_monitored_metered(key, params, world, n_rounds):\n"
            "    return compose.composed_scan(key, params, world, "
            "n_rounds)\n\n\n"
            "def run_monitored_batch(keys, params, worlds, n_rounds):\n"
            "    compose.composed_batch_scan(keys, params, worlds, "
            "n_rounds)\n"
            "    return swim.swim_tick(0, params)\n"
        )
        findings = lint.thin_entries(
            graph_of(tmp_path, {"chaos/monitor.py": monitor_src}))
        assert ids_of(findings) == {
            "thin-entry:run_monitored_batch:swim_tick"}

    def test_same_module_helper_is_checked_one_hop(self, tmp_path):
        # tick logic hidden behind a same-module plain helper still
        # fires (the shard_run -> _composed_shard_run plumbing shape is
        # audited one hop deep)
        mesh_src = (
            "from scalecube_cluster_tpu.models import compose\n"
            "from scalecube_cluster_tpu.models import swim\n\n\n"
            "def _helper(key, params, world, n_rounds):\n"
            "    compose.composed_shard_scan(key, params, world, "
            "n_rounds)\n"
            "    return swim.swim_tick(0, params)\n\n\n"
            "def shard_run(key, params, world, n_rounds, mesh):\n"
            "    return _helper(key, params, world, n_rounds)\n\n\n"
            "def shard_run_metered(key, params, world, n_rounds, mesh):\n"
            "    return compose.composed_shard_scan(key, params, world, "
            "n_rounds)\n"
        )
        findings = lint.thin_entries(
            graph_of(tmp_path, {"parallel/mesh.py": mesh_src}))
        assert ids_of(findings) == {"thin-entry:shard_run:swim_tick"}

    def test_entry_and_helper_reaching_same_internal_fire_once(
            self, tmp_path):
        # one defect, one finding: the entry AND its helper both
        # touching the same internal must not double-count (or mutate
        # the id through the engine's :x2 collapse, which would turn a
        # baseline row stale against the real regression id)
        mesh_src = (
            "from scalecube_cluster_tpu.models import compose\n"
            "from scalecube_cluster_tpu.models import swim\n\n\n"
            "def _helper(key, params, world, n_rounds):\n"
            "    compose.composed_shard_scan(key, params, world, "
            "n_rounds)\n"
            "    return swim.swim_tick(0, params)\n\n\n"
            "def shard_run(key, params, world, n_rounds, mesh):\n"
            "    _helper(key, params, world, n_rounds)\n"
            "    return swim.swim_tick(0, params)\n\n\n"
            "def shard_run_metered(key, params, world, n_rounds, mesh):\n"
            "    return compose.composed_shard_scan(key, params, world, "
            "n_rounds)\n"
        )
        findings = lint.thin_entries(
            graph_of(tmp_path, {"parallel/mesh.py": mesh_src}))
        assert [f.id for f in findings] == \
            ["thin-entry:shard_run:swim_tick"]

    def test_head_package_is_clean(self):
        root = pathlib.Path(compile_audit.__file__).resolve().parents[1]
        assert lint.thin_entries(PackageGraph(root)) == []


# --------------------------------------------------------------------------
# trace-safety
# --------------------------------------------------------------------------

class TestTraceSafety:
    def test_host_entropy_in_device_module_fires(self, tmp_path):
        swim_src = MINI_SWIM.replace(
            "import dataclasses",
            "import dataclasses\nimport numpy as np",
        ).replace(
            "def _tick_scatter(state, params):\n"
            "    return state + params.sync_interval",
            "def _tick_scatter(state, params):\n"
            "    return state + np.random.uniform()",
        )
        findings = lint.trace_safety(
            graph_of(tmp_path, {"models/swim.py": swim_src}))
        assert ids_of(findings) == {
            "trace-safety:models/swim.py:_tick_scatter:"
            "numpy.random.uniform"}

    def test_host_entropy_outside_device_modules_is_fine(self, tmp_path):
        files = {"oracle/helpers.py":
                 "import random\n\n\ndef pick(xs):\n"
                 "    return random.choice(xs)\n"}
        assert lint.trace_safety(graph_of(tmp_path, files)) == []

    def test_item_in_device_cone_fires(self, tmp_path):
        swim_src = MINI_SWIM.replace(
            "def swim_tick_recv(state, params):\n"
            "    return state + params.sync_interval",
            "def swim_tick_recv(state, params):\n"
            "    return state.item() + params.sync_interval",
        )
        findings = lint.trace_safety(
            graph_of(tmp_path, {"models/swim.py": swim_src}))
        assert ids_of(findings) == {
            "trace-safety:models/swim.py:swim_tick_recv:.item"}

    def test_item_in_host_side_helper_is_fine(self, tmp_path):
        files = {"models/snapshots.py":
                 "def decode(state):\n"
                 "    return state.count.item()\n"}
        assert lint.trace_safety(graph_of(tmp_path, files)) == []

    def test_float_of_reduction_in_cone_fires(self, tmp_path):
        swim_src = MINI_SWIM.replace(
            "def _tick_shift(state, params):\n"
            "    return state + params.sync_interval",
            "def _tick_shift(state, params):\n"
            "    return float(state.sum()) + params.sync_interval",
        )
        findings = lint.trace_safety(
            graph_of(tmp_path, {"models/swim.py": swim_src}))
        assert ids_of(findings) == {
            "trace-safety:models/swim.py:_tick_shift:float-coercion"}

    def test_float_of_static_knob_is_fine(self, tmp_path):
        swim_src = MINI_SWIM.replace(
            "def _tick_shift(state, params):\n"
            "    return state + params.sync_interval",
            "def _tick_shift(state, params):\n"
            "    return state + float(params.sync_interval)",
        )
        assert lint.trace_safety(
            graph_of(tmp_path, {"models/swim.py": swim_src})) == []

    def test_head_package_is_clean(self):
        root = pathlib.Path(compile_audit.__file__).resolve().parents[1]
        assert lint.trace_safety(PackageGraph(root)) == []


# --------------------------------------------------------------------------
# donation-safety
# --------------------------------------------------------------------------

DONOR = """\
from functools import partial

import jax


@partial(jax.jit, donate_argnames=("state",))
def consume(key, state):
    return state


def rebind_ok(key, state):
    state = consume(key, state=state)
    return state


def multiline_call_ok(key, state):
    out = consume(
        key,
        state=state,
    )
    return out


def read_after_donate_bad(key, state):
    out = consume(key, state=state)
    return out + state


def positional_donate_bad(key, state):
    out = consume(key, state)
    return out + state


def same_line_read_bad(key, state):
    return consume(key, state=state) + state


def rebind_rhs_bad(key, state):
    out = consume(key, state=state)
    state = state + 1
    return out, state


def augassign_bad(key, state):
    out = consume(key, state=state)
    state += 1
    return out, state
"""


class TestDonationSafety:
    def test_read_after_donate_fires_and_safe_shapes_do_not(
            self, tmp_path):
        findings = lint.donation_safety(
            graph_of(tmp_path, {"models/donor.py": DONOR}))
        # keyword, positional, and same-line reads all fire; the rebind
        # and multi-line-call shapes do not
        assert ids_of(findings) == {
            "donation-safety:models/donor.py:read_after_donate_bad:"
            "state",
            "donation-safety:models/donor.py:positional_donate_bad:"
            "state",
            "donation-safety:models/donor.py:same_line_read_bad:state",
            # the rebind line's RHS executes BEFORE the store: reading
            # the donated name there is still a read-after-donate —
            # and `state += 1` is exactly that read in disguise
            "donation-safety:models/donor.py:rebind_rhs_bad:state",
            "donation-safety:models/donor.py:augassign_bad:state",
        }

    def test_same_bare_name_non_donating_function_does_not_fire(
            self, tmp_path):
        """The package has several same-named ``run`` functions and
        only swim's donates — the rule resolves callees through the
        symbol table, so a positional call to a NON-donating namesake
        followed by a read is clean."""
        files = {
            "models/donor.py": DONOR,
            "models/fd2.py": ("def consume(key, state):\n"
                              "    return state\n"),
            "models/caller.py": (
                "from scalecube_cluster_tpu.models import fd2\n\n\n"
                "def use(key, state):\n"
                "    out = fd2.consume(key, state)\n"
                "    return out + state\n"),
        }
        findings = lint.donation_safety(graph_of(tmp_path, files))
        assert not any("caller.py" in f.id for f in findings)

    def test_head_package_is_clean(self):
        root = pathlib.Path(compile_audit.__file__).resolve().parents[1]
        assert lint.donation_safety(PackageGraph(root)) == []


# --------------------------------------------------------------------------
# magic-literal
# --------------------------------------------------------------------------

class TestMagicLiterals:
    def test_planted_saturation_literal_fires(self, tmp_path):
        from scalecube_cluster_tpu.ops import delivery

        cap = delivery.WIRE16.inc_sat(0)  # 8191
        files = {"models/caps.py": f"CAP = {cap}\n"}
        findings = lint.magic_literals(graph_of(tmp_path, files))
        assert ids_of(findings) == {
            f"magic-literal:wire-saturation:models/caps.py:{cap}"}

    def test_docstring_citation_is_not_a_clamp_site(self, tmp_path):
        files = {"models/doc.py":
                 '"""Saturates at 8191 (see the table)."""\n\nX = 1\n'}
        assert lint.magic_literals(graph_of(tmp_path, files)) == []

    def test_carry_bound_outside_swim_fires_inside_is_allowed(
            self, tmp_path):
        bound = (1 << 15) - 1
        files = {"models/elsewhere.py": f"LIM = {bound}\n",
                 "models/swim.py":
                 MINI_SWIM + f"\n_DEADLINE_NONE16 = {bound}\n"}
        findings = lint.magic_literals(graph_of(tmp_path, files))
        assert ids_of(findings) == {
            f"magic-literal:carry-bound:models/elsewhere.py:{bound}"}

    def test_monitor_code_comparison_fires_outside_monitor(
            self, tmp_path):
        body = ("def is_resurrection(v):\n"
                "    return v.code == 6\n")
        findings = lint.magic_literals(graph_of(
            tmp_path, {"models/checks.py": body}))
        assert ids_of(findings) == {
            "magic-literal:monitor-code:models/checks.py"}
        # the owning module may spell its own codes
        assert lint.magic_literals(graph_of(
            tmp_path / "owning", {"chaos/monitor.py": body},
            base=False)) == []

    def test_literal_epoch_width_fires(self, tmp_path):
        files = {"models/packer.py":
                 "def g(p, key):\n"
                 "    return p.pack(key, epoch_bits=4)\n"}
        findings = lint.magic_literals(graph_of(tmp_path, files))
        assert ids_of(findings) == {
            "magic-literal:epoch-width:models/packer.py"}


# --------------------------------------------------------------------------
# compile-audit plumbing (the full seven-entry audit runs in
# tests/test_analysis_gate.py; these pin the detectors on toy programs)
# --------------------------------------------------------------------------

class TestCompileAuditDetectors:
    def test_planted_callback_is_detected(self):
        import jax
        import jax.numpy as jnp

        def bad(c):
            return jax.pure_callback(
                lambda a: a, jax.ShapeDtypeStruct(c.shape, c.dtype), c)

        jaxpr = jax.make_jaxpr(
            lambda x: jax.lax.scan(lambda c, _: (bad(c), None), x, None,
                                   length=3))(jnp.ones(3))
        names = {eqn.primitive.name
                 for eqn in compile_audit._iter_eqns(jaxpr.jaxpr)}
        assert any("callback" in n for n in names)

    def test_scan_carry_avals_see_narrow_lanes(self):
        import jax
        import jax.numpy as jnp

        def f(x16, x32):
            return jax.lax.scan(
                lambda c, _: (c, None), (x16, x32), None, length=2)

        jaxpr = jax.make_jaxpr(f)(jnp.ones(4, jnp.int16),
                                  jnp.ones(4, jnp.int32))
        carries = compile_audit._scan_carry_avals(jaxpr.jaxpr)
        assert len(carries) == 1
        dtypes = sorted(str(a.dtype) for a in carries[0])
        assert dtypes == ["int16", "int32"]

    def test_cache_size_counter_behaviour(self):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: x + 1)
        f(jnp.ones(3))
        base = f._cache_size()
        f(jnp.ones(3))
        assert f._cache_size() == base          # same shape: cache hit
        f(jnp.ones(4))
        assert f._cache_size() == base + 1      # new shape: miss
