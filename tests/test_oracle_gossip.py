"""Gossip dissemination tests, ported from the reference's
GossipProtocolTest.java (cluster/src/test/java/io/scalecube/cluster/gossip/):
the {N, loss%, delay} experiment matrix asserting full dissemination before
the sweep timeout and zero double delivery, with ClusterMath as the oracle
— on virtual time with a seeded PRNG, so the statistical envelope is
deterministic per seed."""

import pytest

from scalecube_cluster_tpu import swim_math
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.oracle import (
    GossipProtocol,
    Member,
    Message,
    Simulator,
    Transport,
)
from scalecube_cluster_tpu.oracle.membership import MembershipEvent


def make_gossip_cluster(sim, n, config, loss_percent=0, mean_delay_ms=0):
    """n gossip protocols with stubbed membership (GossipProtocolTest.java:254-274)."""
    transports = [Transport(sim) for _ in range(n)]
    members = [Member(f"m{i}", t.address) for i, t in enumerate(transports)]
    protocols = []
    for i in range(n):
        if loss_percent or mean_delay_ms:
            transports[i].network_emulator.set_default_link_settings(loss_percent, mean_delay_ms)
        g = GossipProtocol(members[i], transports[i], config, sim)
        for j in range(n):
            if j != i:
                g.on_member_event(MembershipEvent.added(members[j], None))
        protocols.append(g)
        g.start()
    return transports, members, protocols


# The reference matrix (GossipProtocolTest.java:50-66), thinned to keep the
# suite fast: N up to 50, loss up to 25%, delay up to 100ms.
MATRIX = [
    (2, 0, 0),
    (5, 0, 0),
    (10, 0, 0),
    (50, 0, 0),
    (10, 25, 0),
    (50, 25, 0),
    (10, 0, 100),
    (50, 10, 2),
]


@pytest.mark.parametrize("n,loss,delay", MATRIX)
def test_dissemination_and_no_double_delivery(n, loss, delay):
    """GossipProtocolTest.testGossipProtocol-shaped:156-175."""
    sim = Simulator(seed=42 + n + loss + delay)
    config = ClusterConfig.default()  # LAN: fanout 3, repeat 3, interval 200ms
    _, members, protocols = make_gossip_cluster(sim, n, config, loss, delay)

    received = {i: [] for i in range(n)}
    for i, g in enumerate(protocols):
        g.listen(lambda msg, i=i: received[i].append(msg))

    spread_future = protocols[0].spread(Message(qualifier="user/chat", data="juicy rumor"))
    sweep_ms = swim_math.gossip_timeout_to_sweep(
        config.gossip_repeat_mult, n, config.gossip_interval
    )
    sim.run_for(2 * sweep_ms + 1_000)

    delivered = [i for i in range(1, n) if received[i]]
    assert len(delivered) == n - 1, f"dissemination incomplete: {len(delivered)}/{n-1}"
    # Zero double delivery (dedup by gossip id, GossipProtocolImpl.java:176-180).
    for i in range(1, n):
        assert len(received[i]) == 1, f"node {i} got {len(received[i])} deliveries"
    # The spread future resolves on sweep (GossipProtocolImpl.java:283-308).
    assert spread_future.done


def test_dissemination_time_within_analytic_envelope():
    """Measured rounds-to-full-dissemination tracks ClusterMath's
    periodsToSpread prediction (GossipProtocolTest.java:178-205 logs this;
    we assert a 2x envelope)."""
    n = 50
    config = ClusterConfig.default()
    sim = Simulator(seed=7)
    _, members, protocols = make_gossip_cluster(sim, n, config)

    done_at = {}
    for i, g in enumerate(protocols[1:], start=1):
        g.listen(lambda msg, i=i: done_at.setdefault(i, sim.now))

    protocols[0].spread(Message(qualifier="q", data="x"))
    predicted_ms = swim_math.gossip_dissemination_time(
        config.gossip_repeat_mult, n, config.gossip_interval
    )
    sim.run_for(4 * predicted_ms)
    assert len(done_at) == n - 1
    measured_ms = max(done_at.values())
    assert measured_ms <= 2 * predicted_ms, (measured_ms, predicted_ms)


def test_max_messages_per_node_bounded():
    """Per-gossip sends per node stay within ClusterMath's bound
    (ClusterMath.java:65-67; sweep stops retransmission)."""
    n = 10
    config = ClusterConfig.default()
    sim = Simulator(seed=9)
    transports, members, protocols = make_gossip_cluster(sim, n, config)
    protocols[0].spread(Message(qualifier="q", data="x"))
    sweep_ms = swim_math.gossip_timeout_to_sweep(
        config.gossip_repeat_mult, n, config.gossip_interval
    )
    sim.run_for(3 * sweep_ms)
    # Exact protocol bound: the spread window is inclusive
    # (``infectionPeriod + periodsToSpread >= period``,
    # GossipProtocolImpl.java:243-247), i.e. periodsToSpread+1 periods of at
    # most ``fanout`` sends — one more period than ClusterMath's estimate
    # (ClusterMath.java:65-67), which the reference never asserts on counters.
    bound = config.gossip_fanout * (
        swim_math.gossip_periods_to_spread(config.gossip_repeat_mult, n) + 1
    )
    for t in transports:
        assert t.network_emulator.total_message_sent_count <= bound


def test_gossip_stops_after_sweep():
    """After the sweep horizon no node retransmits (GossipProtocolImpl.java:283-308)."""
    sim = Simulator(seed=10)
    config = ClusterConfig.default()
    transports, members, protocols = make_gossip_cluster(sim, 5, config)
    protocols[0].spread(Message(qualifier="q", data="x"))
    sweep_ms = swim_math.gossip_timeout_to_sweep(config.gossip_repeat_mult, 5, config.gossip_interval)
    sim.run_for(2 * sweep_ms)
    counts = [t.network_emulator.total_message_sent_count for t in transports]
    sim.run_for(5 * config.gossip_interval)
    assert [t.network_emulator.total_message_sent_count for t in transports] == counts
    assert all(not g.gossips for g in protocols)
