"""bench.py --metrics --smoke: the metrics-overhead JSON contract.

Like tests/test_bench_smoke.py for tracing: the bench is the one entry
point the measurements flow through, so this tier-1 test runs the real
script in a subprocess and pins the published contract — one JSON line,
a finite metrics_overhead_ratio over both measured rates, a
BENCH_*-style artifact, and a manifest whose ``metrics_window`` rows
round-trip through the sink reader and the query layer's SLO fold.
"""

import json
import math
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.metrics

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_bench_metrics_smoke_contract(tmp_path):
    artifact = tmp_path / "metrics_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_METRICS_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--metrics", "--smoke"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    result = json.loads(lines[0])

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == "swim_metrics_overhead_ratio"

    # Both rates measured, ratio consistent and finite.  No tight bound
    # here (a loaded CI box can skew one 80-round window); the
    # committed artifacts/metrics_smoke.json records the pinned <= 1.05
    # measurement and the regress CLI gates future ones.
    ratio = result["metrics_overhead_ratio"]
    unmetered = result["unmetered_member_rounds_per_sec"]
    metered = result["metered_member_rounds_per_sec"]
    assert unmetered > 0 and metered > 0
    assert math.isfinite(ratio) and ratio > 0
    assert ratio == pytest.approx(unmetered / metered, rel=1e-3)
    assert result["value"] == ratio

    # Registry digest: the health counters moved.
    counters = result["counters"]
    assert counters["fd_probes_sent"] > 0
    assert counters["gossip_messages"] > 0
    assert counters["live_observer_rounds"] > 0
    assert counters["suspicions_started"] > 0    # the crash-at-10 wave
    assert result["slos"]["false_positive_observer_rate"] is not None
    assert result["windows"] >= 2

    # The artifact round-trips and carries the same measurement.
    art = json.loads(artifact.read_text())
    assert art["metric"] == "metered_vs_unmetered_member_rounds_per_sec"
    assert art["metrics_overhead_ratio"] == ratio
    assert art["counters"] == counters
    assert art["smoke"] is True

    # The manifest's metrics_window rows fold back through the query
    # layer (the CLI's report path).
    from scalecube_cluster_tpu.telemetry import query as tquery
    from scalecube_cluster_tpu.telemetry import sink as tsink

    path = result["manifest"]
    assert os.path.dirname(path) == str(tmp_path)
    windows = tsink.read_records(path, kind="metrics_window")
    assert len(windows) == result["windows"]
    ends = [w["round_end"] for w in windows]
    assert ends == sorted(ends) and ends[-1] == result["rounds_timed"]
    report = tquery.load_report(path)
    assert report.counters == counters
    slos = tquery.compute_slos(report)
    assert slos["rounds_covered"] == result["rounds_timed"]

    # And the regress gate accepts the fresh artifact (ratio sane).
    ok, rows = tquery.regress([str(artifact)])
    ratio_rows = [r for r in rows
                  if r.get("check") == "slo/metrics_overhead_ratio"]
    assert len(ratio_rows) == 1
