"""The metadata KV plane (models/metadata.py + SwimParams.metadata_keys).

Four contracts, the sync-plane test shape applied to config:

  1. *off = bit-identical*: ``metadata_keys=0`` (the default) compiles
     the plane out — zero-size lanes, no new draws, the metrics tree is
     exactly the plane-less program's;
  2. *the packed word is LWW by construction*: within one (slot,
     epoch) the word is monotone in (version, value) so the merge is a
     plain max; epoch-mismatched words are dropped and a belief change
     zeroes stale cells (a reused slot never inherits config); a
     member never accepts external words about its own cells;
  3. *pushes propagate and converge*: an owner-local push reaches every
     live observer within the convergence bound on a healthy world,
     and through a quiesced partition heal ONLY with the anti-entropy
     exchange on — the gossip-only control stays divergent forever
     (the acceptance claim ``bench.py --rollout`` measures);
  4. *every run shape carries the plane unchanged* — including the
     sharded pipelined twin.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.models import metadata as md_plane
from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.metadata

STATE_FIELDS = ("status", "inc", "spread_until", "suspect_deadline",
                "self_inc")


def _assert_states_equal(a, b, fields=STATE_FIELDS):
    for f in fields:
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f))), f


def _md_value(state, observer, owner, key=0):
    return int(np.asarray(
        md_plane.word_value(state.md[observer, owner, key])))


def _md_version(state, observer, owner, key=0):
    return int(np.asarray(
        md_plane.word_version(state.md[observer, owner, key])))


# --------------------------------------------------------------------------
# 1: disabled default == baseline
# --------------------------------------------------------------------------


def test_metadata_defaults_off():
    params = swim.SwimParams.from_config(fast_config(), n_members=8)
    assert params.metadata_keys == 0
    explicit = dataclasses.replace(params, metadata_keys=0)
    assert explicit == params          # same static params, same program
    state = swim.initial_state(params, swim.SwimWorld.healthy(params))
    assert state.md.shape == (8, 0, 0)
    assert state.md_spread.shape == (8, 0)


def test_param_validation():
    params = swim.SwimParams.from_config(fast_config(), n_members=8,
                                         delivery="shift")
    with pytest.raises(ValueError, match="metadata_keys"):
        dataclasses.replace(params, metadata_keys=-1)
    with pytest.raises(ValueError, match="k_block"):
        dataclasses.replace(params, metadata_keys=1, k_block=4)
    focal = swim.SwimParams.from_config(fast_config(), n_members=8,
                                        delivery="scatter")
    with pytest.raises(ValueError, match="full view"):
        dataclasses.replace(focal, metadata_keys=1, n_subjects=4)


@pytest.mark.parametrize("delivery,subjects,layout", [
    ("scatter", None, "wide"),
    ("shift", None, "wide"),
    ("shift", None, "openworld"),
    ("shift", None, "compact"),
    ("scatter", None, "wire16"),
])
def test_plane_on_quiet_world_is_table_noop(delivery, subjects, layout):
    """With the plane armed but NO pushes scheduled, the carry and
    every existing metric are bit-identical to plane-off — the plane
    reuses the round's existing channel draws, so there is nothing to
    perturb.  Only the ``metadata_divergent`` observable is new, and it
    reads 0 (empty tables agree)."""
    n = 24
    p_off = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=subjects,
        delivery=delivery,
        open_world=layout == "openworld",
        compact_carry=layout == "compact", int16_wire=layout == "wire16",
    )
    p_on = dataclasses.replace(p_off, metadata_keys=2)
    world = swim.SwimWorld.healthy(p_off)
    s_off, m_off = swim.run(jax.random.key(0), p_off, world, 20)
    s_on, m_on = swim.run(jax.random.key(0), p_on, world, 20)
    _assert_states_equal(s_off, s_on)
    assert "metadata_divergent" not in m_off
    assert set(m_on) == set(m_off) | {"metadata_divergent"}
    for k in m_off:
        assert np.array_equal(np.asarray(m_off[k]), np.asarray(m_on[k])), k
    assert (np.asarray(m_on["metadata_divergent"]) == 0).all()
    assert (np.asarray(s_on.md) == 0).all()


# --------------------------------------------------------------------------
# 2: the packed word and the merge gates
# --------------------------------------------------------------------------


def test_word_packing_roundtrip_and_lww_order():
    ep = jnp.array([0, 3, 127, 130])        # 130 masks to 2
    ver = jnp.array([0, 1, 16383, 7])
    val = jnp.array([0, 1023, 512, 9])
    w = md_plane.pack_word(ep, ver, val)
    assert (np.asarray(w) >= 0).all()       # sign bit clear: max-safe
    assert np.array_equal(np.asarray(md_plane.word_epoch(w)),
                          [0, 3, 127, 2])
    assert np.array_equal(np.asarray(md_plane.word_version(w)),
                          np.asarray(ver))
    assert np.array_equal(np.asarray(md_plane.word_value(w)),
                          np.asarray(val))
    # Within one epoch the word is monotone in (version, value): the
    # jnp.maximum merge IS last-writer-wins.
    low = md_plane.pack_word(1, 3, 1023)
    high = md_plane.pack_word(1, 4, 0)
    assert int(high) > int(low)


def _merge_params():
    return swim.SwimParams.from_config(
        fast_config(), n_members=4, delivery="shift", open_world=True,
        metadata_keys=1)


def test_merge_is_lww_and_opens_spread_window():
    params = _merge_params()
    md = jnp.zeros((4, 4, 1), jnp.int32)
    md = md.at[0, 2, 0].set(int(md_plane.pack_word(0, 2, 5)))
    arr = jnp.zeros((4, 4, 1), jnp.int32)
    arr = arr.at[0, 2, 0].set(int(md_plane.pack_word(0, 3, 1)))   # newer
    arr = arr.at[1, 2, 0].set(int(md_plane.pack_word(0, 1, 9)))   # news
    is_self = jnp.zeros((4, 4), jnp.bool_)
    new_md, new_spread = md_plane.merge(
        md, jnp.zeros((4, 4), jnp.int32), arr.reshape(4, 4),
        jnp.int32(10), params, is_self,
        jnp.zeros((4, 4), jnp.int32), jnp.zeros((4,), jnp.bool_))
    assert _md_version(type("S", (), {"md": new_md}), 0, 2) == 3
    assert _md_value(type("S", (), {"md": new_md}), 0, 2) == 1
    # strictly-improved rows open the gossip window; untouched rows
    # stay closed
    assert int(new_spread[0, 2]) == 10 + 1 + params.periods_to_spread
    assert int(new_spread[1, 2]) == 10 + 1 + params.periods_to_spread
    assert int(new_spread[0, 0]) == 0
    # an OLDER arrival loses: replaying the stale word changes nothing
    again, _ = md_plane.merge(
        new_md, new_spread, md.reshape(4, 4), jnp.int32(11), params,
        is_self, jnp.zeros((4, 4), jnp.int32),
        jnp.zeros((4,), jnp.bool_))
    assert np.array_equal(np.asarray(again), np.asarray(new_md))


def test_merge_epoch_gate_drops_and_zeroes_stale():
    """Versions are per (slot, epoch): a word from the slot's PREVIOUS
    occupant is dropped at the receiver, and a belief change zeroes the
    receiver's own stale cell — a reused slot starts from an empty
    map."""
    params = _merge_params()
    stale = int(md_plane.pack_word(0, 9, 7))         # old occupant's word
    md = jnp.zeros((4, 4, 1), jnp.int32).at[0, 2, 0].set(stale)
    belief = jnp.zeros((4, 4), jnp.int32).at[0, 2].set(1)  # new epoch
    arr = jnp.zeros((4, 4, 1), jnp.int32).at[0, 2, 0].set(stale)
    new_md, _ = md_plane.merge(
        md, jnp.zeros((4, 4), jnp.int32), arr.reshape(4, 4),
        jnp.int32(5), params, jnp.zeros((4, 4), jnp.bool_), belief,
        jnp.zeros((4,), jnp.bool_))
    assert int(new_md[0, 2, 0]) == 0                 # dropped AND zeroed
    # a word carrying the CURRENT epoch is accepted
    fresh = jnp.zeros((4, 4, 1), jnp.int32).at[0, 2, 0].set(
        int(md_plane.pack_word(1, 1, 3)))
    new_md, _ = md_plane.merge(
        new_md, jnp.zeros((4, 4), jnp.int32), fresh.reshape(4, 4),
        jnp.int32(6), params, jnp.zeros((4, 4), jnp.bool_), belief,
        jnp.zeros((4,), jnp.bool_))
    assert _md_value(type("S", (), {"md": new_md}), 0, 2) == 3


def test_merge_self_pin_rejects_external_words_about_own_cells():
    params = _merge_params()
    md = jnp.zeros((4, 4, 1), jnp.int32)
    arr = jnp.zeros((4, 4, 1), jnp.int32).at[1, 1, 0].set(
        int(md_plane.pack_word(0, 5, 5)))
    is_self = (jnp.arange(4)[:, None] == jnp.arange(4)[None, :])
    new_md, _ = md_plane.merge(
        md, jnp.zeros((4, 4), jnp.int32), arr.reshape(4, 4),
        jnp.int32(3), params, is_self, jnp.zeros((4, 4), jnp.int32),
        jnp.zeros((4,), jnp.bool_))
    assert int(new_md[1, 1, 0]) == 0    # the owner is the sole authority


# --------------------------------------------------------------------------
# 3: pushes propagate; heal converges only with the exchange
# --------------------------------------------------------------------------


def _push_params(n, delivery="shift", sync_interval=4, **overrides):
    return swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery=delivery, sync_every=0,
        sync_interval=sync_interval, metadata_keys=1, **overrides)


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_push_reaches_every_observer(delivery):
    from scalecube_cluster_tpu.chaos import scenarios as cs

    n = 16
    params = _push_params(n, delivery=delivery)
    rounds = cs.metadata_convergence_bound(params, n)
    world = swim.SwimWorld.healthy(params) \
        .with_metadata_push(3, key=0, value=641, at_round=4)
    state, metrics = swim.run(jax.random.key(2), params, world, rounds)
    for obs in range(n):
        assert _md_value(state, obs, 3) == 641, obs
        assert _md_version(state, obs, 3) == 1
    assert int(md_plane.divergence_probe(state, params, world,
                                         rounds)) == 0
    # the divergence metric saw the spread and then settled to 0
    div = np.asarray(metrics["metadata_divergent"])
    assert div.max() > 0 and div[-1] == 0


def test_second_push_wins_everywhere():
    """Two pushes to the same (owner, key): version 2 and the LATER
    value end up in every observer's table — LWW, not first-writer."""
    from scalecube_cluster_tpu.chaos import scenarios as cs

    n = 16
    params = _push_params(n)
    rounds = 8 + cs.metadata_convergence_bound(params, n)
    world = swim.SwimWorld.healthy(params) \
        .with_metadata_push(5, key=0, value=900, at_round=3) \
        .with_metadata_push(5, key=0, value=17, at_round=8)
    state, _ = swim.run(jax.random.key(3), params, world, rounds)
    for obs in range(n):
        assert _md_value(state, obs, 5) == 17, obs
        assert _md_version(state, obs, 5) == 2


def test_crashed_owner_cannot_push():
    n = 16
    params = _push_params(n)
    world = swim.SwimWorld.healthy(params) \
        .with_crash(6, at_round=0) \
        .with_metadata_push(6, key=0, value=99, at_round=4)
    state, _ = swim.run(jax.random.key(4), params, world, 40)
    assert (np.asarray(state.md) == 0).all()


def _heal_setup(delivery, n=24, sync_interval=8):
    from scalecube_cluster_tpu.chaos import scenarios as cs

    p_ctl = _push_params(n, delivery=delivery, sync_interval=0)
    p_on = dataclasses.replace(p_ctl, sync_interval=sync_interval)
    phase = -(-cs.quiesce_bound(p_on, n) // 16) * 16
    rounds = phase + cs.metadata_convergence_bound(p_on, n)
    world = swim.SwimWorld.healthy(p_on)
    part = np.zeros((4, n), np.int8)
    part[0, : n // 2] = 1
    # the push lands INSIDE the split and goes cold (spread window
    # expires) long before heal: gossip alone can never carry it to
    # the far half afterwards
    world = world.with_partition_schedule(part, phase) \
        .with_metadata_push(0, key=0, value=321, at_round=8)
    return p_ctl, p_on, world, rounds


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_quiesced_heal_converges_only_with_exchange(delivery):
    p_ctl, p_on, world, rounds = _heal_setup(delivery)
    s_ctl, _ = swim.run(jax.random.key(5), p_ctl, world, rounds)
    s_on, _ = swim.run(jax.random.key(5), p_on, world, rounds)
    assert int(md_plane.divergence_probe(s_ctl, p_ctl, world,
                                         rounds)) > 0
    assert int(md_plane.divergence_probe(s_on, p_on, world,
                                         rounds)) == 0
    for obs in range(p_on.n_members):
        assert _md_value(s_on, obs, 0) == 321, obs
    # per-member view of the same fact
    conv = np.asarray(md_plane.member_converged(s_on, p_on, world,
                                                rounds))
    assert conv.all()
    assert not np.asarray(md_plane.member_converged(
        s_ctl, p_ctl, world, rounds)).all()


# --------------------------------------------------------------------------
# 4: every run shape carries the plane unchanged
# --------------------------------------------------------------------------


def test_run_shapes_agree_with_pushes():
    from scalecube_cluster_tpu.chaos import monitor as cm

    n = 16
    params = _push_params(n, delivery="scatter")
    world = swim.SwimWorld.healthy(params) \
        .with_metadata_push(2, key=0, value=55, at_round=3)
    rounds = 48
    ref, m_ref = swim.run(jax.random.key(8), params, world, rounds)
    traced, _, _ = swim.run_traced(jax.random.key(8), params, world,
                                   rounds)
    metered, _, m_met = swim.run_metered(jax.random.key(8), params,
                                         world, rounds)
    spec = cm.MonitorSpec.passive(params)
    monitored, _, _ = cm.run_monitored(jax.random.key(8), params, world,
                                       spec, rounds)
    mm, _, _, _ = cm.run_monitored_metered(jax.random.key(8), params,
                                           world, spec, rounds)
    for other in (traced, metered, monitored, mm):
        _assert_states_equal(ref, other)
        assert np.array_equal(np.asarray(ref.md), np.asarray(other.md))
    assert np.array_equal(np.asarray(m_ref["metadata_divergent"]),
                          np.asarray(m_met["metadata_divergent"]))


def test_checkpoint_roundtrips_metadata_lanes(tmp_path):
    from scalecube_cluster_tpu.utils import checkpoint as ckpt

    n = 16
    params = _push_params(n)
    world = swim.SwimWorld.healthy(params) \
        .with_metadata_push(1, key=0, value=7, at_round=2)
    state, _ = swim.run(jax.random.key(9), params, world, 24)
    path = str(tmp_path / "md.npz")
    ckpt.save(path, state, next_round=24)
    restored, next_round, _, _ = ckpt.load(path, params=params)
    assert next_round == 24
    assert np.array_equal(np.asarray(state.md), np.asarray(restored.md))
    assert np.array_equal(np.asarray(state.md_spread),
                          np.asarray(restored.md_spread))


@pytest.mark.multichip
def test_sharded_pipelined_equals_serial_with_pushes_and_heals():
    from scalecube_cluster_tpu.parallel import compat
    from scalecube_cluster_tpu.parallel import mesh as pmesh

    if not compat.HAS_SHARD_MAP:
        pytest.skip(compat.SKIP_REASON)
    n = 32
    _, p_on, world, rounds = _heal_setup("scatter", n=n)
    mesh = pmesh.make_mesh(4)
    s_ser, m_ser = pmesh.shard_run(jax.random.key(6), p_on, world,
                                   rounds, mesh, pipelined=False)
    s_pip, m_pip = pmesh.shard_run(jax.random.key(6), p_on, world,
                                   rounds, mesh, pipelined=True)
    _assert_states_equal(s_ser, s_pip)
    assert np.array_equal(np.asarray(s_ser.md), np.asarray(s_pip.md))
    assert np.array_equal(np.asarray(s_ser.md_spread),
                          np.asarray(s_pip.md_spread))
    for k in m_ser:
        assert np.array_equal(np.asarray(m_ser[k]),
                              np.asarray(m_pip[k])), k
    assert "metadata_divergent" in m_ser
    # the sharded run converged: every shard's final table carries the
    # pushed word for every observer
    md = np.asarray(s_ser.md).reshape(n, n, 1)
    assert (np.asarray(md_plane.word_value(md[:, 0, 0])) == 321).all()


# --------------------------------------------------------------------------
# The full churn matrix: identity epochs keep LWW sound (slow)
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_config_survives_churn_storm_scenario():
    """A ConfigPush riding a real churn scenario end to end through the
    monitored campaign path: metadata auto-armed, the monitor green,
    and the pushed word converged across the survivors at horizon."""
    from scalecube_cluster_tpu.chaos import campaign as cc
    from scalecube_cluster_tpu.chaos import scenarios as cs

    n = 24
    storm = cs.ChurnStorm(nodes=(1, 2, 3, 4), wave_size=2,
                          start_round=8, wave_every=24, down_rounds=60)
    push = cs.ConfigPush(node=9, key=0, value=777, at_round=12)
    params0 = swim.SwimParams.from_config(
        cc.campaign_config(), n_members=n, delivery="shift",
        sync_every=0, sync_interval=8, metadata_keys=1)
    bound = cs.metadata_convergence_bound(params0, n)
    horizon = -(-(storm.start_round + 2 * 24 + 60 + bound) // 64) * 64
    scen = cs.Scenario(name="churn+push", n_members=n, horizon=horizon,
                       ops=(storm, push), loss_probability=0.0, seed=0)
    params = cc.campaign_params(scen, delivery="shift", sync_every=0,
                                sync_interval=8)
    assert params.metadata_keys == 1     # armed by the scenario
    world, spec = scen.build(params)
    from scalecube_cluster_tpu.chaos import monitor as cm

    state, mon, _ = cm.run_monitored(
        jax.random.key(0), params, world, spec, horizon)
    assert cm.verdict(mon)["green"]
    assert int(md_plane.divergence_probe(state, params, world,
                                         horizon)) == 0
    for obs in range(n):
        assert _md_value(state, obs, 9) == 777, obs


def test_merge_frozen_rows_keep_their_lanes():
    """Frozen (crashed/left) rows are a stopped JVM: arrivals that
    would improve them are ignored and their spread lanes hold — the
    same carry-freeze rule every other plane follows."""
    params = _merge_params()
    md = jnp.zeros((4, 4, 1), jnp.int32)
    spread = jnp.full((4, 4), 7, jnp.int32)
    arr = jnp.zeros((4, 4, 1), jnp.int32)
    arr = arr.at[2, 0, 0].set(int(md_plane.pack_word(0, 4, 8)))
    arr = arr.at[3, 0, 0].set(int(md_plane.pack_word(0, 4, 8)))
    frozen = jnp.asarray([False, False, True, False])
    new_md, new_spread = md_plane.merge(
        md, spread, arr.reshape(4, 4), jnp.int32(10), params,
        jnp.zeros((4, 4), jnp.bool_), jnp.zeros((4, 4), jnp.int32),
        frozen)
    assert int(new_md[2, 0, 0]) == 0            # frozen: word dropped
    assert int(new_spread[2, 0]) == 7           # frozen: lane held
    assert int(new_md[3, 0, 0]) == int(md_plane.pack_word(0, 4, 8))
    assert int(new_spread[3, 0]) == 10 + 1 + params.periods_to_spread


def test_dead_suppression_window_keeps_words_and_versions_resume():
    """dead_suppress_rounds interplay: a crashed owner's words are NOT
    tombstoned — observers keep the last LWW value straight through the
    suppression window, the owner's frozen row preserves its version
    counter, and the first post-revival push resumes at version 2 and
    reconverges everywhere."""
    from scalecube_cluster_tpu.chaos import scenarios as cs

    n = 16
    params = _push_params(n, dead_suppress_rounds=24)
    bound = cs.metadata_convergence_bound(params, n)
    crash_at, revive_at = 8 + bound, 8 + bound + 40   # > suppress window
    world = (swim.SwimWorld.healthy(params)
             .with_metadata_push(2, key=0, value=555, at_round=4)
             .with_crash(2, at_round=crash_at, until_round=revive_at)
             .with_metadata_push(2, key=0, value=777,
                                 at_round=revive_at + 8))
    rounds = revive_at + 8 + bound

    # Mid-run probe: inside the dead window every live observer still
    # holds the dead owner's last write (no tombstone zeroing).
    mid_state, _ = swim.run(jax.random.key(3), params, world,
                            crash_at + 12)
    for obs in range(n):
        if obs != 2:
            assert _md_value(mid_state, obs, 2) == 555, obs
            assert _md_version(mid_state, obs, 2) == 1

    state, _ = swim.run(jax.random.key(3), params, world, rounds)
    for obs in range(n):
        assert _md_value(state, obs, 2) == 777, obs
        assert _md_version(state, obs, 2) == 2, obs     # counter resumed
    assert int(md_plane.divergence_probe(state, params, world,
                                         rounds)) == 0
