"""Tests for the gossip-only TPU model (models/gossip.py).

Mirrors the reference's statistical experiment design
(GossipProtocolTest.java:50-66: matrix over {N, loss, delay}; asserts full
dissemination within the sweep window and no double delivery; compares
measured curves to ClusterMath predictions at :178-205).
"""

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu import swim_math
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import gossip


def make_params(n, fanout=3, repeat_mult=3, loss=0.0, n_gossips=1):
    config = ClusterConfig.default().replace(
        gossip_fanout=fanout, gossip_repeat_mult=repeat_mult
    )
    return gossip.GossipSimParams.from_config(
        config, n_members=n, n_gossips=n_gossips, loss_probability=loss
    )


class TestDissemination:
    @pytest.mark.parametrize("n", [2, 3, 5, 10, 50])
    def test_full_dissemination_no_loss(self, n):
        """Lossless gossip reaches all N within the sweep window (reference
        envelope: GossipProtocolTest.java:156-175 asserts the same)."""
        params = make_params(n)
        sweep = swim_math.gossip_periods_to_sweep(3, n)
        _, metrics = gossip.run(jax.random.key(0), params, sweep)
        rounds = gossip.dissemination_rounds(metrics, n)
        assert int(rounds[0]) >= 0, "gossip never fully disseminated"

    def test_dissemination_near_analytic_prediction(self):
        """Measured full-dissemination round tracks repeatMult*ceilLog2(n+1)
        (ClusterMath.java:111-113) within a small factor."""
        n = 128
        params = make_params(n)
        predicted = swim_math.gossip_periods_to_spread(3, n)
        _, metrics = gossip.run(jax.random.key(1), params, 4 * predicted)
        measured = int(gossip.dissemination_rounds(metrics, n)[0])
        assert 0 < measured <= predicted, (measured, predicted)

    @pytest.mark.parametrize("loss", [0.10, 0.25])
    def test_dissemination_under_loss(self, loss):
        """Under <=25% loss, dissemination still completes within the sweep
        window with margin (reference matrix runs loss in {0,10,25,50}%)."""
        n = 50
        params = make_params(n, loss=loss, n_gossips=4)
        sweep = swim_math.gossip_periods_to_sweep(3, n)
        _, metrics = gossip.run(jax.random.key(2), params, sweep)
        rounds = np.asarray(gossip.dissemination_rounds(metrics, n))
        assert np.all(rounds >= 0), rounds

    def test_convergence_probability_vs_cluster_math(self):
        """Fraction of fully-converged gossips >= the analytic lower-ish bound
        (ClusterMath.java:38-43), the reference's published model."""
        n, loss = 64, 0.25
        params = make_params(n, loss=loss, n_gossips=64)
        sweep = swim_math.gossip_periods_to_sweep(3, n)
        _, metrics = gossip.run(jax.random.key(3), params, sweep)
        rounds = np.asarray(gossip.dissemination_rounds(metrics, n))
        measured = float(np.mean(rounds >= 0))
        predicted = swim_math.gossip_convergence_probability(3, 3, n, loss)
        assert measured >= predicted - 0.05, (measured, predicted)


class TestProtocolInvariants:
    def test_messages_bounded_by_cluster_math(self):
        """Per-gossip transmissions <= fanout*repeatMult*ceilLog2(n+1) per node
        (ClusterMath.java:65-67 worst-case bound) aggregated over nodes."""
        n = 50
        params = make_params(n)
        sweep = swim_math.gossip_periods_to_sweep(3, n)
        _, metrics = gossip.run(jax.random.key(4), params, sweep)
        total = int(np.asarray(metrics["messages_sent"]).sum())
        bound = swim_math.max_messages_per_gossip_total(3, 3, n)
        assert total <= bound, (total, bound)

    def test_no_double_delivery(self):
        """newly_infected totals N-1 + origin exactly once per gossip — the
        dedup-by-id assertion of GossipProtocolTest.java:156-175."""
        n = 32
        params = make_params(n, n_gossips=3)
        sweep = swim_math.gossip_periods_to_sweep(3, n)
        _, metrics = gossip.run(jax.random.key(5), params, sweep)
        newly_total = np.asarray(metrics["newly_infected"]).sum(axis=0)
        assert np.all(newly_total <= n - 1)

    def test_spread_stops_after_sweep_window(self):
        """After every member's spread window closes, no more messages flow
        (sweepGossips analog, GossipProtocolImpl.java:283-308)."""
        n = 16
        params = make_params(n)
        sweep = swim_math.gossip_periods_to_sweep(3, n)
        horizon = 3 * sweep
        _, metrics = gossip.run(jax.random.key(6), params, horizon)
        sent = np.asarray(metrics["messages_sent"])[:, 0]
        assert sent[-1] == 0
        # Once it stops it stays stopped.
        stopped_at = np.argmax(sent == 0)
        assert np.all(sent[stopped_at:] == 0)

    def test_determinism(self):
        params = make_params(20, n_gossips=2)
        _, m1 = gossip.run(jax.random.key(7), params, 30)
        _, m2 = gossip.run(jax.random.key(7), params, 30)
        np.testing.assert_array_equal(
            np.asarray(m1["infected_count"]), np.asarray(m2["infected_count"])
        )

    def test_different_seed_different_trace(self):
        params = make_params(20, loss=0.3, n_gossips=2)
        _, m1 = gossip.run(jax.random.key(8), params, 30)
        _, m2 = gossip.run(jax.random.key(9), params, 30)
        assert not np.array_equal(
            np.asarray(m1["infected_count"]), np.asarray(m2["infected_count"])
        )
