"""bench.py --churn --smoke: the open-world A/B JSON contract.

Like tests/test_bench_lifeguard_smoke.py for the health plane: the
bench is the one entry point the open-world measurement flows through,
so this tier-1 test runs the real script in a subprocess (CPU) and pins
the published contract — one JSON line with the A/B fields (the epoch
guard holding zero NO_RESURRECTION / JOIN_COMPLETENESS violations with
join propagation inside the bound, the naive control arm demonstrating
the resurrection failure, net-positive growth), an
artifacts/churn_growth.json-style artifact the query layer loads as a
real payload, and the regress gate walking it with the absolute churn
checks.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.openworld

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_churn_bench(tmp_path, extra_env=None, timeout=540):
    artifact = tmp_path / "churn_growth_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_CHURN_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--churn", "--smoke"],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    return json.loads(lines[0]), artifact


def test_bench_churn_smoke_contract(tmp_path):
    result, artifact = _run_churn_bench(tmp_path)

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == "churn_growth"
    # value stays None BY DESIGN (absolute violation/latency gates must
    # not enter the generic throughput walk); the payload says so.
    assert result["value"] is None
    assert "value_note" in result

    # The headline acceptance: the guard arm is clean, the naive arm
    # demonstrates the hazard, the joins propagate inside the bound,
    # and the storm actually grew the cluster.
    assert result["guard_green"] is True
    assert result["no_resurrection_violations"] == 0
    assert result["join_completeness_violations"] == 0
    assert result["naive_no_resurrection_violations"] > 0
    assert result["join_propagation_p99_rounds"] is not None
    assert (result["join_propagation_p99_rounds"]
            <= result["join_propagation_bound_rounds"])
    assert result["net_growth_members"] > 0
    assert result["joins_admitted"] > 0
    assert result["joined_events"] > 0
    # The identity-confusion refutation burn is a naive-arm property.
    assert result["refutations_naive"] > result["refutations_guard"]

    # Workload provenance: the seeded scenario and its repro line.
    assert result["n_scenarios"] >= 1
    assert result["delivery"] == "shift"
    for row in result["scenarios"]:
        assert "churn_growth_scenario" in row["repro"]
        assert row["joined_events"] > 0

    # The artifact landed and is a real query-layer payload with the
    # absolute churn gates passing.
    assert artifact.exists()
    art = json.loads(artifact.read_text())
    assert art["no_resurrection_violations"] == 0

    from scalecube_cluster_tpu.telemetry import query

    payload, note = query.load_bench_payload(str(artifact))
    assert payload is not None, note
    ok, rows = query.regress([str(artifact)])
    assert ok, rows
    checks = {r["check"] for r in rows if r.get("ok") is not None}
    assert "slo/churn_no_resurrection" in checks
    assert "slo/churn_naive_demonstrates_failure" in checks
    assert "slo/churn_join_propagation_within_bound" in checks
    assert "slo/churn_net_positive_growth" in checks

    # The in-bench regress gate ran and passed.
    assert result["regress"]["ok"] is True
