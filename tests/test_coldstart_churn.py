"""Cold-start join + churn cross-validation: oracle ↔ scatter ↔ shift.

Round-4's verdict flagged cold start and churn as the one regime where
the tick's delivery modes were known to deviate (partially-joined nodes
probe less in shift mode; push-only SYNC made joins sync-quantized and
heavy-tailed).  Round 5 fixed the root cause — the joiner ⇄ seed SYNC
round trip (models/swim._seed_anti_entropy, the reference's
doSync-seeds-∪-live + syncAck protocol,
MembershipProtocolImpl.java:298-331,346-367) — and this module pins the
resulting cross-layer agreement:

  - seed-hub cold-start join time (MembershipProtocolTest.java:432-462's
    regime): oracle median 3 rounds; both tick modes within one sync
    cycle;
  - crash DURING cold start (partial knowledge): DEAD declaration on the
    oracle timescale in both modes;
  - freeze/revive churn (the reference's partition+restart scenarios,
    MembershipProtocolTest.java:368-430): detection AND re-acceptance
    timescales agree;
  - the shift-mode probe-rate deviation is bounded: the ramp to full
    probing completes within two fd cycles of the views filling.

Measured 8-seed medians that set the bands (2026-07-31, N=16):
  join:    oracle 3 (3..4) | scatter 4 (3..4) | shift 3 (3..4)
  cs-dead: oracle 34       | scatter 34 (34..44) | shift 34 (32..34)
  churn:   oracle dead 33, back 10 | ticks dead 30, back 3..4
"""

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.oracle import Cluster, Simulator
from scalecube_cluster_tpu.records import MemberStatus

N = 16
ROUND_MS = 100
CFG = ClusterConfig.default_local().replace(
    gossip_interval=ROUND_MS,
    ping_interval=200,
    ping_timeout=100,
    sync_interval=1_000,
    suspicion_mult=3,
)
N_SEEDS = 8
SYNC_CYCLE = CFG.sync_interval // ROUND_MS


def median(xs):
    return float(np.median(xs))


def build_oracle(seed, warmup_ms=0):
    sim = Simulator(seed=seed)
    clusters = [Cluster.join(sim, config=CFG, alias="m0")]
    for i in range(1, N):
        clusters.append(Cluster.join(sim, seeds=[clusters[0].address],
                                     config=CFG, alias=f"m{i}"))
    if warmup_ms:
        sim.run_for(warmup_ms)
    return sim, clusters


def cold_state(params, world):
    return swim.initial_state(params, world, warm=False)


# --------------------------------------------------------------------------
# (1) Seed-hub cold-start join
# --------------------------------------------------------------------------


def oracle_join_rounds(seed):
    sim, clusters = build_oracle(seed)
    t0 = sim.now
    for _ in range(120):
        sim.run_for(ROUND_MS)
        if all(len(c.members()) == N for c in clusters):
            return (sim.now - t0) / ROUND_MS
    return float("inf")


def tick_join_rounds(seed, delivery):
    params = swim.SwimParams.from_config(CFG, n_members=N, delivery=delivery)
    world = swim.SwimWorld.healthy(params).with_seeds(0)
    _, m = swim.run(jax.random.key(seed), params, world, 120,
                    state=cold_state(params, world))
    full = np.all(np.asarray(m["alive"]) == N - 1, axis=1)
    idx = np.flatnonzero(full)
    return float(idx[0]) if idx.size else float("inf")


@pytest.fixture(scope="module")
def oracle_join_stats():
    return [oracle_join_rounds(s) for s in range(N_SEEDS)]


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_cold_start_join_matches_oracle(oracle_join_stats, delivery):
    o_med = median(oracle_join_stats)
    t_runs = [tick_join_rounds(s, delivery) for s in range(N_SEEDS)]
    t_med = median(t_runs)
    assert np.isfinite(o_med), oracle_join_stats
    assert np.isfinite(t_med), t_runs
    # Measured medians 3 vs 3-4; the band is one sync cycle + 2 — before
    # the seed round trip this was 40 (scatter) / 100-with-inf (shift).
    assert abs(t_med - o_med) <= SYNC_CYCLE + 2, (delivery, t_med, o_med,
                                                  t_runs)
    # And no heavy tail: every seed joins within 3 sync cycles.
    assert max(t_runs) <= 3 * SYNC_CYCLE, (delivery, t_runs)


# --------------------------------------------------------------------------
# (2) Crash during cold start (partial knowledge)
# --------------------------------------------------------------------------

CRASH_AT = 2


def oracle_coldstart_dead_rounds(seed):
    """Rounds from cluster start to first observer declaring the victim
    (which crashed CRASH_AT rounds in) dead."""
    sim, clusters = build_oracle(seed)
    sim.run_for(CRASH_AT * ROUND_MS)
    victim = clusters[5]
    vid = victim.member().id
    victim.transport.stop()
    others = [c for c in clusters if c is not victim]
    for r in range(300):
        sim.run_for(ROUND_MS)
        for c in others:
            recs = {rr.member.id for rr in c.membership.membership_records()}
            # Declared dead = once known, now removed (r > a few rounds
            # guards the window before anyone learned the victim existed).
            if r > 5 and vid not in recs and len(c.members()) >= N - 1:
                return float(r + CRASH_AT)
    return float("inf")


def tick_coldstart_dead_rounds(seed, delivery):
    params = swim.SwimParams.from_config(CFG, n_members=N, delivery=delivery)
    world = (swim.SwimWorld.healthy(params).with_seeds(0)
             .with_crash(5, at_round=CRASH_AT))
    _, m = swim.run(jax.random.key(seed), params, world, 300,
                    state=cold_state(params, world))
    idx = np.flatnonzero(np.asarray(m["dead"])[:, 5] > 0)
    return float(idx[0]) if idx.size else float("inf")


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_cold_start_crash_detection_matches_oracle(delivery):
    o_runs = [oracle_coldstart_dead_rounds(s) for s in range(6)]
    t_runs = [tick_coldstart_dead_rounds(s, delivery) for s in range(6)]
    o_med, t_med = median(o_runs), median(t_runs)
    assert np.isfinite(o_med), o_runs
    assert np.isfinite(t_med), t_runs
    # Measured: oracle 34, ticks 34 (scatter tail to 44 when the victim
    # dies before some observers learned of it — the same effect delays
    # the oracle's own declaration on other seeds).  15% + 3.
    assert abs(t_med - o_med) <= 0.15 * o_med + 3, (delivery, t_med, o_med,
                                                    t_runs)


# --------------------------------------------------------------------------
# (3) Freeze / revive churn
# --------------------------------------------------------------------------

FREEZE_ROUNDS = 60


def oracle_churn_rounds(seed):
    """(dead_first, back_all) — detection of a frozen member and
    re-acceptance after it thaws (block-all is the oracle analog of the
    tick's frozen-JVM crash window: state intact, no traffic)."""
    sim, clusters = build_oracle(seed, warmup_ms=2_000)
    victim = clusters[3]
    vid = victim.member().id
    others = [c for c in clusters if c is not victim]
    victim.network_emulator.block(
        [c.address for c in clusters if c is not victim])
    for c in others:
        c.network_emulator.block(victim.address)
    t0 = sim.now
    dead_first = None
    for _ in range(FREEZE_ROUNDS):
        sim.run_for(ROUND_MS)
        if dead_first is None and any(
                vid not in {m.id for m in c.members()} for c in others):
            dead_first = (sim.now - t0) / ROUND_MS
    victim.network_emulator.unblock_all()
    for c in others:
        c.network_emulator.unblock(victim.address)
    t1 = sim.now
    for _ in range(150):
        sim.run_for(ROUND_MS)
        if all(vid in {m.id for m in c.members()} for c in others):
            return (dead_first or float("inf"),
                    (sim.now - t1) / ROUND_MS)
    return dead_first or float("inf"), float("inf")


def tick_churn_rounds(seed, delivery):
    params = swim.SwimParams.from_config(CFG, n_members=N, delivery=delivery)
    world = swim.SwimWorld.healthy(params).with_crash(
        3, at_round=0, until_round=FREEZE_ROUNDS)
    horizon = FREEZE_ROUNDS + 160
    _, m = swim.run(jax.random.key(seed), params, world, horizon)
    deads = np.asarray(m["dead"])[:, 3]
    alive_v = np.asarray(m["alive"])[:, 3]
    dead_idx = np.flatnonzero(deads > 0)
    back_idx = np.flatnonzero(
        (alive_v == N - 1) & (np.arange(horizon) >= FREEZE_ROUNDS))
    return (float(dead_idx[0]) if dead_idx.size else float("inf"),
            float(back_idx[0] - FREEZE_ROUNDS) if back_idx.size
            else float("inf"))


@pytest.fixture(scope="module")
def oracle_churn_stats():
    return [oracle_churn_rounds(s) for s in range(N_SEEDS)]


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_churn_freeze_revive_matches_oracle(oracle_churn_stats, delivery):
    o_dead = median([d for d, _ in oracle_churn_stats])
    o_back = median([b for _, b in oracle_churn_stats])
    t_runs = [tick_churn_rounds(s, delivery) for s in range(N_SEEDS)]
    t_dead = median([d for d, _ in t_runs])
    t_back = median([b for _, b in t_runs])
    assert np.isfinite([o_dead, o_back, t_dead, t_back]).all(), \
        (oracle_churn_stats, t_runs)
    # Detection: measured 33 vs 30 (the within-round verdict offset).
    assert abs(t_dead - o_dead) <= 0.15 * o_dead + 3, (delivery, t_dead,
                                                       o_dead, t_runs)
    # Re-acceptance: the revived member's refutation travels by gossip on
    # the tick (3-4 rounds) while the oracle's victim must first LEARN it
    # was declared dead (sync-quantized: 10) — agreement within one sync
    # cycle + 2.
    assert abs(t_back - o_back) <= SYNC_CYCLE + 2, (delivery, t_back,
                                                    o_back, t_runs)


# --------------------------------------------------------------------------
# (4) The shift-mode probe-rate deviation, quantified and bounded
# --------------------------------------------------------------------------


def test_shift_probe_ramp_bounded():
    """Shift-mode FD probes only when the shared offset lands on a known
    entry, so during cold start its probe rate tracks the fraction known
    (module docstring deviation).  With the seed round trip the views
    fill in ~1 sync cycle, so the deviation is bounded: full probe rate
    within 2 fd cycles of the join completing.  Scatter mode (known-only
    uniform draws) probes near-fully from the first fd round — the two
    modes' counters document the deviation rather than hiding it."""
    rates = {}
    for delivery in ("scatter", "shift"):
        params = swim.SwimParams.from_config(CFG, n_members=N,
                                             delivery=delivery)
        world = swim.SwimWorld.healthy(params).with_seeds(0)
        _, m = swim.run(jax.random.key(0), params, world, 60,
                        state=cold_state(params, world))
        ps = np.asarray(m["messages_ping_sent"])
        alive = np.asarray(m["alive"])
        full_at = int(np.flatnonzero(np.all(alive == N - 1, axis=1))[0])
        fd_rounds = np.flatnonzero(ps > 0)
        rates[delivery] = ps
        # Full probing (= N pings per fd round) within 2 fd cycles of the
        # views filling.
        fd_cycle = params.ping_every
        late = fd_rounds[fd_rounds >= full_at + 2 * fd_cycle]
        assert late.size and (ps[late] == N).all(), (delivery, full_at, ps)
    # The deviation exists and is confined to the cold window: shift's
    # cumulative probes never exceed scatter's there.
    assert rates["shift"][:4].sum() <= rates["scatter"][:4].sum()
