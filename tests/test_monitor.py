"""Monitor/introspection snapshots — the JMX MBean analog on both layers.

Reference: ClusterImpl.JmxMonitorMBean (ClusterImpl.java:366-396) and
MembershipProtocolImpl.JmxMonitorMBean (:693-749): member identity,
incarnation, alive/suspected member lists, removal ring, metadata dump.
"""

import jax
import numpy as np

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.oracle import Cluster, Simulator

from tests.test_swim_model import fast_config


def test_oracle_monitor_snapshot():
    sim = Simulator(seed=9)
    alice = Cluster.join(sim, alias="alice", metadata={"role": "seed"})
    bob = Cluster.join(sim, seeds=[alice.address], alias="bob")
    carol = Cluster.join(sim, seeds=[alice.address], alias="carol")
    sim.run_for(2_000)

    snap = alice.monitor()
    assert snap["member"].startswith("alice@")
    assert any("bob@" in m for m in snap["alive_members"])
    assert snap["metadata"] == {"role": "seed"}
    assert snap["removed_members"] == []

    carol.transport.stop()
    sim.run_for(4_000)  # > FD rotation + ping interval + timeout
    mid = alice.monitor()
    assert any("carol@" in m for m in mid["suspected_members"])

    sim.run_for(20_000)
    end = alice.monitor()
    assert [r["member"] for r in end["removed_members"]] == [str(carol.member())]
    assert not any("carol@" in m for m in end["alive_members"])


def test_tick_node_snapshot():
    n = 12
    params = swim.SwimParams.from_config(fast_config(), n_members=n)
    world = swim.SwimWorld.healthy(params).with_crash(4, at_round=0)
    state, _ = swim.run(jax.random.key(2), params, world, 12)

    snap = swim.node_snapshot(state, params, world, node_id=0)
    assert snap["node_id"] == 0
    assert 4 in (snap["suspected_members"] + snap["dead_tombstones"]
                 + snap["alive_members"])
    # Every pending timer belongs to a currently-suspected subject.
    for subject in snap["pending_suspicion_timers"]:
        assert subject in snap["suspected_members"]
    # All live members tracked at some incarnation.
    assert set(snap["record_incarnations"]) >= set(snap["alive_members"])


def test_tick_snapshot_after_refutation_shows_bumped_incarnation():
    n = 12
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, loss_probability=0.3
    )
    world = swim.SwimWorld.healthy(params)
    state, metrics = swim.run(jax.random.key(5), params, world, 300)
    assert np.asarray(metrics["refutations"]).sum() > 0
    incs = [swim.node_snapshot(state, params, world, i)["incarnation"]
            for i in range(n)]
    assert max(incs) > 0
