"""Monitor/introspection snapshots — the JMX MBean analog on both layers.

Reference: ClusterImpl.JmxMonitorMBean (ClusterImpl.java:366-396) and
MembershipProtocolImpl.JmxMonitorMBean (:693-749): member identity,
incarnation, alive/suspected member lists, removal ring, metadata dump.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.oracle import Cluster, Simulator

from tests.test_swim_model import fast_config


def test_oracle_monitor_snapshot():
    sim = Simulator(seed=9)
    alice = Cluster.join(sim, alias="alice", metadata={"role": "seed"})
    bob = Cluster.join(sim, seeds=[alice.address], alias="bob")
    carol = Cluster.join(sim, seeds=[alice.address], alias="carol")
    sim.run_for(2_000)

    snap = alice.monitor()
    assert snap["member"].startswith("alice@")
    assert any("bob@" in m for m in snap["alive_members"])
    assert snap["metadata"] == {"role": "seed"}
    assert snap["removed_members"] == []

    carol.transport.stop()
    sim.run_for(4_000)  # > FD rotation + ping interval + timeout
    mid = alice.monitor()
    assert any("carol@" in m for m in mid["suspected_members"])

    sim.run_for(20_000)
    end = alice.monitor()
    assert [r["member"] for r in end["removed_members"]] == [str(carol.member())]
    assert not any("carol@" in m for m in end["alive_members"])


def test_tick_node_snapshot():
    n = 12
    params = swim.SwimParams.from_config(fast_config(), n_members=n)
    world = swim.SwimWorld.healthy(params).with_crash(4, at_round=0)
    state, _ = swim.run(jax.random.key(2), params, world, 12)

    snap = swim.node_snapshot(state, params, world, node_id=0)
    assert snap["node_id"] == 0
    assert 4 in (snap["suspected_members"] + snap["dead_tombstones"]
                 + snap["alive_members"])
    # Every pending timer belongs to a currently-suspected subject.
    for subject in snap["pending_suspicion_timers"]:
        assert subject in snap["suspected_members"]
    # All live members tracked at some incarnation.
    assert set(snap["record_incarnations"]) >= set(snap["alive_members"])


def test_tick_snapshot_after_refutation_shows_bumped_incarnation():
    n = 12
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, loss_probability=0.3
    )
    world = swim.SwimWorld.healthy(params)
    state, metrics = swim.run(jax.random.key(5), params, world, 300)
    assert np.asarray(metrics["refutations"]).sum() > 0
    incs = [swim.node_snapshot(state, params, world, i)["incarnation"]
            for i in range(n)]
    assert max(incs) > 0


# --------------------------------------------------------------------------
# POST_HEAL_DIVERGENCE: the SYNC anti-entropy re-convergence contract
# --------------------------------------------------------------------------


def _heal_scenario(n, sync_interval):
    """A quiesced single split/heal cycle + its params (plane on when
    sync_interval > 0; the in-tick push channel off in both arms so the
    control is honestly gossip-only)."""
    from scalecube_cluster_tpu.chaos import scenarios as cs

    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", sync_every=0,
        sync_interval=sync_interval,
    )
    scen = cs.quiesced_heal_scenario(params, n, name="monitor-heal")
    world, spec = scen.build(params)
    return params, world, spec, scen


@pytest.mark.sync
def test_post_heal_divergence_trips_on_gossip_only_heal():
    """Gossip-only control: the healed halves' stale tombstones are
    never repaired, so past the agreement deadline the code trips —
    exact totals every round plus first-trip evidence lanes."""
    from scalecube_cluster_tpu.chaos import monitor as cm
    from scalecube_cluster_tpu.chaos import scenarios as cs

    n = 16
    params, world, spec, scen = _heal_scenario(n, sync_interval=0)
    # build() makes no agreement promise without the plane; arm the
    # deadline manually to demonstrate the divergence is real.
    assert int(spec.agree_from) == np.iinfo(np.int32).max
    p_on = dataclasses.replace(params, sync_interval=8)
    agree_from = (scen.ops[0].phase_rounds
                  + cs.post_heal_agreement_bound(p_on, n))
    spec = dataclasses.replace(spec, agree_from=jnp.int32(agree_from),
                               check_agreement=True)

    _, mon, _ = cm.run_monitored(jax.random.key(0), params, world, spec,
                                 scen.horizon)
    v = cm.verdict(mon)
    code = v["codes"]["POST_HEAL_DIVERGENCE"]
    assert not v["green"]
    assert code["violations"] > 0
    assert code["first_round"] == agree_from      # trips the moment due
    # Every OTHER safety code stays green: the divergence is the only
    # contract the gossip-only heal breaks.
    assert all(d["violations"] == 0 for name, d in v["codes"].items()
               if name != "POST_HEAL_DIVERGENCE")
    # First-trip evidence lanes carry the divergent cells.
    lanes = [x for x in cm.decode_violations(mon)
             if x.code == cm.InvariantCode.POST_HEAL_DIVERGENCE]
    assert lanes and all(x.round == agree_from for x in lanes)
    assert all(0 <= x.observer < n and 0 <= x.subject < n for x in lanes)


@pytest.mark.sync
def test_post_heal_divergence_green_with_sync_plane():
    """Same schedule with the plane on: build() arms the agreement
    promise itself and the monitored run is green — the bounded
    re-convergence contract holds."""
    from scalecube_cluster_tpu.chaos import monitor as cm

    n = 16
    params, world, spec, scen = _heal_scenario(n, sync_interval=8)
    assert int(spec.agree_from) < scen.horizon    # promise armed
    _, mon, _ = cm.run_monitored(jax.random.key(0), params, world, spec,
                                 scen.horizon)
    v = cm.verdict(mon)
    assert v["green"], v["codes"]


@pytest.mark.sync
def test_agreement_promise_needs_quiesced_heal():
    """A split shorter than quiesce_bound releases hot tombstones into
    the heal — a regime the merge precedence cannot bound — so build()
    must NOT promise agreement for it even with the plane on."""
    from scalecube_cluster_tpu.chaos import scenarios as cs

    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", sync_every=0,
        sync_interval=8,
    )
    short = cs.quiesce_bound(params, n) // 2
    short -= short % 16
    scen = cs.Scenario(
        name="mid-flight-heal", n_members=n, horizon=256,
        ops=(cs.RollingPartition(from_round=0, phase_rounds=max(short, 16),
                                 n_cycles=1),),
    )
    _, spec = scen.build(params)
    assert int(spec.agree_from) == np.iinfo(np.int32).max
    # Background loss also voids the promise (transient false suspicions
    # legitimately break agreement at any time).
    lossy = dataclasses.replace(scen, loss_probability=0.05)
    _, spec = lossy.build(params)
    assert int(spec.agree_from) == np.iinfo(np.int32).max


@pytest.mark.sync
def test_agreement_window_accounts_for_crash_maturation():
    """A permanent crash's suspicion timers mature INSIDE any naive
    fault-round + dissemination window: the agreement deadline must sit
    past detection + suspicion + tombstone spread (quiesce_bound), or a
    legitimate run trips POST_HEAL_DIVERGENCE while observers hold the
    mid-maturation ALIVE/SUSPECT/DEAD mixture."""
    from scalecube_cluster_tpu.chaos import monitor as cm
    from scalecube_cluster_tpu.chaos import scenarios as cs

    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", sync_every=0,
        sync_interval=8,
    )
    crash_at = 8
    horizon = (crash_at + cs.quiesce_bound(params, n)
               + cs.post_heal_agreement_bound(params, n) + 32)
    scen = cs.Scenario(name="crash-agree", n_members=n, horizon=horizon,
                       ops=(cs.Crash(3, at_round=crash_at),))
    world, spec = scen.build(params)
    agree_from = int(spec.agree_from)
    # Armed (plane on, pristine, permanent crash quiesces) and past the
    # maturation window.
    assert agree_from < horizon
    assert agree_from >= crash_at + params.suspicion_rounds
    _, mon, _ = cm.run_monitored(jax.random.key(0), params, world, spec,
                                 horizon)
    v = cm.verdict(mon)
    assert v["green"], v["codes"]
