"""The SWIM paper's headline curves, asserted (the BASELINE north star:
"reproduce the paper's O(log n) dissemination and first-false-positive
curves"; ClusterMath as the analytic anchor).

tests/test_gossip_model.py pins per-n values against ClusterMath; this
suite pins the *shape across n*: dissemination grows log-linearly in
cluster size (infection-style spread, README.md:10-12), with small
residuals, and first-false-positive timing scales with the loss rate.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu import swim_math
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import fd as fdmodel
from scalecube_cluster_tpu.models import gossip as gmodel
from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config

NS = [64, 256, 1024, 4096, 16384]
SEEDS = 8
GOSSIPS = 4


@pytest.fixture(scope="module")
def dissemination_samples():
    """All per-gossip dissemination rounds at each n: 8 seeds x 4 gossips
    = 32 instances per cluster size (O(N*G) state, so n=16384 is cheap)."""
    cfg = ClusterConfig.default()
    out = {}
    for n in NS:
        rounds = []
        for seed in range(SEEDS):
            p = gmodel.GossipSimParams.from_config(
                cfg, n_members=n, n_gossips=GOSSIPS
            )
            _, m = gmodel.run(jax.random.key(seed), p, 100)
            r = np.asarray(gmodel.dissemination_rounds(m, n))
            rounds.extend(r[r > 0].tolist())
        assert len(rounds) == SEEDS * GOSSIPS, (
            f"not every gossip disseminated at n={n}"
        )
        out[n] = np.asarray(rounds, dtype=np.float64)
    return out


def test_dissemination_is_log_linear_in_n(dissemination_samples):
    """MEAN dissemination rounds fit a + b*log2(n) within the BASELINE 5%
    target (measured 1.05% max residual over n in {64..16384}; pinned at
    3% as the regression band — a mean moving ~0.15 rounds breaks it).

    Round 3 reported 5.3% residuals and missed the 5% target — that was
    the *integer median* statistic's quantization floor, not protocol
    drift: medians of integer round counts can only take integer values,
    and no line passes within 5% of those integers
    (test_median_dissemination_is_quantization_limited proves it).  The
    mean over 32 gossip instances has ~1/32-round resolution and lands
    the same protocol behavior at 1% residuals."""
    means = np.asarray([dissemination_samples[n].mean() for n in NS])
    x = np.log2(np.asarray(NS, dtype=np.float64))
    b, a = np.polyfit(x, means, 1)
    fit = a + b * x
    rel_resid = np.abs(means - fit) / fit
    assert rel_resid.max() <= 0.03, (means.tolist(), fit.tolist())
    # Epidemic growth with fanout 3 multiplies the infected set by ~4 per
    # round (slope 1/log2(4) = 0.5) plus a straggler tail; measured slope
    # lands between those regimes.
    assert 0.4 <= b <= 1.2, b
    # Shape sanity: strictly increasing with n, and every point within the
    # analytic spread window (ClusterMath.java:111-113).
    assert np.all(np.diff(means) > 0)
    for n, mean in zip(NS, means):
        assert mean <= swim_math.gossip_periods_to_spread(3, n), (n, mean)


def test_median_dissemination_is_quantization_limited(dissemination_samples):
    """The round-3 5.3% residual was the integer-median statistic, not the
    protocol — the "prove the quantization floor" arm of verdict item 7:

      1. the medians are the mean-fit line QUANTIZED to integers —
         their deviation from log-linearity is rounding, so each median
         sits within one quantization step (±1 round) of the rounded
         fit.  Exact equality was a knife-edge: when the fit passes
         near a half-integer at one N (e.g. 8.5 between the 8 the
         median sampled and the 9 the fit rounds to), which side the
         integer median lands on is sampling noise INSIDE the
         quantization floor the test is about — so the pin is the
         quantization scale, not the coin flip;
      2. the LS fit of those integers carries a ~5% max residual (the
         rounding scale, half a round over ~7 rounds) while the means of
         the same runs fit within ~1%.

    (A Chebyshev min-max line can reach ~4.4% on the integers, so the
    honest statement is about the rounding identity + the LS procedure
    round 3 used, not "no line exists within 5%".)"""
    meds = np.asarray([np.median(dissemination_samples[n]) for n in NS])
    means = np.asarray([dissemination_samples[n].mean() for n in NS])
    assert np.all(meds == np.round(meds)), "medians of 32 samples: integers"
    x = np.log2(np.asarray(NS, dtype=np.float64))

    # (1) the medians track the ideal (mean-fit) curve to within the
    # integer-quantization step.
    b, a = np.polyfit(x, means, 1)
    assert np.all(np.abs(np.round(a + b * x) - meds) <= 1), (
        np.round(a + b * x).tolist(), meds.tolist())

    # (2) the LS fit of the integers is stuck at the rounding scale,
    # well above what the means achieve on the same runs.
    bm, am = np.polyfit(x, meds, 1)
    med_resid = (np.abs(meds - (am + bm * x)) / (am + bm * x)).max()
    mean_resid = (np.abs(means - (a + b * x)) / (a + b * x)).max()
    assert med_resid > 0.04, med_resid
    assert mean_resid < 0.03, mean_resid
    assert med_resid > 2.5 * mean_resid


def test_convergence_probability_matches_cluster_math():
    """ClusterMath.gossipConvergenceProbability (ClusterMath.java:38-43)
    vs the measured fraction of gossips reaching all N before sweep,
    G=2048 gossips per {fanout, loss} grid point (the BASELINE 5% target,
    enforced).

    Two regimes, asserted separately:
      - the reference's own experiment envelope (fanout >= 2, loss <= 50%,
        GossipProtocolTest.java:50-66): prediction and measurement must
        agree TWO-SIDED within 5 pp;
      - stress points outside it (fanout 1 at heavy loss, where the
        prediction drops below 1): the formula is the SWIM paper's
        asymptotic for lambda = repeatMult transmission rounds, while the
        protocol actually retransmits for repeatMult*ceilLog2(n) periods
        (ClusterMath.java:111-113) — so in-protocol convergence may only
        EXCEED it.  Asserted as a floor: measured >= predicted - 5 pp.
    """
    cfg0 = ClusterConfig.default()
    n, g = 64, 2048
    m = cfg0.gossip_repeat_mult

    def measured(fanout, loss, seed=0):
        cfg = cfg0.replace(gossip_fanout=fanout)
        p = gmodel.GossipSimParams.from_config(
            cfg, n_members=n, n_gossips=g, loss_probability=loss
        )
        horizon = swim_math.gossip_periods_to_sweep(m, n)
        _, met = gmodel.run(jax.random.key(seed), p, horizon)
        return float((np.asarray(met["infected_count"])[-1] == n).mean())

    # Reference envelope: two-sided 5 pp.
    for fanout in (2, 3):
        for loss in (0.0, 0.25, 0.5):
            pred = swim_math.gossip_convergence_probability(fanout, m, n, loss)
            meas = measured(fanout, loss)
            assert abs(meas - pred) <= 0.05, (fanout, loss, meas, pred)

    # Stress points: conservative-floor property.
    for fanout, loss in ((1, 0.0), (1, 0.25), (1, 0.5)):
        pred = swim_math.gossip_convergence_probability(fanout, m, n, loss)
        meas = measured(fanout, loss)
        assert meas >= pred - 0.05, (fanout, loss, meas, pred)


def measured_false_onsets(n, loss, ping_req, rounds, seeds, delivery="shift"):
    """Total false-suspicion onsets over ``seeds`` FD-only runs.

    The measurement setup fd_expected_false_onsets models: warm full
    view, everyone live, every round an fd round, suspicion horizon
    pushed past the run so entries never mature to DEAD and nothing
    refutes (gossip/SYNC off via fd_only_knobs).
    """
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, loss_probability=loss,
        ping_req_members=ping_req, delivery=delivery,
        per_subject_metrics=False,
    )
    world = swim.SwimWorld.healthy(params)
    knobs = dataclasses.replace(
        fdmodel.fd_only_knobs(params),
        ping_every=jnp.int32(1),
        suspicion_rounds=jnp.int32(1_000_000),
    )
    total = 0
    for seed in range(seeds):
        _, m = swim.run(jax.random.key(seed), params, world, rounds,
                        knobs=knobs)
        total += int(np.asarray(m["false_suspicion_onsets"]).sum())
    return total


def test_first_fp_rate_matches_closed_form():
    """Measured false-suspicion onset counts vs the closed-form probe
    model (swim_math.fd_false_suspect_probability) — the quantitative
    first-false-positive validation BASELINE.md's north star asks for
    (the reference's methodology: measure, then compare against
    ClusterMath — GossipProtocolTest.java:178-205 — which had no FD
    analog until swim_math's extension).

    Band: 5% relative plus a 3.5-sigma Poisson allowance 3.5/sqrt(E)
    (onsets are rare ~independent events; for the sparse cells the
    statistical noise of the run itself exceeds 5%, so a bare 5% band
    would test the seed, not the model).  The TPU-scale sweep
    (experiments/fp_curve.py, n=10k, 12 cells) drives every cell's E
    high enough that the Poisson term is <=2.6%; here the CPU-sized
    grid covers both delivery modes and the ping_req scaling.
    """
    n, rounds = 512, 400
    cells = [
        # (loss, ping_req, seeds, delivery)
        (0.10, 0, 1, "shift"),
        (0.10, 3, 4, "shift"),
        (0.25, 1, 1, "shift"),
        (0.25, 3, 1, "shift"),
        (0.10, 3, 2, "scatter"),
    ]
    for loss, pr, seeds, delivery in cells:
        expected = seeds * swim_math.fd_expected_false_onsets(
            loss, pr, n, rounds
        )
        measured = measured_false_onsets(n, loss, pr, rounds, seeds,
                                         delivery)
        band = 0.05 + 3.5 / np.sqrt(expected)
        assert abs(measured / expected - 1.0) <= band, (
            f"loss={loss} ping_req={pr} {delivery}: measured {measured} "
            f"vs expected {expected:.0f} (band {band:.3f})"
        )


def test_first_false_positive_scales_with_loss():
    """Higher symmetric loss -> earlier first false suspicion; lossless ->
    none (the first-false-positive curve's monotone backbone)."""
    n = 32

    def first_fp(loss, seed):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, loss_probability=loss,
            delivery="scatter",
        )
        world = swim.SwimWorld.healthy(params)
        _, m = swim.run(jax.random.key(seed), params, world, 150)
        fp = np.asarray(m["false_positives"]).sum(axis=1)
        idx = np.flatnonzero(fp > 0)
        return float(idx[0]) if idx.size else float("inf")

    assert first_fp(0.0, 0) == float("inf")
    med_10 = np.median([first_fp(0.10, s) for s in range(4)])
    med_30 = np.median([first_fp(0.30, s) for s in range(4)])
    assert np.isfinite(med_30), "30% loss never produced a false suspicion"
    assert med_30 <= med_10, (med_30, med_10)
