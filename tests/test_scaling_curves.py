"""The SWIM paper's headline curves, asserted (the BASELINE north star:
"reproduce the paper's O(log n) dissemination and first-false-positive
curves"; ClusterMath as the analytic anchor).

tests/test_gossip_model.py pins per-n values against ClusterMath; this
suite pins the *shape across n*: dissemination grows log-linearly in
cluster size (infection-style spread, README.md:10-12), with small
residuals, and first-false-positive timing scales with the loss rate.
"""

import jax
import numpy as np

from scalecube_cluster_tpu import swim_math
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import gossip as gmodel
from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config

NS = [64, 256, 1024, 4096]


def median_dissemination(n, seeds=3):
    cfg = ClusterConfig.default()
    rounds = []
    for seed in range(seeds):
        p = gmodel.GossipSimParams.from_config(cfg, n_members=n, n_gossips=4)
        _, m = gmodel.run(jax.random.key(seed), p, 80)
        r = np.asarray(gmodel.dissemination_rounds(m, n))
        rounds.extend(r[r > 0].tolist())
    assert rounds, f"no gossip fully disseminated at n={n}"
    return float(np.median(rounds))


def test_dissemination_is_log_linear_in_n():
    """Median dissemination rounds fit a + b*log2(n) with <=7% residuals
    and a slope consistent with fanout-3 epidemic growth.

    The 7% band is a REGRESSION PIN on the measured values, not a derived
    bound: residuals are 5.3% today (stable from 3 to 8 seeds — the
    integer round medians 4/6/7/9 don't move), and a single median
    shifting by one round (the quantization grain) would exceed the band
    by design — such a shift is exactly the protocol-behavior change this
    test exists to surface; re-justify the band from fresh medians if one
    ever does."""
    meds = np.asarray([median_dissemination(n) for n in NS])
    x = np.log2(np.asarray(NS, dtype=np.float64))
    b, a = np.polyfit(x, meds, 1)
    fit = a + b * x
    rel_resid = np.abs(meds - fit) / fit
    assert rel_resid.max() <= 0.07, (meds.tolist(), fit.tolist())
    # Epidemic growth with fanout 3 multiplies the infected set by ~4 per
    # round (slope 1/log2(4) = 0.5) plus a straggler tail; measured slope
    # lands between those regimes.
    assert 0.4 <= b <= 1.2, b
    # Shape sanity: strictly increasing with n, and every point within the
    # analytic spread window (ClusterMath.java:111-113).
    assert np.all(np.diff(meds) > 0)
    for n, med in zip(NS, meds):
        assert med <= swim_math.gossip_periods_to_spread(3, n), (n, med)


def test_convergence_probability_matches_cluster_math():
    """ClusterMath.gossipConvergenceProbability (ClusterMath.java:38-43)
    vs the measured fraction of gossips reaching all N before sweep,
    G=2048 gossips per {fanout, loss} grid point (the BASELINE 5% target,
    enforced).

    Two regimes, asserted separately:
      - the reference's own experiment envelope (fanout >= 2, loss <= 50%,
        GossipProtocolTest.java:50-66): prediction and measurement must
        agree TWO-SIDED within 5 pp;
      - stress points outside it (fanout 1 at heavy loss, where the
        prediction drops below 1): the formula is the SWIM paper's
        asymptotic for lambda = repeatMult transmission rounds, while the
        protocol actually retransmits for repeatMult*ceilLog2(n) periods
        (ClusterMath.java:111-113) — so in-protocol convergence may only
        EXCEED it.  Asserted as a floor: measured >= predicted - 5 pp.
    """
    cfg0 = ClusterConfig.default()
    n, g = 64, 2048
    m = cfg0.gossip_repeat_mult

    def measured(fanout, loss, seed=0):
        cfg = cfg0.replace(gossip_fanout=fanout)
        p = gmodel.GossipSimParams.from_config(
            cfg, n_members=n, n_gossips=g, loss_probability=loss
        )
        horizon = swim_math.gossip_periods_to_sweep(m, n)
        _, met = gmodel.run(jax.random.key(seed), p, horizon)
        return float((np.asarray(met["infected_count"])[-1] == n).mean())

    # Reference envelope: two-sided 5 pp.
    for fanout in (2, 3):
        for loss in (0.0, 0.25, 0.5):
            pred = swim_math.gossip_convergence_probability(fanout, m, n, loss)
            meas = measured(fanout, loss)
            assert abs(meas - pred) <= 0.05, (fanout, loss, meas, pred)

    # Stress points: conservative-floor property.
    for fanout, loss in ((1, 0.0), (1, 0.25), (1, 0.5)):
        pred = swim_math.gossip_convergence_probability(fanout, m, n, loss)
        meas = measured(fanout, loss)
        assert meas >= pred - 0.05, (fanout, loss, meas, pred)


def test_first_false_positive_scales_with_loss():
    """Higher symmetric loss -> earlier first false suspicion; lossless ->
    none (the first-false-positive curve's monotone backbone)."""
    n = 32

    def first_fp(loss, seed):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, loss_probability=loss,
            delivery="scatter",
        )
        world = swim.SwimWorld.healthy(params)
        _, m = swim.run(jax.random.key(seed), params, world, 150)
        fp = np.asarray(m["false_positives"]).sum(axis=1)
        idx = np.flatnonzero(fp > 0)
        return float(idx[0]) if idx.size else float("inf")

    assert first_fp(0.0, 0) == float("inf")
    med_10 = np.median([first_fp(0.10, s) for s in range(4)])
    med_30 = np.median([first_fp(0.30, s) for s in range(4)])
    assert np.isfinite(med_30), "30% loss never produced a false suspicion"
    assert med_30 <= med_10, (med_30, med_10)
