"""Failure-detector tests, ported from the reference's FailureDetectorTest.java
(cluster/src/test/java/io/scalecube/cluster/fdetector/, 515 LoC).

Uses the reference's harness trick: FDs built directly on transports with the
membership feed stubbed as pre-seeded member lists
(FailureDetectorTest.java:414-428), so the component is tested in isolation.
"""

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.oracle import (
    CorrelationIdGenerator,
    FailureDetector,
    Member,
    Simulator,
    Transport,
)
from scalecube_cluster_tpu.oracle.membership import MembershipEvent
from scalecube_cluster_tpu.records import MemberStatus


def make_fd_cluster(sim, n, config=None):
    """n transports + FDs, everyone fed everyone's membership (stubbed)."""
    config = config or ClusterConfig.default_local()
    transports = [Transport(sim) for _ in range(n)]
    members = [Member(f"m{i}", t.address) for i, t in enumerate(transports)]
    fds = []
    for i in range(n):
        fd = FailureDetector(
            members[i], transports[i], config, sim, CorrelationIdGenerator(f"m{i}")
        )
        for j in range(n):
            if j != i:
                fd.on_member_event(MembershipEvent.added(members[j], None))
        fds.append(fd)
    return transports, members, fds


def last_verdicts(fd, events):
    """Latest status per member id from a recorded event list."""
    out = {}
    for e in events:
        out[e.member.id] = e.status
    return out


def record(fd):
    events = []
    fd.listen(events.append)
    return events


def test_all_trusted():
    """FailureDetectorTest.testTrusted-shaped:80-115 — clean network => ALIVE."""
    sim = Simulator(seed=1)
    _, members, fds = make_fd_cluster(sim, 3)
    logs = [record(fd) for fd in fds]
    for fd in fds:
        fd.start()
    sim.run_for(5_000)
    for log in logs:
        assert log, "expected verdicts"
        assert all(e.status == MemberStatus.ALIVE for e in log)


def test_blocked_member_suspected():
    """Full block of one member => SUSPECT verdicts (FailureDetectorTest:80-115)."""
    sim = Simulator(seed=2)
    transports, members, fds = make_fd_cluster(sim, 3)
    log0 = record(fds[0])
    # Block all traffic to/from m2.
    for i in (0, 1):
        transports[i].network_emulator.block(members[2].address)
    transports[2].network_emulator.block(members[0].address, members[1].address)
    for fd in fds:
        fd.start()
    sim.run_for(10_000)
    verdicts = last_verdicts(fds[0], log0)
    assert verdicts["m2"] == MemberStatus.SUSPECT
    assert verdicts["m1"] == MemberStatus.ALIVE


def test_ping_req_rescues_asymmetric_link():
    """One bad direct link but healthy proxies => stays ALIVE via PING_REQ
    (FailureDetectorTest.java:117-147)."""
    sim = Simulator(seed=3)
    transports, members, fds = make_fd_cluster(sim, 4)
    log0 = record(fds[0])
    # Only the m0->m1 direct link is dead (both directions for determinism);
    # m0's probes of m1 must succeed through proxies m2/m3.
    transports[0].network_emulator.block(members[1].address)
    transports[1].network_emulator.block(members[0].address)
    for fd in fds:
        fd.start()
    sim.run_for(20_000)
    verdicts = last_verdicts(fds[0], log0)
    assert verdicts["m1"] == MemberStatus.ALIVE


def test_no_ping_req_members_fails_fast():
    """2-node cluster, link dead, no proxies available => SUSPECT
    (FailureDetectorTest two-member scenarios)."""
    sim = Simulator(seed=4)
    transports, members, fds = make_fd_cluster(sim, 2)
    log0 = record(fds[0])
    transports[0].network_emulator.block(members[1].address)
    for fd in fds:
        fd.start()
    sim.run_for(5_000)
    assert last_verdicts(fds[0], log0)["m1"] == MemberStatus.SUSPECT


def test_recovery_after_unblock():
    """Partition then heal => SUSPECT flips back to ALIVE
    (FailureDetectorTest partition/recovery scenarios:180-300)."""
    sim = Simulator(seed=5)
    transports, members, fds = make_fd_cluster(sim, 3)
    log0 = record(fds[0])
    for i in (0, 1):
        transports[i].network_emulator.block(members[2].address)
    transports[2].network_emulator.block(members[0].address, members[1].address)
    for fd in fds:
        fd.start()
    sim.run_for(10_000)
    assert last_verdicts(fds[0], log0)["m2"] == MemberStatus.SUSPECT
    for t in transports:
        t.network_emulator.unblock_all()
    sim.run_for(10_000)
    assert last_verdicts(fds[0], log0)["m2"] == MemberStatus.ALIVE


def test_multi_proxy_rescue_publishes_no_false_suspect():
    """With k>=2 proxies sharing the original ping's cid, ALL their pending
    request-responses must resolve on the first relayed ack (shared
    inbound-stream matching, TransportImpl.java:205-232) — no phantom
    SUSPECT verdicts for a healthy member."""
    sim = Simulator(seed=7)
    config = ClusterConfig.default_local().replace(ping_req_members=3)
    transports, members, fds = make_fd_cluster(sim, 5, config)
    log0 = record(fds[0])
    transports[0].network_emulator.block(members[1].address)
    transports[1].network_emulator.block(members[0].address)
    for fd in fds:
        fd.start()
    sim.run_for(30_000)
    m1_verdicts = [e.status for e in log0 if e.member.id == "m1"]
    assert m1_verdicts, "expected m1 to be probed"
    assert all(v == MemberStatus.ALIVE for v in m1_verdicts), m1_verdicts


def test_transit_ack_round_trip_uses_three_hops():
    """The PING_REQ path is really 3-hop: issuer->proxy->target->proxy->issuer
    (FailureDetectorImpl.java:258-315).  Verified by blocking the direct
    target->issuer return path too: the rescue must still work because the
    ack travels through the proxy."""
    sim = Simulator(seed=6)
    transports, members, fds = make_fd_cluster(sim, 3)
    log0 = record(fds[0])
    transports[0].network_emulator.block(members[1].address)  # no direct ping
    transports[1].network_emulator.block(members[0].address)  # no direct ack either
    for fd in fds:
        fd.start()
    sim.run_for(10_000)
    assert last_verdicts(fds[0], log0)["m1"] == MemberStatus.ALIVE
