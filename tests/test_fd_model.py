"""FD-only model (models/fd.py): BASELINE config 3 in miniature.

"10k-member FailureDetectorImpl ping/ping-req under 5% packet loss" —
here at reduced N for CI, with the defining property pinned: with gossip
and SYNC silenced, verdicts are LOCAL (no dissemination between
observers), exactly like the reference FD with membership stubbed
(FailureDetectorTest.java:414-428).
"""

import dataclasses

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import fd, swim

from tests.test_swim_model import make


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_probes_detect_crash_without_dissemination(delivery):
    """Observers suspect the crashed node only via their OWN probes: the
    suspect count grows by at most ~the per-round probe coverage, never
    jumping epidemic-style, and no DEAD view ever disseminates (verdicts
    stay local)."""
    n = 32
    params, world = make(n, delivery=delivery)
    world = world.with_crash(0, at_round=0)
    _, m = fd.run(jax.random.key(0), params, world, 400)
    suspects = np.asarray(m["suspect"])[:, 0]
    deads = np.asarray(m["dead"])[:, 0]
    assert suspects.max() > 0, "no probe ever suspected the crashed node"
    # Without gossip, knowledge accumulates probe by probe; it must take
    # many rounds to reach half the observers (epidemic spread would do it
    # in ~3 rounds at n=32).
    half = np.flatnonzero(suspects + deads >= (n - 1) // 2)
    assert half.size == 0 or half[0] > 20
    # Gossip really is off: messages_gossip trace is all zero.
    assert np.asarray(m["messages_gossip"]).sum() == 0

def test_ping_req_rescues_under_loss():
    """Config-3 regime: 5% loss.  With 3 proxies the false-suspicion rate
    collapses versus direct-ping-only (the FD's signature guarantee,
    FailureDetectorTest.java:117-147).  Note: in FD ISOLATION a persistent
    false suspicion times out to a *local* DEAD — there is no refutation
    path without membership/gossip, matching the reference where ALIVE
    verdicts never override SUSPECT (MembershipProtocolImpl.java:379-391);
    so the assertion is about rates, not absolutes."""
    n = 64

    def fp_total(ping_req_members, seed):
        params, world = make(n, loss=0.05, delivery="shift",
                             ping_req_members=ping_req_members)
        _, m = fd.run(jax.random.key(seed), params, world, 300)
        return int(np.asarray(m["false_positives"]).sum())

    with_proxies = sum(fp_total(3, s) for s in range(3))
    without = sum(fp_total(0, s) for s in range(3))
    assert without > 0, "control produced no false suspicion at 5% loss"
    assert with_proxies < without / 5, (with_proxies, without)


def test_planted_suspicion_stays_local():
    """No channel leaks a record between observers — including the round-0
    SYNC edge (sync_every=0 sentinel): plant one SUSPECT entry, run, and
    no other live observer ever learns of it."""
    n = 16
    params, world = make(n, delivery="scatter")
    state = swim.initial_state(params, world)
    # Observer 1 suspects live node 0.
    status = np.asarray(state.status).copy()
    status[1, 0] = 1  # SUSPECT
    state = dataclasses.replace(
        state,
        status=jax.numpy.asarray(status),
        spread_until=state.spread_until.at[1, 0].set(10_000),  # hot forever
    )
    # ping_every=0 disables probing entirely (the <=0 sentinel; a huge
    # modulo value would still fire at round 0).
    kn = dataclasses.replace(
        fd.fd_only_knobs(params),
        ping_every=jax.numpy.int32(0),
        suspicion_rounds=jax.numpy.int32(2**30),
    )
    _, m = swim.run(jax.random.key(5), params, world, 30, state=state,
                    knobs=kn)
    suspects = np.asarray(m["suspect"])[:, 0]
    assert suspects.max() == 1, "planted suspicion leaked to another observer"
