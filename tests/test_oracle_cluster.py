"""Full-API e2e tests, ported from the reference's ClusterTest.java (402 LoC)
and the examples module (SURVEY.md §2.1 row 13): join semantics, user
messaging, gossip, metadata propagation, graceful shutdown, dead seeds."""

from scalecube_cluster_tpu.oracle import Address, Cluster, Message, Simulator

from tests.oracle_helpers import FAST, ids


def test_join_await_semantics():
    """Cluster.joinAwait-shaped: on_joined resolves after initial sync."""
    sim = Simulator(seed=1)
    alice = Cluster.join(sim, config=FAST, alias="alice")
    assert alice.on_joined.done  # seedless join completes immediately
    bob = Cluster.join(sim, seeds=[alice.address], config=FAST, alias="bob")
    assert not bob.on_joined.done
    sim.run_for(200)
    assert bob.on_joined.done
    assert ids(bob.other_members()) == ["alice"]


def test_user_messaging_filters_system_messages():
    """MessagingExample.java:15-48 + ClusterImpl.listen filter:202-205."""
    sim = Simulator(seed=2)
    alice = Cluster.join(sim, config=FAST, alias="alice")
    bob = Cluster.join(sim, seeds=[alice.address], config=FAST, alias="bob")
    sim.run_for(1_000)
    inbox = []
    alice.listen(lambda m: inbox.append(m))
    bob.send(alice.member(), Message(qualifier="greeting", data="hi alice"))
    sim.run_for(5_000)  # plenty of protocol chatter in between
    assert [m.data for m in inbox] == ["hi alice"]  # no system messages leaked


def test_request_response_between_members():
    sim = Simulator(seed=3)
    alice = Cluster.join(sim, config=FAST, alias="alice")
    bob = Cluster.join(sim, seeds=[alice.address], config=FAST, alias="bob")
    sim.run_for(1_000)
    alice.listen(
        lambda m: alice.send(
            m.sender, Message(qualifier="pong", correlation_id=m.correlation_id, data=m.data + 1)
        )
        if m.qualifier == "ping-user"
        else None
    )
    results = []
    bob.request_response(
        alice.member(), Message(qualifier="ping-user", correlation_id="u-1", data=41)
    ).subscribe(results.append)
    sim.run_for(100)
    assert len(results) == 1 and results[0].data == 42


def test_user_gossip_delivered_once_to_everyone():
    """GossipExample.java:15-37."""
    sim = Simulator(seed=4)
    alice = Cluster.join(sim, config=FAST, alias="alice")
    others = [
        Cluster.join(sim, seeds=[alice.address], config=FAST, alias=f"n{i}") for i in range(5)
    ]
    sim.run_for(2_000)
    received = {c.member().id: [] for c in others}
    for c in others:
        c.listen_gossips(lambda m, c=c: received[c.member().id].append(m))
    alice.spread_gossip(Message(qualifier="user/news", data="breaking"))
    sim.run_for(10_000)
    for member_id, msgs in received.items():
        assert [m.data for m in msgs] == ["breaking"], member_id


def test_metadata_update_propagates_via_incarnation_bump():
    """ClusterMetadataExample.java:21-57 + ClusterTest metadata tests:107-303."""
    sim = Simulator(seed=5)
    alice = Cluster.join(sim, config=FAST, alias="alice", metadata={"role": "seed"})
    bob = Cluster.join(
        sim, seeds=[alice.address], config=FAST, alias="bob", metadata={"role": "worker"}
    )
    sim.run_for(2_000)
    assert alice.metadata(bob.member()) == {"role": "worker"}
    assert bob.metadata(alice.member()) == {"role": "seed"}

    updates = []
    bob.membership.listen(lambda e: updates.append(e) if e.is_updated() else None)
    alice.update_metadata({"role": "seed", "version": "2"})
    sim.run_for(5_000)
    assert bob.metadata(alice.member()) == {"role": "seed", "version": "2"}
    assert updates and updates[-1].new_metadata == {"role": "seed", "version": "2"}
    assert updates[-1].old_metadata == {"role": "seed"}


def test_update_metadata_property():
    sim = Simulator(seed=6)
    alice = Cluster.join(sim, config=FAST, alias="alice", metadata={"a": "1"})
    bob = Cluster.join(sim, seeds=[alice.address], config=FAST, alias="bob")
    sim.run_for(2_000)
    alice.update_metadata_property("b", "2")
    sim.run_for(5_000)
    assert bob.metadata(alice.member()) == {"a": "1", "b": "2"}
    alice.remove_metadata_property("a")
    sim.run_for(5_000)
    assert bob.metadata(alice.member()) == {"b": "2"}


def test_graceful_shutdown_removes_metadata():
    """ClusterTest.testMemberMetadataRemoved:331-373."""
    sim = Simulator(seed=7)
    alice = Cluster.join(sim, config=FAST, alias="alice")
    bob = Cluster.join(
        sim, seeds=[alice.address], config=FAST, alias="bob", metadata={"k": "v"}
    )
    sim.run_for(2_000)
    assert alice.metadata(bob.member()) == {"k": "v"}
    removed = []
    alice.membership.listen(lambda e: removed.append(e) if e.is_removed() else None)
    bob.shutdown()
    sim.run_for(5_000)
    assert bob.is_shutdown
    assert removed and removed[0].member.id == "bob"
    assert removed[0].old_metadata == {"k": "v"}  # REMOVED carries last metadata
    assert alice.metadata(bob.member()) is None


def test_join_via_dead_seed_then_alive_seed():
    """ClusterTest.testJoinDeadSeedMembers:375-388."""
    sim = Simulator(seed=8)
    alice = Cluster.join(sim, config=FAST, alias="alice")
    dead = Address("localhost", 1)  # nothing bound
    bob = Cluster.join(sim, seeds=[dead, alice.address], config=FAST, alias="bob")
    sim.run_for(5_000)
    assert ids(bob.other_members()) == ["alice"]


def test_join_via_all_dead_seeds_starts_alone():
    """Join succeeds (alone) even when every seed is dead; periodic sync
    keeps retrying them (MembershipProtocolImpl.java:298-314)."""
    sim = Simulator(seed=9)
    bob = Cluster.join(sim, seeds=[Address("localhost", 1)], config=FAST, alias="bob")
    sim.run_for(5_000)
    assert bob.on_joined.done
    assert bob.other_members() == []
    # The seed comes up later; periodic sync finds it.
    alice = Cluster.join(sim, config=FAST.replace(port=1), alias="alice")
    sim.run_for(10_000)
    assert ids(bob.other_members()) == ["alice"]


def test_listen_membership_prepends_existing_members():
    """ClusterImpl.listenMembership:283-293."""
    sim = Simulator(seed=10)
    alice = Cluster.join(sim, config=FAST, alias="alice")
    bob = Cluster.join(sim, seeds=[alice.address], config=FAST, alias="bob")
    sim.run_for(2_000)
    events = []
    alice.listen_membership(events.append)
    assert [(e.type.value, e.member.id) for e in events] == [("added", "bob")]


def test_member_host_override():
    """TransportConfig.memberHost/memberPort: a member advertises a
    different address than its bind address, and peers reach it there
    (MembershipProtocolTest.java:464-535)."""
    sim = Simulator(seed=21)
    alice = Cluster.join(sim, alias="alice", config=FAST)
    override = FAST.replace(member_host="10.1.2.3", member_port=7777)
    bob = Cluster.join(sim, seeds=[alice.address], config=override,
                       alias="bob")
    sim.run_for(3_000)

    assert str(bob.member().address) == "10.1.2.3:7777"
    seen = {m.id: str(m.address) for m in alice.other_members()}
    assert seen == {"bob": "10.1.2.3:7777"}

    # Messaging to the advertised address reaches bob's transport.
    got = []
    bob.listen(lambda m: got.append(m.data))
    alice.send(bob.member(), Message(qualifier="hi", data="via-override"))
    sim.run_for(500)
    assert got == ["via-override"]
