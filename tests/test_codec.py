"""MessageCodec round-trips (oracle/codec.py).

The analog of the reference's serialization tests
(gossip/GossipRequestTest.java:40-69: Jackson round-trip of nested
polymorphic GossipRequest) for every payload type in the 9-qualifier wire
protocol (SURVEY.md §2.1), plus the failure mode: an unserializable
payload must fail the send, like a codec error on a real wire.
"""

from scalecube_cluster_tpu.oracle import (
    Address, Cluster, Member, Message, Simulator, Transport,
)
from scalecube_cluster_tpu.oracle.codec import CodecError, JsonMessageCodec
from scalecube_cluster_tpu.oracle.fdetector import PingData
from scalecube_cluster_tpu.oracle.gossip import Gossip, GossipRequest
from scalecube_cluster_tpu.oracle.membership import MembershipRecord, SyncData
from scalecube_cluster_tpu.oracle.metadata import (
    GetMetadataRequest, GetMetadataResponse,
)
from scalecube_cluster_tpu.records import MemberStatus

CODEC = JsonMessageCodec()
ALICE = Member(id="alice", address=Address("localhost", 4801))
BOB = Member(id="bob", address=Address("localhost", 4802))


def roundtrip(msg: Message) -> Message:
    return CODEC.deserialize(CODEC.serialize(msg))


def test_plain_user_message():
    msg = Message(qualifier="greeting", correlation_id="cid-1",
                  data={"text": "hello", "n": 3}, sender=ALICE.address)
    back = roundtrip(msg)
    assert back == msg


def test_ping_data_with_transit_issuer():
    msg = Message(qualifier="sc/fdetector/pingReq", correlation_id="c-9",
                  data=PingData(from_=ALICE, to=BOB, original_issuer=ALICE))
    back = roundtrip(msg)
    assert back.data.from_ == ALICE
    assert back.data.original_issuer == ALICE


def test_sync_data_full_table():
    table = (
        MembershipRecord(ALICE, MemberStatus.ALIVE, 0),
        MembershipRecord(BOB, MemberStatus.SUSPECT, 3),
    )
    msg = Message(qualifier="sc/membership/sync",
                  data=SyncData(membership=table, sync_group="default"))
    back = roundtrip(msg)
    assert back.data.membership == table
    assert back.data.membership[1].status is MemberStatus.SUSPECT


def test_nested_polymorphic_gossip_request():
    """The GossipRequestTest.java:40-69 case: gossips wrap whole Messages."""
    inner = Message(qualifier="news", data=["a", 1, None])
    req = GossipRequest(
        gossips=(Gossip(gossip_id="alice-0", message=inner),),
        from_id="alice",
    )
    back = roundtrip(Message(qualifier="sc/gossip/req", data=req))
    assert back.data.from_id == "alice"
    assert back.data.gossips[0].gossip_id == "alice-0"
    assert back.data.gossips[0].message.qualifier == "news"
    assert back.data.gossips[0].message.data == ["a", 1, None]


def test_metadata_request_response():
    req = roundtrip(Message(qualifier="sc/metadata/req",
                            data=GetMetadataRequest(BOB)))
    assert req.data.member == BOB
    resp = roundtrip(Message(
        qualifier="sc/metadata/resp",
        data=GetMetadataResponse(BOB, {"role": "worker"}),
    ))
    assert resp.data.metadata == {"role": "worker"}


def test_unserializable_payload_fails_the_send():
    class NotWire:
        pass

    sim = Simulator(seed=1)
    a = Transport(sim)
    b = Transport(sim)
    errors = []
    fut = a.send(b.address, Message(qualifier="x", data=NotWire()))
    fut.subscribe(None, errors.append)
    sim.run_for(100)
    assert errors and isinstance(errors[0], CodecError)


def test_cluster_wire_is_codec_backed():
    """End-to-end: a whole join + gossip cycle runs over serialized bytes
    (the Transport default codec), not live object hand-off."""
    sim = Simulator(seed=5)
    alice = Cluster.join(sim, alias="alice")
    assert alice.transport.codec is not None
    bob = Cluster.join(sim, seeds=[alice.address], alias="bob")
    sim.run_for(2_000)
    assert sorted(m.id for m in alice.other_members()) == ["bob"]
    assert sorted(m.id for m in bob.other_members()) == ["alice"]
