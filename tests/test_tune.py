"""Protocol autotuner (tune/search.py + tune/profiles.py): Pareto
logic, grid construction, the one-compile-per-shape-bucket witness,
and the shipped tuned-default profiles.

The contract under test (ISSUE 17 tentpole b):

  - ``dominates``/``pareto_front`` implement strict Pareto dominance
    over the SLO objectives (minimization; duplicates of a frontier
    point all stay on the frontier);
  - ``default_grid`` puts the reference default FIRST, never emits a
    duplicate config, and every override validates against the knob
    ceilings (``Knobs.for_params``) — a grid row that could not ship
    as dynamic knob data is a bug in the grid, not a runtime surprise;
  - ``sweep`` compiles ONCE per scenario shape bucket and NEVER per
    config: knob data is traced operands (the tentpole's perf claim —
    bench.py --tune records the same witness in the artifact);
  - every shipped profile resolves against any base params, ships as
    both static ``SwimParams.tuned(...)`` and dynamic
    ``profile_knobs`` data, strictly improves its target objective vs
    the reference default without being Pareto-dominated (@slow, the
    bench workload), and passes the held-out chaos fuzz oracle
    (@slow, a DIFFERENT held-out seed than the bench's).
"""

import dataclasses
import os

import numpy as np
import pytest

from scalecube_cluster_tpu.chaos import campaign as ccampaign
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.parallel import traffic
from scalecube_cluster_tpu.tune import profiles as tprofiles
from scalecube_cluster_tpu.tune import search as tsearch

pytestmark = pytest.mark.tune


def tune_base(n=16):
    base = swim.SwimParams.from_config(
        ccampaign.campaign_config(), n_members=n, delivery="shift")
    return dataclasses.replace(base, **tsearch.TUNE_PARAM_OVERRIDES)


# --------------------------------------------------------------------------
# Pareto logic on synthetic grids
# --------------------------------------------------------------------------


def _slo(x, y):
    return {"x": float(x), "y": float(y)}


def test_dominates_is_strict_and_asymmetric():
    objs = ("x", "y")
    assert tsearch.dominates(_slo(1, 1), _slo(2, 1), objs)
    assert not tsearch.dominates(_slo(2, 1), _slo(1, 1), objs)
    # equal rows dominate in neither direction
    assert not tsearch.dominates(_slo(1, 1), _slo(1, 1), objs)
    # trade-offs (better on one, worse on the other) never dominate
    assert not tsearch.dominates(_slo(1, 3), _slo(3, 1), objs)
    assert not tsearch.dominates(_slo(3, 1), _slo(1, 3), objs)


def test_pareto_front_on_synthetic_grid():
    objs = ("x", "y")
    rows = [_slo(1, 4), _slo(2, 2), _slo(4, 1), _slo(3, 3), _slo(2, 2)]
    # (3,3) is dominated by (2,2); the duplicate frontier point keeps
    # BOTH copies (stable order)
    assert tsearch.pareto_front(rows, objs) == [0, 1, 2, 4]
    # a single row is trivially non-dominated
    assert tsearch.pareto_front([_slo(9, 9)], objs) == [0]
    assert tsearch.pareto_front([], objs) == []


# --------------------------------------------------------------------------
# Grid construction
# --------------------------------------------------------------------------


def test_default_grid_reference_first_unique_and_valid():
    params = tune_base()
    for smoke in (False, True):
        grid = tsearch.default_grid(params, smoke=smoke)
        assert grid[0] == {"name": "reference", "overrides": {}}
        names = [c["name"] for c in grid]
        assert len(names) == len(set(names))
        keys = [tuple(sorted(c["overrides"].items())) for c in grid]
        assert len(keys) == len(set(keys))
        for cfg in grid[1:]:
            assert cfg["overrides"], cfg["name"]
            # every grid row must be shippable as dynamic knob data
            swim.Knobs.for_params(params, **cfg["overrides"])
    assert len(tsearch.default_grid(params, smoke=True)) < \
        len(tsearch.default_grid(params, smoke=False))


def test_grid_skips_axes_for_disabled_planes():
    """Arms for planes the params disable are skipped instead of
    shipping knobs the program would ignore."""
    params = dataclasses.replace(tune_base(), lhm_max=0,
                                 dead_suppress_rounds=0, sync_every=0)
    swept = {k for cfg in tsearch.default_grid(params)
             for k in cfg["overrides"]}
    assert not swept & {"lhm_max", "dead_suppress_rounds", "sync_every"}


def test_tune_scenarios_drop_join_storms():
    scens = tsearch.tune_scenarios(500, 12, n=16)
    assert scens and all(not s.has_joins for s in scens)


# --------------------------------------------------------------------------
# Profiles
# --------------------------------------------------------------------------


def test_profiles_resolve_and_ship_both_ways():
    params = tune_base()
    assert len(tprofiles.PROFILES) >= 2
    for name, prof in tprofiles.PROFILES.items():
        assert prof["target"] in tsearch.OBJECTIVES
        overrides = tprofiles.resolve(name, params)
        assert overrides  # a profile that changes nothing is no profile
        assert set(overrides) <= {f.name for f in
                                  dataclasses.fields(swim.SwimParams)}
        # static shipping: params constructor
        tuned = swim.SwimParams.tuned(name, base=params)
        for field, val in overrides.items():
            assert float(getattr(tuned, field)) == float(val), \
                (name, field)
        # dynamic shipping: validated knob data for the SAME program
        tprofiles.profile_knobs(name, params)


def test_tuned_params_constructor_defaults_and_overrides():
    tuned = swim.SwimParams.tuned("fast-detect")
    assert tuned.n_members == 32 and tuned.ping_every == 1
    small = swim.SwimParams.tuned("fast-detect", n_members=16)
    assert small.n_members == 16
    # explicit overrides win over the profile's resolved values
    pinned = swim.SwimParams.tuned("fast-detect", ping_every=3)
    assert pinned.ping_every == 3


def test_unknown_profile_raises():
    with pytest.raises(ValueError, match="unknown tuned profile"):
        tprofiles.resolve("warp-speed", tune_base())
    with pytest.raises(ValueError, match="unknown tuned profile"):
        swim.SwimParams.tuned("warp-speed")


# --------------------------------------------------------------------------
# Scoring plumbing
# --------------------------------------------------------------------------


def test_wire_bytes_total_prices_the_wire_format():
    params = tune_base()
    kb = traffic._key_bytes(params)
    k = params.n_subjects
    metrics = {"messages_gossip": np.array([2, 1]),
               "messages_ping_sent": np.array([5]),
               "messages_anti_entropy": np.array([3]),
               "messages_ping_recv": np.array([99])}  # recv: not wire-priced
    expect = 3 * k * kb + 5 * kb + 3 * 2 * k * kb
    assert tsearch.wire_bytes_total(params, metrics) == expect


def test_finalize_slos_empty_is_all_zero():
    slos = tsearch._finalize_slos([])
    assert set(tsearch.OBJECTIVES) < set(slos)
    assert all(slos[o] == 0.0 for o in tsearch.OBJECTIVES)
    assert slos["latency_samples"] == 0


# --------------------------------------------------------------------------
# The compiled sweep: one compile per shape bucket, zero per config
# --------------------------------------------------------------------------


def test_sweep_compiles_once_per_bucket_never_per_config():
    """THE tentpole witness: C configs over B shape buckets = B * C
    device calls but at most B fresh compiles, and a follow-up sweep
    with NEW knob settings adds zero — knob data is traced operands.
    (bench.py --tune records the same cache-size witness at the full
    grid in artifacts/tune_pareto.json.)"""
    scens = tsearch.tune_scenarios(321, 2, n=16)
    configs = [{"name": "reference", "overrides": {}},
               {"name": "pe1", "overrides": {"ping_every": 1}}]
    rows, info = tsearch.sweep(scens, configs=configs, seed=321,
                               capacity=96)
    assert info["shape_buckets"] >= 1
    assert info["calls"] == info["shape_buckets"] * len(configs)
    assert info["compiles"] <= info["shape_buckets"]
    for row, cfg in zip(rows, configs):
        assert row["name"] == cfg["name"]
        assert isinstance(row["green"], bool)
        assert set(tsearch.OBJECTIVES) < set(row["slos"])
    # new knob values, same buckets: the grid reruns warm programs
    _, again = tsearch.sweep(
        scens, configs=[{"name": "sus9",
                         "overrides": {"suspicion_rounds": 9,
                                       "ping_timeout_ms": 75.0}}],
        seed=321, capacity=96)
    assert again["compiles"] == 0


def test_sweep_rejects_out_of_ceiling_overrides():
    """A config outside the knob ceilings fails loudly at sweep time
    (Knobs.for_params), never as silent clamping."""
    scens = tsearch.tune_scenarios(321, 2, n=16)
    with pytest.raises(ValueError):
        tsearch.sweep(
            scens, configs=[{"name": "bad",
                             "overrides": {"loss_probability": 1.5}}],
            seed=321, capacity=96)


# --------------------------------------------------------------------------
# @slow: the bench-scale workload + held-out fuzz oracle
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_full_grid_profiles_beat_default_on_target():
    """The bench workload (env-scaled): every shipped profile is
    monitor-green, STRICTLY better than the reference default on its
    target objective, and not Pareto-dominated by it."""
    n = int(os.environ.get("SCALECUBE_TUNE_TEST_N", 32))
    n_scen = int(os.environ.get("SCALECUBE_TUNE_TEST_SCENARIOS", 12))
    seed = int(os.environ.get("SCALECUBE_TUNE_TEST_SEED", 500))
    scens = tsearch.tune_scenarios(seed, n_scen, n=n)
    rows, info = tsearch.sweep(scens, seed=seed, smoke=False)
    assert info["compiles"] <= info["shape_buckets"]
    ref = rows[0]
    assert ref["name"] == "reference" and ref["green"]
    by_name = {r["name"]: r for r in rows}
    for name, prof in tprofiles.PROFILES.items():
        row = by_name[name]
        target = prof["target"]
        assert row["green"], name
        assert row["slos"][target] < ref["slos"][target], \
            (name, target, row["slos"][target], ref["slos"][target])
        assert not tsearch.dominates(ref["slos"], row["slos"]), name
    # and the frontier over green rows contains every profile row
    green = [r for r in rows if r["green"]]
    front = {green[i]["name"]
             for i in tsearch.pareto_front([r["slos"] for r in green])}
    assert set(tprofiles.PROFILES) <= front


@pytest.mark.slow
def test_profiles_fuzz_green_on_fresh_held_out_seed():
    """The full fuzz oracle (completeness deadlines rebuilt under each
    profile's static schedule) stays green on a held-out seed DISTINCT
    from the bench's — profiles generalize past the seeds that
    selected them."""
    for name in sorted(tprofiles.PROFILES):
        out = tsearch.validate_profile(name, seed=9203, seeds_per_tier=1,
                                       n=16)
        assert out["green"], (name, out["violations_by_code"])
        assert out["green_scenarios"] == out["scenarios"]
