"""bench.py --lifeguard --smoke: the Lifeguard A/B JSON contract.

Like tests/test_bench_sync_smoke.py for the anti-entropy plane: the
bench is the one entry point the adaptivity measurement flows through,
so this tier-1 test runs the real script in a subprocess (CPU) and
pins the published contract — one JSON line with the A/B fields (the
plane's false-positive observer rate at most half the control's, crash
detection latency P99 within one round), an
artifacts/lifeguard_fp.json-style artifact the query layer loads as a
real payload, and the regress gate walking it with the absolute
lifeguard checks.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.lifeguard

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_lifeguard_bench(tmp_path, extra_env=None, timeout=540):
    artifact = tmp_path / "lifeguard_fp_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_LIFEGUARD_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--lifeguard", "--smoke"],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    return json.loads(lines[0]), artifact


def test_bench_lifeguard_smoke_contract(tmp_path):
    result, artifact = _run_lifeguard_bench(tmp_path)

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == "lifeguard_fp_observer_rate"
    # value stays None BY DESIGN (smaller-is-better ratio must not
    # enter the generic throughput walk); the payload says so.
    assert result["value"] is None
    assert "value_note" in result

    # The headline acceptance: the plane at least halves the
    # false-positive observer rate of its own control while keeping
    # crash-detection latency P99 within one round.
    assert result["false_positive_observer_rate_off"] > 0
    assert result["fp_ratio"] is not None
    assert result["fp_ratio"] <= 0.5
    assert (result["false_positive_observer_rate_on"]
            < result["false_positive_observer_rate_off"])
    assert result["detection_p99_delta_rounds"] <= 1.0

    # Workload provenance: the seeded scenario, its repro line, and
    # the plane's knobs.
    assert result["lhm_max"] > 0
    assert result["n_scenarios"] >= 1
    assert result["delivery"] == "scatter"
    assert result["live_observer_rounds"] > 0
    for row in result["scenarios"]:
        assert "asymmetric_degradation" in row["repro"]
        assert row["fp_onsets_off"] >= row["fp_onsets_on"]
        assert row["lhm_gauge"] is not None

    # The artifact round-trips and loads as a REAL (non-stub) payload.
    art = json.loads(artifact.read_text())
    assert art["metric"] == result["metric"]
    assert art["fp_ratio"] == result["fp_ratio"]

    from scalecube_cluster_tpu.telemetry import query as tquery

    payload, skip_note = tquery.load_bench_payload(str(artifact))
    assert skip_note is None
    assert payload["fp_ratio"] == result["fp_ratio"]

    # The in-bench regress gate ran and the dedicated absolute checks
    # are present and green for the fresh artifact.
    assert result["regress"]["ok"] is True
    assert result["regress"]["artifacts"] >= 1
    ok, rows = tquery.regress([str(artifact)])
    assert ok
    names = {r["check"] for r in rows}
    assert {"slo/lifeguard_fp_improvement",
            "slo/lifeguard_detection_parity"} <= names


def test_regress_fails_on_rotted_lifeguard_win(tmp_path):
    """An artifact recording a lost FP win (or a detection-latency
    cost) must fail the gate — the committed claim cannot silently
    rot."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    bad = tmp_path / "lifeguard_fp_bad.json"
    bad.write_text(json.dumps({
        "metric": "lifeguard_fp_observer_rate", "value": None,
        "fp_ratio": 0.8, "detection_p99_delta_rounds": 4.0,
        "false_positive_observer_rate_off": 0.1,
        "false_positive_observer_rate_on": 0.08,
    }))
    ok, rows = tquery.regress([str(bad)])
    assert not ok
    failed = {r["check"] for r in rows if r.get("ok") is False}
    assert "slo/lifeguard_fp_improvement" in failed
    assert "slo/lifeguard_detection_parity" in failed


def test_regress_smoke_artifacts_are_provenance_next_to_full(tmp_path):
    """A smoke lifeguard artifact sitting next to a full one is a
    provenance row; the full round carries the gates."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    def art(path, smoke, ratio):
        path.write_text(json.dumps({
            "metric": "lifeguard_fp_observer_rate", "value": None,
            "smoke": smoke, "fp_ratio": ratio,
            "detection_p99_delta_rounds": 0.0,
        }))
        return str(path)

    full = art(tmp_path / "lifeguard_fp.json", False, 0.3)
    smoke = art(tmp_path / "lifeguard_fp_smoke.json", True, 0.9)
    ok, rows = tquery.regress([full, smoke])
    assert ok                              # the bad smoke round skips
    notes = [r for r in rows if r.get("ok") is None
             and r["check"] == "slo/lifeguard_fp"]
    assert notes and "smoke" in notes[0]["note"]


@pytest.mark.slow
def test_bench_lifeguard_full_campaign(tmp_path):
    """The full (non-smoke) A/B campaign: every scenario seed's A/B
    pair through the real bench, the aggregate gates green."""
    artifact = tmp_path / "lifeguard_fp_full.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_LIFEGUARD_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--lifeguard"],
        capture_output=True, text=True, timeout=3000, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "error" not in result, result
    assert result["smoke"] is False
    assert result["n_scenarios"] >= 3
    assert result["fp_ratio"] <= 0.5
    assert result["detection_p99_delta_rounds"] <= 1.0
    assert result["regress"]["ok"] is True
