"""JSONL run-manifest sink + TensorBoard exporter (telemetry/sink.py).

Pins the round-trip contract (write -> parse -> same typed events), the
manifest invariants (run id, schema version, stable config digest,
device info), counter-row digestion (incl. the empty-metrics edge), and
the exporter's env gating.
"""

import json
import os

import numpy as np
import pytest

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.telemetry import sink as tsink
from scalecube_cluster_tpu.telemetry.events import (
    MembershipTraceEvent,
    TraceEventType,
)


def sample_events(n=7):
    return [
        MembershipTraceEvent(
            round=10 + i, observer=i, subject=3,
            event_type=TraceEventType(i % 5), incarnation=i % 3,
        )
        for i in range(n)
    ]


def test_events_roundtrip(tmp_path):
    """write -> parse -> the same typed event list, with the drop count
    carried alongside so a truncated trace is never silently complete."""
    events = sample_events(2500)          # spans multiple batches
    with tsink.TelemetrySink(str(tmp_path), prefix="t") as sink:
        sink.write_events(events, dropped=4)
        path = sink.path
    assert tsink.read_events(path) == events
    footer = tsink.read_records(path, kind="events_footer")
    assert footer == [{"kind": "events_footer", "run_id": sink.run_id,
                       "recorded": 2500, "dropped": 4}]


def test_manifest_fields_and_digest_stability(tmp_path):
    cfg = ClusterConfig.default()
    params = swim.SwimParams.from_config(cfg, n_members=64, n_subjects=16)
    with tsink.TelemetrySink(str(tmp_path)) as sink:
        sink.write_manifest(params=params, workload={"n": 64})
    (manifest,) = tsink.read_records(sink.path, kind="manifest")
    assert manifest["schema_version"] == tsink.SCHEMA_VERSION
    assert manifest["run_id"] == sink.run_id
    assert manifest["workload"] == {"n": 64}
    assert "backend" in manifest["device"]
    # Digest is a pure function of the knobs: same params -> same digest,
    # any knob change -> different digest.
    assert manifest["config_digest"] == tsink.config_digest(params)
    same = swim.SwimParams.from_config(cfg, n_members=64, n_subjects=16)
    other = swim.SwimParams.from_config(cfg, n_members=64, n_subjects=16,
                                        loss_probability=0.1)
    assert tsink.config_digest(same) == manifest["config_digest"]
    assert tsink.config_digest(other) != manifest["config_digest"]


def test_counters_histogram_curve_records(tmp_path):
    metrics = {
        "messages_gossip": np.arange(10, dtype=np.int32),
        "false_positives": np.ones((10, 4), dtype=np.int32),
        "dead": np.zeros((10, 4), dtype=np.int32),
    }
    with tsink.TelemetrySink(str(tmp_path)) as sink:
        sink.write_counters(metrics, round_offset=100, label="chunk_0")
        sink.write_counters({}, label="empty_chunk")   # must not crash
        sink.write_histogram("detection_latency_rounds",
                             edges=[0, 1, 2, 4], counts=[5, 0, 3, 1],
                             subject=3)
        sink.write_curve("fraction_informed", np.linspace(0, 1, 5000),
                         subject=3)
        sink.write_summary(event_drops=0)

    rows = tsink.read_records(sink.path, kind="counters")
    assert rows[0]["label"] == "chunk_0"
    assert rows[0]["round_offset"] == 100
    assert rows[0]["n_rounds"] == 10
    assert rows[0]["messages_gossip"] == 45
    assert rows[0]["false_positives"] == 40     # per-subject trace summed
    assert rows[1] == {"kind": "counters", "run_id": sink.run_id,
                       "label": "empty_chunk", "round_offset": 0,
                       "n_rounds": 0}

    (hist,) = tsink.read_records(sink.path, kind="histogram")
    assert hist["name"] == "detection_latency_rounds"
    assert hist["edges"] == [0, 1, 2, 4]
    assert hist["counts"] == [5, 0, 3, 1]
    assert hist["subject"] == 3

    (curve,) = tsink.read_records(sink.path, kind="curve")
    assert len(curve["values"]) <= 2048           # downsampled
    assert curve["values"][0] == 0.0

    (summary,) = tsink.read_records(sink.path, kind="summary")
    assert summary["event_drops"] == 0


def test_from_env_resolution(tmp_path, monkeypatch):
    monkeypatch.delenv(tsink.TELEMETRY_DIR_ENV, raising=False)
    assert tsink.TelemetrySink.from_env() is None
    sink = tsink.TelemetrySink.from_env(default_dir=str(tmp_path / "a"))
    assert sink is not None and sink.path.startswith(str(tmp_path / "a"))
    sink.close()
    monkeypatch.setenv(tsink.TELEMETRY_DIR_ENV, str(tmp_path / "b"))
    sink = tsink.TelemetrySink.from_env(default_dir=str(tmp_path / "a"))
    assert sink is not None and sink.path.startswith(str(tmp_path / "b"))
    sink.close()


def test_tensorboard_export_gated_off_without_env(monkeypatch):
    monkeypatch.delenv(tsink.PROFILE_DIR_ENV, raising=False)
    assert tsink.maybe_export_tensorboard("run-x",
                                          scalars={"a": [1, 2]}) is None


def test_tensorboard_export_writes_event_files(tmp_path, monkeypatch):
    pytest.importorskip("tensorboardX")
    monkeypatch.setenv(tsink.PROFILE_DIR_ENV, str(tmp_path))
    path = tsink.maybe_export_tensorboard(
        "run-y",
        scalars={"telemetry/dead_views": np.arange(50)},
        histograms={"telemetry/detection":
                    ([0, 1, 2, 4], [3, 2, 1, 0])},
    )
    assert path is not None
    produced = [
        os.path.join(root, f)
        for root, _, files in os.walk(path) for f in files
    ]
    assert produced, "exporter wrote no event files"


def test_bench_manifest_shape_end_to_end(tmp_path):
    """The full pipeline at test scale: a traced crash run digested
    through the sink exactly the way bench.py writes it, then read back
    — the manifest carries histogram BUCKETS (distributions, not means)
    and a zero drop count."""
    import jax

    from scalecube_cluster_tpu.telemetry import trace as ttrace

    cfg = ClusterConfig.default_local().replace(
        gossip_interval=100, ping_interval=200, ping_timeout=100,
        sync_interval=1_000, suspicion_mult=3,
    )
    params = swim.SwimParams.from_config(cfg, n_members=16,
                                         delivery="shift")
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=10)
    _, tel, metrics = swim.run_traced(jax.random.key(0), params, world, 90)
    hists = ttrace.latency_histograms(tel, world)

    with tsink.TelemetrySink(str(tmp_path), prefix="bench") as sink:
        sink.write_manifest(params=params)
        sink.write_counters(metrics, label="scenario")
        sink.write_histogram(
            "detection_latency_rounds",
            np.asarray(hists["edges"]), np.asarray(hists["detection"])[3],
            subject=3,
        )
        sink.write_events(ttrace.decode_events(tel),
                          dropped=int(tel.trace.dropped))
        sink.write_summary(event_drops=int(tel.trace.dropped))

    (hist,) = tsink.read_records(sink.path, kind="histogram")
    assert sum(hist["counts"]) == 15 and len(hist["counts"]) > 1
    (summary,) = tsink.read_records(sink.path, kind="summary")
    assert summary["event_drops"] == 0
    assert tsink.read_events(sink.path) == ttrace.decode_events(tel)


# --------------------------------------------------------------------------
# Torn-line hardening + the resumable-journal surface (resilience)
# --------------------------------------------------------------------------


def _write_lines(path, lines, torn_tail=None):
    with open(path, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
        if torn_tail is not None:
            f.write(torn_tail)          # no newline: a mid-write kill


def test_read_records_skips_torn_trailing_line(tmp_path):
    path = str(tmp_path / "run.jsonl")
    whole = [{"kind": "manifest", "run_id": "r"},
             {"kind": "segment", "round_start": 0, "round_end": 8}]
    torn = json.dumps({"kind": "segment", "round_start": 8,
                       "round_end": 16})[:25]
    _write_lines(path, whole, torn_tail=torn)
    with pytest.warns(UserWarning, match="torn trailing"):
        recs = tsink.read_records(path)
    assert recs == whole                # the torn record never counts
    with pytest.warns(UserWarning, match="torn trailing"):
        assert tsink.covered_upto(path) == 8


def test_parseable_but_unterminated_tail_is_still_torn(tmp_path):
    """A kill can land BETWEEN a record's payload bytes and its
    newline: the line parses but is not durable (reopen truncates it),
    so the readers — and above all the dedup cursor — must not count
    it.  Counting it would dedup a resumed segment against a record the
    heal then deletes: a permanent journal hole."""
    path = str(tmp_path / "run.jsonl")
    whole = [{"kind": "segment", "round_start": 0, "round_end": 8}]
    parseable_torn = json.dumps(
        {"kind": "segment", "round_start": 8, "round_end": 16})
    _write_lines(path, whole, torn_tail=parseable_torn)   # no newline
    with pytest.warns(UserWarning, match="torn trailing"):
        assert tsink.covered_upto(path) == 8              # NOT 16
    # The heal + rewrite path converges to a whole file covering 16.
    with pytest.warns(UserWarning, match="torn trailing"):
        sink = tsink.TelemetrySink(path=path, append=True)
    sink.write_record("segment", {"round_start": 8, "round_end": 16})
    sink.close()
    assert tsink.covered_upto(path) == 16


def test_read_records_raises_on_interior_corruption(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "manifest"}) + "\n")
        f.write("{definitely not json\n")
        f.write(json.dumps({"kind": "summary"}) + "\n")
    with pytest.raises(ValueError, match="interior"):
        tsink.read_records(path)


def test_append_mode_heals_torn_tail_before_writing(tmp_path):
    """A relaunched writer must not fuse its first record onto a torn
    fragment: the unterminated tail is truncated at reopen (it was
    never durable), and the resumed file parses clean end to end."""
    path = str(tmp_path / "run.jsonl")
    whole = [{"kind": "segment", "round_start": 0, "round_end": 8}]
    _write_lines(path, whole, torn_tail='{"kind": "segm')
    with pytest.warns(UserWarning, match="torn trailing"):
        sink = tsink.TelemetrySink(path=path, append=True)
    sink.write_record("segment", {"round_start": 8, "round_end": 16})
    sink.close()
    recs = tsink.read_records(path)     # no warning: file is clean now
    assert [r["round_end"] for r in recs if r["kind"] == "segment"] \
        == [8, 16]
    assert tsink.covered_upto(path) == 16


def test_append_mode_continues_existing_file(tmp_path):
    path = str(tmp_path / "run.jsonl")
    first = tsink.TelemetrySink(path=path)
    first.write_record("segment", {"round_start": 0, "round_end": 4})
    first.close()
    second = tsink.TelemetrySink(path=path, append=True)
    second.write_record("segment", {"round_start": 4, "round_end": 8})
    second.close()
    assert tsink.covered_upto(path) == 8
    # Both writers stamped the same run id (derived from the filename).
    run_ids = {r["run_id"] for r in tsink.read_records(path)}
    assert run_ids == {"run"}


def test_covered_upto_missing_and_empty(tmp_path):
    assert tsink.covered_upto(str(tmp_path / "nope.jsonl")) == 0
    path = str(tmp_path / "empty.jsonl")
    open(path, "w").close()
    assert tsink.covered_upto(path) == 0


def test_sink_requires_out_dir_or_path():
    with pytest.raises(ValueError, match="out_dir or path"):
        tsink.TelemetrySink()


def test_counters_row_warns_once_on_non_numeric_lane():
    """A non-numeric counter lane is skipped from the row but NEVER
    silently: the first failure per key warns (once per process — a
    broken lane repeats every flush window and one warning per window
    would bury the signal), so a registry/driver schema drift can't
    quietly lose a lane forever."""
    import warnings

    tsink._WARNED_NON_NUMERIC.discard("messages_gossip")
    bad = {
        "messages_gossip": np.asarray(["a", "b", "c"]),   # non-numeric
        "refutations": np.arange(3, dtype=np.int32),      # fine
    }
    with pytest.warns(UserWarning, match="non-numeric metric "
                                         "'messages_gossip'"):
        row = tsink.counters_row(bad)
    assert "messages_gossip" not in row       # dropped, not garbage
    assert row["refutations"] == 3            # numeric lanes unaffected
    assert row["n_rounds"] == 3

    # Second flush with the same broken lane: no second warning.
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        row2 = tsink.counters_row(bad)
    assert "messages_gossip" not in row2


def test_metrics_window_record_roundtrip(tmp_path):
    """write_metrics_window -> read_records -> the same payload, with
    the round_end cursor visible to covered_upto (the resumable-journal
    contract the windowed registry flush rides)."""
    window = {
        "round_start": 0, "round_end": 32,
        "counters": {"fd_probes_sent": 7},
        "gauges": {"suspect_entries": 2.0},
        "histograms": {"suspicion_lifetime_rounds":
                       {"edges": [0, 1, 2], "counts": [0, 1, 0]}},
    }
    with tsink.TelemetrySink(str(tmp_path)) as sink:
        sink.write_metrics_window(window)
        with pytest.raises(ValueError, match="round_end"):
            sink.write_metrics_window({"round_start": 32,
                                       "counters": {}})
    (rec,) = tsink.read_records(sink.path, kind="metrics_window")
    for k, v in window.items():
        assert rec[k] == v
    assert tsink.covered_upto(sink.path, kind="metrics_window") == 32
