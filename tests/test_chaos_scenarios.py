"""Scenario DSL compilation + seeded campaign generation.

The DSL's contract: ops compile to exactly the ``SwimWorld``/
``LinkFaults`` schedule arrays the dense tick already consumes, the
derived ``MonitorSpec`` encodes what each scenario promises (pristine
networks check false suspicion; permanent faults get completeness
deadlines; permanent disruptions promise nothing), and
``generate_scenario`` is a pure function of (seed, n, severity) — the
one-line-repro property every campaign failure relies on.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.chaos import campaign as cc
from scalecube_cluster_tpu.chaos import scenarios as cs
from scalecube_cluster_tpu.models import swim

pytestmark = pytest.mark.chaos

INT32_MAX = cs.INT32_MAX
N = 24


def build(ops, horizon=192, loss=0.0, **scen_kw):
    scen = cs.Scenario(name="t", n_members=N, horizon=horizon,
                       ops=tuple(ops), loss_probability=loss, **scen_kw)
    params = cc.campaign_params(scen)
    world, spec = scen.build(params)
    return scen, params, world, spec


# --------------------------------------------------------------------------
# Op compilation
# --------------------------------------------------------------------------


def test_crash_burst_and_leave_compile_to_world_schedules():
    _, _, world, _ = build([
        cs.CrashBurst((1, 2, 3), at_round=4),
        cs.Crash(5, at_round=8, until_round=40),
        cs.Leave(7, at_round=12),
    ])
    df = np.asarray(world.down_from)
    du = np.asarray(world.down_until)
    assert df[1] == df[2] == df[3] == 4 and du[1] == INT32_MAX
    assert df[5] == 8 and du[5] == 40
    assert int(np.asarray(world.leave_at)[7]) == 12 and df[7] == 13


def test_churn_storm_staggers_disjoint_waves():
    storm = cs.ChurnStorm((10, 11, 12, 13), wave_size=2, start_round=6,
                          wave_every=9, down_rounds=30)
    _, _, world, _ = build([storm])
    df = np.asarray(world.down_from)
    du = np.asarray(world.down_until)
    assert df[10] == df[11] == 6 and du[10] == 36
    assert df[12] == df[13] == 15 and du[12] == 45


def test_churn_storm_rejects_ragged_waves():
    with pytest.raises(ValueError, match="wave_size"):
        cs.ChurnStorm((1, 2, 3), wave_size=2, start_round=0, wave_every=4)


def test_flapping_link_compiles_to_block_windows():
    flap = cs.FlappingLink(2, 9, from_round=10, n_cycles=3,
                           down_rounds=4, up_rounds=6)
    _, _, world, _ = build([flap])
    f = world.faults
    live = [(int(f.from_round[r]), int(f.until_round[r]), float(f.loss[r]))
            for r in range(f.n_rules) if int(f.src_hi[r]) > int(f.src_lo[r])]
    assert live == [(10, 14, 1.0), (20, 24, 1.0), (30, 34, 1.0)]
    assert flap.disruption(N, 192) == (10, 34)


def test_brownout_ramps_up_holds_and_ramps_down():
    b = cs.Brownout(src=(0, 12), dst=(12, 24), peak_loss=0.6,
                    from_round=8, ramp_rounds=12, hold_rounds=10, steps=3)
    _, _, world, _ = build([b])
    f = world.faults
    live = [(int(f.from_round[r]), int(f.until_round[r]),
             round(float(f.loss[r]), 2))
            for r in range(f.n_rules) if int(f.src_hi[r]) > int(f.src_lo[r])]
    assert live == [(8, 12, 0.2), (12, 16, 0.4), (16, 20, 0.6),
                    (20, 30, 0.6), (30, 34, 0.4), (34, 38, 0.2)]
    # Asymmetric: only src-range -> dst-range.
    assert int(f.src_lo[0]) == 0 and int(f.src_hi[0]) == 12
    assert int(f.dst_lo[0]) == 12 and int(f.dst_hi[0]) == 24


def test_rolling_partition_phases_and_tail():
    rp = cs.RollingPartition(from_round=16, phase_rounds=16, n_cycles=2,
                             rotate=3)
    _, _, world, _ = build([rp], horizon=192)
    sched = np.asarray(world.partition_of)
    pr = int(np.asarray(world.partition_phase_rounds))
    assert pr == 16
    # lead zero phase, split, heal, split, heal, zero tail past horizon.
    assert not sched[0].any()
    assert sched[1].any() and not sched[2].any() and sched[3].any()
    assert sched.shape[0] * pr > 192
    assert not sched[4:].any()
    # Rotation: cycle 2's split differs from cycle 1's.
    assert sched[1].tolist() != sched[3].tolist()
    assert rp.disruption(N, 192) == (16, 64)


def test_brownout_without_hold_skips_the_empty_window():
    b = cs.Brownout(src=(0, 12), dst=(12, 24), peak_loss=0.6,
                    from_round=8, ramp_rounds=12, hold_rounds=0, steps=3)
    _, _, world, _ = build([b])      # builds cleanly (no empty rule)
    f = world.faults
    live = [(int(f.from_round[r]), int(f.until_round[r]))
            for r in range(f.n_rules) if int(f.src_hi[r]) > int(f.src_lo[r])]
    assert live == [(8, 12), (12, 16), (16, 20), (20, 24), (24, 28)]


def test_flapping_link_rejects_empty_down_window():
    with pytest.raises(ValueError, match="down_rounds"):
        cs.FlappingLink(0, 1, from_round=0, n_cycles=2, down_rounds=0,
                        up_rounds=4)


def test_rolling_partition_rejects_unaligned_start():
    with pytest.raises(ValueError, match="multiple of"):
        cs.RollingPartition(from_round=10, phase_rounds=16, n_cycles=1)


def test_rule_padding_preserves_semantics_and_shape():
    _, _, world, _ = build([cs.LinkLoss(0, 1, loss=0.5)])
    assert world.faults.n_rules == cs._RULE_PAD     # padded to fixed width
    # Pad rules are empty ranges: they match no (src, dst) pair.
    loss, _ = swim.link_eval(world.faults, 0,
                             jnp.arange(N), jnp.arange(N)[:, None], 0.0, 0.0)
    assert float(np.asarray(loss)[1, 0]) == 0.5     # dst=1 row, src=0
    assert float(np.asarray(loss).sum()) == 0.5     # nothing else matches


# --------------------------------------------------------------------------
# MonitorSpec derivation
# --------------------------------------------------------------------------


def test_pristine_scenario_checks_false_suspicion():
    _, params, _, spec = build([cs.Crash(3, at_round=5)])
    assert spec.check_false_suspicion
    bound = cs.completeness_bound(params, N)
    assert int(spec.complete_by[3]) == 5 + bound
    others = np.delete(np.asarray(spec.complete_by), 3)
    assert (others == INT32_MAX).all()


def test_network_disruption_disables_false_suspicion_check():
    for ops, loss in ([[cs.LinkLoss(0, 1, loss=0.3)], 0.0],
                      [[cs.RollingPartition(0, 16, 1)], 0.0],
                      [[cs.Crash(3, at_round=5)], 0.05]):
        _, _, _, spec = build(ops, loss=loss)
        assert not spec.check_false_suspicion, (ops, loss)


def test_disruption_extends_completeness_deadline():
    scen, params, _, spec = build([
        cs.Crash(3, at_round=5),
        cs.FlappingLink(1, 2, from_round=20, n_cycles=2,
                        down_rounds=4, up_rounds=6),
    ], horizon=256)
    bound = cs.completeness_bound(params, N)
    assert int(spec.complete_by[3]) == 34 + bound   # disruption end, not 5


def test_permanent_disruption_voids_completeness():
    _, _, _, spec = build([
        cs.Crash(3, at_round=5),
        cs.LinkLoss((0, N), 7, loss=1.0),           # forever block
    ])
    assert (np.asarray(spec.complete_by) == INT32_MAX).all()


def test_revived_crash_has_no_completeness_deadline():
    _, _, _, spec = build([cs.Crash(3, at_round=5, until_round=60)])
    assert int(spec.complete_by[3]) == INT32_MAX


def test_build_rejects_mismatched_params():
    scen = cs.Scenario(name="t", n_members=N, horizon=64,
                       ops=(cs.Crash(0, at_round=1),))
    other = swim.SwimParams.from_config(cc.campaign_config(),
                                        n_members=N * 2)
    with pytest.raises(ValueError, match="n_members"):
        scen.build(other)


# --------------------------------------------------------------------------
# Campaign generation
# --------------------------------------------------------------------------


def test_generate_scenario_is_pure_and_tiered():
    for sev in cs.SEVERITIES:
        a = cs.generate_scenario(seed=11, n=32, severity=sev)
        b = cs.generate_scenario(seed=11, n=32, severity=sev)
        assert a == b                       # the one-line-repro property
        assert a.severity == sev and a.seed == 11
        assert a.horizon % 64 == 0          # quantized (compile sharing)
        assert f"severity={sev!r}" in a.repro()
    assert (cs.generate_scenario(seed=11, n=32, severity="mild")
            != cs.generate_scenario(seed=12, n=32, severity="mild"))


def test_generated_severities_escalate():
    mild = cs.generate_scenario(seed=3, n=32, severity="mild")
    severe = cs.generate_scenario(seed=3, n=32, severity="severe")
    # Mild = exactly one FAULT op; the trailing metadata ConfigPush
    # (PR 19, drawn for half the seeds in every tier) is not a fault.
    faults = [op for op in mild.ops if not isinstance(op, cs.ConfigPush)]
    assert len(faults) == 1
    assert mild.loss_probability == 0.0
    assert severe.loss_probability > 0.0
    assert any(isinstance(op, cs.RollingPartition) for op in severe.ops)
    assert any(isinstance(op, cs.ChurnStorm) for op in severe.ops)


def test_generated_scenarios_build_cleanly():
    """Every tier x several seeds compiles to a world + spec without
    touching the DSL validation (the generator only emits legal ops)."""
    for seed in range(5):
        for sev in cs.SEVERITIES:
            scen = cs.generate_scenario(seed=seed, n=32, severity=sev)
            params = cc.campaign_params(scen)
            world, spec = scen.build(params)
            assert world.faults.n_rules % cs._RULE_PAD == 0
            assert spec.complete_by.shape == (32,)
            assert scen.horizon >= cs.completeness_bound(params, 32)


def test_generate_campaign_cycles_severities():
    scens = cs.generate_campaign(seed=50, n_scenarios=7, n=32)
    assert [s.severity for s in scens] == [
        "mild", "moderate", "severe", "mild", "moderate", "severe", "mild"]
    assert [s.seed for s in scens] == list(range(50, 57))
    assert len({s.name for s in scens}) == 7


def test_extra_slack_widens_deadlines():
    _, params, _, spec0 = build([cs.Crash(3, at_round=5)])
    _, _, _, spec1 = build([cs.Crash(3, at_round=5)], extra_slack=40)
    assert int(spec1.complete_by[3]) == int(spec0.complete_by[3]) + 40
