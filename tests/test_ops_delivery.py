"""Tests for the dense delivery ops (the TpuSimTransport fast path).

Pins: pack/unpack bijection, scatter-max == brute-force numpy delivery,
and — the load-bearing one — ``merge_inbox`` equals a per-message scalar
serialization of the reference's updateMembership loop
(MembershipProtocolImpl.java:475-541) over every small inbound multiset.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.ops import delivery, prng

ALIVE, SUSPECT, DEAD, ABSENT = (
    records.ALIVE,
    records.SUSPECT,
    records.DEAD,
    records.ABSENT,
)


class TestPackUnpack:
    def test_roundtrip_all_statuses(self):
        statuses = jnp.array([ALIVE, SUSPECT, DEAD] * 4, dtype=jnp.int8)
        incs = jnp.array([0, 1, 7, 12345] * 3, dtype=jnp.int32)
        key = delivery.pack_record(statuses, incs)
        s2, i2 = delivery.unpack_record(key)
        np.testing.assert_array_equal(np.asarray(s2), np.asarray(statuses))
        np.testing.assert_array_equal(np.asarray(i2), np.asarray(incs))

    def test_absent_packs_to_no_message(self):
        key = delivery.pack_record(jnp.int8(ABSENT), jnp.int32(5))
        assert int(key) == -1
        s, i = delivery.unpack_record(key)
        assert int(s) == ABSENT and int(i) == 0

    def test_key_order_matches_merge_priority(self):
        # DEAD > higher inc > SUSPECT-at-equal-inc > ALIVE (records.merge_key).
        k = lambda s, i: int(delivery.pack_record(jnp.int8(s), jnp.int32(i)))
        assert k(DEAD, 0) > k(SUSPECT, 10**6) > k(ALIVE, 10**6) > k(SUSPECT, 1) > k(ALIVE, 1)


class TestScatter:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_scatter_max_matches_numpy(self, seed):
        rng = np.random.default_rng(seed)
        n_senders, n_rows, n_subjects, fanout = 17, 13, 5, 3
        values = rng.integers(-1, 100, size=(n_senders, n_subjects)).astype(np.int32)
        targets = rng.integers(0, n_rows, size=(n_senders, fanout)).astype(np.int32)
        drop = rng.random((n_senders, fanout)) < 0.3

        expected = np.full((n_rows, n_subjects), -1, dtype=np.int32)
        for s in range(n_senders):
            for f in range(fanout):
                if not drop[s, f]:
                    r = targets[s, f]
                    expected[r] = np.maximum(expected[r], values[s])

        got = delivery.scatter_max(
            jnp.asarray(values), jnp.asarray(targets), jnp.asarray(drop), n_rows
        )
        np.testing.assert_array_equal(np.asarray(got), expected)

    @pytest.mark.parametrize("seed", [0, 3])
    def test_scatter_or_matches_numpy(self, seed):
        rng = np.random.default_rng(seed)
        n_senders, n_rows, n_subjects, fanout = 11, 9, 4, 2
        flags = rng.random((n_senders, n_subjects)) < 0.4
        targets = rng.integers(0, n_rows, size=(n_senders, fanout)).astype(np.int32)
        drop = rng.random((n_senders, fanout)) < 0.3

        expected = np.zeros((n_rows, n_subjects), dtype=bool)
        for s in range(n_senders):
            for f in range(fanout):
                if not drop[s, f]:
                    expected[targets[s, f]] |= flags[s]

        got = delivery.scatter_or(
            jnp.asarray(flags), jnp.asarray(targets), jnp.asarray(drop), n_rows
        )
        np.testing.assert_array_equal(np.asarray(got), expected)


def _scalar_serialized_merge(entry, inbound):
    """Apply inbound records one at a time, scalar is_overrides per record.

    This is the arrival-order serialization merge_inbox canonicalizes:
    non-DEAD records in ascending merge_key order, then DEAD records in
    *descending* key order.  (Arrival order is arbitrary in the reference —
    one scheduler thread drains messages as they come,
    MembershipProtocolImpl.java:475-541 — so any fixed order is a faithful
    schedule; this one is the one whose outcome the associative max-fold
    reproduces.  The orders differ only in which incarnation a removed
    record's death notice retains — the reference stores nothing at all for
    removed records, MembershipProtocolImpl.java:512-516.)  A stored DEAD
    gates like ABSENT (the entry was deleted); accepted records store as-is.
    """
    status, inc = entry
    key_of = lambda r: int(records.merge_key(r[0], r[1]))
    live = sorted((r for r in inbound if r[0] != DEAD), key=key_of)
    dead = sorted((r for r in inbound if r[0] == DEAD), key=key_of, reverse=True)
    for r_status, r_inc in live + dead:
        gate = ABSENT if status == DEAD else status
        if records.is_overrides(r_status, r_inc, gate, inc):
            status, inc = r_status, r_inc
    return status, inc


class TestMergeInbox:
    def test_exhaustive_small_multisets(self):
        """Every entry x inbound multiset (size<=2) over status x inc {0,1,2}."""
        wire_records = [
            (s, i) for s in (ALIVE, SUSPECT, DEAD) for i in (0, 1, 2)
        ]
        entries = [(s, i) for s in (ALIVE, SUSPECT, DEAD, ABSENT) for i in (0, 1, 2)]
        multisets = [()] + [(r,) for r in wire_records] + list(
            itertools.combinations_with_replacement(wire_records, 2)
        )

        cases, expected = [], []
        for entry in entries:
            for ms in multisets:
                cases.append((entry, ms))
                expected.append(_scalar_serialized_merge(entry, ms))

        entry_status = jnp.array([c[0][0] for c in cases], dtype=jnp.int8)
        entry_inc = jnp.array([c[0][1] for c in cases], dtype=jnp.int32)
        inbox_key = jnp.array(
            [
                max((int(records.merge_key(s, i)) for s, i in ms), default=-1)
                for _, ms in cases
            ],
            dtype=jnp.int32,
        )
        any_alive = jnp.array(
            [any(s == ALIVE for s, _ in ms) for _, ms in cases], dtype=jnp.bool_
        )

        got_status, got_inc, _ = delivery.merge_inbox(
            entry_status, entry_inc, inbox_key, any_alive
        )
        exp_status = np.array([e[0] for e in expected], dtype=np.int8)
        exp_inc = np.array([e[1] for e in expected], dtype=np.int32)
        np.testing.assert_array_equal(np.asarray(got_status), exp_status)
        np.testing.assert_array_equal(np.asarray(got_inc), exp_inc)

    def test_changed_flag(self):
        # Accepted-but-identical must not report change (stored DEAD + DEAD rebroadcast).
        status, inc, changed = delivery.merge_inbox(
            jnp.array([DEAD, ALIVE], dtype=jnp.int8),
            jnp.array([3, 1], dtype=jnp.int32),
            delivery.pack_record(
                jnp.array([DEAD, SUSPECT], dtype=jnp.int8),
                jnp.array([3, 1], dtype=jnp.int32),
            ),
            jnp.array([True, False]),
        )
        assert bool(changed[0]) is False
        assert bool(changed[1]) is True and int(status[1]) == SUSPECT


class TestPrng:
    def test_targets_exclude_self_and_in_range(self):
        key = jax.random.key(0)
        t = prng.targets_excluding_self(key, 64, 64, 3)
        t = np.asarray(t)
        assert t.min() >= 0 and t.max() < 64
        sender = np.arange(64)[:, None]
        assert not np.any(t == sender)

    def test_targets_with_offset(self):
        key = jax.random.key(1)
        t = np.asarray(prng.targets_excluding_self(key, 8, 64, 3, sender_offset=16))
        sender = (np.arange(8) + 16)[:, None]
        assert not np.any(t == sender)
        assert t.min() >= 0 and t.max() < 64

    def test_choose_eligible_respects_mask(self):
        key = jax.random.key(2)
        eligible = jnp.array([[True, False, True, False], [False, False, False, True]])
        idx, any_ok = prng.choose_eligible(key, eligible)
        assert bool(any_ok[0]) and bool(any_ok[1])
        assert int(idx[0]) in (0, 2)
        assert int(idx[1]) == 3

    def test_choose_eligible_none(self):
        key = jax.random.key(3)
        _, any_ok = prng.choose_eligible(key, jnp.zeros((2, 4), dtype=bool))
        assert not bool(any_ok[0]) and not bool(any_ok[1])

    def test_choose_eligible_roughly_uniform(self):
        keys = jax.random.split(jax.random.key(4), 2000)
        eligible = jnp.array([[True, True, False, True]])
        idxs = np.asarray(
            jax.vmap(lambda k: prng.choose_eligible(k, eligible)[0])(keys)
        ).ravel()
        counts = np.bincount(idxs, minlength=4)
        assert counts[2] == 0
        for slot in (0, 1, 3):
            assert 500 < counts[slot] < 840  # ~667 expected


def test_is_alive_key_matches_pack_record():
    """The ALIVE-gate classifier agrees with pack_record for every status
    at several incarnations, and rejects NO_MESSAGE."""
    for inc in (0, 1, 7, 2**29 - 1):
        for status, expect in (
            (records.ALIVE, True),
            (records.SUSPECT, False),
            (records.DEAD, False),
        ):
            key = delivery.pack_record(jnp.int8(status), jnp.int32(inc))
            assert bool(delivery.is_alive_key(key)) is expect, (status, inc)
    assert not bool(delivery.is_alive_key(delivery.NO_MESSAGE))
    # ABSENT packs to NO_MESSAGE and must not read as alive.
    key = delivery.pack_record(jnp.int8(records.ABSENT), jnp.int32(5))
    assert not bool(delivery.is_alive_key(key))
