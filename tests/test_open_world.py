"""Open-world membership plane (PR 10): JOIN admission into recycled
slots guarded by per-slot identity epochs.

Pins, in order:

  - the wire layer: the epoch-extended key layout (epoch directly under
    the dead bit — ops/delivery.py), its fold order, and the merge
    gate's cross-epoch semantics (lower drops, higher admits only via
    ALIVE, admission overrides the dead-suppression window);
  - the STRONG no-op contract (the PR-7/PR-9 pattern): open_world=True
    with no scheduled joins is table+trace+metrics-identical to
    open_world=False across full-view/focal/compact/wire16 layouts,
    both delivery modes, the blocked tick, round fusion, and the
    sharded pipelined==serial path;
  - join semantics: every live observer admits the new identity (epoch
    1, incarnation 0), the JOINED trace lane disambiguates admissions
    from same-identity re-adds, a suppressed tombstone does not block
    the join, and the naive-reuse control (epoch_guard=False) exhibits
    the resurrection hazard the monitor's NO_RESURRECTION /
    JOIN_COMPLETENESS codes count;
  - layout/run-shape identity with joins ON: compact/wire16/k_block/
    fused twins bit-identical, the five run shapes agreeing, and the
    sharded pipelined path == the serial combine through a real join;
  - checkpoint back-compat: a pre-epoch checkpoint loads as zero-epoch
    for an open-world resume (utils/checkpoint.state_from_arrays);
  - the oracle ground truth: a net-positive churn schedule with
    mid-run ``Cluster.join`` replayed on the event-driven oracle
    produces the same per-slot ADDED/SUSPECTED/REMOVED key sets
    (chaos/campaign.cross_validate_churn).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.chaos import campaign as cc
from scalecube_cluster_tpu.chaos import monitor as cmonitor
from scalecube_cluster_tpu.chaos import scenarios as cs
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.ops import delivery
from scalecube_cluster_tpu.telemetry import trace as ttrace
from scalecube_cluster_tpu.telemetry.events import TraceEventType

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.openworld

INT32_MAX = int(jnp.iinfo(jnp.int32).max)


def make(n, k=None, open_world=True, **overrides):
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=k,
        open_world=open_world, **overrides,
    )
    return params, swim.SwimWorld.healthy(params)


def crash_join_world(world, slot=3, crash_at=5, join_at=40):
    return world.with_crash(slot, crash_at).with_join(slot, join_at)


def assert_tables_equal(a, b, msg="", with_epoch=True):
    np.testing.assert_array_equal(np.asarray(a.status),
                                  np.asarray(b.status),
                                  err_msg=f"{msg}: status")
    np.testing.assert_array_equal(np.asarray(a.inc, dtype=np.int32),
                                  np.asarray(b.inc, dtype=np.int32),
                                  err_msg=f"{msg}: inc")
    if with_epoch and a.epoch.size and b.epoch.size:
        np.testing.assert_array_equal(
            np.asarray(a.epoch, dtype=np.int32),
            np.asarray(b.epoch, dtype=np.int32),
            err_msg=f"{msg}: epoch")


def assert_metrics_equal(ma, mb, msg=""):
    assert set(ma) == set(mb), msg
    for name in ma:
        np.testing.assert_array_equal(np.asarray(ma[name]),
                                      np.asarray(mb[name]),
                                      err_msg=f"{msg}: metric {name}")


# --------------------------------------------------------------------------
# Wire layer
# --------------------------------------------------------------------------


class TestEpochWire:
    def test_plane_off_layout_is_the_legacy_key(self):
        st = jnp.asarray([records.ALIVE, records.SUSPECT, records.DEAD,
                          records.ABSENT], jnp.int8)
        inc = jnp.asarray([0, 5, 7, 3])
        np.testing.assert_array_equal(
            np.asarray(delivery.pack_record(st, inc)),
            np.asarray(records.merge_key(st, inc)))
        np.testing.assert_array_equal(
            np.asarray(delivery.pack_record(st, inc, compact=True)),
            np.asarray(records.merge_key16(st, inc)))

    @pytest.mark.parametrize("compact,eb", [
        (False, delivery.EPOCH_BITS_WIDE),
        (True, delivery.EPOCH_BITS_COMPACT),
    ])
    def test_epoch_key_roundtrip_and_order(self, compact, eb):
        st = jnp.asarray([records.ALIVE, records.SUSPECT, records.DEAD,
                          records.ABSENT], jnp.int8)
        inc = jnp.asarray([4, 9, 2, 0])
        ep = jnp.asarray([1, 0, 2, 0])
        key = delivery.pack_record(st, inc, compact=compact, epoch=ep,
                                   epoch_bits=eb)
        got_st, got_inc = delivery.unpack_record(key, compact=compact,
                                                 epoch_bits=eb)
        got_ep = delivery.unpack_epoch(key, compact=compact, epoch_bits=eb)
        np.testing.assert_array_equal(np.asarray(got_st), np.asarray(st))
        np.testing.assert_array_equal(np.asarray(got_inc),
                                      np.asarray([4, 9, 2, 0]))
        np.testing.assert_array_equal(np.asarray(got_ep),
                                      np.asarray([1, 0, 2, 0]))
        # is_alive_key is layout-invariant (dead bit + suspect bit
        # positions are unchanged by the epoch field).
        np.testing.assert_array_equal(
            np.asarray(delivery.is_alive_key(key, compact=compact)),
            np.asarray([True, False, False, False]))

        def k(s, i, e):
            return int(delivery.pack_record(jnp.int8(s), jnp.int32(i),
                                            compact=compact, epoch=e,
                                            epoch_bits=eb))

        # Fold order: DEAD absorbs across epochs (the reference's rule
        # 3 stays on top); within a liveness class a higher epoch
        # outranks any incarnation of an older occupant.
        assert k(records.DEAD, 0, 0) > k(records.ALIVE, 100, 1)
        assert k(records.ALIVE, 0, 1) > k(records.ALIVE, 100, 0)
        assert k(records.SUSPECT, 3, 1) > k(records.ALIVE, 3, 1)

    def test_inc_saturation_cap_drops_by_epoch_bits(self):
        p_off, _ = make(8, open_world=False)
        p_on, _ = make(8)
        assert swim._wire_inc_sat(p_off) == (1 << 29) - 1
        assert swim._wire_inc_sat(p_on) == (
            1 << (29 - delivery.EPOCH_BITS_WIDE)) - 1
        p_c = dataclasses.replace(p_on, int16_wire=True)
        assert swim._wire_inc_sat(p_c) == (
            1 << (13 - delivery.EPOCH_BITS_COMPACT)) - 1

    def test_naive_arm_epoch_bits_are_zero(self):
        """The naive control arm runs the TRUE legacy wire: no lane, no
        epoch field (SwimParams.epoch_bits docstring)."""
        p, _ = make(8)
        p_naive = dataclasses.replace(p, epoch_guard=False)
        assert p.epoch_bits > 0
        assert p_naive.epoch_bits == 0
        assert swim.initial_epoch(p_naive).size == 0


class TestEpochMergeGate:
    EB = delivery.EPOCH_BITS_WIDE

    def _merge(self, entry, key, any_alive=True, suppress=None,
               guard=True):
        st, inc, ep = entry
        s, i, e, ch = delivery.merge_inbox(
            jnp.asarray([st], jnp.int8), jnp.asarray([inc]),
            jnp.asarray([key]), jnp.asarray([any_alive]),
            suppress=None if suppress is None else jnp.asarray([suppress]),
            entry_epoch=jnp.asarray([ep]), epoch_bits=self.EB,
            epoch_guard=guard,
        )
        return s[0], i[0], e[0], ch

    def _key(self, st, inc, ep):
        return int(delivery.pack_record(jnp.int8(st), jnp.int32(inc),
                                        epoch=ep, epoch_bits=self.EB))

    def test_lower_epoch_records_drop(self):
        """The old occupant's tombstone AND its hot ALIVE notice both
        bounce off a higher-epoch record — the slot-recycling hazard."""
        for st, inc in ((records.DEAD, 7), (records.ALIVE, 9),
                        (records.SUSPECT, 9)):
            s, i, e, ch = self._merge((records.ALIVE, 0, 1),
                                      self._key(st, inc, 0))
            assert (int(s), int(i), int(e)) == (records.ALIVE, 0, 1)
            assert not bool(ch[0])

    def test_higher_epoch_admits_only_alive(self):
        s, i, e, ch = self._merge((records.DEAD, 7, 0),
                                  self._key(records.ALIVE, 0, 1))
        assert (int(s), int(i), int(e)) == (records.ALIVE, 0, 1)
        assert bool(ch[0])
        # A higher-epoch SUSPECT/DEAD is NOT an admission (the ABSENT
        # null-gate rule applied per identity).
        for st in (records.SUSPECT, records.DEAD):
            s, i, e, ch = self._merge((records.DEAD, 7, 0),
                                      self._key(st, 0, 1))
            assert (int(s), int(e)) == (records.DEAD, 0)
            assert not bool(ch[0])

    def test_suppressed_tombstone_does_not_block_higher_epoch_join(self):
        """The dead_suppress_rounds interplay pin: the window guards the
        OLD identity's death notice, never a new identity's arrival."""
        # Same-epoch ALIVE: suppressed (the PR-9 contract)...
        s, _, _, ch = self._merge((records.DEAD, 7, 0),
                                  self._key(records.ALIVE, 9, 0),
                                  suppress=True)
        assert int(s) == records.DEAD and not bool(ch[0])
        # ...but the higher-epoch JOIN admits through it.
        s, i, e, ch = self._merge((records.DEAD, 7, 0),
                                  self._key(records.ALIVE, 0, 1),
                                  suppress=True)
        assert (int(s), int(i), int(e)) == (records.ALIVE, 0, 1)
        assert bool(ch[0])

    def test_same_epoch_gate_is_the_legacy_gate(self):
        s, i, e, ch = self._merge((records.ALIVE, 3, 1),
                                  self._key(records.SUSPECT, 3, 1))
        assert (int(s), int(i), int(e)) == (records.SUSPECT, 3, 1)
        assert bool(ch[0])

    def test_guard_off_is_epoch_blind(self):
        """The unit-level demonstration of what the guard changes: on
        identical keys, the blind gate lets the old tombstone kill the
        new identity."""
        s, i, e, ch = self._merge((records.ALIVE, 0, 1),
                                  self._key(records.DEAD, 7, 0),
                                  guard=False)
        assert (int(s), int(i), int(e)) == (records.DEAD, 7, 0)
        assert bool(ch[0])


# --------------------------------------------------------------------------
# Strong no-op: plane on, no joins == plane off
# --------------------------------------------------------------------------


LAYOUTS = {
    "fullview-shift": dict(n=16, delivery="shift"),
    "fullview-scatter": dict(n=16, delivery="scatter"),
    "focal-scatter": dict(n=24, k=8, delivery="scatter"),
    "compact": dict(n=16, delivery="shift", compact_carry=True),
    "wire16": dict(n=16, delivery="shift", int16_wire=True),
    "blocked": dict(n=16, delivery="shift", k_block=4),
    "fused": dict(n=16, delivery="shift", rounds_per_step=4),
}


class TestStrongNoOp:
    @pytest.mark.parametrize("layout", sorted(LAYOUTS))
    def test_plane_on_without_joins_is_identical(self, layout):
        kw = dict(LAYOUTS[layout])
        n = kw.pop("n")
        k = kw.pop("k", None)
        p_off, world = make(n, k=k, open_world=False, **kw)
        p_on, _ = make(n, k=k, open_world=True, **kw)
        # A little background churn (crash + leave, no joins) so the
        # no-op holds through real fault machinery, not just warm idle.
        world = world.with_crash(1, 6).with_leave(2, 9)
        st_off, m_off = swim.run(jax.random.key(0), p_off, world, 48)
        st_on, m_on = swim.run(jax.random.key(0), p_on, world, 48)
        assert_tables_equal(st_off, st_on, msg=layout, with_epoch=False)
        assert_metrics_equal(m_off, m_on, msg=layout)
        # The lane exists, and nothing ever advanced an epoch.
        if p_on.epoch_bits:
            assert np.asarray(st_on.epoch).max(initial=0) == 0

    def test_trace_identical_without_joins(self):
        p_off, world = make(16, open_world=False)
        p_on, _ = make(16, open_world=True)
        world = world.with_crash(1, 6, 30)      # crash + revive re-add
        _, tel_off, _ = swim.run_traced(jax.random.key(0), p_off, world, 64)
        _, tel_on, _ = swim.run_traced(jax.random.key(0), p_on, world, 64)
        ev_off = [e.key() for e in ttrace.decode_events(tel_off)]
        ev_on = [e.key() for e in ttrace.decode_events(tel_on)]
        assert ev_off == ev_on
        # The revival re-add stays a plain ADDED (same identity — the
        # JOINED lane is admissions only).
        assert not any(e.event_type == TraceEventType.JOINED
                       for e in ttrace.decode_events(tel_on))


# --------------------------------------------------------------------------
# Join semantics
# --------------------------------------------------------------------------


class TestJoinSemantics:
    @pytest.mark.parametrize("mode", ["shift", "scatter"])
    def test_every_observer_admits_the_new_identity(self, mode):
        p, world = make(16, delivery=mode)
        world = crash_join_world(world, slot=3, crash_at=5, join_at=40)
        st, _ = swim.run(jax.random.key(0), p, world, 90)
        stt = np.asarray(st.status)[:, 3]
        ep = np.asarray(st.epoch)[:, 3]
        inc = np.asarray(st.inc)[:, 3]
        assert (stt == records.ALIVE).all()
        assert (ep == 1).all()
        assert (inc == 0).all()
        assert int(np.asarray(st.self_inc)[3]) == 0

    def test_joined_events_fire_for_admissions(self):
        p, world = make(16)
        world = crash_join_world(world, slot=3, crash_at=5, join_at=40)
        _, tel, _ = swim.run_traced(jax.random.key(0), p, world, 90)
        ev = ttrace.decode_events(tel)
        joined = [e for e in ev if e.event_type == TraceEventType.JOINED]
        assert {e.subject for e in joined} == {3}
        assert all(e.incarnation == 0 and e.round >= 40 for e in joined)
        # Every OTHER live member admits exactly once (the joiner's own
        # self cell is pinned, not an event).
        assert {e.observer for e in joined} == set(range(16)) - {3}
        # The old identity's lifecycle stays on the legacy lanes.
        assert any(e.event_type == TraceEventType.REMOVED
                   and e.subject == 3 for e in ev)

    def test_join_mid_suppression_window(self):
        """A tombstone inside its dead_suppress_rounds window must not
        block the join (the ISSUE's interplay requirement), and the
        suppression expiry riding the deadline lane is cleared by the
        admission."""
        p, world = make(16, dead_suppress_rounds=64)
        world = crash_join_world(world, slot=3, crash_at=5, join_at=44)
        st, _ = swim.run(jax.random.key(0), p, world, 90)
        assert (np.asarray(st.status)[:, 3] == records.ALIVE).all()
        assert (np.asarray(st.epoch)[:, 3] == 1).all()
        assert (np.asarray(st.suspect_deadline)[:, 3] == INT32_MAX).all()

    def test_focal_mode_admission(self):
        """Focal layout (K << N): a tracked subject's slot recycles and
        every observer's column admits the new identity."""
        p, world = make(24, k=8, delivery="scatter")
        world = crash_join_world(world, slot=3, crash_at=5, join_at=40)
        st, _ = swim.run(jax.random.key(0), p, world, 120)
        col = 3                                  # subject_ids = arange(8)
        assert (np.asarray(st.status)[:, col] == records.ALIVE).all()
        assert (np.asarray(st.epoch)[:, col] == 1).all()
        assert (np.asarray(st.inc)[:, col] == 0).all()

    def test_delay_ring_rows_cleared_at_join(self):
        """With delay modeling on, messages queued for the OLD occupant
        die with it (the ring rows reset) and the admission still
        propagates.  (A mean delay near the ping budget legitimately
        false-suspects live members in this regime, so the pin is the
        IDENTITY outcome: every cell admitted the new epoch and nobody
        holds the new member DEAD.)"""
        p, world = make(16, delivery="scatter", max_delay_rounds=2,
                        mean_delay_ms=120.0)
        world = crash_join_world(world, slot=3, crash_at=5, join_at=40)
        st, _ = swim.run(jax.random.key(0), p, world, 120)
        col = np.asarray(st.status)[:, 3]
        assert ((col == records.ALIVE) | (col == records.SUSPECT)).all()
        assert (np.asarray(st.epoch)[:, 3] == 1).all()

    def test_joiner_bootstraps_via_seeds(self):
        """With seeds configured, the joiner's cold row relearns the
        cluster through the existing joiner<->seed SYNC round trip —
        the reference's arrival path reused verbatim."""
        p, world = make(16, delivery="scatter")
        world = crash_join_world(world.with_seeds([0, 1]), slot=3,
                                 crash_at=5, join_at=40)
        st, _ = swim.run(jax.random.key(0), p, world, 120)
        row = np.asarray(st.status)[3]
        assert (row == records.ALIVE).sum() >= 14  # knows ~everyone

    def test_naive_reuse_exhibits_resurrection(self):
        """The A/B that motivates the plane (bench.py --churn): on the
        canonical churn-growth storm the guard holds zero join-code
        violations while the epoch-blind control arm provably holds
        dead identities' records as live (NO_RESURRECTION > 0) and
        burns incarnations refuting the ghost's death notices."""
        scen = cs.churn_growth_scenario(seed=3, n=24)
        p = cc.campaign_params(scen, delivery="shift")
        assert p.open_world and p.epoch_guard
        world, spec = scen.build(p)
        assert spec.check_joins
        _, mon, m = cmonitor.run_monitored(
            jax.random.key(0), p, world, spec, scen.horizon)
        v = cmonitor.verdict(mon)
        assert v["green"], v["codes"]

        p_naive = dataclasses.replace(p, epoch_guard=False)
        world_n, spec_n = scen.build(p_naive)
        _, mon_n, m_n = cmonitor.run_monitored(
            jax.random.key(0), p_naive, world_n, spec_n, scen.horizon)
        v_n = cmonitor.verdict(mon_n)
        assert v_n["codes"]["NO_RESURRECTION"]["violations"] > 0
        assert (int(np.asarray(m_n["refutations"]).sum())
                > int(np.asarray(m["refutations"]).sum()))
        # Net-positive growth: the storm ends with more live members
        # than it started with.
        alive0 = int(np.asarray(world.alive_at(0)).sum())
        alive1 = int(np.asarray(world.alive_at(scen.horizon - 1)).sum())
        assert alive1 > alive0


# --------------------------------------------------------------------------
# Layout / run-shape identity with joins ON
# --------------------------------------------------------------------------


class TestLayoutIdentityWithJoins:
    def _world(self, p):
        w = swim.SwimWorld.healthy(p)
        return crash_join_world(w, slot=3, crash_at=5, join_at=26)

    def test_compact_wire16_blocked_fused_identical(self):
        p_wide, _ = make(16, delivery="shift")
        world = self._world(p_wide)
        st_ref, m_ref = swim.run(jax.random.key(1), p_wide, world, 60)
        for name, kw in (("compact", dict(compact_carry=True)),
                         ("wire16", dict(int16_wire=True)),
                         ("blocked", dict(k_block=4)),
                         ("fused", dict(rounds_per_step=4))):
            p = dataclasses.replace(p_wide, **kw)
            st, m = swim.run(jax.random.key(1), p, world, 60)
            assert_tables_equal(st_ref, st, msg=name)
            assert_metrics_equal(m_ref, m, msg=name)

    def test_five_run_shapes_agree(self):
        p, _ = make(16, delivery="shift")
        world = self._world(p)
        key = jax.random.key(1)
        st_run, m_run = swim.run(key, p, world, 60)
        st_tr, _, m_tr = swim.run_traced(key, p, world, 60)
        st_me, _, m_me = swim.run_metered(key, p, world, 60)
        spec = cmonitor.MonitorSpec.passive(p)
        st_mo, _, m_mo = cmonitor.run_monitored(key, p, world, spec, 60)
        st_mm, _, _, m_mm = cmonitor.run_monitored_metered(
            key, p, world, spec, 60)
        for name, st, m in (("traced", st_tr, m_tr),
                            ("metered", st_me, m_me),
                            ("monitored", st_mo, m_mo),
                            ("monitored_metered", st_mm, m_mm)):
            assert_tables_equal(st_run, st, msg=name)
            assert_metrics_equal(m_run, m, msg=name)


# --------------------------------------------------------------------------
# Checkpoint back-compat
# --------------------------------------------------------------------------


class TestCheckpointBackCompat:
    def test_pre_epoch_checkpoint_loads_as_zero_epoch(self, tmp_path):
        from scalecube_cluster_tpu.utils import checkpoint as ckpt

        p, world = make(12, open_world=False)
        st = swim.initial_state(p, world)
        arrays = ckpt.state_to_arrays(st)
        del arrays["state/epoch"]               # a pre-PR-10 checkpoint
        fields = {k[len("state/"):]: np.asarray(v)
                  for k, v in arrays.items()}
        # Plane-off load: the zero-size lane (the lhm pattern).
        loaded = ckpt.state_from_arrays(dict(fields))
        assert loaded.epoch.size == 0
        # Open-world load with params: ZERO-EPOCH — a full lane of
        # zeros in the params' carry dtype, so the resumed run treats
        # every record as the original occupants'.
        p_on, _ = make(12, open_world=True)
        loaded_on = ckpt.state_from_arrays(dict(fields), params=p_on)
        assert loaded_on.epoch.shape == (12, 12)
        assert int(np.asarray(loaded_on.epoch).max()) == 0
        p_c = dataclasses.replace(p_on, compact_carry=True,
                                  delivery="shift")
        loaded_c = ckpt.state_from_arrays(dict(fields), params=p_c)
        assert loaded_c.epoch.dtype == jnp.int16

    def test_epoch_lane_roundtrips(self, tmp_path):
        from scalecube_cluster_tpu.utils import checkpoint as ckpt

        p, world = make(12)
        world = crash_join_world(world, slot=3, crash_at=5, join_at=26)
        st, _ = swim.run(jax.random.key(0), p, world, 40)
        path = str(tmp_path / "ck.npz")
        ckpt.save(path, st, next_round=40)
        loaded, nxt, _, _ = ckpt.load(path)
        assert nxt == 40
        assert_tables_equal(st, loaded, msg="roundtrip")
        # Resume is bit-exact: 40+20 == 60 in one go.
        st_resumed, _ = swim.run(jax.random.key(0), p, world, 20,
                                 state=loaded, start_round=40)
        st_full, _ = swim.run(jax.random.key(0), p, world, 60)
        assert_tables_equal(st_full, st_resumed, msg="resume")


# --------------------------------------------------------------------------
# Sharded pipelined == serial through a real join
# --------------------------------------------------------------------------


def _has_shard_map():
    from scalecube_cluster_tpu.parallel import compat
    return compat.HAS_SHARD_MAP


@pytest.mark.multichip
@pytest.mark.skipif(not _has_shard_map(),
                    reason="jax.shard_map unavailable")
def test_sharded_pipelined_equals_serial_through_join():
    from scalecube_cluster_tpu.parallel import mesh as pmesh

    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    mesh = pmesh.make_mesh(8)
    p, world = make(16, delivery="scatter")
    world = crash_join_world(world, slot=3, crash_at=5, join_at=26)
    key = jax.random.key(0)
    f_ser, m_ser = pmesh.shard_run(key, p, world, 60, mesh,
                                   pipelined=False)
    f_pip, m_pip = pmesh.shard_run(key, p, world, 60, mesh,
                                   pipelined=True)
    assert_tables_equal(f_ser, f_pip, msg="pipelined")
    assert_metrics_equal(m_ser, m_pip, msg="pipelined")
    # And the join actually happened in the sharded run.
    assert (np.asarray(f_ser.epoch)[:, 3] == 1).all()
    assert (np.asarray(f_ser.status)[:, 3] == records.ALIVE).all()


# --------------------------------------------------------------------------
# Oracle ground truth: mid-run Cluster.join parity
# --------------------------------------------------------------------------


def test_oracle_mid_run_join_key_set_parity():
    """A quiesced net-positive churn schedule (two permanent crashes,
    two joins — one recycling a crashed slot, one consuming a pre-dead
    free slot) replayed on the event-driven oracle with genuine mid-run
    ``Cluster.join`` members: the model's ADDED/SUSPECTED/REMOVED key
    sets match per slot over continuously-live observers (JOINED
    normalizes to ADDED — campaign.cross_validate_churn)."""
    n = 12
    params = swim.SwimParams.from_config(cc.campaign_config(),
                                         n_members=n)
    # Quiesced: the old identities' deaths fully mature and go cold
    # before the joins, so both layers reach the same terminal key sets
    # (the cross_validate determinism precondition).
    join_at = 8 + cs.quiesce_bound(params, n)
    horizon = join_at + cs.completeness_bound(params, n) + 16
    scen = cs.Scenario(
        name="oracle-churn-join", n_members=n, horizon=horizon,
        ops=(cs.Crash(3, at_round=8), cs.Crash(5, at_round=0),
             cs.Join(3, at_round=join_at),
             cs.Join(5, at_round=join_at + 2)),
    )
    diff = cc.cross_validate_churn(scen, seed=0)
    assert diff is not None
    assert diff["joins"] == 2 and diff["crashes"] == 2
    assert diff["agree"], diff["slots"]


def test_cross_validate_churn_inexpressible_returns_none():
    n = 12
    scen = cs.Scenario(          # no joins -> not a churn-join replay
        name="nope", n_members=n, horizon=64,
        ops=(cs.Crash(3, at_round=8),))
    assert cc.cross_validate_churn(scen, seed=0) is None
    scen2 = cs.Scenario(         # revive schedules are out of scope
        name="nope2", n_members=n, horizon=64,
        ops=(cs.Crash(3, at_round=8, until_round=20),
             cs.Join(5, at_round=30)))
    assert cc.cross_validate_churn(scen2, seed=0) is None


# --------------------------------------------------------------------------
# Full storm matrix (slow tier)
# --------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("delivery", ["shift", "scatter"])
def test_churn_growth_matrix_guard_green(seed, delivery):
    scen = cs.churn_growth_scenario(seed=seed, n=32)
    p = cc.campaign_params(scen, delivery=delivery)
    world, spec = scen.build(p)
    _, mon, _ = cmonitor.run_monitored(
        jax.random.key(seed), p, world, spec, scen.horizon)
    v = cmonitor.verdict(mon)
    assert v["green"], (scen.repro(), v["codes"])


@pytest.mark.slow
@pytest.mark.parametrize("suppress", [0, 64])
def test_churn_growth_matrix_suppress_interplay(suppress):
    scen = cs.churn_growth_scenario(seed=11, n=32)
    p = cc.campaign_params(scen, delivery="shift",
                           dead_suppress_rounds=suppress)
    world, spec = scen.build(p)
    _, mon, _ = cmonitor.run_monitored(
        jax.random.key(11), p, world, spec, scen.horizon)
    v = cmonitor.verdict(mon)
    assert v["green"], (suppress, v["codes"])
