"""Constant audit: ops/delivery.WIRE_FORMATS is the ONE source of every
wire-saturation constant.

Every clamp site in the tree — the self-refutation bump
(models/swim._merge_and_timers), the WIRE_SATURATION monitor bound
(chaos/monitor), the compact-carry encode clamp (models/swim.
_carry_encode) — derives from the format table via
models/swim._wire_inc_sat.  The grep-proof below tokenizes the whole
package and fails if any evaluated saturation literal (8191, 2047,
2^23-1, ...) reappears in CODE outside ops/delivery.py and records.py
(records.py DEFINES the wide/wire16 key builders the table delegates
to; comments and docstrings may cite the numbers — documentation is
not a clamp site).
"""

import io
import pathlib
import tokenize

import pytest

from scalecube_cluster_tpu.chaos import monitor as chaos_monitor
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.ops import delivery

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.wire

PKG = pathlib.Path(swim.__file__).resolve().parents[1]

# The saturation points of every rung x epoch width, evaluated: any of
# these appearing as a bare literal outside the format table is a
# hand-copied constant waiting to rot.
BANNED_LITERALS = {
    delivery.WIRE16.inc_sat(0),                           # 8191
    delivery.WIRE16.inc_sat(delivery.WIRE16.epoch_bits),  # 2047
    delivery.WIRE24.inc_sat(0),                           # 2^22-1
    delivery.WIRE24.inc_sat(delivery.WIRE24.epoch_bits),  # 2^18-1
    delivery.WIDE.inc_sat(0),                             # 2^29-1
    delivery.WIDE.inc_sat(delivery.WIDE.epoch_bits),      # 2^23-1
}

# The two files allowed to spell the layout out: the format table
# itself, and the records.py key builders it delegates the legacy
# rungs to.
ALLOWED = {"ops/delivery.py", "records.py"}


def test_table_is_the_single_source_of_saturation_literals():
    offenders = []
    for path in sorted(PKG.rglob("*.py")):
        rel = str(path.relative_to(PKG))
        if rel in ALLOWED:
            continue
        toks = tokenize.generate_tokens(
            io.StringIO(path.read_text()).readline)
        for tok in toks:
            if tok.type != tokenize.NUMBER:
                continue
            try:
                value = int(tok.string, 0)
            except ValueError:
                continue
            if value in BANNED_LITERALS:
                offenders.append(f"{rel}:{tok.start[0]}: {tok.line.strip()}")
    assert not offenders, (
        "wire-saturation literals outside ops/delivery.WIRE_FORMATS "
        "(derive from the table via swim._wire_inc_sat instead):\n"
        + "\n".join(offenders)
    )


def test_format_table_layout():
    """The ladder's shape: dead bit / epoch width / word dtype per rung,
    and the saturation arithmetic they imply."""
    assert delivery.WIDE.dead_bit == 30
    assert delivery.WIRE24.dead_bit == 23
    assert delivery.WIRE16.dead_bit == 14
    assert (delivery.WIDE.epoch_bits, delivery.WIRE24.epoch_bits,
            delivery.WIRE16.epoch_bits) == (6, 4, 2)
    assert delivery.WIDE.word_bytes == delivery.WIRE24.word_bytes == 4
    assert delivery.WIRE16.word_bytes == 2
    for fmt in delivery.WIRE_FORMATS.values():
        assert fmt.inc_sat(0) == (1 << (fmt.dead_bit - 1)) - 1
        assert fmt.inc_sat(fmt.epoch_bits) == \
            (1 << (fmt.dead_bit - 1 - fmt.epoch_bits)) - 1
    # The wire24 motivation, in numbers: 16x the wire16+epoch headroom.
    assert delivery.WIRE24.inc_sat(4) == \
        (delivery.WIRE16.inc_sat(2) + 1) * 128 - 1


@pytest.mark.parametrize("kw,expected", [
    (dict(), delivery.WIDE.inc_sat(0)),
    (dict(open_world=True), delivery.WIDE.inc_sat(6)),
    (dict(int16_wire=True), delivery.WIRE16.inc_sat(0)),
    (dict(compact_carry=True), delivery.WIRE16.inc_sat(0)),
    (dict(compact_carry=True, open_world=True), delivery.WIRE16.inc_sat(2)),
    # wire24: the wire field out-carries the int16 STORED table, so the
    # carry dtype ceiling binds — with or without the epoch field.
    (dict(compact_carry=True, wire24=True), (1 << 15) - 1),
    (dict(compact_carry=True, wire24=True, open_world=True), (1 << 15) - 1),
])
def test_wire_inc_sat_derives_from_table(kw, expected):
    params = swim.SwimParams.from_config(fast_config(), n_members=16, **kw)
    assert swim._wire_inc_sat(params) == expected


def test_monitor_bound_follows_the_format(monkeypatch):
    """The WIRE_SATURATION invariant bound is _wire_inc_sat of the
    ACTIVE params — not a per-call literal: a spy on _wire_inc_sat sees
    the monitor consult the table."""
    params = swim.SwimParams.from_config(fast_config(), n_members=16,
                                         compact_carry=True, wire24=True)
    seen = []
    real = swim._wire_inc_sat

    def spy(p):
        seen.append(real(p))
        return real(p)

    monkeypatch.setattr(swim, "_wire_inc_sat", spy)
    world = swim.SwimWorld.healthy(params)
    state = swim.initial_state(params, world)
    chaos_monitor._check_cells(
        chaos_monitor.MonitorSpec.passive(params), params,
        swim.Knobs.from_params(params), 0, state, state, world,
    )
    assert (1 << 15) - 1 in seen
