"""Constant audit: ops/delivery.WIRE_FORMATS is the ONE source of every
wire-saturation constant.

Every clamp site in the tree — the self-refutation bump
(models/swim._merge_and_timers), the WIRE_SATURATION monitor bound
(chaos/monitor), the compact-carry encode clamp (models/swim.
_carry_encode) — derives from the format table via
models/swim._wire_inc_sat.  The grep-proof fails if any evaluated
saturation literal (8191, 2047, 2^23-1, ...) reappears in CODE outside
ops/delivery.py and records.py (records.py DEFINES the wide/wire16 key
builders the table delegates to; comments and docstrings may cite the
numbers — documentation is not a clamp site).  Since PR 14 the scan
itself lives in the swimlint rule engine (analysis/rules.magic_literals,
`python -m scalecube_cluster_tpu.analysis check`); this file keeps the
pins and asserts the rule enforces exactly them.
"""

import pathlib

import pytest

from scalecube_cluster_tpu.analysis import callgraph
from scalecube_cluster_tpu.analysis import rules as lint
from scalecube_cluster_tpu.chaos import monitor as chaos_monitor
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.ops import delivery

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.wire

PKG = pathlib.Path(swim.__file__).resolve().parents[1]

# The saturation points of every rung x epoch width, evaluated: any of
# these appearing as a bare literal outside the format table is a
# hand-copied constant waiting to rot.
BANNED_LITERALS = {
    delivery.WIRE16.inc_sat(0),                           # 8191
    delivery.WIRE16.inc_sat(delivery.WIRE16.epoch_bits),  # 2047
    delivery.WIRE24.inc_sat(0),                           # 2^22-1
    delivery.WIRE24.inc_sat(delivery.WIRE24.epoch_bits),  # 2^18-1
    delivery.WIDE.inc_sat(0),                             # 2^29-1
    delivery.WIDE.inc_sat(delivery.WIDE.epoch_bits),      # 2^23-1
}

# The two files allowed to spell the layout out: the format table
# itself, and the records.py key builders it delegates the legacy
# rungs to.
ALLOWED = {"ops/delivery.py", "records.py"}


def test_table_is_the_single_source_of_saturation_literals():
    """ONE implementation since PR 14: the swimlint magic-literal rule
    (scalecube_cluster_tpu/analysis/rules.py) — this test pins that the
    rule's wire-saturation family carries EXACTLY the banned values and
    allowed files the original PR-13 tokenizer grep-proof enforced, and
    that it holds at HEAD."""
    families = [f for f in lint.default_literal_families()
                if f.name == "wire-saturation"]
    assert len(families) == 1
    fam = families[0]
    # identical pins: same evaluated literals, same owning files
    assert fam.values == frozenset(BANNED_LITERALS)
    assert fam.allowed == frozenset(ALLOWED)
    findings = lint.magic_literals(callgraph.PackageGraph(PKG),
                                   families=[fam])
    findings = [f for f in findings if f.rule == "magic-literal"
                and f.id.startswith("magic-literal:wire-saturation:")]
    assert not findings, (
        "wire-saturation literals outside ops/delivery.WIRE_FORMATS "
        "(derive from the table via swim._wire_inc_sat instead):\n"
        + "\n".join(f"{f.path}:{f.line}: {f.message}" for f in findings)
    )


def test_format_table_layout():
    """The ladder's shape: dead bit / epoch width / word dtype per rung,
    and the saturation arithmetic they imply."""
    assert delivery.WIDE.dead_bit == 30
    assert delivery.WIRE24.dead_bit == 23
    assert delivery.WIRE16.dead_bit == 14
    assert (delivery.WIDE.epoch_bits, delivery.WIRE24.epoch_bits,
            delivery.WIRE16.epoch_bits) == (6, 4, 2)
    assert delivery.WIDE.word_bytes == delivery.WIRE24.word_bytes == 4
    assert delivery.WIRE16.word_bytes == 2
    for fmt in delivery.WIRE_FORMATS.values():
        assert fmt.inc_sat(0) == (1 << (fmt.dead_bit - 1)) - 1
        assert fmt.inc_sat(fmt.epoch_bits) == \
            (1 << (fmt.dead_bit - 1 - fmt.epoch_bits)) - 1
    # The wire24 motivation, in numbers: 16x the wire16+epoch headroom.
    assert delivery.WIRE24.inc_sat(4) == \
        (delivery.WIRE16.inc_sat(2) + 1) * 128 - 1


@pytest.mark.parametrize("kw,expected", [
    (dict(), delivery.WIDE.inc_sat(0)),
    (dict(open_world=True), delivery.WIDE.inc_sat(6)),
    (dict(int16_wire=True), delivery.WIRE16.inc_sat(0)),
    (dict(compact_carry=True), delivery.WIRE16.inc_sat(0)),
    (dict(compact_carry=True, open_world=True), delivery.WIRE16.inc_sat(2)),
    # wire24: the wire field out-carries the int16 STORED table, so the
    # carry dtype ceiling binds — with or without the epoch field.
    (dict(compact_carry=True, wire24=True), (1 << 15) - 1),
    (dict(compact_carry=True, wire24=True, open_world=True), (1 << 15) - 1),
])
def test_wire_inc_sat_derives_from_table(kw, expected):
    params = swim.SwimParams.from_config(fast_config(), n_members=16, **kw)
    assert swim._wire_inc_sat(params) == expected


def test_monitor_bound_follows_the_format(monkeypatch):
    """The WIRE_SATURATION invariant bound is _wire_inc_sat of the
    ACTIVE params — not a per-call literal: a spy on _wire_inc_sat sees
    the monitor consult the table."""
    params = swim.SwimParams.from_config(fast_config(), n_members=16,
                                         compact_carry=True, wire24=True)
    seen = []
    real = swim._wire_inc_sat

    def spy(p):
        seen.append(real(p))
        return real(p)

    monkeypatch.setattr(swim, "_wire_inc_sat", spy)
    world = swim.SwimWorld.healthy(params)
    state = swim.initial_state(params, world)
    chaos_monitor._check_cells(
        chaos_monitor.MonitorSpec.passive(params), params,
        swim.Knobs.from_params(params), 0, state, state, world,
    )
    assert (1 << 15) - 1 in seen
