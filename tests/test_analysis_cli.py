"""swimlint CLI JSON contract: exit codes (0 clean / 1 findings /
2 input error), artifact schema, and the baseline-file contract
(mandatory justifications, stale-row findings).
"""

import json
import subprocess
import sys

import pytest

from scalecube_cluster_tpu.analysis.__main__ import main

from tests.analysis_helpers import MINI_SWIM, write_tree

pytestmark = pytest.mark.lint

ENTRY_NAMES = ["run", "run_traced", "run_metered", "run_monitored",
               "run_monitored_metered", "shard_run", "shard_run_metered"]
BODY_NAMES = ["scatter", "shift", "k_block", "pipelined"]


def empty_baseline(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"suppressions": []}))
    return str(p)


def broken_tree(tmp_path):
    """Mini package with one planted plane-matrix finding."""
    swim_src = MINI_SWIM.replace(
        "def _tick_shift_blocked(state, params):\n"
        "    return state + params.sync_interval",
        "def _tick_shift_blocked(state, params):\n"
        "    return state + 0",
    )
    return str(write_tree(tmp_path, {"models/swim.py": swim_src}))


PLANTED_ID = "plane-matrix:sync_interval:body:k_block"


class TestExitCodes:
    def test_check_clean_at_head_is_0(self, tmp_path):
        art = tmp_path / "a.json"
        assert main(["check", "--no-compile",
                     "--artifact", str(art)]) == 0

    def test_check_findings_is_1_report_is_0(self, tmp_path):
        root = broken_tree(tmp_path)
        base = empty_baseline(tmp_path)
        common = ["--root", root, "--baseline", base, "--artifact", ""]
        assert main(["check"] + common) == 1
        assert main(["report"] + common) == 0

    def test_bad_root_is_2(self, tmp_path):
        assert main(["check", "--root", str(tmp_path / "nope"),
                     "--artifact", ""]) == 2

    def test_foreign_root_defaults_to_no_baseline(self, tmp_path):
        """A clean copied/fixture tree without --baseline must exit 0 —
        the installed package's suppressions would all read as stale
        there (engine.run_analysis defaults the baseline only for the
        installed root)."""
        root = str(write_tree(tmp_path, {}))
        assert main(["check", "--root", root, "--artifact", ""]) == 0

    def test_parseable_but_foreign_package_is_2(self, tmp_path):
        """A tree of valid .py files that is NOT this package (no
        models/swim.py / SwimParams) is an input error, not a crash."""
        root = str(write_tree(tmp_path, {"utils/other.py": "X = 1\n"},
                              base=False))
        assert main(["check", "--root", root, "--artifact", ""]) == 2

    @pytest.mark.parametrize("doc", [
        "{not json",
        json.dumps({"suppressions": [{"id": "x"}]}),             # no reason
        json.dumps({"suppressions": [{"id": "x",
                                      "justification": "  "}]}),  # blank
        json.dumps({"wrong_key": []}),
        json.dumps({"suppressions": [{"id": "x", "justification": "ok"},
                                     {"id": "x",
                                      "justification": "dup"}]}),
    ])
    def test_malformed_baseline_is_2(self, tmp_path, doc):
        bad = tmp_path / "bad_baseline.json"
        bad.write_text(doc)
        assert main(["check", "--no-compile", "--artifact", "",
                     "--baseline", str(bad)]) == 2


class TestBaselineContract:
    def test_justified_suppression_makes_check_clean(self, tmp_path):
        root = broken_tree(tmp_path)
        base = tmp_path / "b.json"
        base.write_text(json.dumps({"suppressions": [
            {"id": PLANTED_ID, "justification": "planted by the test"},
        ]}))
        art = tmp_path / "a.json"
        assert main(["check", "--root", root, "--baseline", str(base),
                     "--artifact", str(art)]) == 0
        doc = json.loads(art.read_text())
        assert doc["findings_total"] == 0
        assert doc["suppressed_total"] == 1
        assert doc["suppressed"][0]["id"] == PLANTED_ID
        assert doc["suppressed"][0]["justification"] == \
            "planted by the test"

    def test_suppression_cannot_absorb_a_second_occurrence(
            self, tmp_path):
        """Same-id findings collapse with an ``:x<k>`` occurrence
        suffix, so a baseline row for ONE justified literal cannot
        silently mask a SECOND hand-copied one in the same file: the
        old row goes stale (a finding) and the new ``:x2`` id is
        unsuppressed."""
        from scalecube_cluster_tpu.ops import delivery

        cap = delivery.WIRE16.inc_sat(0)  # 8191
        one_id = f"magic-literal:wire-saturation:models/caps.py:{cap}"
        base = tmp_path / "b.json"
        base.write_text(json.dumps({"suppressions": [
            {"id": one_id, "justification": "the one known site"},
        ]}))
        root = str(write_tree(tmp_path, {
            "models/caps.py": f"CAP = {cap}\n"}))
        assert main(["check", "--root", root, "--baseline", str(base),
                     "--artifact", ""]) == 0
        root2 = str(write_tree(tmp_path / "two", {
            "models/caps.py": f"CAP = {cap}\nCAP2 = {cap}\n"}))
        art = tmp_path / "a.json"
        assert main(["check", "--root", root2, "--baseline", str(base),
                     "--artifact", str(art)]) == 1
        got = {f["id"] for f in json.loads(art.read_text())["findings"]}
        assert got == {f"{one_id}:x2", f"baseline:stale:{one_id}"}

    def test_stale_suppression_is_a_finding(self, tmp_path):
        root = str(write_tree(tmp_path, {}))  # clean mini tree
        base = tmp_path / "b.json"
        base.write_text(json.dumps({"suppressions": [
            {"id": PLANTED_ID, "justification": "no longer true"},
        ]}))
        art = tmp_path / "a.json"
        assert main(["check", "--root", root, "--baseline", str(base),
                     "--artifact", str(art)]) == 1
        doc = json.loads(art.read_text())
        assert [f["id"] for f in doc["findings"]] == \
            [f"baseline:stale:{PLANTED_ID}"]


class TestArtifactSchema:
    def test_artifact_contract(self, tmp_path):
        art = tmp_path / "static_analysis.json"
        assert main(["check", "--no-compile",
                     "--artifact", str(art)]) == 0
        doc = json.loads(art.read_text())
        assert doc["schema"] == "swimlint/1"
        assert doc["metric"] == "static_analysis"
        assert doc["ok"] is True
        assert doc["findings_total"] == 0 and doc["findings"] == []
        assert doc["entry_points"] == ENTRY_NAMES
        assert doc["tick_bodies"] == BODY_NAMES
        # the knob rows are extracted from SwimParams, not curated
        for knob in ("sync_interval", "lhm_max", "dead_suppress_rounds",
                     "open_world", "fused_wire", "rounds_per_step"):
            assert knob in doc["fields"]
        # matrix cells: {count, sites} with rel:line site strings, and
        # a threaded knob reaches every run shape
        row = doc["matrix"]["entries"]["sync_interval"]
        for entry in ENTRY_NAMES:
            cell = row[entry]
            assert cell["count"] >= 1
            assert all(":" in s for s in cell["sites"])
            assert len(cell["sites"]) <= cell["count"]
        # suppressions carry their justification into the artifact
        assert doc["suppressed_total"] == len(doc["suppressed"])
        assert all(f.get("justification")
                   for f in doc["suppressed"])
        # AST-only run records why the compile audits did not run
        assert doc["compile_audit"] == {"skipped": "disabled"}

    def test_foreign_root_never_writes_the_default_artifact(
            self, tmp_path, monkeypatch):
        """A mutation-debug run on a copied tree must not clobber the
        committed artifacts/static_analysis.json: the default artifact
        path applies only to the installed package."""
        monkeypatch.chdir(tmp_path)
        root = broken_tree(tmp_path)
        base = empty_baseline(tmp_path)
        assert main(["check", "--root", root,
                     "--baseline", base]) == 1
        assert not (tmp_path / "artifacts").exists()

    def test_no_compile_never_writes_the_default_artifact(
            self, tmp_path, monkeypatch):
        """The AST-only fast pass must not replace the committed
        artifact's compile-audit blocks with a skipped note — only a
        FULL run on the installed tree writes the default path."""
        monkeypatch.chdir(tmp_path)
        assert main(["check", "--no-compile"]) == 0
        assert not (tmp_path / "artifacts").exists()

    def test_json_flag_prints_the_artifact(self, tmp_path, capsys):
        assert main(["check", "--no-compile", "--artifact", "",
                     "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["schema"] == "swimlint/1"
        assert doc["findings_total"] == 0


def test_module_entry_point():
    """``python -m scalecube_cluster_tpu.analysis`` is wired
    (the -m path the README documents)."""
    proc = subprocess.run(
        [sys.executable, "-m", "scalecube_cluster_tpu.analysis",
         "check", "--no-compile", "--artifact", ""],
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr
    assert "findings: none" in proc.stdout
