"""On-disk checkpoint/resume of the scan carry (utils/checkpoint.py).

The reference has no persistence (SURVEY.md §5.4); this is the subsystem a
10k-round TPU run needs: kill the driver mid-run, restart, and the resumed
trace must be bit-identical to an unbroken run (possible because every draw
is a pure function of (key, round) — ops/prng.py).
"""

import os

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.utils import checkpoint

from tests.test_swim_model import make


def test_save_load_roundtrip(tmp_path):
    params, world = make(12, loss=0.1)
    key = jax.random.key(3)
    state, _ = swim.run(key, params, world, 20)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, state, next_round=20, key=key, meta={"n": 12})

    state2, next_round, key2, meta = checkpoint.load(path)
    assert next_round == 20
    assert meta == {"n": 12}
    np.testing.assert_array_equal(np.asarray(state.status), np.asarray(state2.status))
    np.testing.assert_array_equal(np.asarray(state.inc), np.asarray(state2.inc))
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(key)), np.asarray(jax.random.key_data(key2))
    )


def test_kill_and_resume_matches_unbroken_run(tmp_path):
    """Simulated preemption: run chunks 0-2, 'kill', re-invoke — the driver
    resumes from disk and the final state equals one unbroken run."""
    params, world = make(12, loss=0.1)
    world = world.with_crash(4, at_round=10)
    key = jax.random.key(4)
    n_rounds, chunk = 60, 20
    path = str(tmp_path / "ckpt.npz")

    final_unbroken, _ = swim.run(key, params, world, n_rounds)

    # First driver invocation dies after 2 chunks (40 rounds).
    calls = {"n": 0}

    def dying_run(*args, **kwargs):
        if calls["n"] == 2:
            raise KeyboardInterrupt("simulated preemption")
        calls["n"] += 1
        return swim.run(*args, **kwargs)

    with pytest.raises(KeyboardInterrupt):
        checkpoint.run_checkpointed(
            dying_run, key, params, world, n_rounds, path, chunk=chunk
        )
    assert os.path.exists(path)
    _, saved_round, _, _ = checkpoint.load(path)
    assert saved_round == 40

    # Second invocation resumes from disk and completes; metrics from the
    # pre-kill chunks are reloaded so the returned traces are complete.
    final_resumed, chunks = checkpoint.run_checkpointed(
        swim.run, key, params, world, n_rounds, path, chunk=chunk
    )
    assert len(chunks) == 3  # 2 reloaded + 1 re-run
    full_alive = np.concatenate([np.asarray(c["alive"]) for c in chunks])
    assert full_alive.shape[0] == n_rounds
    np.testing.assert_array_equal(
        np.asarray(final_unbroken.status), np.asarray(final_resumed.status)
    )
    np.testing.assert_array_equal(
        np.asarray(final_unbroken.inc), np.asarray(final_resumed.inc)
    )


def test_resume_meta_mismatch_refuses(tmp_path):
    params, world = make(8)
    key = jax.random.key(5)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.run_checkpointed(
        swim.run, key, params, world, 10, path, chunk=5, meta={"cfg": "a"}
    )
    with pytest.raises(ValueError, match="meta mismatch"):
        checkpoint.run_checkpointed(
            swim.run, key, params, world, 20, path, chunk=5, meta={"cfg": "b"}
        )


def test_atomic_write_leaves_no_tmp(tmp_path):
    params, world = make(8)
    state = swim.initial_state(params, world)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, state, next_round=0)
    checkpoint.save(path, state, next_round=5)  # overwrite in place
    leftovers = [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]
    assert leftovers == []
    _, r, _, _ = checkpoint.load(path)
    assert r == 5
