"""On-disk checkpoint/resume of the scan carry (utils/checkpoint.py).

The reference has no persistence (SURVEY.md §5.4); this is the subsystem a
10k-round TPU run needs: kill the driver mid-run, restart, and the resumed
trace must be bit-identical to an unbroken run (possible because every draw
is a pure function of (key, round) — ops/prng.py).
"""

import os

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.utils import checkpoint

from tests.test_swim_model import make


def test_save_load_roundtrip(tmp_path):
    params, world = make(12, loss=0.1)
    key = jax.random.key(3)
    state, _ = swim.run(key, params, world, 20)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, state, next_round=20, key=key, meta={"n": 12})

    state2, next_round, key2, meta = checkpoint.load(path)
    assert next_round == 20
    assert meta == {"n": 12}
    np.testing.assert_array_equal(np.asarray(state.status), np.asarray(state2.status))
    np.testing.assert_array_equal(np.asarray(state.inc), np.asarray(state2.inc))
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(key)), np.asarray(jax.random.key_data(key2))
    )


def test_kill_and_resume_matches_unbroken_run(tmp_path):
    """Simulated preemption: run chunks 0-2, 'kill', re-invoke — the driver
    resumes from disk and the final state equals one unbroken run."""
    params, world = make(12, loss=0.1)
    world = world.with_crash(4, at_round=10)
    key = jax.random.key(4)
    n_rounds, chunk = 60, 20
    path = str(tmp_path / "ckpt.npz")

    final_unbroken, _ = swim.run(key, params, world, n_rounds)

    # First driver invocation dies after 2 chunks (40 rounds).
    calls = {"n": 0}

    def dying_run(*args, **kwargs):
        if calls["n"] == 2:
            raise KeyboardInterrupt("simulated preemption")
        calls["n"] += 1
        return swim.run(*args, **kwargs)

    with pytest.raises(KeyboardInterrupt):
        checkpoint.run_checkpointed(
            dying_run, key, params, world, n_rounds, path, chunk=chunk
        )
    assert os.path.exists(path)
    _, saved_round, _, _ = checkpoint.load(path)
    assert saved_round == 40

    # Second invocation resumes from disk and completes; metrics from the
    # pre-kill chunks are reloaded so the returned traces are complete.
    final_resumed, chunks = checkpoint.run_checkpointed(
        swim.run, key, params, world, n_rounds, path, chunk=chunk
    )
    assert len(chunks) == 3  # 2 reloaded + 1 re-run
    full_alive = np.concatenate([np.asarray(c["alive"]) for c in chunks])
    assert full_alive.shape[0] == n_rounds
    np.testing.assert_array_equal(
        np.asarray(final_unbroken.status), np.asarray(final_resumed.status)
    )
    np.testing.assert_array_equal(
        np.asarray(final_unbroken.inc), np.asarray(final_resumed.inc)
    )


def test_resume_meta_mismatch_refuses(tmp_path):
    params, world = make(8)
    key = jax.random.key(5)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.run_checkpointed(
        swim.run, key, params, world, 10, path, chunk=5, meta={"cfg": "a"}
    )
    with pytest.raises(ValueError, match="meta mismatch"):
        checkpoint.run_checkpointed(
            swim.run, key, params, world, 20, path, chunk=5, meta={"cfg": "b"}
        )


def test_resume_with_different_chunk_reloads_all_traces(tmp_path):
    """Re-chunking an interrupted run is safe: trace boundaries are
    discovered from disk, so the reloaded list still covers every round."""
    params, world = make(8)
    key = jax.random.key(6)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.run_checkpointed(
        swim.run, key, params, world, 10, path, chunk=5
    )
    final, chunks = checkpoint.run_checkpointed(
        swim.run, key, params, world, 22, path, chunk=4
    )
    total = sum(len(np.asarray(c["alive"])) for c in chunks)
    assert total == 22  # 5+5 reloaded, then 4+4+4 re-chunked
    unbroken, _ = swim.run(key, params, world, 22)
    np.testing.assert_array_equal(
        np.asarray(unbroken.status), np.asarray(final.status)
    )


def test_json_lossy_meta_resumes(tmp_path):
    """JSON-lossy meta values (tuples, int keys) must not spuriously refuse
    a legitimate resume: both sides normalize through a JSON round-trip."""
    params, world = make(8)
    key = jax.random.key(7)
    path = str(tmp_path / "ckpt.npz")
    meta = {"shape": (8, 4), "knobs": {1: "a"}}
    checkpoint.run_checkpointed(
        swim.run, key, params, world, 10, path, chunk=5, meta=meta
    )
    _, chunks = checkpoint.run_checkpointed(
        swim.run, key, params, world, 20, path, chunk=5, meta=meta
    )
    assert len(chunks) == 4  # 2 reloaded + 2 run


def test_extension_past_nonaligned_end_reloads_all_traces(tmp_path):
    """A run whose n_rounds is not a multiple of chunk writes a short final
    chunk; extending and resuming must still reload every trace file (the
    boundaries are discovered from disk, not assumed grid-aligned)."""
    params, world = make(8)
    key = jax.random.key(8)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.run_checkpointed(swim.run, key, params, world, 12, path, chunk=5)
    checkpoint.run_checkpointed(swim.run, key, params, world, 20, path, chunk=5)
    _, chunks = checkpoint.run_checkpointed(
        swim.run, key, params, world, 20, path, chunk=5
    )
    total = sum(len(np.asarray(c["alive"])) for c in chunks)
    assert total == 20  # rounds [0, 20) fully covered: 5+5+2+5+3


def test_interior_trace_hole_raises(tmp_path):
    """An out-of-band deletion of a mid-prefix trace file must raise on
    resume — returning a list with a silent gap would misalign every
    round-indexed consumer."""
    params, world = make(8)
    key = jax.random.key(12)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.run_checkpointed(swim.run, key, params, world, 15, path, chunk=5)
    os.unlink(checkpoint._metrics_path(path, 10))
    with pytest.raises(ValueError, match="deleted out-of-band"):
        checkpoint.run_checkpointed(
            swim.run, key, params, world, 20, path, chunk=5
        )


def test_missing_suffix_trace_raises(tmp_path):
    """Deleting the trace that ends at the checkpoint cursor must also
    raise — a suffix gap misaligns consumers just like an interior one."""
    params, world = make(8)
    key = jax.random.key(13)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.run_checkpointed(swim.run, key, params, world, 15, path, chunk=5)
    os.unlink(checkpoint._metrics_path(path, 15))
    with pytest.raises(ValueError, match="deleted out-of-band"):
        checkpoint.run_checkpointed(
            swim.run, key, params, world, 20, path, chunk=5
        )


def test_orphan_trace_beyond_cursor_is_rewritten(tmp_path):
    """A preemption between the trace write and the checkpoint write leaves
    an orphan trace past the cursor; resume must discard it and re-run the
    chunk (bit-reproducible), not reload the orphan."""
    params, world = make(8)
    key = jax.random.key(9)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.run_checkpointed(swim.run, key, params, world, 10, path, chunk=5)
    checkpoint._atomic_savez(
        checkpoint._metrics_path(path, 15), {"alive": np.zeros((5, 1))}
    )
    _, chunks = checkpoint.run_checkpointed(
        swim.run, key, params, world, 15, path, chunk=5
    )
    assert len(chunks) == 3
    assert np.asarray(chunks[-1]["alive"]).sum() > 0  # re-run, not the fake


def test_legacy_checkpoint_resumes_through_composed_runner(tmp_path):
    """A checkpoint written BEFORE the lifeguard/open-world/user-gossip
    plane lanes existed (its arrays lack ``lhm``/``epoch``/``g_*``)
    resumes through the composed full-stack runner bit-identically:
    the missing plane slices load zero-size (the PR-9/PR-10 rule), and
    the composed carry is the same ``SwimState`` the checkpoint format
    has always stored."""
    from scalecube_cluster_tpu.chaos import monitor as cmonitor
    from scalecube_cluster_tpu.models import compose

    params, world = make(12, loss=0.1)
    world = world.with_crash(4, at_round=10)
    key = jax.random.key(17)
    spec = cmonitor.MonitorSpec.passive(params)
    unbroken, _, _ = compose.run_composed(key, params, world, 40,
                                          monitor_spec=spec)

    mid, _, _ = compose.run_composed(key, params, world, 20,
                                     monitor_spec=spec)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, mid, next_round=20, key=key)
    # Strip the plane lanes to forge the pre-plane checkpoint layout.
    with np.load(path) as z:
        arrays = {name: z[name] for name in z.files
                  if not name.startswith(("state/lhm", "state/epoch",
                                          "state/g_"))}
    checkpoint._atomic_savez(path, arrays)

    state2, next_round, key2, _ = checkpoint.load(path)
    assert next_round == 20
    assert state2.lhm.shape == (0,) and state2.epoch.shape == (12, 0)
    resumed, _, _ = compose.run_composed(key2, params, world, 20,
                                         monitor_spec=spec, state=state2,
                                         start_round=20)
    np.testing.assert_array_equal(np.asarray(unbroken.status),
                                  np.asarray(resumed.status))
    np.testing.assert_array_equal(np.asarray(unbroken.inc),
                                  np.asarray(resumed.inc))


def test_run_checkpointed_drives_the_composed_runner(tmp_path):
    """``run_checkpointed`` (the simulated-preemption driver) accepts a
    composed-runner run_fn: kill after two chunks, relaunch, and the
    resumed final state equals one unbroken composed run — the
    kill/resume smoke for the composed scan."""
    from scalecube_cluster_tpu.models import compose

    params, world = make(12, loss=0.1)
    world = world.with_crash(4, at_round=10)
    key = jax.random.key(19)

    def composed_run(key, params, world, n_rounds, state=None,
                     start_round=0):
        final, _, metrics = compose.run_composed(
            key, params, world, n_rounds, with_trace=False,
            with_monitor=False, state=state, start_round=start_round)
        return final, metrics

    unbroken, _ = composed_run(key, params, world, 60)

    calls = {"n": 0}

    def dying_run(*args, **kwargs):
        if calls["n"] == 2:
            raise KeyboardInterrupt("simulated preemption")
        calls["n"] += 1
        return composed_run(*args, **kwargs)

    path = str(tmp_path / "ckpt.npz")
    with pytest.raises(KeyboardInterrupt):
        checkpoint.run_checkpointed(
            dying_run, key, params, world, 60, path, chunk=20
        )
    final, chunks = checkpoint.run_checkpointed(
        composed_run, key, params, world, 60, path, chunk=20
    )
    assert len(chunks) == 3
    np.testing.assert_array_equal(np.asarray(unbroken.status),
                                  np.asarray(final.status))


def test_atomic_write_leaves_no_tmp(tmp_path):
    params, world = make(8)
    state = swim.initial_state(params, world)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, state, next_round=0)
    checkpoint.save(path, state, next_round=5)  # overwrite in place
    leftovers = [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]
    assert leftovers == []
    _, r, _, _ = checkpoint.load(path)
    assert r == 5
