"""K-tiled shift tick (SwimParams.k_block) — bit-identity + validation.

The blocked body exists to move the full-view single-chip capacity
ceiling (its [N, Kb] transients replace the unblocked body's six [N, K]
channel temps — SwimParams.k_block docstring, measured in
experiments/fullview_ceiling.py).  Its correctness contract is total:
same PRNG draws, same delivery, same merges — every metric and every
state field bit-identical to the unblocked shift tick, in both carry
layouts, with faults, leaves, link rules, and user gossip co-running.
"""

import dataclasses

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config


def run_pair_blocked(n, rounds, kb, world_fn=None, seed=0, **overrides):
    out = []
    for k_block in (0, kb):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, delivery="shift",
            k_block=k_block, **overrides,
        )
        world = swim.SwimWorld.healthy(params)
        if world_fn is not None:
            world = world_fn(world)
        state, metrics = swim.run(jax.random.key(seed), params, world,
                                  rounds)
        out.append((state, metrics))
    return out


SCENARIOS = {
    "crash_revive": lambda w: w.with_crash(3, at_round=5, until_round=60),
    "leave": lambda w: w.with_leave(7, at_round=12),
    "link_block": lambda w: w.with_block(1, (0, 48), until_round=50),
    "partition": lambda w: w.with_partition_schedule(
        np.r_[np.zeros(24), np.ones(24)].astype(np.int8), phase_rounds=30
    ),
}


@pytest.mark.parametrize("compact", [False, True])
@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_blocked_trace_identical(compact, scenario):
    (s0, m0), (sb, mb) = run_pair_blocked(
        48, 100, kb=16, world_fn=SCENARIOS[scenario],
        loss_probability=0.2, compact_carry=compact, seed=3,
    )
    for name in m0:
        np.testing.assert_array_equal(
            np.asarray(m0[name]), np.asarray(mb[name]),
            err_msg=f"{scenario}/compact={compact}: metric {name}",
        )
    for fld in ("status", "inc", "spread_until", "suspect_deadline",
                "self_inc"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s0, fld)), np.asarray(getattr(sb, fld)),
            err_msg=f"{scenario}/compact={compact}: state {fld}",
        )


@pytest.mark.parametrize("per_subject", [False, True])
def test_blocked_metrics_both_aggregations(per_subject):
    (s0, m0), (sb, mb) = run_pair_blocked(
        32, 80, kb=8, world_fn=lambda w: w.with_crash(5, at_round=4),
        loss_probability=0.3, per_subject_metrics=per_subject, seed=1,
    )
    for name in m0:
        np.testing.assert_array_equal(
            np.asarray(m0[name]), np.asarray(mb[name]), err_msg=name
        )


def test_blocked_with_user_gossip_identical():
    (s0, m0), (sb, mb) = run_pair_blocked(
        32, 60, kb=8, seed=1, n_user_gossips=2,
        world_fn=lambda w: (w.with_crash(5, at_round=3)
                            .with_spread(0, 1, 0).with_spread(1, 20, 10)),
    )
    for name in m0:
        np.testing.assert_array_equal(
            np.asarray(m0[name]), np.asarray(mb[name]), err_msg=name
        )
    np.testing.assert_array_equal(np.asarray(s0.g_infected),
                                  np.asarray(sb.g_infected))


def test_blocked_checkpoint_resume():
    """Resume mid-run in blocked mode stays bit-exact (the carry never
    leaves the stored layout)."""
    params = swim.SwimParams.from_config(
        fast_config(), n_members=32, delivery="shift", k_block=8,
        compact_carry=True, loss_probability=0.1,
    )
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=5)
    key = jax.random.key(0)
    s_full, _ = swim.run(key, params, world, 60)
    s_half, _ = swim.run(key, params, world, 30)
    s_res, _ = swim.run(key, params, world, 30, state=s_half,
                        start_round=30)
    for fld in ("status", "inc", "spread_until", "suspect_deadline",
                "self_inc"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_full, fld)),
            np.asarray(getattr(s_res, fld)), err_msg=fld,
        )


def test_blocked_validation():
    base = swim.SwimParams.from_config(fast_config(), n_members=32,
                                       delivery="shift")
    with pytest.raises(ValueError, match="divide"):
        dataclasses.replace(base, k_block=7)
    with pytest.raises(ValueError, match="full-view"):
        swim.SwimParams.from_config(fast_config(), n_members=32,
                                    n_subjects=8, k_block=4)
    with pytest.raises(ValueError, match="full-view"):
        dataclasses.replace(base, delivery="scatter", k_block=8)
    with pytest.raises(ValueError, match="capacity"):
        dataclasses.replace(base, k_block=8, max_delay_rounds=2)
    # Seed-gated contacts are rejected at trace time.
    params = dataclasses.replace(base, k_block=8)
    world = swim.SwimWorld.healthy(params).with_seeds([0])
    state = swim.initial_state(params, world)
    with pytest.raises(NotImplementedError, match="seed-gated"):
        swim.swim_tick(state, 0, jax.random.key(0), params, world)
