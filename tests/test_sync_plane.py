"""The SYNC anti-entropy plane (models/sync.py + SwimParams.sync_interval).

Three contracts, each pinned across carry layouts and run shapes:

  1. *off = bit-identical*: ``sync_interval=0`` (the default) compiles
     the plane out — the tick's draws, tables, and metrics tree are
     exactly the plane-less program's;
  2. *on + converged table = semantic no-op*: on a healthy warm world
     the exchange delivers keys equal to the stored keys, the strict
     merge gate accepts nothing, and the tables stay bit-identical to
     the plane-off run (only the ``messages_anti_entropy`` counter is
     new) — enabling the repair plane costs no protocol perturbation;
  3. *quiesced heal converges; gossip-only does not*: after a split
     long enough for tombstones to go cold (chaos/scenarios.
     quiesce_bound), the plane's exchange reopens the stale tombstones
     and the tables re-converge, while the gossip-only control stays
     divergent forever — the acceptance claim ``bench.py --sync``
     measures.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.models import sync as sync_plane

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.sync

STATE_FIELDS = ("status", "inc", "spread_until", "suspect_deadline",
                "self_inc")


def _assert_states_equal(a, b, fields=STATE_FIELDS):
    for f in fields:
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f))), f


def _heal_world(params, n, phase, n_phases=4):
    """One split phase of ``phase`` rounds over contiguous halves, then
    healed for the rest of the schedule."""
    world = swim.SwimWorld.healthy(params)
    part = np.zeros((n_phases, n), np.int8)
    part[0, : n // 2] = 1
    return world.with_partition_schedule(part, phase)


# --------------------------------------------------------------------------
# 1 + 2: disabled default == baseline; enabled on warm state == no-op
# --------------------------------------------------------------------------


def test_sync_interval_defaults_off():
    params = swim.SwimParams.from_config(fast_config(), n_members=8)
    assert params.sync_interval == 0
    explicit = dataclasses.replace(params, sync_interval=0)
    assert explicit == params          # same static params, same program


@pytest.mark.parametrize("delivery,subjects,layout", [
    ("scatter", None, "wide"),
    ("shift", None, "wide"),
    ("shift", 8, "wide"),              # focal
    ("shift", None, "compact"),
    ("scatter", None, "wire16"),
])
def test_plane_on_warm_world_is_table_noop(delivery, subjects, layout):
    """On a healthy converged table the exchange accepts nothing: the
    plane-on run's carry is bit-identical to plane-off, and the metrics
    tree differs ONLY by the new counter.  This is the strong form of
    the off-switch pin: the plane's draws come from dedicated key folds,
    so enabling it perturbs no existing stream."""
    n = 24
    p_off = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=subjects,
        delivery=delivery,
        compact_carry=layout == "compact", int16_wire=layout == "wire16",
    )
    p_on = dataclasses.replace(p_off, sync_interval=4)
    world = swim.SwimWorld.healthy(p_off)
    s_off, m_off = swim.run(jax.random.key(0), p_off, world, 20)
    s_on, m_on = swim.run(jax.random.key(0), p_on, world, 20)
    _assert_states_equal(s_off, s_on)
    assert "messages_anti_entropy" not in m_off
    assert set(m_on) == set(m_off) | {"messages_anti_entropy"}
    for k in m_off:
        assert np.array_equal(np.asarray(m_off[k]), np.asarray(m_on[k])), k


def test_exchange_counter_cadence():
    """2 messages per live member, exactly on exchange rounds."""
    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", sync_interval=5)
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=0)
    _, m = swim.run(jax.random.key(1), params, world, 12)
    ae = np.asarray(m["messages_anti_entropy"])
    expect = np.where(np.arange(12) % 5 == 0, 2 * (n - 1), 0)
    assert np.array_equal(ae, expect)


def test_param_validation():
    params = swim.SwimParams.from_config(fast_config(), n_members=8)
    with pytest.raises(ValueError, match="sync_interval"):
        dataclasses.replace(params, sync_interval=-1)
    solo = swim.SwimParams.from_config(fast_config(), n_members=1)
    with pytest.raises(ValueError, match="n_members >= 2"):
        dataclasses.replace(solo, sync_interval=4)


# --------------------------------------------------------------------------
# 3: the heal claim
# --------------------------------------------------------------------------


def _heal_setup(delivery, n=24, sync_interval=8, **overrides):
    from scalecube_cluster_tpu.chaos import scenarios as cs

    p_off = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery=delivery, sync_every=0,
        **overrides)
    p_on = dataclasses.replace(p_off, sync_interval=sync_interval)
    phase = -(-cs.quiesce_bound(p_on, n) // 16) * 16
    rounds = phase + cs.post_heal_agreement_bound(p_on, n)
    return p_off, p_on, _heal_world(p_on, n, phase), rounds


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_quiesced_heal_converges_only_with_plane(delivery):
    p_off, p_on, world, rounds = _heal_setup(delivery)
    s_off, _ = swim.run(jax.random.key(1), p_off, world, rounds)
    s_on, _ = swim.run(jax.random.key(1), p_on, world, rounds)
    assert int(sync_plane.divergence_probe(s_off, p_off, world,
                                           rounds)) > 0
    assert int(sync_plane.divergence_probe(s_on, p_on, world,
                                           rounds)) == 0
    # The healed table is accurate, not merely consistent: every member
    # is ALIVE everywhere again.
    assert (np.asarray(s_on.status) == 0).all()
    # And the repair went through the stored-DEAD-reopens-for-ALIVE
    # merge gate, not refutation storms: nobody burned incarnations.
    assert int(np.asarray(s_on.self_inc).max()) == 0


def test_blocked_and_compact_layouts_identical_with_plane():
    """Blocked tick bit-identity + compact-carry trace-identity with
    the plane on, through the split's tombstoning (identity pins need
    the exchange ACTIVE, not a full convergence horizon — the heal
    claim itself is pinned above)."""
    n = 32
    _, p_on, world, full_rounds = _heal_setup("shift", n=n)
    rounds = min(full_rounds, 180)       # split + first exchanges
    s_ref, m_ref = swim.run(jax.random.key(3), p_on, world, rounds)
    p_blk = dataclasses.replace(p_on, k_block=8)
    s_blk, m_blk = swim.run(jax.random.key(3), p_blk, world, rounds)
    _assert_states_equal(s_ref, s_blk)
    assert np.array_equal(np.asarray(m_ref["messages_anti_entropy"]),
                          np.asarray(m_blk["messages_anti_entropy"]))
    p_c = dataclasses.replace(p_on, compact_carry=True)
    s_c, _ = swim.run(jax.random.key(3), p_c, world, rounds)
    dec = swim._carry_decode(s_c, jnp.int32(rounds))
    assert np.array_equal(np.asarray(s_ref.status), np.asarray(dec.status))
    assert np.array_equal(np.asarray(s_ref.inc), np.asarray(dec.inc))


def test_focal_heal_converges():
    """Focal mode (the 1M bench shape): subjects spread over both
    halves; the exchange repairs the focal columns."""
    n, k = 64, 8
    from scalecube_cluster_tpu.chaos import scenarios as cs

    p_off = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=k, delivery="shift",
        sync_every=0)
    p_on = dataclasses.replace(p_off, sync_interval=8)
    phase = -(-cs.quiesce_bound(p_on, n) // 16) * 16
    rounds = phase + cs.post_heal_agreement_bound(p_on, n)
    subject_ids = jnp.arange(k, dtype=jnp.int32) * (n // k)
    world = swim.SwimWorld.healthy(p_on, subject_ids=subject_ids)
    part = np.zeros((4, n), np.int8)
    part[0, : n // 2] = 1
    world = world.with_partition_schedule(part, phase)
    s_off, _ = swim.run(jax.random.key(5), p_off, world, rounds)
    s_on, _ = swim.run(jax.random.key(5), p_on, world, rounds)
    assert int(sync_plane.divergence_probe(s_off, p_off, world,
                                           rounds)) > 0
    assert int(sync_plane.divergence_probe(s_on, p_on, world,
                                           rounds)) == 0


# --------------------------------------------------------------------------
# Sharded twins (incl. the pipelined double-buffer)
# --------------------------------------------------------------------------


@pytest.mark.multichip
def test_sharded_pipelined_equals_serial_with_plane_and_heals():
    """The exchange rides the pipelined contribution buffer: sharded
    pipelined == sharded serial bit for bit with the plane on, through
    a real partition heal — and the sharded run converges."""
    from scalecube_cluster_tpu.parallel import compat
    from scalecube_cluster_tpu.parallel import mesh as pmesh

    if not compat.HAS_SHARD_MAP:
        pytest.skip(compat.SKIP_REASON)
    n = 32
    _, p_on, world, rounds = _heal_setup("scatter", n=n)
    mesh = pmesh.make_mesh(4)
    s_ser, m_ser = pmesh.shard_run(jax.random.key(6), p_on, world,
                                   rounds, mesh, pipelined=False)
    s_pip, m_pip = pmesh.shard_run(jax.random.key(6), p_on, world,
                                   rounds, mesh, pipelined=True)
    _assert_states_equal(s_ser, s_pip)
    for k in m_ser:
        assert np.array_equal(np.asarray(m_ser[k]),
                              np.asarray(m_pip[k])), k
    assert "messages_anti_entropy" in m_ser
    assert int(sync_plane.divergence_probe(s_ser, p_on, world,
                                           rounds)) == 0


@pytest.mark.multichip
def test_sharded_metered_carries_plane_counter():
    from scalecube_cluster_tpu.parallel import compat
    from scalecube_cluster_tpu.parallel import mesh as pmesh

    if not compat.HAS_SHARD_MAP:
        pytest.skip(compat.SKIP_REASON)
    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", sync_interval=4)
    world = swim.SwimWorld.healthy(params)
    _, _, metrics = pmesh.shard_run_metered(
        jax.random.key(7), params, world, 8, pmesh.make_mesh(4))
    ae = np.asarray(metrics["messages_anti_entropy"])
    expect = np.where(np.arange(8) % 4 == 0, 2 * n, 0)
    assert np.array_equal(ae, expect)


# --------------------------------------------------------------------------
# Monitored / traced / metered shapes carry the plane unchanged
# --------------------------------------------------------------------------


def test_run_shapes_agree_with_plane_on():
    """run / run_traced / run_metered / run_monitored /
    run_monitored_metered all execute the identical tick with the plane
    on — final tables agree bit for bit across every shape.  (Shape
    parity needs the exchange active, not a convergence horizon.)"""
    from scalecube_cluster_tpu.chaos import monitor as cm

    _, p_on, world, rounds = _heal_setup("scatter", n=16)
    rounds = min(rounds, 72)
    ref, _ = swim.run(jax.random.key(8), p_on, world, rounds)
    traced, _, _ = swim.run_traced(jax.random.key(8), p_on, world, rounds)
    metered, _, _ = swim.run_metered(jax.random.key(8), p_on, world,
                                     rounds)
    spec = cm.MonitorSpec.passive(p_on)
    monitored, _, _ = cm.run_monitored(jax.random.key(8), p_on, world,
                                       spec, rounds)
    mm, _, _, _ = cm.run_monitored_metered(jax.random.key(8), p_on,
                                           world, spec, rounds)
    for other in (traced, metered, monitored, mm):
        _assert_states_equal(ref, other)
