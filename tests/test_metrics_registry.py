"""The in-jit health-metrics registry (telemetry/metrics.py).

Pins the tentpole contracts: the registry only OBSERVES (metered runs
are bit-identical to plain runs), counters agree with the per-round
metric traces they digest, suspicion lifetimes land in the declared
buckets, gauges sample the final carry, the windowed flush dedups on
resume through the journal cursor, and the sharded path psums the
registry across the mesh to the single-device-consistent totals.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.telemetry import metrics as tmetrics
from scalecube_cluster_tpu.telemetry import sink as tsink

pytestmark = pytest.mark.metrics

N = 16
VICTIM = 3

CFG = ClusterConfig.default_local().replace(
    gossip_interval=100, ping_interval=200, ping_timeout=100,
    sync_interval=1_000, suspicion_mult=3,
)


def make_params(**overrides):
    return swim.SwimParams.from_config(CFG, n_members=N, **overrides)


def crash_world(params, at_round=10):
    return swim.SwimWorld.healthy(params).with_crash(VICTIM,
                                                     at_round=at_round)


def registry_dict(ms, spec=None):
    return tmetrics.to_json(jax.device_get(ms),
                            spec or tmetrics.MetricsSpec.default())


# --------------------------------------------------------------------------
# Spec + pure ops
# --------------------------------------------------------------------------


def test_spec_validation():
    with pytest.raises(ValueError, match="duplicate counters"):
        tmetrics.MetricsSpec(counters=("a", "a"))
    with pytest.raises(ValueError, match="increasing"):
        tmetrics.MetricsSpec(histograms=(("h", (4, 2)),))
    with pytest.raises(KeyError):
        tmetrics.MetricsSpec.default().histogram_edges("nope")


def test_inc_set_observe_ops():
    spec = tmetrics.MetricsSpec(
        counters=("c1", "c2"), gauges=("g1",),
        histograms=(("h", (0, 2, 4)),),
    )
    ms = tmetrics.MetricsState.init(spec)
    ms = tmetrics.inc(ms, spec, "c1", 3)
    ms = tmetrics.inc_many(ms, spec, {"c1": 2, "c2": 7})
    ms = tmetrics.set_gauge(ms, spec, "g1", 1.5)
    ms = tmetrics.set_gauge(ms, spec, "g1", 2.5)      # last write wins
    # values 0,1 -> bucket 0; 2,3 -> bucket 1; >=4 -> open bucket 2;
    # masked-out samples don't count.
    ms = tmetrics.observe(ms, spec, "h",
                          jnp.asarray([0, 1, 2, 3, 4, 99, 5]),
                          jnp.asarray([1, 1, 1, 1, 1, 1, 0], bool))
    d = registry_dict(ms, spec)
    assert d["counters"] == {"c1": 5, "c2": 7}
    assert d["gauges"]["g1"] == 2.5
    assert d["histograms"]["h"]["counts"] == [2, 2, 2]
    # The all-masked observe is the identity (the emptiness gate).
    ms2 = tmetrics.observe(ms, spec, "h", jnp.asarray([1, 2]),
                           jnp.zeros(2, bool))
    assert registry_dict(ms2, spec) == d
    # Unknown names are trace-time errors, not silent drops.
    with pytest.raises(ValueError):
        tmetrics.inc(ms, spec, "nope", 1)


def test_reset_window_keeps_gauges():
    spec = tmetrics.MetricsSpec.default()
    ms = tmetrics.MetricsState.init(spec)
    ms = tmetrics.inc(ms, spec, "fd_probes_sent", 9)
    ms = tmetrics.set_gauge(ms, spec, "suspect_entries", 4.0)
    ms = tmetrics.reset_window(ms)
    d = registry_dict(ms)
    assert d["counters"]["fd_probes_sent"] == 0
    assert d["gauges"]["suspect_entries"] == 4.0


# --------------------------------------------------------------------------
# run_metered
# --------------------------------------------------------------------------


class TestRunMetered:
    def test_observes_only_bit_identical_state_and_metrics(self):
        params = make_params(delivery="shift")
        world = crash_world(params)
        st_p, m_p = swim.run(jax.random.key(0), params, world, 90)
        st_m, _, m_m = swim.run_metered(jax.random.key(0), params, world,
                                        90)
        for f in dataclasses.fields(swim.SwimState):
            np.testing.assert_array_equal(
                np.asarray(getattr(st_p, f.name)),
                np.asarray(getattr(st_m, f.name)), err_msg=f.name)
        for k in m_p:
            np.testing.assert_array_equal(np.asarray(m_p[k]),
                                          np.asarray(m_m[k]), err_msg=k)

    def test_counters_agree_with_metric_traces(self):
        params = make_params(delivery="shift")
        world = crash_world(params)
        _, ms, m = swim.run_metered(jax.random.key(1), params, world, 90)
        d = registry_dict(ms)
        for counter, key in (("fd_probes_sent", "messages_ping_sent"),
                             ("fd_ping_req_sent", "messages_ping_req_sent"),
                             ("fd_tracked_verdicts", "messages_ping"),
                             ("gossip_messages", "messages_gossip"),
                             ("refutations", "refutations")):
            assert d["counters"][counter] == int(np.asarray(m[key]).sum()), \
                counter

    def test_crash_lifecycle_counts_and_lifetime_histogram(self):
        """One permanent crash: every live observer suspects the victim
        once and the suspicion fires at exactly the timeout — the
        lifetime histogram holds N-1 samples in the suspicion_rounds
        bucket."""
        params = make_params(delivery="shift")
        world = crash_world(params)
        _, ms, _ = swim.run_metered(jax.random.key(2), params, world, 90)
        d = registry_dict(ms)
        c = d["counters"]
        assert c["suspicions_started"] == N - 1
        assert c["suspicions_fired"] == N - 1
        assert c["suspicions_refuted"] == 0
        assert c["false_suspicion_onsets"] == 0   # the victim IS dead
        h = d["histograms"]["suspicion_lifetime_rounds"]
        assert sum(h["counts"]) == N - 1
        edges = h["edges"]
        bucket = np.searchsorted(edges, params.suspicion_rounds,
                                 side="right") - 1
        assert h["counts"][bucket] == N - 1
        # Gauges sample the final carry: everyone holds the tombstone.
        assert d["gauges"]["dead_entries"] == N - 1
        assert d["gauges"]["suspect_entries"] == 0
        assert d["gauges"]["live_members"] == N - 1

    def test_refutation_lifecycle_under_revival(self):
        """Crash + revive before the timeout: suspicions resolve by
        refutation, with lifetimes strictly below suspicion_rounds."""
        params = make_params(delivery="shift")
        world = swim.SwimWorld.healthy(params).with_crash(
            VICTIM, at_round=10, until_round=14)
        _, ms, _ = swim.run_metered(jax.random.key(3), params, world, 120)
        d = registry_dict(ms)
        c = d["counters"]
        assert c["suspicions_refuted"] >= 1
        assert c["refutations"] >= 1
        h = d["histograms"]["suspicion_lifetime_rounds"]
        assert sum(h["counts"]) == c["suspicions_refuted"] \
            + c["suspicions_fired"]
        # At least one refutation resolved before the full timeout.
        edges = h["edges"]
        fire_bucket = np.searchsorted(edges, params.suspicion_rounds,
                                      side="right") - 1
        assert sum(h["counts"][:fire_bucket]) >= 1

    def test_healthy_run_is_silent(self):
        params = make_params(delivery="shift")
        world = swim.SwimWorld.healthy(params)
        _, ms, _ = swim.run_metered(jax.random.key(4), params, world, 60)
        d = registry_dict(ms)
        for k in ("suspicions_started", "suspicions_fired",
                  "false_suspicion_onsets", "false_positive_rounds"):
            assert d["counters"][k] == 0, k
        assert d["counters"]["live_observer_rounds"] == N * 60
        assert d["gauges"]["live_members"] == N

    def test_round_fusion_matches_unfused(self):
        params = make_params(delivery="shift", rounds_per_step=4)
        base = make_params(delivery="shift")
        world = crash_world(params)
        _, ms_f, _ = swim.run_metered(jax.random.key(5), params, world, 90)
        _, ms_1, _ = swim.run_metered(jax.random.key(5), base, world, 90)
        assert registry_dict(ms_f) == registry_dict(ms_1)

    def test_compact_carry_matches_wide(self):
        params = make_params(delivery="shift", compact_carry=True)
        wide = make_params(delivery="shift")
        world = crash_world(params)
        _, ms_c, _ = swim.run_metered(jax.random.key(6), params, world, 90)
        _, ms_w, _ = swim.run_metered(jax.random.key(6), wide, world, 90)
        assert registry_dict(ms_c) == registry_dict(ms_w)

    def test_custom_spec_subset(self):
        spec = tmetrics.MetricsSpec(
            counters=("fd_probes_sent",), gauges=("live_members",),
            histograms=(),
        )
        params = make_params(delivery="shift")
        world = crash_world(params)
        _, ms, m = swim.run_metered(jax.random.key(7), params, world, 40,
                                    spec=spec)
        d = registry_dict(ms, spec)
        assert set(d["counters"]) == {"fd_probes_sent"}
        assert d["counters"]["fd_probes_sent"] \
            == int(np.asarray(m["messages_ping_sent"]).sum())
        assert d["histograms"] == {}


# --------------------------------------------------------------------------
# Monitored + metered (chaos shape)
# --------------------------------------------------------------------------


class TestMonitoredMetered:
    def test_chaos_violations_counter_tracks_monitor_totals(self):
        from scalecube_cluster_tpu import chaos
        from scalecube_cluster_tpu.chaos import campaign as ccampaign
        from scalecube_cluster_tpu.chaos import monitor as cmonitor

        scen = chaos.generate_scenario(seed=3, n=24, severity="moderate")
        params = ccampaign.campaign_params(scen)
        world, mon_spec = scen.build(params)
        st, mon, ms, m = cmonitor.run_monitored_metered(
            jax.random.key(0), params, world, mon_spec, scen.horizon)
        st_r, mon_r, m_r = cmonitor.run_monitored(
            jax.random.key(0), params, world, mon_spec, scen.horizon)
        np.testing.assert_array_equal(np.asarray(mon.code_counts),
                                      np.asarray(mon_r.code_counts))
        np.testing.assert_array_equal(np.asarray(st.status),
                                      np.asarray(st_r.status))
        d = registry_dict(ms)
        assert d["counters"]["chaos_violations"] \
            == int(np.asarray(mon.code_counts).sum())


# --------------------------------------------------------------------------
# Windowed flush + resume dedup
# --------------------------------------------------------------------------


class TestStreamMetered:
    def test_windows_partition_the_run(self, tmp_path):
        params = make_params(delivery="shift")
        world = crash_world(params)
        path = str(tmp_path / "run.jsonl")
        with tsink.TelemetrySink(path=path) as sink:
            _, rows = tmetrics.stream_metered_run(
                jax.random.key(0), params, world, 90, sink=sink,
                window_rounds=40)
        recs = tsink.read_records(path, kind="metrics_window")
        assert [(r["round_start"], r["round_end"]) for r in recs] \
            == [(0, 40), (40, 80), (80, 90)]
        # Written records == the driver's returned rows, modulo the
        # sink's record envelope.
        assert [{k: r[k] for k in rows[0]} for r in recs] == rows
        # Window counters sum to the monolithic run's totals (counters
        # are window totals; the reset between windows loses nothing).
        _, ms_mono, _ = swim.run_metered(jax.random.key(0), params,
                                         world, 90)
        mono = registry_dict(ms_mono)["counters"]
        for name in mono:
            assert sum(r["counters"][name] for r in recs) == mono[name], \
                name
        # Gauges: the LAST window's sample equals the monolithic one's.
        assert recs[-1]["gauges"] == registry_dict(ms_mono)["gauges"]

    def test_resume_skips_covered_windows(self, tmp_path):
        params = make_params(delivery="shift")
        world = crash_world(params)
        path = str(tmp_path / "run.jsonl")
        with tsink.TelemetrySink(path=path) as sink:
            tmetrics.stream_metered_run(jax.random.key(0), params, world,
                                        90, sink=sink, window_rounds=40)
        before = tsink.read_records(path, kind="metrics_window")
        # Relaunch appending to the same journal: covered windows are
        # recomputed but not re-written — no duplicate rows.
        with tsink.TelemetrySink(path=path, append=True) as sink:
            tmetrics.stream_metered_run(jax.random.key(0), params, world,
                                        90, sink=sink, window_rounds=40)
        after = tsink.read_records(path, kind="metrics_window")
        assert after == before
        assert tsink.covered_upto(path, kind="metrics_window") == 90


# --------------------------------------------------------------------------
# Sharded: registry psum across the mesh
# --------------------------------------------------------------------------


from scalecube_cluster_tpu.parallel import compat  # noqa: E402


@pytest.mark.skipif(not compat.HAS_SHARD_MAP, reason=compat.SKIP_REASON)
class TestShardRunMetered:
    def test_registry_consistent_with_sharded_metric_traces(self):
        from scalecube_cluster_tpu.parallel import mesh as pmesh

        params = swim.SwimParams.from_config(
            CFG, n_members=64, delivery="scatter")
        world = swim.SwimWorld.healthy(params).with_crash(5, at_round=5)
        mesh = pmesh.make_mesh(8)
        _, ms, m = pmesh.shard_run_metered(jax.random.key(1), params,
                                           world, 80, mesh)
        d = registry_dict(ms)
        # The lead-device dedup + end-of-run psum must reproduce the
        # (already psum-global) per-round traces exactly once.
        for counter, key in (("fd_probes_sent", "messages_ping_sent"),
                             ("gossip_messages", "messages_gossip"),
                             ("fd_ping_req_sent", "messages_ping_req_sent")):
            assert d["counters"][counter] == int(np.asarray(m[key]).sum()), \
                counter
        # Row-local lanes psum to the global lifecycle counts.
        assert d["counters"]["suspicions_started"] == 63
        assert d["counters"]["suspicions_fired"] == 63
        assert sum(d["histograms"]["suspicion_lifetime_rounds"]["counts"]) \
            == 63
        assert d["gauges"]["dead_entries"] == 63.0
        assert d["gauges"]["live_members"] == 63.0
