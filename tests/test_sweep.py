"""Sweep harness (sweep.py + models/swim.Knobs).

The knob overrides must be semantics-preserving at the default point
(knobs=None == Knobs.from_params), and the vmapped grid must reproduce
single runs and the protocol's analytic trends (BASELINE config 5;
ClusterMath as the anchor, GossipProtocolTest.java:178-205's pattern).
"""

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu import sweep
from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config


def make(n, delivery="shift", **overrides):
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery=delivery, **overrides
    )
    world = swim.SwimWorld.healthy(params).with_crash(0, at_round=0)
    return params, world


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_default_knobs_are_identity(delivery):
    params, world = make(16, delivery=delivery)
    key = jax.random.key(0)
    _, m_plain = swim.run(key, params, world, 60)
    _, m_knobs = swim.run(key, params, world, 60,
                          knobs=swim.Knobs.from_params(params))
    for name in m_plain:
        np.testing.assert_array_equal(
            np.asarray(m_plain[name]), np.asarray(m_knobs[name])
        )


def test_grid_point_matches_single_run():
    """Grid point b of a sweep == a standalone run with that knob set,
    the grid-point key, and (shift delivery) the shared shift key."""
    params, world = make(16)
    base_key = jax.random.key(3)
    knobs = sweep.knob_grid(params, ping_every=[2, 4])
    metrics = sweep.sweep_run(base_key, params, world, 50, knobs)

    kn1 = jax.tree.map(lambda a: a[1], knobs)
    _, single = swim.run(jax.random.fold_in(base_key, 1), params, world, 50,
                         knobs=kn1, shift_key=base_key)
    for name in single:
        np.testing.assert_array_equal(
            np.asarray(metrics[name])[1], np.asarray(single[name])
        )


def test_shared_shifts_preserve_per_instance_independence():
    """Shared-shift batching must change ONLY the channel topology
    source: a scatter-mode sweep (no shifts) is bit-identical with and
    without it, and shift grid points still differ from each other."""
    params, world = make(16, delivery="scatter")
    key = jax.random.key(5)
    knobs = sweep.knob_grid(params, ping_every=[2, 4])
    m_a = sweep.sweep_run(key, params, world, 40, knobs, share_shifts=False)
    m_b = sweep.sweep_run(key, params, world, 40, knobs, share_shifts=True)
    for name in m_a:
        np.testing.assert_array_equal(np.asarray(m_a[name]),
                                      np.asarray(m_b[name]))
    params_s, world_s = make(16, delivery="shift")
    m_s = sweep.sweep_run(key, params_s, world_s, 40,
                          sweep.knob_grid(params_s, loss_probability=[0.3,
                                                                      0.3]))
    # Same knobs, different instance keys: loss draws stay independent.
    assert not np.array_equal(np.asarray(m_s["false_positives"])[0],
                              np.asarray(m_s["false_positives"])[1])


def test_suspicion_knob_moves_detection_time():
    """Detection (first DEAD) must track the swept suspicion timeout —
    the ClusterMath.suspicionTimeout anchor (ClusterMath.java:123-125)."""
    res = sweep.run_crash_sweep(
        32, 260, config=fast_config(), suspicion_rounds=[10, 40],
        delivery="shift",
    )
    det = res["curves"]["detection_rounds"]
    assert det[0] + 20 <= det[1], det
    # Detection can't beat the configured timeout.
    assert det[0] >= 10
    assert det[1] >= 40


def test_fanout_knob_moves_dissemination():
    """Higher fanout must not slow dissemination; measured dissemination
    stays inside the analytic spread window (gossip_periods_to_spread)."""
    res = sweep.run_crash_sweep(
        64, 300, config=fast_config(), fanout=[1, 4], delivery="shift",
    )
    dis = res["curves"]["dissemination_rounds"]
    det = res["curves"]["detection_rounds"]
    assert dis[1] <= dis[0], dis
    # Post-detection dissemination must finish within the analytic spread
    # window (repeat_mult * ceil(log2(n+1)) periods, ClusterMath.java:111-113)
    # at the default fanout or higher.
    spread = res["analytic"]["periods_to_spread"]
    assert dis[1] - det[1] <= spread, (dis, det, spread)


def test_loss_knob_drives_false_positives():
    res = sweep.run_crash_sweep(
        32, 200, config=fast_config(), loss_probability=[0.0, 0.3],
        delivery="scatter",
    )
    fp = res["curves"]["false_positive_rate"]
    assert fp[0] == 0.0
    assert fp[1] > 0.0


def test_cli_writes_curve_artifact(tmp_path):
    """The sweep CLI (python -m scalecube_cluster_tpu.sweep) produces the
    curve artifact end to end."""
    import json

    out = str(tmp_path / "curves.json")
    sweep.main([
        "--n-members", "64", "--n-rounds", "120",
        "--fanout", "2", "3", "--ping-every", "2",
        "--loss", "0.0", "--out", out,
    ])
    with open(out) as f:
        result = json.load(f)
    assert result["n_members"] == 64
    assert len(result["curves"]["detection_rounds"]) == 2  # 2 fanouts
    assert result["analytic"]["periods_to_spread"] > 0


def test_shift_vmap_guard_warns_above_threshold(monkeypatch):
    """The vmap-gather trap now only applies to the explicit
    share_shifts=False opt-out (sweep.py performance note): that path
    warns at large N; the default shared-shift batching does not."""
    # Shrink the threshold so the test doesn't need a big compile.
    monkeypatch.setattr(sweep, "SHIFT_VMAP_N_WARN", 32)
    with pytest.warns(UserWarning, match="vmapped shift-mode sweep"):
        sweep.run_crash_sweep(64, 30, config=fast_config(),
                              fanout=[2, 3], share_shifts=False)
    import warnings as _w
    with _w.catch_warnings():
        # Only the guard's own message is promoted to an error, so an
        # unrelated upstream warning can't fail this test spuriously.
        _w.filterwarnings("error", message=".*vmapped shift-mode sweep.*")
        sweep.run_crash_sweep(64, 30, config=fast_config(),
                              fanout=[2, 3])
        sweep.run_crash_sweep(16, 30, config=fast_config(),
                              fanout=[2, 3], share_shifts=False)
        sweep.run_crash_sweep(64, 30, config=fast_config(),
                              delivery="scatter", fanout=[2, 3])
