"""Saturation pressure: the measured motivation for the wire24 rung.

ROADMAP item 3 (the PR-10 debt): open-world epoch bits squeeze the
compact wire16 key's incarnation saturation to 2^11-1 = 2047.  The
scenario here makes that cap REAL: a seeded long-horizon severe-churn
run — a mid-suspicion partition heal (the PR-7 unbounded DEAD/ALIVE
reinfection burn, tests/test_dead_suppression.py) plus a crash/revive
churn rider, Lifeguard plane on — burns incarnations linearly
(~0.4/round for the hottest members) until the wire16 arm PINS at the
cap: refutation bumps clamp there (models/swim._wire_inc_sat), so
refutations stop landing and the protocol degrades loudly.  The SAME
seeded scenario under wire24 — same int32 word already crossing ICI,
zero extra wire bytes (parallel/traffic.scatter_wire_bytes_per_slot) —
keeps climbing past 2047, far from its own binding cap (the int16
carry ceiling 32767).

The WIRE_SATURATION monitor runs over the wire16 arm as the loudness
evidence: the invariant (carry/self_inc strictly ABOVE the cap) stays
green, i.e. the clamp held exactly AT the boundary — saturation is a
visible protocol plateau, never a silent wire/table divergence.

Mini version tier-1 (~6k rounds, seconds on the compiled scan); the
full horizon lives behind @slow (SCALECUBE_SAT_ROUNDS, default 20k).
"""

import os

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.chaos import monitor as chaos_monitor
from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.wire

N = 16
SPLIT = 48          # < quiesce bound: tombstones still hot at the heal


def pressure_params(wire24: bool):
    return swim.SwimParams.from_config(
        fast_config(), n_members=N, delivery="scatter", sync_interval=8,
        compact_carry=True, wire24=wire24, open_world=True, lhm_max=4,
    )


def pressure_world(params):
    """Mid-suspicion heal (unbounded incarnation burn) + churn rider."""
    world = swim.SwimWorld.healthy(params)
    part = np.zeros((8, N), np.int8)
    part[0, : N // 2] = 1
    world = world.with_partition_schedule(part, SPLIT)
    return world.with_crash(3, at_round=10, until_round=30)


def run_pressure(wire24: bool, rounds: int):
    params = pressure_params(wire24)
    state, _ = swim.run(jax.random.key(1), params, pressure_world(params),
                        rounds)
    return params, state


def assert_pressure(rounds: int):
    p16, s16 = run_pressure(wire24=False, rounds=rounds)
    p24, s24 = run_pressure(wire24=True, rounds=rounds)
    cap16, cap24 = swim._wire_inc_sat(p16), swim._wire_inc_sat(p24)
    assert (cap16, cap24) == (2047, 32767)      # the ROADMAP numbers

    si16 = np.asarray(s16.self_inc)
    si24 = np.asarray(s24.self_inc)
    # wire16 TRIPS the cap: hottest members pinned exactly AT 2047,
    # and the carry never exceeds it (the clamp, not an overflow).
    assert si16.max() == cap16
    assert (si16 == cap16).sum() >= 2, si16
    assert np.asarray(s16.inc).max() <= cap16
    # The SAME seeded scenario under wire24: unsaturated — the burn
    # kept counting past 2047 (so wire16's plateau really was the cap
    # binding, not the scenario running out of pressure), with ample
    # headroom to its own carry-ceiling cap.
    assert si24.max() > cap16
    assert si24.max() < cap24
    # (Sub-cap trace parity between the rungs is pinned separately —
    # tests/test_wire16.py::test_wire24_trace_identical_below_cap; here
    # the arms legitimately diverge once the first member saturates,
    # because a pinned refutation changes the gossip the whole cluster
    # sees.)
    return p16


def test_saturation_pressure_mini():
    """Tier-1 mini horizon: the wire16 arm reaches and pins at 2^11-1
    while wire24 keeps counting — plus the monitor evidence that the
    clamped arm stayed green (no silent divergence AT the cap)."""
    p16 = assert_pressure(rounds=6000)
    # WIRE_SATURATION monitor evidence on a saturated-window replay:
    # run the wire16 arm monitored PAST the plateau — the invariant
    # (inc strictly above the cap) must stay green while the state
    # demonstrably sits at the cap.
    spec = chaos_monitor.MonitorSpec.passive(p16)
    state, mon, _ = chaos_monitor.run_monitored(
        jax.random.key(1), p16, pressure_world(p16), spec, 6000)
    v = chaos_monitor.verdict(mon)
    assert v["green"], v
    assert int(np.asarray(state.self_inc).max()) == swim._wire_inc_sat(p16)


@pytest.mark.slow
def test_saturation_pressure_full_horizon():
    """The full long-horizon version (SCALECUBE_SAT_ROUNDS, default
    20k): deep into the saturated regime the wire16 plateau holds and
    wire24 is STILL unsaturated."""
    rounds = int(os.environ.get("SCALECUBE_SAT_ROUNDS", "20000"))
    assert_pressure(rounds=rounds)
