"""The collective-traffic model (parallel/traffic.py) is pinned to the
tick: the exchange counts the formulas assume are the exchange counts the
code performs.  SURVEY.md §5.8's promise, made checkable.

Two layers of pinning:
  - trace-time counters (mock ShiftEngine.deliver / lax.pmax) — fast,
    per-exchange granularity;
  - the COMPILED program: ``shard_run`` lowered on the virtual 8-device
    mesh, its HLO parsed, and the collective ops' counts and operand
    bytes asserted against the model (the round-3 verdict's demand: the
    byte model must be pinned by the compiler, not by its own
    arithmetic re-derived in a test comment).
"""

import dataclasses
import re
from unittest import mock

import jax
import pytest

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.ops import shift as shift_ops
from scalecube_cluster_tpu.parallel import compat
from scalecube_cluster_tpu.parallel import mesh as pmesh
from scalecube_cluster_tpu.parallel import traffic

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.skipif(not compat.HAS_SHARD_MAP,
                                reason=compat.SKIP_REASON)

N_DEV = 8

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                "s32": 4, "u32": 4, "f32": 4}


def _compiled_hlo(params, world, n_rounds=4, pipelined=False):
    mesh = pmesh.make_mesh(N_DEV)
    state = swim.initial_state(params, world)
    return pmesh.shard_run.lower(
        jax.random.key(0), params, world, n_rounds, mesh,
        state=state, start_round=0, pipelined=pipelined,
    ).compile().as_text()


def _op_operand_bytes(hlo_text, op_name):
    """[(dtype, dims, bytes)] for every non-tuple ``op_name`` instruction."""
    out = []
    for m in re.finditer(
        r"= (\w+)\[([\d,]*)\]\S* " + re.escape(op_name) + r"\(", hlo_text
    ):
        dtype, dims = m.group(1), m.group(2)
        size = 1
        for d in dims.split(","):
            if d:
                size *= int(d)
        out.append((dtype, dims, size * _DTYPE_BYTES[dtype]))
    return out


hlo_pinned = pytest.mark.skipif(
    compat.HAS_SHARD_MAP and not compat.MODERN_LOWERING,
    reason=compat.LEGACY_LOWERING_REASON,
)


@hlo_pinned
@pytest.mark.parametrize("n,k,gate,layout", [
    (256, 16, False, "wide"),
    (128, 128, True, "wide"),
    # compact layout: int16 keys must halve the key exchanges' ICI bytes
    # in the compiled program too — full-view and focal (the no_message
    # dtype discipline is what keeps int16 buffers from silently
    # promoting back to int32; a promotion doubles the compiled bytes
    # and fails here).
    (128, 128, False, "compact"),
    (256, 16, False, "compact"),
    # int16_wire: the wire narrows while the carry stays wide — the
    # compiled ppermute bytes must match _key_bytes' compact_wire
    # accounting (the "sharded ICI bytes DO halve" claim in RESULTS.md's
    # int16-wire negative is a compiled-program fact, not just a model).
    (256, 16, False, "wire16"),
])
def test_shift_hlo_collectives_match_traffic_model(n, k, gate, layout):
    """The compiled sharded shift program's collective-permutes ARE the
    model: count == exchanges x 2 rotations x D branches (one ppermute
    per lax.switch branch; exactly 2 execute per exchange), and total
    operand bytes / D == shift_ici_bytes_per_device_round."""
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n,
        n_subjects=(None if k == n else k), delivery="shift",
        compact_carry=layout == "compact",
        int16_wire=layout == "wire16",
    )
    world = swim.SwimWorld.healthy(params)
    if gate:
        world = world.with_seeds([0, 1])   # enables full-view contact gate
    hlo = _compiled_hlo(params, world)

    cps = _op_operand_bytes(hlo, "collective-permute")
    exchanges = traffic.shift_exchanges_per_round(params, gate_contacts=gate)
    assert len(cps) == len(exchanges) * 2 * N_DEV, (
        f"compiled program holds {len(cps)} collective-permutes; model "
        f"expects {len(exchanges)} exchanges x 2 rotations x {N_DEV} "
        f"switch branches"
    )
    # Every branch of one rotation switch moves the same block, so summing
    # all instances and dividing by the branch count D gives the bytes one
    # device actually sends per round.
    hlo_bytes_per_device = sum(b for _, _, b in cps) // N_DEV
    assert hlo_bytes_per_device == traffic.shift_ici_bytes_per_device_round(
        params, N_DEV, gate_contacts=gate
    )
    # Shift mode's delivery uses no all-reduce; the only one is the fused
    # variadic metrics psum (a tuple op, excluded by the non-tuple regex).
    assert _op_operand_bytes(hlo, "all-reduce") == []


# The wire-format ladder x fused/legacy wire matrix the scatter HLO
# pins run over: (params overrides, expected key dtype in the HLO).
WIRE_LAYOUTS = {
    "wide": ({}, "s32"),
    "wire16": ({"compact_carry": True}, "s16"),
    "wire24": ({"compact_carry": True, "wire24": True}, "s32"),
}


def _scatter_params(n, k, layout, fused):
    overrides, key_dtype = WIRE_LAYOUTS[layout]
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=k, delivery="scatter",
        fused_wire=fused, **overrides,
    )
    return params, key_dtype


@hlo_pinned
@pytest.mark.parametrize("fused", [True, False], ids=["fused", "legacy"])
@pytest.mark.parametrize("layout", sorted(WIRE_LAYOUTS))
def test_scatter_hlo_collectives_match_traffic_model(layout, fused):
    """The full-height pmax combines per round: ONE combined key buffer
    under the fused wire (the ALIVE flags ride the key bits — no s8
    buffer in the compiled program at all), the key + s8 flag pair on
    the legacy two-buffer wire."""
    n, k = 256, 16
    params, key_dtype = _scatter_params(n, k, layout, fused)
    world = swim.SwimWorld.healthy(params)
    hlo = _compiled_hlo(params, world)

    ars = _op_operand_bytes(hlo, "all-reduce")
    n_combines = traffic.scatter_collectives_per_round(params)
    assert n_combines == (1 if fused else 2)
    assert len(ars) == n_combines
    dims = sorted(d for _, d, _ in ars)
    assert dims == [f"{n},{k}"] * n_combines
    key_dtypes = {t for t, _, _ in ars}
    assert key_dtypes == ({key_dtype} if fused else {key_dtype, "s8"})
    buffer_bytes = sum(b for _, _, b in ars)
    assert buffer_bytes == n * k * traffic.scatter_wire_bytes_per_slot(params)
    # Ring all-reduce: each device sends 2*(D-1)/D of the buffer.
    assert int(2 * (N_DEV - 1) / N_DEV * buffer_bytes) == (
        traffic.scatter_ici_bytes_per_device_round(params, N_DEV)
    )
    assert _op_operand_bytes(hlo, "collective-permute") == []


@hlo_pinned
@pytest.mark.parametrize("fused", [True, False], ids=["fused", "legacy"])
@pytest.mark.parametrize("layout", sorted(WIRE_LAYOUTS))
def test_pipelined_scatter_hlo_collectives_match_traffic_model(layout,
                                                               fused):
    """The PIPELINED scatter program doubles the combine instruction
    count (loop-body combine over the carried contribution + epilogue
    combine for the final round) without adding per-round traffic — the
    placement move is visible in the compiled text exactly as
    traffic.pipelined_scatter_hlo_collectives models it.  Under the
    fused wire that is ONE instruction in the body and one in the
    epilogue: the pipelined carry is a single buffer."""
    n, k = 256, 16
    params, key_dtype = _scatter_params(n, k, layout, fused)
    world = swim.SwimWorld.healthy(params)
    hlo = _compiled_hlo(params, world, pipelined=True)

    ars = _op_operand_bytes(hlo, "all-reduce")
    n_instr = traffic.pipelined_scatter_hlo_collectives(params)
    assert n_instr == (2 if fused else 4)
    assert len(ars) == n_instr
    dims = sorted(d for _, d, _ in ars)
    assert dims == [f"{n},{k}"] * n_instr
    key_dtypes = {t for t, _, _ in ars}
    assert key_dtypes == ({key_dtype} if fused else {key_dtype, "s8"})
    # Per-ROUND bytes are the serial figure — half the instructions run
    # per iteration, the other half once at the epilogue.
    loop_bytes = sum(b for _, _, b in ars) // 2
    assert int(2 * (N_DEV - 1) / N_DEV * loop_bytes) == (
        traffic.scatter_ici_bytes_per_device_round(params, N_DEV)
    )
    assert _op_operand_bytes(hlo, "collective-permute") == []


def test_fused_wire_byte_model():
    """The 4-vs-5 B/slot headline, straight from the model: the fused
    wire drops the s8 flag byte per inbox slot on every rung, wire24
    costs exactly what the pre-ladder wide wire paid for its key alone,
    and SHIFT-mode accounting is untouched by the flag fold (shift
    ships tx masks, not flag buffers)."""
    def p(fused, **kw):
        return swim.SwimParams.from_config(
            fast_config(), n_members=256, n_subjects=16,
            fused_wire=fused, **kw)

    assert traffic.scatter_wire_bytes_per_slot(p(True)) == 4
    assert traffic.scatter_wire_bytes_per_slot(p(False)) == 5
    assert traffic.scatter_wire_bytes_per_slot(
        p(True, compact_carry=True)) == 2
    assert traffic.scatter_wire_bytes_per_slot(
        p(False, compact_carry=True)) == 3
    assert traffic.scatter_wire_bytes_per_slot(
        p(True, compact_carry=True, wire24=True)) == 4
    for kw in ({}, {"compact_carry": True},
               {"compact_carry": True, "wire24": True}):
        a = p(True, delivery="shift", **kw)
        b = p(False, delivery="shift", **kw)
        assert traffic.shift_ici_bytes_per_device_round(a, N_DEV) == \
            traffic.shift_ici_bytes_per_device_round(b, N_DEV)
        assert traffic.shift_exchanges_per_round(a) == \
            traffic.shift_exchanges_per_round(b)


def test_pipelined_combine_count_doubles_lowering_neutral():
    """Lowering-neutral version of the instruction-count pin (runs on
    the legacy per-psum lowering too): counting ONLY the full-height
    [N, K] combines — metric psums are [K]/scalar shaped — the
    pipelined program holds exactly twice the serial count, the
    loop-body pair plus the epilogue pair."""
    n, k = 256, 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=k, delivery="scatter",
    )
    world = swim.SwimWorld.healthy(params)

    def full_height_combines(pipelined):
        hlo = _compiled_hlo(params, world, pipelined=pipelined)
        return [x for x in _op_operand_bytes(hlo, "all-reduce")
                if x[1] == f"{n},{k}"]

    serial = full_height_combines(False)
    pipelined = full_height_combines(True)
    assert len(serial) == traffic.scatter_collectives_per_round(params)
    assert len(pipelined) == traffic.pipelined_scatter_hlo_collectives(params)
    assert len(pipelined) == 2 * len(serial)


def test_pipelined_async_collective_overlap():
    """On backends that lower collectives to async start/done pairs
    (TPU), the pipelined body must hold compute between a combine's
    start and done — the overlap the pipeline exists for.  CPU lowers
    collectives synchronously; skip there, like the other
    lowering-specific pins."""
    n, k = 256, 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=k, delivery="scatter",
    )
    world = swim.SwimWorld.healthy(params)
    hlo = _compiled_hlo(params, world, pipelined=True)
    if "all-reduce-start" not in hlo:
        pytest.skip("backend lowers collectives synchronously "
                    "(no all-reduce-start/done pairs in the compiled text)")
    starts = [m.start() for m in re.finditer(r"all-reduce-start", hlo)]
    dones = [m.start() for m in re.finditer(r"all-reduce-done", hlo)]
    assert starts and len(starts) == len(dones)
    # At least one start/done pair brackets real compute: the text
    # between them contains non-collective instructions (the next
    # round's draw pipeline the scheduler slid under the transfer).
    overlapped = any(
        len(hlo[s:d].splitlines()) > 2
        for s, d in zip(starts, dones) if d > s
    )
    assert overlapped, "no compute scheduled between start/done pairs"


@hlo_pinned
@pytest.mark.sync
def test_sync_plane_shift_hlo_collectives_match_traffic_model():
    """With the anti-entropy plane on, the compiled sharded shift
    program grows exactly the two ±s payload-channel exchanges the
    model adds (keys + txmask each) — and nothing else."""
    base = swim.SwimParams.from_config(
        fast_config(), n_members=256, n_subjects=16, delivery="shift",
    )
    params = dataclasses.replace(base, sync_interval=8)
    world = swim.SwimWorld.healthy(params)
    hlo = _compiled_hlo(params, world)

    cps = _op_operand_bytes(hlo, "collective-permute")
    exchanges = traffic.shift_exchanges_per_round(params)
    assert len(cps) == len(exchanges) * 2 * N_DEV
    base_exchanges = traffic.shift_exchanges_per_round(base)
    assert len(exchanges) == len(base_exchanges) + 4    # 2x (keys+txmask)
    hlo_bytes_per_device = sum(b for _, _, b in cps) // N_DEV
    assert hlo_bytes_per_device == traffic.shift_ici_bytes_per_device_round(
        params, N_DEV
    )
    assert _op_operand_bytes(hlo, "all-reduce") == []


@hlo_pinned
@pytest.mark.sync
def test_sync_plane_scatter_hlo_adds_no_collectives():
    """Scatter mode: the plane's exchange folds into the SAME
    contribution buffers the regular channels pmax — collective count
    and operand bytes in the compiled program are UNCHANGED with the
    plane on (the scatter_ici_bytes_per_device_round docstring's
    claim)."""
    n, k = 256, 16
    base = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=k, delivery="scatter",
    )
    params = dataclasses.replace(base, sync_interval=8)
    world = swim.SwimWorld.healthy(params)
    ars = _op_operand_bytes(_compiled_hlo(params, world), "all-reduce")
    ars_base = _op_operand_bytes(
        _compiled_hlo(base, swim.SwimWorld.healthy(base)), "all-reduce")
    assert len(ars) == len(ars_base) == (
        traffic.scatter_collectives_per_round(params))
    assert sorted(b for _, _, b in ars) == sorted(b for _, _, b in ars_base)


@pytest.mark.sync
def test_sync_plane_bytes_model():
    params = swim.SwimParams.from_config(
        fast_config(), n_members=1024, n_subjects=16, delivery="shift",
        sync_interval=64,
    )
    per_exchange = traffic.sync_exchange_bytes_per_member(params)
    assert per_exchange == 2 * 16 * 4                 # both directions
    # Amortized over the interval, the repair plane is a small fraction
    # of the per-round piggyback budget.
    amortized = per_exchange / params.sync_interval
    assert amortized < traffic.piggyback_bytes_per_member_round(params) / 8
    # int16 wire halves the exchange bytes like every key buffer.
    compact = dataclasses.replace(params, int16_wire=True)
    assert traffic.sync_exchange_bytes_per_member(compact) * 2 == per_exchange


def _tick_once(params, world, axis_name=None):
    state = swim.initial_state(params, world)
    # Trace (not execute): the python-level deliver/pmax calls happen at
    # trace time, which is what the counters observe.
    jax.make_jaxpr(
        lambda s: swim.swim_tick(s, jax.numpy.int32(0), jax.random.key(0),
                                 params, world)
    )(state)


@pytest.mark.parametrize("gate", [False, True])
@pytest.mark.parametrize("sync_interval", [0, 8])
def test_shift_exchange_count_matches_tick(gate, sync_interval):
    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="shift",
        sync_interval=sync_interval,
    )
    world = swim.SwimWorld.healthy(params)
    if gate:
        world = world.with_seeds([0, 1])   # enables full-view contact gate
    model = traffic.shift_exchanges_per_round(params, gate_contacts=gate)

    calls = []
    orig = shift_ops.ShiftEngine.deliver

    def counting(self, h, s):
        calls.append(h.shape)
        return orig(self, h, s)

    with mock.patch.object(shift_ops.ShiftEngine, "deliver", counting):
        _tick_once(params, world)
    assert len(calls) == len(model), (
        f"tick performs {len(calls)} block exchanges, model counts "
        f"{len(model)}: {sorted(model)}"
    )


def test_shift_bytes_formula_consistency():
    params = swim.SwimParams.from_config(
        fast_config(), n_members=1024, n_subjects=16, delivery="shift"
    )
    params = dataclasses.replace(params, fanout=3)
    # fanout+2 = 5 channels x (64+16) B/row + 3 hot_any + 2 refuting
    # flags x 1 B/row = 405 B/row; 2 rotations x n_local rows.
    per_dev = traffic.shift_ici_bytes_per_device_round(params, n_devices=8)
    assert per_dev == 2 * (1024 // 8) * (5 * (64 + 16) + 5)
    # Weak scaling: per-device ICI halves when D doubles at fixed N.
    assert traffic.shift_ici_bytes_per_device_round(params, 16) * 2 == per_dev * 1
    # Scatter per-device ICI is ~constant in D (ring allreduce factor only).
    s8 = traffic.scatter_ici_bytes_per_device_round(params, 8)
    s16 = traffic.scatter_ici_bytes_per_device_round(params, 16)
    assert s16 > s8  # (D-1)/D grows toward the constant 2*N*K*5
    assert s16 < 2 * 1024 * 16 * 5


def test_scatter_collective_count_matches_tick():
    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter"
    )
    world = swim.SwimWorld.healthy(params)

    pmax_calls = []
    orig = jax.lax.pmax

    def counting(x, axis_name):
        pmax_calls.append(getattr(x, "shape", None))
        return orig(x, axis_name)

    state = swim.initial_state(params, world)

    def body(s):
        # offset/axis wiring as mesh.shard_run does, single "device".
        return swim.swim_tick(s, jax.numpy.int32(0), jax.random.key(0),
                              params, world, offset=0, axis_name="x",
                              n_devices=1)

    with mock.patch.object(jax.lax, "pmax", counting):
        jax.make_jaxpr(
            lambda s: compat.shard_map(
                body, mesh=jax.sharding.Mesh(jax.devices()[:1], ("x",)),
                in_specs=(jax.sharding.PartitionSpec(),),
                out_specs=jax.sharding.PartitionSpec(),
                check_replication=False,
            )(s)
        )(state)
    assert len(pmax_calls) == traffic.scatter_collectives_per_round(params)
