"""The collective-traffic model (parallel/traffic.py) is pinned to the
tick: the exchange counts the formulas assume are the exchange counts the
code performs.  SURVEY.md §5.8's promise, made checkable."""

import dataclasses
from unittest import mock

import jax
import pytest

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.ops import shift as shift_ops
from scalecube_cluster_tpu.parallel import traffic

from tests.test_swim_model import fast_config


def _tick_once(params, world, axis_name=None):
    state = swim.initial_state(params, world)
    # Trace (not execute): the python-level deliver/pmax calls happen at
    # trace time, which is what the counters observe.
    jax.make_jaxpr(
        lambda s: swim.swim_tick(s, jax.numpy.int32(0), jax.random.key(0),
                                 params, world)
    )(state)


@pytest.mark.parametrize("gate", [False, True])
def test_shift_exchange_count_matches_tick(gate):
    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="shift"
    )
    world = swim.SwimWorld.healthy(params)
    if gate:
        world = world.with_seeds([0, 1])   # enables full-view contact gate
    model = traffic.shift_exchanges_per_round(params, gate_contacts=gate)

    calls = []
    orig = shift_ops.ShiftEngine.deliver

    def counting(self, h, s):
        calls.append(h.shape)
        return orig(self, h, s)

    with mock.patch.object(shift_ops.ShiftEngine, "deliver", counting):
        _tick_once(params, world)
    assert len(calls) == len(model), (
        f"tick performs {len(calls)} block exchanges, model counts "
        f"{len(model)}: {sorted(model)}"
    )


def test_shift_bytes_formula_consistency():
    params = swim.SwimParams.from_config(
        fast_config(), n_members=1024, n_subjects=16, delivery="shift"
    )
    params = dataclasses.replace(params, fanout=3)
    # fanout+2 = 5 channels x (64+16) B/row + 3 hot_any + 2 refuting
    # flags x 1 B/row = 405 B/row; 2 rotations x n_local rows.
    per_dev = traffic.shift_ici_bytes_per_device_round(params, n_devices=8)
    assert per_dev == 2 * (1024 // 8) * (5 * (64 + 16) + 5)
    # Weak scaling: per-device ICI halves when D doubles at fixed N.
    assert traffic.shift_ici_bytes_per_device_round(params, 16) * 2 == per_dev * 1
    # Scatter per-device ICI is ~constant in D (ring allreduce factor only).
    s8 = traffic.scatter_ici_bytes_per_device_round(params, 8)
    s16 = traffic.scatter_ici_bytes_per_device_round(params, 16)
    assert s16 > s8  # (D-1)/D grows toward the constant 2*N*K*5
    assert s16 < 2 * 1024 * 16 * 5


def test_scatter_collective_count_matches_tick():
    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter"
    )
    world = swim.SwimWorld.healthy(params)

    pmax_calls = []
    orig = jax.lax.pmax

    def counting(x, axis_name):
        pmax_calls.append(getattr(x, "shape", None))
        return orig(x, axis_name)

    state = swim.initial_state(params, world)

    def body(s):
        # offset/axis wiring as mesh.shard_run does, single "device".
        return swim.swim_tick(s, jax.numpy.int32(0), jax.random.key(0),
                              params, world, offset=0, axis_name="x",
                              n_devices=1)

    with mock.patch.object(jax.lax, "pmax", counting):
        jax.make_jaxpr(
            lambda s: jax.shard_map(
                body, mesh=jax.sharding.Mesh(jax.devices()[:1], ("x",)),
                in_specs=(jax.sharding.PartitionSpec(),),
                out_specs=jax.sharding.PartitionSpec(),
                check_vma=False,
            )(s)
        )(state)
    assert len(pmax_calls) == traffic.scatter_collectives_per_round(params)
