"""The Lifeguard health plane (models/lifeguard.py + SwimParams.lhm_max).

Four contracts, mirroring tests/test_sync_plane.py's structure:

  1. *off = bit-identical*: ``lhm_max=0`` (the default) compiles the
     plane out — zero-size lane, no extra draws, the plane-less
     program exactly;
  2. *on + healthy = no-op*: with every member healthy the multiplier
     pins at 1, the scaled budgets/deadlines equal their base values
     and the probe gate always passes, so warm no-fault runs are
     table- AND metrics-identical to plane-off across every layout,
     both delivery modes, and the sharded pipelined path;
  3. *the LHM contract*: the multiplier stays clamped to
     ``[1, lhm_max]``, effective timeouts and suspicion deadlines
     never drop below their base values (property-tested on the pure
     schedule functions), a degraded observer ramps up and decays
     back, and its probe rate drops accordingly;
  4. *buddy refutation*: with the plane on, a falsely suspected member
     learns of its suspicion in the probe ACK path and refutes even
     with the membership SYNC channel off (``sync_every=0``) — without
     the plane the suspicion matures to a false DEAD.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.models import fd as fd_model
from scalecube_cluster_tpu.models import lifeguard
from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.lifeguard

STATE_FIELDS = ("status", "inc", "spread_until", "suspect_deadline",
                "self_inc")


def _assert_states_equal(a, b, fields=STATE_FIELDS):
    for f in fields:
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f))), f


def _degraded_world(params, node=0, loss=0.8, until=10 ** 6):
    """Inbound loss on one observer: its probes of healthy peers lose
    the ack hop — the observer-side degradation the LHM detects."""
    n = params.n_members
    return swim.SwimWorld.healthy(params).with_link_fault(
        (1, n), node, loss=loss, until_round=until)


# --------------------------------------------------------------------------
# 1 + 2: disabled default == baseline; enabled on healthy world == no-op
# --------------------------------------------------------------------------


def test_lhm_defaults_off():
    params = swim.SwimParams.from_config(fast_config(), n_members=8)
    assert params.lhm_max == 0
    explicit = dataclasses.replace(params, lhm_max=0)
    assert explicit == params          # same static params, same program
    state = swim.initial_state(params, swim.SwimWorld.healthy(params))
    assert state.lhm.shape == (0,)     # the lane is compiled out


def test_param_validation():
    params = swim.SwimParams.from_config(fast_config(), n_members=8)
    with pytest.raises(ValueError, match="lhm_max"):
        dataclasses.replace(params, lhm_max=-1)
    with pytest.raises(ValueError, match="dead_suppress_rounds"):
        dataclasses.replace(params, dead_suppress_rounds=-1)
    # compact_carry caps the scaled deadline horizon.
    with pytest.raises(ValueError, match="lhm_max"):
        swim.SwimParams.from_config(
            fast_config(), n_members=8, delivery="shift",
            compact_carry=True, lhm_max=3000)


@pytest.mark.parametrize("delivery,subjects,layout", [
    ("scatter", None, "wide"),
    ("shift", None, "wide"),
    ("shift", 8, "wide"),              # focal
    ("shift", None, "compact"),
    ("scatter", None, "wire16"),
])
def test_plane_on_healthy_world_is_noop(delivery, subjects, layout):
    """All-healthy members pin lhm at 1: gate always passes, budgets
    and deadlines equal base — tables AND the metrics tree are
    bit-identical to plane-off (the strong off-switch pin: the plane's
    draws come from a dedicated key fold, so enabling it perturbs no
    existing stream)."""
    n = 24
    p_off = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=subjects,
        delivery=delivery,
        compact_carry=layout == "compact", int16_wire=layout == "wire16",
    )
    p_on = dataclasses.replace(p_off, lhm_max=8)
    world = swim.SwimWorld.healthy(p_off)
    s_off, m_off = swim.run(jax.random.key(0), p_off, world, 20)
    s_on, m_on = swim.run(jax.random.key(0), p_on, world, 20)
    _assert_states_equal(s_off, s_on)
    assert np.all(np.asarray(s_on.lhm) == 1)
    assert set(m_on) == set(m_off)
    for k in m_off:
        assert np.array_equal(np.asarray(m_off[k]), np.asarray(m_on[k])), k


# --------------------------------------------------------------------------
# 3: the LHM contract
# --------------------------------------------------------------------------


def test_deadline_schedule_never_below_base():
    """Property: the LHA Suspicion schedule is >= base for every
    (lhm, n_live) pair, monotone in both, equal to base at lhm=1, and
    capped at base * lhm_max."""
    base = jnp.int32(36)
    n = 64
    lhm = jnp.arange(1, 9, dtype=jnp.int32)
    for n_live in (0, 1, 3, 17, 32, 64):
        d = np.asarray(lifeguard.suspicion_deadline_rounds(
            base, lhm, jnp.int32(n_live), n))
        assert (d >= 36).all()
        assert (np.diff(d) >= 0).all()          # monotone in lhm
        assert d[0] == 36                       # lhm=1 -> exactly base
        assert (d <= 36 * 8).all()
    full = np.asarray(lifeguard.suspicion_deadline_rounds(
        base, jnp.int32(8), jnp.int32(n), n))
    assert full == 36 * 8                       # n_live=N -> full scale


def test_probe_budgets_never_below_base():
    params = swim.SwimParams.from_config(fast_config(), n_members=16,
                                         lhm_max=8)
    lhm = jnp.arange(1, 9, dtype=jnp.int32)
    ping, ping_req = fd_model.effective_probe_budgets(params, lhm)
    assert (np.asarray(ping) >= params.ping_timeout_ms).all()
    assert (np.asarray(ping_req)
            >= params.ping_interval_ms - params.ping_timeout_ms).all()
    assert float(ping[0]) == params.ping_timeout_ms      # lhm=1 = base


def test_lhm_update_clamps():
    """The transition never leaves [1, lhm_max] and frozen members keep
    their multiplier."""
    lhm = jnp.asarray([1, 1, 8, 8, 4], jnp.int32)
    fail = jnp.asarray([0, 1, 1, 0, 1], jnp.bool_)
    clean = jnp.asarray([1, 0, 0, 1, 0], jnp.bool_)
    refuted = jnp.asarray([0, 1, 1, 0, 0], jnp.bool_)
    alive = jnp.asarray([1, 1, 1, 1, 0], jnp.bool_)
    out = np.asarray(lifeguard.update(lhm, fail, clean, refuted, alive, 8))
    assert out.tolist() == [1,   # 1 - 1 clamps up to 1
                            3,   # 1 + 1 + 1
                            8,   # 8 + 2 clamps down to 8
                            7,   # 8 - 1
                            4]   # frozen: unchanged
    assert (out >= 1).all() and (out <= 8).all()


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_degraded_observer_ramps_and_recovers(delivery):
    """Inbound loss on one observer ramps ITS multiplier to the cap
    while healthy members stay at ~1; after the fault lifts it decays
    back down.  Resumes across run segments keep the clamp."""
    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery=delivery, lhm_max=8)
    world = _degraded_world(params, node=0, loss=0.85, until=60)
    state, _ = swim.run(jax.random.key(2), params, world, 60)
    mid = np.asarray(state.lhm)
    assert mid[0] == 8                       # degraded observer at cap
    assert (mid >= 1).all() and (mid <= 8).all()
    assert np.median(mid[1:]) <= 2           # healthy stay low
    state, _ = swim.run(jax.random.key(2), params, world, 300,
                        state=state, start_round=60)
    final = np.asarray(state.lhm)
    assert final[0] <= 2                     # decayed after the heal
    assert (final >= 1).all() and (final <= 8).all()


def test_probe_rate_scales_down_under_degradation():
    """LHA Probe's interval scaling: the degraded observer issues
    measurably fewer probes with the plane on (messages_ping_sent)."""
    n = 16
    p_on = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", lhm_max=8)
    p_off = dataclasses.replace(p_on, lhm_max=0)
    world = _degraded_world(p_on, node=0, loss=0.85)
    _, m_on = swim.run(jax.random.key(3), p_on, world, 200)
    _, m_off = swim.run(jax.random.key(3), p_off, world, 200)
    sent_on = int(np.asarray(m_on["messages_ping_sent"]).sum())
    sent_off = int(np.asarray(m_off["messages_ping_sent"]).sum())
    assert sent_on < sent_off


def test_armed_deadlines_respect_scaled_bound():
    """Every pending suspicion timer in a plane-on run stays within
    [base, base * lhm_max] rounds of arming — the TIMER_BOUND contract
    the monitor enforces, checked here directly on the carry."""
    from scalecube_cluster_tpu.chaos import monitor as cm

    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", lhm_max=4)
    world = _degraded_world(params, node=0, loss=0.8)
    spec = cm.MonitorSpec.passive(params)
    _, mon, _ = cm.run_monitored(jax.random.key(4), params, world, spec,
                                 120)
    assert cm.verdict(mon)["green"], cm.verdict(mon)


# --------------------------------------------------------------------------
# 4: buddy refutation over the ack path
# --------------------------------------------------------------------------


def test_buddy_refutes_over_the_ack_path_alone():
    """The FD-isolation configuration (gossip fanout 0 AND
    sync_every=0 — models/fd.fd_only_knobs) leaves the probe ACK path
    as the ONLY way a suspected member can learn of its suspicion.  A
    transient all-acks block gets members falsely suspected; with the
    plane on, a later successful probe's ack carries the suspicion
    back (the buddy push) and the member self-refutes — plane off,
    verdicts stay strictly observer-local and nobody ever bumps (the
    fd.py caveat note)."""
    n = 16
    p_off = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", sync_every=0)
    p_on = dataclasses.replace(p_off, lhm_max=8)
    kn_off = dataclasses.replace(swim.Knobs.from_params(p_off),
                                 fanout=jnp.int32(0))
    kn_on = dataclasses.replace(swim.Knobs.from_params(p_on),
                                fanout=jnp.int32(0))
    # Block all acks for a window shorter than the suspicion timeout,
    # then heal: probers suspect their targets meanwhile, and
    # post-heal probes of still-suspected entries succeed.
    world = swim.SwimWorld.healthy(p_off).with_block(
        (0, n), (0, n), from_round=4, until_round=14)
    rounds = 60
    s_off, _ = swim.run(jax.random.key(5), p_off, world, rounds,
                        knobs=kn_off)
    s_on, _ = swim.run(jax.random.key(5), p_on, world, rounds,
                       knobs=kn_on)
    # Plane on: buddy pushes delivered suspicions back over the ack
    # path; members learned and bumped.
    assert int(np.asarray(s_on.self_inc).max()) > 0
    # Plane off: no dissemination channel exists — nobody ever learned
    # of any suspicion, so nobody bumped.
    assert int(np.asarray(s_off.self_inc).max()) == 0


# --------------------------------------------------------------------------
# Sharded twins (incl. the pipelined double-buffer)
# --------------------------------------------------------------------------


@pytest.mark.multichip
def test_sharded_pipelined_equals_serial_with_plane():
    """The LHM lane and its probe evidence ride the pipelined carry:
    sharded pipelined == sharded serial bit for bit with the plane on,
    through real degradation + a crash."""
    from scalecube_cluster_tpu.parallel import compat
    from scalecube_cluster_tpu.parallel import mesh as pmesh

    if not compat.HAS_SHARD_MAP:
        pytest.skip(compat.SKIP_REASON)
    n = 32
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", lhm_max=4)
    world = swim.SwimWorld.healthy(params).with_link_fault(
        (4, n), (0, 4), loss=0.8).with_crash(9, at_round=10)
    mesh = pmesh.make_mesh(4)
    s_ser, m_ser = pmesh.shard_run(jax.random.key(6), params, world, 50,
                                   mesh, pipelined=False)
    s_pip, m_pip = pmesh.shard_run(jax.random.key(6), params, world, 50,
                                   mesh, pipelined=True)
    _assert_states_equal(s_ser, s_pip, fields=STATE_FIELDS + ("lhm",))
    for k in m_ser:
        assert np.array_equal(np.asarray(m_ser[k]),
                              np.asarray(m_pip[k])), k
    assert int(np.asarray(s_ser.lhm).max()) > 1   # degradation was seen


@pytest.mark.multichip
def test_sharded_metered_samples_lhm_gauge():
    from scalecube_cluster_tpu.parallel import compat
    from scalecube_cluster_tpu.parallel import mesh as pmesh
    from scalecube_cluster_tpu.telemetry import metrics as tm

    if not compat.HAS_SHARD_MAP:
        pytest.skip(compat.SKIP_REASON)
    n = 32
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", lhm_max=4)
    world = swim.SwimWorld.healthy(params).with_link_fault(
        (4, n), (0, 4), loss=0.8)
    _, ms, _ = pmesh.shard_run_metered(
        jax.random.key(7), params, world, 40, pmesh.make_mesh(4))
    d = tm.to_json(jax.device_get(ms), tm.MetricsSpec.default())
    assert d["gauges"]["lhm"] >= 1.0         # plane on: mean over live


# --------------------------------------------------------------------------
# Run shapes + layouts carry the plane unchanged
# --------------------------------------------------------------------------


def test_run_shapes_agree_with_plane_on():
    """run / run_traced / run_metered / run_monitored /
    run_monitored_metered all execute the identical tick with the plane
    on — final tables and lhm lanes agree bit for bit; the metered
    shape samples the lhm gauge."""
    from scalecube_cluster_tpu.chaos import monitor as cm
    from scalecube_cluster_tpu.telemetry import metrics as tm

    n = 16
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="scatter", lhm_max=4)
    world = _degraded_world(params, node=0, loss=0.7)
    rounds = 40
    ref, _ = swim.run(jax.random.key(8), params, world, rounds)
    traced, _, _ = swim.run_traced(jax.random.key(8), params, world,
                                   rounds)
    metered, ms, _ = swim.run_metered(jax.random.key(8), params, world,
                                      rounds)
    spec = cm.MonitorSpec.passive(params)
    monitored, _, _ = cm.run_monitored(jax.random.key(8), params, world,
                                       spec, rounds)
    mm, _, _, _ = cm.run_monitored_metered(jax.random.key(8), params,
                                           world, spec, rounds)
    for other in (traced, metered, monitored, mm):
        _assert_states_equal(ref, other, fields=STATE_FIELDS + ("lhm",))
    d = tm.to_json(jax.device_get(ms), tm.MetricsSpec.default())
    assert d["gauges"]["lhm"] >= 1.0


def test_blocked_and_compact_layouts_identical_with_plane():
    """k_block bit-identity + compact-carry trace-identity with the
    plane on, through real degradation."""
    n = 32
    p_on = swim.SwimParams.from_config(
        fast_config(), n_members=n, delivery="shift", lhm_max=4)
    world = _degraded_world(p_on, node=0, loss=0.8)
    rounds = 80
    s_ref, m_ref = swim.run(jax.random.key(9), p_on, world, rounds)
    p_blk = dataclasses.replace(p_on, k_block=8)
    s_blk, _ = swim.run(jax.random.key(9), p_blk, world, rounds)
    _assert_states_equal(s_ref, s_blk, fields=STATE_FIELDS + ("lhm",))
    p_c = dataclasses.replace(p_on, compact_carry=True)
    s_c, _ = swim.run(jax.random.key(9), p_c, world, rounds)
    dec = swim._carry_decode(s_c, jnp.int32(rounds))
    assert np.array_equal(np.asarray(s_ref.status), np.asarray(dec.status))
    assert np.array_equal(np.asarray(s_ref.inc), np.asarray(dec.inc))
    assert np.array_equal(np.asarray(s_ref.lhm), np.asarray(s_c.lhm))
    assert int(np.asarray(s_ref.lhm)[0]) > 1
