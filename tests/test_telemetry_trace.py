"""The on-device membership event trace (telemetry/trace.py + run_traced).

The trace is observability doubling as a correctness surface: the tick's
decoded event stream and the oracle's merge-funnel trace
(``MembershipProtocol.listen_trace``) speak one schema
(telemetry/events.py), so a fault scenario's event streams are directly
diffable across layers.  Rounds are stochastic, so parity compares the
timing-free key sets (observer, subject, type, incarnation) — which ARE
deterministic for scenarios that run to quiescence.

Also pinned here: ring-buffer overflow accounting (drops counted, the
recorded prefix exact — never silent truncation), record-order
determinism, the in-jit latency histograms against a host-side
recomputation from the decoded events, and the graceful-leave LEAVING
event.
"""

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.oracle import Cluster, Simulator
from scalecube_cluster_tpu.telemetry import trace as ttrace
from scalecube_cluster_tpu.telemetry.events import (
    MembershipTraceEvent,
    OracleTraceCollector,
    TraceEventType,
    diff_event_streams,
    event_key_set,
)

N = 16
ROUND_MS = 100
VICTIM = 3

# The sped-up two-layer config of tests/test_cross_validation.py:
# suspicion resolves in 30 rounds, so scenarios quiesce fast.
CFG = ClusterConfig.default_local().replace(
    gossip_interval=ROUND_MS,
    ping_interval=200,
    ping_timeout=100,
    sync_interval=1_000,
    suspicion_mult=3,
)

SUSPECTED = TraceEventType.SUSPECTED
REMOVED = TraceEventType.REMOVED
ADDED = TraceEventType.ADDED
ALIVE_REFUTED = TraceEventType.ALIVE_REFUTED
LEAVING = TraceEventType.LEAVING


def make_params(**overrides):
    return swim.SwimParams.from_config(CFG, n_members=N, **overrides)


def build_oracle(seed: int):
    """N warmed-up oracle clusters with integer-aliased members and one
    attached trace collector."""
    sim = Simulator(seed=seed)
    clusters = [Cluster.join(sim, config=CFG, alias="m0")]
    for i in range(1, N):
        clusters.append(
            Cluster.join(sim, seeds=[clusters[0].address], config=CFG,
                         alias=f"m{i}")
        )
    sim.run_for(4_000)
    assert all(len(c.members()) == N for c in clusters), "warmup incomplete"
    collector = OracleTraceCollector(
        sim, ROUND_MS, index_of=lambda m: int(m.id[1:])
    )
    for i, c in enumerate(clusters):
        collector.watch(c, observer_index=i)
    return sim, clusters, collector


def observers():
    return [i for i in range(N) if i != VICTIM]


# --------------------------------------------------------------------------
# Model-vs-oracle event-stream parity
# --------------------------------------------------------------------------


class TestCrashParity:
    """A crash-at-round-k scenario: the decoded model trace's
    SUSPECTED/REMOVED events must match the oracle's event stream
    exactly (the acceptance criterion).  The comparison excludes the
    victim-as-observer: the oracle's stopped transport leaves the
    victim's scheduler running (it falsely suspects everyone), while
    the dense crash freezes the whole row — the documented crash-model
    difference; every LIVE observer's stream must agree."""

    def oracle_keys(self, seed=0):
        sim, clusters, collector = build_oracle(seed)
        clusters[VICTIM].transport.stop()
        sim.run_for(120 * ROUND_MS)
        return event_key_set(
            collector.events, types=[SUSPECTED, REMOVED],
            subjects=[VICTIM], observers=observers(),
        )

    @pytest.mark.parametrize("delivery", ["scatter", "shift"])
    def test_crash_suspected_removed_match_oracle(self, delivery):
        oracle_keys = self.oracle_keys()
        params = make_params(delivery=delivery)
        world = swim.SwimWorld.healthy(params).with_crash(
            VICTIM, at_round=0
        )
        _, tel, _ = swim.run_traced(jax.random.key(0), params, world, 120)
        assert int(tel.trace.dropped) == 0
        model_keys = event_key_set(
            ttrace.decode_events(tel), types=[SUSPECTED, REMOVED],
            subjects=[VICTIM],
        )
        only_model, only_oracle = (model_keys - oracle_keys,
                                   oracle_keys - model_keys)
        assert not only_model and not only_oracle, (only_model, only_oracle)
        # And both equal the closed-form expectation: every live observer
        # suspects, then removes, the victim at incarnation 0.
        expected = {
            (o, VICTIM, int(t), 0)
            for o in observers() for t in (SUSPECTED, REMOVED)
        }
        assert model_keys == expected

    def test_crash_revive_readd_matches_oracle(self):
        """Crash long enough for full removal, then revive: every live
        observer re-accepts the victim (ADDED at the old incarnation —
        the delete-then-re-add path) on BOTH layers.  The oracle's
        'crash' is a full link blockade (its transport has no restart);
        the blockade and the frozen dense crash agree on everything a
        live observer can see."""
        down_at, up_at, horizon = 0, 70, 160

        sim, clusters, collector = build_oracle(seed=1)
        victim = clusters[VICTIM]
        rest = [c for c in clusters if c is not victim]
        victim.network_emulator.block([c.address for c in rest])
        for c in rest:
            c.network_emulator.block(victim.address)
        sim.run_for((up_at - down_at) * ROUND_MS)
        assert all(len(c.members()) == N - 1 for c in rest), \
            "oracle removal incomplete before revival"
        for c in clusters:
            c.network_emulator.unblock_all()
        sim.run_for((horizon - up_at) * ROUND_MS)

        oracle_crash = event_key_set(
            collector.events, types=[SUSPECTED, REMOVED],
            subjects=[VICTIM], observers=observers(),
        )
        oracle_readd = event_key_set(
            collector.events, types=[ADDED], subjects=[VICTIM],
            observers=observers(), min_round=up_at,
        )

        params = make_params(delivery="shift")
        world = swim.SwimWorld.healthy(params).with_crash(
            VICTIM, at_round=down_at, until_round=up_at
        )
        _, tel, _ = swim.run_traced(jax.random.key(1), params, world,
                                    horizon)
        assert int(tel.trace.dropped) == 0
        events = ttrace.decode_events(tel)
        model_crash = event_key_set(
            events, types=[SUSPECTED, REMOVED], subjects=[VICTIM],
        )
        model_readd = event_key_set(
            events, types=[ADDED], subjects=[VICTIM], min_round=up_at,
        )
        assert model_crash == oracle_crash, \
            diff_event_streams(events, collector.events,
                               types=[SUSPECTED, REMOVED],
                               subjects=[VICTIM], observers=observers())
        assert model_readd == oracle_readd
        assert model_readd == {(o, VICTIM, int(ADDED), 0)
                               for o in observers()}


def test_short_crash_refutation_events():
    """A crash shorter than the suspicion timeout: the revived node
    refutes (incarnation bump) and observers' SUSPECT entries resolve by
    ALIVE_REFUTED — nobody ever emits REMOVED.  (Which observers
    suspected before the revival is seed-dependent, so this asserts the
    model's event semantics rather than cross-layer set equality.)"""
    params = make_params(delivery="shift")
    world = swim.SwimWorld.healthy(params).with_crash(
        VICTIM, at_round=5, until_round=15
    )
    state, tel, _ = swim.run_traced(jax.random.key(2), params, world, 120)
    events = ttrace.decode_events(tel)
    refuted = [e for e in events
               if e.event_type == ALIVE_REFUTED and e.subject == VICTIM]
    suspected = [e for e in events
                 if e.event_type == SUSPECTED and e.subject == VICTIM]
    assert suspected, "nobody suspected the briefly-crashed node"
    assert refuted, "no refutation event reached any observer"
    assert all(e.incarnation >= 1 for e in refuted)
    assert int(np.asarray(state.self_inc)[VICTIM]) >= 1
    assert not [e for e in events
                if e.event_type == REMOVED and e.subject == VICTIM]


def test_graceful_leave_events():
    """with_leave: the leaver announces LEAVING@inc+1 in its final round
    (one event, observer == subject) and every live observer REMOVEs it
    at the announced incarnation — the oracle's leaveCluster surface."""
    leaver, leave_at = 5, 10
    params = make_params(delivery="shift")
    world = swim.SwimWorld.healthy(params).with_leave(leaver, at_round=leave_at)
    _, tel, _ = swim.run_traced(jax.random.key(3), params, world, 60)
    events = ttrace.decode_events(tel)
    leaving = [e for e in events if e.event_type == LEAVING]
    assert leaving == [MembershipTraceEvent(
        round=leave_at, observer=leaver, subject=leaver,
        event_type=LEAVING, incarnation=1,
    )]
    removed = event_key_set(events, types=[REMOVED], subjects=[leaver])
    assert removed == {(o, leaver, int(REMOVED), 1)
                      for o in range(N) if o != leaver}


def test_oracle_leave_emits_leaving_trace():
    """The oracle side of the LEAVING surface: leave_cluster emits one
    LEAVING trace record at incarnation + 1, and the leaver's death
    disseminates as REMOVED@1 at the observers."""
    sim, clusters, collector = build_oracle(seed=4)
    clusters[VICTIM].shutdown()
    sim.run_for(60 * ROUND_MS)
    leaving = [e for e in collector.events if e.event_type == LEAVING]
    assert [(e.observer, e.subject, e.incarnation) for e in leaving] == \
        [(VICTIM, VICTIM, 1)]
    removed = event_key_set(collector.events, types=[REMOVED],
                            subjects=[VICTIM], observers=observers())
    assert removed == {(o, VICTIM, int(REMOVED), 1) for o in observers()}


# --------------------------------------------------------------------------
# Buffer mechanics
# --------------------------------------------------------------------------


def run_crash(capacity=ttrace.DEFAULT_CAPACITY, seed=0, rounds=120):
    params = make_params(delivery="shift")
    world = swim.SwimWorld.healthy(params).with_crash(VICTIM, at_round=0)
    return swim.run_traced(jax.random.key(seed), params, world, rounds,
                           trace_capacity=capacity)


def test_overflow_counts_drops_exactly():
    """A too-small buffer records an exact prefix and counts every
    dropped event — count + dropped equals the untruncated stream's
    length, and the recorded events are its prefix (never silent
    truncation, never corruption)."""
    _, tel_full, _ = run_crash()
    full_events = ttrace.decode_events(tel_full)
    assert int(tel_full.trace.dropped) == 0

    cap = 7
    _, tel_small, _ = run_crash(capacity=cap)
    small_events = ttrace.decode_events(tel_small)
    assert int(tel_small.trace.count) == cap
    assert len(small_events) == cap
    assert int(tel_small.trace.count) + int(tel_small.trace.dropped) \
        == len(full_events)
    assert small_events == full_events[:cap]


def test_trace_is_deterministic():
    _, tel_a, _ = run_crash(seed=9)
    _, tel_b, _ = run_crash(seed=9)
    assert np.array_equal(np.asarray(tel_a.trace.lanes),
                          np.asarray(tel_b.trace.lanes))
    assert int(tel_a.trace.count) == int(tel_b.trace.count)


def test_trace_resumes_across_chunks():
    """Chunked scans (the checkpointing pattern) thread the telemetry
    carry through: two 60-round chunks equal one 120-round trace."""
    params = make_params(delivery="shift")
    world = swim.SwimWorld.healthy(params).with_crash(VICTIM, at_round=0)
    key = jax.random.key(5)
    _, tel_once, _ = swim.run_traced(key, params, world, 120)

    state = swim.initial_state(params, world)
    tel = None
    for chunk_start in (0, 60):
        state, tel, _ = swim.run_traced(
            key, params, world, 60, state=state, start_round=chunk_start,
            telemetry=tel,
        )
    assert ttrace.decode_events(tel) == ttrace.decode_events(tel_once)


def test_healthy_run_is_silent():
    """No faults, warm start: the trace records nothing (every event is
    a real transition, not noise)."""
    params = make_params(delivery="shift")
    world = swim.SwimWorld.healthy(params)
    _, tel, _ = swim.run_traced(jax.random.key(6), params, world, 80)
    assert int(tel.trace.count) == 0
    assert int(tel.trace.dropped) == 0


# --------------------------------------------------------------------------
# In-jit latency histograms
# --------------------------------------------------------------------------


def test_latency_histograms_match_decoded_events():
    """The on-device histograms equal a host-side recomputation from the
    decoded event stream — same buckets, same counts, and distribution
    (not just mean) granularity."""
    crash_at = 10
    params = make_params(delivery="shift")
    world = swim.SwimWorld.healthy(params).with_crash(
        VICTIM, at_round=crash_at
    )
    _, tel, _ = swim.run_traced(jax.random.key(8), params, world, 120)
    hists = ttrace.latency_histograms(tel, world)
    edges = np.asarray(hists["edges"])
    events = ttrace.decode_events(tel)

    for name, etype in (("detection", SUSPECTED), ("removal", REMOVED)):
        firsts = {}
        for e in events:
            if e.event_type == etype and e.subject == VICTIM:
                firsts.setdefault(e.observer, e.round)
        lat = np.asarray(sorted(r - crash_at for r in firsts.values()))
        expected = np.zeros(len(edges), dtype=np.int64)
        for v in lat:
            expected[np.searchsorted(edges, v, side="right") - 1] += 1
        got = np.asarray(hists[name])[VICTIM]
        assert np.array_equal(got, expected), (name, got, expected)
        assert got.sum() == N - 1          # every live observer sampled
        assert int(np.asarray(hists[name + "_undetected"])[VICTIM]) == 0

    # Healthy subjects contribute no latency samples (false-positive
    # transitions would be pre-fault and are excluded by construction).
    other = [k for k in range(N) if k != VICTIM]
    assert np.asarray(hists["detection"])[other].sum() == 0


def test_latency_histograms_undetected_accounting():
    """Observers that never see the fault land in the undetected count:
    truncate the run before the suspicion timeout fires — detection
    samples exist, removal samples don't."""
    crash_at = 5
    params = make_params(delivery="shift")
    world = swim.SwimWorld.healthy(params).with_crash(
        VICTIM, at_round=crash_at
    )
    # Long enough to suspect (a probe cycle or two), far short of the
    # 30-round suspicion timeout.
    _, tel, _ = swim.run_traced(jax.random.key(10), params, world,
                                crash_at + 10)
    hists = ttrace.latency_histograms(tel, world)
    det = np.asarray(hists["detection"])[VICTIM]
    assert det.sum() + int(np.asarray(hists["detection_undetected"])[VICTIM]) \
        == N - 1
    assert np.asarray(hists["removal"])[VICTIM].sum() == 0
    assert int(np.asarray(hists["removal_undetected"])[VICTIM]) == N - 1


def test_latency_histograms_empty_observation_window():
    """A telemetry state that observed NOTHING (fresh matrices, no
    transitions): every bucket zero, and — because the subject IS
    faulted — every live observer counts as undetected.  The edge the
    windowed/segmented drivers hit when a fault lands after the last
    observed round."""
    params = make_params()
    world = swim.SwimWorld.healthy(params).with_crash(VICTIM, at_round=10)
    tel = ttrace.TelemetryState.init(N, params.n_subjects)
    hists = ttrace.latency_histograms(tel, world)
    for name in ("detection", "removal"):
        counts = np.asarray(hists[name])
        assert counts.shape == (params.n_subjects,
                                len(ttrace.DEFAULT_LATENCY_EDGES))
        assert counts.sum() == 0
        undet = np.asarray(hists[name + "_undetected"])
        assert int(undet[VICTIM]) == N - 1      # faulted, never seen
        others = [k for k in range(N) if k != VICTIM]
        assert undet[others].sum() == 0         # unfaulted: not "missed"


def test_latency_histograms_all_overflow_buckets():
    """Latencies past the last edge all land in the OPEN last bucket —
    counted, never dropped (the never-silent-truncation contract,
    histogram flavor)."""
    crash_at = 10
    params = make_params()
    world = swim.SwimWorld.healthy(params).with_crash(VICTIM, at_round=crash_at)
    tel = ttrace.TelemetryState.init(N, params.n_subjects)
    # Every observer "detected" the victim absurdly late: beyond the
    # last finite edge by construction.
    beyond = crash_at + int(ttrace.DEFAULT_LATENCY_EDGES[-1]) + 123
    first_suspect = np.full((N, params.n_subjects), ttrace.INT32_MAX,
                            dtype=np.int32)
    first_suspect[:, VICTIM] = beyond
    tel = ttrace.TelemetryState(trace=tel.trace,
                                first_suspect=jax.numpy.asarray(first_suspect),
                                first_removed=tel.first_removed)
    hists = ttrace.latency_histograms(tel, world)
    det = np.asarray(hists["detection"])[VICTIM]
    assert det[-1] == N - 1                     # all in the open bucket
    assert det[:-1].sum() == 0
    assert int(np.asarray(hists["detection_undetected"])[VICTIM]) == 0
