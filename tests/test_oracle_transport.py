"""Transport + NetworkEmulator tests, ported from the reference's
TransportTest.java / NetworkEmulatorTest.java / TransportSendOrderTest.java
(transport/src/test/java/io/scalecube/transport/) onto virtual time."""

import pytest

from scalecube_cluster_tpu.oracle import (
    Address,
    Message,
    NetworkLinkSettings,
    Simulator,
    TimeoutError_,
    Transport,
)


def make_pair(seed=1):
    sim = Simulator(seed=seed)
    return sim, Transport(sim), Transport(sim)


def test_ping_pong():
    """TransportTest.testPingPongOnSingleChannel:105-127."""
    sim, client, server = make_pair()
    server.listen(
        lambda msg: server.send(msg.sender, Message(qualifier="pong", data=msg.data))
        if msg.qualifier == "ping"
        else None
    )
    got = []
    client.listen(lambda msg: got.append(msg))
    client.send(server.address, Message(qualifier="ping", data="hello"))
    sim.run_for(10)
    assert len(got) == 1
    assert got[0].qualifier == "pong" and got[0].data == "hello"
    assert got[0].sender == server.address


def test_request_response_matches_correlation_id():
    """TransportTest.testRequestResponse-shaped (TransportImpl.java:205-232)."""
    sim, client, server = make_pair()
    server.listen(
        lambda msg: server.send(
            msg.sender,
            Message(qualifier="resp", correlation_id=msg.correlation_id, data=msg.data * 2),
        )
    )
    results = []
    client.request_response(
        Message(qualifier="req", correlation_id="cid-1", data=21), server.address, timeout_ms=100
    ).subscribe(results.append)
    # An unrelated message with a different cid must not resolve it.
    sim.run_for(10)
    assert len(results) == 1 and results[0].data == 42


def test_request_response_timeout():
    sim, client, server = make_pair()
    errors = []
    client.request_response(
        Message(qualifier="req", correlation_id="cid-1"), server.address, timeout_ms=50
    ).subscribe(None, errors.append)
    sim.run_for(100)
    assert len(errors) == 1 and isinstance(errors[0], TimeoutError_)


def test_send_to_unbound_address_errors():
    """TransportTest.testUnresolvedHostConnection-shaped:60-73."""
    sim = Simulator()
    t = Transport(sim)
    errors = []
    t.send(Address("localhost", 9), Message(qualifier="x")).subscribe(None, errors.append)
    sim.run_for(10)
    assert len(errors) == 1 and isinstance(errors[0], ConnectionError)


def test_bind_conflict():
    """TransportTest.testBindExceptionWithoutPortAutoIncrement-shaped:41-58."""
    sim = Simulator()
    t = Transport(sim, Address("localhost", 5000))
    with pytest.raises(RuntimeError):
        Transport(sim, Address("localhost", 5000))
    t.stop()
    Transport(sim, Address("localhost", 5000))  # rebind after stop works


def test_fifty_percent_loss_honored_statistically():
    """TransportTest.testNetworkSettings:129-153 — 50% loss ±10%."""
    sim, sender, receiver = make_pair(seed=3)
    sender.network_emulator.set_link_settings(receiver.address, loss_percent=50, mean_delay_ms=0)
    got = []
    receiver.listen(lambda m: got.append(m))
    total = 1000
    for i in range(total):
        sender.send(receiver.address, Message(qualifier="q", data=i))
    sim.run_for(10)
    assert 0.4 * total < len(got) < 0.6 * total
    assert sender.network_emulator.total_message_sent_count == total
    assert sender.network_emulator.total_message_lost_count == total - len(got)


def test_block_and_unblock():
    """TransportTest.testBlockAndUnblockTraffic:334-355."""
    sim, a, b = make_pair()
    got = []
    b.listen(lambda m: got.append(m.data))
    a.network_emulator.block(b.address)
    a.send(b.address, Message(qualifier="q", data="blocked"))
    sim.run_for(10)
    assert got == []
    a.network_emulator.unblock(b.address)
    a.send(b.address, Message(qualifier="q", data="open"))
    sim.run_for(10)
    assert got == ["open"]


def test_exponential_delay_orders_by_draw():
    """NetworkLinkSettings delay distribution sanity (NetworkLinkSettings.java:64-74)."""
    sim = Simulator(seed=5)
    settings = NetworkLinkSettings(0, 100)
    draws = [settings.evaluate_delay(sim.rng) for _ in range(5000)]
    mean = sum(draws) / len(draws)
    assert 85 < mean < 115  # exponential with mean 100
    assert all(d >= 0 for d in draws)


def test_fifo_order_per_link_without_delay():
    """TransportSendOrderTest.java:39-209 — FIFO preserved on clean links."""
    sim, a, b = make_pair()
    got = []
    b.listen(lambda m: got.append(m.data))
    for i in range(100):
        a.send(b.address, Message(qualifier="q", data=i))
    sim.run_for(10)
    assert got == list(range(100))


def test_stopped_transport_delivers_nothing():
    """TransportTest stream completion on stop:257-283."""
    sim, a, b = make_pair()
    got = []
    b.listen(lambda m: got.append(m))
    b.stop()
    errors = []
    a.send(b.address, Message(qualifier="q")).subscribe(None, errors.append)
    sim.run_for(10)
    assert got == [] and len(errors) == 1


class TestSendOrder:
    """TransportSendOrderTest.java:39-217 analog: per-link FIFO.

    The reference guarantees FIFO per connection (TCP + flushOnEach,
    TransportImpl.java:262); the oracle's scheduler delivers equal-delay
    sends in submission order (stable (when, seq) heap ordering)."""

    def test_fifo_order_single_sender(self):
        sim = Simulator(seed=1)
        a, b = Transport(sim), Transport(sim)
        got = []
        b.listen(lambda m: got.append(m.data))
        n = 1000
        for i in range(n):
            a.send(b.address, Message(qualifier="seq", data=i))
        sim.run_for(1_000)
        assert got == list(range(n))

    def test_random_delay_may_reorder_but_loses_nothing(self):
        """With emulator delays on, per-link ordering is NOT guaranteed —
        matching the reference, whose NetworkEmulator delays each message
        independently before the write (TransportImpl.java:257-269; its
        FIFO test runs with the emulator disabled) — but every message
        still arrives exactly once."""
        sim = Simulator(seed=2)
        a, b = Transport(sim), Transport(sim)
        a.network_emulator.set_default_link_settings(0, 50)  # mean 50ms
        got = []
        b.listen(lambda m: got.append(m.data))
        for i in range(200):
            a.send(b.address, Message(qualifier="seq", data=i))
        sim.run_for(10_000)
        assert sorted(got) == list(range(200))

    def test_two_senders_each_stream_fifo(self):
        sim = Simulator(seed=3)
        a, b, c = Transport(sim), Transport(sim), Transport(sim)
        got = []
        c.listen(lambda m: got.append((str(m.sender), m.data)))
        for i in range(100):
            a.send(c.address, Message(qualifier="seq", data=i))
            b.send(c.address, Message(qualifier="seq", data=i))
        sim.run_for(1_000)
        for sender in (str(a.address), str(b.address)):
            stream = [d for s, d in got if s == sender]
            assert stream == list(range(100))


def test_member_id_uniqueness():
    """IdGeneratorTest.java:13-31 analog: ids unique over many draws."""
    import random

    from scalecube_cluster_tpu.oracle.core import generate_member_id

    rng = random.Random(7)
    ids_ = {generate_member_id(rng) for _ in range(200_000)}
    assert len(ids_) == 200_000


def test_max_frame_length_enforced_at_codec_seam():
    """An oversized serialized frame fails the send future before the
    emulator hook — the reference's 2MB length-prefix framing
    (TransportImpl.java:370-384, TransportConfig.java:9)."""
    from scalecube_cluster_tpu.oracle.transport import FrameTooLongError

    sim = Simulator(seed=1)
    small = Transport(sim, max_frame_length=256)
    receiver = Transport(sim)
    got, errors = [], []
    receiver.listen(got.append)

    small.send(receiver.address,
               Message(qualifier="big", data="x" * 1024)).subscribe(
        None, errors.append)
    small.send(receiver.address,
               Message(qualifier="ok", data="tiny")).subscribe(
        None, errors.append)
    sim.run_for(10)
    assert len(got) == 1 and got[0].qualifier == "ok"
    assert len(errors) == 1 and isinstance(errors[0], FrameTooLongError)
    # An oversized frame never reached the wire: the emulator's sent
    # counter saw only the small message (framing sits before tryFail).
    assert small.network_emulator.total_message_sent_count == 1


def test_default_max_frame_length_is_two_megabytes():
    """Default transports accept payloads the reference would (well under
    2MB) and the configured default matches TransportConfig.java:9."""
    from scalecube_cluster_tpu.config import DEFAULT_MAX_FRAME_LENGTH

    assert DEFAULT_MAX_FRAME_LENGTH == 2 * 1024 * 1024
    sim, client, server = make_pair()
    assert client.max_frame_length == DEFAULT_MAX_FRAME_LENGTH
    got = []
    server.listen(got.append)
    client.send(server.address, Message(qualifier="q", data="y" * 100_000))
    sim.run_for(10)
    assert len(got) == 1
