"""int16_wire (int16 wire keys, WIDE carry) is protocol-trace-identical
to the int32 wire.

The hybrid exists as a bandwidth lever for the 1M focal headline: the
round-3 narrow-int negative narrowed the CARRY lanes (slower merge); this
knob narrows only the wire-format buffers — payloads, channel delivers,
inbox, delay-ring slots — to records.merge_key16 while the carry keeps
its wide dtypes (SwimParams.int16_wire docstring).  Contract: below the
8191 incarnation saturation every protocol outcome is bit-identical —
same PRNG draws, same merge winners, same timers — because merge_key16
preserves the merge lattice order and the merge upcasts on load.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config


def run_pair(n, rounds, world_fn=None, seed=0, spread=None, **overrides):
    """(wide-wire metrics+state, int16-wire metrics+state), same scenario."""
    out = []
    for wire16 in (False, True):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, int16_wire=wire16, **overrides
        )
        world = swim.SwimWorld.healthy(params)
        if world_fn is not None:
            world = world_fn(world)
        if spread is not None:
            for idx, origin, at_round in spread:
                world = world.with_spread(idx, origin, at_round)
        state, metrics = swim.run(jax.random.key(seed), params, world, rounds)
        out.append((state, metrics))
    return out


def assert_identical(pair, rounds, msg):
    (s_w, m_w), (s_16, m_16) = pair
    for name in m_w:
        np.testing.assert_array_equal(
            np.asarray(m_w[name]), np.asarray(m_16[name]),
            err_msg=f"{msg}: metric {name} diverged",
        )
    # The carry is wide in BOTH modes: compare fields directly.
    for field in ("status", "inc", "spread_until", "suspect_deadline",
                  "self_inc", "g_infected", "g_spread_until"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_w, field)),
            np.asarray(getattr(s_16, field)),
            err_msg=f"{msg}: state.{field} diverged",
        )


SCENARIOS = {
    "crash_revive": lambda w: w.with_crash(3, at_round=5, until_round=60),
    "leave": lambda w: w.with_leave(2, at_round=10),
    "asym_link": lambda w: w.with_link_fault(1, 4, loss=0.9),
}


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_wire16_trace_identical(delivery, scenario):
    pair = run_pair(32, 120, SCENARIOS[scenario], delivery=delivery,
                    loss_probability=0.1)
    assert_identical(pair, 120, f"{scenario}/{delivery}")


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_wire16_delay_ring_trace_identical(delivery):
    # The ring slots hold wire keys, so int16_wire narrows them too; the
    # cross-round delivery must still merge identically.
    pair = run_pair(
        32, 120, SCENARIOS["crash_revive"], delivery=delivery,
        loss_probability=0.1, mean_delay_ms=150.0, max_delay_rounds=2,
    )
    assert_identical(pair, 120, f"delay-ring/{delivery}")
    # And the ring dtype actually narrowed.
    assert pair[1][0].inbox_ring.dtype == jnp.int16
    assert pair[0][0].inbox_ring.dtype == jnp.int32


def test_wire16_user_gossip_trace_identical():
    pair = run_pair(
        32, 80, SCENARIOS["crash_revive"], delivery="shift",
        loss_probability=0.05, n_user_gossips=2,
        spread=[(0, 1, 0), (1, 7, 4)],
    )
    assert_identical(pair, 80, "user-gossip/shift")


def test_wire16_blocked_tick_trace_identical():
    # k_block + int16_wire without compact_carry: the block bodies pack
    # and deliver int16 keys while decoding a WIDE carry.
    outs = []
    for wire16 in (False, True):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=64, delivery="shift",
            int16_wire=wire16, k_block=16, per_subject_metrics=False,
        )
        world = swim.SwimWorld.healthy(params).with_crash(5, at_round=4)
        state, metrics = swim.run(jax.random.key(1), params, world, 60)
        outs.append((state, metrics))
    assert_identical(outs, 60, "blocked/shift")


def test_wire16_carry_stays_wide():
    params = swim.SwimParams.from_config(
        fast_config(), n_members=16, delivery="shift", int16_wire=True
    )
    state = swim.initial_state(params, swim.SwimWorld.healthy(params))
    assert state.inc.dtype == jnp.int32
    assert state.spread_until.dtype == jnp.int32
    assert state.suspect_deadline.dtype == jnp.int32
    assert params.compact_wire and not params.compact_carry


def test_compact_carry_implies_compact_wire():
    params = swim.SwimParams.from_config(
        fast_config(), n_members=16, delivery="shift", compact_carry=True
    )
    assert params.compact_wire
