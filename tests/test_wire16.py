"""int16_wire (int16 wire keys, WIDE carry) is protocol-trace-identical
to the int32 wire.

The hybrid exists as a bandwidth lever for the 1M focal headline: the
round-3 narrow-int negative narrowed the CARRY lanes (slower merge); this
knob narrows only the wire-format buffers — payloads, channel delivers,
inbox, delay-ring slots — to records.merge_key16 while the carry keeps
its wide dtypes (SwimParams.int16_wire docstring).  Contract: below the
8191 incarnation saturation every protocol outcome is bit-identical —
same PRNG draws, same merge winners, same timers — because merge_key16
preserves the merge lattice order and the merge upcasts on load.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config


def run_pair(n, rounds, world_fn=None, seed=0, spread=None, **overrides):
    """(wide-wire metrics+state, int16-wire metrics+state), same scenario."""
    out = []
    for wire16 in (False, True):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, int16_wire=wire16, **overrides
        )
        world = swim.SwimWorld.healthy(params)
        if world_fn is not None:
            world = world_fn(world)
        if spread is not None:
            for idx, origin, at_round in spread:
                world = world.with_spread(idx, origin, at_round)
        state, metrics = swim.run(jax.random.key(seed), params, world, rounds)
        out.append((state, metrics))
    return out


def assert_identical(pair, rounds, msg):
    (s_w, m_w), (s_16, m_16) = pair
    for name in m_w:
        np.testing.assert_array_equal(
            np.asarray(m_w[name]), np.asarray(m_16[name]),
            err_msg=f"{msg}: metric {name} diverged",
        )
    # The carry is wide in BOTH modes: compare fields directly.
    for field in ("status", "inc", "spread_until", "suspect_deadline",
                  "self_inc", "g_infected", "g_spread_until"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_w, field)),
            np.asarray(getattr(s_16, field)),
            err_msg=f"{msg}: state.{field} diverged",
        )


SCENARIOS = {
    "crash_revive": lambda w: w.with_crash(3, at_round=5, until_round=60),
    "leave": lambda w: w.with_leave(2, at_round=10),
    "asym_link": lambda w: w.with_link_fault(1, 4, loss=0.9),
}


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_wire16_trace_identical(delivery, scenario):
    pair = run_pair(32, 120, SCENARIOS[scenario], delivery=delivery,
                    loss_probability=0.1)
    assert_identical(pair, 120, f"{scenario}/{delivery}")


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_wire16_delay_ring_trace_identical(delivery):
    # The ring slots hold wire keys, so int16_wire narrows them too; the
    # cross-round delivery must still merge identically.
    pair = run_pair(
        32, 120, SCENARIOS["crash_revive"], delivery=delivery,
        loss_probability=0.1, mean_delay_ms=150.0, max_delay_rounds=2,
    )
    assert_identical(pair, 120, f"delay-ring/{delivery}")
    # And the ring dtype actually narrowed.
    assert pair[1][0].inbox_ring.dtype == jnp.int16
    assert pair[0][0].inbox_ring.dtype == jnp.int32


def test_wire16_user_gossip_trace_identical():
    pair = run_pair(
        32, 80, SCENARIOS["crash_revive"], delivery="shift",
        loss_probability=0.05, n_user_gossips=2,
        spread=[(0, 1, 0), (1, 7, 4)],
    )
    assert_identical(pair, 80, "user-gossip/shift")


def test_wire16_blocked_tick_trace_identical():
    # k_block + int16_wire without compact_carry: the block bodies pack
    # and deliver int16 keys while decoding a WIDE carry.
    outs = []
    for wire16 in (False, True):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=64, delivery="shift",
            int16_wire=wire16, k_block=16, per_subject_metrics=False,
        )
        world = swim.SwimWorld.healthy(params).with_crash(5, at_round=4)
        state, metrics = swim.run(jax.random.key(1), params, world, 60)
        outs.append((state, metrics))
    assert_identical(outs, 60, "blocked/shift")


def test_wire16_carry_stays_wide():
    params = swim.SwimParams.from_config(
        fast_config(), n_members=16, delivery="shift", int16_wire=True
    )
    state = swim.initial_state(params, swim.SwimWorld.healthy(params))
    assert state.inc.dtype == jnp.int32
    assert state.spread_until.dtype == jnp.int32
    assert state.suspect_deadline.dtype == jnp.int32
    assert params.compact_wire and not params.compact_carry


def test_compact_carry_implies_compact_wire():
    params = swim.SwimParams.from_config(
        fast_config(), n_members=16, delivery="shift", compact_carry=True
    )
    assert params.compact_wire


# --------------------------------------------------------------------------
# The 8191 saturation boundary (the int16 wire key's incarnation cap)
# --------------------------------------------------------------------------


WIRE16_INC_CAP = (1 << 13) - 1      # records.merge_key16 saturation


def test_merge_gate_at_wire16_saturation_boundary():
    """Merge behavior exactly AT the int16 wire's incarnation cap
    (ops/delivery.merge_inbox's ``inbox_key > entry_key`` gate):

      - one below the cap, a refutation still lands (ALIVE@8191 beats
        SUSPECT@8190);
      - at the cap, incarnations stop distinguishing: ALIVE@8191 does
        NOT override SUSPECT@8191 (the suspect bit wins a key tie), and
        any incarnation above the cap packs to the same key as 8191;
      - DEAD still absorbs everything at the cap (the dead bit sits
        above the incarnation field, so saturation never corrupts
        rule 3).
    """
    from scalecube_cluster_tpu import records
    from scalecube_cluster_tpu.ops import delivery

    cap = WIRE16_INC_CAP

    def merge_one(entry_status, entry_inc, in_status, in_inc):
        key = delivery.pack_record(
            jnp.int8(in_status), jnp.int32(in_inc), compact=True
        )
        status, inc, changed = delivery.merge_inbox(
            jnp.int8(entry_status), jnp.int32(entry_inc),
            key, jnp.asarray(in_status == records.ALIVE), compact=True,
        )
        return int(status), int(inc), bool(changed)

    # Below the cap: higher incarnation refutes a suspicion.
    assert merge_one(records.SUSPECT, cap - 1, records.ALIVE, cap) == \
        (records.ALIVE, cap, True)
    # At the cap: the refutation no longer lands (key tie, suspect bit
    # wins) — the documented degradation, loud in the protocol (the
    # suspicion matures) rather than a silent wire/table divergence.
    status, inc, changed = merge_one(records.SUSPECT, cap,
                                     records.ALIVE, cap)
    assert (status, changed) == (records.SUSPECT, False)
    # Above the cap the wire saturates: 8192 packs like 8191.
    status, _, changed = merge_one(records.SUSPECT, cap,
                                   records.ALIVE, cap + 1)
    assert (status, changed) == (records.SUSPECT, False)
    # DEAD absorbs at the cap (dead bit above the inc field).
    status, _, changed = merge_one(records.SUSPECT, cap,
                                   records.DEAD, cap)
    assert (status, changed) == (records.DEAD, True)


# --------------------------------------------------------------------------
# Format-parameterized boundary matrix: the same edges for every rung
# of the wire-format ladder (ops/delivery.WIRE_FORMATS), with and
# without the open-world epoch field.
# --------------------------------------------------------------------------


FORMATS = ["wire16", "wire24", "wide"]


def _fmt(name):
    from scalecube_cluster_tpu.ops import delivery
    return delivery.WIRE_FORMATS[name]


@pytest.mark.wire
@pytest.mark.parametrize("epoch_on", [False, True], ids=["flat", "epoch"])
@pytest.mark.parametrize("fmt_name", FORMATS)
def test_saturation_edge_per_format(fmt_name, epoch_on):
    """The merge gate exactly AT each format's incarnation cap: one
    below the cap a refutation lands; at the cap incarnations stop
    distinguishing (suspect bit wins the key tie — loud in the
    protocol, never a silent wire/table divergence); above the cap the
    key saturates; DEAD still absorbs (dead bit above the inc field)."""
    from scalecube_cluster_tpu import records
    from scalecube_cluster_tpu.ops import delivery

    fmt = _fmt(fmt_name)
    eb = fmt.epoch_bits if epoch_on else 0
    cap = fmt.inc_sat(eb)

    def merge_one(entry_status, entry_inc, in_status, in_inc):
        key = delivery.pack_record(jnp.int8(in_status), jnp.int32(in_inc),
                                   fmt=fmt, epoch_bits=eb)
        out = delivery.merge_inbox(
            jnp.int8(entry_status), jnp.int32(entry_inc),
            key, jnp.asarray(in_status == records.ALIVE), fmt=fmt,
            entry_epoch=jnp.int32(0) if eb else None, epoch_bits=eb,
        )
        status, inc, changed = out[0], out[1], out[-1]
        return int(status), int(inc), bool(changed)

    assert merge_one(records.SUSPECT, cap - 1, records.ALIVE, cap) == \
        (records.ALIVE, cap, True)
    status, _, changed = merge_one(records.SUSPECT, cap, records.ALIVE, cap)
    assert (status, changed) == (records.SUSPECT, False)
    status, _, changed = merge_one(records.SUSPECT, cap,
                                   records.ALIVE, cap + 1)
    assert (status, changed) == (records.SUSPECT, False)
    status, _, changed = merge_one(records.SUSPECT, cap, records.DEAD, cap)
    assert (status, changed) == (records.DEAD, True)


@pytest.mark.wire
@pytest.mark.parametrize("fmt_name", FORMATS)
def test_epoch_rollover_per_format(fmt_name):
    """The epoch field at each format's width: the top epoch value
    round-trips through pack/unpack, packing clips above the cap
    (epochs never wrap into the dead bit), and a top-epoch ALIVE still
    sits BELOW any DEAD key — the fold order survives rollover."""
    from scalecube_cluster_tpu import records
    from scalecube_cluster_tpu.ops import delivery

    fmt = _fmt(fmt_name)
    eb = fmt.epoch_bits
    top = fmt.epoch_cap()
    key_top = delivery.pack_record(jnp.int8(records.ALIVE), jnp.int32(7),
                                   fmt=fmt, epoch=jnp.int32(top),
                                   epoch_bits=eb)
    assert int(delivery.unpack_epoch(key_top, fmt=fmt, epoch_bits=eb)) == top
    st, inc = delivery.unpack_record(key_top, fmt=fmt, epoch_bits=eb)
    assert (int(st), int(inc)) == (records.ALIVE, 7)
    # Above the cap the pack clips to the cap instead of carrying into
    # the dead bit.
    key_over = delivery.pack_record(jnp.int8(records.ALIVE), jnp.int32(7),
                                    fmt=fmt, epoch=jnp.int32(top + 1),
                                    epoch_bits=eb)
    assert int(key_over) == int(key_top)
    # DEAD at epoch 0 still absorbs a top-epoch ALIVE in the fold.
    key_dead0 = delivery.pack_record(jnp.int8(records.DEAD), jnp.int32(0),
                                     fmt=fmt, epoch=jnp.int32(0),
                                     epoch_bits=eb)
    assert int(key_dead0) > int(key_top)
    # With the epoch field compiled OUT (epoch_bits=0) a passed epoch
    # value is IGNORED — it must not shift into the dead bit (the
    # wire24 flat layout reaches the generic pack branch, where an
    # off-by-one clip would turn ALIVE@epoch>0 into a DEAD key).
    key_flat = delivery.pack_record(jnp.int8(records.ALIVE), jnp.int32(7),
                                    fmt=fmt, epoch=jnp.int32(1),
                                    epoch_bits=0)
    st, inc = delivery.unpack_record(key_flat, fmt=fmt)
    assert (int(st), int(inc)) == (records.ALIVE, 7)
    assert int(key_flat) == int(delivery.pack_record(
        jnp.int8(records.ALIVE), jnp.int32(7), fmt=fmt))


@pytest.mark.wire
@pytest.mark.parametrize("epoch_on", [False, True], ids=["flat", "epoch"])
@pytest.mark.parametrize("fmt_name", FORMATS)
def test_dead_absorbs_precedence_per_format(fmt_name, epoch_on):
    """records lattice order survives every layout: within a liveness
    class higher incarnation wins, suspect beats alive at equal inc,
    and ANY dead key beats every live key (the reference's
    DEAD-absorbs max-fold order, records.merge_key docstring)."""
    from scalecube_cluster_tpu import records
    from scalecube_cluster_tpu.ops import delivery

    fmt = _fmt(fmt_name)
    eb = fmt.epoch_bits if epoch_on else 0

    def k(status, inc):
        return int(delivery.pack_record(jnp.int8(status), jnp.int32(inc),
                                        fmt=fmt, epoch_bits=eb))

    cap = fmt.inc_sat(eb)
    assert k(records.ALIVE, 5) > k(records.ALIVE, 4)
    assert k(records.SUSPECT, 5) > k(records.ALIVE, 5)
    assert k(records.ALIVE, 6) > k(records.SUSPECT, 5)
    assert k(records.DEAD, 0) > k(records.SUSPECT, cap)
    assert k(records.DEAD, 0) > k(records.ALIVE, cap)
    assert k(records.DEAD, 1) > k(records.DEAD, 0)
    # ABSENT packs to the no-message sentinel and never wins a fold.
    assert k(records.ABSENT, 0) == int(delivery.no_message(fmt=fmt))


@pytest.mark.wire
@pytest.mark.parametrize("fmt_name", FORMATS)
def test_flag_fold_equivalence_per_format(fmt_name):
    """The ISSUE's lexicographic ``combined = key << 8 | flag`` fold is
    pointwise equal to deriving the flag from the folded winner key
    (is_alive_key of the max), because the ALIVE flag is a pure
    function of the key bits: for equal keys max over flag bytes IS the
    OR the separate pmax computed, and for differing keys the winner's
    flag rides along.  That equivalence is why the fused wire ships NO
    flag buffer at all — pinned here over every (status, inc) pair
    combination per format."""
    from scalecube_cluster_tpu import records
    from scalecube_cluster_tpu.ops import delivery

    fmt = _fmt(fmt_name)
    cap = fmt.inc_sat(0)
    statuses = [records.ABSENT, records.ALIVE, records.SUSPECT, records.DEAD]
    incs = [0, 1, cap - 1, cap]
    recs = [(s, i) for s in statuses for i in incs]
    keys = np.asarray(
        [int(delivery.pack_record(jnp.int8(s), jnp.int32(i), fmt=fmt))
         for s, i in recs], np.int64)
    flags = np.asarray(
        delivery.is_alive_key(jnp.asarray(keys, jnp.int32), fmt=fmt))
    a = np.repeat(keys, keys.shape[0])
    b = np.tile(keys, keys.shape[0])
    fa = np.repeat(flags, flags.shape[0])
    fb = np.tile(flags, flags.shape[0])
    # The issue's explicit bitfield fold, in int64 numpy scratch — the
    # wide key's dead bit 30 would overflow an int32 ``key << 8``
    # (which is exactly why the implementation derives the flag from
    # the unshifted key instead of spending 8 key bits).
    combined = np.maximum((a << 8) | fa, (b << 8) | fb)
    lex_winner, lex_flag = combined >> 8, (combined & 0xFF) != 0
    # The implemented fold: max the keys, rederive the flag.
    winner = np.maximum(a, b)
    derived_flag = np.asarray(delivery.is_alive_key(
        jnp.asarray(winner, jnp.int32), fmt=fmt))
    np.testing.assert_array_equal(lex_winner, winner)
    np.testing.assert_array_equal(lex_flag, derived_flag)
    # And for EQUAL keys the winner flag is exactly the OR of the pair.
    eq = a == b
    np.testing.assert_array_equal((fa | fb)[eq], derived_flag[eq])


@pytest.mark.wire
@pytest.mark.parametrize("delivery_mode", ["scatter", "shift"])
def test_wire24_trace_identical_below_cap(delivery_mode):
    """wire24 vs wire16 (both compact_carry) below the wire16 cap:
    table semantics are pinned bit-identical on BOTH delivery modes —
    the headroom rung changes only what the wire can express, never
    what a sub-cap run computes."""
    out = []
    for wire24 in (False, True):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=32, delivery=delivery_mode,
            compact_carry=True, wire24=wire24, loss_probability=0.1,
        )
        world = SCENARIOS["crash_revive"](swim.SwimWorld.healthy(params))
        out.append(swim.run(jax.random.key(3), params, world, 120))
    (s_16, m_16), (s_24, m_24) = out
    for name in m_16:
        np.testing.assert_array_equal(
            np.asarray(m_16[name]), np.asarray(m_24[name]),
            err_msg=f"wire24/{delivery_mode}: metric {name} diverged",
        )
    for field in ("status", "inc", "spread_until", "suspect_deadline",
                  "self_inc"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_16, field)),
            np.asarray(getattr(s_24, field)),
            err_msg=f"wire24/{delivery_mode}: state.{field} diverged",
        )


@pytest.mark.wire
def test_wire24_restores_refutation_above_wire16_cap():
    """THE headroom pin at merge level: at inc = the wire16 cap a
    refutation no longer lands on wire16 (key tie), but the SAME merge
    on wire24 — same int32 word on the wire — still distinguishes the
    incarnations and lands it, all the way to the int16 CARRY ceiling
    (models/swim._wire_inc_sat: 32767, now the binding cap)."""
    from scalecube_cluster_tpu import records
    from scalecube_cluster_tpu.ops import delivery

    cap16 = _fmt("wire16").inc_sat(0)

    def merge_one(fmt, entry_inc, in_inc):
        key = delivery.pack_record(jnp.int8(records.ALIVE),
                                   jnp.int32(in_inc), fmt=fmt)
        status, inc, changed = delivery.merge_inbox(
            jnp.int8(records.SUSPECT), jnp.int32(entry_inc),
            key, jnp.asarray(True), fmt=fmt,
        )
        return int(status), int(inc), bool(changed)

    # wire16: saturated tie, the suspicion stands.
    status, _, changed = merge_one(_fmt("wire16"), cap16, cap16 + 1)
    assert (status, changed) == (records.SUSPECT, False)
    # wire24: the refutation lands, and keeps landing at the carry cap.
    assert merge_one(_fmt("wire24"), cap16, cap16 + 1) == \
        (records.ALIVE, cap16 + 1, True)
    carry_cap = (1 << 15) - 1
    assert merge_one(_fmt("wire24"), carry_cap - 1, carry_cap) == \
        (records.ALIVE, carry_cap, True)


@pytest.mark.parametrize("wire16,expected_cap", [
    (True, WIRE16_INC_CAP),          # int16 wire: bump clamps at 8191
    (False, WIRE16_INC_CAP + 1),     # wide wire: 8191 is an ordinary inc
])
def test_refutation_bump_saturates_at_wire_cap(wire16, expected_cap):
    """The self-refutation bump is clamped to the ACTIVE wire format's
    incarnation saturation (models/swim._wire_inc_sat): the carry never
    holds an incarnation the wire cannot express, so table and wire
    cannot silently disagree at the merge gate.  A brief crash/revive
    with every incarnation pre-seeded AT the int16 cap pins it: under
    the int16 wire the revived node's bump saturates at 8191; under the
    wide wire the same scenario bumps to 8192 (its cap is 2^29-1)."""
    import dataclasses

    victim = 3
    params = swim.SwimParams.from_config(
        fast_config(), n_members=8, delivery="shift", int16_wire=wire16,
    )
    world = swim.SwimWorld.healthy(params).with_crash(
        victim, at_round=5, until_round=15
    )
    state = swim.initial_state(params, world)
    state = dataclasses.replace(
        state,
        inc=jnp.full_like(state.inc, WIRE16_INC_CAP),
        self_inc=jnp.full_like(state.self_inc, WIRE16_INC_CAP),
    )
    final, _ = swim.run(jax.random.key(0), params, world, 60, state=state)
    max_self = int(np.asarray(final.self_inc).max())
    assert max_self == expected_cap, \
        f"self_inc bump should saturate at {expected_cap}, got {max_self}"
    # The invariant the clamp enforces: no carried incarnation exceeds
    # what the wire key can pack exactly.
    if wire16:
        assert int(np.asarray(final.inc).max()) <= WIRE16_INC_CAP
