"""int16_wire (int16 wire keys, WIDE carry) is protocol-trace-identical
to the int32 wire.

The hybrid exists as a bandwidth lever for the 1M focal headline: the
round-3 narrow-int negative narrowed the CARRY lanes (slower merge); this
knob narrows only the wire-format buffers — payloads, channel delivers,
inbox, delay-ring slots — to records.merge_key16 while the carry keeps
its wide dtypes (SwimParams.int16_wire docstring).  Contract: below the
8191 incarnation saturation every protocol outcome is bit-identical —
same PRNG draws, same merge winners, same timers — because merge_key16
preserves the merge lattice order and the merge upcasts on load.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim

from tests.test_swim_model import fast_config


def run_pair(n, rounds, world_fn=None, seed=0, spread=None, **overrides):
    """(wide-wire metrics+state, int16-wire metrics+state), same scenario."""
    out = []
    for wire16 in (False, True):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=n, int16_wire=wire16, **overrides
        )
        world = swim.SwimWorld.healthy(params)
        if world_fn is not None:
            world = world_fn(world)
        if spread is not None:
            for idx, origin, at_round in spread:
                world = world.with_spread(idx, origin, at_round)
        state, metrics = swim.run(jax.random.key(seed), params, world, rounds)
        out.append((state, metrics))
    return out


def assert_identical(pair, rounds, msg):
    (s_w, m_w), (s_16, m_16) = pair
    for name in m_w:
        np.testing.assert_array_equal(
            np.asarray(m_w[name]), np.asarray(m_16[name]),
            err_msg=f"{msg}: metric {name} diverged",
        )
    # The carry is wide in BOTH modes: compare fields directly.
    for field in ("status", "inc", "spread_until", "suspect_deadline",
                  "self_inc", "g_infected", "g_spread_until"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_w, field)),
            np.asarray(getattr(s_16, field)),
            err_msg=f"{msg}: state.{field} diverged",
        )


SCENARIOS = {
    "crash_revive": lambda w: w.with_crash(3, at_round=5, until_round=60),
    "leave": lambda w: w.with_leave(2, at_round=10),
    "asym_link": lambda w: w.with_link_fault(1, 4, loss=0.9),
}


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_wire16_trace_identical(delivery, scenario):
    pair = run_pair(32, 120, SCENARIOS[scenario], delivery=delivery,
                    loss_probability=0.1)
    assert_identical(pair, 120, f"{scenario}/{delivery}")


@pytest.mark.parametrize("delivery", ["scatter", "shift"])
def test_wire16_delay_ring_trace_identical(delivery):
    # The ring slots hold wire keys, so int16_wire narrows them too; the
    # cross-round delivery must still merge identically.
    pair = run_pair(
        32, 120, SCENARIOS["crash_revive"], delivery=delivery,
        loss_probability=0.1, mean_delay_ms=150.0, max_delay_rounds=2,
    )
    assert_identical(pair, 120, f"delay-ring/{delivery}")
    # And the ring dtype actually narrowed.
    assert pair[1][0].inbox_ring.dtype == jnp.int16
    assert pair[0][0].inbox_ring.dtype == jnp.int32


def test_wire16_user_gossip_trace_identical():
    pair = run_pair(
        32, 80, SCENARIOS["crash_revive"], delivery="shift",
        loss_probability=0.05, n_user_gossips=2,
        spread=[(0, 1, 0), (1, 7, 4)],
    )
    assert_identical(pair, 80, "user-gossip/shift")


def test_wire16_blocked_tick_trace_identical():
    # k_block + int16_wire without compact_carry: the block bodies pack
    # and deliver int16 keys while decoding a WIDE carry.
    outs = []
    for wire16 in (False, True):
        params = swim.SwimParams.from_config(
            fast_config(), n_members=64, delivery="shift",
            int16_wire=wire16, k_block=16, per_subject_metrics=False,
        )
        world = swim.SwimWorld.healthy(params).with_crash(5, at_round=4)
        state, metrics = swim.run(jax.random.key(1), params, world, 60)
        outs.append((state, metrics))
    assert_identical(outs, 60, "blocked/shift")


def test_wire16_carry_stays_wide():
    params = swim.SwimParams.from_config(
        fast_config(), n_members=16, delivery="shift", int16_wire=True
    )
    state = swim.initial_state(params, swim.SwimWorld.healthy(params))
    assert state.inc.dtype == jnp.int32
    assert state.spread_until.dtype == jnp.int32
    assert state.suspect_deadline.dtype == jnp.int32
    assert params.compact_wire and not params.compact_carry


def test_compact_carry_implies_compact_wire():
    params = swim.SwimParams.from_config(
        fast_config(), n_members=16, delivery="shift", compact_carry=True
    )
    assert params.compact_wire


# --------------------------------------------------------------------------
# The 8191 saturation boundary (the int16 wire key's incarnation cap)
# --------------------------------------------------------------------------


WIRE16_INC_CAP = (1 << 13) - 1      # records.merge_key16 saturation


def test_merge_gate_at_wire16_saturation_boundary():
    """Merge behavior exactly AT the int16 wire's incarnation cap
    (ops/delivery.merge_inbox's ``inbox_key > entry_key`` gate):

      - one below the cap, a refutation still lands (ALIVE@8191 beats
        SUSPECT@8190);
      - at the cap, incarnations stop distinguishing: ALIVE@8191 does
        NOT override SUSPECT@8191 (the suspect bit wins a key tie), and
        any incarnation above the cap packs to the same key as 8191;
      - DEAD still absorbs everything at the cap (the dead bit sits
        above the incarnation field, so saturation never corrupts
        rule 3).
    """
    from scalecube_cluster_tpu import records
    from scalecube_cluster_tpu.ops import delivery

    cap = WIRE16_INC_CAP

    def merge_one(entry_status, entry_inc, in_status, in_inc):
        key = delivery.pack_record(
            jnp.int8(in_status), jnp.int32(in_inc), compact=True
        )
        status, inc, changed = delivery.merge_inbox(
            jnp.int8(entry_status), jnp.int32(entry_inc),
            key, jnp.asarray(in_status == records.ALIVE), compact=True,
        )
        return int(status), int(inc), bool(changed)

    # Below the cap: higher incarnation refutes a suspicion.
    assert merge_one(records.SUSPECT, cap - 1, records.ALIVE, cap) == \
        (records.ALIVE, cap, True)
    # At the cap: the refutation no longer lands (key tie, suspect bit
    # wins) — the documented degradation, loud in the protocol (the
    # suspicion matures) rather than a silent wire/table divergence.
    status, inc, changed = merge_one(records.SUSPECT, cap,
                                     records.ALIVE, cap)
    assert (status, changed) == (records.SUSPECT, False)
    # Above the cap the wire saturates: 8192 packs like 8191.
    status, _, changed = merge_one(records.SUSPECT, cap,
                                   records.ALIVE, cap + 1)
    assert (status, changed) == (records.SUSPECT, False)
    # DEAD absorbs at the cap (dead bit above the inc field).
    status, _, changed = merge_one(records.SUSPECT, cap,
                                   records.DEAD, cap)
    assert (status, changed) == (records.DEAD, True)


@pytest.mark.parametrize("wire16,expected_cap", [
    (True, WIRE16_INC_CAP),          # int16 wire: bump clamps at 8191
    (False, WIRE16_INC_CAP + 1),     # wide wire: 8191 is an ordinary inc
])
def test_refutation_bump_saturates_at_wire_cap(wire16, expected_cap):
    """The self-refutation bump is clamped to the ACTIVE wire format's
    incarnation saturation (models/swim._wire_inc_sat): the carry never
    holds an incarnation the wire cannot express, so table and wire
    cannot silently disagree at the merge gate.  A brief crash/revive
    with every incarnation pre-seeded AT the int16 cap pins it: under
    the int16 wire the revived node's bump saturates at 8191; under the
    wide wire the same scenario bumps to 8192 (its cap is 2^29-1)."""
    import dataclasses

    victim = 3
    params = swim.SwimParams.from_config(
        fast_config(), n_members=8, delivery="shift", int16_wire=wire16,
    )
    world = swim.SwimWorld.healthy(params).with_crash(
        victim, at_round=5, until_round=15
    )
    state = swim.initial_state(params, world)
    state = dataclasses.replace(
        state,
        inc=jnp.full_like(state.inc, WIRE16_INC_CAP),
        self_inc=jnp.full_like(state.self_inc, WIRE16_INC_CAP),
    )
    final, _ = swim.run(jax.random.key(0), params, world, 60, state=state)
    max_self = int(np.asarray(final.self_inc).max())
    assert max_self == expected_cap, \
        f"self_inc bump should saturate at {expected_cap}, got {max_self}"
    # The invariant the clamp enforces: no carried incarnation exceeds
    # what the wire key can pack exactly.
    if wire16:
        assert int(np.asarray(final.inc).max()) <= WIRE16_INC_CAP
