"""The observability helpers (utils/runlog.py): profiler hook + counters.

These are live in bench.py (the timed region is wrapped in ``profiled``,
its metrics digested by ``log_metrics_summary``) and in
experiments/profile_roofline.py; the tests pin their contracts: the
profiler hook only activates under SCALECUBE_TPU_PROFILE_DIR and writes a
real trace, and the summary digests the tick's metric tensors into the
reference-style counters (SURVEY.md §5.1).
"""

import logging
import os

import jax
import numpy as np

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.utils import runlog

from tests.test_swim_model import make


def test_log_metrics_summary_digests_counters(caplog):
    params, world = make(16, loss=0.2)
    _, metrics = swim.run(jax.random.key(2), params, world, 60)
    logger = runlog.get_logger("test_runlog")
    logger.propagate = True  # let caplog's root handler see it
    with caplog.at_level(logging.INFO, logger="test_runlog"):
        runlog.log_metrics_summary(logger, metrics, round_offset=0)
    assert len(caplog.records) == 1
    msg = caplog.records[0].getMessage()
    assert "rounds [0, 59]" in msg
    gossip = int(np.asarray(metrics["messages_gossip"]).sum())
    verdicts = int(np.asarray(metrics["messages_ping"]).sum())
    sent = int(np.asarray(metrics["messages_ping_sent"]).sum())
    pingreq = int(np.asarray(metrics["messages_ping_req_sent"]).sum())
    assert f"gossip msgs {gossip}" in msg
    assert f"pings sent {sent} (+{pingreq} ping-req fan-outs)" in msg
    assert f"tracked-subject probe verdicts {verdicts}" in msg


def test_log_metrics_summary_empty_metrics_logs_no_metrics_line(caplog):
    """An empty metrics dict (a zero-round chunk at a checkpoint
    boundary) must log a 'no metrics' line, not raise StopIteration."""
    logger = runlog.get_logger("test_runlog_empty")
    logger.propagate = True
    with caplog.at_level(logging.INFO, logger="test_runlog_empty"):
        runlog.log_metrics_summary(logger, {}, round_offset=500)
    assert len(caplog.records) == 1
    msg = caplog.records[0].getMessage()
    assert "no metrics" in msg and "500" in msg


def test_get_logger_reapplies_level_on_repeat_calls():
    """The resolved level applies on EVERY call — a later explicit
    ``level`` must take effect even though the handler already exists."""
    name = "test_runlog_levels"
    logger = runlog.get_logger(name, level=logging.WARNING)
    assert logger.level == logging.WARNING
    assert len(logger.handlers) == 1
    logger = runlog.get_logger(name, level=logging.DEBUG)
    assert logger.level == logging.DEBUG
    assert len(logger.handlers) == 1          # no handler duplication
    logger = runlog.get_logger(name, level="ERROR")
    assert logger.level == logging.ERROR


def test_get_logger_level_from_env(monkeypatch):
    monkeypatch.setenv("SCALECUBE_TPU_LOGLEVEL", "WARNING")
    logger = runlog.get_logger("test_runlog_env_level")
    assert logger.level == logging.WARNING
    # Explicit argument beats the env var.
    logger = runlog.get_logger("test_runlog_env_level", level="DEBUG")
    assert logger.level == logging.DEBUG


def test_profiled_noop_without_env(monkeypatch):
    monkeypatch.delenv("SCALECUBE_TPU_PROFILE_DIR", raising=False)
    with runlog.profiled():
        x = jax.numpy.arange(8).sum()
    assert int(x) == 28


def test_profiled_writes_trace_when_env_set(tmp_path, monkeypatch):
    trace_dir = str(tmp_path / "trace")
    monkeypatch.setenv("SCALECUBE_TPU_PROFILE_DIR", trace_dir)
    with runlog.profiled():
        jax.block_until_ready(jax.numpy.arange(128).sum())
    produced = [
        os.path.join(root, f)
        for root, _, files in os.walk(trace_dir) for f in files
    ]
    assert produced, "profiled() wrote no trace files under the env dir"
