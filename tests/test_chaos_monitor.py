"""In-jit invariant monitor: green on correct runs, the matching code
(and ONLY evidence — never a crash) on broken ones.

Graceful degradation is the contract under test: a violated run
completes, returns its full metrics, and reports (round, observer,
subject, code, detail) evidence lanes with overflow counted — the
acceptance criterion's "trips the matching invariant code rather than
crashing the run".
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.chaos import campaign as cc
from scalecube_cluster_tpu.chaos import monitor as cm
from scalecube_cluster_tpu.chaos import scenarios as cs
from scalecube_cluster_tpu.models import swim

pytestmark = pytest.mark.chaos

INT32_MAX = cs.INT32_MAX
N = 24


def crash_scenario(**kw):
    return cs.Scenario(name="crash", n_members=N, horizon=192,
                       ops=(cs.Crash(3, at_round=5),), **kw)


def run(scen, spec=None, knobs=None, state=None, capacity=256, seed=0,
        horizon=None, params=None):
    params = params if params is not None else cc.campaign_params(scen)
    world, built_spec = scen.build(params)
    return cm.run_monitored(
        jax.random.key(seed), params, world,
        built_spec if spec is None else spec,
        horizon or scen.horizon, capacity=capacity, state=state,
        knobs=knobs,
    ), params, world, built_spec


# --------------------------------------------------------------------------
# Green paths
# --------------------------------------------------------------------------


def test_healthy_and_crash_runs_are_green():
    (_, mon, metrics), _, _, _ = run(crash_scenario())
    v = cm.verdict(mon)
    assert v["green"] and v["total_violations"] == 0
    assert v["evidence"] == [] and v["evidence_dropped"] == 0
    # The run's protocol metrics come back intact (the monitor only
    # observes — swim.run semantics unchanged).
    assert int(np.asarray(metrics["dead"])[-1, 3]) == N - 1


@pytest.mark.parametrize("layout", [{}, {"compact_carry": True},
                                    {"int16_wire": True}])
def test_monitor_is_layout_transparent(layout):
    scen = crash_scenario()
    params = cc.campaign_params(scen, **layout)
    (_, mon, _), _, _, _ = run(scen, params=params)
    assert cm.verdict(mon)["green"], (layout, cm.verdict(mon)["codes"])


def test_monitor_is_deterministic():
    (_, a, _), _, _, _ = run(crash_scenario(), seed=4)
    (_, b, _), _, _, _ = run(crash_scenario(), seed=4)
    assert np.array_equal(np.asarray(a.lanes), np.asarray(b.lanes))
    assert int(a.count) == int(b.count)
    assert np.array_equal(np.asarray(a.code_counts),
                          np.asarray(b.code_counts))


# --------------------------------------------------------------------------
# Broken scenarios trip the MATCHING code (and never crash)
# --------------------------------------------------------------------------


def broken_codes(mon):
    v = cm.verdict(mon)
    return {c for c, d in v["codes"].items() if d["violations"]}


def test_suspicion_timeout_above_completeness_bound_trips_completeness():
    """The acceptance-criterion scenario: the spec's completeness
    deadline assumes params.suspicion_rounds, but the run's (traced)
    suspicion timeout is far larger — removal provably lands after the
    deadline, tripping COMPLETENESS (with evidence), not an exception."""
    scen = crash_scenario()
    params = cc.campaign_params(scen)
    kn = swim.Knobs.from_params(params)
    kn = dataclasses.replace(
        kn, suspicion_rounds=jnp.int32(10 * params.suspicion_rounds))
    (_, mon, _), _, _, spec = run(scen, knobs=kn)
    v = cm.verdict(mon)
    assert not v["green"]
    assert broken_codes(mon) == {"COMPLETENESS"}
    assert v["codes"]["COMPLETENESS"]["first_round"] \
        == int(spec.complete_by[3])
    ev = v["evidence"]
    assert ev and all(e["code"] == "COMPLETENESS" and e["subject"] == 3
                      for e in ev)


def test_loss_with_pristine_spec_trips_false_suspicion():
    """A scenario that PROMISES a pristine network but runs with 25%
    wire loss: FALSE_SUSPICION trips with (observer, subject) evidence
    — the no-false-suspicion-absent-faults safety property, violated
    on purpose."""
    scen = crash_scenario()
    params = cc.campaign_params(scen, loss_probability=0.25)
    world, spec = scen.build(params)
    assert not spec.check_false_suspicion      # build() is honest
    forced = dataclasses.replace(spec, check_false_suspicion=True,
                                 complete_by=jnp.full(
                                     (N,), INT32_MAX, jnp.int32))
    _, mon, _ = cm.run_monitored(jax.random.key(0), params, world,
                                 forced, 120, capacity=256)
    assert broken_codes(mon) == {"FALSE_SUSPICION"}
    ev = cm.decode_violations(mon)
    assert ev and all(e.code == cm.InvariantCode.FALSE_SUSPICION
                      for e in ev)


def test_corrupt_timer_state_trips_timer_bound():
    """A pending suspicion timer on an ALIVE entry (and a SUSPECT entry
    with no timer) — the timer contract's two halves."""
    scen = crash_scenario()
    params = cc.campaign_params(scen)
    world, spec = scen.build(params)
    state = swim.initial_state(params, world)
    state = dataclasses.replace(
        state,
        suspect_deadline=state.suspect_deadline.at[2, 7].set(50),
        status=state.status.at[4, 9].set(1),       # SUSPECT, no timer
    )
    _, mon, _ = cm.run_monitored(jax.random.key(0), params, world, spec,
                                 4, capacity=64, state=state)
    assert "TIMER_BOUND" in broken_codes(mon)
    cells = {(e.observer, e.subject) for e in cm.decode_violations(mon)
             if e.code == cm.InvariantCode.TIMER_BOUND}
    assert (2, 7) in cells


def test_saturated_incarnation_trips_wire_saturation():
    scen = crash_scenario()
    params = cc.campaign_params(scen, int16_wire=True)   # sat = 8191
    world, spec = scen.build(params)
    state = swim.initial_state(params, world)
    state = dataclasses.replace(
        state, inc=state.inc.at[1, 6].set(9000))
    _, mon, _ = cm.run_monitored(jax.random.key(0), params, world, spec,
                                 2, capacity=64, state=state)
    assert "WIRE_SATURATION" in broken_codes(mon)
    ev = [e for e in cm.decode_violations(mon)
          if e.code == cm.InvariantCode.WIRE_SATURATION]
    assert any(e.observer == 1 and e.subject == 6 and e.detail == 9000
               for e in ev)


def test_check_round_flags_inc_regression_directly():
    """The one invariant no protocol path can reach (that is the
    point): unit-test check_round on a synthetic regression — a LIVE
    cell's incarnation stepping down without turning DEAD."""
    scen = crash_scenario()
    params = cc.campaign_params(scen)
    world, spec = scen.build(params)
    kn = swim.Knobs.from_params(params)
    prev = swim.initial_state(params, world)
    prev = dataclasses.replace(prev, inc=prev.inc.at[2, 5].set(4))
    new = dataclasses.replace(prev, inc=prev.inc.at[2, 5].set(1))
    mon = cm.check_round(cm.MonitorState.init(64), spec, params, kn,
                         jnp.int32(7), prev, new, world)
    assert int(mon.code_counts[cm.InvariantCode.INC_REGRESSION]) == 1
    (ev,) = cm.decode_violations(mon)
    assert (ev.round, ev.observer, ev.subject, ev.detail) == (7, 2, 5, 1)
    # A DEAD winner with a lower incarnation is LEGAL (isOverrides
    # case 3) — same cells, new status DEAD: no violation.
    dead = dataclasses.replace(new, status=new.status.at[2, 5].set(2))
    mon2 = cm.check_round(cm.MonitorState.init(64), spec, params, kn,
                          jnp.int32(7), prev, dead, world)
    assert int(mon2.code_counts.sum()) == 0


# --------------------------------------------------------------------------
# Evidence mechanics
# --------------------------------------------------------------------------


def test_evidence_overflow_is_counted_never_silent():
    scen = crash_scenario()
    spec_broken = cs.Scenario(name="b", n_members=N, horizon=64,
                              ops=(cs.Crash(3, at_round=5),))
    params = cc.campaign_params(spec_broken)
    world, spec = spec_broken.build(params)
    spec = dataclasses.replace(
        spec, complete_by=spec.complete_by.at[3].set(7))
    _, mon_small, _ = cm.run_monitored(jax.random.key(0), params, world,
                                       spec, 64, capacity=4)
    _, mon_big, _ = cm.run_monitored(jax.random.key(0), params, world,
                                     spec, 64, capacity=4096)
    assert int(mon_small.count) == 4
    assert int(mon_small.dropped) > 0
    # Exact accounting: small buffer's count+dropped = big buffer's
    # recorded evidence; the recorded lanes are an exact prefix.
    assert (int(mon_small.count) + int(mon_small.dropped)
            == int(mon_big.count))
    assert cm.decode_violations(mon_small) \
        == cm.decode_violations(mon_big)[:4]
    # Totals are NOT capacity-limited — every violating cell counts.
    assert np.array_equal(np.asarray(mon_small.code_counts),
                          np.asarray(mon_big.code_counts))


def test_persistent_violation_records_first_round_only():
    """COMPLETENESS re-fires every round past the deadline; the lanes
    hold only the first round's cells (flood-proof) while code_counts
    keeps the exact running total."""
    scen = crash_scenario()
    params = cc.campaign_params(scen)
    world, spec = scen.build(params)
    spec = dataclasses.replace(
        spec, complete_by=spec.complete_by.at[3].set(7))
    _, mon, _ = cm.run_monitored(jax.random.key(0), params, world, spec,
                                 64, capacity=4096)
    ev = cm.decode_violations(mon)
    assert ev
    assert {e.round for e in ev} == {7}
    total = int(mon.code_counts[cm.InvariantCode.COMPLETENESS])
    assert total > len(ev)                 # kept counting after round 7
    assert int(mon.code_first_round[cm.InvariantCode.COMPLETENESS]) == 7


def test_monitor_resumes_across_chunks():
    scen = crash_scenario()
    params = cc.campaign_params(scen)
    world, spec = scen.build(params)
    _, mon_once, _ = cm.run_monitored(jax.random.key(1), params, world,
                                      spec, 128)
    state, mon = None, None
    for start in (0, 64):
        state, mon, _ = cm.run_monitored(
            jax.random.key(1), params, world, spec, 64, state=state,
            start_round=start, monitor=mon)
    assert np.array_equal(np.asarray(mon.lanes),
                          np.asarray(mon_once.lanes))
    assert np.array_equal(np.asarray(mon.code_counts),
                          np.asarray(mon_once.code_counts))


def test_verdict_json_roundtrips():
    import json

    (_, mon, _), _, _, _ = run(crash_scenario())
    v = cm.verdict(mon)
    assert json.loads(json.dumps(v)) == v
