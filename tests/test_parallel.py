"""Tests for the sharded SWIM runner (parallel/mesh.py) on the virtual
8-device CPU mesh (tests/conftest.py), mirroring how the reference tests
"multi-node" in one process (SURVEY.md §4).
"""

import jax
import numpy as np
import pytest

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.parallel import compat
from scalecube_cluster_tpu.parallel import mesh as pmesh

from tests.test_swim_model import fast_config

pytestmark = pytest.mark.skipif(not compat.HAS_SHARD_MAP,
                                reason=compat.SKIP_REASON)


def make(n, k=None, loss=0.0, **overrides):
    params = swim.SwimParams.from_config(
        fast_config(), n_members=n, n_subjects=k, loss_probability=loss,
        **overrides,
    )
    return params, swim.SwimWorld.healthy(params)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return pmesh.make_mesh(8)


class TestShardRun:
    def test_healthy_no_false_positives(self, mesh8):
        params, world = make(64)
        _, metrics = pmesh.shard_run(jax.random.key(0), params, world, 60, mesh8)
        assert np.asarray(metrics["false_positives"]).sum() == 0
        assert np.all(np.asarray(metrics["alive"])[-1] == params.n_members - 1)

    def test_crash_detected_and_disseminated(self, mesh8):
        n = 64
        params, world = make(n)
        world = world.with_crash(5, at_round=0)
        horizon = params.ping_every * n // 4 + params.suspicion_rounds + 200
        _, metrics = pmesh.shard_run(jax.random.key(1), params, world, horizon, mesh8)
        alive_view = np.asarray(metrics["alive"])[:, 5]
        assert alive_view[-1] == 0, "sharded run failed to disseminate death"

    def test_sharded_matches_single_device_invariants(self, mesh8):
        """Sharded and single-device runs aren't bit-identical (per-device
        PRNG folding) but must agree on protocol outcomes."""
        n = 32
        params, world = make(n)
        world = world.with_crash(3, at_round=0)
        _, m_shard = pmesh.shard_run(jax.random.key(2), params, world, 250, mesh8)
        _, m_single = swim.run(jax.random.key(2), params, world, 250)
        for m in (m_shard, m_single):
            assert np.asarray(m["alive"])[-1, 3] == 0
            # no live member ever declared dead
            dead = np.asarray(m["dead"])
            assert dead[:, np.arange(n) != 3].sum() == 0

    def test_sharded_determinism(self, mesh8):
        params, world = make(32, loss=0.2)
        _, m1 = pmesh.shard_run(jax.random.key(3), params, world, 50, mesh8)
        _, m2 = pmesh.shard_run(jax.random.key(3), params, world, 50, mesh8)
        for k in m1:
            np.testing.assert_array_equal(np.asarray(m1[k]), np.asarray(m2[k]))

    def test_focal_mode_sharded(self, mesh8):
        """Focal mode (K << N) under sharding: the 1M-member configuration
        in miniature."""
        params, world = make(512, k=8, ping_known_only=False)
        world = world.with_crash(2, at_round=0)
        _, metrics = pmesh.shard_run(jax.random.key(4), params, world, 400, mesh8)
        alive_view = np.asarray(metrics["alive"])[:, 2]
        assert alive_view[-1] < alive_view[0]
        fp_other = np.asarray(metrics["false_positives"])
        assert fp_other[:, np.arange(8) != 2].sum() == 0

    def test_final_state_sharding(self, mesh8):
        params, world = make(64)
        final, _ = pmesh.shard_run(jax.random.key(5), params, world, 10, mesh8)
        # Final state comes back sharded over the node axis.
        assert final.status.shape == (64, 64)
        shard_sizes = {s.data.shape[0] for s in final.status.addressable_shards}
        assert shard_sizes == {8}


class TestShardedShiftMode:
    """Shift delivery under shard_map: payload blocks ride block-rotation
    ppermutes (ops/shift.ShiftEngine) instead of the scatter path's
    full-height pmax."""

    def test_crash_detected_and_disseminated(self, mesh8):
        n = 64
        params, world = make(n, delivery="shift")
        world = world.with_crash(0, at_round=0)
        horizon = params.ping_every * n // 4 + params.suspicion_rounds + 200
        _, metrics = pmesh.shard_run(
            jax.random.key(7), params, world, horizon, mesh8
        )
        alive_view = np.asarray(metrics["alive"])[:, 0]
        deads = np.asarray(metrics["dead"])[:, 0]
        assert deads.max() > 0
        assert alive_view[-1] == 0

    def test_healthy_no_false_positives(self, mesh8):
        params, world = make(64, delivery="shift")
        _, metrics = pmesh.shard_run(
            jax.random.key(8), params, world, 60, mesh8
        )
        assert np.asarray(metrics["false_positives"]).sum() == 0

    def test_sharded_determinism(self, mesh8):
        params, world = make(32, loss=0.2, delivery="shift")
        _, m1 = pmesh.shard_run(jax.random.key(9), params, world, 50, mesh8)
        _, m2 = pmesh.shard_run(jax.random.key(9), params, world, 50, mesh8)
        for k in m1:
            np.testing.assert_array_equal(np.asarray(m1[k]), np.asarray(m2[k]))

    def test_focal_mode_sharded_shift(self, mesh8):
        params, world = make(512, k=8, ping_known_only=False,
                             delivery="shift")
        world = world.with_crash(2, at_round=0)
        _, metrics = pmesh.shard_run(
            jax.random.key(10), params, world, 400, mesh8
        )
        alive_view = np.asarray(metrics["alive"])[:, 2]
        assert alive_view[-1] < alive_view[0]

    def test_fullview_256_crash_heal_timeline(self, mesh8):
        """The 32k sharded crash+heal demo's shape at CI cost: N=256
        exact-semantics full view over 8 devices, shift delivery — the
        same sharded path (ShiftEngine block rotations) the ~100-min
        `experiments/fullview_scale.py` artifact exercises, asserting the
        suspected -> DEAD -> disseminated -> healed timeline every run.
        """
        n, crash_node = 256, 9
        crash_at, revive_at, horizon = 2, 150, 320
        params, world = make(n, delivery="shift")
        assert params.full_view
        world = world.with_crash(crash_node, at_round=crash_at,
                                 until_round=revive_at)
        _, metrics = pmesh.shard_run(
            jax.random.key(11), params, world, horizon, mesh8
        )
        suspects = np.asarray(metrics["suspect"])[:, crash_node]
        deads = np.asarray(metrics["dead"])[:, crash_node]
        alive_view = np.asarray(metrics["alive"])[:, crash_node]

        def first(cond):
            idx = np.flatnonzero(cond)
            assert idx.size, "timeline event never happened"
            return int(idx[0])

        suspected = first(suspects > 0)
        dead_declared = first(deads > 0)
        # Death disseminated: every live observer (n-2: all but the
        # subject and itself... the subject is down, so n-1 observers
        # minus none — alive observers exclude the crashed subject) holds
        # the tombstone and nobody holds ALIVE/SUSPECT.
        disseminated = first(
            (alive_view == 0) & (suspects == 0) & (deads == n - 1)
        )
        healed = first(
            (np.arange(horizon) >= revive_at) & (alive_view == n - 1)
        )
        assert crash_at <= suspected <= crash_at + 3 * params.ping_every
        # DEAD at the first suspicion's timeout (+ slack for stragglers).
        assert suspected + params.suspicion_rounds <= dead_declared \
            <= suspected + params.suspicion_rounds + 4 * params.ping_every
        assert dead_declared < disseminated < revive_at
        assert revive_at < healed < horizon
        # The revival is a refutation (incarnation bump), not a
        # false-positive: no live member was ever wrongly suspected.
        assert np.asarray(metrics["false_suspicion_onsets"]).sum() == 0


class TestShardedLayouts:
    """Narrow-wire layouts through the sharded shift path: the block-
    rotation ppermutes carry int16 payloads (compact_wire), and the
    compact carry additionally re-relativizes its encodings every tick.
    Both must trace-match the wide layout exactly — the single-device
    contracts of tests/test_wire16.py / test_compact_carry.py lifted to
    the 8-device mesh.
    """

    @pytest.mark.parametrize("layout", ["int16_wire", "compact_carry"])
    def test_sharded_layout_trace_identical(self, mesh8, layout):
        out = []
        for on in (False, True):
            params, world = make(64, loss=0.1, delivery="shift",
                                 **{layout: on})
            world = world.with_crash(5, at_round=4, until_round=80)
            _, m = pmesh.shard_run(
                jax.random.key(12), params, world, 120, mesh8
            )
            out.append(m)
        for name in out[0]:
            np.testing.assert_array_equal(
                np.asarray(out[0][name]), np.asarray(out[1][name]),
                err_msg=f"sharded {layout}: metric {name} diverged",
            )
